"""serve_bench: throughput-latency of continuous vs static batching.

Methodology (mirrors the root bench.py contract of honest numbers):

- **One synthetic Poisson trace, two servers.** Requests arrive by an
  exponential inter-arrival clock (seeded NumPy — the trace is identical
  across runs and across the two servers). Prompts are random token
  spans with mixed lengths; per-request `max_new_tokens` is drawn from a
  range, which is the realistic heterogeneity static batching handles
  worst (every request pays for the batch's longest).
- **Continuous server**: SlotEngine + Scheduler on the monotonic clock —
  requests join the running decode batch at slot granularity and release
  at their own length.
- **Static baseline**: the one-shot `make_generate_fn` program at batch
  = max_slots, every prompt padded to one width and every request run to
  the trace's MAXIMUM new-token count (one compile, the strongest honest
  static config — bucketing per batch would recompile per composition).
  Arrivals queue while the current batch runs; a request's latency ends
  when its whole batch returns.
- **Useful tokens only.** Both servers are scored on the tokens each
  request asked for; the static server's overshoot past a request's own
  `max_new_tokens` is discarded, not credited.

Wall-clock timing closes with a host readback (np.asarray of the token
block / the scheduler's device_get per step), so no async dispatch leaks
into the window. Warmup compiles happen before the trace clock starts
for BOTH servers.

`--replicas N` additionally replays the trace through N replicas behind
the fault-tolerant router (serve/router.py); with `--fault-plan` the
router row becomes a GOODPUT-under-faults measurement — tokens still
delivered while a seeded FaultPlan crashes replicas, stalls ticks, or
poisons logits. replicas=1 with no plan measures the router's own
overhead against the direct continuous path (should be within noise —
the router adds host-side bookkeeping only).

Every row (except static, which has no phases) reports a per-phase
latency breakdown — queue/prefill/decode/stall p50/p99 from the
completions' flight records — and `--trace-out` writes the run's
request-lifecycle spans as Chrome trace JSON (utils/trace.py; warmup
excluded; tracing overhead measured < 1%, BENCHMARKS.md).
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys
import time
from typing import Optional

import numpy as np


def build_trace(
    *,
    n_requests: int,
    rate_hz: float,
    vocab: int,
    prompt_len_range=(2, 16),
    max_new_range=(4, 32),
    seed: int = 0,
) -> list:
    """Poisson arrivals with mixed prompt lengths and token budgets."""
    rng = np.random.default_rng(seed)
    gaps = rng.exponential(1.0 / rate_hz, n_requests)
    arrivals = np.cumsum(gaps)
    trace = []
    for i in range(n_requests):
        plen = int(rng.integers(prompt_len_range[0], prompt_len_range[1] + 1))
        trace.append({
            "rid": i,
            "arrival": float(arrivals[i]),
            "prompt": rng.integers(0, vocab, plen).tolist(),
            "max_new_tokens": int(
                rng.integers(max_new_range[0], max_new_range[1] + 1)
            ),
        })
    return trace


def build_shared_prefix_trace(
    *,
    n_requests: int,
    rate_hz: float,
    vocab: int,
    k_prefixes: int = 2,
    prefix_len: int = 48,
    tail_range=(1, 8),
    max_new_range=(8, 24),
    seed: int = 0,
) -> list:
    """K seeded system prompts x many continuations — the PR-6 prefix
    workload: every request is one of `k_prefixes` fixed prefixes plus a
    short unique tail, arriving Poisson. Deterministic per seed (same
    trace replays through the plain and prefix-sharing engines)."""
    rng = np.random.default_rng(seed)
    prefixes = [
        rng.integers(0, vocab, prefix_len).tolist()
        for _ in range(k_prefixes)
    ]
    gaps = rng.exponential(1.0 / rate_hz, n_requests)
    arrivals = np.cumsum(gaps)
    trace = []
    for i in range(n_requests):
        pre = prefixes[int(rng.integers(0, k_prefixes))]
        tail = rng.integers(
            0, vocab, int(rng.integers(tail_range[0], tail_range[1] + 1))
        ).tolist()
        trace.append({
            "rid": i,
            "arrival": float(arrivals[i]),
            "prompt": list(pre) + tail,
            "max_new_tokens": int(
                rng.integers(max_new_range[0], max_new_range[1] + 1)
            ),
        })
    return trace


def build_lookup_trace(
    *,
    n_requests: int,
    rate_hz: float,
    vocab: int,
    motif_range=(2, 4),
    prompt_len_range=(6, 16),
    max_new_range=(8, 24),
    seed: int = 0,
) -> list:
    """Lookup-friendly prompts: each is a short random motif repeated to
    length (summarization / code-edit / quoting traffic in miniature —
    the text keeps citing its own earlier spans). This is the workload
    prompt-lookup speculative decoding (serve/spec.py) targets: the
    trailing n-gram recurs, so drafts fire and verify accepts runs.
    Deterministic per seed, same trace replays through both arms."""
    rng = np.random.default_rng(seed)
    gaps = rng.exponential(1.0 / rate_hz, n_requests)
    arrivals = np.cumsum(gaps)
    trace = []
    for i in range(n_requests):
        motif = rng.integers(
            0, vocab, int(rng.integers(motif_range[0], motif_range[1] + 1))
        ).tolist()
        plen = int(rng.integers(prompt_len_range[0],
                                prompt_len_range[1] + 1))
        reps = -(-plen // len(motif))
        trace.append({
            "rid": i,
            "arrival": float(arrivals[i]),
            "prompt": (motif * reps)[:plen],
            "max_new_tokens": int(
                rng.integers(max_new_range[0], max_new_range[1] + 1)
            ),
        })
    return trace


def _build_model(*, vocab, max_len, hidden, depth, heads, mlp,
                 kv_cache_dtype=None):
    import jax
    import jax.numpy as jnp

    from ddp_practice_tpu.models import create_model

    model = create_model(
        "lm_tiny", vocab_size=vocab, max_len=max_len, hidden_dim=hidden,
        depth=depth, num_heads=heads, mlp_dim=mlp, pos_emb="rope",
        kv_cache_dtype=kv_cache_dtype,
    )
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    return model, params


def _kv_bytes_per_token(cache, num_blocks, block_size) -> float:
    """HBM bytes one context position costs in a paged pool: every
    non-scalar cache leaf's bytes (K/V + any int8 scale pages), divided
    by the pool's positions. The int8-halving acceptance number."""
    import jax

    total = sum(
        leaf.nbytes for leaf in jax.tree.leaves(cache) if leaf.ndim
    )
    return total / (num_blocks * block_size)


def _percentiles(xs) -> dict:
    # the plane-wide percentile implementation (utils/metrics.py):
    # bench rows, /flight scrapes, and SLO verdicts all quote the same
    # nearest-rank quantiles
    from ddp_practice_tpu.utils.metrics import percentile_summary

    return percentile_summary(xs, (50, 90, 99))


def _phase_breakdown(completions) -> dict:
    """Per-phase latency percentiles from the completions' flight
    records (scheduler/router attach them): WHERE the latency percentile
    rows' time actually went — queue wait vs prefill vs decode vs
    stalled (parked between retries / not on any replica)."""
    out = {}
    flights = [c.flight for c in completions if c.flight is not None]
    for key in ("queue_s", "prefill_s", "decode_s", "stall_s"):
        out[key] = _percentiles([f[key] for f in flights])
    return out


def _make_tracer():
    from ddp_practice_tpu.utils.trace import TraceRecorder

    return TraceRecorder()


class _Scraper:
    """Background self-scraper: GETs /metrics, /healthz, /flight round-
    robin at `hz` for the whole bench window, so the plane-on overhead
    row pays for serving REAL scrape traffic, not an idle listener."""

    def __init__(self, port: int, hz: float = 10.0) -> None:
        import threading

        self.port = port
        self.period = 1.0 / hz
        self.count = 0
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="bench-scraper", daemon=True
        )
        self._thread.start()

    def _run(self) -> None:
        import http.client

        paths = ("/metrics", "/healthz", "/flight")
        i = 0
        while not self._stop.wait(self.period):
            try:
                conn = http.client.HTTPConnection(
                    "127.0.0.1", self.port, timeout=1.0
                )
                conn.request("GET", paths[i % len(paths)])
                conn.getresponse().read()
                conn.close()
                self.count += 1
            except Exception:
                # server mid-shutdown or a torn response: keep scraping
                # (a dead scraper would quietly measure an idle listener
                # as "plane on")
                pass
            i += 1

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=2.0)


def _run_continuous(model, params, trace, *, max_slots, prompt_buckets,
                    max_len, decode_burst, eos_id, paged: bool = False,
                    block_size: int = 16, prefix_cache: bool = False,
                    num_blocks: Optional[int] = None,
                    spec_decode: bool = False, spec_k: int = 4,
                    collect_tokens: bool = False, tracer=None,
                    telemetry=None, health_slot=None) -> dict:
    from ddp_practice_tpu.serve.engine import (
        EngineConfig,
        PagedEngine,
        SlotEngine,
    )
    from ddp_practice_tpu.serve.scheduler import Request, Scheduler

    if paged:
        # per-slot capacity sized to the WORKLOAD's worst context
        # (bucket + burst-rounded max_new), not to max_len — this is the
        # paged decoupling: attention span follows the request, while
        # the POOL carries max_len-equivalent memory per slot so both
        # engines hold the same HBM. `num_blocks` overrides the pool
        # size (the shared-prefix bench undersizes it so block pressure
        # — what sharing relieves — is actually on the table).
        worst_new = max(t["max_new_tokens"] for t in trace)
        worst_new = -(-worst_new // decode_burst) * decode_burst
        if spec_decode:
            # the verify program grows every slot spec_k + 1 positions
            # before knowing the acceptance — the scheduler's admission
            # slack (_needed_positions) must fit the per-slot capacity
            worst_new += spec_k + 1
        cap_blocks = -(-(max(prompt_buckets) + worst_new) // block_size)
        engine = PagedEngine(
            model, params,
            EngineConfig(
                max_slots=max_slots, max_len=max_len,
                prompt_buckets=prompt_buckets, temperature=0.0,
                decode_burst=decode_burst, eos_id=eos_id,
                block_size=block_size, max_blocks_per_slot=cap_blocks,
                num_blocks=(
                    num_blocks if num_blocks is not None
                    else 1 + max_slots * (-(-max_len // block_size))
                ),
                prefix_cache=prefix_cache,
                spec_decode=spec_decode, spec_k=spec_k,
            ),
        )
    else:
        engine = SlotEngine(
            model, params,
            EngineConfig(
                max_slots=max_slots, max_len=max_len,
                prompt_buckets=prompt_buckets, temperature=0.0,
                decode_burst=decode_burst, eos_id=eos_id,
            ),
        )
    # no ServeMetrics inside the timed window: the bench computes its own
    # percentiles from completions, and the static baseline carries no
    # per-tick bookkeeping — keep the measured loops symmetric.
    # `telemetry` (when the plane is on) IS deliberately inside the
    # window: its cost is exactly what the overhead row measures.
    sched = Scheduler(engine, max_queue=len(trace), tracer=tracer,
                      telemetry=telemetry)
    if health_slot is not None:
        # single replica: /healthz reports one always-healthy lane
        health_slot["fn"] = lambda: {0: "healthy"}
    # warmup compiles outside the timed window: one admit per bucket in
    # play + one decode dispatch, then rewind (slot pool only — paged
    # blocks free individually at release, nothing to rewind)
    widths = sorted({engine.bucket_for(len(t["prompt"])) for t in trace})
    for w in widths:
        # budget only the one warmup burst: a default (reserve-the-cap)
        # paged admit could outsize a small pool that the gated
        # scheduler path would happily serve
        slot = engine.admit(list(range(1, w + 1))[:w],
                            max_positions=decode_burst)
        engine.step_burst()
        engine.release(slot)
    if getattr(engine, "drafter", None) is not None:
        # speculation on: compile the verify program outside the timed
        # window too. An all-ones prompt makes the lookup drafter
        # propose (every trailing n-gram recurs), then the warm
        # dispatch's counters are zeroed so the report reconciles
        # against workload-only numbers (same as engine.warm_engine).
        slot = engine.admit([1] * min(engine.buckets),
                            max_positions=spec_k + 1)
        w_drafts, w_lens, _ = engine.propose_drafts()
        engine.step_verify(w_drafts, w_lens)
        engine.release(slot)
        engine.spec_drafted_tokens = 0
        engine.spec_accepted_tokens = 0
        engine.spec_dispatches = 0
    if paged and prefix_cache:
        # warm the HIT path too: re-admitting a just-cached prompt
        # compiles the suffix-bucket prefix-prefill program. Then the
        # tree and its counters reset, so the timed window starts cold.
        for w in widths:
            slot = engine.admit(list(range(1, w + 1))[:w],
                                max_positions=decode_burst)
            engine.step_burst()
            engine.release(slot)
        engine.radix.clear()
        engine.radix.hit_tokens = engine.radix.miss_tokens = 0
        engine.preemptions = 0
    if not paged:
        engine.reset_epoch()
    if tracer is not None:
        # attach the engine lanes only after warmup, and drop anything
        # recorded so far: compile-time spans would dwarf the workload
        from ddp_practice_tpu.utils.trace import label_replica

        engine.set_tracer(tracer, 0)
        label_replica(tracer, 0, max_slots)
        tracer.clear()
        if telemetry is not None and hasattr(telemetry, "attach"):
            # sink attached only NOW: the stream gets the same
            # warmup-free timeline as the exit dump (labels replay)
            telemetry.attach(tracer)

    t0 = time.monotonic()
    i = 0
    while not (i >= len(trace) and sched.idle):
        now = time.monotonic() - t0
        while i < len(trace) and trace[i]["arrival"] <= now:
            t = trace[i]
            # arrivals are polled between scheduler steps, so a request
            # can be submitted up to one decode dispatch late; stamping
            # the TRUE trace arrival keeps its queueing wait inside the
            # measured TTFT/latency (the static loop is charged from the
            # same trace times)
            sched.submit(Request(
                rid=t["rid"], prompt=t["prompt"],
                max_new_tokens=t["max_new_tokens"],
                arrival=t0 + t["arrival"],
            ))
            i += 1
        if sched.idle:
            time.sleep(max(0.0, trace[i]["arrival"] - now))
            continue
        sched.step()
    elapsed = time.monotonic() - t0

    tokens = sum(len(c.tokens) for c in sched.completions)
    lat = [c.finish - c.arrival for c in sched.completions]
    extra = {}
    if paged:
        extra["preemptions"] = engine.preemptions
        extra["kv_bytes_per_token"] = _kv_bytes_per_token(
            engine._cache, engine.blocks.num_blocks, block_size
        )
        extra["num_blocks"] = engine.blocks.num_blocks
        if getattr(engine, "drafter", None) is not None:
            # the accept-rate observables the spec gate reads: how much
            # was drafted, how much the model agreed with, and how many
            # sequential dispatches speculation actually saved
            extra["spec"] = {
                "spec_k": spec_k,
                "drafted_tokens": engine.spec_drafted_tokens,
                "accepted_tokens": engine.spec_accepted_tokens,
                "accept_rate": (
                    engine.spec_accepted_tokens
                    / max(1, engine.spec_drafted_tokens)
                ),
                "verify_dispatches": engine.spec_dispatches,
            }
        if prefix_cache:
            # the proof-of-reuse counters the acceptance gate reads
            extra["prefix_cache"] = {
                "hit_tokens": engine.radix.hit_tokens,
                "miss_tokens": engine.radix.miss_tokens,
                "hit_rate": (
                    engine.radix.hit_tokens
                    / max(1, engine.radix.hit_tokens
                          + engine.radix.miss_tokens)
                ),
                "nodes": len(engine.radix),
            }
    if collect_tokens:
        # per-rid streams for cross-arm identity checks (the spec bench
        # compares them, then drops them from the written report)
        extra["tokens_by_rid"] = {
            c.rid: list(c.tokens) for c in sched.completions
        }
    return {
        "mode": ("paged+spec" if paged and spec_decode
                 else "paged+prefix" if paged and prefix_cache
                 else "paged" if paged else "continuous"),
        **extra,
        # largest total context one request can reach: the slot pool is
        # hard-capped by its shared clock (a request can never span more
        # than max_len - max_bucket decode positions from base), the
        # paged engine by its per-slot page-table width — which is free
        # to exceed max_len
        "max_servable_context": (
            engine.max_context if paged else max_len
        ),
        "elapsed_s": elapsed,
        "useful_tokens": tokens,
        "tokens_per_sec": tokens / elapsed,
        "ttft_s": _percentiles(
            [c.ttft for c in sched.completions if c.ttft is not None]
        ),
        "tpot_s": _percentiles(
            [c.tpot for c in sched.completions if c.tpot is not None]
        ),
        "latency_s": _percentiles(lat),
        # per-phase breakdown of the same latency population (flight
        # records: queue wait / prefill / decode / stall percentiles)
        "phases": _phase_breakdown(sched.completions),
        "completions": len(sched.completions),
        "compile_stats": engine.compile_stats(),
    }


def _run_router(model, params, trace, *, replicas, max_slots,
                prompt_buckets, max_len, decode_burst, eos_id,
                fault_plan=None, tracer=None, slo_config=None,
                telemetry=None, exporter=None, registry=None,
                health_slot=None, alert_sinks=None) -> dict:
    """The fleet path: N identical replicas behind the fault-tolerant
    router (serve/router.py). Scored like the continuous server — useful
    tokens of requests that finished ok — which under an injected
    FaultPlan is a GOODPUT number: tokens the fleet still delivered
    while replicas crashed, stalled, or emitted NaNs."""
    from ddp_practice_tpu.serve.engine import EngineConfig
    from ddp_practice_tpu.serve.router import RouterConfig, make_router
    from ddp_practice_tpu.serve.scheduler import MonotonicClock, Request

    clock = MonotonicClock()
    watchdog = None
    if slo_config is not None:
        from ddp_practice_tpu.serve.slo import AlertSinks, SLOWatchdog

        # live burn-rate alerting over the run's completions; alert
        # instants land in the trace and the JSONL stream, the router's
        # brown-out listens, and --alert-sink edges PUSH to operators
        # (command/webhook/jsonl with backoff + dead-sink breaker)
        sinks = (AlertSinks(alert_sinks, clock=clock,
                            registry=registry)
                 if alert_sinks else None)
        watchdog = SLOWatchdog(
            slo_config, clock=clock, registry=registry,
            tracer=tracer, telemetry=exporter, sinks=sinks,
        )
    router = make_router(
        model, params, replicas,
        EngineConfig(
            max_slots=max_slots, max_len=max_len,
            prompt_buckets=prompt_buckets, temperature=0.0,
            decode_burst=decode_burst, eos_id=eos_id,
        ),
        clock=clock,
        max_queue=len(trace),
        config=RouterConfig(),
        fault_plan=fault_plan,
        registry=registry,
        tracer=tracer,
        slo=watchdog,
        telemetry=telemetry,
    )
    if health_slot is not None:
        health_slot["fn"] = router.states
    # warm EVERY configured bucket, not just the trace prompts' widths:
    # failover re-prefills carry prompt+salvaged-tokens and can land in
    # a larger bucket — its compile must happen out here, not inside the
    # timed goodput window
    router.warmup()
    if tracer is not None:
        tracer.clear()  # drop warmup spans; keep the workload timeline
        if exporter is not None:
            # sink attached only after the clear: the streamed JSONL is
            # as warmup-free as the exit dump (lane labels replay)
            exporter.attach(tracer)

    t0 = time.monotonic()
    i = 0
    while not (i >= len(trace) and router.idle):
        now = time.monotonic() - t0
        while i < len(trace) and trace[i]["arrival"] <= now:
            t = trace[i]
            router.submit(Request(
                rid=t["rid"], prompt=t["prompt"],
                max_new_tokens=t["max_new_tokens"],
                arrival=t0 + t["arrival"],
            ))
            i += 1
        if router.idle:
            # idle with arrivals left: sleep to the next one. (idle with
            # NONE left is reachable too — door sheds on a dead fleet
            # finalize instantly — and the loop condition exits then.)
            if i < len(trace):
                time.sleep(max(0.0, trace[i]["arrival"] - now))
            continue
        router.step()
    elapsed = time.monotonic() - t0

    ok = [c for c in router.completions if c.status in ("eos", "length")]
    ok_tokens = sum(len(c.tokens) for c in ok)
    statuses: dict = {}
    for c in router.completions:
        statuses[c.status] = statuses.get(c.status, 0) + 1
    m = router.metrics
    out = {
        "mode": f"router x{replicas}",
        "elapsed_s": elapsed,
        "useful_tokens": ok_tokens,
        "tokens_per_sec": ok_tokens / elapsed,
        "goodput_tokens_per_sec": ok_tokens / elapsed,
        "ttft_s": _percentiles([c.ttft for c in ok if c.ttft is not None]),
        "tpot_s": _percentiles([c.tpot for c in ok if c.tpot is not None]),
        "latency_s": _percentiles([c.finish - c.arrival for c in ok]),
        # phase breakdown over the same ok population as latency_s;
        # stall_s here includes retry parking + dead-replica gaps
        "phases": _phase_breakdown(ok),
        "completions": len(router.completions),
        "statuses": statuses,
        "retries": m.retries.value,
        "failovers": m.failovers.value,
        "breaker_trips": m.breaker_trips.value,
        "replica_states": router.states(),
        "compile_stats": router.compile_stats(),
    }
    if watchdog is not None:
        out["slo"] = {
            "alerts": [
                {"t": t, "event": edge, "objective": obj}
                for t, edge, obj in watchdog.alert_log
            ],
            "active": dict(watchdog.alerts),
        }
    return out


def _fleet_wait(router, max_s: float) -> None:
    """Event-driven nap for a FLEET drive loop: sleep on the workers'
    push-stream fds so the parent wakes the instant a completion frame
    lands — no spin stealing the workers' core, no sleep-quantized
    consumption lag. Falls back to a 1 ms nap while streams are down."""
    import select

    fds = []
    for h in router.handles:
        fn = getattr(h, "stream_fileno", None)
        fd = fn() if fn is not None else None
        if fd is not None:
            fds.append(fd)
    if not fds:
        time.sleep(min(max_s, 0.001))
        return
    try:
        select.select(fds, [], [], max_s)
    except (OSError, ValueError):
        time.sleep(0.001)  # a stream died mid-select: step will resync


def _replay_through_router(router, trace, *, rid_offset: int = 0,
                           driver=None, fleet: bool = False) -> dict:
    """Replay one arrival trace through an EXISTING router (in-process
    or fleet — same Router API, that is the seam's point) and score it.
    `rid_offset` keeps rids unique across reps; `driver` is an optional
    FleetFaultDriver polled with elapsed seconds; `fleet=True` makes
    the drive loop EVENT-DRIVEN between ticks (select on the push
    streams — the decode runs in worker processes that a spinning
    parent would preempt on small machines; the in-process router
    decodes inside step(), so its loop must never sleep)."""
    from ddp_practice_tpu.serve.scheduler import Request

    before = len(router.completions)
    t0 = time.monotonic()
    i = 0
    while not (i >= len(trace) and router.idle):
        now = time.monotonic() - t0
        if driver is not None:
            driver.poll(now)
        while i < len(trace) and trace[i]["arrival"] <= now:
            t = trace[i]
            router.submit(Request(
                rid=t["rid"] + rid_offset, prompt=t["prompt"],
                max_new_tokens=t["max_new_tokens"],
                arrival=t0 + t["arrival"],
                # multi-tenant traces (serve/workload.py) carry these;
                # the single-tenant builders don't, and the defaults
                # keep their replays byte-identical
                tenant=t.get("tenant"),
                priority=t.get("priority", 0),
            ))
            i += 1
        if router.idle:
            if i < len(trace):
                time.sleep(max(0.0, trace[i]["arrival"] - now))
            continue
        router.step()
        if fleet:
            until_arrival = (trace[i]["arrival"] - (time.monotonic() - t0)
                             if i < len(trace) else 0.005)
            _fleet_wait(router, min(0.005, max(0.0, until_arrival)))
    elapsed = time.monotonic() - t0
    comps = router.completions[before:]
    ok = [c for c in comps if c.status in ("eos", "length")]
    ok_tokens = sum(len(c.tokens) for c in ok)
    statuses: dict = {}
    for c in comps:
        statuses[c.status] = statuses.get(c.status, 0) + 1
    return {
        "elapsed_s": elapsed,
        "useful_tokens": ok_tokens,
        "goodput_tokens_per_sec": ok_tokens / elapsed,
        "tokens_per_sec": ok_tokens / elapsed,
        "ttft_s": _percentiles([c.ttft for c in ok if c.ttft is not None]),
        "tpot_s": _percentiles([c.tpot for c in ok if c.tpot is not None]),
        "latency_s": _percentiles([c.finish - c.arrival for c in ok]),
        "phases": _phase_breakdown(ok),
        "completions": len(comps),
        # the zero-lost invariant, checked, not assumed
        "lost": len(trace) - len(comps),
        "statuses": statuses,
    }


def fleet_bench(
    *,
    n_requests: int = 32,
    rate_hz: float = 8.0,
    procs: int = 2,
    max_slots: int = 8,
    vocab: int = 64,
    hidden: int = 128,
    depth: int = 2,
    heads: int = 4,
    mlp: int = 256,
    max_len: int = 128,
    prompt_buckets=(8, 16),
    prompt_len_range=(2, 16),
    max_new_range=(2, 32),
    decode_burst: int = 8,
    eos_id: Optional[int] = 46,
    seed: int = 0,
    reps: int = 6,
    fault_plan=None,
    metrics_port: Optional[int] = None,
    trace_out: Optional[str] = None,
    trace_sample: float = 1.0,
    trace_keep_slow_s: Optional[float] = None,
    otlp_out: Optional[str] = None,
    otlp_endpoint: Optional[str] = None,
    trace_tenant_rates: Optional[dict] = None,
) -> dict:
    """One Poisson trace through `procs` worker OS PROCESSES behind the
    RPC seam (serve/worker.py + serve/supervisor.py) AND through
    `procs` in-process router replicas — the ratio rows are the seam's
    bill (acceptance gate: latency p50 <= 1.10x at 8 rps).

    `trace_out` arms the FLEET TRACE PLANE on the fleet side: workers
    record their own prefill/decode/request spans and stream them back
    over the push stream, the router-side TraceCollector merges them
    (clock-offset-aligned, pid=worker-N lanes) with the router's own
    dispatch/failover instants into ONE Chrome trace — under a kill
    plan, the dead worker's pre-crash spans and the survivor's spans
    share each migrated request's original trace_id. Validate with
    ``tools/check_traces.py --fleet``.

    Methodology (the PR-5 telemetry-overhead lesson, which measured ~5%
    of pure machine drift on this box): both routers are built ONCE
    (compiles amortized, same warm engines throughout), then the trace
    replays `reps` times ALTERNATING which side goes first; the
    headline ratios are medians of per-rep p50 ratios, so run-order
    drift cancels instead of being billed to the seam. A kill-bearing
    `fault_plan` switches to a single chaos rep (a killed worker is not
    a steady state to amortize) — real SIGKILL/SIGSTOP to live worker
    pids, goodput + zero-lost measured against actual process death."""
    from ddp_practice_tpu.serve.engine import EngineConfig
    from ddp_practice_tpu.serve.faults import FleetFaultDriver
    from ddp_practice_tpu.serve.router import RouterConfig, make_router
    from ddp_practice_tpu.serve.scheduler import MonotonicClock, Request
    from ddp_practice_tpu.serve.supervisor import (
        SupervisorConfig,
        make_federated_server,
        make_fleet_router,
    )
    from ddp_practice_tpu.serve.worker import WorkerSpec

    model_kw = {
        "vocab_size": vocab, "max_len": max_len, "hidden_dim": hidden,
        "depth": depth, "num_heads": heads, "mlp_dim": mlp,
        "pos_emb": "rope",
    }
    model, params = _build_model(
        vocab=vocab, max_len=max_len, hidden=hidden, depth=depth,
        heads=heads, mlp=mlp,
    )
    trace = build_trace(
        n_requests=n_requests, rate_hz=rate_hz, vocab=vocab,
        prompt_len_range=prompt_len_range, max_new_range=max_new_range,
        seed=seed,
    )
    chaos = fault_plan is not None and bool(fault_plan.kills())
    if fault_plan is not None:
        sim = [f.kind for f in fault_plan.faults if f.kind != "kill"]
        if sim:
            # refusing beats lying: workers carry no injector, so a
            # sim spec here would run fault-FREE while the report
            # stamps a fault plan it never executed
            raise ValueError(
                f"the --procs fleet bench interprets only 'kill' "
                f"specs (real signals); simulated faults {sim} ride "
                f"the in-process --replicas path"
            )
        bad = [f.replica for f in fault_plan.kills()
               if not 0 <= f.replica < procs]
        if bad:
            raise ValueError(
                f"kill spec replica(s) {bad} out of range for "
                f"--procs {procs}"
            )
    if chaos:
        reps = 1
    engine_cfg = EngineConfig(
        max_slots=max_slots, max_len=max_len,
        prompt_buckets=tuple(prompt_buckets), temperature=0.0,
        decode_burst=decode_burst, eos_id=eos_id,
    )
    # enough queue for every rep's worst backlog
    max_queue = len(trace) * max(1, reps)
    inproc = make_router(
        model, params, procs, engine_cfg, clock=MonotonicClock(),
        max_queue=max_queue, config=RouterConfig(),
    )
    inproc.warmup()
    tracer = _make_tracer() if trace_out else None
    spec = WorkerSpec(
        model=model_kw,
        engine={
            "max_slots": max_slots, "max_len": max_len,
            "prompt_buckets": list(prompt_buckets),
            "temperature": 0.0, "decode_burst": decode_burst,
            "eos_id": eos_id,
        },
        max_queue=max_queue,
        trace=(trace_out is not None or otlp_out is not None
               or otlp_endpoint is not None),
        trace_sample=trace_sample,
        trace_keep_slow_s=trace_keep_slow_s,
        trace_tenant_rates=trace_tenant_rates,
    )
    if tracer is None and (otlp_out or otlp_endpoint):
        tracer = _make_tracer()
    fleet_router, sup, handles = make_fleet_router(
        spec, procs, sup_config=SupervisorConfig(restart_base_s=0.25),
        tracer=tracer,
    )
    pusher = None
    if otlp_endpoint is not None and tracer is not None:
        # live egress for the whole run: kept spans drain to the
        # collector as they land, not at exit — the operator posture
        # the ISSUE-12 plane exists for
        from ddp_practice_tpu.utils.telemetry import OtlpPusher

        pusher = OtlpPusher(otlp_endpoint, tracer)
    server = None
    rep_rows = {"in_process": [], "fleet": []}
    ratios_p50 = []
    try:
        if metrics_port is not None:
            _, server = make_federated_server(sup, handles,
                                              port=metrics_port)
        driver = (FleetFaultDriver(fault_plan, sup.kill)
                  if chaos else None)
        for rep in range(reps):
            order = ["in_process", "fleet"]
            if rep % 2:
                order.reverse()
            for side in order:
                if side == "in_process":
                    row = _replay_through_router(
                        inproc, trace, rid_offset=rep * 1_000_000,
                    )
                else:
                    row = _replay_through_router(
                        fleet_router, trace,
                        rid_offset=rep * 1_000_000,
                        driver=driver, fleet=True,
                    )
                rep_rows[side].append(row)
            ratios_p50.append(
                rep_rows["fleet"][-1]["latency_s"]["p50"]
                / rep_rows["in_process"][-1]["latency_s"]["p50"]
            )

        def med(xs):
            s = sorted(xs)
            n = len(s)
            return (s[n // 2] if n % 2
                    else 0.5 * (s[n // 2 - 1] + s[n // 2]))

        def agg(side, key, pct):
            return med([r[key][pct] for r in rep_rows[side]])

        m = fleet_router.metrics
        fleet_row = dict(rep_rows["fleet"][-1])
        fleet_row.update({
            "mode": f"fleet x{procs}", "procs": procs,
            "latency_s": {p: agg("fleet", "latency_s", p)
                          for p in ("p50", "p90", "p99")},
            "ttft_s": {p: agg("fleet", "ttft_s", p)
                       for p in ("p50", "p90", "p99")},
            "lost": sum(r["lost"] for r in rep_rows["fleet"]),
            "retries": m.retries.value,
            "failovers": m.failovers.value,
            "breaker_trips": m.breaker_trips.value,
            "replica_states": fleet_router.states(),
            "worker_restarts": list(sup.restarts),
        })
        if driver is not None:
            fleet_row["kills_fired"] = [
                {"replica": f.replica, "sig": f.sig, "at_s": f.at_s}
                for f in driver.fired
            ]
        if server is not None:
            fleet_row["federated_port"] = server.port
        inproc_row = dict(rep_rows["in_process"][-1])
        inproc_row.update({
            "mode": f"router x{procs}",
            "latency_s": {p: agg("in_process", "latency_s", p)
                          for p in ("p50", "p90", "p99")},
            "ttft_s": {p: agg("in_process", "ttft_s", p)
                       for p in ("p50", "p90", "p99")},
            "lost": sum(r["lost"] for r in rep_rows["in_process"]),
        })
        report = {
            "trace": {
                "n_requests": n_requests, "rate_hz": rate_hz,
                "seed": seed,
                "prompt_len_range": list(prompt_len_range),
                "max_new_range": list(max_new_range),
            },
            "procs": procs,
            "reps": reps,
            "in_process": inproc_row,
            "fleet": fleet_row,
            # medians of per-rep ratios: order-balanced, drift-robust
            "latency_ratio_p50": med(ratios_p50),
            "latency_ratio_p50_per_rep": ratios_p50,
            "latency_ratio_p99": med(
                [f["latency_s"]["p99"] / i["latency_s"]["p99"]
                 for f, i in zip(rep_rows["fleet"],
                                 rep_rows["in_process"])]
            ),
            "goodput_ratio": med(
                [f["goodput_tokens_per_sec"]
                 / i["goodput_tokens_per_sec"]
                 for f, i in zip(rep_rows["fleet"],
                                 rep_rows["in_process"])]
            ),
        }
        # steady-state decode parity (TPOT: inter-token latency after
        # the first token — the RPC seam is off this path entirely) and
        # admission overhead (TTFT: the submit hop + worker wake ARE on
        # this path) — the decomposition of where the ratio comes from
        report["tpot_ratio_p50"] = med(
            [f["tpot_s"]["p50"] / i["tpot_s"]["p50"]
             for f, i in zip(rep_rows["fleet"], rep_rows["in_process"])
             if i["tpot_s"]["p50"]]
        )
        report["ttft_ratio_p50"] = med(
            [f["ttft_s"]["p50"] / i["ttft_s"]["p50"]
             for f, i in zip(rep_rows["fleet"], rep_rows["in_process"])
             if i["ttft_s"]["p50"]]
        )
        if fault_plan is not None:
            report["fault_plan"] = fault_plan.to_json()
        if tracer is not None:
            col = fleet_router.trace_collector
            if trace_out:
                tracer.save(trace_out)
                report["trace_out"] = trace_out
            if otlp_out:
                tracer.save_otlp(otlp_out)
                report["otlp_out"] = otlp_out
            report["trace_events"] = len(tracer)
            report["trace_plane"] = {
                "worker_frames": col.frames if col else 0,
                "worker_events": col.events if col else 0,
                "dropped": tracer.dropped,
                "skew_bound_s": col.skew_bound() if col else None,
            }
            meta = tracer.sampling_meta()
            if meta is not None:
                report["sampling"] = meta
        if pusher is not None:
            pusher.close()  # final drain before the counters are read
            report["otlp_push"] = {
                "endpoint": otlp_endpoint,
                "batches_sent": pusher.batches_sent,
                "spans_sent": pusher.spans_sent,
                "batches_dropped": pusher.batches_dropped,
                "post_failures": pusher.post_failures,
                "dead": pusher.dead,
            }
            pusher = None
        return report
    finally:
        if pusher is not None:
            pusher.close()
        if server is not None:
            server.close()
        sup.stop()


def _fleet_kv_counters(router) -> tuple:
    """Summed (hit_tokens, miss_tokens) over every worker's
    heartbeat-carried kv summary — the fleet's prefix-cache ledger."""
    hit = miss = 0
    for h in router.handles:
        kv = getattr(h, "kv_summary", None)
        if isinstance(kv, dict):
            hit += kv.get("hit_tokens", 0)
            miss += kv.get("miss_tokens", 0)
    return hit, miss


def cache_routing_bench(
    *,
    n_requests: int = 48,
    rate_hz: float = 100.0,
    procs: int = 2,
    max_slots: int = 4,
    block_size: int = 16,
    # undersized on purpose: 24 usable blocks can hold TWO families'
    # prefix blocks (12) plus the transient working set, but not all
    # FOUR (24) — so spraying every family across the fleet (least-
    # loaded) keeps evicting and re-paying cold prefill in steady
    # state, while affinity's partition stays warm. 32+ blocks fit
    # everything resident and flatten the contrast to the one-time
    # warmup; tighter starves decode on both arms.
    num_blocks: int = 25,
    k_prefixes: int = 4,
    prefix_len: int = 96,
    vocab: int = 64,
    hidden: int = 128,
    depth: int = 2,
    heads: int = 4,
    mlp: int = 256,
    max_len: int = 128,
    decode_burst: int = 8,
    seed: int = 0,
    reps: int = 6,
) -> dict:
    """The cache-aware routing A/B: ONE shared-prefix trace (K system
    prompts x unique tails) replayed through TWO identical 2-worker
    fleets at the same paged pool — one routing by prefix affinity
    (RouterConfig.cache_aware, serve/affinity.py), one by the classic
    least-loaded order. Affinity partitions the K families across the
    fleet so each warms ONCE; least-loaded sprays them, so every family
    pays its cold prefill on every worker (and re-pays it whenever
    churn evicts a copy). Headlines: the fleet prefix-hit-token rate
    (from the workers' own radix hit/miss counters — ground truth, not
    the router's estimate) and the goodput ratio, plus the zero-lost
    and greedy token-identity invariants (routing must change WHERE
    requests run, never WHAT they produce). Order-balanced alternating
    reps, medians of per-rep ratios, same methodology as fleet_bench."""
    from ddp_practice_tpu.serve.router import RouterConfig
    from ddp_practice_tpu.serve.supervisor import (
        SupervisorConfig,
        make_fleet_router,
    )
    from ddp_practice_tpu.serve.worker import WorkerSpec

    trace = build_shared_prefix_trace(
        n_requests=n_requests, rate_hz=rate_hz, vocab=vocab,
        k_prefixes=k_prefixes, prefix_len=prefix_len,
        tail_range=(1, 8), max_new_range=(8, 24), seed=seed,
    )
    max_prompt = max(len(t["prompt"]) for t in trace)
    bucket = block_size
    while bucket < max_prompt:
        bucket += block_size
    # small buckets matter: a warm admit prefills only the UNCACHED
    # remainder, and its span is matched + bucket_for(remainder) — with
    # only the full-prompt bucket, every warm request would blow the
    # per-slot capacity and be rejected instead of hitting the cache
    buckets = sorted({16, 32, 64, bucket})
    spec = WorkerSpec(
        model={
            "vocab_size": vocab, "max_len": max_len,
            "hidden_dim": hidden, "depth": depth, "num_heads": heads,
            "mlp_dim": mlp, "pos_emb": "rope",
        },
        engine={
            "paged": True, "prefix_cache": True,
            "num_blocks": num_blocks, "block_size": block_size,
            "max_slots": max_slots, "max_len": max_len,
            "prompt_buckets": buckets,
            # greedy: the token-identity invariant needs bit-equal
            # streams across arms
            "temperature": 0.0, "decode_burst": decode_burst,
            "eos_id": None,
        },
        max_queue=len(trace) * max(1, reps),
    )
    arms = {}
    sups = []
    try:
        for name, aware in (("affinity", True), ("least_loaded", False)):
            router, sup, _handles = make_fleet_router(
                spec, procs,
                config=RouterConfig(cache_aware=aware),
                sup_config=SupervisorConfig(restart_base_s=0.25),
            )
            arms[name] = router
            sups.append(sup)
        rep_rows = {"affinity": [], "least_loaded": []}
        tokens_by_rid = {"affinity": {}, "least_loaded": {}}
        for rep in range(reps):
            order = ["affinity", "least_loaded"]
            if rep % 2:
                order.reverse()
            for side in order:
                router = arms[side]
                before_kv = _fleet_kv_counters(router)
                n_before = len(router.completions)
                row = _replay_through_router(
                    router, trace, rid_offset=rep * 1_000_000,
                    fleet=True,
                )
                # one settle tick so the final heartbeat's kv counters
                # (which rode the last poll) are current before the delta
                router.step()
                hit0, miss0 = before_kv
                hit1, miss1 = _fleet_kv_counters(router)
                dh, dm = hit1 - hit0, miss1 - miss0
                row["hit_tokens"] = dh
                row["miss_tokens"] = dm
                row["hit_rate"] = dh / (dh + dm) if dh + dm else 0.0
                rep_rows[side].append(row)
                for c in router.completions[n_before:]:
                    if c.status in ("eos", "length"):
                        tokens_by_rid[side][c.rid] = list(c.tokens)

        def med(xs):
            s = sorted(xs)
            n = len(s)
            return (s[n // 2] if n % 2
                    else 0.5 * (s[n // 2 - 1] + s[n // 2]))

        # greedy token identity: same rid (rep-offset included) must
        # yield the same tokens on both arms — routing is placement,
        # never content
        shared = set(tokens_by_rid["affinity"]) & set(
            tokens_by_rid["least_loaded"])
        same = sum(
            1 for r in shared
            if tokens_by_rid["affinity"][r]
            == tokens_by_rid["least_loaded"][r]
        )
        identity = same / len(shared) if shared else 0.0
        routes: dict = {}
        for c in arms["affinity"].completions:
            fl = c.flight or {}
            r = fl.get("route")
            if r is not None:
                routes[r] = routes.get(r, 0) + 1

        def arm_row(side):
            rows = rep_rows[side]
            return {
                "mode": f"{side} x{procs}",
                "goodput_tokens_per_sec": med(
                    [r["goodput_tokens_per_sec"] for r in rows]),
                "hit_rate": med([r["hit_rate"] for r in rows]),
                "hit_tokens": sum(r["hit_tokens"] for r in rows),
                "miss_tokens": sum(r["miss_tokens"] for r in rows),
                "latency_s": {p: med([r["latency_s"][p] for r in rows])
                              for p in ("p50", "p90", "p99")},
                "lost": sum(r["lost"] for r in rows),
            }

        aff, ll = arm_row("affinity"), arm_row("least_loaded")
        aff["route_decisions"] = routes
        return {
            "trace": {
                "n_requests": n_requests, "rate_hz": rate_hz,
                "seed": seed, "k_prefixes": k_prefixes,
                "prefix_len": prefix_len,
            },
            "pool": {"num_blocks": num_blocks,
                     "block_size": block_size},
            "procs": procs,
            "reps": reps,
            "affinity": aff,
            "least_loaded": ll,
            # medians of per-rep ratios (order-balanced): the fleet
            # prefix memory's bill, robust to machine drift
            "hit_rate_ratio": med([
                (a["hit_rate"] / b["hit_rate"]) if b["hit_rate"]
                else float(a["hit_rate"] > 0)
                for a, b in zip(rep_rows["affinity"],
                                rep_rows["least_loaded"])
            ]),
            "goodput_ratio": med([
                a["goodput_tokens_per_sec"]
                / b["goodput_tokens_per_sec"]
                for a, b in zip(rep_rows["affinity"],
                                rep_rows["least_loaded"])
            ]),
            "token_identity": identity,
            "lost": aff["lost"] + ll["lost"],
        }
    finally:
        for sup in sups:
            sup.stop()


def fleet_trace_overhead_bench(
    *,
    n_requests: int = 32,
    rate_hz: float = 8.0,
    procs: int = 2,
    max_slots: int = 8,
    vocab: int = 64,
    hidden: int = 128,
    depth: int = 2,
    heads: int = 4,
    mlp: int = 256,
    max_len: int = 128,
    prompt_buckets=(8, 16),
    prompt_len_range=(2, 16),
    max_new_range=(2, 32),
    decode_burst: int = 8,
    eos_id: Optional[int] = 46,
    seed: int = 0,
    pairs: int = 12,
    trace_out: Optional[str] = None,
) -> dict:
    """Fleet trace COLLECTION on/off overhead at the
    fleet_x2_overhead_8rps operating point (the acceptance gate:
    mean <= 2%).

    ONE warm worker fleet serves every rep; the whole trace plane —
    worker-side span recording (flipped live via the rpc ``trace``
    op), push-frame streaming, router-side collection and the fleet
    recorder — toggles between reps. Reps run in ALTERNATING order
    (on-first, then off-first) and the headline is the median of
    per-pair ratios, the PR-5/PR-7 methodology that cancels this box's
    ±15% drift instead of billing it to the plane. The ON reps' merged
    timeline is saved to `trace_out` (validated fleet-mode by the
    caller/tests), and the report carries the exemplar-resolution
    check: every trace_id exposed as a /metrics bucket exemplar must
    name a request present in the merged trace."""
    from ddp_practice_tpu.serve.supervisor import (
        SupervisorConfig,
        make_fleet_router,
    )
    from ddp_practice_tpu.serve.worker import WorkerSpec

    model_kw = {
        "vocab_size": vocab, "max_len": max_len, "hidden_dim": hidden,
        "depth": depth, "num_heads": heads, "mlp_dim": mlp,
        "pos_emb": "rope",
    }
    trace = build_trace(
        n_requests=n_requests, rate_hz=rate_hz, vocab=vocab,
        prompt_len_range=prompt_len_range, max_new_range=max_new_range,
        seed=seed,
    )
    tracer = _make_tracer()
    spec = WorkerSpec(
        model=model_kw,
        engine={
            "max_slots": max_slots, "max_len": max_len,
            "prompt_buckets": list(prompt_buckets),
            "temperature": 0.0, "decode_burst": decode_burst,
            "eos_id": eos_id,
        },
        max_queue=len(trace) * (2 * pairs + 2),
        trace=True,
    )
    router, sup, handles = make_fleet_router(
        spec, procs, sup_config=SupervisorConfig(restart_base_s=0.25),
        tracer=tracer,
    )

    def set_plane(on: bool) -> None:
        for h in handles:
            h.set_trace(on)
        if on:
            tracer.enable()
        else:
            tracer.disable()

    rows = {"on": [], "off": []}
    try:
        # one untimed shakeout rep with the plane ON: streams connect,
        # clock offsets get their first samples, then the recorder
        # clears so the saved timeline holds only measured reps
        set_plane(True)
        _replay_through_router(router, trace, rid_offset=90_000_000,
                               fleet=True)
        tracer.clear()
        for i in range(pairs):
            order = ["on", "off"] if i % 2 == 0 else ["off", "on"]
            for side in order:
                set_plane(side == "on")
                rows[side].append(_replay_through_router(
                    router, trace,
                    rid_offset=(2 * i + order.index(side)) * 1_000_000,
                    fleet=True,
                ))
        # one final ON rep: the buckets' last-exemplar slots now point
        # at requests that ARE in the merged timeline (off-rep requests
        # legitimately are not — their spans were never recorded)
        set_plane(True)
        _replay_through_router(router, trace, rid_offset=91_000_000,
                               fleet=True)

        def med(xs):
            s = sorted(xs)
            n = len(s)
            return (s[n // 2] if n % 2
                    else 0.5 * (s[n // 2 - 1] + s[n // 2]))

        ratios_p50 = [on["latency_s"]["p50"] / off["latency_s"]["p50"]
                      for on, off in zip(rows["on"], rows["off"])]
        ratios_mean = [on["latency_s"]["mean"] / off["latency_s"]["mean"]
                       for on, off in zip(rows["on"], rows["off"])]
        col = router.trace_collector
        report = {
            "trace": {
                "n_requests": n_requests, "rate_hz": rate_hz,
                "seed": seed,
                "prompt_len_range": list(prompt_len_range),
                "max_new_range": list(max_new_range),
            },
            "procs": procs,
            "pairs": pairs,
            "gate": "mean <= 1.02x",
            "latency_ratio_p50": med(ratios_p50),
            "latency_ratio_mean": med(ratios_mean),
            "latency_ratio_mean_per_pair": ratios_mean,
            "goodput_ratio": med(
                [on["goodput_tokens_per_sec"]
                 / off["goodput_tokens_per_sec"]
                 for on, off in zip(rows["on"], rows["off"])]
            ),
            "on": {"latency_s": rows["on"][-1]["latency_s"],
                   "lost": sum(r["lost"] for r in rows["on"])},
            "off": {"latency_s": rows["off"][-1]["latency_s"],
                    "lost": sum(r["lost"] for r in rows["off"])},
            "trace_events": len(tracer),
            "trace_plane": {
                "worker_frames": col.frames if col else 0,
                "worker_events": col.events if col else 0,
                "dropped": tracer.dropped,
                "skew_bound_s": col.skew_bound() if col else None,
            },
        }
        # exemplar resolution: every trace_id a worker's /metrics
        # exposes as a bucket exemplar must point at a request present
        # in the merged timeline — the p99-bucket-to-trace jump works
        report["exemplars"] = _exemplar_resolution(sup, handles, tracer)
        if trace_out:
            tracer.save(trace_out)
            report["trace_out"] = trace_out
        return report
    finally:
        sup.stop()


def fleet_trace_sampling_bench(
    *,
    n_requests: int = 200,
    rate_hz: float = 100.0,
    procs: int = 2,
    max_slots: int = 8,
    vocab: int = 64,
    hidden: int = 128,
    depth: int = 2,
    heads: int = 4,
    mlp: int = 256,
    max_len: int = 128,
    prompt_buckets=(8, 16),
    prompt_len_range=(2, 16),
    max_new_range=(2, 32),
    decode_burst: int = 8,
    eos_id: Optional[int] = 46,
    seed: int = 0,
    pairs: int = 6,
    sample: float = 0.01,
    keep_slow_s: Optional[float] = None,
    trace_out: Optional[str] = None,
    otlp_out: Optional[str] = None,
) -> dict:
    """Head-sampled trace plane at 100 rps: three arms against ONE warm
    worker fleet — ``sampled`` (head rate `sample`, default 1%),
    ``full`` (rate 1.0) and ``off`` (plane disabled), rotated in
    order-balanced rounds (the PR-5/7 drift-cancelling methodology).

    The two acceptance numbers:

    - ``span_reduction``: 1 - sampled/full recorded-span count (median
      over rounds; gate >= 0.95 at 1%) — upstream SUPPRESSION, counted
      at the fleet recorder after worker streaming, so it proves the
      workers never recorded/streamed the suppressed spans, not that a
      collector filtered them;
    - ``mean_ratio``: sampled-arm / off-arm mean latency (median over
      rounds; gate <= 1.02x) — what the 1% plane costs against no
      plane at all.

    Both ends of the RPC seam hold a sampler over the SAME crc32 hash
    (utils/trace.py head_keep) and the router's verdict additionally
    rides each submit frame, so worker and router cannot disagree; the
    per-arm rate flips live via the rpc ``trace`` op's ``sample``
    field. The final sampled rep's merged timeline is saved to
    `trace_out` (Chrome) and `otlp_out` (OTLP-JSON,
    tools/check_otlp.py)."""
    from ddp_practice_tpu.serve.supervisor import (
        SupervisorConfig,
        make_fleet_router,
    )
    from ddp_practice_tpu.serve.worker import WorkerSpec

    model_kw = {
        "vocab_size": vocab, "max_len": max_len, "hidden_dim": hidden,
        "depth": depth, "num_heads": heads, "mlp_dim": mlp,
        "pos_emb": "rope",
    }
    trace = build_trace(
        n_requests=n_requests, rate_hz=rate_hz, vocab=vocab,
        prompt_len_range=prompt_len_range, max_new_range=max_new_range,
        seed=seed,
    )
    tracer = _make_tracer()
    spec = WorkerSpec(
        model=model_kw,
        engine={
            "max_slots": max_slots, "max_len": max_len,
            "prompt_buckets": list(prompt_buckets),
            "temperature": 0.0, "decode_burst": decode_burst,
            "eos_id": eos_id,
        },
        max_queue=len(trace) * (3 * pairs + 2),
        trace=True,
        trace_sample=sample,
        trace_keep_slow_s=keep_slow_s,
    )
    router, sup, handles = make_fleet_router(
        spec, procs, sup_config=SupervisorConfig(restart_base_s=0.25),
        tracer=tracer,
    )
    if tracer.sampler is None:  # --trace-sample 1.0: still need a knob
        from ddp_practice_tpu.utils.trace import TraceSampler

        tracer.set_sampler(TraceSampler(sample, keep_slow_s=keep_slow_s))
    arms = ("sampled", "full", "off")
    rates = {"sampled": sample, "full": 1.0}

    def set_arm(arm: str) -> None:
        if arm == "off":
            for h in handles:
                h.set_trace(False)
            tracer.disable()
            return
        for h in handles:
            h.set_trace(True, sample=rates[arm])
        tracer.sampler.rate = rates[arm]
        tracer.enable()

    def drain_frames() -> None:
        # trace frames ride the push stream behind the pub frames —
        # give the last worker flush a moment to land before counting
        deadline = time.monotonic() + 0.5
        while time.monotonic() < deadline:
            router.step()
            _fleet_wait(router, 0.01)

    rows = {a: [] for a in arms}
    spans = {a: [] for a in arms}
    try:
        # untimed shakeout: streams connect, offsets sampled, compiles
        # long since amortized by make_fleet_router's warm boot
        set_arm("sampled")
        _replay_through_router(router, trace, rid_offset=90_000_000,
                               fleet=True)
        drain_frames()
        tracer.clear()
        for i in range(pairs):
            order = arms[i % 3:] + arms[:i % 3]
            for arm in order:
                set_arm(arm)
                rows[arm].append(_replay_through_router(
                    router, trace,
                    rid_offset=(3 * i + order.index(arm)) * 1_000_000,
                    fleet=True,
                ))
                if arm != "off":
                    drain_frames()
                spans[arm].append(len(tracer))
                tracer.clear()
        # one final SAMPLED rep, kept in the recorder: the exported
        # artifacts show what a 1% operator actually ships
        set_arm("sampled")
        _replay_through_router(router, trace, rid_offset=91_000_000,
                               fleet=True)
        drain_frames()

        def med(xs):
            s = sorted(xs)
            n = len(s)
            return (s[n // 2] if n % 2
                    else 0.5 * (s[n // 2 - 1] + s[n // 2]))

        mean_ratios = [
            s["latency_s"]["mean"] / o["latency_s"]["mean"]
            for s, o in zip(rows["sampled"], rows["off"])
        ]
        # headline = ratio of per-arm MEDIAN means, not the median of
        # per-round ratios: one scheduler hiccup in one round inflates
        # a paired ratio permanently, while the pooled medians shrug
        # off a spiked round on either side (the per-round ratios stay
        # in the report to keep the spread visible)
        pooled_mean_ratio = (
            med([r["latency_s"]["mean"] for r in rows["sampled"]])
            / med([r["latency_s"]["mean"] for r in rows["off"]])
        )
        reductions = [
            1.0 - (s / f) if f else 0.0
            for s, f in zip(spans["sampled"], spans["full"])
        ]
        col = router.trace_collector
        report = {
            "trace": {
                "n_requests": n_requests, "rate_hz": rate_hz,
                "seed": seed,
                "prompt_len_range": list(prompt_len_range),
                "max_new_range": list(max_new_range),
            },
            "procs": procs,
            "pairs": pairs,
            "head_rate": sample,
            "keep_slow_s": keep_slow_s,
            "gate": "mean <= 1.02x vs off; span reduction >= 0.95",
            "mean_ratio": pooled_mean_ratio,
            "mean_ratio_per_round": mean_ratios,
            "span_reduction": med(reductions),
            "span_reduction_per_round": reductions,
            "spans_per_rep": {a: spans[a] for a in arms},
            "sampled": {
                "latency_s": rows["sampled"][-1]["latency_s"],
                "lost": sum(r["lost"] for r in rows["sampled"]),
            },
            "off": {"latency_s": rows["off"][-1]["latency_s"],
                    "lost": sum(r["lost"] for r in rows["off"])},
            "full": {"lost": sum(r["lost"] for r in rows["full"])},
            "sampling": tracer.sampling_meta(),
            "trace_plane": {
                "worker_frames": col.frames if col else 0,
                "worker_events": col.events if col else 0,
                "dropped": tracer.dropped,
                "skew_bound_s": col.skew_bound() if col else None,
            },
        }
        if trace_out:
            tracer.save(trace_out)
            report["trace_out"] = trace_out
        if otlp_out:
            tracer.save_otlp(otlp_out)
            report["otlp_out"] = otlp_out
        return report
    finally:
        sup.stop()


def fleet_otlp_push_bench(
    *,
    n_requests: int = 200,
    rate_hz: float = 100.0,
    procs: int = 2,
    max_slots: int = 8,
    vocab: int = 64,
    hidden: int = 128,
    depth: int = 2,
    heads: int = 4,
    mlp: int = 256,
    max_len: int = 128,
    prompt_buckets=(8, 16),
    prompt_len_range=(2, 16),
    max_new_range=(2, 32),
    decode_burst: int = 8,
    eos_id: Optional[int] = 46,
    seed: int = 0,
    pairs: int = 6,
    sample: float = 1.0,
    otlp_endpoint: Optional[str] = None,
    capture_dir: Optional[str] = None,
) -> dict:
    """Live OTLP/HTTP push vs file-only export at 100 rps: two arms
    against ONE warm worker fleet — ``file`` (tracer on, spans kept in
    memory for an exit-time save, the PR-11 posture) and ``push`` (the
    same tracer drained live by a background OtlpPusher POSTing real
    batches over real HTTP), rotated in order-balanced rounds.

    The acceptance number is ``mean_ratio``: push-arm / file-arm mean
    latency (ratio of per-arm median means; gate <= 1.02x) — what
    LIVE egress costs the serve loop against batching to disk. The
    tracer runs at FULL head rate by default so the pusher is fed the
    worst-case span flow, not a 1% trickle.

    With no ``otlp_endpoint`` the bench stands up its own
    StubOtlpCollector and additionally audits COMPLETENESS: every span
    the pusher claims to have sent must be present in the collector's
    batch-id-deduped capture (``spans_delivered`` == ``spans_pushed``).
    Each push round gets a fresh pusher whose final flush happens in
    ``close()`` OUTSIDE the timed window — the timed cost is the
    concurrent drain/POST traffic, which is the thing the gate is
    about."""
    from ddp_practice_tpu.serve.supervisor import (
        SupervisorConfig,
        make_fleet_router,
    )
    from ddp_practice_tpu.serve.worker import WorkerSpec
    from ddp_practice_tpu.utils.telemetry import (
        OtlpPusher,
        StubOtlpCollector,
    )

    model_kw = {
        "vocab_size": vocab, "max_len": max_len, "hidden_dim": hidden,
        "depth": depth, "num_heads": heads, "mlp_dim": mlp,
        "pos_emb": "rope",
    }
    trace = build_trace(
        n_requests=n_requests, rate_hz=rate_hz, vocab=vocab,
        prompt_len_range=prompt_len_range, max_new_range=max_new_range,
        seed=seed,
    )
    tracer = _make_tracer()
    spec = WorkerSpec(
        model=model_kw,
        engine={
            "max_slots": max_slots, "max_len": max_len,
            "prompt_buckets": list(prompt_buckets),
            "temperature": 0.0, "decode_burst": decode_burst,
            "eos_id": eos_id,
        },
        max_queue=len(trace) * (2 * pairs + 2),
        trace=True,
        trace_sample=sample,
    )
    router, sup, handles = make_fleet_router(
        spec, procs, sup_config=SupervisorConfig(restart_base_s=0.25),
        tracer=tracer,
    )
    collector = None
    endpoint = otlp_endpoint
    if endpoint is None:
        collector = StubOtlpCollector(capture_dir=capture_dir)
        endpoint = collector.endpoint

    def drain_frames() -> None:
        deadline = time.monotonic() + 0.5
        while time.monotonic() < deadline:
            router.step()
            _fleet_wait(router, 0.01)

    arms = ("file", "push")
    rows = {a: [] for a in arms}
    push_stats = {"batches_sent": 0, "spans_sent": 0,
                  "post_failures": 0, "batches_dropped": 0}
    try:
        # untimed shakeout (streams, offsets, warm boot amortized)
        _replay_through_router(router, trace, rid_offset=90_000_000,
                               fleet=True)
        drain_frames()
        tracer.clear()
        for i in range(pairs):
            order = arms if i % 2 == 0 else arms[::-1]
            for arm in order:
                rid_offset = (2 * i + order.index(arm)) * 1_000_000
                if arm == "push":
                    pusher = OtlpPusher(endpoint, tracer,
                                        interval_s=0.25)
                    try:
                        rows[arm].append(_replay_through_router(
                            router, trace, rid_offset=rid_offset,
                            fleet=True))
                        drain_frames()
                    finally:
                        pusher.close()  # final flush, untimed
                    for k in push_stats:
                        push_stats[k] += getattr(pusher, k)
                else:
                    rows[arm].append(_replay_through_router(
                        router, trace, rid_offset=rid_offset,
                        fleet=True))
                    drain_frames()
                tracer.clear()

        def med(xs):
            s = sorted(xs)
            n = len(s)
            return (s[n // 2] if n % 2
                    else 0.5 * (s[n // 2 - 1] + s[n // 2]))

        mean_ratios = [
            p["latency_s"]["mean"] / f["latency_s"]["mean"]
            for p, f in zip(rows["push"], rows["file"])
        ]
        pooled_mean_ratio = (
            med([r["latency_s"]["mean"] for r in rows["push"]])
            / med([r["latency_s"]["mean"] for r in rows["file"]])
        )
        report = {
            "trace": {
                "n_requests": n_requests, "rate_hz": rate_hz,
                "seed": seed,
                "prompt_len_range": list(prompt_len_range),
                "max_new_range": list(max_new_range),
            },
            "procs": procs,
            "pairs": pairs,
            "head_rate": sample,
            "gate": "mean <= 1.02x vs file-only export",
            "mean_ratio": pooled_mean_ratio,
            "mean_ratio_per_round": mean_ratios,
            "push": {
                **push_stats,
                "spans_pushed": push_stats["spans_sent"],
            },
            "file": {"latency_s": rows["file"][-1]["latency_s"],
                     "lost": sum(r["lost"] for r in rows["file"])},
            "push_arm": {"latency_s": rows["push"][-1]["latency_s"],
                         "lost": sum(r["lost"] for r in rows["push"])},
        }
        if collector is not None:
            report["push"]["spans_delivered"] = collector.spans
            report["push"]["batches_received"] = len(collector.seen)
            report["push"]["duplicate_batches"] = collector.duplicates
            report["push"]["complete"] = bool(
                collector.spans == push_stats["spans_sent"])
            if capture_dir:
                report["push"]["capture_dir"] = capture_dir
        return report
    finally:
        sup.stop()
        if collector is not None:
            collector.close()


def fleet_adaptive_sampling_bench(
    *,
    rate_hz: float = 100.0,
    step_factor: float = 4.0,
    budget_sps: float = 150.0,
    chunk_s: float = 1.0,
    chunks_base: int = 2,
    chunks_step: int = 5,
    chunks_measure: int = 3,
    procs: int = 2,
    max_slots: int = 8,
    vocab: int = 64,
    hidden: int = 128,
    depth: int = 2,
    heads: int = 4,
    mlp: int = 256,
    max_len: int = 128,
    prompt_buckets=(8, 16),
    prompt_len_range=(2, 16),
    max_new_range=(2, 32),
    decode_burst: int = 8,
    eos_id: Optional[int] = 46,
    seed: int = 0,
) -> dict:
    """Adaptive head-rate control under a real load step: one warm
    fleet driven in ~`chunk_s` arrival chunks at `rate_hz`, then
    stepped to `rate_hz * step_factor` (default 4x), with an
    AdaptiveHeadRateController stepping between chunks and pushing
    every rate change to the workers via the live rpc ``trace`` op.

    The acceptance pair, measured over the FINAL `chunks_measure`
    chunks (after the controller has had the step phase to converge):

    - ``kept_sps`` vs ``budget_sps`` as ``budget_err`` (relative), and
    - ``within_budget``: 1.0 iff the error is <= 0.20 — the ±20%
      contract, reported as a 0/1 so check_bench can gate it
      absolutely (baseline 1, tol 0).

    Both the controller's observations and the final measurement use
    the same wall-clock basis (real elapsed time including inter-chunk
    drains), so the loop is judged against exactly the flow it could
    see. ``rate_changes``/``rate_log`` keep the correction history
    visible — a converged run makes 2-4 changes, not a change per
    evaluation."""
    from ddp_practice_tpu.serve.supervisor import (
        SupervisorConfig,
        make_fleet_router,
    )
    from ddp_practice_tpu.serve.worker import WorkerSpec
    from ddp_practice_tpu.utils.trace import AdaptiveHeadRateController

    model_kw = {
        "vocab_size": vocab, "max_len": max_len, "hidden_dim": hidden,
        "depth": depth, "num_heads": heads, "mlp_dim": mlp,
        "pos_emb": "rope",
    }

    def chunk(rate: float, k: int):
        return build_trace(
            n_requests=max(8, int(rate * chunk_s)), rate_hz=rate,
            vocab=vocab, prompt_len_range=prompt_len_range,
            max_new_range=max_new_range, seed=seed + 7 * k + 1,
        )

    step_rate = rate_hz * step_factor
    total_chunks = chunks_base + chunks_step + chunks_measure
    tracer = _make_tracer()
    spec = WorkerSpec(
        model=model_kw,
        engine={
            "max_slots": max_slots, "max_len": max_len,
            "prompt_buckets": list(prompt_buckets),
            "temperature": 0.0, "decode_burst": decode_burst,
            "eos_id": eos_id,
        },
        max_queue=int(step_rate * chunk_s) * (total_chunks + 2),
        trace=True,
        trace_sample=1.0,
    )
    router, sup, handles = make_fleet_router(
        spec, procs, sup_config=SupervisorConfig(restart_base_s=0.25),
        tracer=tracer,
    )
    if tracer.sampler is None:  # rate 1.0 attaches no sampler by itself
        from ddp_practice_tpu.utils.trace import TraceSampler

        tracer.set_sampler(TraceSampler(1.0))

    def push_rate(rate: float) -> None:
        for h in handles:
            h.set_trace(True, sample=rate)

    ctl = AdaptiveHeadRateController(
        tracer, budget_sps, interval_s=0.5, hold_s=1.0,
        apply_fn=push_rate,
    )

    def drain_frames() -> None:
        deadline = time.monotonic() + 0.3
        while time.monotonic() < deadline:
            router.step()
            _fleet_wait(router, 0.01)

    lost = 0

    def run_chunk(rate: float, k: int) -> None:
        nonlocal lost
        r = _replay_through_router(router, chunk(rate, k),
                                   rid_offset=(k + 1) * 1_000_000,
                                   fleet=True)
        lost += r["lost"]
        drain_frames()
        ctl.step()

    try:
        # untimed shakeout, then the controller's measurement baseline
        _replay_through_router(router, chunk(rate_hz, 0),
                               rid_offset=90_000_000, fleet=True)
        drain_frames()
        ctl.step()
        k = 1
        for _ in range(chunks_base):
            run_chunk(rate_hz, k)
            k += 1
        for _ in range(chunks_step):
            run_chunk(step_rate, k)
            k += 1
        # final window: same wall-clock basis the controller steers by
        k0 = tracer.spans_sampled + tracer.spans_kept
        t0 = time.monotonic()
        for _ in range(chunks_measure):
            run_chunk(step_rate, k)
            k += 1
        kept_sps = ((tracer.spans_sampled + tracer.spans_kept) - k0) \
            / (time.monotonic() - t0)
        budget_err = abs(kept_sps - budget_sps) / budget_sps
        return {
            "rate_hz": rate_hz,
            "step_rate_hz": step_rate,
            "step_factor": step_factor,
            "budget_sps": budget_sps,
            "chunk_s": chunk_s,
            "chunks": {"base": chunks_base, "step": chunks_step,
                       "measure": chunks_measure},
            "procs": procs,
            "gate": "kept_sps within ±20% of budget after the step",
            "kept_sps": kept_sps,
            "budget_err": budget_err,
            "within_budget": 1.0 if budget_err <= 0.20 else 0.0,
            "rate_final": ctl.rate,
            "rate_changes": ctl.changes,
            "rate_log": ctl.rate_log,
            "lost": lost,
            "sampling": tracer.sampling_meta(),
        }
    finally:
        sup.stop()


def fleet_autoscale_bench(
    *,
    rate_hz: float = 25.0,
    step_factor: float = 4.0,
    chunk_s: float = 1.0,
    chunks_base: int = 2,
    chunks_step: int = 3,
    chunks_post: int = 7,
    procs: int = 3,
    autoscale_min: int = 1,
    autoscale_max: int = 3,
    standby: int = 1,
    max_slots: int = 8,
    vocab: int = 64,
    hidden: int = 128,
    depth: int = 2,
    heads: int = 4,
    mlp: int = 256,
    max_len: int = 128,
    prompt_buckets=(8, 16),
    prompt_len_range=(2, 16),
    max_new_range=(32, 96),
    decode_burst: int = 8,
    eos_id: Optional[int] = 46,
    seed: int = 0,
) -> dict:
    """Elastic fleet vs fixed fleet under a 4x arrival step, at equal
    SLO: the same chunked trace (base rate -> `step_factor`x burst ->
    base again) is replayed through TWO separately-built fleets — a
    fixed fleet PROVISIONED FOR THE PEAK (`procs`, the fair fight:
    matching the elastic ceiling `autoscale_max` is what an operator
    without an autoscaler must deploy to survive the burst), then an
    autoscaled fleet that starts at `autoscale_min` with `standby`
    pre-warmed standbys. Both arms carry an identical SLOWatchdog, so
    brown-out shedding judges them by the same rules; the elastic arm's
    claim is GOODPUT PER WORKER-SECOND, not raw goodput.

    The check_bench-gated keys:

    - ``goodput_per_worker_ratio``: elastic useful-tokens per
      worker-second over fixed (worker-seconds integrate the active
      fleet size over the scale-event timeline; the fixed arm pays
      `procs` the whole run);
    - ``lost``: submitted-but-never-completed across BOTH arms (shed at
      the door is a status, lost is a bug) — gated 0;
    - ``reaction_within_window``: 1.0 iff the first scale-up after the
      step landed within ``reaction_window_s`` (one policy evaluation
      interval + eval-phase slack) of the first policy evaluation that
      SAW trigger pressure — the loop's own latency, separated from
      the queue-build physics reported as ``signal_build_s``;
    - ``oscillation_ok``: 1.0 iff scale-direction changes <= the
      hold-window bound floor(elapsed/hold_s) + 1 — the no-thrash
      contract, same shape as the adaptive head-rate gate;
    - ``promote_join_s``: warm-standby promotion latency (pool take ->
      dispatch join), the number that must sit well under the ~15s
      cold spawn also reported here as ``cold_spawn_s``.
    """
    from ddp_practice_tpu.serve.autoscaler import (
        Autoscaler,
        AutoscalerConfig,
    )
    from ddp_practice_tpu.serve.scheduler import MonotonicClock
    from ddp_practice_tpu.serve.slo import SLOConfig, SLOWatchdog
    from ddp_practice_tpu.serve.supervisor import (
        SupervisorConfig,
        make_fleet_router,
    )
    from ddp_practice_tpu.serve.worker import WorkerSpec

    model_kw = {
        "vocab_size": vocab, "max_len": max_len, "hidden_dim": hidden,
        "depth": depth, "num_heads": heads, "mlp_dim": mlp,
        "pos_emb": "rope",
    }

    def chunk(rate: float, k: int):
        return build_trace(
            n_requests=max(8, int(rate * chunk_s)), rate_hz=rate,
            vocab=vocab, prompt_len_range=prompt_len_range,
            max_new_range=max_new_range, seed=seed + 7 * k + 1,
        )

    step_rate = rate_hz * step_factor
    total_chunks = chunks_base + chunks_step + chunks_post
    spec = WorkerSpec(
        model=model_kw,
        engine={
            "max_slots": max_slots, "max_len": max_len,
            "prompt_buckets": list(prompt_buckets),
            "temperature": 0.0, "decode_burst": decode_burst,
            "eos_id": eos_id,
        },
        max_queue=int(step_rate * chunk_s) * (total_chunks + 2),
    )
    # the elastic policy: trip fast (one sub-second evaluation interval,
    # up_pressure just under the router's brown-out threshold so growth
    # fires before shedding clamps the signal), resolve slow (calm must
    # hold down_stable_s, reversals blocked inside hold_s)
    acfg = AutoscalerConfig(
        min_size=autoscale_min, max_size=autoscale_max,
        eval_interval_s=0.4, up_pressure=1.3, down_pressure=0.45,
        hold_s=4.0, cooldown_up_s=1.0, cooldown_down_s=3.0,
        down_stable_s=1.5, standby_target=standby,
    )
    # "within one evaluation window" of the signal: the commit may land
    # an eval after the crossing eval, plus scheduling slack on a
    # loaded box
    reaction_window_s = acfg.eval_interval_s + 0.25

    def slo_watchdog(clock):
        # equal-SLO contract: both arms get this exact config
        return SLOWatchdog(SLOConfig(
            ttft_p99_s=1.5, fast_window_s=1.5, slow_window_s=5.0,
            trip_burn=2.0, resolve_burn=1.0, min_events=8,
        ), clock=clock)

    def drain_frames(router) -> None:
        deadline = time.monotonic() + 0.3
        while time.monotonic() < deadline:
            router.step()
            _fleet_wait(router, 0.01)

    def integrate_size(events, t0, t1, size0) -> float:
        """Worker-seconds from the scale-event ledger: piecewise-
        constant active size over [t0, t1] (event "t"/"size" share the
        bench's time.monotonic basis via MonotonicClock)."""
        pts = [(t0, size0)]
        for e in events:
            if t0 <= e["t"] <= t1:
                pts.append((e["t"], e["size"]))
        ws = 0.0
        last_t, last_s = pts[0]
        for t, s in pts[1:]:
            ws += (t - last_t) * last_s
            last_t, last_s = t, s
        ws += (t1 - last_t) * last_s
        return ws

    def run_arm(auto: bool) -> dict:
        clock = MonotonicClock()
        n0 = autoscale_min if auto else procs
        router, sup, handles = make_fleet_router(
            spec, n0, clock=clock,
            sup_config=SupervisorConfig(restart_base_s=0.25,
                                        shrink_kill_after_s=10.0),
            slo=slo_watchdog(clock),
        )
        asc = None
        cold_spawn_s = None
        try:
            if auto:
                asc = Autoscaler(router, sup, spec, config=acfg,
                                 clock=clock)
                router.autoscaler = asc
                t0 = time.monotonic()
                if not asc.pool.wait_ready(timeout_s=300.0, n=standby):
                    raise RuntimeError("standby pool never warmed")
                # the pool fill IS a cold spawn — the latency a warm
                # promotion buys its way out of
                cold_spawn_s = time.monotonic() - t0
            # untimed shakeout: compile warmup through the seam
            _replay_through_router(router, chunk(rate_hz, 0),
                                   rid_offset=90_000_000, fleet=True)
            drain_frames(router)

            rows = []

            def run_chunk(rate: float, k: int) -> None:
                rows.append(_replay_through_router(
                    router, chunk(rate, k),
                    rid_offset=(k + 1) * 1_000_000, fleet=True))
                drain_frames(router)

            t_start = time.monotonic()
            size0 = sup.active_slots()
            k = 1
            for _ in range(chunks_base):
                run_chunk(rate_hz, k)
                k += 1
            t_burst = time.monotonic()
            for _ in range(chunks_step):
                run_chunk(step_rate, k)
                k += 1
            for _ in range(chunks_post):
                run_chunk(rate_hz, k)
                k += 1
            if auto:
                # let in-flight drains retire so the worker-seconds
                # ledger charges the elastic arm for its drain tail
                deadline = time.monotonic() + 15.0
                while time.monotonic() < deadline and asc._draining:
                    router.step()
                    _fleet_wait(router, 0.02)
            t_end = time.monotonic()

            events = list(asc.events) if auto else []
            ws = (integrate_size(events, t_start, t_end, size0)
                  if auto else procs * (t_end - t_start))
            useful = sum(r["useful_tokens"] for r in rows)
            statuses: dict = {}
            for r in rows:
                for s, n in r["statuses"].items():
                    statuses[s] = statuses.get(s, 0) + n
            arm = {
                "mode": "autoscaled" if auto else "fixed",
                "workers_start": n0,
                "elapsed_s": t_end - t_start,
                "worker_seconds": ws,
                "useful_tokens": useful,
                "goodput_per_worker": useful / ws if ws > 0 else 0.0,
                "lost": sum(r["lost"] for r in rows),
                "statuses": statuses,
                "slo": router.slo.burn_signal(),
            }
            if auto:
                ups = [e for e in events if e["direction"] == "up"]
                post = [e for e in ups if e["t"] >= t_burst]
                warm = [e for e in ups if e.get("warm")]
                dirs = [e["direction"] for e in events]
                changes = sum(1 for a, b in zip(dirs, dirs[1:])
                              if a != b)
                bound = int((t_end - t_start) / acfg.hold_s) + 1
                reaction_s = signal_build_s = None
                if post:
                    # the first policy evaluation that SAW trigger
                    # pressure after the step: reaction is the loop's
                    # own latency from that signal; the queue-build
                    # time before it is physics, reported separately
                    t_up = post[0]["t"]
                    xs = [r["t"] for r in asc.pressure_log
                          if t_burst <= r["t"] <= t_up
                          and r["pressure"] >= acfg.up_pressure]
                    signal_t = xs[0] if xs else t_up
                    reaction_s = t_up - signal_t
                    signal_build_s = signal_t - t_burst
                arm.update({
                    "final_size": sup.active_slots(),
                    "cold_spawn_s": cold_spawn_s,
                    "reaction_s": reaction_s,
                    "signal_build_s": signal_build_s,
                    "promote_join_s": (warm[0]["join_s"]
                                       if warm else None),
                    "direction_changes": changes,
                    "oscillation_bound": bound,
                    "scale_events": events,
                    "autoscaler": asc.snapshot(),
                })
            return arm
        finally:
            if asc is not None:
                asc.close()
            sup.stop()

    fixed = run_arm(auto=False)
    auto = run_arm(auto=True)
    reaction_s = auto.get("reaction_s")
    gpw_ratio = (auto["goodput_per_worker"]
                 / max(fixed["goodput_per_worker"], 1e-9))
    return {
        "rate_hz": rate_hz,
        "step_rate_hz": step_rate,
        "step_factor": step_factor,
        "chunk_s": chunk_s,
        "chunks": {"base": chunks_base, "step": chunks_step,
                   "post": chunks_post},
        "procs_fixed": procs,
        "autoscale": {"min": autoscale_min, "max": autoscale_max,
                      "standby": standby,
                      "eval_interval_s": acfg.eval_interval_s,
                      "hold_s": acfg.hold_s,
                      "up_pressure": acfg.up_pressure,
                      "down_pressure": acfg.down_pressure},
        "gate": ("goodput/worker >= fixed at equal SLO, react within "
                 "one eval window, no thrash, zero lost, warm "
                 "promotion << cold spawn"),
        "fixed": fixed,
        "autoscaled": auto,
        "goodput_per_worker_ratio": gpw_ratio,
        "lost": fixed["lost"] + auto["lost"],
        "reaction_s": reaction_s,
        "signal_build_s": auto.get("signal_build_s"),
        "reaction_window_s": reaction_window_s,
        "reaction_within_window": (
            1.0 if reaction_s is not None
            and reaction_s <= reaction_window_s else 0.0),
        "oscillation_ok": (
            1.0 if auto["direction_changes"]
            <= auto["oscillation_bound"] else 0.0),
        "promote_join_s": auto.get("promote_join_s"),
        "cold_spawn_s": auto.get("cold_spawn_s"),
    }


def _score_streams(router, comps) -> dict:
    """Score and CLEAR the router's TokenStreams from the consumer's
    seat (the bench IS the consumer). Everything here is re-derived
    from the delivered events, independently of the router's own
    cursors: `chunk_dupes`/`chunk_gaps` recount token-offset overlaps
    and holes (the exactly-once gate pins both at 0 — `suppressed` is
    the router absorbing re-decoded salvage and is EXPECTED under
    chaos), `inter_token_s` is the per-token delivery cadence between
    consecutive chunk arrivals, `ttft_s` is first DELIVERED token
    minus arrival, and `resume_gap_s` is the consumer-visible stall a
    failover splice cost each resumed stream."""
    arrival = {c.rid: c.arrival for c in comps}
    final_tokens = {c.rid: c.tokens for c in comps}
    inter, ttft, gaps_s = [], [], []
    dupes = holes = suppressed = resumed = 0
    unterminated = mismatched = 0
    for rid, st in router.streams.items():
        delivered = 0
        last_t = None
        for ev in st.events:
            if ev.kind == "resumed":
                resumed += 1
                continue
            if ev.kind != "tokens" or not ev.tokens:
                continue
            if ev.start < delivered:
                dupes += delivered - ev.start
            elif ev.start > delivered:
                holes += ev.start - delivered
            delivered = ev.start + len(ev.tokens)
            if last_t is None:
                if rid in arrival:
                    ttft.append(ev.t - arrival[rid])
            else:
                # one chunk = one consumer-visible delivery; its
                # tokens share the arrival instant, so the per-token
                # cadence is the chunk gap amortized over the chunk
                inter.extend([(ev.t - last_t) / len(ev.tokens)]
                             * len(ev.tokens))
            last_t = ev.t
        if not st.closed:
            unterminated += 1
        if st.tokens() != final_tokens.get(rid, st.tokens()):
            mismatched += 1  # stream view disagrees with completion
        if st.resume_gap_s:
            gaps_s.append(st.resume_gap_s)
        suppressed += st.suppressed
        holes += st.gaps
    n = len(router.streams)
    router.streams.clear()
    return {
        "streams": n,
        "chunk_dupes": dupes,
        "chunk_gaps": holes,
        "suppressed_tokens": suppressed,
        "resumed_markers": resumed,
        "unterminated": unterminated,
        "stream_completion_mismatches": mismatched,
        "inter_token_s": _percentiles(inter),
        "consumer_ttft_s": _percentiles(ttft),
        "resume_gap_s": _percentiles(gaps_s),
        "resume_gap_p99_s": (_percentiles(gaps_s).get("p99", 0.0)
                             if gaps_s else 0.0),
        "inter_token_p99_s": (_percentiles(inter).get("p99", 0.0)
                              if inter else 0.0),
    }


def streaming_bench(
    *,
    n_requests: int = 32,
    rate_hz: float = 8.0,
    procs: int = 2,
    max_slots: int = 8,
    vocab: int = 64,
    hidden: int = 128,
    depth: int = 2,
    heads: int = 4,
    mlp: int = 256,
    max_len: int = 128,
    prompt_buckets=(8, 16),
    prompt_len_range=(2, 16),
    max_new_range=(2, 32),
    decode_burst: int = 8,
    eos_id: Optional[int] = 46,
    seed: int = 0,
    reps: int = 6,
    fault_plan=None,
    telemetry_out: Optional[str] = None,
) -> dict:
    """Token STREAMING through the worker fleet, two operating points:

    - overhead (no kill plan): the same trace replays through TWO warm
      worker fleets — streaming delivery (chunks in every pub frame,
      router TokenStreams armed) vs end-of-request delivery (chunk
      plane fully off, worker-side and router-side) — in alternating
      order per rep; the headline is the median per-rep MEAN-latency
      ratio (acceptance gate: <= 1.05x at 8 rps). Every rep also
      cross-checks each stream's concatenation against its completion.

    - chaos (`fault_plan` with kill specs): ONE streaming fleet, real
      signals mid-stream, and the report is the CONSUMER'S ledger —
      re-derived duplicate/missing token counts (gated at zero),
      inter-token p99 at the consumer, resume-gap p99 (the stall a
      SIGKILL splice actually cost), resumed-marker count, and the
      tools/check_stream.py audit over the run's telemetry JSONL
      (`telemetry_out`; a temp file when not asked for)."""
    from ddp_practice_tpu.serve.router import RouterConfig
    from ddp_practice_tpu.serve.faults import FleetFaultDriver
    from ddp_practice_tpu.serve.supervisor import (
        SupervisorConfig,
        make_fleet_router,
    )
    from ddp_practice_tpu.serve.worker import WorkerSpec
    from ddp_practice_tpu.utils.telemetry import TelemetryExporter

    model_kw = {
        "vocab_size": vocab, "max_len": max_len, "hidden_dim": hidden,
        "depth": depth, "num_heads": heads, "mlp_dim": mlp,
        "pos_emb": "rope",
    }
    trace = build_trace(
        n_requests=n_requests, rate_hz=rate_hz, vocab=vocab,
        prompt_len_range=prompt_len_range, max_new_range=max_new_range,
        seed=seed,
    )
    chaos = fault_plan is not None and bool(fault_plan.kills())
    if fault_plan is not None and not chaos:
        raise ValueError("streaming_bench interprets only 'kill' specs")
    engine_kw = {
        "max_slots": max_slots, "max_len": max_len,
        "prompt_buckets": list(prompt_buckets),
        "temperature": 0.0, "decode_burst": decode_burst,
        "eos_id": eos_id,
    }
    max_queue = len(trace) * max(1, reps)

    def build(stream: bool, telemetry=None):
        return make_fleet_router(
            WorkerSpec(model=model_kw, engine=dict(engine_kw),
                       max_queue=max_queue, stream=stream),
            procs,
            config=RouterConfig(streaming=stream),
            sup_config=SupervisorConfig(restart_base_s=0.25),
            telemetry=telemetry,
        )

    def med(xs):
        s = sorted(xs)
        n = len(s)
        return (s[n // 2] if n % 2
                else 0.5 * (s[n // 2 - 1] + s[n // 2]))

    report = {
        "trace": {
            "n_requests": n_requests, "rate_hz": rate_hz, "seed": seed,
            "prompt_len_range": list(prompt_len_range),
            "max_new_range": list(max_new_range),
        },
        "procs": procs,
    }

    if chaos:
        # ---------------- chaos arm: one streaming fleet, real kills
        tmp = None
        if telemetry_out is None:
            import tempfile

            tmp = tempfile.NamedTemporaryFile(
                suffix=".jsonl", delete=False)
            telemetry_out = tmp.name
            tmp.close()
        exporter = TelemetryExporter(telemetry_out,
                                     snapshot_interval_s=0.0)
        router, sup, handles = build(True, telemetry=exporter)
        try:
            driver = FleetFaultDriver(fault_plan, sup.kill)
            before = len(router.completions)
            row = _replay_through_router(router, trace, driver=driver,
                                         fleet=True)
            comps = router.completions[before:]
            streams = _score_streams(router, comps)
            m = router.metrics
            row.update({
                "mode": f"stream fleet x{procs}",
                "failovers": m.failovers.value,
                "retries": m.retries.value,
                "worker_restarts": list(sup.restarts),
                "kills_fired": [
                    {"replica": f.replica, "sig": f.sig, "at_s": f.at_s}
                    for f in driver.fired
                ],
            })
            report.update({
                "reps": 1,
                "fleet": row,
                "fault_plan": fault_plan.to_json(),
                "telemetry_out": telemetry_out,
                # the gated keys, at top level for check_bench's dotted
                # paths: exactly-once re-derived at the consumer
                "chunk_dupes": streams["chunk_dupes"],
                "chunk_gaps": streams["chunk_gaps"],
                "lost": row["lost"],
                "unterminated": streams["unterminated"],
                "stream_completion_mismatches":
                    streams["stream_completion_mismatches"],
                "inter_token_p99_s": streams["inter_token_p99_s"],
                "resume_gap_p99_s": streams["resume_gap_p99_s"],
                "streams": streams,
            })
        finally:
            sup.stop()
            exporter.close()
        # offline audit of the SAME contract from the telemetry file
        # alone — the artifact a production incident would have
        try:
            from tools.check_stream import load_jsonl, stream_verdict

            ok, audit = stream_verdict(load_jsonl(telemetry_out))
            report["check_stream"] = {
                "ok": ok, "streams": audit["streams"],
                "violations": sum(len(v)
                                  for v in audit["violations"].values()),
            }
        except ImportError:  # tools/ not importable (installed pkg)
            report["check_stream"] = {"ok": None}
        if tmp is not None:
            os.unlink(telemetry_out)
            report.pop("telemetry_out")
        return report

    # ------------- overhead arm: streaming vs end-of-request delivery
    r_on, sup_on, _ = build(True)
    r_off, sup_off, _ = build(False)
    rows = {"on": [], "off": []}
    mismatches = 0
    try:
        for rep in range(reps):
            order = ["on", "off"] if rep % 2 == 0 else ["off", "on"]
            for side in order:
                router = r_on if side == "on" else r_off
                before = len(router.completions)
                rows[side].append(_replay_through_router(
                    router, trace, rid_offset=rep * 1_000_000,
                    fleet=True,
                ))
                if side == "on":
                    comps = router.completions[before:]
                    streams = _score_streams(router, comps)
                    mismatches += (
                        streams["stream_completion_mismatches"]
                        + streams["chunk_dupes"] + streams["chunk_gaps"]
                        + streams["unterminated"])
                    rows[side][-1]["streams"] = streams
        ratios_mean = [on["latency_s"]["mean"] / off["latency_s"]["mean"]
                       for on, off in zip(rows["on"], rows["off"])]
        ratios_p50 = [on["latency_s"]["p50"] / off["latency_s"]["p50"]
                      for on, off in zip(rows["on"], rows["off"])]
        report.update({
            "reps": reps,
            "gate": "mean <= 1.05x",
            "latency_ratio_mean": med(ratios_mean),
            "latency_ratio_mean_per_rep": ratios_mean,
            "latency_ratio_p50": med(ratios_p50),
            "goodput_ratio": med(
                [on["goodput_tokens_per_sec"]
                 / off["goodput_tokens_per_sec"]
                 for on, off in zip(rows["on"], rows["off"])]
            ),
            "streaming": {
                "latency_s": rows["on"][-1]["latency_s"],
                "lost": sum(r["lost"] for r in rows["on"]),
                "last_rep_streams": rows["on"][-1]["streams"],
            },
            "end_of_request": {
                "latency_s": rows["off"][-1]["latency_s"],
                "lost": sum(r["lost"] for r in rows["off"]),
            },
            # every rep's exactly-once cross-check, summed: stream-vs-
            # completion disagreements + re-derived dupes/gaps +
            # unterminated streams (all must be 0 fault-free)
            "stream_violations": mismatches,
        })
        return report
    finally:
        sup_on.stop()
        sup_off.stop()


def _mixed_prefill_trace(*, n_requests, rate_hz, vocab, long_len,
                         short_range=(2, 10), max_new_range=(4, 8),
                         seed=0) -> list:
    """Every third request carries a LONG cold prompt, the rest are
    short interactive ones — the Sarathi mixed workload where one
    monolithic long prefill head-of-line-blocks every short request
    queued behind it. Chunked prefill's whole claim is the short
    requests' TTFT tail on exactly this trace."""
    rng = np.random.default_rng(seed)
    gaps = rng.exponential(1.0 / rate_hz, n_requests)
    arrivals = np.cumsum(gaps)
    trace = []
    for i in range(n_requests):
        long = i % 3 == 0
        plen = (long_len if long
                else int(rng.integers(short_range[0],
                                      short_range[1] + 1)))
        trace.append({
            "rid": i,
            "arrival": float(arrivals[i]),
            "prompt": rng.integers(0, vocab, plen).tolist(),
            "max_new_tokens": int(rng.integers(
                max_new_range[0], max_new_range[1] + 1)),
            "long": long,
        })
    return trace


def _wire_replay(port, trace, *, body_extra=None,
                 timeout_s: float = 600.0) -> tuple:
    """Fire one arrival trace at a live Frontdoor through REAL client
    sockets — one thread per request, sleeping to its Poisson arrival,
    then a blocking `sse_request`. Returns ``({rid: {status, sent,
    events}}, elapsed_s)``; `body_extra(t)` merges per-request fields
    (sampling knobs, tenant) into the POSTed JSON."""
    import threading

    from ddp_practice_tpu.serve.frontdoor import sse_request

    results: dict = {}
    lock = threading.Lock()
    t0 = time.monotonic()

    def one(t):
        wait = t0 + t["arrival"] - time.monotonic()
        if wait > 0:
            time.sleep(wait)
        body = {"prompt": t["prompt"],
                "max_new_tokens": t["max_new_tokens"], "seed": 0}
        if body_extra is not None:
            body.update(body_extra(t))
        sent = time.monotonic()
        try:
            status, events = sse_request(
                "127.0.0.1", port, body, timeout_s=timeout_s)
        except OSError:
            status, events = -1, []
        with lock:
            results[t["rid"]] = {
                "status": status, "sent": sent, "events": events}

    threads = [threading.Thread(target=one, args=(t,), daemon=True)
               for t in trace]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    return results, time.monotonic() - t0


def _score_wire(trace, results, elapsed) -> tuple:
    """Score a wire replay the way _replay_through_router scores an
    in-process one — goodput over terminal-ok streams, client-side
    TTFT/latency percentiles, loss — and keep the raw SSE capture
    (`{"stream", "id", "event", "data"}` records) for the
    tools/check_stream.py --sse audit. Returns (row, tokens_by_rid,
    capture)."""
    tokens: dict = {}
    capture: list = []
    ttfts, lats = [], []
    statuses: dict = {}
    ended_ok = 0
    resumed = 0
    ok_tokens = 0
    for t in trace:
        rid = t["rid"]
        r = results.get(rid)
        if r is None or r["status"] != 200:
            statuses[f"http_{r['status'] if r else 'none'}"] = (
                statuses.get(
                    f"http_{r['status'] if r else 'none'}", 0) + 1)
            continue
        toks: list = []
        end_status = None
        first_tok_t = None
        for ev in r["events"]:
            capture.append({"stream": f"rid:{rid}", "id": ev["id"],
                            "event": ev["event"], "data": ev["data"]})
            data = ev["data"] if isinstance(ev["data"], dict) else {}
            if ev["event"] == "tokens":
                toks.extend(data.get("tokens") or [])
                if first_tok_t is None:
                    first_tok_t = ev["t"]
            elif ev["event"] == "resumed":
                resumed += 1
            elif ev["event"] == "end":
                end_status = data.get("status")
        tokens[rid] = toks
        key = end_status if end_status is not None else "unterminated"
        statuses[key] = statuses.get(key, 0) + 1
        if end_status in ("eos", "length", "stop"):
            ended_ok += 1
            ok_tokens += len(toks)
            if first_tok_t is not None:
                ttfts.append(first_tok_t - r["sent"])
            lats.append(r["events"][-1]["t"] - r["sent"])
    row = {
        "elapsed_s": elapsed,
        "useful_tokens": ok_tokens,
        "goodput_tokens_per_sec": ok_tokens / elapsed,
        "ttft_s": _percentiles(ttfts) if ttfts else {},
        "latency_s": _percentiles(lats) if lats else {},
        "completions": ended_ok,
        "lost": len(trace) - ended_ok,
        "statuses": statuses,
        "resumed_markers": resumed,
    }
    return row, tokens, capture


def _sse_audit(capture) -> dict:
    """The offline wire audit, in-process: map the SSE capture through
    tools/check_stream.py --sse and report the verdict (the bench's
    own acceptance row, same rules the CLI applies to a dump)."""
    try:
        from tools.check_stream import sse_to_chunks, stream_verdict
    except ImportError:  # tools/ not importable (installed pkg)
        return {"ok": None}
    ok, audit = stream_verdict(sse_to_chunks(capture))
    return {
        "ok": ok, "streams": audit["streams"],
        "violations": sum(len(v)
                          for v in audit["violations"].values()),
    }


def frontdoor_bench(
    *,
    n_requests: int = 24,
    rate_hz: float = 100.0,
    max_slots: int = 8,
    vocab: int = 32,
    hidden: int = 64,
    depth: int = 2,
    heads: int = 4,
    mlp: int = 128,
    decode_burst: int = 8,
    procs: int = 2,
    seed: int = 0,
    sse_out: Optional[str] = None,
) -> dict:
    """End-to-end HTTP/SSE front door (serve/frontdoor.py), four arms
    producing the BENCH_serve.json `frontdoor_100rps` entry and its
    check_bench-gated keys:

    - **wire vs in-process** — the SAME Poisson trace replays through a
      bare `Router.stream` loop and through real client sockets against
      a Frontdoor over an identical router. Gates: `token_identity`
      (greedy streams bit-identical across the wire, 1.0) and
      `goodput_ratio` (wire/in-process — the whole HTTP+SSE+thread hop
      must cost single-digit percent). The wire capture is audited by
      tools/check_stream.py --sse (`check_stream.ok`).
    - **chunked prefill TTFT** — a mixed long/short trace through two
      paged+prefix-cache front doors, `prefill_chunk` on vs off. Gate:
      `ttft_p99_ratio_chunked`, the SHORT (interactive) requests'
      client-side TTFT p99 ratio — chunking exists to stop a monolithic
      long prefill head-of-line-blocking them (<= 0.85 acceptance).
    - **mid-stream SIGKILL** — the same wire consumer against a
      `procs`-worker FLEET front door with a real SIGKILL mid-decode.
      Gate: `sigkill_lost` == 0 (every socket still gets its typed
      terminal; resumes splice under the same ids the --sse audit
      checks).
    - **mixed sampling churn** — greedy and per-request sampled
      traffic interleaved through one per_slot_sampling engine. Gate:
      `sampling_new_compiles` == 0 (one jitted decode program serves
      both, no shape/program churn from the knobs).
    """
    from ddp_practice_tpu.serve.engine import EngineConfig, PagedEngine
    from ddp_practice_tpu.serve.frontdoor import (
        Frontdoor,
        FrontdoorConfig,
    )
    from ddp_practice_tpu.serve.metrics import ServeMetrics
    from ddp_practice_tpu.serve.router import (
        Router,
        RouterConfig,
        make_router,
    )
    from ddp_practice_tpu.serve.scheduler import (
        MonotonicClock,
        Request,
        Scheduler,
    )

    model, params = _build_model(
        vocab=vocab, max_len=128, hidden=hidden, depth=depth,
        heads=heads, mlp=mlp,
    )
    trace = build_trace(
        n_requests=n_requests, rate_hz=rate_hz, vocab=vocab,
        prompt_len_range=(2, 16), max_new_range=(4, 24), seed=seed,
    )
    ecfg = EngineConfig(
        max_slots=max_slots, max_len=96, prompt_buckets=(16,),
        temperature=0.0, decode_burst=decode_burst, eos_id=None,
    )
    report: dict = {
        "trace": {
            "n_requests": n_requests, "rate_hz": rate_hz,
            "seed": seed, "prompt_len_range": [2, 16],
            "max_new_range": [4, 24],
        },
    }

    # ---------------- arm 1: wire identity + goodput vs in-process
    ip_router = make_router(model, params, 1, ecfg)
    ip_router.warmup()
    row_ip = _replay_through_router(ip_router, trace)
    ref_tokens = {t["rid"]: ip_router.stream(t["rid"]).tokens()
                  for t in trace}
    row_ip["mode"] = "in-process router.stream"
    report["in_process"] = row_ip

    fd = Frontdoor(make_router(model, params, 1, ecfg),
                   config=FrontdoorConfig())
    fd.driver.router.warmup()
    fd.start()
    try:
        results, elapsed = _wire_replay(fd.port, trace)
    finally:
        fd.close()
    row_wire, wire_tokens, capture = _score_wire(
        trace, results, elapsed)
    row_wire["mode"] = "frontdoor wire"
    matched = sum(
        1 for t in trace
        if wire_tokens.get(t["rid"]) == ref_tokens[t["rid"]]
        and ref_tokens[t["rid"]]
    )
    report.update({
        "wire": row_wire,
        "token_identity": matched / len(trace),
        "goodput_ratio": (row_wire["goodput_tokens_per_sec"]
                          / row_ip["goodput_tokens_per_sec"]),
        "check_stream": _sse_audit(capture),
    })

    # ---------------- arm 2: chunked prefill TTFT on mixed long/short
    long_len = 720
    model_l, params_l = _build_model(
        vocab=vocab, max_len=1024, hidden=hidden, depth=depth,
        heads=heads, mlp=mlp,
    )
    mixed = _mixed_prefill_trace(
        n_requests=18, rate_hz=rate_hz, vocab=vocab,
        long_len=long_len, seed=seed,
    )

    def paged_frontdoor(chunk: int) -> Frontdoor:
        # bucket 768 + a burst-rounded reservation + the request's own
        # new tokens: leave two bursts of headroom past the bucket
        cap_blocks = -(-(768 + 2 * 32 + decode_burst) // 16)
        engine = PagedEngine(
            model_l, params_l,
            EngineConfig(
                max_slots=4, max_len=1024,
                prompt_buckets=(16, 32, 768), temperature=0.0,
                decode_burst=decode_burst, eos_id=None,
                block_size=16, max_blocks_per_slot=cap_blocks,
                num_blocks=1 + 4 * cap_blocks,
                prefix_cache=True, prefill_chunk=chunk,
            ),
        )
        clock = MonotonicClock()
        sched = Scheduler(engine, clock=clock,
                          max_queue=len(mixed),
                          metrics=ServeMetrics())
        router = Router([sched], clock=clock)
        router.warmup()
        return Frontdoor(router, config=FrontdoorConfig())

    chunk_rows = {}
    chunk_tokens = {}
    for label, chunk in (("unchunked", 0), ("chunked", 32)):
        fd2 = paged_frontdoor(chunk)
        fd2.start()
        try:
            results, elapsed = _wire_replay(fd2.port, mixed)
        finally:
            fd2.close()
        row, toks, _ = _score_wire(mixed, results, elapsed)
        short_ttfts = []
        for t in mixed:
            r = results.get(t["rid"])
            if t["long"] or r is None or r["status"] != 200:
                continue
            first = next((ev["t"] for ev in r["events"]
                          if ev["event"] == "tokens"), None)
            if first is not None:
                short_ttfts.append(first - r["sent"])
        row["ttft_short_s"] = (_percentiles(short_ttfts)
                               if short_ttfts else {})
        chunk_rows[label] = row
        chunk_tokens[label] = toks
    ttft_ratio = (chunk_rows["chunked"]["ttft_short_s"]["p99"]
                  / chunk_rows["unchunked"]["ttft_short_s"]["p99"])
    report.update({
        "chunked_prefill": {
            "trace": {"n_requests": len(mixed), "long_len": long_len,
                      "prefill_chunk": 32},
            "chunked": chunk_rows["chunked"],
            "unchunked": chunk_rows["unchunked"],
            "token_identity": sum(
                1 for t in mixed
                if chunk_tokens["chunked"].get(t["rid"])
                == chunk_tokens["unchunked"].get(t["rid"])
                and chunk_tokens["unchunked"].get(t["rid"])
            ) / len(mixed),
        },
        "ttft_p99_ratio_chunked": ttft_ratio,
    })

    # ---------------- arm 3: mid-stream worker SIGKILL, zero lost
    import threading

    from ddp_practice_tpu.serve.supervisor import (
        SupervisorConfig,
        make_fleet_router,
    )
    from ddp_practice_tpu.serve.worker import WorkerSpec

    kill_trace = [
        dict(t, rid=t["rid"] + 300_000, max_new_tokens=32)
        for t in build_trace(
            n_requests=12, rate_hz=rate_hz, vocab=vocab,
            prompt_len_range=(2, 16), max_new_range=(24, 48),
            seed=seed + 1,
        )
    ]
    router_f, sup, handles = make_fleet_router(
        WorkerSpec(
            model={"vocab_size": vocab, "max_len": 128,
                   "hidden_dim": hidden, "depth": depth,
                   "num_heads": heads, "mlp_dim": mlp,
                   "pos_emb": "rope"},
            engine={"max_slots": max_slots, "max_len": 96,
                    "prompt_buckets": [16], "temperature": 0.0,
                    "decode_burst": decode_burst, "eos_id": None},
            max_queue=len(kill_trace), stream=True,
        ),
        procs,
        config=RouterConfig(streaming=True),
        sup_config=SupervisorConfig(restart_base_s=0.25),
    )
    fd3 = Frontdoor(router_f, config=FrontdoorConfig())
    fd3.start()
    kill_at_s = 0.75
    killer = threading.Timer(kill_at_s, sup.kill, (0, "SIGKILL"))
    try:
        killer.start()
        results, elapsed = _wire_replay(fd3.port, kill_trace)
    finally:
        killer.cancel()
        fd3.close()
        sup.stop()
    row_kill, _, kill_capture = _score_wire(
        kill_trace, results, elapsed)
    row_kill["mode"] = f"frontdoor fleet x{procs} + SIGKILL"
    capture.extend(kill_capture)
    report.update({
        "sigkill": {
            **row_kill,
            "kill_at_s": kill_at_s,
            "worker_restarts": list(sup.restarts),
            "check_stream": _sse_audit(kill_capture),
        },
        "sigkill_lost": row_kill["lost"],
    })

    # ---------------- arm 4: mixed greedy+sampled, zero new compiles
    ecfg_s = dataclasses.replace(ecfg, per_slot_sampling=True)
    router_s = make_router(model, params, 1, ecfg_s)
    router_s.warmup()
    # settle: one greedy + one sampled request so every program the
    # mixed traffic exercises is resident BEFORE the snapshot
    router_s.submit(Request(rid=400_000, prompt=[1, 2, 3],
                            max_new_tokens=4))
    router_s.submit(Request(rid=400_001, prompt=[4, 5, 6],
                            max_new_tokens=4, temperature=0.9,
                            top_k=8, top_p=0.9, seed=7))
    router_s.run_until_idle()
    before = router_s.compile_stats()

    def _count(stats) -> int:
        if isinstance(stats, dict):
            return sum(_count(v) for v in stats.values())
        return int(stats)

    churn = [
        dict(t, rid=t["rid"] + 410_000)
        for t in build_trace(
            n_requests=16, rate_hz=rate_hz, vocab=vocab,
            prompt_len_range=(2, 16), max_new_range=(4, 16),
            seed=seed + 2,
        )
    ]

    def sampling_fields(t):
        i = t["rid"] - 410_000
        if i % 2 == 0:
            return {}
        return {"temperature": 0.6 + 0.05 * (i % 5),
                "top_k": 8 if i % 4 == 1 else 0,
                "top_p": 0.9 if i % 4 == 3 else 0.0,
                "seed": i}

    fd4 = Frontdoor(router_s, config=FrontdoorConfig())
    fd4.start()
    try:
        results, elapsed = _wire_replay(
            fd4.port, churn, body_extra=sampling_fields)
    finally:
        fd4.close()
    row_mix, _, mix_capture = _score_wire(churn, results, elapsed)
    after = router_s.compile_stats()
    report.update({
        "sampling": {
            **row_mix,
            "mode": "per_slot_sampling mixed greedy+sampled",
            "compile_stats_before": before,
            "compile_stats_after": after,
            "check_stream": _sse_audit(mix_capture),
        },
        "sampling_new_compiles": _count(after) - _count(before),
    })

    if sse_out:
        with open(sse_out, "w") as f:
            for rec in capture:
                f.write(json.dumps(rec) + "\n")
        report["sse_out"] = sse_out
    return report


def _exemplar_resolution(sup, handles, tracer) -> dict:
    """Scrape each worker's /metrics and answer the acceptance
    question: does the TTFT p99 latency bucket carry an exemplar
    trace_id that resolves to a request present in the merged trace?
    (Plus counts over every bucket exemplar found — earlier buckets may
    legitimately hold exemplars from trace-plane-off reps.)"""
    import http.client
    import re

    ids_in_trace = set()
    for ev in tracer.to_chrome_trace()["traceEvents"]:
        args = ev.get("args") or {}
        if "trace_id" in args:
            ids_in_trace.add(args["trace_id"])
        if ev.get("id") is not None:
            ids_in_trace.add(ev["id"])
    found = []
    p99_rows = []
    for h in handles:
        w = sup.worker(h.id)
        if w is None:
            continue
        try:
            conn = http.client.HTTPConnection(
                "127.0.0.1", w.telemetry_port, timeout=2.0
            )
            conn.request("GET", "/metrics")
            text = conn.getresponse().read().decode()
            conn.close()
        except OSError:
            continue
        buckets = {}
        for m in re.finditer(
                r'serve_ttft_s_bucket\{le="([^"]+)"\} \d+'
                r'(?: # \{trace_id="([^"]+)"\} ([0-9.e+-]+))?', text):
            le = (float("inf") if m.group(1) == "+Inf"
                  else float(m.group(1)))
            buckets[le] = m.group(2)
            if m.group(2) is not None:
                found.append({"worker": h.id, "le": m.group(1),
                              "trace_id": m.group(2),
                              "resolves": m.group(2) in ids_in_trace})
        p99m = re.search(r'serve_ttft_s\{quantile="0\.99"\} ([0-9.e+-]+)',
                         text)
        if p99m is None or not buckets:
            continue
        p99 = float(p99m.group(1))
        le = min(b for b in buckets if b >= p99)
        tid = buckets[le]
        p99_rows.append({
            "worker": h.id, "p99": p99,
            "le": "+Inf" if le == float("inf") else le,
            "trace_id": tid,
            "resolves": tid is not None and tid in ids_in_trace,
        })
    return {
        "found": len(found),
        "resolved": sum(f["resolves"] for f in found),
        "p99_buckets": p99_rows,
        # ANY worker's p99 bucket naming a merged-trace request proves
        # the jump works; a worker whose p99 bucket was last touched by
        # a trace-plane-OFF rep legitimately points outside the
        # timeline (an always-on fleet has no such reps — the e2e test
        # pins the strict all-resolve case)
        "p99_resolves": any(r["resolves"] for r in p99_rows),
    }


def _tenant_rows_from(completions) -> dict:
    """Per-tenant latency/volume rows over one arm's completions.

    `window_tokens` counts only tokens delivered while load was still
    ARRIVING (finish <= the last arrival) — the contended window.
    These runs drain to idle, so TOTAL delivered tokens always equal
    the offered totals whatever the scheduler did; only the
    window-bounded count can show who actually got served during the
    fight, which is what Jain's index is judged over."""
    from ddp_practice_tpu.serve.fairshare import tenant_name

    by: dict = {}
    for c in completions:
        by.setdefault(tenant_name(getattr(c, "tenant", None)),
                      []).append(c)
    window_end = max((c.arrival for c in completions
                      if c.arrival is not None), default=None)
    out = {}
    for t, comps in sorted(by.items()):
        ok = [c for c in comps if c.status in ("eos", "length")]
        out[t] = {
            "completions": len(comps),
            "ok": len(ok),
            "output_tokens": sum(len(c.tokens) for c in ok),
            "window_tokens": sum(
                len(c.tokens) for c in ok
                if window_end is not None and c.finish is not None
                and c.finish <= window_end),
            "ttft_s": _percentiles(
                [c.ttft for c in ok if c.ttft is not None]),
            "latency_s": _percentiles(
                [c.finish - c.arrival for c in ok]),
        }
    return out


def qos_bench(
    *,
    rate_hz: float = 100.0,
    duration_s: float = 2.0,
    hostile_share: float = 4.0,
    procs: int = 2,
    max_slots: int = 2,
    vocab: int = 64,
    # heavier than the other serve benches on purpose: the arm is only
    # a fairness experiment if 100 req/s genuinely saturates the
    # fleet, so per-step cost is tuned to put capacity well BELOW the
    # hostile tenant's offered token rate
    hidden: int = 256,
    depth: int = 4,
    heads: int = 4,
    mlp: int = 512,
    decode_burst: int = 2,
    seed: int = 0,
    slo=None,
    workload=None,
    kill_at_s: float = 0.75,
    telemetry_out=None,
    trace_out=None,
) -> dict:
    """The multi-tenant QoS lab's bench: one adversarial workload plan
    (serve/workload.py — a hostile tenant offering `hostile_share`x
    the compliant tenant's rate) replayed through three arms, producing
    the BENCH_serve.json ``qos_mixed_tenants_100rps`` entry:

    - **FIFO** — RouterConfig(fair=False): the control. The hostile
      tenant's backlog head-of-line-blocks the compliant tenant.
    - **fair** — RouterConfig(fair=True): per-tenant weighted-fair
      queues (serve/fairshare.py VTC) + a TenantSLORegistry. Gates:
      ``isolation_ttft_p99_ratio`` (compliant tenant's TTFT p99,
      fair/FIFO — the contrast is the feature, acceptance <= 0.7),
      ``fairness_index`` (Jain over delivered tokens, >= 0.9),
      ``hostile_alert_tripped`` / ``compliant_clean`` (the per-tenant
      watchdogs attribute the burn to its cause — 0/1 contracts), and
      ``token_identity`` vs the FIFO arm (scheduling reorders WHO runs
      next, never WHAT a greedy request decodes — 1.0, tol 0) with
      ``lost`` == 0 across both arms.
    - **SIGKILL** — the same plan through a `procs`-worker FLEET
      (WorkerSpec(fair=True): each worker runs its own VTC + ledger)
      with a real mid-run SIGKILL + supervised restart. Gates:
      ``sigkill.lost`` == 0, ``sigkill.token_identity`` == 1.0
      (failover salvage keeps greedy identity), fairness/isolation
      claims re-judged by tools/check_qos.py over the leg's telemetry
      (``sigkill.check_qos_ok``) and the merged fleet timeline
      validated by tools/check_traces.py (``sigkill.trace_ok``).

    `telemetry_out` (a path PREFIX) writes one JSONL per arm —
    ``<prefix>.fifo.jsonl`` / ``.fair.jsonl`` / ``.sigkill.jsonl`` —
    each judgeable offline by tools/check_qos.py; `trace_out` saves
    the SIGKILL leg's merged fleet trace."""
    import threading

    from ddp_practice_tpu.serve.engine import EngineConfig
    from ddp_practice_tpu.serve.fairshare import (
        TenantLedger,
        VirtualTokenCounter,
        jains_index,
        tenant_name,
    )
    from ddp_practice_tpu.serve.router import RouterConfig, make_router
    from ddp_practice_tpu.serve.scheduler import MonotonicClock
    from ddp_practice_tpu.serve.slo import SLOConfig, TenantSLORegistry
    from ddp_practice_tpu.serve.supervisor import (
        SupervisorConfig,
        make_fleet_router,
    )
    from ddp_practice_tpu.serve.worker import WorkerSpec
    from ddp_practice_tpu.serve.workload import TenantSpec, WorkloadPlan
    from ddp_practice_tpu.utils.telemetry import TelemetryExporter

    # short windows so a ~2 s run can trip/resolve; the production
    # defaults (60/300 s) are for fleets, not benches
    slo_cfg = SLOConfig.from_json(slo) if slo is not None else SLOConfig(
        ttft_p99_s=0.5, fast_window_s=0.5, slow_window_s=1.0,
        min_events=5,
    )
    if workload is not None:
        plan = WorkloadPlan.from_json(workload)
    else:
        compliant_rps = rate_hz / (1.0 + hostile_share)
        plan = WorkloadPlan([
            TenantSpec(name="bulk", rate_rps=rate_hz - compliant_rps,
                       arrivals="bursty", burst_every_s=1.0,
                       burst_len_s=0.4, burst_mult=2.0,
                       # long prompts + full budgets: the flood has to
                       # OUTRUN the fleet or there is no contention to
                       # be fair about
                       prompt_len_mean=32.0, prompt_len_cap=64,
                       max_new_mean=16.0, max_new_cap=16,
                       hostile=True),
            TenantSpec(name="acme", rate_rps=compliant_rps,
                       sessions=2, turns_per_session=3,
                       session_prefix_len=8, prompt_len_mean=4.0,
                       prompt_len_cap=8, max_new_mean=8.0,
                       max_new_cap=12),
        ], duration_s=duration_s)
    trace = plan.build(vocab=vocab, seed=seed)
    hostile = set(plan.hostile_tenants())
    compliant = sorted(
        {tenant_name(t["tenant"]) for t in trace}
        - {tenant_name(h) for h in hostile})
    model, params = _build_model(
        vocab=vocab, max_len=128, hidden=hidden, depth=depth,
        heads=heads, mlp=mlp,
    )
    ecfg = EngineConfig(
        max_slots=max_slots, max_len=96, prompt_buckets=(16, 64),
        temperature=0.0, decode_burst=decode_burst, eos_id=None,
    )

    def _arm_out(tag):
        return (f"{telemetry_out}.{tag}.jsonl"
                if telemetry_out else None)

    def _judge(slo_reg, rows):
        """The isolation verdict off the live registry's alert log."""
        tripped = {t for _, edge, _, t in slo_reg.alert_log
                   if edge == "trip"}
        return {
            "alerts": [
                {"t": t, "event": edge, "objective": obj, "tenant": tn}
                for t, edge, obj, tn in slo_reg.alert_log
            ],
            "hostile_alert_tripped": float(bool(
                tripped & {tenant_name(h) for h in hostile})),
            "compliant_clean": float(
                not (tripped & set(compliant))),
            # judged over the CONTENDED window (_tenant_rows_from):
            # a drain-to-idle run delivers everyone's totals in the
            # end, so whole-run token counts cannot show starvation
            "fairness_index": jains_index(
                [rows[t]["window_tokens"] for t in sorted(rows)]),
        }

    def run_arm(fair: bool, tag: str) -> dict:
        from ddp_practice_tpu.utils.metrics import MetricsRegistry

        registry = MetricsRegistry()
        clock = MonotonicClock()
        exporter = None
        out_path = _arm_out(tag)
        if out_path:
            exporter = TelemetryExporter(out_path, registry=registry,
                                         clock=clock)
        slo_reg = TenantSLORegistry(slo_cfg, clock=clock,
                                    registry=registry,
                                    telemetry=exporter)
        vtc = VirtualTokenCounter() if fair else None
        ledger = TenantLedger(registry=registry, vtc=vtc)
        router = make_router(
            model, params, procs, ecfg, clock=clock,
            max_queue=len(trace), config=RouterConfig(fair=fair),
            registry=registry, slo=slo_reg, telemetry=exporter,
            vtc=vtc, ledger=ledger,
        )
        router.warmup()
        row = _replay_through_router(router, trace)
        rows = _tenant_rows_from(router.completions)
        row.update({
            "mode": f"{'fair' if fair else 'fifo'} x{procs}",
            "per_tenant": rows,
            "tenants": ledger.report(),
            **_judge(slo_reg, rows),
        })
        if exporter is not None:
            exporter.close()
            row["telemetry_out"] = out_path
        tokens = {c.rid: list(c.tokens) for c in router.completions
                  if c.status in ("eos", "length")}
        return row, tokens

    fifo_row, fifo_tokens = run_arm(False, "fifo")
    fair_row, fair_tokens = run_arm(True, "fair")
    matched = sum(1 for rid, toks in fifo_tokens.items()
                  if toks and fair_tokens.get(rid) == toks)
    comp = compliant[0] if compliant else None
    isolation = (
        fair_row["per_tenant"][comp]["ttft_s"]["p99"]
        / fifo_row["per_tenant"][comp]["ttft_s"]["p99"]
        if comp and fifo_row["per_tenant"].get(comp, {})
        .get("ttft_s", {}).get("p99") else None
    )
    report: dict = {
        "workload": json.loads(plan.to_json()),
        "slo": json.loads(slo_cfg.to_json()),
        "seed": seed,
        "hostile_tenants": sorted(hostile),
        "compliant_tenants": compliant,
        "fifo": fifo_row,
        "fair": fair_row,
        "isolation_ttft_p99_ratio": isolation,
        # the gated form: the raw ratio sits near 0.03x and jitters
        # run-to-run, so CI pins the verdict against the acceptance
        # bound, not the ratio (tools/check_bench.py DEFAULT_GATES)
        "isolation_ok": float(isolation is not None
                              and isolation <= 0.7),
        "token_identity": (matched / len(fifo_tokens)
                           if fifo_tokens else 0.0),
        "lost": fifo_row["lost"] + fair_row["lost"],
        "fairness_index": fair_row["fairness_index"],
        "hostile_alert_tripped": fair_row["hostile_alert_tripped"],
        "compliant_clean": fair_row["compliant_clean"],
    }

    # ------------- SIGKILL leg: fair FLEET + real mid-run worker death
    from ddp_practice_tpu.utils.metrics import MetricsRegistry

    # the chaos leg is judged against the FAILURE budget, not the
    # steady-state one: when the worker holding a tenant's flights is
    # SIGKILLed, those TTFTs ride out the restart no matter who the
    # scheduler favours, so the steady-state target would page every
    # tenant and the per-tenant attribution claim (hostile trips,
    # compliant doesn't) would be unfalsifiable. 5x the latency
    # targets is the single-worker-outage budget; the flooder's
    # backlog sails past it anyway.
    chaos_cfg = dataclasses.replace(
        slo_cfg,
        ttft_p99_s=(None if slo_cfg.ttft_p99_s is None
                    else slo_cfg.ttft_p99_s * 5),
        tpot_p99_s=(None if slo_cfg.tpot_p99_s is None
                    else slo_cfg.tpot_p99_s * 5),
    )
    registry = MetricsRegistry()
    clock = MonotonicClock()
    exporter = None
    kill_path = _arm_out("sigkill")
    if kill_path:
        exporter = TelemetryExporter(kill_path, registry=registry,
                                     clock=clock)
    slo_reg = TenantSLORegistry(chaos_cfg, clock=clock,
                                registry=registry, telemetry=exporter)
    ledger = TenantLedger(registry=registry)
    tracer = _make_tracer() if trace_out else None
    router_f, sup, handles = make_fleet_router(
        WorkerSpec(
            model={"vocab_size": vocab, "max_len": 128,
                   "hidden_dim": hidden, "depth": depth,
                   "num_heads": heads, "mlp_dim": mlp,
                   "pos_emb": "rope"},
            engine={"max_slots": max_slots, "max_len": 96,
                    "prompt_buckets": [16, 64], "temperature": 0.0,
                    "decode_burst": decode_burst, "eos_id": None},
            max_queue=len(trace), fair=True,
            trace=tracer is not None,
        ),
        procs,
        clock=clock,
        sup_config=SupervisorConfig(restart_base_s=0.25),
        registry=registry, tracer=tracer, slo=slo_reg,
        telemetry=exporter, ledger=ledger,
    )
    killer = threading.Timer(kill_at_s, sup.kill, (0, "SIGKILL"))
    try:
        killer.start()
        kill_row = _replay_through_router(router_f, trace, fleet=True)
    finally:
        killer.cancel()
        sup.stop()
    rows = _tenant_rows_from(router_f.completions)
    kill_tokens = {c.rid: list(c.tokens) for c in router_f.completions
                   if c.status in ("eos", "length")}
    kmatched = sum(1 for rid, toks in fifo_tokens.items()
                   if toks and kill_tokens.get(rid) == toks)
    kill_row.update({
        "mode": f"fair fleet x{procs} + SIGKILL",
        "kill_at_s": kill_at_s,
        "slo_chaos": json.loads(chaos_cfg.to_json()),
        "per_tenant": rows,
        "worker_restarts": list(sup.restarts),
        "token_identity": (kmatched / len(fifo_tokens)
                           if fifo_tokens else 0.0),
        **_judge(slo_reg, rows),
    })
    if exporter is not None:
        exporter.close()
        kill_row["telemetry_out"] = kill_path
        # the offline verdict over the leg's own telemetry: per-tenant
        # SLOs + fairness + hostile-trip attribution, same tool a CI
        # run applies to the checked-in artifact
        try:
            from tools.check_qos import qos_report
            from tools.check_slo import load_events

            records, _trunc = load_events(kill_path)
            qr = qos_report(
                records, chaos_cfg, hostile=sorted(hostile),
                min_fairness=0.5, expect_hostile_trip=True)
            kill_row["check_qos_ok"] = float(qr["ok"])
            kill_row["check_qos_problems"] = qr["problems"]
        except ImportError:
            kill_row["check_qos_ok"] = None
    if tracer is not None:
        tracer.save(trace_out)
        kill_row["trace_out"] = trace_out
        try:
            from tools.check_traces import validate_fleet

            with open(trace_out) as f:
                errs = validate_fleet(json.load(f))
            kill_row["trace_ok"] = float(not errs)
            kill_row["trace_errors"] = errs[:5]
        except ImportError:
            kill_row["trace_ok"] = None
    report["sigkill"] = kill_row
    report["sigkill_lost"] = kill_row["lost"]
    return report


def _run_static(model, params, trace, *, max_slots, width, max_new,
                eos_id) -> dict:
    """Static-batch baseline: fixed (max_slots, width) prompts, everyone
    decodes `max_new` tokens, arrivals wait for the whole batch. EOS
    only pads the tail — the fixed-length scan runs to max_new
    regardless, which is exactly the decode compute continuous batching
    reclaims."""
    import jax
    import jax.numpy as jnp

    from ddp_practice_tpu.inference import make_generate_fn

    gen = jax.jit(make_generate_fn(
        model, max_new_tokens=max_new, temperature=0.0, eos_id=eos_id,
        pad_id=-1,  # distinguishable from real tokens when counting
    ))

    def run_batch(batch):
        toks = np.full((max_slots, width), 0, np.int32)
        lens = np.ones((max_slots,), np.int32)
        for j, t in enumerate(batch):
            p = t["prompt"]
            toks[j, width - len(p):] = p
            lens[j] = len(p)
        out = np.asarray(gen(
            params, jnp.asarray(toks), None, jnp.asarray(lens)
        ))
        return out[:, width:]

    run_batch(trace[:1])  # warmup compile outside the window

    t0 = time.monotonic()
    i = 0
    done = []
    while i < len(trace):
        now = time.monotonic() - t0
        if trace[i]["arrival"] > now:
            time.sleep(trace[i]["arrival"] - now)
            continue
        batch = []
        while i < len(trace) and len(batch) < max_slots \
                and trace[i]["arrival"] <= time.monotonic() - t0:
            batch.append(trace[i])
            i += 1
        new = run_batch(batch)
        finish = time.monotonic() - t0
        for j, t in enumerate(batch):
            # useful tokens: up to this request's OWN budget, cut at its
            # EOS (post-EOS slots hold the pad sentinel) — the same
            # accounting the continuous server's release logic applies
            row = new[j, : t["max_new_tokens"]]
            done.append({
                "rid": t["rid"],
                "tokens": int((row != -1).sum()),
                "latency": finish - t["arrival"],
            })
    elapsed = time.monotonic() - t0
    tokens = sum(d["tokens"] for d in done)
    lat = [d["latency"] for d in done]
    return {
        "mode": "static",
        "elapsed_s": elapsed,
        "useful_tokens": tokens,
        "tokens_per_sec": tokens / elapsed,
        # every token arrives when the batch returns: TTFT == latency
        "ttft_s": _percentiles(lat),
        "latency_s": _percentiles(lat),
        "completions": len(done),
    }


def shared_prefix_bench(
    *,
    n_requests: int = 32,
    # effectively-instant arrivals: the tiny CPU bench model drains 100
    # real rps without queueing, and an arrival-bound run measures the
    # Poisson clock, not the pool — saturate so the ratio is the
    # engines' goodput at full block pressure
    rate_hz: float = 1000.0,
    max_slots: int = 8,
    vocab: int = 64,
    hidden: int = 128,
    depth: int = 2,
    heads: int = 4,
    mlp: int = 256,
    max_len: int = 128,
    prompt_buckets=(16, 128),
    # the workload: K fixed system prompts (block-aligned so the radix
    # tree caches exactly the prefix) x short unique tails — prefixes
    # deliberately DOMINATE each prompt (96 of ~100 tokens), the
    # production shape ROADMAP item 2 names
    k_prefixes: int = 2,
    prefix_len: int = 96,
    tail_range=(1, 8),
    max_new_range=(4, 8),
    decode_burst: int = 4,
    block_size: int = 16,
    # UNDERSIZED pool (19 real blocks ~ 2 plain worst-case contexts for
    # 8 slots): block pressure is what prefix sharing + preemption
    # relieve, so the pool must actually be contended — the plain row
    # runs ~2 contexts at a time while the prefix row's slots share the
    # two 6-block prefixes and fit ~7
    num_blocks: int = 20,
    seed: int = 0,
    kv_int8: bool = False,
) -> dict:
    """Replay ONE shared-prefix Poisson trace through the plain paged
    engine and the prefix-sharing engine at the SAME pool size.

    The report's `prefix_vs_paged` goodput ratio is the PR-6 acceptance
    number (>= 1.5x target): the prefix engine pays prefill only for
    each request's tail and shares the K prefixes' blocks refcounted,
    so the same 24 blocks hold ~2x the concurrent contexts. Hit/miss
    token counters prove the reuse. `kv_int8=True` additionally stores
    the pool int8 with per-block scale pages (halved KV bytes/token —
    reported against the same model's fp32 pool)."""
    model, params = _build_model(
        vocab=vocab, max_len=max_len, hidden=hidden, depth=depth,
        heads=heads, mlp=mlp,
        kv_cache_dtype="int8" if kv_int8 else None,
    )
    trace = build_shared_prefix_trace(
        n_requests=n_requests, rate_hz=rate_hz, vocab=vocab,
        k_prefixes=k_prefixes, prefix_len=prefix_len,
        tail_range=tail_range, max_new_range=max_new_range, seed=seed,
    )
    common = dict(
        max_slots=max_slots, prompt_buckets=tuple(prompt_buckets),
        max_len=max_len, decode_burst=decode_burst, eos_id=None,
        paged=True, block_size=block_size, num_blocks=num_blocks,
    )
    plain = _run_continuous(model, params, trace, **common)
    prefix = _run_continuous(model, params, trace, prefix_cache=True,
                             **common)
    report = {
        "trace": {
            "n_requests": n_requests, "rate_hz": rate_hz, "seed": seed,
            "k_prefixes": k_prefixes, "prefix_len": prefix_len,
            "tail_range": list(tail_range),
            "max_new_range": list(max_new_range),
        },
        "pool": {
            "num_blocks": num_blocks, "block_size": block_size,
            "max_slots": max_slots,
            "kv_cache_dtype": "int8" if kv_int8 else "f32",
        },
        "paged": plain,
        "paged_prefix": prefix,
        "prefix_vs_paged": (
            prefix["tokens_per_sec"] / plain["tokens_per_sec"]
            if plain["tokens_per_sec"] else float("inf")
        ),
    }
    if kv_int8:
        # bytes/token against the SAME architecture's fp32 pool — the
        # halved-KV acceptance number (shapes only, no fp32 arrays)
        import jax

        f32_model, _ = _build_model(
            vocab=vocab, max_len=max_len, hidden=hidden, depth=depth,
            heads=heads, mlp=mlp,
        )
        from ddp_practice_tpu.serve.kv_pages import make_paged_cache

        f32_cache = jax.eval_shape(
            lambda: make_paged_cache(f32_model, num_blocks, block_size)
        )
        f32_bytes = sum(
            int(np.prod(leaf.shape)) * leaf.dtype.itemsize
            for leaf in jax.tree.leaves(f32_cache) if leaf.ndim
        ) / (num_blocks * block_size)
        report["kv_bytes_per_token_f32"] = f32_bytes
        report["kv_bytes_ratio"] = (
            prefix["kv_bytes_per_token"] / f32_bytes
        )
    return report


def spec_decode_bench(
    *,
    n_requests: int = 32,
    rate_hz: float = 8.0,
    max_slots: int = 4,
    vocab: int = 64,
    hidden: int = 128,
    depth: int = 2,
    heads: int = 4,
    mlp: int = 256,
    max_len: int = 128,
    prompt_buckets=(16,),
    # the workload: repeated-motif prompts (build_lookup_trace) — the
    # self-quoting traffic shape where prompt-lookup drafts actually hit
    motif_range=(2, 4),
    prompt_len_range=(6, 16),
    max_new_range=(8, 24),
    # burst=1 for BOTH arms: the honest comparison pins tokens-per-
    # dispatch at 1 on the plain side, so the ratio isolates exactly
    # what speculation changes — the number of sequential dispatches
    # per emitted token. (At burst=B the plain arm lands B tokens per
    # dispatch and the comparison conflates bursting with drafting.)
    decode_burst: int = 1,
    block_size: int = 16,
    spec_k: int = 4,
    seed: int = 0,
) -> dict:
    """Replay ONE lookup-friendly Poisson trace through the plain paged
    engine and the spec-decoding paged engine at the same pool.

    The report's `tpot_ratio` (spec p50 / plain p50, < 1.0 target) is
    the ISSUE-13 acceptance number: a verified run lands k+1 tokens in
    one dispatch, so inter-token pacing drops wherever drafts hit.
    `token_identity` (fraction of requests with bit-identical streams,
    target 1.0) is the exactness half of the claim — speculation is a
    latency lever, never a quality knob. `accept_rate` explains WHY the
    ratio moved (no accepts = no speedup, by construction)."""
    model, params = _build_model(
        vocab=vocab, max_len=max_len, hidden=hidden, depth=depth,
        heads=heads, mlp=mlp,
    )
    trace = build_lookup_trace(
        n_requests=n_requests, rate_hz=rate_hz, vocab=vocab,
        motif_range=motif_range, prompt_len_range=prompt_len_range,
        max_new_range=max_new_range, seed=seed,
    )
    common = dict(
        max_slots=max_slots, prompt_buckets=tuple(prompt_buckets),
        max_len=max_len, decode_burst=decode_burst, eos_id=None,
        paged=True, block_size=block_size, collect_tokens=True,
    )
    plain = _run_continuous(model, params, trace, **common)
    spec = _run_continuous(model, params, trace, spec_decode=True,
                           spec_k=spec_k, **common)
    plain_toks = plain.pop("tokens_by_rid")
    spec_toks = spec.pop("tokens_by_rid")
    identical = sum(
        1 for rid in plain_toks if spec_toks.get(rid) == plain_toks[rid]
    )
    return {
        "trace": {
            "n_requests": n_requests, "rate_hz": rate_hz, "seed": seed,
            "motif_range": list(motif_range),
            "prompt_len_range": list(prompt_len_range),
            "max_new_range": list(max_new_range),
        },
        "spec_k": spec_k,
        "paged": plain,
        "paged_spec": spec,
        "token_identity": identical / max(1, len(plain_toks)),
        "tpot_ratio": (
            spec["tpot_s"]["p50"] / plain["tpot_s"]["p50"]
            if plain["tpot_s"]["p50"] else float("inf")
        ),
        "latency_ratio_p50": (
            spec["latency_s"]["p50"] / plain["latency_s"]["p50"]
            if plain["latency_s"]["p50"] else float("inf")
        ),
        "accept_rate": spec["spec"]["accept_rate"],
    }


def serve_bench(
    *,
    n_requests: int = 32,
    rate_hz: float = 8.0,
    max_slots: int = 8,
    vocab: int = 64,
    hidden: int = 128,
    depth: int = 2,
    heads: int = 4,
    mlp: int = 256,
    # sized to the trace: the decode-attention span is the whole pool
    # every step (the shared-cursor design reads [0, max_len) masked), so
    # an oversized pool taxes ONLY the continuous server — 128 fits the
    # 96-token cap plus the 16-wide prompt base with room to spare
    max_len: int = 128,
    prompt_buckets=(8, 16),
    prompt_len_range=(2, 16),
    # wide budget spread: the static baseline pays max_new for everyone,
    # the continuous engine pays what each request asked (+burst round-up)
    max_new_range=(2, 96),
    decode_burst: int = 8,
    # the trace's end-of-sequence token: with the default params seed,
    # greedy decode emits 46 early in roughly half the streams and never
    # in the rest — a realistic early-stop mix. The continuous server
    # reclaims the slot at EOS; the static scan runs to max_new
    # regardless. None = no EOS in the trace.
    eos_id: Optional[int] = 46,
    seed: int = 0,
    # fleet path: 0 = skip the router bench; N >= 1 runs the SAME trace
    # through N replicas behind serve/router.py (replicas=1 measures the
    # router's overhead against the direct continuous path)
    replicas: int = 0,
    fault_plan=None,
    # also run the trace through the paged-KV engine (serve/kv_pages.py)
    # — the span-decoupling measurement: the slot engine's decode
    # attention scans [0, max_len) every step, the paged engine only
    # each request's own pages, so growing max_len taxes the slot row
    # and leaves the paged row flat (BENCHMARKS.md)
    paged: bool = False,
    block_size: int = 16,
    # Chrome trace-event JSON output (utils/trace.py): the recorder
    # rides the ROUTER run when replicas >= 1, else the continuous run
    # (warmup spans excluded either way). Validate/eyeball with
    # tools/check_traces.py; None = tracing fully off.
    trace_out: Optional[str] = None,
    # ---- live telemetry plane (utils/telemetry.py): all default-off.
    # telemetry_out streams kind-tagged JSONL (trace events via the
    # recorder sink, flight records, metrics snapshots) DURING the run;
    # metrics_port binds the /metrics /healthz /flight scrape server
    # (0 = ephemeral); scrape_hz self-scrapes all three endpoints from a
    # background thread — the overhead-measurement methodology, so the
    # "plane on" bench row pays for serving real scrapes, not an idle
    # listener. slo (SLOConfig/JSON/path) arms the burn-rate watchdog
    # on the router run (needs replicas >= 1).
    telemetry_out: Optional[str] = None,
    metrics_port: Optional[int] = None,
    scrape_hz: float = 0.0,
    slo=None,
    alert_sinks=None,
) -> dict:
    """Replay one Poisson trace through both servers; return the report."""
    model, params = _build_model(
        vocab=vocab, max_len=max_len, hidden=hidden, depth=depth,
        heads=heads, mlp=mlp,
    )
    trace = build_trace(
        n_requests=n_requests, rate_hz=rate_hz, vocab=vocab,
        prompt_len_range=prompt_len_range, max_new_range=max_new_range,
        seed=seed,
    )
    # a recorder exists for EITHER output: --trace-out wants the exit
    # dump, --telemetry-out wants the live stream (the sink) — each is
    # self-sufficient
    tracer = _make_tracer() if (trace_out or telemetry_out) else None

    slo_config = None
    if slo is not None:
        from ddp_practice_tpu.serve.slo import SLOConfig

        if replicas < 1:
            raise ValueError("--slo needs --replicas N (the watchdog "
                             "feeds the router's brown-out hook)")
        slo_config = SLOConfig.from_json(slo)
    plane_on = telemetry_out is not None or metrics_port is not None
    registry = exporter = server = scraper = None
    health_slot = {"fn": None}
    if plane_on or slo_config is not None:
        from ddp_practice_tpu.utils.metrics import MetricsRegistry

        registry = MetricsRegistry()
    try:
        if telemetry_out is not None:
            from ddp_practice_tpu.utils.telemetry import TelemetryExporter

            # NOT attached to the tracer yet: the runs attach the sink
            # only after their warmup + tracer.clear(), so compile-time
            # spans stay out of the stream exactly as they stay out of
            # the trace_out dump
            exporter = TelemetryExporter(telemetry_out, registry=registry)
        if metrics_port is not None:
            from ddp_practice_tpu.utils.telemetry import (
                FlightStats,
                TelemetryServer,
            )

            flight = exporter.flight if exporter else FlightStats()
            server = TelemetryServer(
                registry=registry,
                health_fn=lambda: (health_slot["fn"]()
                                   if health_slot["fn"] else {}),
                flight_fn=flight.report,
                port=metrics_port,
            )
            if exporter is None:
                # no JSONL stream, but /flight still needs feeding
                exporter_or_flight = flight
            else:
                exporter_or_flight = exporter
        else:
            exporter_or_flight = exporter
        if server is not None and scrape_hz > 0:
            scraper = _Scraper(server.port, hz=scrape_hz)
    except BaseException:
        # half-built plane (e.g. the port is taken): drain and close
        # what already started before surfacing the error
        if server is not None:
            server.close()
        if exporter is not None:
            exporter.close()
        raise

    try:
        cont = _run_continuous(
            model, params, trace, max_slots=max_slots,
            prompt_buckets=tuple(prompt_buckets), max_len=max_len,
            decode_burst=decode_burst, eos_id=eos_id,
            tracer=None if replicas >= 1 else tracer,
            telemetry=None if replicas >= 1 else exporter_or_flight,
            health_slot=None if replicas >= 1 else health_slot,
        )
        static = _run_static(
            model, params, trace, max_slots=max_slots,
            width=max(prompt_buckets), max_new=max(max_new_range),
            eos_id=eos_id,
        )
        report = {
            "trace": {
                "n_requests": n_requests, "rate_hz": rate_hz, "seed": seed,
                "prompt_len_range": list(prompt_len_range),
                "max_new_range": list(max_new_range),
            },
            "max_len": max_len,
            "continuous": cont,
            "static": static,
            "throughput_ratio": (
                cont["tokens_per_sec"] / static["tokens_per_sec"]
                if static["tokens_per_sec"] else float("inf")
            ),
        }
        if paged:
            report["paged"] = _run_continuous(
                model, params, trace, max_slots=max_slots,
                prompt_buckets=tuple(prompt_buckets), max_len=max_len,
                decode_burst=decode_burst, eos_id=eos_id,
                paged=True, block_size=block_size,
            )
            report["paged_vs_static"] = (
                report["paged"]["tokens_per_sec"] / static["tokens_per_sec"]
                if static["tokens_per_sec"] else float("inf")
            )
            report["paged_vs_continuous"] = (
                report["paged"]["tokens_per_sec"] / cont["tokens_per_sec"]
                if cont["tokens_per_sec"] else float("inf")
            )
        if replicas >= 1:
            report["router"] = _run_router(
                model, params, trace, replicas=replicas,
                max_slots=max_slots,
                prompt_buckets=tuple(prompt_buckets), max_len=max_len,
                decode_burst=decode_burst, eos_id=eos_id,
                fault_plan=fault_plan, tracer=tracer,
                slo_config=slo_config, telemetry=exporter_or_flight,
                exporter=exporter, registry=registry,
                health_slot=health_slot, alert_sinks=alert_sinks,
            )
            if fault_plan is not None:
                report["fault_plan"] = fault_plan.to_json()
            report["router_vs_continuous"] = (
                report["router"]["tokens_per_sec"] / cont["tokens_per_sec"]
                if cont["tokens_per_sec"] else float("inf")
            )
        if tracer is not None and trace_out:
            tracer.save(trace_out)
            report["trace_out"] = trace_out
            report["trace_events"] = len(tracer)
    finally:
        # the plane outlives a crashed run only as a closed, drained
        # file — that is the flush-on-crash contract
        if scraper is not None:
            scraper.stop()
        if server is not None:
            server.close()
        if exporter is not None:
            exporter.close()
    if plane_on:
        report["telemetry"] = {
            "telemetry_out": telemetry_out,
            "metrics_port": server.port if server is not None else None,
            "scrapes": scraper.count if scraper is not None else 0,
            "dropped": exporter.dropped if exporter is not None else 0,
        }
    return report


# --------------------------------------------------------------------- CLI
def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        "ddp_practice_tpu serve",
        description="continuous-batching serving: bench a synthetic "
                    "Poisson trace (default) or serve prompts from a "
                    "trained RoPE LM checkpoint",
    )
    p.add_argument("--ckpt_dir", default=None,
                   help="serve these --prompt strings from a checkpoint "
                        "instead of running the bench (needs a "
                        "pos_emb=rope LM checkpoint)")
    p.add_argument("--prompt", action="append", default=None,
                   help="repeatable; byte-level prompt(s) to serve")
    p.add_argument("--max_new_tokens", type=int, default=64)
    p.add_argument("--temperature", type=float, default=0.0)
    p.add_argument("--top_k", type=int, default=0)
    p.add_argument("--top_p", type=float, default=0.0)
    p.add_argument("--eos_id", type=int, default=None)
    p.add_argument("--max_slots", type=int, default=4)
    p.add_argument("--decode_burst", type=int, default=None,
                   help="decode steps per dispatch (amortizes host "
                        "overhead; releases are burst-granular; default: "
                        "8 for the bench, 1 for checkpoint serving)")
    p.add_argument("--requests", type=int, default=32,
                   help="bench: trace length")
    p.add_argument("--rate", type=float, default=8.0,
                   help="bench: Poisson arrival rate (req/s)")
    p.add_argument("--replicas", type=int, default=0,
                   help="bench: also run the trace through N engine "
                        "replicas behind the fault-tolerant router "
                        "(serve/router.py; 0 = skip)")
    p.add_argument("--procs", type=int, default=0,
                   help="bench: run the trace through N worker OS "
                        "PROCESSES behind the RPC seam AND through N "
                        "in-process router replicas — reports the "
                        "seam's latency/goodput overhead "
                        "(serve/worker.py + serve/supervisor.py; "
                        "--fault-plan kill specs deliver real "
                        "SIGKILL/SIGSTOP to live workers)")
    p.add_argument("--fault-plan", dest="fault_plan", default=None,
                   metavar="JSON",
                   help="bench: inject a serve/faults.py FaultPlan into "
                        "the router run — a JSON string or a path to a "
                        "JSON file; the router row then reports GOODPUT "
                        "under those faults (requires --replicas)")
    p.add_argument("--paged", action="store_true",
                   help="bench: also run the trace through the paged-KV "
                        "engine (serve/kv_pages.py) — adds a 'paged' row; "
                        "compare against 'continuous' at large --max-len "
                        "to see the span decoupling")
    p.add_argument("--block-size", dest="block_size", type=int, default=16,
                   help="paged engine: positions per KV block")
    p.add_argument("--shared-prefix", dest="shared_prefix",
                   action="store_true",
                   help="bench: replay a deterministic K-system-prompts x"
                        " continuations trace through the plain paged "
                        "engine AND the prefix-sharing engine at the "
                        "same (undersized) pool — reports the goodput "
                        "ratio plus prefix-cache hit/miss token "
                        "counters (serve/kv_pages.py RadixPrefixCache)")
    p.add_argument("--kv-int8", dest="kv_int8", action="store_true",
                   help="with --shared-prefix: store the paged pool "
                        "int8 with per-block scale pages — halves KV "
                        "bytes/token (reported vs the fp32 pool)")
    p.add_argument("--spec-decode", dest="spec_decode",
                   action="store_true",
                   help="bench: replay ONE lookup-friendly trace "
                        "(repeated-motif prompts) through the plain "
                        "paged engine AND the speculative-decoding "
                        "engine (serve/spec.py prompt-lookup drafts + "
                        "jitted k-token verify) — reports tpot_ratio, "
                        "accept_rate, and token_identity (greedy "
                        "streams must be bit-identical across arms)")
    p.add_argument("--spec-k", dest="spec_k", type=int, default=4,
                   help="with --spec-decode: drafted tokens per verify "
                        "window")
    p.add_argument("--trace-out", "--trace_out", dest="trace_out",
                   default=None, metavar="PATH",
                   help="write a Chrome trace-event JSON of the request "
                        "lifecycle (queued/prefill/decode-burst spans, "
                        "retry/failover instants; pid=replica, tid=slot) "
                        "— the router run when --replicas, else the "
                        "continuous run; open in Perfetto, validate with "
                        "tools/check_traces.py")
    p.add_argument("--telemetry-out", "--telemetry_out",
                   dest="telemetry_out", default=None, metavar="PATH",
                   help="stream the run's telemetry as line-delimited "
                        "JSONL WHILE it runs (trace events, flight "
                        "records, periodic metrics snapshots — "
                        "utils/telemetry.py): a killed run still leaves "
                        "a parseable file; validate with "
                        "tools/check_traces.py, judge with "
                        "tools/check_slo.py")
    p.add_argument("--metrics-port", "--metrics_port",
                   dest="metrics_port", type=int, default=None,
                   metavar="PORT",
                   help="serve /metrics (Prometheus exposition), "
                        "/healthz (per-replica health), /flight "
                        "(rolling phase percentiles) on this port "
                        "during the bench (0 = ephemeral; the report "
                        "records the bound port)")
    p.add_argument("--scrape-hz", dest="scrape_hz", type=float,
                   default=0.0,
                   help="self-scrape the endpoints at this rate during "
                        "the run (overhead-measurement methodology; "
                        "needs --metrics-port)")
    p.add_argument("--slo", default=None, metavar="JSON|PATH",
                   help="SLO config (serve/slo.py SLOConfig: ttft_p99_s/"
                        "tpot_p99_s/error_rate/availability + windows) — "
                        "arms the burn-rate watchdog on the router run; "
                        "alerts land in the trace/telemetry stream and "
                        "can trip the router's brown-out (requires "
                        "--replicas)")
    p.add_argument("--alert-sink", "--alert_sink", dest="alert_sink",
                   action="append", default=None, metavar="KIND:TARGET",
                   help="repeatable; PUSH SLO alert edges to an operator "
                        "sink — command:..., webhook:http://..., "
                        "jsonl:path (serve/slo.py AlertSinks: per-sink "
                        "retry backoff, dead-sink breaker); needs --slo")
    p.add_argument("--streaming", action="store_true",
                   help="with --procs: bench STREAMING token delivery "
                        "(per-burst TokenChunks over the push stream, "
                        "router TokenStreams). Without --fault-plan: "
                        "A/B vs end-of-request delivery over order-"
                        "balanced reps (gate: mean latency <= 1.05x). "
                        "With a kill --fault-plan: one chaos rep, real "
                        "signals mid-stream, consumer-side exactly-once "
                        "ledger (dupes/gaps gated 0, inter-token p99, "
                        "resume-gap p99) + tools/check_stream.py audit "
                        "of the telemetry JSONL")
    p.add_argument("--trace-overhead", dest="trace_overhead",
                   action="store_true",
                   help="with --procs: measure the fleet trace plane's "
                        "on/off overhead (worker span recording + push "
                        "streaming + router-side collection) over "
                        "order-balanced alternating reps against ONE "
                        "warm fleet; reports the latency ratios the "
                        "<=2%% acceptance gate judges, saves the merged "
                        "ON-rep timeline to --trace-out, and checks "
                        "/metrics bucket exemplars resolve into it")
    p.add_argument("--trace-sampling", dest="trace_sampling",
                   action="store_true",
                   help="with --procs: bench the HEAD-SAMPLED trace "
                        "plane (utils/trace.py TraceSampler) at the "
                        "--rate operating point — three arms (sampled/"
                        "full/off) rotated against ONE warm fleet; "
                        "reports span_reduction (gate >= 0.95 at 1%%) "
                        "and mean latency vs off (gate <= 1.02x); "
                        "saves the final sampled timeline to "
                        "--trace-out / --otlp-out")
    p.add_argument("--trace-sample", dest="trace_sample", type=float,
                   default=None, metavar="RATE",
                   help="head-sampling rate in [0,1]: one deterministic "
                        "keep/stage decision per trace_id (crc32 hash — "
                        "every process agrees), staged spans promoted "
                        "by the tail keep-rules (errors, sheds, "
                        "retries, failovers, resumes, preemptions, "
                        "--trace-keep-slow-s). Default: no sampling "
                        "(rate 1.0); the sampling bench defaults 0.01")
    p.add_argument("--trace-keep-slow-s", dest="trace_keep_slow_s",
                   type=float, default=None, metavar="S",
                   help="tail keep-rule: a request slower than this "
                        "end-to-end is kept regardless of the head "
                        "decision (set from the SLO: ~2x the latency "
                        "p99 target)")
    p.add_argument("--otlp-out", "--otlp_out", dest="otlp_out",
                   default=None, metavar="PATH",
                   help="write the run's request spans as OTLP-JSON "
                        "(ExportTraceServiceRequest shape — POST-able "
                        "to any OTLP/HTTP collector's /v1/traces); "
                        "validate with tools/check_otlp.py")
    p.add_argument("--otlp-endpoint", "--otlp_endpoint",
                   dest="otlp_endpoint", default=None, metavar="URL",
                   help="push kept spans LIVE to this OTLP/HTTP "
                        "collector (.../v1/traces) from a background "
                        "batcher (utils/telemetry.py OtlpPusher: "
                        "bounded queue, retry backoff, dead-endpoint "
                        "breaker; at-least-once with ddp.push.batch_id "
                        "for collector-side dedup). With "
                        "--otlp-push-overhead and no endpoint, a stub "
                        "collector is stood up automatically")
    p.add_argument("--otlp-push-overhead", dest="otlp_push_overhead",
                   action="store_true",
                   help="with --procs: A/B the LIVE push pipeline "
                        "against file-only export over order-balanced "
                        "rounds on ONE warm fleet (gate: mean latency "
                        "<= 1.02x) and audit capture completeness "
                        "against the batch-id-deduped collector")
    p.add_argument("--adaptive-sampling", dest="adaptive_sampling",
                   action="store_true",
                   help="with --procs: drive a 4x arrival step through "
                        "one warm fleet with the adaptive head-rate "
                        "controller active (utils/trace.py "
                        "AdaptiveHeadRateController) and report "
                        "kept-spans/s vs --trace-budget-sps (gate: "
                        "within ±20%% after the step, no thrash)")
    p.add_argument("--trace-budget-sps", dest="trace_budget_sps",
                   type=float, default=None, metavar="SPS",
                   help="kept-spans-per-second budget the adaptive "
                        "controller steers the fleet head rate toward "
                        "(multiplicative correction, deadband + hold "
                        "window; every change stamped as a trace_rate "
                        "instant and pushed live over the rpc trace op)")
    p.add_argument("--trace-tenant-rates", "--trace_tenant_rates",
                   dest="trace_tenant_rates", default=None,
                   metavar="JSON",
                   help="per-tenant head-rate overrides as a JSON "
                        'object, e.g. \'{"acme": 1.0, "free-tier": '
                        "0.01}' — tenants not listed use the fleet "
                        "rate; tail keep-rules stay tenant-blind, so "
                        "fault-affected requests are kept for EVERY "
                        "tenant")
    p.add_argument("--cache-aware", dest="cache_aware",
                   action="store_true",
                   help="with --procs: A/B cache-aware (prefix-"
                        "affinity) routing against least-loaded over "
                        "one shared-prefix trace through two identical "
                        "paged+prefix-cache worker fleets at the same "
                        "pool (serve/affinity.py) — reports the fleet "
                        "prefix-hit-token rate and goodput ratios, "
                        "zero-lost, and greedy token identity")
    p.add_argument("--frontdoor", action="store_true",
                   help="bench the HTTP/SSE front door end-to-end "
                        "through REAL client sockets "
                        "(serve/frontdoor.py): wire-vs-in-process "
                        "goodput + greedy token identity, chunked-"
                        "prefill short-request TTFT p99 ratio, "
                        "mid-stream worker SIGKILL with zero lost "
                        "streams (--procs workers), and mixed greedy+"
                        "sampled churn with zero new compiles — the "
                        "BENCH_serve.json frontdoor_100rps entry")
    p.add_argument("--sse-out", dest="sse_out", default=None,
                   metavar="PATH",
                   help="with --frontdoor: dump the wire-side SSE "
                        "frame capture as JSONL — audit with "
                        "tools/check_stream.py --sse")
    p.add_argument("--qos", action="store_true",
                   help="the multi-tenant QoS lab "
                        "(serve/workload.py): one adversarial plan "
                        "(hostile tenant at 4x the compliant share) "
                        "through FIFO and weighted-fair arms plus a "
                        "fair FLEET leg under a real SIGKILL — "
                        "reports the compliant tenant's TTFT-p99 "
                        "isolation ratio, Jain's fairness index, "
                        "per-tenant alert attribution, greedy token "
                        "identity and zero-lost; the "
                        "BENCH_serve.json qos_mixed_tenants_100rps "
                        "entry. --workload/--slo override the plan "
                        "and targets; --telemetry-out (prefix) "
                        "writes per-arm JSONLs for tools/"
                        "check_qos.py; --trace-out saves the kill "
                        "leg's fleet timeline")
    p.add_argument("--workload", default=None, metavar="JSON|PATH",
                   help="with --qos: a serve/workload.py WorkloadPlan "
                        "(JSON literal or path) replacing the default "
                        "hostile+compliant plan")
    p.add_argument("--qos-duration", dest="qos_duration", type=float,
                   default=2.0,
                   help="with --qos: plan duration in seconds "
                        "(arrival window; the run drains past it)")
    p.add_argument("--autoscale", action="store_true",
                   help="with --procs: A/B an ELASTIC fleet against the "
                        "fixed --procs fleet under a 4x arrival step "
                        "(serve/autoscaler.py: SLO-burn/queue-pressure "
                        "policy, pre-warmed standby promotion, graceful "
                        "drain scale-down) — gates goodput per "
                        "worker-second at equal SLO, reaction within "
                        "one evaluation window, zero lost, no thrash")
    p.add_argument("--autoscale-max", dest="autoscale_max", type=int,
                   default=3,
                   help="with --autoscale: elastic fleet size ceiling "
                        "(floor is 1)")
    p.add_argument("--standby", type=int, default=1,
                   help="with --autoscale: pre-warmed standby workers "
                        "kept ready to promote (pool replenishes in "
                        "the background after each promotion)")
    p.add_argument("--max-len", dest="max_len", type=int, default=None,
                   help="bench: slot-pool span / paged pool sizing "
                        "(default 128); the slot engine's decode cost "
                        "scales with this, the paged engine's does not")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--json", action="store_true")
    return p


def _serve_checkpoint(args) -> int:
    import jax.numpy as jnp

    from ddp_practice_tpu.generate import load_lm
    from ddp_practice_tpu.inference import decode_bytes, encode_bytes
    from ddp_practice_tpu.serve.engine import EngineConfig, SlotEngine
    from ddp_practice_tpu.serve.metrics import ServeMetrics
    from ddp_practice_tpu.serve.scheduler import Request, Scheduler

    model, params, batch_stats, step = load_lm(args.ckpt_dir)
    prompts = args.prompt or ["\n"]
    max_prompt = max(len(p.encode("utf-8")) for p in prompts)
    bucket = 8
    while bucket < max_prompt:
        bucket *= 2
    engine = SlotEngine(
        model, params,
        EngineConfig(
            max_slots=args.max_slots,
            prompt_buckets=(bucket,),
            temperature=args.temperature, top_k=args.top_k,
            top_p=args.top_p, eos_id=args.eos_id,
            decode_burst=args.decode_burst or 1,
        ),
        batch_stats=batch_stats,
    )
    tracer = None
    if args.trace_out:
        from ddp_practice_tpu.utils.trace import label_replica

        tracer = _make_tracer()
        engine.set_tracer(tracer, 0)
        label_replica(tracer, 0, args.max_slots)
    metrics = ServeMetrics()
    sched = Scheduler(engine, metrics=metrics, tracer=tracer)
    t0 = time.monotonic()
    for i, text in enumerate(prompts):
        toks = encode_bytes(text)[0].tolist()
        sched.submit(Request(
            rid=i, prompt=toks, max_new_tokens=args.max_new_tokens,
            seed=args.seed,
        ))
    completions = sched.run_until_idle()
    elapsed = time.monotonic() - t0
    for c in sorted(completions, key=lambda c: c.rid):
        toks = c.tokens
        if args.eos_id is not None and args.eos_id in toks:
            toks = toks[: toks.index(args.eos_id)]
        print(f"--- request {c.rid} [{c.status}] "
              f"ttft {c.ttft:.3f}s ---" if c.ttft is not None
              else f"--- request {c.rid} [{c.status}] ---")
        print(prompts[c.rid] + decode_bytes(jnp.asarray(toks)))
    metrics.emit(elapsed)
    if tracer is not None:
        tracer.save(args.trace_out)
        print(f"wrote trace to {args.trace_out} ({len(tracer)} events)")
    return 0


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.ckpt_dir:
        return _serve_checkpoint(args)
    if args.kv_int8 and not args.shared_prefix:
        raise SystemExit("--kv-int8 rides the --shared-prefix bench")
    if args.shared_prefix:
        report = shared_prefix_bench(
            n_requests=args.requests, rate_hz=args.rate,
            max_slots=args.max_slots, block_size=args.block_size,
            seed=args.seed, kv_int8=args.kv_int8,
        )
        if args.json:
            print(json.dumps(report))
        else:
            pl, pf = report["paged"], report["paged_prefix"]
            pc = pf["prefix_cache"]
            print(f"[shared_prefix_bench] {args.requests} requests @ "
                  f"{args.rate}/s, pool {report['pool']['num_blocks']} "
                  f"blocks x {report['pool']['block_size']} "
                  f"({report['pool']['kv_cache_dtype']})")
            for r in (pl, pf):
                print(f"  {r['mode']:>12}: {r['tokens_per_sec']:8.1f} "
                      f"tok/s  ttft p50 {r['ttft_s']['p50'] * 1e3:7.1f} "
                      f"ms  p99 {r['ttft_s']['p99'] * 1e3:7.1f} ms  "
                      f"preemptions {r['preemptions']}")
            print(f"  prefix/paged goodput: "
                  f"{report['prefix_vs_paged']:.2f}x  "
                  f"hit/miss tokens {pc['hit_tokens']}/"
                  f"{pc['miss_tokens']} "
                  f"(hit rate {pc['hit_rate']:.2f})")
            if args.kv_int8:
                print(f"  kv bytes/token: int8 "
                      f"{pf['kv_bytes_per_token']:.0f} vs f32 "
                      f"{report['kv_bytes_per_token_f32']:.0f} "
                      f"({report['kv_bytes_ratio']:.2f}x)")
        return 0
    if args.spec_decode:
        report = spec_decode_bench(
            n_requests=args.requests, rate_hz=args.rate,
            max_slots=args.max_slots, block_size=args.block_size,
            spec_k=args.spec_k, seed=args.seed,
            **({"decode_burst": args.decode_burst}
               if args.decode_burst is not None else {}),
        )
        if args.json:
            print(json.dumps(report))
        else:
            pl, sp = report["paged"], report["paged_spec"]
            print(f"[spec_decode_bench] {args.requests} requests @ "
                  f"{args.rate}/s, spec_k {report['spec_k']}")
            for r in (pl, sp):
                print(f"  {r['mode']:>12}: {r['tokens_per_sec']:8.1f} "
                      f"tok/s  tpot p50 {r['tpot_s']['p50'] * 1e3:6.2f} "
                      f"ms  latency p50 "
                      f"{r['latency_s']['p50'] * 1e3:7.1f} ms")
            print(f"  spec/paged tpot: {report['tpot_ratio']:.2f}x  "
                  f"latency p50: {report['latency_ratio_p50']:.2f}x  "
                  f"accept rate {report['accept_rate']:.2f}  "
                  f"token identity {report['token_identity']:.2f}")
        return 0
    if args.frontdoor:
        report = frontdoor_bench(
            n_requests=args.requests, rate_hz=args.rate,
            max_slots=args.max_slots, procs=args.procs or 2,
            seed=args.seed, sse_out=args.sse_out,
            **({"decode_burst": args.decode_burst}
               if args.decode_burst is not None else {}),
        )
        if args.json:
            print(json.dumps(report))
        else:
            ip, w = report["in_process"], report["wire"]
            print(f"[frontdoor_bench] "
                  f"{report['trace']['n_requests']} requests @ "
                  f"{report['trace']['rate_hz']}/s through real "
                  f"sockets")
            for r in (ip, w):
                print(f"  {r['mode']:>24}: "
                      f"{r['goodput_tokens_per_sec']:8.1f} tok/s  "
                      f"ttft p50 {r['ttft_s']['p50'] * 1e3:7.1f} ms  "
                      f"lost {r['lost']}")
            cs = report["check_stream"]
            print(f"  wire/in-process goodput "
                  f"{report['goodput_ratio']:.3f}x  token identity "
                  f"{report['token_identity']:.2f}  --sse audit "
                  f"ok={cs.get('ok')} ({cs.get('streams', 0)} "
                  f"streams, {cs.get('violations', 0)} violations)")
            cp = report["chunked_prefill"]
            print(f"  chunked prefill: short-TTFT p99 "
                  f"{cp['chunked']['ttft_short_s']['p99'] * 1e3:.0f}"
                  f" ms vs "
                  f"{cp['unchunked']['ttft_short_s']['p99'] * 1e3:.0f}"
                  f" ms unchunked — ratio "
                  f"{report['ttft_p99_ratio_chunked']:.3f}x  "
                  f"identity {cp['token_identity']:.2f}")
            sk = report["sigkill"]
            print(f"  SIGKILL @ {sk['kill_at_s']}s: lost "
                  f"{report['sigkill_lost']}  resumed markers "
                  f"{sk['resumed_markers']}  restarts "
                  f"{sk['worker_restarts']}  audit "
                  f"ok={sk['check_stream'].get('ok')}")
            sm = report["sampling"]
            print(f"  sampling churn: new compiles "
                  f"{report['sampling_new_compiles']}  statuses "
                  f"{sm['statuses']}  audit "
                  f"ok={sm['check_stream'].get('ok')}")
            if "sse_out" in report:
                print(f"  wrote SSE capture to {report['sse_out']} — "
                      f"audit with tools/check_stream.py --sse")
        return 0
    if args.qos:
        report = qos_bench(
            rate_hz=args.rate, duration_s=args.qos_duration,
            max_slots=args.max_slots, procs=args.procs or 2,
            seed=args.seed, slo=args.slo, workload=args.workload,
            telemetry_out=args.telemetry_out, trace_out=args.trace_out,
            **({"decode_burst": args.decode_burst}
               if args.decode_burst is not None else {}),
        )
        if args.json:
            print(json.dumps(report))
        else:
            print(f"[qos_bench] {len(report['workload']['tenants'])} "
                  f"tenants @ {args.rate}/s for "
                  f"{report['workload']['duration_s']}s — hostile "
                  f"{report['hostile_tenants']} vs compliant "
                  f"{report['compliant_tenants']}")
            for tag in ("fifo", "fair"):
                r = report[tag]
                for t, row in r["per_tenant"].items():
                    print(f"  {r['mode']:>10} {t:>8}: ttft p99 "
                          f"{row['ttft_s'].get('p99', 0) * 1e3:7.1f} "
                          f"ms  {row['output_tokens']:5d} tok  "
                          f"({row['ok']}/{row['completions']} ok)")
                print(f"  {r['mode']:>10} fairness "
                      f"{r['fairness_index']:.4f}  trips "
                      f"{sum(a['event'] == 'trip' for a in r['alerts'])}"
                      f"  lost {r['lost']}")
            print(f"  isolation ttft p99 fair/fifo "
                  f"{report['isolation_ttft_p99_ratio']:.3f}x  "
                  f"token identity {report['token_identity']:.2f}  "
                  f"hostile tripped "
                  f"{report['hostile_alert_tripped']:.0f}  compliant "
                  f"clean {report['compliant_clean']:.0f}")
            sk = report["sigkill"]
            print(f"  SIGKILL @ {sk['kill_at_s']}s: lost "
                  f"{sk['lost']}  identity "
                  f"{sk['token_identity']:.2f}  fairness "
                  f"{sk['fairness_index']:.4f}  restarts "
                  f"{len(sk['worker_restarts'])}  check_qos "
                  f"ok={sk.get('check_qos_ok')}  trace "
                  f"ok={sk.get('trace_ok')}")
        return 0
    if args.procs and args.otlp_push_overhead:
        report = fleet_otlp_push_bench(
            n_requests=args.requests, rate_hz=args.rate,
            max_slots=args.max_slots, procs=args.procs,
            seed=args.seed, otlp_endpoint=args.otlp_endpoint,
            **({"sample": args.trace_sample}
               if args.trace_sample is not None else {}),
            **({"decode_burst": args.decode_burst}
               if args.decode_burst is not None else {}),
        )
        if args.json:
            print(json.dumps(report))
        else:
            pu = report["push"]
            print(f"[fleet_otlp_push] {args.requests} requests @ "
                  f"{args.rate}/s, {args.procs} workers, head rate "
                  f"{report['head_rate']}, {report['pairs']} "
                  f"order-balanced rounds")
            print(f"  push vs file-only: latency mean "
                  f"{report['mean_ratio']:.3f}x  ({report['gate']})")
            print(f"  pushed {pu['spans_sent']} spans in "
                  f"{pu['batches_sent']} batches "
                  f"(dropped {pu['batches_dropped']}, post failures "
                  f"{pu['post_failures']})")
            if "spans_delivered" in pu:
                print(f"  collector: {pu['spans_delivered']} spans "
                      f"after dedup of {pu['duplicate_batches']} "
                      f"duplicate batch(es) — complete="
                      f"{pu['complete']}")
        return 0
    if args.procs and args.autoscale:
        report = fleet_autoscale_bench(
            rate_hz=args.rate, procs=args.procs,
            autoscale_max=args.autoscale_max, standby=args.standby,
            max_slots=args.max_slots, seed=args.seed,
            **({"decode_burst": args.decode_burst}
               if args.decode_burst is not None else {}),
        )
        if args.json:
            print(json.dumps(report))
        else:
            au, fx = report["autoscaled"], report["fixed"]
            print(f"[fleet_autoscale] {args.rate}/s -> "
                  f"{report['step_rate_hz']}/s step; fixed "
                  f"{report['procs_fixed']} workers vs elastic "
                  f"{report['autoscale']['min']}.."
                  f"{report['autoscale']['max']} "
                  f"(+{report['autoscale']['standby']} standby)")
            for r in (fx, au):
                print(f"  {r['mode']:>10}: "
                      f"{r['goodput_per_worker']:7.1f} tok/s/worker  "
                      f"({r['useful_tokens']} tok over "
                      f"{r['worker_seconds']:.1f} worker-s)  lost "
                      f"{r['lost']}")
            rs, pj = report["reaction_s"], report["promote_join_s"]
            print(f"  goodput/worker ratio "
                  f"{report['goodput_per_worker_ratio']:.2f}x  "
                  + (f"reaction {rs:.2f}s (window "
                     f"{report['reaction_window_s']:.2f}s, within="
                     f"{report['reaction_within_window']:.0f})"
                     if rs is not None
                     else "no scale-up observed after the step"))
            print("  warm promotion "
                  + (f"{pj:.3f}s" if pj is not None else "n/a")
                  + f" vs cold spawn {report['cold_spawn_s']:.1f}s  "
                  f"direction changes {au['direction_changes']} "
                  f"(bound {au['oscillation_bound']}, "
                  f"ok={report['oscillation_ok']:.0f})")
        return 0
    if args.procs and args.adaptive_sampling:
        report = fleet_adaptive_sampling_bench(
            rate_hz=args.rate, procs=args.procs,
            max_slots=args.max_slots, seed=args.seed,
            **({"budget_sps": args.trace_budget_sps}
               if args.trace_budget_sps is not None else {}),
            **({"decode_burst": args.decode_burst}
               if args.decode_burst is not None else {}),
        )
        if args.json:
            print(json.dumps(report))
        else:
            print(f"[fleet_adaptive_sampling] {args.rate}/s -> "
                  f"{report['step_rate_hz']}/s step, {args.procs} "
                  f"workers, budget {report['budget_sps']} kept "
                  f"spans/s")
            print(f"  kept {report['kept_sps']:.1f} spans/s in the "
                  f"final window — err {report['budget_err']:.2f} "
                  f"({report['gate']}; within_budget="
                  f"{report['within_budget']:.0f})")
            print(f"  head rate {report['rate_final']:.4f} after "
                  f"{report['rate_changes']} change(s): "
                  + ", ".join(
                      f"{c['prev']:.3f}->{c['rate']:.3f}"
                      for c in report["rate_log"]))
        return 0
    if args.procs and args.trace_sampling:
        report = fleet_trace_sampling_bench(
            n_requests=args.requests, rate_hz=args.rate,
            max_slots=args.max_slots, procs=args.procs,
            seed=args.seed, trace_out=args.trace_out,
            otlp_out=args.otlp_out,
            keep_slow_s=args.trace_keep_slow_s,
            **({"sample": args.trace_sample}
               if args.trace_sample is not None else {}),
            **({"decode_burst": args.decode_burst}
               if args.decode_burst is not None else {}),
        )
        if args.json:
            print(json.dumps(report))
        else:
            print(f"[fleet_trace_sampling] {args.requests} requests @ "
                  f"{args.rate}/s, {args.procs} workers, head rate "
                  f"{report['head_rate']}, {report['pairs']} "
                  f"order-balanced rounds")
            print(f"  span reduction vs full tracing: "
                  f"{report['span_reduction']:.3f}  latency mean vs "
                  f"off: {report['mean_ratio']:.3f}x  "
                  f"({report['gate']})")
            sm = report.get("sampling") or {}
            print(f"  traces: {sm.get('traces_sampled', 0)} head-"
                  f"sampled, {sm.get('traces_kept', 0)} tail-kept "
                  f"{dict(sm.get('kept_reasons') or {})}, "
                  f"{sm.get('traces_suppressed', 0)} suppressed; "
                  f"spans suppressed "
                  f"{sm.get('spans_suppressed', 0)}")
            if "trace_out" in report:
                print(f"  wrote sampled timeline to "
                      f"{report['trace_out']} — validate with "
                      f"tools/check_traces.py --fleet")
            if "otlp_out" in report:
                print(f"  wrote OTLP export to {report['otlp_out']} — "
                      f"validate with tools/check_otlp.py")
        return 0
    if args.procs and args.trace_overhead:
        report = fleet_trace_overhead_bench(
            n_requests=args.requests, rate_hz=args.rate,
            max_slots=args.max_slots, procs=args.procs,
            seed=args.seed, trace_out=args.trace_out,
            **({"decode_burst": args.decode_burst}
               if args.decode_burst is not None else {}),
        )
        if args.json:
            print(json.dumps(report))
        else:
            print(f"[fleet_trace_overhead] {args.requests} requests @ "
                  f"{args.rate}/s, {args.procs} workers, "
                  f"{report['pairs']} order-balanced pairs")
            print(f"  trace plane on/off: latency p50 "
                  f"{report['latency_ratio_p50']:.3f}x  mean "
                  f"{report['latency_ratio_mean']:.3f}x  goodput "
                  f"{report['goodput_ratio']:.3f}x  ({report['gate']})")
            tp = report["trace_plane"]
            print(f"  merged timeline: {report['trace_events']} events "
                  f"({tp['worker_events']} from workers in "
                  f"{tp['worker_frames']} frames, dropped "
                  f"{tp['dropped']}, skew bound "
                  f"{(tp['skew_bound_s'] or 0) * 1e3:.2f} ms)")
            ex = report["exemplars"]
            print(f"  exemplars: {ex['resolved']}/{ex['found']} bucket "
                  f"exemplars resolve; p99 bucket resolves: "
                  f"{ex['p99_resolves']}")
            if "trace_out" in report:
                print(f"  wrote merged trace to {report['trace_out']} — "
                      f"validate with tools/check_traces.py --fleet")
        return 0
    if args.procs and args.streaming:
        from ddp_practice_tpu.serve.faults import FaultPlan

        plan = (FaultPlan.from_json(args.fault_plan)
                if args.fault_plan else None)
        report = streaming_bench(
            n_requests=args.requests, rate_hz=args.rate,
            max_slots=args.max_slots, procs=args.procs,
            seed=args.seed, fault_plan=plan,
            telemetry_out=args.telemetry_out,
            **({"decode_burst": args.decode_burst}
               if args.decode_burst is not None else {}),
        )
        if args.json:
            print(json.dumps(report))
        elif "fleet" in report:  # chaos arm
            fl, st = report["fleet"], report["streams"]
            print(f"[streaming_bench chaos] {args.requests} requests @ "
                  f"{args.rate}/s, {args.procs} workers, kills "
                  f"{fl['kills_fired']}")
            print(f"  consumer ledger: dupes {report['chunk_dupes']}  "
                  f"gaps {report['chunk_gaps']}  lost {report['lost']}  "
                  f"unterminated {report['unterminated']}  "
                  f"resumed markers {st['resumed_markers']}  "
                  f"suppressed {st['suppressed_tokens']} tok")
            print(f"  inter-token p99 "
                  f"{report['inter_token_p99_s'] * 1e3:.2f} ms  "
                  f"resume gap p99 "
                  f"{report['resume_gap_p99_s'] * 1e3:.1f} ms")
            cs = report.get("check_stream", {})
            print(f"  check_stream audit: ok={cs.get('ok')} over "
                  f"{cs.get('streams', 0)} stream(s), "
                  f"{cs.get('violations', 0)} violation(s)")
        else:
            print(f"[streaming_bench] {args.requests} requests @ "
                  f"{args.rate}/s, {args.procs} workers, "
                  f"{report['reps']} order-balanced reps")
            print(f"  streaming vs end-of-request: latency mean "
                  f"{report['latency_ratio_mean']:.3f}x  p50 "
                  f"{report['latency_ratio_p50']:.3f}x  goodput "
                  f"{report['goodput_ratio']:.3f}x  ({report['gate']})")
            print(f"  exactly-once cross-check violations: "
                  f"{report['stream_violations']}")
        return 0
    if args.procs and args.cache_aware:
        report = cache_routing_bench(
            n_requests=args.requests, rate_hz=args.rate,
            procs=args.procs, max_slots=args.max_slots,
            block_size=args.block_size, seed=args.seed,
            **({"decode_burst": args.decode_burst}
               if args.decode_burst is not None else {}),
        )
        if args.json:
            print(json.dumps(report))
        else:
            aff, ll = report["affinity"], report["least_loaded"]
            print(f"[cache_routing_bench] {report['trace']['n_requests']}"
                  f" requests @ {report['trace']['rate_hz']}/s, "
                  f"{report['procs']} workers, "
                  f"{report['trace']['k_prefixes']} prefix families x "
                  f"{report['trace']['prefix_len']} tokens, pool "
                  f"{report['pool']['num_blocks']} x "
                  f"{report['pool']['block_size']}")
            for r in (ll, aff):
                print(f"  {r['mode']:>16}: "
                      f"{r['goodput_tokens_per_sec']:8.1f} tok/s  "
                      f"hit rate {r['hit_rate']:.3f}  "
                      f"({r['hit_tokens']}/"
                      f"{r['hit_tokens'] + r['miss_tokens']} prefill "
                      f"tokens warm)  lost {r['lost']}")
            print(f"  affinity/least-loaded: hit rate "
                  f"{report['hit_rate_ratio']:.2f}x  goodput "
                  f"{report['goodput_ratio']:.2f}x  token identity "
                  f"{report['token_identity']:.2f}  routes "
                  f"{aff['route_decisions']}")
        return 0
    if args.procs:
        from ddp_practice_tpu.serve.faults import FaultPlan

        plan = (FaultPlan.from_json(args.fault_plan)
                if args.fault_plan else None)
        report = fleet_bench(
            n_requests=args.requests, rate_hz=args.rate,
            max_slots=args.max_slots, procs=args.procs,
            seed=args.seed, fault_plan=plan,
            metrics_port=args.metrics_port,
            trace_out=args.trace_out,
            otlp_out=args.otlp_out,
            otlp_endpoint=args.otlp_endpoint,
            trace_keep_slow_s=args.trace_keep_slow_s,
            trace_tenant_rates=(
                json.loads(args.trace_tenant_rates)
                if args.trace_tenant_rates else None),
            **({"trace_sample": args.trace_sample}
               if args.trace_sample is not None else {}),
            **({"decode_burst": args.decode_burst}
               if args.decode_burst is not None else {}),
        )
        if args.json:
            print(json.dumps(report))
        else:
            ip, fl = report["in_process"], report["fleet"]
            kills = " under real kills" if args.fault_plan else ""
            print(f"[fleet_bench] {args.requests} requests @ "
                  f"{args.rate}/s, {args.procs} workers{kills}")
            for r in (ip, fl):
                print(f"  {r['mode']:>12}: "
                      f"{r['goodput_tokens_per_sec']:8.1f} tok/s  "
                      f"ttft p50 {r['ttft_s']['p50'] * 1e3:7.1f} ms  "
                      f"latency p50 {r['latency_s']['p50'] * 1e3:7.1f}"
                      f"/p99 {r['latency_s']['p99'] * 1e3:.1f} ms")
            print(f"  contended latency ratio p50 "
                  f"{report['latency_ratio_p50']:.3f}x  p99 "
                  f"{report['latency_ratio_p99']:.3f}x  goodput "
                  f"{report['goodput_ratio']:.3f}x")
            if "tpot_ratio_p50" in report:
                print(f"  decomposition: tpot (steady decode) "
                      f"{report['tpot_ratio_p50']:.3f}x  ttft "
                      f"(admission hop) {report['ttft_ratio_p50']:.3f}x")
            print(f"  fleet: statuses {fl['statuses']}  lost "
                  f"{fl['lost']}  failovers {fl['failovers']:.0f}  "
                  f"restarts {fl['worker_restarts']}"
                  + (f"  kills {fl.get('kills_fired')}"
                     if "kills_fired" in fl else ""))
            if "trace_out" in report:
                tp = report["trace_plane"]
                print(f"  wrote merged fleet trace to "
                      f"{report['trace_out']} "
                      f"({report['trace_events']} events, "
                      f"{tp['worker_events']} from workers, dropped "
                      f"{tp['dropped']}) — validate with "
                      f"tools/check_traces.py --fleet")
            if "sampling" in report:
                sm = report["sampling"]
                print(f"  sampling: head rate {sm['head_rate']:g} — "
                      f"{sm['traces_sampled']} head-sampled, "
                      f"{sm['traces_kept']} tail-kept "
                      f"{sm['kept_reasons']}, "
                      f"{sm['traces_suppressed']} suppressed")
            if "otlp_out" in report:
                print(f"  wrote OTLP export to {report['otlp_out']} — "
                      f"validate with tools/check_otlp.py")
        return 0
    if args.trace_overhead:
        raise SystemExit("--trace-overhead needs --procs N (it measures "
                         "the fleet trace plane against worker "
                         "processes)")
    if args.streaming:
        raise SystemExit("--streaming needs --procs N (chunks ride the "
                         "worker push stream; the in-process router "
                         "streams by default already)")
    if args.alert_sink and not args.slo:
        raise SystemExit("--alert-sink needs --slo (the sinks carry the "
                         "watchdog's trip/resolve edges)")
    if args.fault_plan and not args.replicas:
        raise SystemExit("--fault-plan needs --replicas N (faults are "
                         "injected into the router fleet run)")
    if args.slo and not args.replicas:
        raise SystemExit("--slo needs --replicas N (the watchdog feeds "
                         "the router's brown-out hook)")
    if args.scrape_hz and args.metrics_port is None:
        raise SystemExit("--scrape-hz needs --metrics-port (there is "
                         "nothing to scrape without the server)")
    bench_kw = {}
    if args.decode_burst is not None:
        bench_kw["decode_burst"] = args.decode_burst
    if args.paged:
        bench_kw["paged"] = True
        bench_kw["block_size"] = args.block_size
    if args.max_len is not None:
        bench_kw["max_len"] = args.max_len
    if args.trace_out:
        bench_kw["trace_out"] = args.trace_out
    if args.telemetry_out:
        bench_kw["telemetry_out"] = args.telemetry_out
    if args.metrics_port is not None:
        bench_kw["metrics_port"] = args.metrics_port
        bench_kw["scrape_hz"] = args.scrape_hz
    if args.slo:
        bench_kw["slo"] = args.slo
        if args.alert_sink:
            bench_kw["alert_sinks"] = args.alert_sink
    if args.replicas:
        from ddp_practice_tpu.serve.faults import FaultPlan

        bench_kw["replicas"] = args.replicas
        if args.fault_plan:
            bench_kw["fault_plan"] = FaultPlan.from_json(args.fault_plan)
    report = serve_bench(
        n_requests=args.requests, rate_hz=args.rate,
        max_slots=args.max_slots, seed=args.seed, **bench_kw,
    )
    if args.json:
        print(json.dumps(report))
    else:
        c, s = report["continuous"], report["static"]
        print(
            f"[serve_bench] {args.requests} requests @ {args.rate}/s, "
            f"{args.max_slots} slots"
        )
        rows = [c, s] + ([report["paged"]] if "paged" in report else []) \
            + ([report["router"]] if "router" in report else [])
        for r in rows:
            print(
                f"  {r['mode']:>10}: {r['tokens_per_sec']:8.1f} tok/s  "
                f"ttft p50 {r['ttft_s']['p50'] * 1e3:7.1f} ms  "
                f"p99 {r['ttft_s']['p99'] * 1e3:7.1f} ms  "
                f"latency p50 {r['latency_s']['p50'] * 1e3:7.1f} ms"
            )
            ph = r.get("phases")
            if ph:
                print(
                    "              phases p50/p99 ms:  "
                    + "  ".join(
                        f"{k[:-2]} {ph[k]['p50'] * 1e3:.1f}/"
                        f"{ph[k]['p99'] * 1e3:.1f}"
                        for k in ("queue_s", "prefill_s", "decode_s",
                                  "stall_s")
                    )
                )
        print(f"  continuous/static throughput: "
              f"{report['throughput_ratio']:.2f}x")
        if "paged" in report:
            print(
                f"  paged/continuous throughput: "
                f"{report['paged_vs_continuous']:.2f}x  "
                f"(max servable context: paged "
                f"{report['paged']['max_servable_context']} vs slot "
                f"{report['continuous']['max_servable_context']} "
                f"@ max_len {report['max_len']})"
            )
        if "router" in report:
            r = report["router"]
            faults = " under injected faults" if args.fault_plan else ""
            print(
                f"  router{faults}: goodput "
                f"{r['goodput_tokens_per_sec']:.1f} tok/s  statuses "
                f"{r['statuses']}  retries {r['retries']:.0f}  "
                f"failovers {r['failovers']:.0f}  "
                f"breaker trips {r['breaker_trips']:.0f}"
            )
            print(f"  router/continuous throughput: "
                  f"{report['router_vs_continuous']:.2f}x")
        if "trace_out" in report:
            print(f"  wrote trace to {report['trace_out']} "
                  f"({report['trace_events']} events) — validate with "
                  f"tools/check_traces.py")
        if "telemetry" in report:
            t = report["telemetry"]
            line = (f"  telemetry plane: port {t['metrics_port']}  "
                    f"scrapes {t['scrapes']}  dropped {t['dropped']}")
            if t["telemetry_out"]:
                line += (f"  jsonl {t['telemetry_out']} — judge with "
                         f"tools/check_slo.py")
            print(line)
        slo_rep = report.get("router", {}).get("slo")
        if slo_rep:
            trips = sum(a["event"] == "trip" for a in slo_rep["alerts"])
            print(f"  slo: {trips} alert trip(s), "
                  f"active at end: "
                  f"{[k for k, v in slo_rep['active'].items() if v]}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
