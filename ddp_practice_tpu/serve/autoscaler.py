"""Elastic fleet: SLO-driven autoscaling with pre-warmed standbys.

ROADMAP item 4 said it outright: all the sensors and actuators exist —
close the loop. The sensors are the federated fleet view (queue depth,
occupancy, heartbeat ages) and serve/slo.py's multi-window burn rates;
the actuators are the supervisor's spawn pipeline and the PR-9 SIGTERM
drain. This module is the loop:

- **AutoscalerPolicy** — the host-pure controller. Trip FAST on SLO
  burn or queue pressure to scale UP; resolve SLOW (a continuous calm
  window on top of the slow burn window) to scale DOWN; a deadband
  between the two thresholds where nothing moves. The no-oscillation
  contract is the same one utils/trace.py's AdaptiveHeadRateController
  pins: after any scale event, an event in the OPPOSITE direction is
  forbidden for `hold_s` — so direction reversals are at least `hold_s`
  apart BY CONSTRUCTION (at most elapsed/hold_s reversals, ever), and
  per-direction cooldowns pace same-direction steps on top. Every
  input is an argument and time is a parameter, so FakeClock pins can
  replay every transition.

- **StandbyPool** — workers spawned AHEAD of demand. The measured
  ~15 s jax-import+warm spawn cost makes reactive cold scaling useless
  (the burst is over before the replica exists); the pool keeps
  `standby_target` workers warm-before-READY, so promotion is a probe
  plus a dispatch join — milliseconds. One background thread spawns
  serially (a spawn is expensive; two at once would starve the fleet),
  replenishing after each take; every child rides the module-level
  atexit registry in serve/supervisor.py, so a pooled standby can no
  more leak than a fleet worker can.

- **Autoscaler** — the orchestrator the Router ticks (router.step ->
  autoscaler.step, right after the SLO pass so burn rates are fresh).
  `grow` promotes a standby via `Supervisor.grow(spec, worker=...)`
  (falling back to the cold spawn pipeline when the pool is empty) and
  joins the new RemoteReplicaHandle into the router with the same
  breaker arming __init__ applies. `shrink` drains the newest RUNNING
  slot via the PR-9 SIGTERM path — refuse new submits, finish
  in-flight streams — and retires the handle once the process exits;
  if chaos SIGKILLs the draining worker mid-scale-down, the handle's
  normal death path salvages and fails over, so the exactly-once
  stream contract (check_stream: 0 lost / 0 dup) holds either way.

Scale events ride the observability plane whole: tracer instants with
trigger attrs, `fleet_size` / `standby_ready` gauges and the
`scale_events_total{direction,trigger}` ledger (serve/metrics.py),
and alert-sink edges with scope "autoscale".
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Callable, Dict, List, Optional

from ddp_practice_tpu.serve.router import ROUTER_PID
from ddp_practice_tpu.serve.scheduler import MonotonicClock
from ddp_practice_tpu.serve.supervisor import (
    RUNNING,
    STOPPED,
    RemoteReplicaHandle,
    Supervisor,
    spawn_worker,
)
from ddp_practice_tpu.serve.worker import WorkerSpec


# ------------------------------------------------------------------ policy
@dataclasses.dataclass(frozen=True)
class AutoscalerConfig:
    min_size: int = 1
    max_size: int = 4
    # policy evaluation spacing — the "one evaluation window" the
    # acceptance pin measures reaction time against
    eval_interval_s: float = 1.0
    # fleet pressure = demand / decode slots. Above `up_pressure` the
    # queue is outrunning the fleet (brownout_on territory — grow
    # instead of shedding); below `down_pressure` a replica is idle
    # weight. Between them is the DEADBAND: nothing moves.
    up_pressure: float = 1.5
    down_pressure: float = 0.5
    # no-reversal window: after ANY scale event, no event in the
    # OPPOSITE direction for this long (the anti-oscillation contract)
    hold_s: float = 10.0
    # per-direction pacing for CONSECUTIVE same-direction events:
    # trip fast (short up cooldown), resolve slow (long down cooldown)
    cooldown_up_s: float = 2.0
    cooldown_down_s: float = 15.0
    # scale-down additionally requires the calm signal (low pressure,
    # slow burn resolved) to have held CONTINUOUSLY this long — one
    # quiet sample between bursts is noise, not calm
    down_stable_s: float = 5.0
    # warm standbys the pool keeps ahead of demand
    standby_target: int = 1

    def __post_init__(self):
        if self.min_size < 1:
            raise ValueError("min_size must be >= 1")
        if self.max_size < self.min_size:
            raise ValueError("max_size must be >= min_size")
        if self.down_pressure >= self.up_pressure:
            raise ValueError(
                "down_pressure must be < up_pressure (the deadband)"
            )


class AutoscalerPolicy:
    """The host-pure control law: inputs in, at most one decision out.

    `step()` returns None (throttled / deadband / held / clamped) or a
    decision dict {"direction", "trigger", ...}. State is four
    timestamps and a calm-window anchor — every transition FakeClock
    pins replay exactly:

    - throttle: at most one evaluation per `eval_interval_s`;
    - trip fast: SLO burn alerting (`slo_active`) or pressure at/above
      `up_pressure` wants UP, this evaluation;
    - resolve slow: DOWN wants no alert, the slow burn window resolved
      AND pressure at/below `down_pressure`, continuously for
      `down_stable_s`;
    - deadband: neither condition -> None;
    - no-reversal-inside-hold: a decision opposite to the last one is
      refused until `hold_s` has passed since it — so the loop cannot
      flap up/down faster than hold_s per reversal, provably;
    - per-direction cooldown, then min/max clamp.
    """

    def __init__(self, config: AutoscalerConfig = AutoscalerConfig()
                 ) -> None:
        self.config = config
        self._last_eval = -1e18
        self._last_change_t: Optional[float] = None
        self._last_direction: Optional[str] = None
        self._last_up_t: Optional[float] = None
        self._last_down_t: Optional[float] = None
        self._calm_since: Optional[float] = None
        self.events: List[dict] = []   # every committed decision

    def _blocked(self, now: float, direction: str) -> bool:
        cfg = self.config
        if (self._last_change_t is not None
                and self._last_direction is not None
                and self._last_direction != direction
                and now - self._last_change_t < cfg.hold_s):
            return True   # reversal inside the hold window: refused
        last_same = (self._last_up_t if direction == "up"
                     else self._last_down_t)
        cooldown = (cfg.cooldown_up_s if direction == "up"
                    else cfg.cooldown_down_s)
        return last_same is not None and now - last_same < cooldown

    def _commit(self, now: float, direction: str, trigger: str,
                size: int, pressure: float) -> dict:
        self._last_change_t = now
        self._last_direction = direction
        if direction == "up":
            self._last_up_t = now
            # the grow is about to relieve pressure: the calm window
            # must re-anchor from scratch, not inherit burst samples
            self._calm_since = None
        else:
            self._last_down_t = now
        decision = {
            "t": now, "direction": direction, "trigger": trigger,
            "size": size, "pressure": round(pressure, 4),
        }
        self.events.append(decision)
        return decision

    def step(self, now: float, *, size: int, pressure: float,
             slo_active: bool = False, slo_resolved: bool = True,
             standby_ready: int = 0) -> Optional[dict]:
        cfg = self.config
        if now - self._last_eval < cfg.eval_interval_s:
            return None
        self._last_eval = now
        up_want = slo_active or pressure >= cfg.up_pressure
        calm_now = (not slo_active and slo_resolved
                    and pressure <= cfg.down_pressure)
        if calm_now and not up_want:
            if self._calm_since is None:
                self._calm_since = now
        else:
            self._calm_since = None
        if up_want:
            if size >= cfg.max_size or self._blocked(now, "up"):
                return None
            trigger = "slo_burn" if slo_active else "queue_pressure"
            return self._commit(now, "up", trigger, size, pressure)
        if (self._calm_since is not None
                and now - self._calm_since >= cfg.down_stable_s):
            if size <= cfg.min_size or self._blocked(now, "down"):
                return None
            return self._commit(now, "down", "slo_resolved",
                                size, pressure)
        return None   # deadband (or calm still proving itself)


# ------------------------------------------------------------ standby pool
class StandbyPool:
    """Warm workers spawned ahead of demand, one background thread.

    `provision(rid)` queues one standby build (spec_fn(rid) names it);
    the thread spawns serially — through serve/supervisor.py's
    `spawn_worker`, so every child lands in the atexit-reaped registry
    — and finished workers wait warm in FIFO order. `take()` pops the
    oldest (rid, spec, worker) for promotion; `close()` reaps whatever
    is left. `spawn_in_thread=False` makes provision() synchronous for
    host-pure tests."""

    def __init__(self, spec_fn: Callable[[int], WorkerSpec], *,
                 spawn_fn: Optional[Callable] = None,
                 spawn_in_thread: bool = True) -> None:
        self.spec_fn = spec_fn
        self.spawn_fn = spawn_fn or spawn_worker
        self.spawn_in_thread = spawn_in_thread
        self._lock = threading.Lock()
        self._queue: List[int] = []        # rids awaiting a spawn
        self._ready: List[tuple] = []      # (rid, spec, worker), FIFO
        self._thread: Optional[threading.Thread] = None
        self._closed = False
        self.spawned_total = 0
        self.spawn_errors: List[tuple] = []   # (rid, repr(exc))

    # ------------------------------------------------------------ intake
    def provision(self, rid: int) -> None:
        """Queue one standby build for a pre-assigned replica id."""
        with self._lock:
            if self._closed:
                return
            self._queue.append(rid)
            if not self.spawn_in_thread:
                pass   # drained synchronously below
            elif self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(
                    target=self._run, name="standby-pool", daemon=True
                )
                self._thread.start()
        if not self.spawn_in_thread:
            self._drain_queue()

    def _drain_queue(self) -> None:
        while True:
            with self._lock:
                if self._closed or not self._queue:
                    return
                rid = self._queue.pop(0)
            spec = self.spec_fn(rid)
            try:
                worker = self.spawn_fn(spec)
            except BaseException as e:   # noqa: BLE001 — ledger, not mask
                with self._lock:
                    self.spawn_errors.append((rid, repr(e)))
                continue
            with self._lock:
                if self._closed:
                    worker.reap()
                    return
                self._ready.append((rid, spec, worker))
                self.spawned_total += 1

    def _run(self) -> None:
        self._drain_queue()

    # ----------------------------------------------------------- consume
    @property
    def ready_count(self) -> int:
        with self._lock:
            return len(self._ready)

    @property
    def in_flight(self) -> int:
        with self._lock:
            return len(self._queue)

    def take(self) -> Optional[tuple]:
        """Pop the oldest warm standby, (rid, spec, worker) — None when
        the pool has nothing ready (the caller falls back cold)."""
        with self._lock:
            if not self._ready:
                return None
            return self._ready.pop(0)

    def wait_ready(self, timeout_s: float = 300.0,
                   n: int = 1) -> bool:
        """Block until >= n standbys are warm (bench pre-warm barrier);
        False on timeout or a closed pool."""
        import time as _time

        deadline = _time.monotonic() + timeout_s
        while _time.monotonic() < deadline:
            with self._lock:
                if self._closed:
                    return False
                if len(self._ready) >= n:
                    return True
                if not self._queue and (
                        self._thread is None
                        or not self._thread.is_alive()):
                    return len(self._ready) >= n
            _time.sleep(0.02)
        return False

    def close(self) -> None:
        """Reap every pooled standby and refuse further provisioning
        (the atexit registry would catch leaks anyway — this is the
        polite path)."""
        with self._lock:
            self._closed = True
            self._queue.clear()
            ready, self._ready = self._ready, []
        t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout=5.0)
        for _rid, _spec, worker in ready:
            worker.reap()


# ------------------------------------------------------------ orchestrator
class Autoscaler:
    """Close the loop: policy decisions -> supervisor/router actuation.

    Ticked by Router.step (set `router.autoscaler = this`) right after
    the SLO pass. Owns the replica-id counter — slot ids are stable and
    monotonically increasing across scale events, pool pre-assignments
    included — and the drain ledger that retires a shrunk handle from
    the router once its process is gone."""

    def __init__(self, router, supervisor: Supervisor,
                 base_spec: WorkerSpec, *,
                 config: AutoscalerConfig = AutoscalerConfig(),
                 clock=None,
                 pool: Optional[StandbyPool] = None,
                 tracer=None, sinks=None,
                 handle_factory: Optional[Callable] = None,
                 heartbeat_timeout_s: float = 2.0,
                 spawn_fn: Optional[Callable] = None,
                 spawn_in_thread: bool = True) -> None:
        self.router = router
        self.supervisor = supervisor
        self.base_spec = base_spec
        self.config = config
        self.clock = clock or getattr(router, "clock", None) \
            or MonotonicClock()
        self.policy = AutoscalerPolicy(config)
        # scale events belong on the same timeline as the dispatches
        # they reshape: default to the router's recorder
        self.tracer = tracer if tracer is not None \
            else getattr(router, "tracer", None)
        self.sinks = sinks
        self.heartbeat_timeout_s = heartbeat_timeout_s
        self._handle_factory = handle_factory or self._default_handle
        self._next_replica = len(supervisor.specs)
        self.pool = pool or StandbyPool(
            self._spec_for, spawn_fn=spawn_fn,
            spawn_in_thread=spawn_in_thread,
        )
        self.events: List[dict] = []     # actuated scale events
        self._draining: Dict[int, object] = {}   # slot -> handle
        self.drain_log: List[dict] = []
        # one row per POLICY EVALUATION (post-throttle): the signal the
        # control law actually saw, so a bench or an operator can place
        # every scale event against the pressure trace that caused it
        self.pressure_log: List[dict] = []
        self.last_join_s: Optional[float] = None
        for _ in range(config.standby_target):
            self.pool.provision(self._alloc_rid())

    # ------------------------------------------------------------ plumbing
    def _alloc_rid(self) -> int:
        rid = self._next_replica
        self._next_replica += 1
        return rid

    def _spec_for(self, rid: int) -> WorkerSpec:
        return dataclasses.replace(self.base_spec, replica=rid)

    def _default_handle(self, slot: int, spec: WorkerSpec):
        return RemoteReplicaHandle(
            slot, self.supervisor, spec, clock=self.clock,
            heartbeat_timeout_s=self.heartbeat_timeout_s,
            trace_collector=getattr(self.router, "trace_collector",
                                    None),
        )

    def _pressure(self) -> float:
        """Demand per decode slot over the dispatchable fleet — the
        same signal Router._update_brownout reads, except `load` (which
        counts submits still in flight to a replica synchronously)
        stands in for the heartbeat-lagged queue+active pair, and
        draining replicas count for neither work nor capacity."""
        slots = 0
        work = 0.0
        for h in self.router.handles:
            if not h.health.alive:
                continue
            if getattr(h, "_drain_requested", False):
                continue
            slots += h.max_slots
            work += h.load
        return (work / slots) if slots else float("inf")

    def _emit(self, now: float, event: dict) -> None:
        self.events.append(event)
        metrics = getattr(self.router, "metrics", None)
        if metrics is not None:
            metrics.on_scale_event(event["direction"], event["trigger"])
        if self.tracer is not None and self.tracer.enabled:
            attrs = {k: v for k, v in event.items() if k != "t"}
            self.tracer.instant(
                f"scale_{event['direction']}", pid=ROUTER_PID, **attrs
            )
        if self.sinks is not None:
            self.sinks.send(dict(event, kind="alert", t=now,
                                 scope="autoscale",
                                 event=f"scale_{event['direction']}"))

    # ---------------------------------------------------------------- tick
    def step(self, now: Optional[float] = None) -> Optional[dict]:
        now = self.clock.now() if now is None else now
        self._retire_drained(now)
        size = self.supervisor.active_slots()
        slo = getattr(self.router, "slo", None)
        if slo is not None:
            sig = slo.burn_signal()
            slo_active = bool(sig["active"])
            slo_resolved = bool(sig["resolved"])
        else:
            slo_active, slo_resolved = False, True
        pressure = self._pressure()
        decision = self.policy.step(
            now, size=size, pressure=pressure,
            slo_active=slo_active, slo_resolved=slo_resolved,
            standby_ready=self.pool.ready_count,
        )
        if self.policy._last_eval == now:
            # the policy evaluated (not throttled) this tick
            self.pressure_log.append(
                {"t": now, "size": size, "pressure": pressure}
            )
            if len(self.pressure_log) > 4096:
                del self.pressure_log[:2048]
        event = None
        if decision is not None:
            if decision["direction"] == "up":
                event = self._grow(now, decision)
            else:
                event = self._scale_down(now, decision)
        metrics = getattr(self.router, "metrics", None)
        if metrics is not None:
            metrics.fleet_size.set(self.supervisor.active_slots())
            metrics.standby_ready.set(self.pool.ready_count)
        return event

    # ------------------------------------------------------------ actuate
    def _grow(self, now: float, decision: dict) -> dict:
        t0 = self.clock.now()
        item = self.pool.take()
        if item is not None:
            rid, spec, worker = item
            slot = self.supervisor.grow(spec, worker=worker)
            warm = True
        else:
            # pool empty (burst outran replenishment): fall back to the
            # cold spawn pipeline — the slot joins BACKOFF due now and
            # the supervisor's spawn thread brings it up (~15 s); the
            # handle joins dead and the router's probe path admits it
            # when the process answers. spec.replica may differ from
            # the slot index here (pool pre-assignments are already
            # minted); that is label cosmetics — slot ids stay stable.
            spec = self._spec_for(self._alloc_rid())
            slot = self.supervisor.grow(spec)
            warm = False
        handle = self._handle_factory(slot, spec)
        collector = getattr(self.router, "trace_collector", None)
        if collector is not None:
            collector.label_worker(slot, spec.engine.get("max_slots", 4))
        self.router.add_handle(handle)
        if warm:
            # promotion = probe + dispatch join, milliseconds: the ~15 s
            # import+warm already happened in the pool's background
            handle.probe_ok(now)
            if collector is not None:
                handle.measure_clock()
        join_s = max(0.0, self.clock.now() - t0)
        self.last_join_s = join_s
        # replenish BEHIND the promotion, never in front of it
        self.pool.provision(self._alloc_rid())
        event = dict(decision, slot=slot, warm=warm,
                     join_s=round(join_s, 6),
                     size=self.supervisor.active_slots())
        self._emit(now, event)
        return event

    def _scale_down(self, now: float, decision: dict) -> Optional[dict]:
        candidates = [
            h for h in self.router.handles
            if h.id < len(self.supervisor.specs)
            and self.supervisor.state(h.id) == RUNNING
            and not getattr(h, "_drain_requested", False)
        ]
        if not candidates:
            return None
        victim = max(candidates, key=lambda h: h.id)   # newest leaves
        victim.begin_drain()          # dispatch stops offering it NOW
        self.supervisor.shrink(victim.id)   # rpc drain + SIGTERM
        self._draining[victim.id] = victim
        event = dict(decision, slot=victim.id,
                     size=self.supervisor.active_slots())
        self._emit(now, event)
        return event

    def _retire_drained(self, now: float) -> None:
        """Reap the ledger: once a draining slot's process is gone
        (clean exit or chaos SIGKILL — the supervisor retires both to
        STOPPED without a budget charge), pull its handle out of the
        router. remove_handle flushes + salvages anything left, so a
        drain cut short mid-stream still fails over exactly-once.
        Retirement also re-homes the slot's sticky prefix families:
        remove_handle drops its digest view, and the router's
        rendezvous placement over the SURVIVING ids deterministically
        re-assigns each family — no ledger of families is kept, the
        hash ring IS the ledger."""
        for slot, handle in list(self._draining.items()):
            if self.supervisor.state(slot) != STOPPED:
                continue
            self.router.remove_handle(handle)
            del self._draining[slot]
            self.drain_log.append({"t": now, "slot": slot})
            if self.tracer is not None and self.tracer.enabled:
                self.tracer.instant("scale_down_done", pid=ROUTER_PID,
                                    slot=slot)

    # ----------------------------------------------------------- introspect
    def snapshot(self) -> dict:
        """The /healthz + tools/check_fleet.py state block."""
        return {
            "size": self.supervisor.active_slots(),
            "min": self.config.min_size,
            "max": self.config.max_size,
            "standby_ready": self.pool.ready_count,
            "standby_target": self.config.standby_target,
            "draining": sorted(self._draining),
            "events_total": len(self.events),
            "last_event": dict(self.events[-1]) if self.events else None,
            "last_join_s": self.last_join_s,
        }

    def close(self) -> None:
        self.pool.close()
