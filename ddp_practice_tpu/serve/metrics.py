"""Serving observability: TTFT / TPOT / queue depth / occupancy / tok/s.

A thin adapter between the scheduler's lifecycle hooks and the generic
registry (utils/metrics.py). The scheduler calls `on_submit` /
`on_tick` / `on_complete`; this class names the metrics and decides
what is a counter vs a gauge vs a distribution:

- ``serve_ttft_s`` (histogram): arrival -> first generated token, the
  user-perceived responsiveness number continuous batching exists to
  protect (a queued request's clock runs while it waits);
- ``serve_tpot_s`` (histogram): mean inter-token latency after the
  first token — the streaming smoothness number;
- ``serve_queue_depth`` / ``serve_slot_occupancy`` (gauges): the two
  saturation signals (queue growing = shed soon; occupancy < 1 with a
  queue = admission is the bottleneck);
- ``serve_tokens_total`` and per-status request counters.

`report(elapsed_s)` folds in tokens/sec; `emit()` logs one JSON line
through the process-0 gate (utils/logging.emit_metrics) so multi-host
replicas don't duplicate metric lines.
"""

from __future__ import annotations

from typing import Optional

from ddp_practice_tpu.utils.logging import emit_metrics
from ddp_practice_tpu.utils.metrics import MetricsRegistry


class ServeMetrics:
    def __init__(self, registry: Optional[MetricsRegistry] = None) -> None:
        self.registry = registry or MetricsRegistry()
        r = self.registry
        self.ttft = r.histogram("serve_ttft_s")
        self.tpot = r.histogram("serve_tpot_s")
        self.queue_depth = r.gauge("serve_queue_depth")
        self.slot_occupancy = r.gauge("serve_slot_occupancy")
        self.tokens_total = r.counter("serve_tokens_total")
        self.submitted = r.counter("serve_requests_submitted")

    # scheduler hooks ------------------------------------------------------
    def on_submit(self, scheduler) -> None:
        self.submitted.inc()
        self.queue_depth.set(len(scheduler.queue))

    def on_tick(self, scheduler) -> None:
        self.queue_depth.set(len(scheduler.queue))
        eng = scheduler.engine
        self.slot_occupancy.set(eng.num_active / eng.allocator.max_slots)

    def on_complete(self, completion, scheduler) -> None:
        self.registry.counter(f"serve_requests_{completion.status}").inc()
        self.tokens_total.inc(len(completion.tokens))
        if completion.ttft is not None:
            self.ttft.observe(completion.ttft)
        if completion.tpot is not None:
            self.tpot.observe(completion.tpot)

    # reporting ------------------------------------------------------------
    def report(self, elapsed_s: Optional[float] = None) -> dict:
        snap = self.registry.snapshot()
        if elapsed_s and elapsed_s > 0:
            snap["serve_tokens_per_sec"] = (
                self.tokens_total.value / elapsed_s
            )
        return snap

    def emit(self, elapsed_s: Optional[float] = None, logger=None):
        """One `metrics {...}` line on process 0 (None elsewhere)."""
        return emit_metrics(self.report(elapsed_s), logger)
