"""Serving observability: TTFT / TPOT / queue depth / occupancy / tok/s.

A thin adapter between the scheduler's lifecycle hooks and the generic
registry (utils/metrics.py). The scheduler calls `on_submit` /
`on_tick` / `on_complete`; this class names the metrics and decides
what is a counter vs a gauge vs a distribution:

- ``serve_ttft_s`` (histogram): arrival -> first generated token, the
  user-perceived responsiveness number continuous batching exists to
  protect (a queued request's clock runs while it waits);
- ``serve_tpot_s`` (histogram): mean inter-token latency after the
  first token — the streaming smoothness number;
- ``serve_queue_depth`` / ``serve_slot_occupancy`` (gauges): the two
  saturation signals (queue growing = shed soon; occupancy < 1 with a
  queue = admission is the bottleneck);
- ``serve_tokens_total`` and per-status request counters.

`report(elapsed_s)` folds in tokens/sec; `emit()` logs one JSON line
through the process-0 gate (utils/logging.emit_metrics) so multi-host
replicas don't duplicate metric lines.
"""

from __future__ import annotations

from typing import Optional

from ddp_practice_tpu.utils.logging import emit_metrics
from ddp_practice_tpu.utils.metrics import MetricsRegistry, labelled


class ServeMetrics:
    def __init__(self, registry: Optional[MetricsRegistry] = None) -> None:
        self.registry = registry or MetricsRegistry()
        r = self.registry
        self.ttft = r.histogram("serve_ttft_s")
        self.tpot = r.histogram("serve_tpot_s")
        self.queue_depth = r.gauge("serve_queue_depth")
        self.slot_occupancy = r.gauge("serve_slot_occupancy")
        # paged-engine pool gauges (serve/kv_pages.py): block occupancy
        # is the paged saturation signal — slots can be free while
        # blocks are the binding constraint (long contexts) and vice
        # versa (many short requests). Stay 0 for the slot engine.
        self.block_occupancy = r.gauge("serve_block_occupancy")
        self.blocks_free = r.gauge("serve_blocks_free")
        # prefix-sharing / preemption observables (PR 6): in-use and
        # SHARED (refcount > 1) block gauges, cumulative prefix-cache
        # hit/miss token counters (proof the radix cache earns its
        # keep), and block-aware preemption count. Exported as deltas
        # from the engine's own cumulative fields each tick, so they
        # ride /metrics and the telemetry JSONL like everything else.
        self.kv_blocks_in_use = r.gauge("kv_blocks_in_use")
        self.kv_blocks_shared = r.gauge("kv_blocks_shared")
        self.prefix_hit_tokens = r.counter("prefix_cache_hit_tokens_total")
        self.prefix_miss_tokens = r.counter("prefix_cache_miss_tokens_total")
        self.preemptions = r.counter("preemptions_total")
        self._last_hit = self._last_miss = self._last_preempt = 0
        # speculative decoding observables (serve/spec.py): drafted vs
        # accepted token counters, exported as deltas from the engine's
        # cumulative fields each tick. The fleet-wide accept rate is
        # accepted/drafted over any scrape window; per-request accept
        # rates live in flight records, not here.
        self.spec_drafted = r.counter("spec_drafted_tokens_total")
        self.spec_accepted = r.counter("spec_accepted_tokens_total")
        self._last_drafted = self._last_accepted = 0
        self.tokens_total = r.counter("serve_tokens_total")
        self.submitted = r.counter("serve_requests_submitted")

    # scheduler hooks ------------------------------------------------------
    def on_submit(self, scheduler) -> None:
        self.submitted.inc()
        self.queue_depth.set(len(scheduler.queue))

    def on_tick(self, scheduler) -> None:
        self.queue_depth.set(len(scheduler.queue))
        eng = scheduler.engine
        self.slot_occupancy.set(eng.num_active / eng.allocator.max_slots)
        blocks = getattr(eng, "blocks", None)  # PagedEngine only
        if blocks is not None:
            # blocks_available counts free + prefix-cache-evictable —
            # what admission actually gates on; a gauge built from the
            # raw free list would show a "full" pool whose cached
            # prefixes are one make_room away from being promisable
            allocatable = blocks.num_blocks - 1  # minus the garbage block
            available = eng.blocks_available
            self.block_occupancy.set(
                (allocatable - available) / allocatable
            )
            self.blocks_free.set(available)
            self.kv_blocks_in_use.set(blocks.num_used)
            self.kv_blocks_shared.set(blocks.num_shared)
            preempt = getattr(eng, "preemptions", 0)
            self.preemptions.inc(preempt - self._last_preempt)
            self._last_preempt = preempt
            radix = getattr(eng, "radix", None)
            if radix is not None:
                self.prefix_hit_tokens.inc(
                    radix.hit_tokens - self._last_hit
                )
                self.prefix_miss_tokens.inc(
                    radix.miss_tokens - self._last_miss
                )
                self._last_hit = radix.hit_tokens
                self._last_miss = radix.miss_tokens
        drafted = getattr(eng, "spec_drafted_tokens", 0)
        accepted = getattr(eng, "spec_accepted_tokens", 0)
        self.spec_drafted.inc(drafted - self._last_drafted)
        self.spec_accepted.inc(accepted - self._last_accepted)
        self._last_drafted = drafted
        self._last_accepted = accepted

    def on_complete(self, completion, scheduler) -> None:
        self.registry.counter(f"serve_requests_{completion.status}").inc()
        self.tokens_total.inc(len(completion.tokens))
        tenant = getattr(completion, "tenant", None)
        if tenant is not None:
            # per-tenant attribution, behind the labelled() cardinality
            # guard: past the per-label limit an adversarial flood of
            # tenant ids lands in tenant="other" instead of growing the
            # registry without bound
            self.registry.counter(labelled(
                "serve_tenant_requests_total",
                tenant=tenant, status=completion.status)).inc()
            self.registry.counter(labelled(
                "serve_tenant_tokens_total", tenant=tenant)).inc(
                    len(completion.tokens))
        # exemplar = the completion's trace_id: the latency histograms
        # in /metrics carry a per-bucket pointer back into the trace
        # timeline (render_text emits OpenMetrics `# {trace_id=...}`).
        # Only KEPT traces may be cited — an exemplar naming a
        # sampling-suppressed trace_id is a dead link by construction.
        ex = (completion.trace_id
              if getattr(completion, "trace_sampled", True) else None)
        if completion.ttft is not None:
            self.ttft.observe(completion.ttft, exemplar=ex)
        if completion.tpot is not None:
            self.tpot.observe(completion.tpot, exemplar=ex)

    # reporting ------------------------------------------------------------
    def report(self, elapsed_s: Optional[float] = None) -> dict:
        snap = self.registry.snapshot()
        if elapsed_s and elapsed_s > 0:
            snap["serve_tokens_per_sec"] = (
                self.tokens_total.value / elapsed_s
            )
        return snap

    def emit(self, elapsed_s: Optional[float] = None, logger=None):
        """One `metrics {...}` line on process 0 (None elsewhere)."""
        return emit_metrics(self.report(elapsed_s), logger)


# health-state gauge encoding (serve_replica_state{replica=i}): a gauge
# is a float, so the states get stable small ints. "removed" is the
# elastic-fleet terminal: a drained slot's gauge parks there instead of
# masquerading as a crash ("dead" pages someone; a scale-down must not)
STATE_CODES = {"healthy": 0.0, "degraded": 1.0, "dead": 2.0,
               "removed": 3.0}


class RouterMetrics:
    """Fleet-level observability for serve/router.py.

    Same registry idiom as ServeMetrics but for the router's concerns:
    retries/failovers (how often the fault machinery earns its keep),
    sheds BY REASON (queue_full vs brownout vs no_replica are three
    different operator actions), per-replica breaker state, and the
    brown-out gauge pair (active flag + the fleet-pressure signal that
    drives it).
    """

    def __init__(self, registry: Optional[MetricsRegistry] = None) -> None:
        self.registry = registry or MetricsRegistry()
        r = self.registry
        self.retries = r.counter("serve_retries_total")
        self.failovers = r.counter("serve_failovers_total")
        self.breaker_trips = r.counter("serve_breaker_trips_total")
        self.brownout_active = r.gauge("serve_brownout_active")
        self.fleet_pressure = r.gauge("serve_fleet_pressure")
        self.tokens_total = r.counter("serve_router_tokens_total")
        self.submitted = r.counter("serve_router_requests_submitted")
        # client-perceived latency ACROSS attempts (the per-replica
        # ServeMetrics only see their own attempt) — exemplar-fed, so
        # the fleet /metrics p99 bucket names an offending trace_id
        self.ttft = r.histogram("serve_router_ttft_s")
        self.tpot = r.histogram("serve_router_tpot_s")
        # elastic-fleet observables (serve/autoscaler.py): current
        # active size, warm standbys ready to promote, and the scale
        # ledger by direction x trigger (slo_burn vs queue_pressure up,
        # slo_resolved down — the labels an operator pivots on)
        self.fleet_size = r.gauge("fleet_size")
        self.standby_ready = r.gauge("standby_ready")

    def on_scale_event(self, direction: str, trigger: str) -> None:
        self.registry.counter(labelled(
            "scale_events_total", direction=direction, trigger=trigger
        )).inc()

    def on_shed(self, reason: str) -> None:
        self.registry.counter(
            labelled("serve_sheds_total", reason=reason)
        ).inc()

    def on_route(self, decision: str) -> None:
        """Dispatch-policy ledger: how often placement was won by cache
        affinity vs the load tiebreak vs the digestless fallback —
        the first thing to pivot on when fleet hit rate drifts."""
        self.registry.counter(labelled(
            "serve_route_decisions_total", decision=decision
        )).inc()

    def on_replica_state(self, replica: int, state: str) -> None:
        self.registry.gauge(
            labelled("serve_replica_state", replica=replica)
        ).set(STATE_CODES[state])

    def on_finalize(self, completion) -> None:
        self.registry.counter(
            f"serve_router_requests_{completion.status}"
        ).inc()
        self.tokens_total.inc(len(completion.tokens))
        # kept-only exemplars, same contract as ServeMetrics.on_complete
        ex = (completion.trace_id
              if getattr(completion, "trace_sampled", True) else None)
        if completion.ttft is not None:
            self.ttft.observe(completion.ttft, exemplar=ex)
        if completion.tpot is not None:
            self.tpot.observe(completion.tpot, exemplar=ex)
        tenant = getattr(completion, "tenant", None)
        if tenant is not None:
            # fleet-level per-tenant attribution: request/token counters
            # and a TTFT histogram per tenant, all behind the labelled()
            # cardinality guard (overflow tenants fold into "other")
            self.registry.counter(labelled(
                "serve_router_tenant_requests_total",
                tenant=tenant, status=completion.status)).inc()
            self.registry.counter(labelled(
                "serve_router_tenant_tokens_total", tenant=tenant)).inc(
                    len(completion.tokens))
            if completion.ttft is not None:
                self.registry.histogram(labelled(
                    "serve_router_tenant_ttft_s", tenant=tenant)).observe(
                        completion.ttft, exemplar=ex)

    def report(self) -> dict:
        return self.registry.snapshot()

    def emit(self, logger=None):
        return emit_metrics(self.report(), logger)


class FrontdoorMetrics:
    """Wire-surface observability for serve/frontdoor.py.

    Everything below the door is already measured (ServeMetrics per
    replica, RouterMetrics per fleet); this layer counts what only the
    door can see — HTTP responses by status code, admission refusals
    by reason, SSE frames shipped, and slow-consumer sheds. The
    generic `count` hook keeps the front door decoupled from metric
    naming: it labels and prefixes so the door just states facts
    ("http code=429", "admission_refused reason=rate").
    """

    def __init__(self, registry: Optional[MetricsRegistry] = None) -> None:
        self.registry = registry or MetricsRegistry()
        r = self.registry
        self.sse_frames = r.counter("frontdoor_sse_frames_total")
        self.slow_consumer_sheds = r.counter(
            "frontdoor_slow_consumer_sheds_total")

    def count(self, what: str, **labels) -> None:
        self.registry.counter(
            labelled(f"frontdoor_{what}_total", **labels)
        ).inc()

    def report(self) -> dict:
        return self.registry.snapshot()

    def emit(self, logger=None):
        return emit_metrics(self.report(), logger)
