"""Prefix-affinity routing: N per-replica radix caches as ONE fleet memory.

The PR-6 radix cache (serve/kv_pages.py) made shared-prefix prefill
cheap *per replica*; the router's least-loaded dispatch then sprayed
each prefix family across every replica, so the fleet paid K x N cache
bytes for K prefixes and the per-replica hit rate collapsed as the
fleet grew. This module closes the loop, SGLang-style (RadixAttention's
cache-aware scheduling) with the vLLM paged block as the unit of reuse:

* `DigestPublisher` — worker side. A compact fingerprint of the warm
  radix tree: one 64-bit rolling hash per cached block-aligned prefix
  (node hash extends its parent's, so a depth-d entry names the whole
  d-block prefix, not one chunk). Depth-capped, size-bounded (MRU), and
  DELTA-encoded against the last emitted frame so steady-state
  heartbeats carry a handful of ints, with a periodic full frame as the
  resync path for receivers that missed deltas. Rides the `_kv_summary`
  heartbeat payload and the poll/push frames.
* `DigestView` — receiver side. Applies frames idempotently (same
  version = no-op, base mismatch = stale-until-next-full, epoch change
  = restart detected, state dropped). A stale or cold view is simply
  unusable for scoring — it can cost a cache MISS, never correctness,
  because routing is a hint and the worker's own radix match is the
  ground truth.
* `AffinityPolicy` — the router's pluggable dispatch scorer. Hashes the
  incoming prompt's block-aligned prefixes the same way, scores every
  candidate by expected matched tokens from its digest, and dispatches
  by the blended score `affinity_tokens - load_penalty * load`, with an
  imbalance cap so a hot family can never starve a replica, rendezvous
  (HRW) placement for first-seen families (sticky across autoscaler
  grow/shrink: membership changes move only the families that hash to
  the changed replica), and clean fallback to the least-loaded order
  when digests are absent or cold.
* `LeastLoadedPolicy` — the PR-2 order behind the same seam: HEALTHY
  before DEGRADED, then least-loaded, then stable id. The control arm
  of `fleet_bench --cache-aware`, and the Router default when
  `RouterConfig.cache_aware` is off.

Everything here is host-pure (no jax), deterministic, and wire-safe:
digests are plain ints/lists in JSON frames.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence, Tuple

from ddp_practice_tpu.serve.health import HealthState

# FNV-1a, 64-bit: stable across processes (unlike Python's salted
# hash()), cheap, and EXTENDABLE — hashing chunk c from parent state h
# yields the hash of the concatenated prefix, which is exactly what a
# radix path is.
_FNV_OFFSET = 0xCBF29CE484222325
_FNV_PRIME = 0x100000001B3
_MASK = (1 << 64) - 1

# digest shape bounds (wire-size control, not correctness): depth-cap
# the tree walk — past ~32 blocks the marginal prefix is this request's
# private tail, not a shared family — and MRU-bound the entry count.
DIGEST_MAX_DEPTH = 32
DIGEST_MAX_ENTRIES = 512
# a full (non-delta) frame at least every N frame() calls: the resync
# beat for receivers whose delta chain broke (missed heartbeat, late
# join). Worst-case cold time is N heartbeats, then exact again.
DIGEST_FULL_EVERY = 8

_epoch_counter = 0


def hash_extend(parent: int, chunk: Sequence[int]) -> int:
    """Roll `chunk`'s tokens into `parent`'s hash state. The radix
    invariant: hash of a depth-d node = hash_extend applied d times
    down the path, so worker (tree walk) and router (prompt walk)
    compute identical names for identical block-aligned prefixes."""
    h = parent
    for t in chunk:
        h ^= int(t) & _MASK
        h = (h * _FNV_PRIME) & _MASK
    return h


def prompt_prefix_hashes(prompt: Sequence[int], block_size: int,
                         max_depth: int = DIGEST_MAX_DEPTH) -> List[int]:
    """Rolling hashes of `prompt`'s block-aligned prefixes, shallowest
    first: out[d] names prompt[:(d+1)*block_size]. Matches what
    DigestPublisher publishes for a radix path of the same tokens."""
    out: List[int] = []
    h = _FNV_OFFSET
    bs = int(block_size)
    if bs <= 0:
        return out
    for d in range(min(max_depth, len(prompt) // bs)):
        h = hash_extend(h, prompt[d * bs:(d + 1) * bs])
        out.append(h)
    return out


def rendezvous_pick(family: int, ids: Sequence[int]) -> Optional[int]:
    """Highest-random-weight (rendezvous) choice of replica id for a
    prefix family: max over mix(family, id). Stable under membership
    churn — adding a replica moves only the families that now hash
    highest on it; removing one re-homes exactly its own families."""
    best = None
    best_w = -1
    for i in ids:
        w = hash_extend(family, (0x9E3779B9, int(i)))
        if w > best_w or (w == best_w and (best is None or i < best)):
            best, best_w = i, w
    return best


# --------------------------------------------------------------- publisher
class DigestPublisher:
    """Worker-side digest of a RadixPrefixCache, delta-encoded frames.

    `frame()` is cheap to call per heartbeat: the tree is re-walked only
    when `radix.edit_seq` moved (insert/evict structural edges), and the
    version bumps only when the bounded hash set actually changed.
    Frames are self-describing: `{"v", "epoch", "bs", "n"}` plus either
    `"full": [hashes]` or `"base", "adds", "dels"` (the delta from
    version v-1). `epoch` names this publisher incarnation — a worker
    restart starts a fresh tree AND a fresh epoch, so a receiver can
    never blend two lifetimes into one view."""

    def __init__(self, radix, *, max_depth: int = DIGEST_MAX_DEPTH,
                 max_entries: int = DIGEST_MAX_ENTRIES,
                 full_every: int = DIGEST_FULL_EVERY) -> None:
        global _epoch_counter
        _epoch_counter += 1
        self.radix = radix
        self.max_depth = max_depth
        self.max_entries = max_entries
        self.full_every = max(1, full_every)
        self.epoch = f"{os.getpid()}.{_epoch_counter}"
        self._set: frozenset = frozenset()
        self._version = 0
        self._adds: List[int] = []
        self._dels: List[int] = []
        self._last_edit: Optional[int] = None
        self._calls_since_full = 0
        self._sent_full = False

    def _build(self) -> frozenset:
        """Walk the tree (depth-capped), rolling each node's hash off
        its parent's; MRU-bound the result by LRU stamp so a huge warm
        cache publishes its HOT families, not its history."""
        radix = self.radix
        out: Dict[int, int] = {}
        root = radix._root
        stack: List[Tuple[object, int, int]] = [
            (child, _FNV_OFFSET, 1) for child in root.children.values()
        ]
        while stack:
            node, parent_h, depth = stack.pop()
            h = hash_extend(parent_h, node.tokens)
            last = out.get(h)
            if last is None or node.last_use > last:
                out[h] = node.last_use
            if depth < self.max_depth:
                for child in node.children.values():
                    stack.append((child, h, depth + 1))
        if len(out) > self.max_entries:
            keep = sorted(out.items(), key=lambda kv: -kv[1])
            out = dict(keep[:self.max_entries])
        return frozenset(out)

    def frame(self) -> dict:
        edit = getattr(self.radix, "edit_seq", None)
        if edit is None or edit != self._last_edit:
            cur = self._build()
            self._last_edit = edit
            if cur != self._set:
                self._version += 1
                self._adds = sorted(cur - self._set)
                self._dels = sorted(self._set - cur)
                self._set = cur
        base = {"v": self._version, "epoch": self.epoch,
                "bs": self.radix.block_size, "n": len(self._set)}
        self._calls_since_full += 1
        if (not self._sent_full
                or self._calls_since_full >= self.full_every):
            self._calls_since_full = 0
            self._sent_full = True
            base["full"] = sorted(self._set)
            return base
        base["base"] = self._version - 1
        base["adds"] = self._adds
        base["dels"] = self._dels
        return base


# ------------------------------------------------------------------ view
class DigestView:
    """Receiver-side digest state for ONE replica, fed by frames.

    Apply rules (in order): a None frame or epoch change resets; a
    frame at our version is a freshness touch; a full frame replaces;
    a delta whose base is our version applies; anything else marks the
    view STALE until the next full frame. Stale/cold views simply drop
    out of scoring — the documented failure mode is a cache miss."""

    def __init__(self) -> None:
        self.hashes: set = set()
        self.version: Optional[int] = None
        self.epoch: Optional[str] = None
        self.block_size: Optional[int] = None
        self.updated_at: Optional[float] = None
        self.stale = True

    def reset(self) -> None:
        self.hashes = set()
        self.version = None
        self.epoch = None
        self.block_size = None
        self.updated_at = None
        self.stale = True

    def apply(self, frame: Optional[dict], now: float) -> None:
        if not frame:
            self.reset()
            return
        epoch = frame.get("epoch")
        if epoch != self.epoch:
            # a new publisher incarnation (worker restart): the old
            # hashes describe a tree that no longer exists
            self.reset()
            self.epoch = epoch
        v = frame.get("v")
        self.block_size = frame.get("bs", self.block_size)
        if "full" in frame:
            self.hashes = set(frame["full"])
            self.version = v
            self.stale = False
            self.updated_at = now
        elif v == self.version and self.version is not None:
            self.updated_at = now  # unchanged re-emit: still fresh
        elif (self.version is not None
                and frame.get("base") == self.version):
            self.hashes.difference_update(frame.get("dels", ()))
            self.hashes.update(frame.get("adds", ()))
            self.version = v
            self.stale = False
            self.updated_at = now
        else:
            # broke the delta chain (missed frames / joined mid-stream):
            # unusable until the publisher's periodic full frame
            self.stale = True

    def usable(self, now: float, max_age_s: float) -> bool:
        return (not self.stale and self.block_size
                and self.updated_at is not None
                and now - self.updated_at <= max_age_s)

    def expected_hit_tokens(self, hashes: Sequence[int]) -> int:
        """Deepest published prefix level matched by the prompt's
        rolling hashes, in TOKENS. The walk stops at the first gap —
        radix paths are prefix-closed, so a missing level means deeper
        entries (hash collisions aside) belong to other families."""
        if not self.hashes or self.block_size is None:
            return 0
        depth = 0
        for h in hashes:
            if h not in self.hashes:
                break
            depth += 1
        return depth * self.block_size


# -------------------------------------------------------------- policies
def least_loaded_key(h):
    """The PR-2 inline sort key: HEALTHY before DEGRADED, then
    least-loaded, then stable id."""
    return (h.health.state is HealthState.DEGRADED, h.load, h.id)


class LeastLoadedPolicy:
    """The pre-affinity dispatch order behind the pluggable seam.
    `order()` returns (candidates in preference order, decision per
    handle id, expected-hit-tokens per handle id)."""

    def order(self, cands: list, prompt: Sequence[int],
              now: float) -> Tuple[list, Dict[int, str], Dict[int, int]]:
        ordered = sorted(cands, key=least_loaded_key)
        return ordered, {h.id: "fallback" for h in ordered}, {}

    def forget(self, replica_id: int) -> None:
        pass


class AffinityPolicy:
    """Cache-aware dispatch: blended affinity/load score over digests.

    Per candidate: expected matched tokens from its DigestView minus
    `load_penalty` tokens per unit of load. The best blended score wins
    — UNLESS its load exceeds the fleet minimum by more than
    `imbalance_cap` requests, in which case load wins outright (a hot
    family can never starve a replica). First-seen families (digests
    warm, prompt unknown) go to their rendezvous home so the cache
    warms where future traffic will land. No usable digest anywhere =
    the least-loaded order, byte-for-byte."""

    def __init__(self, *, load_penalty: float = 32.0,
                 imbalance_cap: float = 4.0,
                 max_age_s: float = 10.0,
                 max_depth: int = DIGEST_MAX_DEPTH) -> None:
        self.load_penalty = load_penalty
        self.imbalance_cap = imbalance_cap
        self.max_age_s = max_age_s
        self.max_depth = max_depth
        self.views: Dict[int, DigestView] = {}

    def forget(self, replica_id: int) -> None:
        """Invalidate one replica's digest (router kill / restart /
        retirement): its next full frame rebuilds the view; until then
        it scores 0 — a miss at worst, never a wrong answer."""
        self.views.pop(replica_id, None)

    def order(self, cands: list, prompt: Sequence[int],
              now: float) -> Tuple[list, Dict[int, str], Dict[int, int]]:
        fallback = sorted(cands, key=least_loaded_key)
        usable: Dict[int, DigestView] = {}
        for h in cands:
            kv = getattr(h, "kv_summary", None)
            frame = kv.get("digest") if isinstance(kv, dict) else None
            view = self.views.setdefault(h.id, DigestView())
            view.apply(frame, now)
            if view.usable(now, self.max_age_s):
                usable[h.id] = view
        if not usable:
            # digests absent or cold everywhere: exactly the old order
            return fallback, {h.id: "fallback" for h in cands}, {}
        # per-candidate expected hit, hashing the prompt once per
        # distinct block size (fleets are homogeneous in practice)
        hashes_by_bs: Dict[int, List[int]] = {}
        exp: Dict[int, int] = {}
        for h in cands:
            view = usable.get(h.id)
            if view is None:
                exp[h.id] = 0
                continue
            bs = int(view.block_size)
            if bs not in hashes_by_bs:
                hashes_by_bs[bs] = prompt_prefix_hashes(
                    prompt, bs, self.max_depth)
            exp[h.id] = view.expected_hit_tokens(hashes_by_bs[bs])
        loads = {h.id: h.load for h in cands}
        min_load = min(loads.values())
        # DEGRADED replicas keep their back-of-the-line seat: score
        # only the healthy pool unless nothing healthy remains
        pool = [h for h in cands
                if h.health.state is not HealthState.DEGRADED] or cands
        winner = max(pool, key=lambda h: (
            exp[h.id] - self.load_penalty * loads[h.id],
            -loads[h.id], -h.id,
        ))
        decision = "affinity"
        if exp[winner.id] <= 0:
            # nobody has this family warm: sticky rendezvous placement
            # so repeats land where THIS one warms the cache
            any_bs = next(iter(hashes_by_bs), None)
            family_hashes = hashes_by_bs.get(any_bs, [])
            if not family_hashes:
                # prompt shorter than one block: nothing to be sticky
                # about, and nothing to cache — load decides
                return fallback, {h.id: "load" for h in cands}, exp
            home = rendezvous_pick(family_hashes[0],
                                   sorted(h.id for h in pool))
            winner = next(h for h in pool if h.id == home)
        if loads[winner.id] - min_load > self.imbalance_cap:
            # the imbalance cap: a warm-but-swamped replica loses to
            # the least-loaded order (the family re-warms elsewhere)
            return fallback, {h.id: "load" for h in cands}, exp
        decisions = {h.id: "load" for h in cands}
        decisions[winner.id] = decision
        ordered = [winner] + [h for h in fallback if h is not winner]
        return ordered, decisions, exp


# ------------------------------------------------------------ kv summary
def kv_summary(engine, publisher: Optional[DigestPublisher] = None) -> dict:
    """The KV/radix occupancy dict riding every heartbeat (and, via the
    in-process handle, every dispatch): blocks in use/shared, hit/miss
    token counters, and — when a publisher is attached — the prefix
    digest frame cache-aware routing scores against. ONE builder for
    the worker and the in-process handle, so the Router sees identical
    shapes on both sides of the RPC seam. Zeros for a slot engine (no
    paged pool), matching ServeMetrics.on_tick's getattr guards."""
    blocks = getattr(engine, "blocks", None)
    radix = getattr(engine, "radix", None)
    hit = getattr(radix, "hit_tokens", 0) if radix is not None else 0
    miss = getattr(radix, "miss_tokens", 0) if radix is not None else 0
    out = {
        "blocks_used": blocks.num_used if blocks is not None else 0,
        "blocks_shared": blocks.num_shared if blocks is not None else 0,
        # minus the garbage block, same accounting as the gauges
        "blocks_total": (blocks.num_blocks - 1
                         if blocks is not None else 0),
        "evictable": radix.evictable() if radix is not None else 0,
        "hit_tokens": hit,
        "miss_tokens": miss,
        "prefix_hit_rate": hit / (hit + miss) if hit + miss else 0.0,
    }
    if radix is not None:
        out["block_size"] = radix.block_size
        if publisher is not None:
            out["digest"] = publisher.frame()
    return out
