"""Weighted-fair service accounting + per-tenant cost metering.

The tenant label rides every seam (scheduler -> router -> RPC ->
worker), the admission layer can refuse one, and the SLO plane can now
burn one's budget — but none of those answers the operational question
a shared fleet actually poses: WHO is consuming the capacity, and is
the split fair? This module holds the two ledgers that answer it:

- **VirtualTokenCounter** — VTC-style weighted service accounting
  ("Fairness in Serving Large Language Models", OSDI'24): each tenant
  accrues ``decode_tokens + prefill_weight * prefill_tokens`` of
  service, normalized by its configured weight. Prefill is discounted
  because a prefill token costs one parallel pass over the prompt while
  a decode token costs a full serial step — charging them equally would
  let a chatty short-prompt tenant starve a long-prompt one. The
  counters drive BOTH enforcement points: the scheduler picks the
  least-served tenant's head when slots free up, and the admission
  layer refuses the most-over-served tenant first under pressure. A
  tenant arriving late (or idle long enough to be forgotten) registers
  at the current FLOOR (the minimum live counter), per the VTC paper:
  absence must not bank unbounded credit it can spend as a burst that
  starves everyone who stayed.
- **TenantLedger** — per-tenant cost metering folded from completion
  flight records: queue/prefill/decode/stall seconds, prompt + output +
  prefix-hit tokens, terminal statuses, and rolling TTFT/TPOT windows
  summarized through the shared ``percentile_summary``. `report()` is
  the ``/tenants`` endpoint body; like ``FlightStats.report`` it ships
  raw sample tails so ``ScrapeFederator.tenants()`` can pool them and
  recompute TRUE fleet percentiles (a percentile of per-worker
  percentiles would be a different, wrong number).

Fairness is summarized as Jain's index over the per-tenant weighted
service totals: 1.0 = perfectly even, 1/n = one tenant took everything.
Exported as the ``tenant_fairness_index`` gauge and paged on by
tools/check_fleet.py ``--min-fairness``.

Host-pure and lock-guarded (the serve loop writes, the HTTP scrape
thread reads); nothing here imports jax or owns a thread.
"""

from __future__ import annotations

from collections import deque
from typing import Dict, Iterable, List, Optional

import threading

from ddp_practice_tpu.utils.metrics import labelled, percentile_summary

# the display/accounting name for requests that carry no tenant label —
# shared with the SLO registry so "the unlabeled tenant" is one tenant
# everywhere, not a None that each consumer renders differently
DEFAULT_TENANT = "default"


def tenant_name(tenant: Optional[str]) -> str:
    return tenant if tenant is not None else DEFAULT_TENANT


def jains_index(values: Iterable[float]) -> float:
    """Jain's fairness index: (sum x)^2 / (n * sum x^2) in (0, 1].

    1.0 when every tenant received equal (weighted) service, 1/n when
    one tenant took everything. Empty or all-zero input is vacuously
    fair (nobody was served, nobody was starved) -> 1.0.
    """
    vals = [float(v) for v in values]
    if not vals:
        return 1.0
    total = sum(vals)
    sq = sum(v * v for v in vals)
    if sq <= 0.0:
        return 1.0
    return (total * total) / (len(vals) * sq)


class VirtualTokenCounter:
    """Per-tenant weighted service counters (the VTC of OSDI'24).

    `charge()` accrues service; `least_served` / `most_over_served`
    are the two enforcement queries (dispatch picks the former's work,
    admission refuses the latter's under pressure). Ties break on the
    tenant name so replays are deterministic regardless of dict order.
    """

    def __init__(self, *, prefill_weight: float = 0.5,
                 weights: Optional[Dict[str, float]] = None) -> None:
        if prefill_weight < 0:
            raise ValueError("prefill_weight must be >= 0")
        self.prefill_weight = prefill_weight
        # tenant -> relative share weight (default 1.0): a weight-2
        # tenant accrues service at half rate, so fair ordering grants
        # it twice the tokens — paid tiers without a second mechanism
        self.weights = dict(weights or {})
        for name, w in self.weights.items():
            if w <= 0:
                raise ValueError(f"tenant {name!r} weight must be > 0")
        self._lock = threading.Lock()
        self._service: Dict[str, float] = {}

    def _weight(self, name: str) -> float:
        return self.weights.get(name, 1.0)

    def _register(self, name: str) -> None:
        # VTC lift: newcomers start at the current floor, not at zero —
        # an idle hour must not become a service credit that lets one
        # tenant monopolize the fleet until the books "catch up"
        if name not in self._service:
            self._service[name] = min(self._service.values(), default=0.0)

    def touch(self, tenant: Optional[str]) -> None:
        """Register a tenant at the current service floor (first
        sighting — queue intake, admission) without charging it."""
        with self._lock:
            self._register(tenant_name(tenant))

    def charge(self, tenant: Optional[str], *, decode: int = 0,
               prefill: int = 0) -> float:
        """Accrue one attempt's weighted service; returns the tenant's
        new counter. Decode tokens at full price, prefill tokens at
        `prefill_weight` (see module docstring)."""
        name = tenant_name(tenant)
        cost = (float(decode) + self.prefill_weight * float(prefill))
        with self._lock:
            self._register(name)
            self._service[name] += cost / self._weight(name)
            return self._service[name]

    def service(self, tenant: Optional[str]) -> float:
        with self._lock:
            return self._service.get(tenant_name(tenant), 0.0)

    def least_served(self, tenants: Iterable[Optional[str]]
                     ) -> Optional[str]:
        """The candidate tenant with the LOWEST weighted service — the
        one fair dispatch serves next. Returns the name as given
        (None stays None so callers can match raw request labels)."""
        best = None
        best_key = None
        with self._lock:
            for t in tenants:
                key = (self._service.get(tenant_name(t), 0.0),
                       tenant_name(t))
                if best_key is None or key < best_key:
                    best, best_key = t, key
        return best

    def most_over_served(self, tenants: Iterable[Optional[str]]
                         ) -> Optional[str]:
        """The candidate tenant with the HIGHEST weighted service — the
        one fair admission refuses first under pressure."""
        worst = None
        worst_key = None
        with self._lock:
            for t in tenants:
                key = (self._service.get(tenant_name(t), 0.0),
                       tenant_name(t))
                if worst_key is None or key > worst_key:
                    worst, worst_key = t, key
        return worst

    def jain(self) -> float:
        """Jain's index over every registered tenant's service total."""
        with self._lock:
            return jains_index(self._service.values())

    def snapshot(self) -> dict:
        with self._lock:
            service = dict(self._service)
        total = sum(service.values())
        return {
            "service": service,
            "share": {n: (v / total if total > 0 else 0.0)
                      for n, v in service.items()},
            "fairness_index": jains_index(service.values()),
        }


class TenantLedger:
    """Per-tenant cost meters folded from completions.

    One `on_completion` per terminal (the router's `_finalize` / the
    scheduler's `_finish` in standalone use — exactly one of them owns
    the hook per deployment, like the SLO watchdog). Registry export
    uses labelled() so the 64-value cardinality guard bounds a hostile
    tenant-id space to the shared "other" bucket.
    """

    PHASES = ("queue_s", "prefill_s", "decode_s", "stall_s")
    # raw TTFT/TPOT tail shipped per report for fleet federation —
    # same contract as FlightStats.SAMPLES_PER_REPORT
    SAMPLES_PER_REPORT = 256

    def __init__(self, *, registry=None, vtc: Optional[
            VirtualTokenCounter] = None, window: int = 512) -> None:
        self.registry = registry
        self.vtc = vtc
        self.window = window
        self._lock = threading.Lock()
        self._tenants: Dict[str, dict] = {}

    def _entry(self, name: str) -> dict:
        e = self._tenants.get(name)
        if e is None:
            e = {
                "requests": {},
                "prompt_tokens": 0,
                "output_tokens": 0,
                "prefix_hit_tokens": 0,
                "seconds": {ph: 0.0 for ph in self.PHASES},
                "ttft": deque(maxlen=self.window),
                "tpot": deque(maxlen=self.window),
            }
            self._tenants[name] = e
        return e

    def on_completion(self, completion, *, prompt_tokens: int = 0,
                      **_kw) -> None:
        """Fold one terminal completion in. `prompt_tokens` comes from
        the caller when it still holds the request (the router's
        _finalize does); otherwise it falls back to the flight record's
        prompt_tokens stamp (the scheduler's, so a worker-side ledger
        with no request back-pointer still bills prefill)."""
        name = tenant_name(getattr(completion, "tenant", None))
        flight = completion.flight or {}
        if not prompt_tokens:
            prompt_tokens = int(flight.get("prompt_tokens", 0) or 0)
        out_tokens = len(completion.tokens)
        hit = int(flight.get("prefix_hit_tokens", 0) or 0)
        with self._lock:
            e = self._entry(name)
            e["requests"][completion.status] = (
                e["requests"].get(completion.status, 0) + 1
            )
            e["prompt_tokens"] += int(prompt_tokens)
            e["output_tokens"] += out_tokens
            e["prefix_hit_tokens"] += hit
            for ph in self.PHASES:
                e["seconds"][ph] += float(flight.get(ph, 0.0) or 0.0)
            if completion.ttft is not None:
                e["ttft"].append(completion.ttft)
            if completion.tpot is not None:
                e["tpot"].append(completion.tpot)
        reg = self.registry
        if reg is not None:
            reg.counter(labelled("tenant_requests_total", tenant=name,
                                 status=completion.status)).inc()
            if prompt_tokens:
                reg.counter(labelled("tenant_prompt_tokens_total",
                                     tenant=name)).inc(prompt_tokens)
            if out_tokens:
                reg.counter(labelled("tenant_output_tokens_total",
                                     tenant=name)).inc(out_tokens)
            if hit:
                reg.counter(labelled("tenant_prefix_hit_tokens_total",
                                     tenant=name)).inc(hit)
            for ph in self.PHASES:
                v = float(flight.get(ph, 0.0) or 0.0)
                if v > 0:
                    reg.counter(labelled(
                        "tenant_cost_seconds_total", tenant=name,
                        phase=ph)).inc(v)
            if self.vtc is not None:
                reg.gauge("tenant_fairness_index").set(self.vtc.jain())

    def report(self) -> dict:
        """The ``/tenants`` endpoint body: per-tenant counters +
        TTFT/TPOT percentile summaries, service shares from the
        attached VTC, and the fleet-local Jain's index. "samples"
        carries the raw latency tails (ScrapeFederator.tenants pools
        them and recomputes — never percentiles of percentiles)."""
        with self._lock:
            snap = {
                name: {
                    "requests": dict(e["requests"]),
                    "prompt_tokens": e["prompt_tokens"],
                    "output_tokens": e["output_tokens"],
                    "prefix_hit_tokens": e["prefix_hit_tokens"],
                    "seconds": dict(e["seconds"]),
                    "ttft": list(e["ttft"]),
                    "tpot": list(e["tpot"]),
                }
                for name, e in self._tenants.items()
            }
        tenants: Dict[str, dict] = {}
        samples: Dict[str, dict] = {}
        cap = self.SAMPLES_PER_REPORT
        for name, e in sorted(snap.items()):
            ttft, tpot = e.pop("ttft"), e.pop("tpot")
            e["ttft_s"] = percentile_summary(ttft)
            e["tpot_s"] = percentile_summary(tpot)
            tenants[name] = e
            samples[name] = {"ttft_s": ttft[-cap:], "tpot_s": tpot[-cap:]}
        out: dict = {"tenants": tenants, "samples": samples}
        if self.vtc is not None:
            vs = self.vtc.snapshot()
            out["service"] = vs["service"]
            out["share"] = vs["share"]
            out["fairness_index"] = vs["fairness_index"]
        else:
            # no VTC attached (fair mode off): fairness over raw output
            # tokens — metering must not require the enforcement knob
            service = {n: float(e["output_tokens"])
                       for n, e in snap.items()}
            total = sum(service.values())
            out["service"] = service
            out["share"] = {n: (v / total if total > 0 else 0.0)
                            for n, v in service.items()}
            out["fairness_index"] = jains_index(service.values())
        return out


def federate_tenant_reports(reports: List[dict]) -> dict:
    """Fold per-worker ``/tenants`` bodies into one fleet view: sum the
    counters, pool the raw sample tails and recompute percentiles,
    re-derive shares + Jain over the SUMMED service. Shared by
    ScrapeFederator.tenants() (live) and tools/check_fleet.py
    (snapshots) so both quote the same numbers."""
    tenants: Dict[str, dict] = {}
    pooled: Dict[str, Dict[str, list]] = {}
    service: Dict[str, float] = {}
    for rep in reports:
        if not isinstance(rep, dict):
            continue
        for name, e in (rep.get("tenants") or {}).items():
            agg = tenants.setdefault(name, {
                "requests": {}, "prompt_tokens": 0, "output_tokens": 0,
                "prefix_hit_tokens": 0,
                "seconds": {ph: 0.0 for ph in TenantLedger.PHASES},
            })
            for st, n in (e.get("requests") or {}).items():
                agg["requests"][st] = agg["requests"].get(st, 0) + n
            for key in ("prompt_tokens", "output_tokens",
                        "prefix_hit_tokens"):
                agg[key] += int(e.get(key, 0) or 0)
            for ph in TenantLedger.PHASES:
                agg["seconds"][ph] += float(
                    (e.get("seconds") or {}).get(ph, 0.0) or 0.0)
        for name, s in (rep.get("samples") or {}).items():
            pool = pooled.setdefault(name, {"ttft_s": [], "tpot_s": []})
            for key in ("ttft_s", "tpot_s"):
                vals = s.get(key)
                if isinstance(vals, list):
                    pool[key].extend(vals)
        for name, v in (rep.get("service") or {}).items():
            service[name] = service.get(name, 0.0) + float(v)
    for name, agg in tenants.items():
        pool = pooled.get(name, {})
        agg["ttft_s"] = percentile_summary(pool.get("ttft_s", []))
        agg["tpot_s"] = percentile_summary(pool.get("tpot_s", []))
    total = sum(service.values())
    return {
        "tenants": {n: tenants[n] for n in sorted(tenants)},
        "service": service,
        "share": {n: (v / total if total > 0 else 0.0)
                  for n, v in service.items()},
        "fairness_index": jains_index(service.values()),
    }
