"""Declarative SLOs with multi-window burn-rate alerting.

The router's brown-out (serve/router.py) trips on instantaneous fleet
pressure — a PROXY for what actually matters to users: are first tokens
late (TTFT), are streams stuttering (TPOT), are requests erroring, are
answers arriving at all. This module watches the real thing, Google-SRE
style:

- **Everything is a bad-event rate.** Each objective classifies every
  completion as good or bad — TTFT over target, TPOT over target,
  status "error", not-served — and carries an error BUDGET (for a p99
  latency target the budget is 1%: up to 1% of requests may exceed the
  target and the SLO still holds). Burn rate = observed bad fraction /
  budget: 1.0 means consuming budget exactly as fast as allowed,
  10 means ten times too fast.
- **Two windows, asymmetric edges.** An alert TRIPS when burn exceeds
  `trip_burn` in BOTH the fast and the slow window (the fast window
  makes detection quick, the slow window stops a two-request blip from
  paging), and RESOLVES only when the SLOW window's burn falls to
  `resolve_burn` (< trip_burn). Trip fast, resolve slow, and the gap
  between the thresholds is the hysteresis band — no flapping when burn
  hovers at the boundary (pinned in tests/test_slo.py).
- **Clock-injected and host-pure.** Time comes from the same clock the
  scheduler uses, so a FakeClock chaos replay produces bit-identical
  alert timelines; nothing here imports jax.

Alert/resolve edges are emitted three ways so every consumer of the
telemetry plane sees them: tracer instants (``slo_alert`` /
``slo_resolve``, streamed through the TelemetryExporter sink), an
``alert`` JSONL line (kind="alert"), and registry metrics
(``slo_alerts_total``, per-objective ``slo_burn_rate`` /
``slo_alert_active`` gauges). The router consumes `active` as a
brown-out trigger: degradation driven by measured SLO violation, not
just occupancy (serve/router.py _update_brownout).

PUSH delivery (`AlertSinks`): edges additionally fan out to operator
sinks — a command to run, a webhook URL to POST, a JSONL file to append
— because a burning SLO that only lands in a scrape endpoint pages
nobody. Per sink: bounded pending queue, exponential backoff between
delivery retries (utils/backoff.py), and a DEAD-SINK BREAKER — after
`max_failures` consecutive failures the sink is abandoned for good
(``alert_sink_dead`` gauge; a flapping webhook must not hold the serve
loop's alert path hostage forever). `FleetAlerts` federates the same
edges at fleet level: a worker the ScrapeFederator judges dead/stale
raises a trip through the same sinks, its recovery a resolve.

tools/check_slo.py evaluates the same objectives OFFLINE over a
telemetry JSONL (bench artifacts, post-mortems), sharing
`SLOConfig` and the percentile implementation.
"""

from __future__ import annotations

import dataclasses
import json
import os
from collections import deque
from typing import Dict, List, Optional, Tuple

from ddp_practice_tpu.utils.backoff import backoff_delay
from ddp_practice_tpu.utils.metrics import labelled
from ddp_practice_tpu.utils.trace import ROUTER_PID, _resolve_clock

# statuses that count as "served" for the availability objective;
# everything else (timeout/shed/rejected/error) spent the user's
# patience without an answer
OK_STATUSES = ("eos", "length")

# latency objectives are p99-shaped: the budget is the 1% of requests
# allowed over the target
_LATENCY_BUDGET = 0.01


@dataclasses.dataclass(frozen=True)
class SLOConfig:
    """Targets (None = objective off) + window/threshold tuning.

    JSON-round-trippable so `--slo` takes a literal or a file path:
    ``{"ttft_p99_s": 0.5, "error_rate": 0.01, "availability": 0.99}``.
    """

    ttft_p99_s: Optional[float] = None   # p99 TTFT target (seconds)
    tpot_p99_s: Optional[float] = None   # p99 TPOT target (seconds)
    error_rate: Optional[float] = None   # max fraction status=="error"
    availability: Optional[float] = None  # min fraction served ok
    fast_window_s: float = 60.0
    slow_window_s: float = 300.0
    trip_burn: float = 2.0      # both windows >= this trips
    resolve_burn: float = 1.0   # slow window <= this resolves
    min_events: int = 5         # don't alert on fewer fast-window events

    def __post_init__(self):
        if self.slow_window_s < self.fast_window_s:
            raise ValueError("slow_window_s must be >= fast_window_s")
        if self.resolve_burn > self.trip_burn:
            raise ValueError(
                "resolve_burn must be <= trip_burn (the hysteresis band)"
            )

    @classmethod
    def from_json(cls, source) -> "SLOConfig":
        """A dict, a JSON string, or a path to a JSON file."""
        if isinstance(source, cls):
            return source
        if isinstance(source, str):
            stripped = source.strip()
            if stripped.startswith("{"):
                source = json.loads(stripped)
            elif os.path.exists(source):
                with open(source) as f:
                    source = json.load(f)
            else:
                raise ValueError(
                    f"--slo wants a JSON object or an existing file path, "
                    f"got {source!r}"
                )
        if not isinstance(source, dict):
            raise TypeError(f"cannot build SLOConfig from {type(source)}")
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(source) - known
        if unknown:
            raise ValueError(f"unknown SLO config keys: {sorted(unknown)}")
        return cls(**source)

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self))

    def objectives(self) -> Dict[str, float]:
        """Active objectives -> error budget (bad fraction allowed)."""
        out: Dict[str, float] = {}
        if self.ttft_p99_s is not None:
            out["ttft_p99"] = _LATENCY_BUDGET
        if self.tpot_p99_s is not None:
            out["tpot_p99"] = _LATENCY_BUDGET
        if self.error_rate is not None:
            out["error_rate"] = self.error_rate
        if self.availability is not None:
            out["availability"] = 1.0 - self.availability
        if not out:
            raise ValueError("SLO config enables no objective")
        for name, budget in out.items():
            if budget <= 0:
                raise ValueError(
                    f"objective {name} has zero error budget — a single "
                    "bad event would be an infinite burn; relax the target"
                )
        return out


def classify(config: SLOConfig, *, status: str,
             ttft: Optional[float] = None,
             tpot: Optional[float] = None) -> Dict[str, bool]:
    """One event's per-objective bad flags (shared with check_slo.py).

    Latency objectives only judge events that HAVE the measurement
    (a request that never produced a token has no TTFT — its failure is
    the availability objective's business, and double-counting it as a
    latency breach would overstate burn)."""
    flags: Dict[str, bool] = {}
    if config.ttft_p99_s is not None and ttft is not None:
        flags["ttft_p99"] = ttft > config.ttft_p99_s
    if config.tpot_p99_s is not None and tpot is not None:
        flags["tpot_p99"] = tpot > config.tpot_p99_s
    if config.error_rate is not None:
        flags["error_rate"] = status == "error"
    if config.availability is not None:
        flags["availability"] = status not in OK_STATUSES
    return flags


class SLOWatchdog:
    """Rolling-window burn-rate evaluation with per-objective alerts."""

    def __init__(self, config: SLOConfig, *, clock=None,
                 registry=None, tracer=None, telemetry=None,
                 sinks=None, pid: int = ROUTER_PID,
                 tenant: Optional[str] = None) -> None:
        self.config = config
        self.budgets = config.objectives()
        self.tracer = tracer
        self.telemetry = telemetry
        self.registry = registry
        # optional AlertSinks: every trip/resolve edge is also PUSHED
        # (command/webhook/jsonl); evaluate() drives the retry backoff
        self.sinks = sinks
        self.pid = pid
        # tenant-scoped watchdog (TenantSLORegistry member): gauges and
        # edge events carry the tenant label; None keeps the single-
        # watchdog surface byte-identical to before the registry existed
        self.tenant = tenant
        self._labels = {} if tenant is None else {"tenant": tenant}
        # default time source when a caller omits `now`/`t` (the router
        # always passes its own clock reading explicitly — same domain)
        self._now = _resolve_clock(clock)
        # (t, {objective: bad}) — pruned past the slow window
        self._events: deque = deque()
        # evaluation is O(events-in-slow-window) per objective, and the
        # router calls evaluate() every tick: throttle the rescan to 5%
        # of the fast window (detection latency <= interval, cost
        # amortized). Callers that need an immediate verdict (tests,
        # edge-of-window assertions) pass force=True.
        self._eval_interval = config.fast_window_s / 20.0
        self._last_eval: Optional[float] = None
        self._last_report: Dict[str, dict] = {}
        self.alerts: Dict[str, bool] = {o: False for o in self.budgets}
        # (t, "trip"|"resolve", objective) history — tests and reports
        self.alert_log: List[Tuple[float, str, str]] = []
        self._alerts_ctr = (
            registry.counter("slo_alerts_total")
            if registry is not None else None
        )

    # ------------------------------------------------------------ intake
    def observe(self, completion) -> None:
        """Feed one scheduler/router Completion."""
        self.observe_event(
            t=completion.finish, status=completion.status,
            ttft=completion.ttft, tpot=completion.tpot,
        )

    def observe_event(self, *, t: Optional[float] = None,
                      status: str = "eos",
                      ttft: Optional[float] = None,
                      tpot: Optional[float] = None) -> None:
        """Generic event intake — the train loop feeds step outcomes
        through here (an anomalous step is status="error"). `t`
        defaults to the injected clock."""
        flags = classify(self.config, status=status, ttft=ttft, tpot=tpot)
        if flags:
            self._events.append(
                (t if t is not None else self._now(), flags)
            )

    # -------------------------------------------------------- evaluation
    def _window_burn(self, objective: str, now: float,
                     window_s: float) -> Tuple[float, int]:
        """(burn rate, events judged) for one objective over one window."""
        lo = now - window_s
        total = bad = 0
        for t, flags in self._events:
            if t <= lo or objective not in flags:
                continue
            total += 1
            bad += flags[objective]
        if total == 0:
            return 0.0, 0
        return (bad / total) / self.budgets[objective], total

    def evaluate(self, now: Optional[float] = None,
                 force: bool = False) -> Dict[str, dict]:
        """Prune, recompute both windows per objective, walk the alert
        state machine; returns the per-objective burn report. `now`
        defaults to the injected clock. Called more often than the
        throttle interval, it returns the cached report (see
        `_eval_interval`) unless `force`."""
        if now is None:
            now = self._now()
        if (not force and self._last_eval is not None
                and now - self._last_eval < self._eval_interval):
            if self.sinks is not None:
                self.sinks.flush(now)   # retry backoffs ride the tick
            return self._last_report
        self._last_eval = now
        cfg = self.config
        lo = now - cfg.slow_window_s
        while self._events and self._events[0][0] <= lo:
            self._events.popleft()
        report: Dict[str, dict] = {}
        for objective in self.budgets:
            fast, n_fast = self._window_burn(
                objective, now, cfg.fast_window_s)
            slow, n_slow = self._window_burn(
                objective, now, cfg.slow_window_s)
            active = self.alerts[objective]
            if (not active and n_fast >= cfg.min_events
                    and fast >= cfg.trip_burn and slow >= cfg.trip_burn):
                self._edge(objective, "trip", now, fast, slow)
                active = True
            elif active and slow <= cfg.resolve_burn:
                self._edge(objective, "resolve", now, fast, slow)
                active = False
            self.alerts[objective] = active
            report[objective] = {
                "burn_fast": fast, "burn_slow": slow,
                "events_fast": n_fast, "events_slow": n_slow,
                "active": active,
            }
            if self.registry is not None:
                self.registry.gauge(labelled(
                    "slo_burn_rate", objective=objective, window="fast",
                    **self._labels,
                )).set(fast)
                self.registry.gauge(labelled(
                    "slo_burn_rate", objective=objective, window="slow",
                    **self._labels,
                )).set(slow)
                self.registry.gauge(labelled(
                    "slo_alert_active", objective=objective,
                    **self._labels,
                )).set(float(active))
        if self.sinks is not None:
            # both paths flush: a backed-off retry must come due even
            # when every evaluate() lands on the full-evaluation branch
            # (low-rate traffic spaced past the throttle interval)
            self.sinks.flush(now)
        self._last_report = report
        return report

    def _edge(self, objective: str, edge: str, now: float,
              fast: float, slow: float) -> None:
        self.alert_log.append((now, edge, objective))
        if edge == "trip" and self._alerts_ctr is not None:
            self._alerts_ctr.inc()
            if self.tenant is not None and self.registry is not None:
                # per-tenant attribution ALONGSIDE the shared total: a
                # fleet dashboard sums one series, a tenant page reads
                # its own
                self.registry.counter(labelled(
                    "slo_alerts_total", tenant=self.tenant,
                )).inc()
        if self.tracer is not None and self.tracer.enabled:
            self.tracer.instant(
                f"slo_{edge}" if edge == "resolve" else "slo_alert",
                pid=self.pid, objective=objective,
                burn_fast=round(fast, 3), burn_slow=round(slow, 3),
                **self._labels,
            )
        if self.telemetry is not None:
            self.telemetry.emit(
                "alert", event=edge, objective=objective,
                burn_fast=fast, burn_slow=slow, **self._labels,
            )
        if self.sinks is not None:
            self.sinks.send({
                "kind": "alert", "t": now, "scope": "slo",
                "event": edge, "objective": objective,
                "burn_fast": fast, "burn_slow": slow, **self._labels,
            })

    @property
    def active(self) -> bool:
        """Any objective currently alerting — the router's brown-out
        trigger."""
        return any(self.alerts.values())

    def burn_signal(self) -> dict:
        """The autoscaler's compressed view of the last evaluation:
        worst fast/slow burn across objectives, whether anything is
        alerting, and whether the SLOW window has settled under the
        resolve threshold everywhere. Trip fast rides `active`;
        `resolved` is the scale-DOWN precondition — burn that merely
        dipped out of the fast window is not calm, it is noise."""
        rep = self._last_report or {}
        fast = max((o.get("burn_fast", 0.0) for o in rep.values()),
                   default=0.0)
        slow = max((o.get("burn_slow", 0.0) for o in rep.values()),
                   default=0.0)
        return {
            "burn_fast": fast,
            "burn_slow": slow,
            "active": self.active,
            "resolved": (not self.active
                         and slow <= self.config.resolve_burn),
        }


class TenantSLORegistry:
    """Keyed SLO watchdogs: one error budget per tenant.

    A single fleet-wide watchdog averages a hostile tenant's burn into
    everyone's, so the tenant being starved never pages and the tenant
    doing the starving never stands out. This registry gives each
    tenant its own `SLOWatchdog` (lazily, keyed off `Request.tenant`,
    None folding to the shared "default" tenant), each with its own
    windows, alert edges, and ``slo_burn_rate{tenant,objective}``
    gauges.

    It presents the SAME surface the router consumes from a single
    watchdog — `observe` / `evaluate` / `active` / `burn_signal` — so
    `Router(slo=...)` takes either interchangeably, plus the
    tenant-scoped queries the brown-out needs to shed ONLY the burning
    tenant's work: `is_burning(tenant)` and `burning_tenants()`.

    Cardinality is bounded like the metric label guard: past
    `max_tenants` distinct tenants, newcomers share one "other"
    watchdog (an unbounded hostile tenant-id space must not mint
    unbounded deques and gauge families). Per-tenant objective
    overrides ride `overrides` (e.g. a batch tenant with a relaxed
    TTFT target).
    """

    OVERFLOW = "other"
    DEFAULT_TENANT = "default"

    def __init__(self, config: SLOConfig, *, clock=None, registry=None,
                 tracer=None, telemetry=None, sinks=None,
                 pid: int = ROUTER_PID, max_tenants: int = 64,
                 overrides: Optional[Dict[str, SLOConfig]] = None) -> None:
        self.config = SLOConfig.from_json(config)
        self.overrides = {
            name: SLOConfig.from_json(cfg)
            for name, cfg in (overrides or {}).items()
        }
        self.max_tenants = max_tenants
        self._deps = dict(clock=clock, registry=registry, tracer=tracer,
                          telemetry=telemetry, sinks=sinks, pid=pid)
        self._dogs: Dict[str, SLOWatchdog] = {}

    def _name(self, tenant: Optional[str]) -> str:
        return tenant if tenant is not None else self.DEFAULT_TENANT

    def _key(self, tenant: Optional[str]) -> str:
        name = self._name(tenant)
        if name in self._dogs or len(self._dogs) < self.max_tenants:
            return name
        return self.OVERFLOW

    def watchdog(self, tenant: Optional[str]) -> SLOWatchdog:
        """The tenant's watchdog, created on first sight (or the shared
        overflow dog past the cap)."""
        name = self._key(tenant)
        dog = self._dogs.get(name)
        if dog is None:
            cfg = self.overrides.get(name, self.config)
            dog = SLOWatchdog(cfg, tenant=name, **self._deps)
            self._dogs[name] = dog
        return dog

    # ------------------------------------------------------------ intake
    def observe(self, completion) -> None:
        self.watchdog(getattr(completion, "tenant", None)).observe(
            completion)

    def observe_event(self, *, tenant: Optional[str] = None,
                      **kw) -> None:
        self.watchdog(tenant).observe_event(**kw)

    # -------------------------------------------------------- evaluation
    def evaluate(self, now: Optional[float] = None,
                 force: bool = False) -> Dict[str, dict]:
        """Evaluate every tenant's watchdog; returns
        {tenant: per-objective report}."""
        return {name: dog.evaluate(now, force)
                for name, dog in self._dogs.items()}

    @property
    def active(self) -> bool:
        return any(dog.active for dog in self._dogs.values())

    def is_burning(self, tenant: Optional[str]) -> bool:
        """Whether THIS tenant's budget is alerting — maps through the
        overflow fold (an over-cap tenant answers for the shared
        "other" dog, the price of bounded cardinality) but never
        creates a watchdog."""
        name = self._name(tenant)
        dog = self._dogs.get(name)
        if dog is None and len(self._dogs) >= self.max_tenants:
            dog = self._dogs.get(self.OVERFLOW)
        return dog is not None and dog.active

    def burning_tenants(self) -> List[str]:
        """Tenant names with an active alert — the brown-out's shed
        scope (sorted: deterministic trace attrs and tests)."""
        return sorted(
            name for name, dog in self._dogs.items() if dog.active
        )

    @property
    def alert_log(self) -> List[Tuple[float, str, str, str]]:
        """Merged (t, edge, objective, tenant) history, time-ordered."""
        out = [
            (t, edge, objective, name)
            for name, dog in self._dogs.items()
            for (t, edge, objective) in dog.alert_log
        ]
        out.sort(key=lambda e: e[0])
        return out

    def burn_signal(self) -> dict:
        """The autoscaler's view: WORST burn across tenants (capacity
        decisions answer the most-burning budget), resolved only when
        every tenant's slow window has settled."""
        sigs = [dog.burn_signal() for dog in self._dogs.values()]
        return {
            "burn_fast": max((s["burn_fast"] for s in sigs), default=0.0),
            "burn_slow": max((s["burn_slow"] for s in sigs), default=0.0),
            "active": self.active,
            "resolved": all(s["resolved"] for s in sigs),
        }


# ------------------------------------------------------------- push alerts
@dataclasses.dataclass(frozen=True)
class AlertSinkSpec:
    """One push destination. `kind` is ``command`` (run it, the alert
    JSON on stdin, exit 0 = delivered), ``webhook`` (POST the JSON to
    the URL, 2xx/3xx = delivered), or ``jsonl`` (append one line to the
    file)."""

    kind: str
    target: str
    timeout_s: float = 3.0

    _KINDS = ("command", "webhook", "jsonl")

    def __post_init__(self):
        if self.kind not in self._KINDS:
            raise ValueError(
                f"unknown alert sink kind {self.kind!r} "
                f"(one of {self._KINDS})"
            )

    @classmethod
    def parse(cls, text: str) -> "AlertSinkSpec":
        """``kind:target`` — e.g. ``jsonl:/var/log/alerts.jsonl``,
        ``webhook:http://pager.example/hook``, ``command:notify-team``.
        A bare ``http(s)://...`` is a webhook; anything else is
        rejected loudly (a silently-misparsed pager is no pager)."""
        if text.startswith(("http://", "https://")):
            return cls("webhook", text)
        kind, sep, target = text.partition(":")
        if not sep or not target:
            raise ValueError(
                f"alert sink wants kind:target, got {text!r}"
            )
        return cls(kind, target)


class AlertSinks:
    """Fan alert edges out to N sinks — bounded queue, retry backoff,
    dead-sink breaker per sink.

    `send(event)` enqueues on every live sink and attempts delivery;
    `flush(now)` retries sinks whose backoff came due (the SLO
    watchdog's evaluate() drives it, so retries ride the serve tick and
    nothing here owns a thread). Delivery failures back off
    exponentially and, after `max_failures` CONSECUTIVE failures, trip
    the sink's breaker: bulk pending drops (counted), the
    ``alert_sink_dead`` gauge flips, and the serve loop stops paying
    the sink's timeout per edge. Dead is HALF-OPEN, not forever: the
    sink keeps exactly ONE queued edge (always the newest — send() to
    a dead sink replaces it) and re-probes with it every
    `probe_cooldown_s` — a pager that was rebooted, rotated, or had
    its disk freed rejoins on its own instead of staying dead until a
    process restart. `deliver` is injectable so the state machine is
    host-pure testable (the real one shells out / POSTs / appends).
    """

    PENDING_CAP = 64

    def __init__(self, specs, *, clock=None, registry=None,
                 max_failures: int = 5, base_s: float = 0.5,
                 max_s: float = 30.0, seed: int = 0,
                 probe_cooldown_s: float = 30.0,
                 deliver=None) -> None:
        self._now = _resolve_clock(clock)
        self.registry = registry
        self.max_failures = max_failures
        self.base_s = base_s
        self.max_s = max_s
        self.seed = seed
        self.probe_cooldown_s = probe_cooldown_s
        self._deliver_fn = deliver
        self.sinks: List[dict] = []
        for spec in specs:
            if isinstance(spec, str):
                spec = AlertSinkSpec.parse(spec)
            self.sinks.append({
                "spec": spec, "pending": deque(maxlen=self.PENDING_CAP),
                "failures": 0, "next_at": 0.0, "dead": False,
                "delivered": 0, "dropped": 0,
            })

    def _metric(self, kind: str, sink: dict):
        if self.registry is None:
            return None
        # labelled() keys cannot represent "," or "=" (its documented
        # limit — they would shear into fabricated labels at exposition
        # time), and sink targets are operator strings that may carry
        # both (webhook query params, comma-joined command args)
        label = (f"{sink['spec'].kind}:{sink['spec'].target}"
                 .replace(",", "_").replace("=", "_"))
        return self.registry.counter(
            labelled(f"alert_sink_{kind}_total", sink=label)
        ) if kind != "dead" else self.registry.gauge(
            labelled("alert_sink_dead", sink=label)
        )

    # ------------------------------------------------------------ intake
    def send(self, event: dict) -> None:
        now = self._now()
        for s in self.sinks:
            if s["dead"]:
                # a dead sink holds exactly ONE edge for its next
                # half-open probe — the newest (a probe that succeeds
                # should deliver the current state of the world, not a
                # stale alarm); the displaced edge drops, counted
                if s["pending"]:
                    s["dropped"] += len(s["pending"])
                    s["pending"].clear()
                s["pending"].append(dict(event))
                continue
            if len(s["pending"]) == s["pending"].maxlen:
                s["dropped"] += 1  # oldest falls off the bounded deque
            s["pending"].append(dict(event))
        self.flush(now)

    # ---------------------------------------------------------- delivery
    def flush(self, now: Optional[float] = None) -> int:
        """Attempt delivery on every live sink whose backoff is due;
        returns events delivered this call."""
        now = self._now() if now is None else now
        delivered = 0
        for s in self.sinks:
            if not s["pending"] or now < s["next_at"]:
                continue
            if s["dead"]:
                # half-open probe: ONE attempt with the kept edge.
                # Success closes the breaker (failures reset, gauge
                # clears — the sink is a normal live sink again);
                # failure re-arms the fixed cool-down, never the
                # exponential schedule (a 30 s heartbeat against a
                # maybe-back pager, not a retry storm).
                if self._try_deliver(s["spec"], s["pending"][0]):
                    s["pending"].popleft()
                    s["dead"] = False
                    s["failures"] = 0
                    s["delivered"] += 1
                    delivered += 1
                    m = self._metric("delivered", s)
                    if m is not None:
                        m.inc()
                    g = self._metric("dead", s)
                    if g is not None:
                        g.set(0)
                else:
                    s["next_at"] = now + self.probe_cooldown_s
                continue
            while s["pending"]:
                ev = s["pending"][0]
                if self._try_deliver(s["spec"], ev):
                    s["pending"].popleft()
                    s["failures"] = 0
                    s["delivered"] += 1
                    delivered += 1
                    m = self._metric("delivered", s)
                    if m is not None:
                        m.inc()
                    continue
                s["failures"] += 1
                m = self._metric("failures", s)
                if m is not None:
                    m.inc()
                if s["failures"] >= self.max_failures:
                    # the dead-sink breaker: bulk pending drops so the
                    # serve loop stops paying this sink's timeout per
                    # edge — but ONE edge (the newest) stays queued for
                    # the half-open probe after `probe_cooldown_s`
                    s["dead"] = True
                    keep = s["pending"][-1]
                    s["dropped"] += len(s["pending"]) - 1
                    s["pending"].clear()
                    s["pending"].append(keep)
                    s["next_at"] = now + self.probe_cooldown_s
                    g = self._metric("dead", s)
                    if g is not None:
                        g.set(1)
                else:
                    s["next_at"] = now + backoff_delay(
                        s["failures"] - 1, base_s=self.base_s,
                        max_s=self.max_s, seed=self.seed,
                    )
                break
        return delivered

    def _try_deliver(self, spec: AlertSinkSpec, event: dict) -> bool:
        try:
            if self._deliver_fn is not None:
                return bool(self._deliver_fn(spec, event))
            return _deliver_real(spec, event)
        except Exception:
            return False

    # --------------------------------------------------------- observing
    @property
    def any_alive(self) -> bool:
        return any(not s["dead"] for s in self.sinks)

    def state(self) -> List[dict]:
        return [
            {"sink": f"{s['spec'].kind}:{s['spec'].target}",
             "dead": s["dead"], "failures": s["failures"],
             "pending": len(s["pending"]),
             "delivered": s["delivered"], "dropped": s["dropped"]}
            for s in self.sinks
        ]


def _deliver_real(spec: AlertSinkSpec, event: dict) -> bool:
    """The three transports. Failures return False (or raise — the
    caller treats both as a failed attempt)."""
    line = json.dumps(event)
    if spec.kind == "jsonl":
        with open(spec.target, "a") as f:
            f.write(line + "\n")
        return True
    if spec.kind == "webhook":
        from ddp_practice_tpu.utils.http_post import post_json

        return post_json(spec.target, line, timeout_s=spec.timeout_s)
    if spec.kind == "command":
        import shlex
        import subprocess

        r = subprocess.run(
            shlex.split(spec.target), input=line + "\n",
            capture_output=True, text=True, timeout=spec.timeout_s,
        )
        return r.returncode == 0
    return False


class FleetAlerts:
    """Fleet-level alert edges from the federated health verdict.

    The SLO watchdog judges request outcomes; this judges the FLEET —
    the ScrapeFederator's per-worker status (healthy / degraded /
    stale / dead). Feed it each federated healthz body (`observe`):
    a worker leaving ``healthy`` raises a trip edge (objective
    ``worker_dead`` / ``worker_stale`` / ...), its return a resolve —
    through the same sinks/tracer/telemetry/counter paths as SLO
    edges, so a dead worker pages exactly like a burning SLO. Host-pure
    (callers do the scraping; tests feed dicts).
    """

    def __init__(self, sinks: Optional[AlertSinks] = None, *,
                 tracer=None, telemetry=None, registry=None,
                 clock=None, pid: int = ROUTER_PID) -> None:
        self.sinks = sinks
        self.tracer = tracer
        self.telemetry = telemetry
        self.pid = pid
        self._now = _resolve_clock(clock)
        self._last: Dict[str, str] = {}
        self.alert_log: List[Tuple[float, str, str, str]] = []
        self._ctr = (registry.counter("fleet_alerts_total")
                     if registry is not None else None)

    def observe(self, healthz: dict,
                now: Optional[float] = None) -> List[dict]:
        """Fold one federated /healthz body in; returns the edge events
        raised (empty when nothing changed)."""
        now = self._now() if now is None else now
        edges: List[dict] = []
        for wid, w in (healthz.get("workers") or {}).items():
            wid = str(wid)
            status = str(w.get("status", "dead")).lower()
            prev = self._last.get(wid, "healthy")
            if status == prev:
                continue
            self._last[wid] = status
            if status != "healthy":
                edges.append({"event": "trip",
                              "objective": f"worker_{status}",
                              "worker": wid})
            if prev != "healthy":
                # whatever it was before has ended — resolve it even
                # when moving between two bad states (stale -> dead),
                # so trips and resolves always pair per objective
                edges.append({"event": "resolve",
                              "objective": f"worker_{prev}",
                              "worker": wid})
        for e in edges:
            self.alert_log.append(
                (now, e["event"], e["objective"], e["worker"])
            )
            if e["event"] == "trip" and self._ctr is not None:
                self._ctr.inc()
            if self.tracer is not None and self.tracer.enabled:
                self.tracer.instant(
                    ("fleet_alert" if e["event"] == "trip"
                     else "fleet_resolve"),
                    pid=self.pid, objective=e["objective"],
                    worker=e["worker"],
                )
            if self.telemetry is not None:
                self.telemetry.emit("alert", **e)
            if self.sinks is not None:
                self.sinks.send({"kind": "alert", "t": now,
                                 "scope": "fleet", **e})
        if self.sinks is not None:
            self.sinks.flush(now)
        return edges
