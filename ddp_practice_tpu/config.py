"""Configuration for training runs.

The reference exposes exactly three CLI flags — `--gpu`, `-e/--epochs`,
`-b/--batch_size` (origin_main.py:34-54) — with everything else hardcoded:
lr 1e-4 (ddp_main.py:125), seed 3407 (ddp_main.py:76), AMP on/off by script
choice. Here the same knobs live in one dataclass, with distribution described
by a device-mesh shape instead of a GPU list.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class PrecisionPolicy:
    """Mixed-precision policy replacing autocast + GradScaler.

    On TPU, bf16 has the same exponent range as fp32, so the dynamic
    loss-scaling machinery the reference needs for fp16 (GradScaler,
    ddp_main.py:10,126,91-93) is unnecessary: we simply run compute in
    ``compute_dtype`` while keeping parameters and optimizer state in
    ``param_dtype``.
    """

    param_dtype: jnp.dtype = jnp.float32
    compute_dtype: jnp.dtype = jnp.float32
    output_dtype: jnp.dtype = jnp.float32

    @staticmethod
    def fp32() -> "PrecisionPolicy":
        return PrecisionPolicy()

    @staticmethod
    def bf16() -> "PrecisionPolicy":
        return PrecisionPolicy(
            param_dtype=jnp.float32,
            compute_dtype=jnp.bfloat16,
            output_dtype=jnp.float32,
        )

    @staticmethod
    def from_name(name: str) -> "PrecisionPolicy":
        name = name.lower()
        if name in ("fp32", "float32", "f32"):
            return PrecisionPolicy.fp32()
        if name in ("bf16", "bfloat16", "mixed"):
            return PrecisionPolicy.bf16()
        raise ValueError(f"unknown precision policy {name!r}")

    @property
    def name(self) -> str:
        return "bf16" if self.compute_dtype == jnp.bfloat16 else "fp32"


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Shape of the device mesh.

    Replaces the reference's rank/world bookkeeping (`--gpu 0,1`,
    WORLD_SIZE env, ddp_main.py:60-66): the mesh *is* the distributed-backend
    configuration. Axes:

    - ``data``: data parallelism (batch sharding + gradient pmean)
    - ``seq``: sequence/context parallelism (ring/ulysses attention)
    - ``tensor``: tensor parallelism (head/feature sharding)
    - ``pipe``: pipeline parallelism (stage-sharded block stacks, GPipe
      microbatch schedule over ppermute)
    - ``expert``: expert parallelism (MoE expert sharding, all-to-all
      token dispatch)

    A size of -1 on the data axis means "all remaining devices".
    """

    data: int = -1
    seq: int = 1
    tensor: int = 1
    pipe: int = 1
    expert: int = 1

    AXIS_DATA = "data"
    AXIS_SEQ = "seq"
    AXIS_TENSOR = "tensor"
    AXIS_PIPE = "pipe"
    AXIS_EXPERT = "expert"

    @property
    def axis_names(self) -> tuple:
        return (
            self.AXIS_DATA,
            self.AXIS_SEQ,
            self.AXIS_TENSOR,
            self.AXIS_PIPE,
            self.AXIS_EXPERT,
        )

    def resolve(self, n_devices: int) -> tuple:
        """Return concrete (data, seq, tensor, pipe, expert) sizes."""
        rest = self.seq * self.tensor * self.pipe * self.expert
        data = self.data
        if data == -1:
            if n_devices % rest != 0:
                raise ValueError(
                    f"{n_devices} devices not divisible by "
                    f"seq*tensor*pipe*expert={rest}"
                )
            data = n_devices // rest
        if data * rest != n_devices:
            raise ValueError(
                f"mesh {data}x{self.seq}x{self.tensor}x{self.pipe}"
                f"x{self.expert} != {n_devices} devices"
            )
        return (data, self.seq, self.tensor, self.pipe, self.expert)


@dataclasses.dataclass(frozen=True)
class TrainConfig:
    """Full training-run configuration.

    Defaults reproduce the reference contract: 3 epochs, per-replica batch 32,
    SGD lr 1e-4 (NOT scaled by world size — parity with ddp_main.py:125 and
    the acknowledged accuracy gap in the reference README), seed 3407.
    """

    # model / data
    model: str = "convnet"
    dataset: str = "mnist"
    data_dir: str = "./data"
    num_classes: int = 10
    # override the synthetic-fallback corpus size (train split; eval gets
    # ~1/6, the MNIST train:test ratio; for LM tasks this is the token
    # count). 0 = per-dataset default.
    synthetic_size: int = 0
    # sequence length for LM models (lm_*): batches are (seq_len + 1)
    # token windows, position t predicting t + 1
    seq_len: int = 256
    # rematerialize LM block activations in backward (jax.checkpoint):
    # ~1/3 more FLOPs for O(depth) less activation memory; with the
    # streaming flash kernels this is what takes lm_base from seq 16k to
    # 32k on one v5e chip (BENCHMARKS.md)
    remat: bool = False
    # LM position encoding: "learned" absolute table (GPT-2 style) or
    # "rope" rotary Q/K (relative positions; ops/rope.py)
    pos_emb: str = "learned"
    # share the token embedding with the output projection (GPT-2 weight
    # tying): removes the (d, vocab) lm_head parameter
    tied_embeddings: bool = False

    # optimization (reference defaults: origin_main.py:37-52, ddp_main.py:125)
    epochs: int = 3
    batch_size: int = 32          # per data-parallel replica, like the reference
    learning_rate: float = 1e-4
    optimizer: str = "sgd"
    momentum: float = 0.0
    # clip gradients to this global L2 norm before the optimizer update
    # (0 = off) — the standard transformer-training stabilizer
    clip_norm: float = 0.0
    # residual-branch + embedding dropout for the transformer families
    # (ViT, LM); 0 = off. Masks are keyed on the global step (train/steps.py
    # _step_rngs): deterministic across resume and driver variants.
    dropout: float = 0.0
    weight_decay: float = 0.0
    lr_schedule: str = "constant"     # constant | cosine | warmup_cosine
    warmup_steps: int = 0
    scale_lr_by_replicas: bool = False  # parity default: False (README.md:506)
    label_smoothing: float = 0.0
    # gradient accumulation: average grads over k micro-steps before the
    # optimizer applies (optax.MultiSteps) — large effective batches
    # without the memory; 1 = off. Decaying lr schedules advance once per
    # optimizer APPLY; make_optimizer divides their horizons (total and
    # warmup) by k so decay still completes over the run
    accum_steps: int = 1

    # rng (reference: 3407 + rank, ddp_main.py:76-80)
    seed: int = 3407

    # precision
    precision: str = "fp32"

    # distribution
    mesh: MeshConfig = dataclasses.field(default_factory=MeshConfig)
    # ZeRO-3: shard params + optimizer state over the 'data' axis (the
    # reference replicates both on every process — SURVEY §2.3)
    fsdp: bool = False
    # sequence-parallel attention scheme when mesh.seq > 1
    sp_impl: str = "ring"              # ring | ulysses
    # local attention kernel: "xla" (compiler-fused) | "flash" (Pallas tiled
    # kernel, ops/flash_attention.py) — composes with ring/ulysses
    attn_impl: str = "xla"
    # GPipe microbatches per step when mesh.pipe > 1
    num_microbatches: int = 4
    # pipeline schedule: "gpipe" (autodiff-of-scan; activation memory grows
    # O(M + P)) | "1f1b" (LM only; explicit interleaved backward with an
    # O(P) input stash — parallel/pipeline_1f1b.py)
    pipe_schedule: str = "gpipe"
    # virtual pipeline chunks per device (interleaved schedule only)
    num_virtual: int = 2
    # on-device input augmentation (random crop + horizontal flip inside
    # the jitted train step, ops/augment.py); image models only
    augment: bool = False
    # which augmentation when --augment is set: "crop_flip" (pad-crop +
    # flip, the CIFAR/MNIST rung) or "rrc" (random resized crop, the
    # ImageNet rung — ResNet-50/224)
    augment_kind: str = "crop_flip"

    # encoder layers as fused Pallas kernels (ops/fused_encoder.py):
    # "auto" (default) = the model picks them whenever its constraints
    # hold (models/vit.py EncoderBlock._auto_fuse); "on"/True = force,
    # raising on unsupported configs; "off"/False = per-op pipeline
    fused_encoder: object = "auto"  # "auto" | "on"/True | "off"/False
    # MoE expert count when mesh.expert > 1 (0 = auto: 8 rounded up to a
    # multiple of the expert axis)
    num_experts: int = 0
    # MoE routing scheme: "topk" (tokens choose experts) |
    # "expert_choice" (experts choose tokens; ops/moe.py)
    moe_router: str = "topk"
    # attention head count override for transformer models (0 = model
    # default); tensor parallelism shards heads, so heads % tensor == 0
    num_heads: int = 0
    # multi-host rendezvous (replaces MASTER_ADDR/MASTER_PORT, ddp_main.py:61-62)
    coordinator_address: Optional[str] = None
    num_processes: Optional[int] = None
    process_id: Optional[int] = None

    # checkpointing (reference saves once at end, no resume: origin_main.py:113)
    checkpoint_dir: Optional[str] = None
    checkpoint_every_epochs: int = 0   # 0 = only at end
    checkpoint_every_steps: int = 0    # 0 = off (periodic mid-epoch saves)
    # periodic saves write on a background thread (gather fences the
    # device, serialization overlaps the next steps); the end-of-fit save
    # is always synchronous, and multi-host saves are always synchronous
    # (collective ordering)
    checkpoint_async: bool = True
    resume: bool = False

    # failure detection / elastic recovery (absent in reference, SURVEY §5.3)
    max_restarts: int = 0              # checkpoint-based restarts on failure
    watchdog_timeout_s: float = 0.0    # 0 = no step watchdog
    # force a device-progress probe every N steps — the watchdog beats only
    # on CONFIRMED device progress, never on dispatch (async dispatch
    # outruns a hung collective). A probe fetches the OLDEST unconfirmed
    # step's metrics scalar (one rung past the last confirmed point), so it
    # blocks for at most ~one step of device time even when the host has
    # dispatched far ahead — the watchdog fires only when NO step completes
    # within the timeout, not when the host merely outruns a healthy
    # device. Independent of N, a probe also fires whenever half the
    # watchdog timeout passes without one, so slow steps can't starve the
    # watchdog into a spurious firing. 0 = time-based probing only.
    watchdog_probe_every_steps: int = 50
    sync_check_every_steps: int = 0    # 0 = no cross-host driver sync checks

    # eval / logging
    max_steps_per_epoch: int = 0       # 0 = full epoch; >0 caps steps (smoke runs)
    eval_every_epochs: int = 0         # 0 = only at end (reference behavior)
    log_every_steps: int = 100
    profile_dir: Optional[str] = None
    # write a Chrome trace-event JSON of the host-side step phases
    # (data / dispatch / block / checkpoint spans, utils/trace.py) at
    # fit end — open in Perfetto; process 0 only. Complements
    # profile_dir: that traces the DEVICE, this traces the driver.
    trace_out: Optional[str] = None
    # append one JSON record per logged train step / eval / run summary
    # (process 0 only) — machine-readable training curves next to the
    # human stdout logs; records carry the global step, so resumed runs
    # append seamlessly
    metrics_file: Optional[str] = None
    # ---- live telemetry plane (utils/telemetry.py; process 0 only)
    # bind /metrics (Prometheus exposition of step-time/MFU/anomaly
    # metrics), /healthz, /flight (rolling step-time percentiles) on
    # this port for the whole fit (0 = ephemeral, logged at startup)
    metrics_port: Optional[int] = None
    # stream trace events + flight/step records + periodic metrics
    # snapshots as line-delimited JSONL WHILE training — a killed run
    # still leaves a parseable file (the exit-time trace_out dump
    # leaves nothing)
    telemetry_out: Optional[str] = None
    # SLO config (serve/slo.py SLOConfig JSON or path): a burn-rate
    # watchdog over the straggler detector's verdicts — sustained
    # anomalous step times trip an alert into the telemetry stream
    slo: Optional[str] = None
    # push-alert sinks ("kind:target" specs, serve/slo.py AlertSinkSpec:
    # command:... / webhook:http://... / jsonl:path): SLO trip/resolve
    # edges are PUSHED to an operator, with per-sink retry backoff and a
    # dead-sink breaker — a burning SLO that only lands in a scrape
    # endpoint pages nobody
    alert_sinks: Optional[tuple] = None

    # input pipeline
    loader_backend: str = "auto"       # auto | native | python
    prefetch: int = 2
    # K optimizer steps per jitted call (lax.scan over stacked batches);
    # amortizes host dispatch + H2D latency for small models. 1 = off.
    # -1 = the whole epoch per call (device-resident data only: the scan
    # gathers batches from HBM, so no per-chunk feeding is needed).
    steps_per_call: int = 1
    # where the corpus lives during training: "host" streams batches (the
    # DataLoader/prefetch path), "device" uploads the whole uint8 corpus to
    # HBM once and sends only per-epoch index grids (single-process only),
    # "auto" picks device when single-process and the corpus fits
    # resident_max_bytes. Same batches and math either way; agreement is
    # to float noise (different XLA programs associate reductions
    # differently — tests/test_resident.py pins the bound).
    data_placement: str = "auto"       # auto | host | device
    resident_max_bytes: int = 256 * 1024 * 1024
    # persistent XLA compilation cache: repeat runs skip compile entirely
    # (measured on the parity run: ~20-30 s cold -> 6-15 s warm, PARITY.md).
    # "auto" = ~/.cache/ddp_practice_tpu/xla (or $JAX_COMPILATION_CACHE_DIR
    # when set); "off" disables; any other value is used as the directory.
    compilation_cache: str = "auto"
    shuffle_eval: bool = False  # the reference baseline shuffles eval; don't (SURVEY §2.5)

    def __post_init__(self):
        if self.steps_per_call == -1 or self.steps_per_call >= 1:
            return
        raise ValueError(
            f"steps_per_call={self.steps_per_call}: must be >= 1 (K steps "
            "per dispatch) or exactly -1 (whole epoch per dispatch, "
            "device-resident data only)"
        )

    def precision_policy(self) -> PrecisionPolicy:
        return PrecisionPolicy.from_name(self.precision)

    def replace(self, **kw) -> "TrainConfig":
        return dataclasses.replace(self, **kw)
