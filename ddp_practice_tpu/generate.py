"""Generate text from a trained LM checkpoint.

    python -m ddp_practice_tpu.generate --ckpt_dir ckpts \
        --prompt "def main" --max_new_tokens 256 --temperature 0.8 --top_k 40

The training invocation's state-shaping knobs (model, optimizer, seq_len,
vocab) are read back from the checkpoint manifest (train/loop.py save()),
so only the checkpoint directory is required; flags override. The
reference has no inference path to cite — this is framework surface the
reference's training-only design stops short of (origin_main.py:113 saves
and exits).
"""

from __future__ import annotations

import argparse
import sys
import time

import jax
import jax.numpy as jnp

from ddp_practice_tpu import checkpoint as ckpt
from ddp_practice_tpu.config import PrecisionPolicy, TrainConfig
from ddp_practice_tpu.inference import (
    cast_params_for_streaming,
    decode_bytes,
    encode_bytes,
    make_generate_fn,
)
from ddp_practice_tpu.models import create_model
from ddp_practice_tpu.train.state import create_state, make_optimizer


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--ckpt_dir", required=True)
    p.add_argument("--prompt", default="\n")
    p.add_argument("--max_new_tokens", type=int, default=256)
    p.add_argument("--temperature", type=float, default=0.8,
                   help="0 = greedy argmax")
    p.add_argument("--top_k", type=int, default=0)
    p.add_argument("--top_p", type=float, default=0.0)
    p.add_argument("--eos_id", type=int, default=None)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--model", default=None,
                   help="override the manifest's model name")
    p.add_argument("--seq_len", type=int, default=0,
                   help="override the manifest's max sequence length")
    p.add_argument("--kv_cache", default="policy",
                   choices=["policy", "int8"],
                   help="KV-cache storage: policy dtype (default) or int8 "
                        "(quantized cache + scales — ~1%% logit error, "
                        "faster past ~768-token contexts; BENCHMARKS.md)")
    return p


def load_lm(ckpt_dir, *, model=None, seq_len=0, kv_cache="policy") -> tuple:
    """(model, params, batch_stats, step) rebuilt from the checkpoint
    manifest + leaves — shared by this CLI and the serving entry point
    (serve/bench.py), which is why it takes plain kwargs rather than the
    parsed argparse namespace."""
    manifest = ckpt.latest_manifest(ckpt_dir)
    if manifest is None:
        raise SystemExit(f"no checkpoint under {ckpt_dir!r}")
    extra = manifest.get("extra", {})
    name = model or extra.get("model")
    if not name or not name.startswith("lm_"):
        raise SystemExit(
            f"checkpoint model {name!r} is not an LM (lm_*) — generation "
            "needs a decoder; pass --model to override"
        )
    if name == "lm_pipe":
        raise SystemExit(
            "lm_pipe has no KV-cache decode path — generate from an "
            "equivalent lm_tiny/lm_base checkpoint instead"
        )
    seq_len = seq_len or int(extra.get("seq_len", 2048))
    vocab = int(extra.get("vocab_size", 256))
    policy = (
        PrecisionPolicy.bf16()
        if extra.get("precision_policy") == "bf16"
        else PrecisionPolicy.fp32()
    )
    model_kw = {}
    if kv_cache == "int8":
        model_kw["kv_cache_dtype"] = "int8"
    model = create_model(
        name, policy=policy, vocab_size=vocab, max_len=seq_len,
        remat=bool(extra.get("remat", False)),
        pos_emb=extra.get("pos_emb", "learned"),
        tied_embeddings=bool(extra.get("tied_embeddings", False)),
        **model_kw,
    )
    # rebuild the train-state TREE abstractly (shapes only, no init FLOPs)
    # so restore()'s strict path check accepts the leaves
    cfg = TrainConfig(
        model=name,
        optimizer=extra.get("optimizer", "sgd"),
        momentum=float(extra.get("momentum", 0.0)),
        clip_norm=float(extra.get("clip_norm", 0.0)),
        weight_decay=float(extra.get("weight_decay", 0.0)),
        accum_steps=int(extra.get("accum_steps", 1)),
    )
    tx = make_optimizer(cfg)
    sample = jnp.zeros((1, seq_len), jnp.int32)
    abstract = jax.eval_shape(
        lambda r: create_state(model, tx, rng=r, sample_input=sample),
        jax.random.PRNGKey(0),
    )
    state = ckpt.restore(ckpt_dir, abstract)
    params = state.params
    if extra.get("precision_policy") == "bf16":
        # inference needs no fp32 masters: stream bf16 params (half the
        # HBM traffic per decode step; bit-identical under this policy)
        params = cast_params_for_streaming(params)
    # non-param state (lm_moe router selection bias) rides along so
    # generation routes like training did (inference.make_generate_fn)
    return (model, jax.device_put(params), state.batch_stats,
            int(extra.get("step", -1)))


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    model, params, batch_stats, step = load_lm(
        args.ckpt_dir, model=args.model, seq_len=args.seq_len,
        kv_cache=args.kv_cache,
    )
    prompt = jnp.asarray(encode_bytes(args.prompt))
    gen = jax.jit(
        make_generate_fn(
            model,
            max_new_tokens=args.max_new_tokens,
            temperature=args.temperature,
            top_k=args.top_k,
            top_p=args.top_p,
            eos_id=args.eos_id,
            batch_stats=batch_stats,
        )
    )
    key = jax.random.PRNGKey(args.seed)
    t0 = time.perf_counter()
    tokens = jax.device_get(gen(params, prompt, key))
    dt = time.perf_counter() - t0
    generated = tokens[0, prompt.shape[1]:]
    if args.eos_id is not None:
        # early EOS leaves pad_id (0) in the post-EOS slots (inference.py
        # done-mask); cut at the first EOS so the text carries no NULs
        hits = (generated == args.eos_id).nonzero()[0]
        if hits.size:
            generated = generated[: int(hits[0])]
    text = decode_bytes(generated)
    print(text)
    print(
        f"[generate] ckpt step {step}, {args.max_new_tokens} tokens in "
        f"{dt:.2f}s ({args.max_new_tokens / dt:.1f} tok/s, incl. compile)",
        file=sys.stderr,
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
