"""Analytic FLOP counting and chip peak rates, for MFU reporting.

The reference publishes wall-clock only (README.md:201,466) with unnamed
hardware; this framework reports model FLOP utilization — analytic forward
FLOPs per image x3 for training (backward ~= 2x forward, the standard
accounting) divided by measured step time and the chip's peak bf16 rate.

Analytic rather than XLA cost analysis: on the TPU backend used here,
`compiled.cost_analysis()["flops"]` undercounts real matmul FLOPs by ~8x
(measured against hand-counted ViT-Tiny), so the numbers below are computed
from the model architecture directly: 2*M*N*K per matmul, conv as the
equivalent im2col matmul. Elementwise/normalization FLOPs are ignored
(<2% for these models), making reported MFU slightly conservative.
"""

from __future__ import annotations

import math
from typing import Optional, Tuple


def conv2d(h: int, w: int, cin: int, cout: int, kh: int, kw: int,
           stride: int = 1) -> Tuple[float, int, int]:
    """FLOPs of a SAME-padded conv, plus output spatial dims."""
    ho = math.ceil(h / stride)
    wo = math.ceil(w / stride)
    return 2.0 * ho * wo * cout * cin * kh * kw, ho, wo


def convnet_forward_flops(image_shape=(28, 28, 1), num_classes: int = 10,
                          features=(16, 32)) -> float:
    """The reference ConvNet (origin_main.py:12-24): [conv5x5->BN->relu->
    maxpool2] per feature block, then a single dense head."""
    h, w, c = image_shape
    total = 0.0
    for feat in features:
        f, h, w = conv2d(h, w, c, feat, 5, 5)
        total += f
        h, w, c = h // 2, w // 2, feat
    total += 2.0 * (h * w * c) * num_classes
    return total


def resnet_forward_flops(image_shape=(32, 32, 3), *, stage_sizes=(2, 2, 2, 2),
                         bottleneck: bool = False, num_filters: int = 64,
                         small_images: bool = True,
                         num_classes: int = 10) -> float:
    """ResNet v1.5 as built in models/resnet.py (3x3 CIFAR stem or 7x7
    ImageNet stem, stride-2 at each stage boundary, 1x1 projection when
    shapes change)."""
    h, w, c = image_shape
    total = 0.0
    if small_images:
        f, h, w = conv2d(h, w, c, num_filters, 3, 3)
    else:
        f, h, w = conv2d(h, w, c, num_filters, 7, 7, stride=2)
    total += f
    c = num_filters
    if not small_images:
        h, w = math.ceil(h / 2), math.ceil(w / 2)  # max_pool 3x3 s2 SAME
    for i, n_blocks in enumerate(stage_sizes):
        filters = num_filters * 2 ** i
        for j in range(n_blocks):
            stride = 2 if (i > 0 and j == 0) else 1
            cin = c
            h_in, w_in = h, w
            if bottleneck:
                f1, h1, w1 = conv2d(h, w, cin, filters, 1, 1)
                f2, h2, w2 = conv2d(h1, w1, filters, filters, 3, 3, stride)
                f3, h, w = conv2d(h2, w2, filters, filters * 4, 1, 1)
                total += f1 + f2 + f3
                cout = filters * 4
            else:
                f1, h1, w1 = conv2d(h, w, cin, filters, 3, 3, stride)
                f2, h, w = conv2d(h1, w1, filters, filters, 3, 3)
                total += f1 + f2
                cout = filters
            if cin != cout or stride != 1:
                fp, _, _ = conv2d(h_in, w_in, cin, cout, 1, 1, stride)
                total += fp
            c = cout
    total += 2.0 * c * num_classes
    return total


def resnet18_forward_flops(image_shape=(32, 32, 3), num_classes: int = 10) -> float:
    return resnet_forward_flops(
        image_shape, stage_sizes=(2, 2, 2, 2), bottleneck=False,
        small_images=True, num_classes=num_classes,
    )


def resnet50_forward_flops(image_shape=(224, 224, 3), num_classes: int = 1000) -> float:
    return resnet_forward_flops(
        image_shape, stage_sizes=(3, 4, 6, 3), bottleneck=True,
        small_images=False, num_classes=num_classes,
    )


def vit_forward_flops(image_shape=(32, 32, 3), *, patch_size: int = 4,
                      hidden_dim: int = 192, depth: int = 12,
                      mlp_dim: int = 768, num_classes: int = 10) -> float:
    """ViT as built in models/vit.py: patch-embed conv, `depth` encoder
    blocks (qkv + scores + weighted-sum + out-proj + 2-layer MLP), dense
    head. Per layer per image: 8*s*d^2 (attn projections) + 4*s^2*d
    (score + value matmuls) + 4*s*d*mlp (MLP)."""
    h, w, c = image_shape
    s = (h // patch_size) * (w // patch_size)
    d = hidden_dim
    embed = 2.0 * s * d * (patch_size * patch_size * c)
    per_layer = 8.0 * s * d * d + 4.0 * s * s * d + 4.0 * s * d * mlp_dim
    head = 2.0 * d * num_classes
    return embed + depth * per_layer + head


def lm_forward_flops_per_token(*, hidden_dim: int, depth: int, mlp_dim: int,
                               vocab_size: int, seq_len: int,
                               causal: bool = True, moe_every: int = 0,
                               moe_top_k: int = 2) -> float:
    """Decoder LM (models/lm.py) forward FLOPs per token. Per layer:
    8*d^2 (qkv + out projections) + 4*d*mlp (MLP) + attention score/value
    matmuls 4*s*d, halved under causal masking (each query attends to s/2
    keys on average — flash skips the masked blocks; the dense path
    wastes them, so causal MFU there is conservative). Plus the 2*d*V
    lm_head. Embedding lookups are gathers, not FLOPs.

    moe_every > 0 (lm_moe): every moe_every-th layer's MLP routes each
    token through top_k experts, so its ACTIVE MLP FLOPs are k * dense
    (plus the negligible d*E router). Dropped tokens make this an upper
    bound on active FLOPs — MFU for MoE is conservative."""
    d, m, v, s = hidden_dim, mlp_dim, vocab_size, seq_len
    attn = 4.0 * s * d * (0.5 if causal else 1.0)
    mlp = 4.0 * d * m
    total = depth * (8.0 * d * d + attn) + 2.0 * d * v
    if moe_every > 0:
        n_moe = depth // moe_every
        total += (depth - n_moe) * mlp + n_moe * moe_top_k * mlp
    else:
        total += depth * mlp
    return total


def lm_train_flops_per_token(**kw) -> float:
    """fwd + bwd FLOPs per token: 3x forward (bwd ~= 2x fwd)."""
    return 3.0 * lm_forward_flops_per_token(**kw)


def train_flops_per_image(model: str, image_shape, num_classes: int = 10,
                          **kw) -> Optional[float]:
    """fwd + bwd FLOPs per image: 3x forward (bwd ~= 2x fwd)."""
    model = model.lower()
    if model == "convnet":
        fwd = convnet_forward_flops(image_shape, num_classes)
    elif model == "resnet18":
        fwd = resnet18_forward_flops(image_shape, num_classes)
    elif model == "resnet50":
        fwd = resnet50_forward_flops(image_shape, num_classes)
    elif model.startswith("vit"):
        fwd = vit_forward_flops(image_shape, num_classes=num_classes, **kw)
    else:
        return None
    return 3.0 * fwd


# Peak dense bf16 matmul FLOP/s per JAX-visible device. v2/v3 report one
# device per core; v4 onward one device per chip (megacore).
_PEAK_BF16 = {
    "TPU v2": 22.5e12,
    "TPU v3": 61.5e12,
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,   # v5e
    "TPU v5": 459e12,        # v5p
    "TPU v6 lite": 918e12,   # v6e / Trillium
}


def chip_peak_flops(device_kind: str) -> Optional[float]:
    """Peak bf16 FLOP/s for a `jax.Device.device_kind`, or None if unknown
    (e.g. the CPU test backend — MFU is only reported on real TPU)."""
    return _lookup(_PEAK_BF16, device_kind)


# HBM bandwidth per device (bytes/s) — the roofline for autoregressive
# decode, where every generated token re-reads the whole parameter set.
_HBM_BYTES = {
    "TPU v2": 700e9,
    "TPU v3": 900e9,
    "TPU v4": 1228e9,
    "TPU v5 lite": 819e9,    # v5e
    "TPU v5": 2765e9,        # v5p
    "TPU v6 lite": 1640e9,   # v6e / Trillium
}


def chip_hbm_bandwidth(device_kind: str) -> Optional[float]:
    """HBM bytes/s for a `jax.Device.device_kind`, or None if unknown."""
    return _lookup(_HBM_BYTES, device_kind)


def _lookup(table: dict, device_kind: str) -> Optional[float]:
    kind = device_kind.strip()
    if kind in table:
        return table[kind]
    # prefix match handles vendor suffixes like "TPU v5 lite0"
    for k, v in sorted(table.items(), key=lambda kv: -len(kv[0])):
        if kind.startswith(k):
            return v
    return None
