"""Wall-clock timing — the reference's only performance instrument
(time.time() around main, origin_main.py:118-121), kept for parity, plus
per-phase accounting for throughput metrics."""

from __future__ import annotations

import time


class Timer:
    def __init__(self):
        self._start = time.perf_counter()
        self._laps = {}

    def elapsed(self) -> float:
        return time.perf_counter() - self._start

    def lap(self, name: str) -> float:
        now = time.perf_counter()
        last = self._laps.get(name, self._start)
        self._laps[name] = now
        return now - last
