"""The one HTTP POST helper every push-plane producer shares.

Two subsystems deliver JSON payloads to an HTTP endpoint: the SLO
webhook sink (serve/slo.py AlertSinks) and the OTLP trace pusher
(utils/telemetry.py OtlpPusher). Both wrap the call in their own
breaker/backoff machinery and both treat "False or raised" as one
failed delivery attempt — so the transport itself lives here, once,
stdlib-only (urllib.request; no new dependency for a POST).
"""

from __future__ import annotations

import json
import urllib.request
from typing import Optional


def post_json(url: str, payload, *, timeout_s: float = 3.0,
              headers: Optional[dict] = None) -> bool:
    """POST `payload` as application/json; True iff the server answered
    with a success status (< 400). `payload` may be a dict/list (dumped
    here), a pre-encoded str, or raw bytes. Network errors and HTTP
    error statuses RAISE (urllib turns 4xx/5xx into URLError) — callers'
    breaker loops already treat an exception exactly like False, and
    swallowing it here would cost them the reason."""
    if isinstance(payload, (bytes, bytearray)):
        data = bytes(payload)
    elif isinstance(payload, str):
        data = payload.encode("utf-8")
    else:
        # compact separators: both producers post machine-read JSON on
        # a hot path — the pretty-print spaces are pure wire/CPU tax
        data = json.dumps(payload, separators=(",", ":")).encode("utf-8")
    hdrs = {"Content-Type": "application/json"}
    if headers:
        hdrs.update(headers)
    req = urllib.request.Request(url, data=data, headers=hdrs)
    with urllib.request.urlopen(req, timeout=timeout_s) as r:
        return r.status < 400
