"""Deterministic exponential backoff with jitter — one helper, three users.

Retry loops live in three layers of the stack (train/elastic.py restart
driver, serve/health.py circuit-breaker probes, serve/router.py request
retries) and the failure mode of hand-rolled backoff is always the same:
either no jitter (a killed fleet retries in lockstep — the thundering
herd the jitter literature exists for) or non-reproducible jitter (a
chaos test that passes or fails by the RNG's mood). This helper fixes
both: delays grow geometrically and cap, and the jitter is a pure
function of (seed, attempt) — same arguments, same delay, so a seeded
fault-injection replay is bit-identical while distinct seeds (one per
replica / per request) still de-synchronize the fleet.
"""

from __future__ import annotations

import random


def backoff_delay(
    attempt: int,
    *,
    base_s: float,
    factor: float = 2.0,
    max_s: float = 60.0,
    jitter: float = 0.5,
    seed: int = 0,
) -> float:
    """Delay before retry number `attempt` (0-based): min(max_s, base_s *
    factor**attempt) stretched by up to `jitter` fraction.

    The jitter draw comes from a Random seeded with an integer mix of
    (seed, attempt) — pure arithmetic, immune to PYTHONHASHSEED — so the
    schedule is reproducible across processes and runs. jitter=0 gives
    the bare geometric schedule.
    """
    if attempt < 0:
        raise ValueError("attempt must be >= 0")
    if base_s < 0 or factor < 1.0 or jitter < 0:
        raise ValueError("need base_s >= 0, factor >= 1, jitter >= 0")
    delay = min(max_s, base_s * factor ** attempt)
    if jitter and delay:
        # Knuth multiplicative mix keeps nearby (seed, attempt) pairs
        # from drawing correlated jitter
        mix = seed * 2_654_435_761 + attempt
        delay *= 1.0 + jitter * random.Random(mix).random()
    return min(delay, max_s * (1.0 + jitter))
