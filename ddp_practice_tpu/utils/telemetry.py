"""Live telemetry plane: streaming JSONL export + HTTP scrape endpoints.

PR 4 made every request a traceable timeline, but the telemetry was dead
on arrival: the trace was one JSON dump at exit (a SIGKILL'd run left
nothing), `render_text()` Prometheus exposition had no scrape endpoint,
and flight-record percentiles existed only in the bench's offline
report. This module is the live half:

- **TelemetryExporter** — a background writer draining a BOUNDED queue
  of events to line-delimited JSONL. Producers (the TraceRecorder sink,
  scheduler/router completion hooks, periodic metrics snapshots) never
  block the serving loop: a full queue drops the event and counts it in
  ``telemetry_dropped_total`` — dropped telemetry is a metric, stalled
  serving is an outage. Every line is written whole and flushed, so a
  killed run leaves a file that is valid line by line (at worst one
  truncated tail line, which the offline tools tolerate).
- **TelemetryServer** — an embedded stdlib ThreadingHTTPServer (port 0
  for tests) exposing ``/metrics`` (utils/metrics.py render_text
  Prometheus exposition), ``/healthz`` (per-replica HEALTHY/DEGRADED/
  DEAD from serve/health.py via an injected callback; 503 only when the
  whole fleet is dead), and ``/flight`` (rolling per-phase
  queue/prefill/decode/stall percentiles from flight records).
- **FlightStats** — the rolling window behind ``/flight``: last-N
  flight records summarized through utils/metrics.percentile_summary,
  the same percentile math the bench and the SLO tools use.
- **StepAnomalyDetector** — train-side rolling median/MAD straggler
  detector: a step time that exceeds the rolling median by k MADs is an
  anomaly (counted, traced, and feedable to an SLO watchdog). MAD
  rather than mean/stddev so one straggler doesn't inflate the baseline
  it is judged against.

Host-pure (nothing here imports jax); the event clock is injectable so
FakeClock runs stamp deterministic times, while the writer thread's
snapshot cadence uses wall time (it is I/O pacing, not data).

JSONL stream schema (one object per line, "kind"-tagged):
``meta`` / ``span`` / ``async`` / ``instant`` come from the
TraceRecorder sink (tools/check_traces.py re-assembles and validates
them as a Chrome trace); ``flight`` carries one completion's merged
flight record (tools/check_slo.py renders SLO verdicts from these);
``metrics`` is a periodic registry snapshot; ``alert`` is an SLO
burn-rate trip/resolve instant (serve/slo.py).
"""

from __future__ import annotations

import json
import queue
import threading
import time
from collections import deque
from statistics import median
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, Optional

from ddp_practice_tpu.utils.metrics import (
    MetricsRegistry,
    percentile_summary,
)
from ddp_practice_tpu.utils.trace import _resolve_clock


class FlightStats:
    """Rolling window of flight records -> per-phase percentiles.

    The live counterpart of the bench's offline phase breakdown: the
    last `window` completions' queue/prefill/decode/stall seconds plus
    TTFT/TPOT, summarized on demand for the ``/flight`` endpoint and
    anything else that wants "where is latency going RIGHT NOW".
    Thread-safe: the serve loop appends, the HTTP thread reads.
    """

    PHASES = ("queue_s", "prefill_s", "decode_s", "stall_s")
    # non-phase flight fields summarized the same way; kept separate
    # from PHASES so phase-sum invariants elsewhere stay honest.
    # spec_accept_rate only appears on flights that actually drafted
    # (speculative decoding, serve/spec.py) — the `key in f` guards
    # below make mixed windows work.
    EXTRAS = ("spec_accept_rate",)
    # raw samples shipped per report, newest last, for fleet rollup
    # (ScrapeFederator.flight pools every worker's samples and
    # recomputes TRUE fleet percentiles — percentiles of percentiles
    # would be a lie)
    SAMPLES_PER_REPORT = 256

    def __init__(self, window: int = 512) -> None:
        self._lock = threading.Lock()
        self._flights: deque = deque(maxlen=window)
        self._ttft: deque = deque(maxlen=window)    # (value, trace_id)
        self._tpot: deque = deque(maxlen=window)

    def on_completion(self, completion, **_kw) -> None:
        tid = getattr(completion, "trace_id", None)
        if not getattr(completion, "trace_sampled", True):
            # suppressed by sampling: the latency sample still counts,
            # but the p99 exemplar must not point at a trace that is
            # not in the timeline
            tid = None
        with self._lock:
            if completion.flight is not None:
                self._flights.append(completion.flight)
            if completion.ttft is not None:
                self._ttft.append((completion.ttft, tid))
            if completion.tpot is not None:
                self._tpot.append((completion.tpot, tid))

    @staticmethod
    def _p99_exemplar(pairs, p99):
        """trace_id of the sample AT the rolling p99 (nearest-rank
        returns an actual sample value, so an exact match exists);
        None when no sample carried a trace_id."""
        for v, tid in pairs:
            if v == p99 and tid is not None:
                return {"trace_id": tid, "value": v}
        return None

    def report(self) -> dict:
        with self._lock:
            flights = list(self._flights)
            ttft = list(self._ttft)
            tpot = list(self._tpot)
        out: dict = {"window": len(flights)}
        for key in self.PHASES + self.EXTRAS:
            out[key] = percentile_summary(
                [f[key] for f in flights if key in f]
            )
        out["ttft_s"] = percentile_summary([v for v, _ in ttft])
        out["tpot_s"] = percentile_summary([v for v, _ in tpot])
        # p99 -> trace pointers (the /flight mirror of the /metrics
        # bucket exemplars) + raw sample tails for fleet federation
        exemplars = {}
        ex = self._p99_exemplar(ttft, out["ttft_s"]["p99"])
        if ex is not None:
            exemplars["ttft_p99"] = ex
        ex = self._p99_exemplar(tpot, out["tpot_s"]["p99"])
        if ex is not None:
            exemplars["tpot_p99"] = ex
        if exemplars:
            out["exemplars"] = exemplars
        cap = self.SAMPLES_PER_REPORT
        samples = {"ttft_s": [v for v, _ in ttft[-cap:]],
                   "tpot_s": [v for v, _ in tpot[-cap:]]}
        for key in self.PHASES + self.EXTRAS:
            samples[key] = [f[key] for f in flights[-cap:] if key in f]
        out["samples"] = samples
        return out


class TelemetryExporter:
    """Background JSONL writer over a bounded, drop-counting queue."""

    def __init__(self, path: str, *, registry: Optional[MetricsRegistry]
                 = None, clock=None, snapshot_interval_s: float = 1.0,
                 max_queue: int = 8192, flight_window: int = 512,
                 start: bool = True) -> None:
        self.path = path
        self.registry = registry
        self._now = _resolve_clock(clock)
        self._interval = snapshot_interval_s
        self._q: queue.Queue = queue.Queue(maxsize=max_queue)
        self.flight = FlightStats(flight_window)
        self.dropped = 0
        self.write_errors = 0  # events the worker could not serialize/write
        self._dropped_ctr = (
            registry.counter("telemetry_dropped_total")
            if registry is not None else None
        )
        self._fh = open(path, "w")
        self._wlock = threading.Lock()  # file writes (worker vs pump/close)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._closed = False
        if start:
            self.start()

    # ------------------------------------------------------------ producers
    def emit(self, kind: str, **fields) -> None:
        """Enqueue one event (never blocks; full queue drops + counts)."""
        self._enqueue({"kind": kind, "t": self._now(), **fields})

    def trace_sink(self, record: dict) -> None:
        """TraceRecorder sink: pass span/async/instant/meta records
        through verbatim (already kind-tagged, already timestamped in
        the recorder's clock domain). Attach via `attach(tracer)`."""
        self._enqueue(record)

    def attach(self, tracer) -> None:
        """Subscribe to a utils/trace.py TraceRecorder (replays lane
        labels recorded before the attach)."""
        tracer.set_sink(self.trace_sink)

    def on_completion(self, completion, slo_exempt: bool = False) -> None:
        """Scheduler/Router completion hook: one ``flight`` line plus
        the rolling /flight window. `slo_exempt` marks completions the
        live watchdog deliberately did not judge (the router's own
        brown-out sheds), so the offline verdict (tools/check_slo.py)
        can reproduce the online judgment instead of disagreeing."""
        self.flight.on_completion(completion)
        ev = {
            "kind": "flight", "t": completion.finish,
            "rid": completion.rid, "status": completion.status,
            "arrival": completion.arrival, "finish": completion.finish,
            "ttft": completion.ttft, "tpot": completion.tpot,
            "tokens": len(completion.tokens),
            "trace_id": getattr(completion, "trace_id", None),
        }
        tenant = getattr(completion, "tenant", None)
        if tenant is not None:
            ev["tenant"] = tenant
        if slo_exempt:
            ev["slo_exempt"] = True
        if completion.flight is not None:
            ev.update(completion.flight)
        self._enqueue(ev)

    def snapshot_now(self) -> None:
        """Enqueue one metrics snapshot out of band (the worker also
        writes one per `snapshot_interval_s` while running)."""
        if self.registry is not None:
            self._enqueue({"kind": "metrics", "t": self._now(),
                           "snapshot": self.registry.snapshot()})

    def _enqueue(self, ev: dict) -> None:
        if self._closed:
            return
        try:
            self._q.put_nowait(ev)
        except queue.Full:
            # the whole point of the bounded queue: a slow disk must
            # never stall the serve/train loop — drop, and make the
            # drop itself observable
            self.dropped += 1
            if self._dropped_ctr is not None:
                self._dropped_ctr.inc()

    # ------------------------------------------------------------- the drain
    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._run, name="telemetry-exporter", daemon=True
        )
        self._thread.start()

    def _run(self) -> None:
        # wall time for PACING (how often to snapshot / poll), the
        # injected clock only stamps event payloads
        last_snap = time.monotonic()
        poll = min(0.2, self._interval) if self._interval else 0.2
        while not self._stop.is_set():
            try:
                ev = self._q.get(timeout=poll)
            except queue.Empty:
                ev = None
            try:
                if ev is not None:
                    self._write(ev)
                if (self.registry is not None and self._interval
                        and time.monotonic() - last_snap
                        >= self._interval):
                    last_snap = time.monotonic()
                    self._write({"kind": "metrics", "t": self._now(),
                                 "snapshot": self.registry.snapshot()})
            except Exception:
                # one bad event (unserializable attr, transient OS
                # error) must not kill the drain thread — that would
                # silently turn every later event into a "drop"
                self.write_errors += 1

    def pump(self) -> int:
        """Drain the queue synchronously (tests run with start=False so
        the file content is deterministic); returns lines written."""
        n = 0
        while True:
            try:
                ev = self._q.get_nowait()
            except queue.Empty:
                return n
            try:
                self._write(ev)
                n += 1
            except Exception:
                # same contract as the worker: one unserializable event
                # skips, it does not break the drain (pump/close run in
                # finally blocks — raising here would mask the real
                # result or exception)
                self.write_errors += 1

    def _write(self, ev: dict) -> None:
        # one json.dumps + one write + one flush per event: after the
        # flush the line is in the OS page cache whole — a SIGKILL can
        # truncate at most the line currently being written
        line = json.dumps(ev)
        with self._wlock:
            if self._fh.closed:
                return
            self._fh.write(line + "\n")
            self._fh.flush()

    def close(self) -> None:
        """Stop the worker, drain everything queued, write one final
        snapshot + drop count, close the file."""
        if self._closed:
            return
        self._closed = True
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        self.pump()
        try:
            if self.registry is not None:
                self._write({"kind": "metrics", "t": self._now(),
                             "snapshot": self.registry.snapshot()})
            self._write({"kind": "telemetry_close", "t": self._now(),
                         "dropped": self.dropped,
                         "write_errors": self.write_errors})
        except Exception:
            self.write_errors += 1  # never raise out of a finally block
        with self._wlock:
            self._fh.close()

    def __enter__(self) -> "TelemetryExporter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


# --------------------------------------------------------------- HTTP plane
# /healthz overall verdict: DEAD only when EVERY replica is dead (a fleet
# with one live replica still serves — degraded is a routing concern, not
# an availability one); 503 only on DEAD so orchestrators restart the
# process exactly when it can no longer serve at all.
def _overall_health(states: Dict) -> str:
    vals = [str(v).lower() for v in states.values()]
    if vals and all(v == "dead" for v in vals):
        return "DEAD"
    if any(v != "healthy" for v in vals):
        return "DEGRADED"
    return "HEALTHY"


class TelemetryServer:
    """Embedded scrape endpoint: /metrics, /healthz, /flight.

    stdlib ThreadingHTTPServer on its own daemon thread — no framework,
    no dependency, good enough for a scraper hitting it a few times a
    second. `port=0` binds an ephemeral port (tests read `.port`).
    Handlers only READ (render_text snapshot, health callback, flight
    window), so they never contend with the serve loop beyond the
    registry's create-lock.
    """

    def __init__(self, *, registry: Optional[MetricsRegistry] = None,
                 health_fn: Optional[Callable[[], Dict]] = None,
                 flight_fn: Optional[Callable[[], dict]] = None,
                 healthz_fn: Optional[Callable[[], dict]] = None,
                 tenants_fn: Optional[Callable[[], dict]] = None,
                 port: int = 0, host: str = "127.0.0.1",
                 start: bool = True) -> None:
        # `registry` is duck-typed: anything with render_text() serves
        # /metrics (a MetricsRegistry, or a ScrapeFederator rolling a
        # whole fleet up). `healthz_fn`, when set, returns the FULL
        # /healthz body (the federated shape carries per-worker
        # heartbeat ages — richer than health_fn's flat state map);
        # the 503-on-DEAD contract is keyed off its "status" field.
        # `tenants_fn` serves the per-tenant QoS rollup (a
        # serve/fairshare.py TenantLedger.report, or a federator's
        # tenants() for the fleet view).
        self.registry = registry
        self.health_fn = health_fn
        self.flight_fn = flight_fn
        self.healthz_fn = healthz_fn
        self.tenants_fn = tenants_fn
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):  # no stderr spam per scrape
                pass

            def do_GET(self):
                try:
                    body, status, ctype = outer._route(self.path)
                except Exception as e:  # a broken callback must not
                    body = f"internal error: {e}".encode()
                    status, ctype = 500, "text/plain"  # kill the server
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self._server = ThreadingHTTPServer((host, port), Handler)
        self._server.daemon_threads = True
        self.host = host
        self.port = self._server.server_address[1]
        self._thread: Optional[threading.Thread] = None
        if start:
            self.start()

    def _route(self, path: str):
        path = path.split("?", 1)[0]
        if path == "/metrics":
            text = (self.registry.render_text()
                    if self.registry is not None else "")
            return text.encode(), 200, "text/plain; version=0.0.4"
        if path == "/healthz":
            if self.healthz_fn is not None:
                body_obj = self.healthz_fn()
                overall = str(body_obj.get("status", "")).upper()
                return (json.dumps(body_obj).encode(),
                        503 if overall == "DEAD" else 200,
                        "application/json")
            states = dict(self.health_fn()) if self.health_fn else {}
            overall = _overall_health(states)
            body = json.dumps({"status": overall, "replicas": states})
            return (body.encode(),
                    503 if overall == "DEAD" else 200,
                    "application/json")
        if path == "/flight":
            report = self.flight_fn() if self.flight_fn else {}
            return json.dumps(report).encode(), 200, "application/json"
        if path == "/tenants":
            report = self.tenants_fn() if self.tenants_fn else {}
            return json.dumps(report).encode(), 200, "application/json"
        return b"not found", 404, "text/plain"

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            kwargs={"poll_interval": 0.1},
            name="telemetry-http", daemon=True,
        )
        self._thread.start()

    def close(self) -> None:
        if self._thread is None:
            return
        self._server.shutdown()
        self._thread.join(timeout=5.0)
        self._server.server_close()
        self._thread = None

    def __enter__(self) -> "TelemetryServer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


# ------------------------------------------------------------ OTLP push
class OtlpPusher:
    """Live OTLP/HTTP egress: drain kept spans into batched
    ``ExportTraceServiceRequest`` payloads and POST them to a collector
    at `endpoint` (the /v1/traces URL) — Monarch-style push instead of
    the exit-time file `save_otlp` writes.

    Contracts, all inherited from this repo's existing planes:

    - **Never stalls serving.** A background daemon thread collects
      (TraceRecorder.drain_otlp) and delivers on its own cadence; the
      pending queue is BOUNDED (`max_pending` batches) and overflow
      drops the OLDEST batch, counted in ``otlp_batches_dropped_total``
      — dropped telemetry is a metric, stalled serving is an outage
      (the TelemetryExporter rule).
    - **At-least-once, deduped by batch id.** A batch stays pending
      until a POST SUCCEEDS, so a delivered-but-response-lost attempt
      is retried and arrives twice; every batch carries a stable
      ``ddp.push.batch_id`` resource attribute so the collector keeps
      the first copy and drops the rest. A SIGKILL therefore loses at
      most what was drained but never acknowledged — and each span
      lives in exactly ONE batch (the drain's seq watermark), so the
      deduped capture never holds a duplicate spanId.
    - **AlertSinks breaker.** Consecutive delivery failures back off on
      the utils/backoff.py schedule; at `max_failures` the endpoint is
      declared DEAD (``otlp_endpoint_dead`` gauge = 1) keeping only the
      single NEWEST batch, and a half-open probe every
      `probe_cooldown_s` retries it — success closes the breaker, a
      failed probe re-arms the FIXED cooldown (never exponential: the
      probe cadence is the detection latency for recovery).

    `post` / `clock` are injectable (tests drive `pump(now)` with a
    FakeClock and a fake transport); the default transport is the
    shared utils/http_post.py helper the SLO webhook sink uses.
    """

    def __init__(self, endpoint: str, recorder, *,
                 registry: Optional[MetricsRegistry] = None,
                 clock=None, interval_s: float = 0.5,
                 timeout_s: float = 3.0, max_pending: int = 64,
                 max_failures: int = 5, base_s: float = 0.5,
                 max_s: float = 30.0, probe_cooldown_s: float = 30.0,
                 seed: int = 0, service_name: str = "ddp-serve",
                 run_token: Optional[str] = None, post=None,
                 start: bool = True) -> None:
        if max_pending < 1:
            raise ValueError("max_pending must be positive")
        from ddp_practice_tpu.utils.http_post import post_json

        self.endpoint = endpoint
        self.recorder = recorder
        self.interval_s = interval_s
        self.timeout_s = timeout_s
        self.max_pending = max_pending
        self.max_failures = max_failures
        self.base_s = base_s
        self.max_s = max_s
        self.probe_cooldown_s = probe_cooldown_s
        self.seed = seed
        self.service_name = service_name
        self._post = post if post is not None else post_json
        self._now = _resolve_clock(clock)
        # batch identity: unique per pusher incarnation (a restarted
        # process is a new producer) + a per-batch sequence — the dedup
        # key the collector keeps first-writer-wins on
        if run_token is None:
            import os
            import zlib as _zlib

            run_token = "%08x" % (_zlib.crc32(
                f"{os.getpid()}:{time.monotonic_ns()}".encode()))
        self.run_token = run_token
        self._batch_seq = 0
        self._pending: deque = deque()
        self._lock = threading.Lock()
        self.failures = 0          # consecutive delivery failures
        self.dead = False
        self._next_at = 0.0        # earliest next delivery attempt
        self.batches_sent = 0
        self.spans_sent = 0
        self.batches_dropped = 0
        self.post_failures = 0
        r = registry
        self._c_sent = (r.counter("otlp_batches_sent_total")
                        if r is not None else None)
        self._c_spans = (r.counter("otlp_spans_sent_total")
                         if r is not None else None)
        self._c_dropped = (r.counter("otlp_batches_dropped_total")
                           if r is not None else None)
        self._c_failures = (r.counter("otlp_post_failures_total")
                            if r is not None else None)
        self._g_dead = (r.gauge("otlp_endpoint_dead")
                        if r is not None else None)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        if start:
            self.start()

    # --------------------------------------------------------- produce
    @staticmethod
    def _span_count(export: dict) -> int:
        return sum(len(ss.get("spans", ()))
                   for rs in export.get("resourceSpans", ())
                   for ss in rs.get("scopeSpans", ()))

    def _drop_batch(self, batch: dict) -> None:
        self.batches_dropped += 1
        if self._c_dropped is not None:
            self._c_dropped.inc()

    def collect(self) -> int:
        """Drain newly-kept spans into one pending batch; returns the
        spans batched (0 when the recorder had nothing new)."""
        export = self.recorder.drain_otlp(service_name=self.service_name)
        if export is None:
            return 0
        with self._lock:
            self._batch_seq += 1
            bid = f"{self.run_token}-{self._batch_seq}"
            res = export["resourceSpans"][0]["resource"]["attributes"]
            res.append({"key": "ddp.push.batch_id",
                        "value": {"stringValue": bid}})
            res.append({"key": "ddp.push.seq",
                        "value": {"intValue": str(self._batch_seq)}})
            n = self._span_count(export)
            batch = {"id": bid, "export": export, "spans": n}
            if self.dead:
                # dead endpoint holds exactly ONE newest batch (the
                # half-open probe's payload) — the AlertSinks contract
                while self._pending:
                    self._drop_batch(self._pending.popleft())
            elif len(self._pending) >= self.max_pending:
                self._drop_batch(self._pending.popleft())
            self._pending.append(batch)
        return n

    # --------------------------------------------------------- deliver
    def _try_post(self, batch: dict) -> bool:
        try:
            return bool(self._post(self.endpoint, batch["export"],
                                   timeout_s=self.timeout_s))
        except Exception:
            return False

    def _book_sent(self, batch: dict) -> None:
        self.batches_sent += 1
        self.spans_sent += batch["spans"]
        if self._c_sent is not None:
            self._c_sent.inc()
        if self._c_spans is not None:
            self._c_spans.inc(batch["spans"])

    def flush(self, now: Optional[float] = None) -> int:
        """Deliver pending batches in order (oldest first); returns
        spans delivered. Honors the backoff/breaker clock — a call
        before `_next_at` is a no-op, not a hammer."""
        from ddp_practice_tpu.utils.backoff import backoff_delay

        if now is None:
            now = self._now()
        sent = 0
        with self._lock:
            if not self._pending or now < self._next_at:
                return 0
            if self.dead:
                # half-open probe with the single kept batch
                batch = self._pending[0]
                if self._try_post(batch):
                    self._pending.popleft()
                    self._book_sent(batch)
                    sent += batch["spans"]
                    self.dead = False
                    self.failures = 0
                    self._next_at = now
                    if self._g_dead is not None:
                        self._g_dead.set(0)
                else:
                    # fixed cooldown, never exponential: probe cadence
                    # IS the recovery-detection latency
                    self._next_at = now + self.probe_cooldown_s
                return sent
            while self._pending:
                batch = self._pending[0]
                if self._try_post(batch):
                    self._pending.popleft()
                    self._book_sent(batch)
                    sent += batch["spans"]
                    self.failures = 0
                    continue
                self.failures += 1
                self.post_failures += 1
                if self._c_failures is not None:
                    self._c_failures.inc()
                if self.failures >= self.max_failures:
                    self.dead = True
                    if self._g_dead is not None:
                        self._g_dead.set(1)
                    # keep the NEWEST batch as the probe payload
                    while len(self._pending) > 1:
                        self._drop_batch(self._pending.popleft())
                    self._next_at = now + self.probe_cooldown_s
                else:
                    self._next_at = now + backoff_delay(
                        self.failures - 1, base_s=self.base_s,
                        max_s=self.max_s, seed=self.seed)
                break
        return sent

    def pump(self, now: Optional[float] = None) -> int:
        """One synchronous collect+flush round (tests run start=False);
        returns spans delivered."""
        self.collect()
        return self.flush(now)

    @property
    def pending_batches(self) -> int:
        with self._lock:
            return len(self._pending)

    # ---------------------------------------------------------- thread
    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._run, name="otlp-pusher", daemon=True)
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self.pump()
            except Exception:
                # delivery machinery must never take the process down;
                # the failure accounting happens inside flush
                pass
            self._stop.wait(self.interval_s)

    def close(self) -> None:
        """Stop the thread and make one final best-effort delivery
        round (a live endpoint gets everything; a dead one keeps its
        breaker state — close is not a license to hammer)."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        try:
            self.pump()
        except Exception:
            pass

    def __enter__(self) -> "OtlpPusher":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class StubOtlpCollector:
    """Stdlib OTLP/HTTP collector for tests and the bench harness: a
    ThreadingHTTPServer accepting ``POST /v1/traces``, deduping whole
    batches by their ``ddp.push.batch_id`` resource attribute (keep
    first — the at-least-once receiver's half of the pusher contract)
    and optionally writing EVERY arriving payload (duplicates included)
    as one JSON file per POST into `capture_dir`, the directory
    tools/check_otlp.py validates in push-capture mode.

    Fault injection for the retry/dedup tests:

    - `fail_next(n)`: the next n POSTs answer 503 WITHOUT capturing —
      a down collector; the pusher backs off and retries.
    - `drop_response_next(n)`: the next n POSTs capture the batch but
      answer 500 — delivered-but-response-lost, the case that makes
      at-least-once produce duplicates the dedup must absorb.

    The intake path is deliberately LAZY: a POST only banks the raw
    body (and appends it to `capture_dir` verbatim); parsing, batch-id
    dedup and span counting happen on first ACCESS of `batches`/`seen`/
    `exports`/`spans`/`duplicates`. The stub shares a core (and a GIL)
    with the serve loop it instruments in the bench — a real collector
    is another machine, so any in-process json.loads during the timed
    window would bill the push arm for work the real deployment never
    pays.
    """

    def __init__(self, capture_dir: Optional[str] = None, *,
                 host: str = "127.0.0.1", port: int = 0,
                 start: bool = True) -> None:
        import os

        self.capture_dir = capture_dir
        if capture_dir is not None:
            os.makedirs(capture_dir, exist_ok=True)
        self._lock = threading.Lock()
        self._raw: list = []        # undigested POST bodies (bytes)
        self._batches: list = []    # batch ids in arrival order, dupes kept
        self._seen: set = set()     # deduped batch ids
        self._exports: list = []    # (batch_id, export) after dedup
        self._spans = 0             # span count after dedup
        self._duplicates = 0
        self.rejected = 0           # fail_next 503s served
        self._fail = 0
        self._drop_response = 0
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):
                pass

            def do_POST(self):
                if self.path.split("?", 1)[0] != "/v1/traces":
                    status = 404
                else:
                    length = int(self.headers.get("Content-Length", 0))
                    body = self.rfile.read(length)
                    status = outer._on_post(body)
                self.send_response(status)
                self.send_header("Content-Length", "0")
                self.end_headers()

        self._server = ThreadingHTTPServer((host, port), Handler)
        self._server.daemon_threads = True
        self.host = host
        self.port = self._server.server_address[1]
        self._thread: Optional[threading.Thread] = None
        self._capture_idx = 0
        if start:
            self.start()

    @property
    def endpoint(self) -> str:
        return f"http://{self.host}:{self.port}/v1/traces"

    # --------------------------------------------------- fault injection
    def fail_next(self, n: int) -> None:
        with self._lock:
            self._fail = n

    def drop_response_next(self, n: int) -> None:
        with self._lock:
            self._drop_response = n

    # ------------------------------------------------------- the intake
    @staticmethod
    def _batch_id(export: dict) -> Optional[str]:
        for rs in export.get("resourceSpans", ()):
            for kv in rs.get("resource", {}).get("attributes", ()):
                if kv.get("key") == "ddp.push.batch_id":
                    return kv.get("value", {}).get("stringValue")
        return None

    def _on_post(self, body: bytes) -> int:
        with self._lock:
            if self._fail > 0:
                self._fail -= 1
                self.rejected += 1
                return 503
            if not body.lstrip()[:1] == b"{":
                # the one shape check cheap enough for the hot path;
                # anything subtler surfaces at digest time
                return 400
            self._raw.append(body)
            if self.capture_dir is not None:
                import os

                path = os.path.join(
                    self.capture_dir,
                    f"batch-{self._capture_idx:04d}.json")
                self._capture_idx += 1
                with open(path, "wb") as f:
                    f.write(body)
            if self._drop_response > 0:
                # the batch IS captured — only the acknowledgement is
                # lost, so the client retries and the dedup absorbs it
                self._drop_response -= 1
                return 500
            return 200

    def _digest(self) -> None:
        """Parse and dedup every banked body (caller holds no lock)."""
        with self._lock:
            raw, self._raw = self._raw, []
            for body in raw:
                try:
                    export = json.loads(body)
                except ValueError:
                    continue
                bid = self._batch_id(export)
                self._batches.append(bid)
                if bid is not None and bid in self._seen:
                    self._duplicates += 1
                else:
                    if bid is not None:
                        self._seen.add(bid)
                    self._exports.append((bid, export))
                    self._spans += OtlpPusher._span_count(export)

    @property
    def batches(self) -> list:
        self._digest()
        return self._batches

    @property
    def seen(self) -> set:
        self._digest()
        return self._seen

    @property
    def exports(self) -> list:
        self._digest()
        return self._exports

    @property
    def spans(self) -> int:
        self._digest()
        return self._spans

    @property
    def duplicates(self) -> int:
        self._digest()
        return self._duplicates

    def span_ids(self) -> set:
        """Every spanId in the deduped capture (the completeness check
        the kill/recover test asserts against the recorder's export)."""
        out = set()
        for _, export in self.exports:
            for rs in export.get("resourceSpans", ()):
                for ss in rs.get("scopeSpans", ()):
                    for sp in ss.get("spans", ()):
                        out.add(sp.get("spanId"))
        return out

    # --------------------------------------------------------- lifecycle
    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            kwargs={"poll_interval": 0.1},
            name="otlp-collector", daemon=True,
        )
        self._thread.start()

    def close(self) -> None:
        if self._thread is None:
            return
        self._server.shutdown()
        self._thread.join(timeout=5.0)
        self._server.server_close()
        self._thread = None

    def __enter__(self) -> "StubOtlpCollector":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


# --------------------------------------------------------- fleet federation
def _relabel_metric_line(line: str, extra: str) -> str:
    """Inject `extra` (e.g. worker="0") as the FIRST label of one
    Prometheus exposition line; comments/blank lines pass through. The
    value is everything after the last space (a float, never spaced),
    so escaped label values cannot confuse the split. An OpenMetrics
    exemplar section (`value # {trace_id="..."} exemplar_value`) is
    split off first and re-attached verbatim — the naive last-space
    split would otherwise label the exemplar value as the sample."""
    if not line or line.startswith("#"):
        return line
    sample, sep, exemplar = line.partition(" # ")
    head, _, val = sample.rpartition(" ")
    if not head:
        return line
    if "{" in head:
        name, rest = head.split("{", 1)
        out = f"{name}{{{extra},{rest} {val}"
    else:
        out = f"{head}{{{extra}}} {val}"
    return out + sep + exemplar


class ScrapeFederator:
    """Roll N workers' /metrics + /healthz into ONE fleet registry.

    `targets_fn()` describes the fleet (serve/supervisor.py
    `fleet_targets`): per worker id, where its TelemetryServer lives
    (host/port — None while the worker is down), its pid, supervisor
    state, restart count, and heartbeat age. Scrapes happen at READ
    time (a federated /metrics GET fans out to the live workers), so
    the federator holds no thread and no staleness of its own beyond
    the per-scrape timeout.

    Duck-types both TelemetryServer hooks: `render_text()` (pass it AS
    the server's `registry`) rewrites every worker metric line with a
    ``worker="N"`` label and prepends fleet-level series
    (``fleet_worker_up`` / ``fleet_heartbeat_age_s`` /
    ``fleet_worker_restarts_total``); `healthz()` (pass as
    `healthz_fn`) renders the fleet verdict tools/check_fleet.py
    judges: DEAD only when every worker is down, per-worker status
    dead / stale / healthy with heartbeat ages attached.
    """

    def __init__(self, targets_fn: Callable[[], Dict], *,
                 timeout_s: float = 1.0,
                 stale_after_s: float = 5.0,
                 autoscaler_fn: Optional[Callable[[], dict]] = None) -> None:
        self.targets_fn = targets_fn
        self.timeout_s = timeout_s
        self.stale_after_s = stale_after_s
        # optional serve/autoscaler.py Autoscaler.snapshot: when set,
        # /healthz carries the controller's state block (size/min/max,
        # standby depth, last scale event) for tools/check_fleet.py
        self.autoscaler_fn = autoscaler_fn

    def _get(self, host: str, port: int, path: str) -> Optional[str]:
        import http.client

        try:
            conn = http.client.HTTPConnection(
                host, port, timeout=self.timeout_s
            )
            conn.request("GET", path)
            body = conn.getresponse().read().decode("utf-8", "replace")
            conn.close()
            return body
        except Exception:
            return None  # a dead worker is a verdict, not a crash

    def _get_many(self, targets: Dict, path: str) -> Dict:
        """Scrape every up-target CONCURRENTLY (one thread each, joined
        on a shared deadline): a stalled worker — the SIGSTOP chaos
        case, which still counts as `up` by waitpid — must cost one
        timeout for the whole fan-out, not one timeout per remaining
        worker serially inside the scrape handler."""
        results: Dict = {}
        threads = []
        for wid, t in targets.items():
            if not (bool(t.get("up")) and t.get("port") is not None):
                continue

            def fetch(wid=wid, t=t):
                results[wid] = self._get(
                    t.get("host", "127.0.0.1"), t["port"], path
                )

            th = threading.Thread(target=fetch, daemon=True,
                                  name=f"scrape-w{wid}")
            th.start()
            threads.append(th)
        deadline = time.monotonic() + self.timeout_s + 0.5
        for th in threads:
            th.join(timeout=max(0.0, deadline - time.monotonic()))
        return results

    # ------------------------------------------------ /metrics rollup
    def render_text(self) -> str:
        targets = self.targets_fn()
        scraped = self._get_many(targets, "/metrics")
        out = []
        for wid in sorted(targets):
            t = targets[wid]
            extra = f'worker="{wid}"'
            up = bool(t.get("up")) and t.get("port") is not None
            out.append(f"fleet_worker_up{{{extra}}} {1 if up else 0}")
            hb = t.get("heartbeat_age_s")
            if hb is not None:
                out.append(f"fleet_heartbeat_age_s{{{extra}}} {hb}")
            out.append(
                f"fleet_worker_restarts_total{{{extra}}} "
                f"{t.get('restarts', 0)}"
            )
            kv = t.get("kv")
            if kv:
                # heartbeat-carried KV/radix summary -> per-worker
                # gauges (no extra scrape: these rode the stats frames)
                out.append(f"fleet_kv_blocks_used{{{extra}}} "
                           f"{kv.get('blocks_used', 0)}")
                out.append(f"fleet_kv_evictable{{{extra}}} "
                           f"{kv.get('evictable', 0)}")
                out.append(f"fleet_prefix_hit_rate{{{extra}}} "
                           f"{kv.get('prefix_hit_rate', 0.0)}")
            if not up:
                continue
            text = scraped.get(wid)
            if text is None:
                out.append(f"fleet_scrape_failed{{{extra}}} 1")
                continue
            for line in text.splitlines():
                if line and not line.startswith("#"):
                    out.append(_relabel_metric_line(line, extra))
        return "\n".join(out) + "\n"

    # -------------------------------------------------- /flight rollup
    def flight(self) -> dict:
        """Fleet-wide latency view: every worker's /flight report,
        plus TRUE fleet percentiles recomputed from the POOLED raw
        sample tails the workers ship (`FlightStats` "samples") through
        the shared percentile_summary — a percentile of per-worker
        percentiles would be a different (wrong) number. Dead workers
        are absent; the rollup is over who answered."""
        targets = self.targets_fn()
        scraped = self._get_many(targets, "/flight")
        workers: Dict[str, dict] = {}
        pooled: Dict[str, list] = {}
        exemplars: Dict[str, dict] = {}
        for wid in sorted(targets):
            body = scraped.get(wid)
            if body is None:
                continue
            try:
                rep = json.loads(body)
            except ValueError:
                continue
            samples = rep.pop("samples", {}) or {}
            for key, vals in samples.items():
                if isinstance(vals, list):
                    pooled.setdefault(key, []).extend(vals)
            for key, ex in (rep.get("exemplars") or {}).items():
                # fleet p99 exemplar: keep the WORST per key — the
                # trace an operator wants is the slowest one anywhere
                cur = exemplars.get(key)
                if cur is None or ex.get("value", 0) > cur.get("value", 0):
                    exemplars[key] = dict(ex, worker=str(wid))
            workers[str(wid)] = rep
        fleet = {
            key: percentile_summary(vals) for key, vals in pooled.items()
        }
        fleet["window"] = sum(
            w.get("window", 0) for w in workers.values()
        )
        if exemplars:
            fleet["exemplars"] = exemplars
        return {"fleet": fleet, "workers": workers}

    # -------------------------------------------------- /tenants rollup
    def tenants(self) -> dict:
        """Fleet-wide per-tenant QoS rollup: every worker's /tenants
        body folded through serve/fairshare.federate_tenant_reports —
        counters summed, raw latency tails pooled and re-summarized
        (the /flight rule: never percentiles of percentiles), shares
        and Jain's index re-derived over the SUMMED service. Dead
        workers are absent; the rollup is over who answered."""
        from ddp_practice_tpu.serve.fairshare import (
            federate_tenant_reports,
        )

        targets = self.targets_fn()
        scraped = self._get_many(targets, "/tenants")
        reports = []
        workers: Dict[str, dict] = {}
        for wid in sorted(targets):
            body = scraped.get(wid)
            if body is None:
                continue
            try:
                rep = json.loads(body)
            except ValueError:
                continue
            if rep:
                reports.append(rep)
                workers[str(wid)] = {
                    "tenants": sorted((rep.get("tenants") or {})),
                    "fairness_index": rep.get("fairness_index"),
                }
        out = federate_tenant_reports(reports)
        out["fleet"] = True
        out["workers"] = workers
        return out

    # ------------------------------------------------ /healthz verdict
    def healthz(self) -> dict:
        targets = self.targets_fn()
        scraped = self._get_many(targets, "/healthz")
        workers: Dict[str, dict] = {}
        for wid in sorted(targets):
            t = targets[wid]
            up = bool(t.get("up")) and t.get("port") is not None
            hb = t.get("heartbeat_age_s")
            inner = None
            if up:
                body = scraped.get(wid)
                if body is not None:
                    try:
                        inner = json.loads(body)
                    except ValueError:
                        inner = None
            if not up or inner is None:
                status = "dead"
            elif hb is not None and hb > self.stale_after_s:
                # answering scrapes but the serving heartbeat is old:
                # the router can't dispatch to it — degraded, loudly
                status = "stale"
            else:
                status = str(inner.get("status", "dead")).lower()
                status = {"healthy": "healthy",
                          "degraded": "degraded"}.get(status, "dead")
            entry = {
                "status": status,
                "pid": t.get("pid"),
                "state": t.get("state"),
                # the flag tools/check_fleet.py skips on: a draining
                # worker going quiet is the drain working, not a page
                "draining": bool(t.get("draining"))
                or t.get("state") == "draining",
                "restarts": t.get("restarts", 0),
                "heartbeat_age_s": hb,
                "replicas": (inner or {}).get("replicas", {}),
            }
            if t.get("kv") is not None:
                entry["kv"] = t["kv"]
            workers[str(wid)] = entry
        # a DRAINING worker is leaving on purpose: its refusals must
        # not read as fleet degradation, so it is excluded from the
        # overall verdict (but stays listed, status annotated)
        voting = [
            w["status"] for w in workers.values()
            if not w.get("draining")
        ]
        vals = voting if voting else [w["status"]
                                      for w in workers.values()]
        if vals and all(v == "dead" for v in vals):
            overall = "DEAD"
        elif not vals or any(v != "healthy" for v in vals):
            overall = "DEGRADED" if vals else "DEAD"
        else:
            overall = "HEALTHY"
        out = {"status": overall, "fleet": True, "workers": workers}
        if self.autoscaler_fn is not None:
            try:
                out["autoscaler"] = self.autoscaler_fn()
            except Exception:
                out["autoscaler"] = None
        return out


# ------------------------------------------------------- train-side rolling
class StepAnomalyDetector:
    """Rolling median/MAD straggler detector for step times.

    An anomaly is a step SLOWER than median + threshold * scale, where
    scale = max(MAD, rel_floor * median): the MAD term adapts to real
    jitter, the relative floor keeps a near-constant step-time history
    (FakeClock, or a well-behaved TPU) from flagging microscopic noise
    once MAD collapses toward zero. Fast steps are never anomalies —
    the detector hunts stragglers, not luck.
    """

    def __init__(self, window: int = 64, threshold: float = 5.0,
                 min_samples: int = 8, rel_floor: float = 0.05) -> None:
        if min_samples < 2:
            raise ValueError("min_samples must be >= 2")
        self._times: deque = deque(maxlen=window)
        self.threshold = threshold
        self.min_samples = min_samples
        self.rel_floor = rel_floor
        self.anomalies = 0

    def observe(self, step_s: float) -> bool:
        """Record one step time; True when it is a straggler vs the
        window BEFORE it (the anomaly is judged against history, then
        joins it — one bad step inflates no baseline)."""
        anomalous = False
        if len(self._times) >= self.min_samples:
            med = median(self._times)
            mad = median([abs(x - med) for x in self._times])
            scale = max(mad, self.rel_floor * med, 1e-9)
            anomalous = (step_s - med) > self.threshold * scale
        self._times.append(step_s)
        if anomalous:
            self.anomalies += 1
        return anomalous
