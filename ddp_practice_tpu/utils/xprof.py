"""XProf trace analysis without TensorBoard: per-op device-time summaries.

`utils/profiling.py` captures traces (`--profile_dir`); this module reads
them back. The usual consumer is TensorBoard's profile plugin, but this
build environment has no xplane proto bindings (tensorboard_plugin_profile
ships without xplane_pb2 here) and no browser — so this parses the
`.xplane.pb` by structure instead: `protoc --decode_raw` (protoc is in
the image) emits a field-number tree, and the XPlane schema is stable
enough to read by field ids. The fields used (verified against traces
from this JAX/libtpu build):

    XSpace.planes = 1;  XPlane.name = 2, .lines = 3,
    .event_metadata = 4 (map entry: key=1, value=2 {id=1, name=2,
    stats=5}), .stat_metadata = 5;  XLine.events = 4;
    XEvent.metadata_id = 1, .stats = 4 (XStat.metadata_id = 1,
    int64=3, uint64=4, str ref=5)
    stat-metadata names: 2=device_duration_ps, 24=hlo_category,
    27=flops, 31=bytes_accessed (ids resolved by NAME, not hardcoded)

Every ResNet-50 / decode / LM profile analysis in BENCHMARKS.md came out
of this parser (the per-category table: device ms, share, achieved
bytes/s from XLA's bytes_accessed).

CLI:  python -m ddp_practice_tpu.utils.xprof <trace_dir_or_xplane.pb>
"""

from __future__ import annotations

import collections
import os
import re
import subprocess
import sys
from typing import Optional

_BLOCK_RE = re.compile(r"(\d+) \{$")
_FIELD_RE = re.compile(r"(\d+): (.*)$")


def _parse_decoded(text: str):
    """decode_raw output -> nested {field_number: [value_or_subdict]}."""
    lines = text.splitlines()
    n = len(lines)

    def block(i):
        fields = collections.defaultdict(list)
        while i < n:
            s = lines[i].strip()
            if s == "}":
                return fields, i + 1
            m = _BLOCK_RE.match(s)
            if m:
                sub, i = block(i + 1)
                fields[int(m.group(1))].append(sub)
                continue
            m = _FIELD_RE.match(s)
            if m:
                fields[int(m.group(1))].append(m.group(2))
                i += 1
                continue
            i += 1
        return fields, i

    i = 0
    planes = []
    while i < n:
        if lines[i].strip() == "1 {":
            blk, i = block(i + 1)
            planes.append(blk)
        else:
            i += 1
    return planes


def _find_xplane(path: str) -> str:
    if os.path.isfile(path):
        return path
    hits = []
    for root, _, files in os.walk(path):
        hits += [os.path.join(root, f) for f in files
                 if f.endswith(".xplane.pb")]
    if not hits:
        raise FileNotFoundError(f"no .xplane.pb under {path!r}")
    return max(hits, key=os.path.getmtime)  # newest capture


def op_summary(path: str, *, device_substr: str = "TPU",
               line_substr: str = "XLA Ops") -> dict:
    """Aggregate a trace: device time/bytes per HLO category and per op.

    Returns {"total_ps", "categories": {cat: {"ps", "count", "bytes"}},
    "ops": {(cat, name): ps}}. `ps` are device picoseconds summed over
    every captured execution (divide by your step count for ms/step).
    """
    xplane = _find_xplane(path)
    with open(xplane, "rb") as f:
        decoded = subprocess.run(
            ["protoc", "--decode_raw"],
            stdin=f,
            capture_output=True,
            check=True,
        ).stdout.decode("utf-8", errors="replace")
    return op_summary_text(decoded, device_substr=device_substr,
                           line_substr=line_substr)


def op_summary_text(decoded: str, *, device_substr: str = "TPU",
                    line_substr: str = "XLA Ops") -> dict:
    """`op_summary` over already-decoded `protoc --decode_raw` text.

    The seam that makes the field-id parser testable without protoc or
    a live capture: tests/data/xplane_decode_raw.txt is a checked-in
    decode_raw snapshot pinned against this function directly
    (tests/test_xprof.py), so schema drift in the parser fails in tier-1
    even where the protoc round-trip test has to skip.
    """
    planes = _parse_decoded(decoded)

    def text(v):
        # decode_raw heuristically prints some short strings as nested
        # messages; anything non-string becomes a best-effort repr
        return v.strip('"') if isinstance(v, str) else str(v)

    cats: dict = collections.defaultdict(
        lambda: {"ps": 0, "count": 0, "bytes": 0}
    )
    ops = collections.Counter()
    matched = 0
    for p in planes:
        if device_substr not in text(p.get(2, ["?"])[0]):
            continue
        # stat-metadata ids resolved by name (ids vary across builds)
        sid = {}
        for m in p.get(5, []):
            sub = m.get(2, [None])[0]
            if isinstance(sub, dict):
                sid[text(sub.get(2, ["?"])[0])] = str(m.get(1, ["?"])[0])
        id_dur = sid.get("device_duration_ps")
        id_cat = sid.get("hlo_category")
        id_bytes = sid.get("bytes_accessed")
        if id_dur is None:
            raise ValueError(
                f"plane {text(p.get(2, ['?'])[0])!r} has no "
                "device_duration_ps stat metadata — xplane schema drift? "
                f"(known stats: {sorted(sid)[:12]})"
            )
        emeta = {}
        for m in p.get(4, []):
            sub = m.get(2, [None])[0]
            if not isinstance(sub, dict):
                continue
            nm = text(sub.get(2, ["?"])[0])
            cat, bts = "?", 0
            for st in sub.get(5, []):
                s_id = st.get(1, ["?"])[0]
                if s_id == id_cat:
                    cat = text(st.get(5, ['"?"'])[0])
                elif s_id == id_bytes:
                    bts = int(st.get(4, ["0"])[0])
            emeta[str(m.get(1, ["?"])[0])] = (nm, cat, bts)
        for line in p.get(3, []):
            if line_substr not in text(line.get(2, ["?"])[0]):
                continue
            matched += 1
            for ev in line.get(4, []):
                nm, cat, bts = emeta.get(
                    str(ev.get(1, ["0"])[0]), ("?", "?", 0)
                )
                if nm.startswith("%while"):
                    continue  # container: children are recorded separately
                d = 0
                for st in ev.get(4, []):
                    if st.get(1, ["?"])[0] == id_dur:
                        d = int(st.get(3, ["0"])[0])
                cats[cat]["ps"] += d
                cats[cat]["count"] += 1
                cats[cat]["bytes"] += bts
                ops[(cat, nm.split(" = ")[0])] += d
    if not matched:
        raise ValueError(
            f"no plane matching {device_substr!r} with line {line_substr!r}"
        )
    return {
        "planes": matched,
        "total_ps": sum(c["ps"] for c in cats.values()),
        "categories": dict(cats),
        "ops": dict(ops),
    }


def print_summary(path: str, *, steps: int = 1, top: int = 12,
                  out=None) -> None:
    """Human-readable per-category + top-op table (the BENCHMARKS.md
    format). `steps` divides totals into per-step numbers."""
    out = out or sys.stdout
    s = op_summary(path)
    tot = s["total_ps"]
    print(f"device op time: {tot / steps / 1e12 * 1e3:.2f} ms/step "
          f"({steps} step(s))", file=out)
    for cat, c in sorted(s["categories"].items(), key=lambda kv: -kv[1]["ps"]):
        if not c["ps"]:
            continue
        gbps = c["bytes"] / (c["ps"] / 1e12) / 1e9
        print(f"  {c['ps'] / tot * 100:5.1f}%  "
              f"{c['ps'] / steps / 1e12 * 1e3:8.2f} ms/step  "
              f"x{c['count'] // steps:6d}  {cat:28s} {gbps:7.0f} GB/s",
              file=out)
    print(f"top {top} ops:", file=out)
    for (cat, nm), d in sorted(s["ops"].items(), key=lambda kv: -kv[1])[:top]:
        print(f"  {d / steps / 1e12 * 1e3:7.3f} ms/step  [{cat[:18]}] "
              f"{nm[:58]}", file=out)


def main(argv=None) -> int:
    args = argv if argv is not None else sys.argv[1:]
    if not args or args[0] in ("-h", "--help"):
        print(__doc__)
        return 0
    steps = int(args[1]) if len(args) > 1 else 1
    print_summary(args[0], steps=steps)
    return 0


if __name__ == "__main__":
    sys.exit(main())
