"""Process-local metrics registry: counters, gauges, histograms.

The training loop logs scalar step metrics straight to its JSONL file
(train/loop.py --metrics_file); serving needs something stateful — TTFT
and per-token-latency distributions, queue-depth gauges, token counters —
that accumulates across thousands of engine steps and renders one
snapshot line. This registry is that accumulator: pure host-side Python
(nothing here touches jax), cheap enough to update inside the serve loop,
and snapshot() flattens to a plain dict so the process-0-gated emitter
(utils/logging.py emit_metrics) and the bench reports can both consume it.

Percentiles come from a bounded reservoir: a histogram keeps the most
recent `max_samples` observations (running count/sum stay exact), so a
long-lived server's memory stays O(1) while p50/p99 track the current
traffic rather than the whole history.

Two render paths: `snapshot()` flattens to a dict (JSONL emit, bench
reports), `render_text()` renders Prometheus text exposition — `labelled`
names become `name{k="v"}` with escaped, sorted label values, histograms
become summaries (`name{quantile="0.5"}` + `_count`/`_sum`) — so any
standard scraper can consume the registry without an adapter.
"""

from __future__ import annotations

import logging
import threading
from typing import Dict, Iterable, Optional, Sequence, Tuple

log = logging.getLogger(__name__)


def _escape_label_value(v: str) -> str:
    """Prometheus label-value escaping: backslash, quote, newline."""
    return (
        str(v).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")
    )


def _split_labelled(name: str):
    """Parse a `labelled()` registry key back into (base, [(k, v)...]).

    Inverse of `labelled` for the exposition renderer. Values are the
    raw strings labelled() embedded; a value containing "," or "=" is
    not representable in this key format (labelled's documented limit).
    """
    if not name.endswith("}") or "{" not in name:
        return name, []
    base, _, inner = name[:-1].partition("{")
    pairs = []
    for item in inner.split(","):
        k, _, v = item.partition("=")
        pairs.append((k, v))
    return base, pairs


def _render_labels(pairs) -> str:
    """`[(k, v)...]` -> `{k="v",...}` sorted by key, values escaped."""
    if not pairs:
        return ""
    inner = ",".join(
        f'{k}="{_escape_label_value(v)}"' for k, v in sorted(pairs)
    )
    return "{" + inner + "}"


def _fmt_value(v: float) -> str:
    f = float(v)
    if f.is_integer() and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


# ---------------------------------------------------- label cardinality guard
# A labelled() family's size is bounded by the number of DISTINCT label
# values it has ever seen; a label fed from unbounded input (request ids,
# error strings, hostnames) would grow the registry — and every scrape —
# without limit over a long run. The guard caps distinct values per
# (metric name, label key): the first `_LABEL_LIMIT` values pass through,
# everything after lands in the shared "other" bucket, counted in
# `metrics_label_overflow_total` (default registry) and warned once per
# family. Process-wide on purpose: labelled() is a pure key-maker used
# against many registries, and the blast radius of a high-cardinality
# label is the process, not one registry.
_LABEL_LIMIT = 64
_LABEL_OVERFLOW = "other"
_label_values: Dict[Tuple[str, str], set] = {}
_label_warned: set = set()
_label_lock = threading.Lock()


def set_label_limit(n: int) -> int:
    """Set the per-(metric, label) distinct-value cap; returns the old
    cap (so tests can restore it)."""
    global _LABEL_LIMIT
    if n < 1:
        raise ValueError("label limit must be positive")
    old, _LABEL_LIMIT = _LABEL_LIMIT, n
    return old


def reset_label_guard() -> None:
    """Forget seen label values (tests; a production process never does)."""
    with _label_lock:
        _label_values.clear()
        _label_warned.clear()


def labelled(name: str, **labels) -> str:
    """Render a labelled metric name: ``labelled("x", r="a")`` -> ``x{r=a}``.

    The registry keys metrics by flat string name; per-replica and
    per-reason families (router breaker state, sheds-by-reason) need one
    metric per label value. Labels render sorted, so the same label set
    always produces the same name however the caller spells the kwargs.
    Distinct values per (name, key) are capped (see the guard above):
    past the cap a value renders as "other" instead of minting a new
    registry entry.
    """
    if not labels:
        return name
    parts = []
    for k in sorted(labels):
        v = str(labels[k])
        with _label_lock:
            seen = _label_values.setdefault((name, k), set())
            if v not in seen:
                if len(seen) >= _LABEL_LIMIT:
                    if (name, k) not in _label_warned:
                        _label_warned.add((name, k))
                        log.warning(
                            "metric %s label %s exceeded %d distinct values"
                            " — overflow bucketed to %r",
                            name, k, _LABEL_LIMIT, _LABEL_OVERFLOW,
                        )
                    v = _LABEL_OVERFLOW
                    overflow = True
                else:
                    seen.add(v)
                    overflow = False
            else:
                overflow = False
        if overflow:
            default_registry().counter(
                "metrics_label_overflow_total"
            ).inc()
        parts.append(f"{k}={v}")
    return f"{name}{{{','.join(parts)}}}"


class Counter:
    """Monotonic count (requests served, tokens emitted, sheds)."""

    def __init__(self) -> None:
        self.value = 0.0

    def inc(self, n: float = 1.0) -> None:
        if n < 0:
            raise ValueError("counters only go up — use a Gauge")
        self.value += n


class Gauge:
    """Last-write-wins level (queue depth, slot occupancy)."""

    def __init__(self) -> None:
        self.value = 0.0

    def set(self, v: float) -> None:
        self.value = float(v)


# exemplar bucket bounds: latency-shaped (seconds), OpenMetrics-style
# cumulative `le` thresholds. An exemplar-fed histogram keeps the LAST
# trace_id observed per bucket, so an operator reading a bad p99 bucket
# in /metrics can jump straight to one offending trace in the merged
# timeline instead of grepping blind.
DEFAULT_EXEMPLAR_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, float("inf"),
)


class Histogram:
    """Streaming distribution with exact count/sum and reservoir quantiles.

    `observe(v, exemplar=...)` additionally files the observation into
    fixed `le` buckets and remembers the last exemplar (a trace_id) per
    bucket; `render_text` then emits OpenMetrics ``name_bucket{le=...}
    N # {trace_id="..."} v`` lines. Histograms never fed an exemplar
    render exactly as before (no bucket lines) — the exposition stays
    byte-stable for existing consumers.
    """

    def __init__(self, max_samples: int = 4096,
                 buckets: Sequence[float] = DEFAULT_EXEMPLAR_BUCKETS
                 ) -> None:
        if max_samples <= 0:
            raise ValueError("max_samples must be positive")
        self.count = 0
        self.sum = 0.0
        self._max = max_samples
        self._samples: list = []
        self._next = 0  # ring-buffer cursor once the reservoir is full
        self.buckets = tuple(buckets)
        if list(self.buckets) != sorted(self.buckets):
            raise ValueError("buckets must be ascending")
        self._bucket_counts = [0] * len(self.buckets)
        # bucket index -> (exemplar_id, value); None until an exemplar
        # was ever observed (gates the exposition's bucket section)
        self._exemplars: Optional[list] = None

    def _bucket_index(self, v: float) -> int:
        from bisect import bisect_left

        i = bisect_left(self.buckets, v)
        return min(i, len(self.buckets) - 1)

    def observe(self, v: float, exemplar: Optional[str] = None) -> None:
        v = float(v)
        self.count += 1
        self.sum += v
        i = self._bucket_index(v)
        self._bucket_counts[i] += 1
        if exemplar is not None:
            if self._exemplars is None:
                self._exemplars = [None] * len(self.buckets)
            self._exemplars[i] = (str(exemplar), v)
        if len(self._samples) < self._max:
            self._samples.append(v)
        else:
            self._samples[self._next] = v
            self._next = (self._next + 1) % self._max

    def exemplar_for(self, p: float):
        """(exemplar_id, value) filed in the bucket holding the p-th
        percentile (None when that bucket never saw an exemplar) — the
        jump-from-p99-to-trace lookup /flight surfaces."""
        if self._exemplars is None or not self._samples:
            return None
        return self._exemplars[self._bucket_index(self.percentile(p))]

    @property
    def mean(self) -> float:
        return self.sum / self.count if self.count else 0.0

    def percentile(self, p: float) -> float:
        """p in [0, 100]; nearest-rank over the retained reservoir."""
        if not self._samples:
            return 0.0
        s = sorted(self._samples)
        rank = min(len(s) - 1, max(0, round(p / 100.0 * (len(s) - 1))))
        return s[int(rank)]

    def summary(self, percentiles: Iterable[float] = (50, 90, 99)) -> dict:
        out = {"count": self.count, "mean": self.mean}
        for p in percentiles:
            out[f"p{p:g}"] = self.percentile(p)
        return out

    @classmethod
    def of(cls, values: Sequence[float]) -> "Histogram":
        """A histogram holding exactly `values` (offline summaries)."""
        h = cls(max_samples=max(len(values), 1))
        for v in values:
            h.observe(v)
        return h


def percentile_summary(values: Sequence[float],
                       percentiles: Iterable[float] = (50, 90, 99)) -> dict:
    """{"p50": ..., "p90": ..., "p99": ..., "mean": ...} over `values`.

    THE percentile implementation of the telemetry plane — the serve
    bench's latency rows, the /flight scrape endpoint, the SLO watchdog,
    and tools/check_slo.py all summarize through here (nearest-rank via
    Histogram.percentile), so a quantile quoted by any of them means the
    same thing. Empty input yields zeros, matching Histogram.
    """
    h = Histogram.of(values)
    out = {f"p{p:g}": h.percentile(p) for p in percentiles}
    out["mean"] = h.mean
    return out


class MetricsRegistry:
    """Create-or-get named metrics; snapshot() flattens to one dict.

    Thread-safe on the create path only (a serve loop is single-threaded,
    but request submission may come from another thread); individual
    updates are plain float ops under the GIL.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counters: Dict[str, Counter] = {}
        self._gauges: Dict[str, Gauge] = {}
        self._histograms: Dict[str, Histogram] = {}

    def counter(self, name: str) -> Counter:
        with self._lock:
            return self._counters.setdefault(name, Counter())

    def gauge(self, name: str) -> Gauge:
        with self._lock:
            return self._gauges.setdefault(name, Gauge())

    def histogram(self, name: str, max_samples: int = 4096) -> Histogram:
        with self._lock:
            return self._histograms.setdefault(
                name, Histogram(max_samples=max_samples)
            )

    def snapshot(self) -> dict:
        """Flat `{name: value}` dict; histograms expand to name_count /
        name_mean / name_p50 / name_p90 / name_p99. The metric dicts
        are copied under the create-lock first — a background reader
        (the telemetry exporter's snapshot thread, an HTTP scrape) must
        not race a serve loop that is still minting labelled metrics."""
        with self._lock:
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            histograms = dict(self._histograms)
        out: dict = {}
        for name, c in counters.items():
            out[name] = c.value
        for name, g in gauges.items():
            out[name] = g.value
        for name, h in histograms.items():
            for k, v in h.summary().items():
                out[f"{name}_{k}"] = v
        return out

    def render_text(self) -> str:
        """Prometheus text exposition of the registry.

        `labelled()` names render as ``name{k="v"}`` (labels sorted by
        key, values escaped per the exposition format: backslash, quote,
        newline); histograms render as summaries — ``name{quantile=
        "0.5"}`` / ``"0.9"`` / ``"0.99"`` over the reservoir plus exact
        ``name_count`` and ``name_sum``. One ``# TYPE`` line per metric
        family, families sorted by name — the output is byte-stable for
        a given registry state, so a scrape endpoint or a test can diff
        it. Ends with a trailing newline per the format spec.
        """
        with self._lock:
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            histograms = dict(self._histograms)
        lines = []

        def simple(metrics, kind):
            fams: Dict[str, list] = {}
            for name, m in metrics.items():
                base, pairs = _split_labelled(name)
                fams.setdefault(base, []).append((pairs, m.value))
            for base in sorted(fams):
                lines.append(f"# TYPE {base} {kind}")
                for pairs, value in sorted(
                        fams[base], key=lambda pv: _render_labels(pv[0])):
                    lines.append(
                        f"{base}{_render_labels(pairs)} {_fmt_value(value)}"
                    )

        simple(counters, "counter")
        simple(gauges, "gauge")
        for name in sorted(histograms):
            h = histograms[name]
            base, pairs = _split_labelled(name)
            lines.append(f"# TYPE {base} summary")
            for q, p in (("0.5", 50), ("0.9", 90), ("0.99", 99)):
                qpairs = pairs + [("quantile", q)]
                lines.append(
                    f"{base}{_render_labels(qpairs)} "
                    f"{_fmt_value(h.percentile(p))}"
                )
            lines.append(
                f"{base}_count{_render_labels(pairs)} {_fmt_value(h.count)}"
            )
            lines.append(
                f"{base}_sum{_render_labels(pairs)} {_fmt_value(h.sum)}"
            )
            if h._exemplars is not None:
                # OpenMetrics exemplar section — only for histograms
                # actually FED exemplars (trace_ids from completions),
                # so the classic summary output above stays byte-stable
                # for everything else. Cumulative le buckets; the last
                # exemplar filed in a bucket rides its line as
                # `# {trace_id="..."} value`.
                cum = 0
                for i, le in enumerate(h.buckets):
                    cum += h._bucket_counts[i]
                    le_s = "+Inf" if le == float("inf") else _fmt_value(le)
                    bpairs = pairs + [("le", le_s)]
                    line = (f"{base}_bucket{_render_labels(bpairs)} "
                            f"{_fmt_value(cum)}")
                    ex = h._exemplars[i]
                    if ex is not None:
                        eid, ev = ex
                        line += (f' # {{trace_id="'
                                 f'{_escape_label_value(eid)}"}} '
                                 f"{_fmt_value(ev)}")
                    lines.append(line)
        return "\n".join(lines) + "\n"


_default: Optional[MetricsRegistry] = None


def default_registry() -> MetricsRegistry:
    """Process-wide registry for callers that don't thread their own."""
    global _default
    if _default is None:
        _default = MetricsRegistry()
    return _default
