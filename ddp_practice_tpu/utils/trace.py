"""Request-lifecycle tracing: Dapper-style spans over an injectable clock.

The serving stack (Scheduler -> SlotEngine/PagedEngine -> Router) and the
training loop both answer "where did the time go?" with aggregate gauges
only (utils/metrics.py) — a bad TTFT or a failover hop leaves no record
of queue wait vs bucketed prefill vs decode-burst stalls vs retry hops.
This module is the missing recorder:

- **Host-pure and thread-safe.** Nothing here touches jax; appends are
  deque ops under the GIL, snapshots take the lock. A serve loop is
  single-threaded, but submission may come from another thread.
- **Injectable clock.** The recorder reads time through the same clock
  the schedulers use (`MonotonicClock` in production, `FakeClock` in
  tests), so a chaos replay's trace is bit-for-bit deterministic.
- **Bounded.** Records live in a ring buffer (`max_events`); a
  long-lived server's tracing memory is O(1), and the exported timeline
  is the most recent window — a flight recorder, not an archive.
- **Zero-overhead when off.** A disabled recorder's `span()` returns a
  shared no-op context manager and `instant()` returns immediately; the
  instrumented hot paths additionally gate on `tracer is not None`, so
  the production default (no tracer) pays a single attribute test.
- **Streamable.** An optional `sink` (utils/telemetry.py
  TelemetryExporter) receives every record as a plain dict THE MOMENT it
  is recorded — line-delimited JSONL export that survives a SIGKILL,
  where `save()` (the exit-time Chrome dump) would leave nothing.
  tools/check_traces.py validates both forms.

Three record kinds, three Chrome trace-event encodings
(`to_chrome_trace()` emits the JSON Perfetto / chrome://tracing /
vLLM's tooling consume):

- **Lane spans** (`span()` / `record_span()`): synchronous work on one
  (pid, tid) lane — a prefill dispatch on a slot lane, a decode burst
  on the engine lane, a train step phase. Exported as matched B/E
  pairs, properly nested per lane (tools/check_traces.py validates).
- **Request spans** (`record_async()`): per-request lifecycle intervals
  ("request", "queued") that overlap freely across requests. Exported
  as Chrome ASYNC events (ph "b"/"e") keyed by `id=trace_id`, so one
  request renders as one timeline row however many replicas it crossed.
- **Instants** (`instant()`): point events (shed, retry, failover,
  brownout flip) — ph "i".

Lane conventions for serving (shared by both engines and the router):
pid = replica id (`ROUTER_PID` for the router's own lane), tid 0 =
`ENGINE_LANE` (decode dispatches + scheduler instants), tid 1+slot =
the slot's prefill lane. `label_replica()` / `label_router()` stamp the
matching process/thread-name metadata so traces open pre-labelled.

Trace-id propagation is the router's failover contract: a re-admitted
request's sub-Request carries the ORIGINAL trace_id, so a crash-migrated
request's spans on the survivor join the same async track as its spans
on the dead replica — one request, one timeline (pinned in
tests/test_trace.py). The engines also name their
`jax.profiler.TraceAnnotation` regions with the dispatch's trace-ids, so
a device timeline captured by utils/profiling.py lines up with the host
spans by name (utils/xprof.py reads the device side back).
"""

from __future__ import annotations

import contextlib
import hashlib
import itertools
import json
import threading
import time
import zlib
from collections import defaultdict, deque
from typing import Dict, Optional

# record kinds (internal)
_DUR, _ASYNC, _INSTANT = 0, 1, 2

# serving lane conventions (see module doc)
ENGINE_LANE = 0          # tid for decode dispatches + scheduler instants
SLOT_LANE_BASE = 1       # tid = SLOT_LANE_BASE + slot for prefill spans
ROUTER_PID = -1          # the router's own pid (replicas are 0..N-1)

# the shared no-op span: what a disabled recorder hands out, and what
# instrumented hot paths use when no tracer is attached at all
NULL_SPAN = contextlib.nullcontext()
_NULL_SPAN = NULL_SPAN


def _resolve_clock(clock):
    """Accept a scheduler-style clock object (has .now()), a plain
    callable, or None (wall monotonic)."""
    if clock is None:
        return time.monotonic
    now = getattr(clock, "now", None)
    if callable(now):
        return now
    if callable(clock):
        return clock
    raise TypeError(f"clock must have .now() or be callable: {clock!r}")


# ------------------------------------------------------------- sampling
# Tail-keep markers: an instant/span with one of these names arriving for
# a staged (head-unsampled) request promotes the whole staged timeline on
# the spot — anomalies keep their traces even if the process dies before
# the request completes. The names match what the router/scheduler/engine
# already record ("retry"/"failover" instants, "preempted"/"preempt",
# "stale_retry", "replica_dead") plus the terminal status instants the
# scheduler stamps for non-eos/length outcomes.
KEEP_MARKERS = frozenset({
    "preempt", "preempted", "retry", "failover", "resumed",
    "stale_retry", "replica_dead", "shed", "timeout", "error",
    "rejected",
})

# statuses that terminate cleanly — anything else is a keep-worthy outcome
_CLEAN_STATUSES = ("eos", "length")


def head_keep(trace_id: str, rate: float) -> bool:
    """The deterministic head-sampling decision: keep `trace_id` at
    `rate`. Dapper's coherence rule is that this decision is made ONCE
    per request and honored by every process the request touches — so it
    must be a pure function of the trace_id, stable across OS processes.
    Python's builtin hash() is salted per interpreter (PYTHONHASHSEED)
    and would give the router and a worker process DIFFERENT answers;
    crc32 is stable everywhere."""
    if rate >= 1.0:
        return True
    if rate <= 0.0:
        return False
    h = zlib.crc32(trace_id.encode("utf-8")) & 0xFFFFFFFF
    return (h / 4294967296.0) < rate


class TraceSampler:
    """Sampling policy: head rate + tail keep-rules.

    `rate` is the head-sampling probability (decided per trace_id by
    `head_keep`, or by an injected `decide` callable in tests);
    `keep_slow_s` is the latency threshold above which a completed
    request is tail-kept even when head-unsampled (SLO-derived: a
    straggler IS the interesting trace); `stage_limit` bounds the
    per-request staging area a head-unsampled request's spans wait in
    until its tail verdict; `tenant_rates` maps tenant id -> head rate
    override (a debugged tenant runs at 1.0 while the fleet default
    stays at 1%), consulted per request via `rate_for`. Tail keep-rules
    are deliberately tenant-blind: a fault-affected request keeps its
    trace whatever its tenant's head rate."""

    def __init__(self, rate: float = 1.0, *,
                 keep_slow_s: Optional[float] = None,
                 stage_limit: int = 256, decide=None,
                 tenant_rates: Optional[Dict[str, float]] = None) -> None:
        if stage_limit < 1:
            raise ValueError("stage_limit must be positive")
        self.rate = float(rate)
        self.keep_slow_s = keep_slow_s
        self.stage_limit = stage_limit
        self._decide = decide
        self.tenant_rates: Optional[Dict[str, float]] = (
            {str(k): float(v) for k, v in tenant_rates.items()}
            if tenant_rates else None)

    def rate_for(self, tenant: Optional[str] = None) -> float:
        """The head rate this request samples at: the tenant's override
        when one is configured, the fleet default otherwise."""
        if tenant is not None and self.tenant_rates:
            r = self.tenant_rates.get(tenant)
            if r is not None:
                return r
        return self.rate

    def sampled(self, trace_id: str,
                tenant: Optional[str] = None) -> bool:
        if self._decide is not None:
            return bool(self._decide(trace_id))
        return head_keep(trace_id, self.rate_for(tenant))

    def keep_reason(self, *, status: Optional[str] = None,
                    latency_s: Optional[float] = None,
                    retries: int = 0, failovers: int = 0
                    ) -> Optional[str]:
        """Tail verdict at completion: the keep reason, or None to
        suppress. Any non-clean terminal status, any retry/failover hop,
        or a latency past the slow threshold keeps the trace."""
        if status is not None and status not in _CLEAN_STATUSES:
            return str(status)
        if failovers:
            return "failover"
        if retries:
            return "retry"
        if (self.keep_slow_s is not None and latency_s is not None
                and latency_s > self.keep_slow_s):
            return "slow"
        return None


class _TailStage:
    """One head-unsampled request's bounded span staging area."""

    __slots__ = ("records", "dropped")

    def __init__(self, limit: int) -> None:
        self.records: deque = deque(maxlen=limit)
        self.dropped = 0


class _Rec:
    __slots__ = ("kind", "name", "t0", "t1", "pid", "tid", "trace_id",
                 "attrs", "seq")

    def __init__(self, kind, name, t0, t1, pid, tid, trace_id, attrs, seq):
        self.kind = kind
        self.name = name
        self.t0 = t0
        self.t1 = t1
        self.pid = pid
        self.tid = tid
        self.trace_id = trace_id
        self.attrs = attrs
        self.seq = seq


class _Span:
    """Context manager for one lane span; created only when enabled."""

    __slots__ = ("rec", "name", "trace_id", "pid", "tid", "attrs", "t0",
                 "sampled_only")

    def __init__(self, rec, name, trace_id, pid, tid, attrs,
                 sampled_only=False):
        self.rec = rec
        self.name = name
        self.trace_id = trace_id
        self.pid = pid
        self.tid = tid
        self.attrs = attrs
        self.sampled_only = sampled_only

    def __enter__(self):
        self.t0 = self.rec._now()
        return self

    def __exit__(self, exc_type, exc, tb):
        self.rec.record_span(
            self.name, self.t0, self.rec._now(), trace_id=self.trace_id,
            pid=self.pid, tid=self.tid, attrs=self.attrs,
            sampled_only=self.sampled_only,
        )
        return False


class TraceRecorder:
    """Bounded, clock-injected span/event recorder (see module doc)."""

    def __init__(self, *, clock=None, max_events: int = 65536,
                 enabled: bool = True, sink=None,
                 drop_counter=None) -> None:
        if max_events < 1:
            raise ValueError("max_events must be positive")
        self._now = _resolve_clock(clock)
        self.enabled = enabled
        self._records: deque = deque(maxlen=max_events)
        self._seq = itertools.count()
        self._lock = threading.Lock()
        # span-loss accounting: the ring buffer SILENTLY evicts the
        # oldest record when full — count every eviction (plus drops
        # reported by external producers, e.g. a worker's bounded trace
        # buffer via TraceCollector) so a truncated timeline is
        # observable instead of quietly validating. `drop_counter` is an
        # optional utils/metrics.py Counter (trace_events_dropped_total);
        # the count is also stamped into the export metadata so
        # tools/check_traces.py can warn.
        self.dropped = 0
        self._drop_counter = drop_counter
        self._process_names: Dict[int, str] = {}
        self._thread_names: Dict[tuple, str] = {}
        # streaming sink (utils/telemetry.py TelemetryExporter): called
        # with one plain dict per record AS IT IS RECORDED, so a killed
        # run's events survive outside this ring buffer. None = the
        # exit-time export (save()) is the only output.
        self._sink = None
        # head sampling + tail keep (None = record everything, the
        # pre-sampling behavior; see set_sampler / begin_trace)
        self.sampler: Optional[TraceSampler] = None
        self._head: Dict[str, int] = {}        # 0 staged / 1 head / 2 kept
        self._staged: Dict[str, _TailStage] = {}
        self._outcomes: Dict[str, bool] = {}   # finished trace -> recorded
        self._active_flowing = 0               # in-flight head/kept traces
        self.spans_sampled = 0                 # records kept by the head decision
        self.spans_kept = 0                    # records kept by a tail rule
        self.spans_suppressed = 0              # staged records discarded
        self.traces_sampled = 0
        self.traces_kept = 0
        self.traces_suppressed = 0
        self.kept_reasons: Dict[str, int] = {}
        self._c_sampled = self._c_kept = self._c_suppressed = None
        self._keep_registry = None
        # incremental OTLP drain state (drain_otlp / OtlpPusher): the
        # high-water seq already exported plus each trace's remembered
        # root spanId, so successive batches never re-emit a record
        # (spanIds must stay unique across a merged push capture) and a
        # span drained after its root shipped still parents onto it.
        self._otlp_drained = -1
        self._otlp_roots: Dict[str, str] = {}
        if sink is not None:
            self.set_sink(sink)

    def set_sink(self, sink) -> None:
        """Attach a streaming consumer: `sink(record_dict)` per span/
        async/instant record (kind-tagged; see _stream) plus one "meta"
        record per lane label. Already-recorded lane labels are replayed
        into the sink at attach time, so a sink attached after
        label_replica() still knows every pid."""
        self._sink = sink
        for pid, name in self._process_names.items():
            sink({"kind": "meta", "meta": "process_name",
                  "pid": pid, "name": name})
        for (pid, tid), name in self._thread_names.items():
            sink({"kind": "meta", "meta": "thread_name",
                  "pid": pid, "tid": tid, "name": name})

    def _stream(self, rec: dict) -> None:
        if self._sink is not None:
            self._sink(rec)

    def _stream_record(self, kind: str, name, pid, tid, trace_id,
                       attrs, **times) -> None:
        """Build + emit one sink record (callers gate on `_sink is not
        None` first, so the no-sink hot path never builds the dict).
        The stream schema has ONE producer: change it here, and every
        record kind follows."""
        rec = {"kind": kind, "name": name, **times, "pid": pid}
        if tid is not None:
            rec["tid"] = tid
        if trace_id is not None:
            rec["trace_id"] = trace_id
        if attrs:
            rec["attrs"] = attrs
        self._sink(rec)

    # ------------------------------------------------------------ recording
    def now(self) -> float:
        return self._now()

    def _append(self, rec: "_Rec") -> None:
        if len(self._records) == self._records.maxlen:
            self._note_drops(1)
        self._records.append(rec)

    def _note_drops(self, n: int) -> None:
        if n <= 0:
            return
        self.dropped += n
        if self._drop_counter is not None:
            self._drop_counter.inc(n)

    def count_external_drops(self, n: int) -> None:
        """Fold drops that happened OUTSIDE this ring buffer (a worker's
        bounded trace buffer, a full push queue) into this recorder's
        loss accounting — one number answers "is this timeline whole"."""
        self._note_drops(n)

    # ------------------------------------------------------------- sampling
    def set_sampler(self, sampler: Optional[TraceSampler], *,
                    registry=None) -> None:
        """Attach the sampling policy. With `registry` (utils/metrics.py
        MetricsRegistry), mints the accounting counters —
        trace_spans_sampled/kept/suppressed_total plus a per-reason
        trace_traces_kept_total{reason=...} family."""
        self.sampler = sampler
        if registry is not None:
            self._c_sampled = registry.counter("trace_spans_sampled_total")
            self._c_kept = registry.counter("trace_spans_kept_total")
            self._c_suppressed = registry.counter(
                "trace_spans_suppressed_total")
            self._keep_registry = registry

    def begin_trace(self, trace_id: Optional[str],
                    sampled: Optional[bool] = None, *,
                    tenant: Optional[str] = None) -> bool:
        """Stamp the head decision for one request at admission.
        Idempotent per trace_id (the router and a scheduler sharing one
        recorder both call it); `sampled` carries an upstream decision
        across the RPC seam (Dapper coherence: decided once, honored
        everywhere); `tenant` selects a per-tenant head-rate override
        when the sampler has one. Returns whether the request's spans
        flow."""
        if self.sampler is None or trace_id is None or not self.enabled:
            return True if sampled is None else bool(sampled)
        v = self._head.get(trace_id)
        if v is not None:
            return v != 0
        if sampled is None:
            sampled = self.sampler.sampled(trace_id, tenant)
        if len(self._head) >= 16384:
            # runaway begin/finish imbalance must not leak: evict the
            # oldest in-flight trace, suppressing anything it staged
            old, ov = next(iter(self._head.items()))
            del self._head[old]
            stg = self._staged.pop(old, None)
            if stg is not None:
                self._suppress(len(stg.records) + stg.dropped)
            elif ov != 0:
                self._active_flowing -= 1
        if sampled:
            self._head[trace_id] = 1
            self._active_flowing += 1
            self.traces_sampled += 1
        else:
            self._head[trace_id] = 0
            self._staged[trace_id] = _TailStage(self.sampler.stage_limit)
        return bool(sampled)

    def note_keep(self, trace_id: Optional[str],
                  reason: str = "marked") -> None:
        """Promote a staged request to kept RIGHT NOW (flush its staged
        spans; everything it records from here on flows). No-op for
        head-sampled / unknown / already-resolved traces."""
        if self.sampler is None or trace_id is None:
            return
        if self._head.get(trace_id) == 0:
            self._promote(trace_id, reason)

    def trace_recorded(self, trace_id: Optional[str]) -> bool:
        """Is this trace_id in the timeline (head-sampled, tail-kept, or
        sampling off)? The exemplar gate: a histogram exemplar citing a
        suppressed trace is a dead link."""
        if self.sampler is None or trace_id is None:
            return True
        v = self._head.get(trace_id)
        if v is not None:
            return v != 0
        return self._outcomes.get(trace_id, True)

    def finish_trace(self, trace_id: Optional[str], *,
                     status: Optional[str] = None,
                     latency_s: Optional[float] = None,
                     retries: int = 0, failovers: int = 0) -> bool:
        """The tail verdict at request completion: promote the staged
        spans when any keep-rule fires, otherwise discard them as
        suppressed. Returns whether the trace is in the timeline (the
        exemplar gate). Idempotent: a second finish (router after
        scheduler on a shared recorder) returns the first outcome."""
        if self.sampler is None or trace_id is None or not self.enabled:
            return True
        v = self._head.pop(trace_id, None)
        if v is None:
            return self._outcomes.get(trace_id, True)
        if v != 0:
            self._active_flowing -= 1
            self._remember(trace_id, True)
            return True
        stg = self._staged.pop(trace_id, None)
        reason = self.sampler.keep_reason(
            status=status, latency_s=latency_s, retries=retries,
            failovers=failovers)
        if reason is not None:
            self.traces_kept += 1
            self._count_reason(reason)
            if stg is not None:
                for r in stg.records:
                    self._flush_rec(r)
                self.spans_kept += len(stg.records)
                if self._c_kept is not None:
                    self._c_kept.inc(len(stg.records))
                if stg.dropped:
                    self._note_drops(stg.dropped)
            self._remember(trace_id, True)
            return True
        self.traces_suppressed += 1
        if stg is not None:
            self._suppress(len(stg.records) + stg.dropped)
        self._remember(trace_id, False)
        return False

    def _remember(self, trace_id: str, recorded: bool) -> None:
        self._outcomes[trace_id] = recorded
        if len(self._outcomes) > 8192:
            self._outcomes.pop(next(iter(self._outcomes)))

    def _suppress(self, n: int) -> None:
        if n <= 0:
            return
        self.spans_suppressed += n
        if self._c_suppressed is not None:
            self._c_suppressed.inc(n)

    def _count_reason(self, reason: str) -> None:
        self.kept_reasons[reason] = self.kept_reasons.get(reason, 0) + 1
        if self._keep_registry is not None:
            from .metrics import labelled
            self._keep_registry.counter(
                labelled("trace_traces_kept_total", reason=reason)).inc()

    def _promote(self, trace_id: str, reason: str) -> None:
        """Staged -> kept: flush the staging area into the ring + sink,
        record the reason, let subsequent records flow."""
        self._head[trace_id] = 2
        self._active_flowing += 1
        self.traces_kept += 1
        self._count_reason(reason)
        stg = self._staged.pop(trace_id, None)
        if stg is None:
            return
        for r in stg.records:
            self._flush_rec(r)
        self.spans_kept += len(stg.records)
        if self._c_kept is not None:
            self._c_kept.inc(len(stg.records))
        if stg.dropped:
            # staged overflow became real loss the moment we kept the
            # trace — fold it into the recorder's drop accounting
            self._note_drops(stg.dropped)

    def _flush_rec(self, r: "_Rec") -> None:
        self._append(r)
        if self._sink is None:
            return
        if r.kind == _DUR:
            self._stream_record("span", r.name, r.pid, r.tid,
                                r.trace_id, r.attrs, t0=r.t0, t1=r.t1)
        elif r.kind == _ASYNC:
            self._stream_record("async", r.name, r.pid, None,
                                r.trace_id, r.attrs, t0=r.t0, t1=r.t1)
        else:
            self._stream_record("instant", r.name, r.pid, r.tid,
                                r.trace_id, r.attrs, t=r.t0)

    def _admit(self, rec: "_Rec", sampled_only: bool = False) -> bool:
        """The sampling gate on every record: True = record now, False =
        staged or suppressed. Marker-named records promote their staged
        trace on the spot (anomalies survive even a later SIGKILL)."""
        tid_ = rec.trace_id
        if tid_ is None:
            # shared lane work (decode bursts): recorded only while some
            # sampled/kept request is in flight when the producer asked
            # for the gate — the residual cost at a 1% head rate
            if sampled_only and self._active_flowing == 0:
                self._suppress(1)
                return False
            return True
        v = self._head.get(tid_)
        if v is None:
            return True
        if v != 0:
            if v == 1:
                self.spans_sampled += 1
                if self._c_sampled is not None:
                    self._c_sampled.inc()
            else:
                self.spans_kept += 1
                if self._c_kept is not None:
                    self._c_kept.inc()
            return True
        if rec.name in KEEP_MARKERS:
            self._promote(tid_, rec.name)
            self.spans_kept += 1
            if self._c_kept is not None:
                self._c_kept.inc()
            return True
        stg = self._staged.get(tid_)
        if stg is None:    # defensive: decision says staged, stage gone
            return True
        if len(stg.records) == stg.records.maxlen:
            stg.dropped += 1
        stg.records.append(rec)
        return False

    def span(self, name: str, *, trace_id: Optional[str] = None,
             pid: int = 0, tid: int = 0, sampled_only: bool = False,
             **attrs):
        """Lane span context manager; a shared no-op when disabled.
        `sampled_only` marks shared-lane work (no trace_id of its own)
        that should be suppressed while nothing sampled is in flight."""
        if not self.enabled:
            return _NULL_SPAN
        return _Span(self, name, trace_id, pid, tid, attrs, sampled_only)

    def record_span(self, name: str, t0: float, t1: float, *,
                    trace_id: Optional[str] = None, pid: int = 0,
                    tid: int = 0, attrs: Optional[dict] = None,
                    sampled_only: bool = False) -> None:
        """Explicit-timestamp lane span (for intervals the caller timed)."""
        if not self.enabled:
            return
        rec = _Rec(
            _DUR, name, t0, t1, pid, tid, trace_id, attrs, next(self._seq)
        )
        if self.sampler is not None and not self._admit(rec, sampled_only):
            return
        self._append(rec)
        if self._sink is not None:
            self._stream_record("span", name, pid, tid, trace_id, attrs,
                                t0=t0, t1=t1)

    def record_async(self, name: str, t0: float, t1: float, *,
                     trace_id: str, pid: int = 0,
                     attrs: Optional[dict] = None) -> None:
        """Per-request interval: exported as async b/e keyed by trace_id,
        so overlapping requests never fight over one lane's B/E stack."""
        if not self.enabled:
            return
        rec = _Rec(
            _ASYNC, name, t0, t1, pid, 0, trace_id, attrs, next(self._seq)
        )
        if self.sampler is not None and not self._admit(rec):
            return
        self._append(rec)
        if self._sink is not None:
            self._stream_record("async", name, pid, None, trace_id,
                                attrs, t0=t0, t1=t1)

    def instant(self, name: str, *, trace_id: Optional[str] = None,
                pid: int = 0, tid: int = 0, **attrs) -> None:
        if not self.enabled:
            return
        self.record_instant(name, self._now(), trace_id=trace_id,
                            pid=pid, tid=tid, attrs=attrs or None)

    def record_instant(self, name: str, t: float, *,
                       trace_id: Optional[str] = None, pid: int = 0,
                       tid: int = 0, attrs: Optional[dict] = None) -> None:
        """Explicit-timestamp instant — for events timed in another
        process's clock domain (TraceCollector merges worker instants
        with the measured offset already applied)."""
        if not self.enabled:
            return
        rec = _Rec(
            _INSTANT, name, t, t, pid, tid, trace_id, attrs or None,
            next(self._seq)
        )
        if self.sampler is not None and not self._admit(rec):
            return
        self._append(rec)
        if self._sink is not None:
            self._stream_record("instant", name, pid, tid, trace_id,
                                attrs or None, t=t)

    # ------------------------------------------------------------- metadata
    def set_process_name(self, pid: int, name: str) -> None:
        self._process_names[pid] = name
        self._stream({"kind": "meta", "meta": "process_name",
                      "pid": pid, "name": name})

    def set_thread_name(self, pid: int, tid: int, name: str) -> None:
        self._thread_names[(pid, tid)] = name
        self._stream({"kind": "meta", "meta": "thread_name",
                      "pid": pid, "tid": tid, "name": name})

    # ------------------------------------------------------------- plumbing
    def __len__(self) -> int:
        return len(self._records)

    def clear(self) -> None:
        """Drop recorded events (lane labels survive) — e.g. after a
        warmup phase whose compile-time spans would dwarf the workload.
        In-flight sampling decisions survive (a cleared recorder must
        still resolve its open requests coherently); their already-staged
        records are dropped with the ring, uncounted, like everything
        else clear() discards."""
        with self._lock:
            self._records.clear()
            for stg in self._staged.values():
                stg.records.clear()
                stg.dropped = 0

    def disable(self) -> None:
        self.enabled = False

    def enable(self) -> None:
        self.enabled = True

    # --------------------------------------------------------------- export
    def to_chrome_trace(self) -> dict:
        """Render the ring buffer as Chrome trace-event JSON.

        Lane spans become matched B/E pairs, emitted per (pid, tid) in
        stack order (outer-first at shared starts), so zero-duration
        spans on a FakeClock still nest cleanly; request spans become
        async b/e pairs keyed by id=trace_id; instants become ph "i".
        ts is microseconds of the recorder's clock domain.
        """
        with self._lock:
            records = list(self._records)
        events = []
        pids = ({r.pid for r in records} | set(self._process_names))
        for pid in sorted(pids):
            events.append({
                "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
                "args": {"name": self._process_names.get(pid, f"pid{pid}")},
            })
        lane_tids = {(r.pid, r.tid) for r in records if r.kind == _DUR}
        for (pid, tid) in sorted(set(self._thread_names) | lane_tids):
            events.append({
                "name": "thread_name", "ph": "M", "pid": pid, "tid": tid,
                "args": {"name": self._thread_names.get(
                    (pid, tid), f"tid{tid}")},
            })

        def us(t: float) -> float:
            return round(t * 1e6, 3)

        def begin(r: _Rec, ph: str) -> dict:
            ev = {"name": r.name, "ph": ph, "ts": us(r.t0),
                  "pid": r.pid, "tid": r.tid}
            args = dict(r.attrs) if r.attrs else {}
            if r.trace_id is not None:
                args["trace_id"] = r.trace_id
            if args:
                ev["args"] = args
            if ph == "b":
                ev["cat"] = "request"
                ev["id"] = r.trace_id
            return ev

        def end(r: _Rec, ph: str) -> dict:
            ev = {"name": r.name, "ph": ph, "ts": us(r.t1),
                  "pid": r.pid, "tid": r.tid}
            if ph == "e":
                ev["cat"] = "request"
                ev["id"] = r.trace_id
            return ev

        def sweep(recs, b_ph, e_ph):
            """Emit properly nested begin/end pairs for one lane: sort by
            (start, -end, seq), close every span that ends at-or-before
            the next span's start, drain at the end. Genuinely crossing
            intervals come out ts-disordered — the validator flags them
            rather than this export papering over them."""
            recs.sort(key=lambda r: (r.t0, -r.t1, r.seq))
            stack = []
            for r in recs:
                while stack and stack[-1].t1 <= r.t0:
                    events.append(end(stack.pop(), e_ph))
                events.append(begin(r, b_ph))
                stack.append(r)
            while stack:
                events.append(end(stack.pop(), e_ph))

        lanes = defaultdict(list)
        asyncs = defaultdict(list)
        instants = []
        for r in records:
            if r.kind == _DUR:
                lanes[(r.pid, r.tid)].append(r)
            elif r.kind == _ASYNC:
                asyncs[(r.pid, r.trace_id)].append(r)
            else:
                instants.append(r)
        for key in sorted(lanes):
            sweep(lanes[key], "B", "E")
        for key in sorted(asyncs, key=lambda k: (k[0], str(k[1]))):
            sweep(asyncs[key], "b", "e")
        for r in instants:
            ev = begin(r, "i")
            ev["s"] = "t"  # thread-scoped instant
            events.append(ev)
        out = {"traceEvents": events, "displayTimeUnit": "ms"}
        meta = {}
        if self.dropped:
            # a flight recorder that lost events must SAY so: the
            # validator (tools/check_traces.py) warns on this instead of
            # blessing a quietly truncated timeline
            meta["trace_events_dropped"] = self.dropped
        sm = self.sampling_meta()
        if sm is not None:
            # ...and a SAMPLED timeline must say it is partial BY POLICY
            # (suppressed != dropped): check_traces reads this back so a
            # missing lane for an unsampled request is not a loss warning
            meta["sampling"] = sm
        if meta:
            out["metadata"] = meta
        return out

    def sampling_meta(self) -> Optional[dict]:
        """The export-header sampling block; None when sampling is off."""
        if self.sampler is None:
            return None
        out = {
            "head_rate": self.sampler.rate,
            "keep_slow_s": self.sampler.keep_slow_s,
            "traces_sampled": self.traces_sampled,
            "traces_kept": self.traces_kept,
            "traces_suppressed": self.traces_suppressed,
            "spans_sampled": self.spans_sampled,
            "spans_kept": self.spans_kept,
            "spans_suppressed": self.spans_suppressed,
            "kept_reasons": dict(self.kept_reasons),
        }
        if self.sampler.tenant_rates:
            out["tenant_rates"] = dict(self.sampler.tenant_rates)
        return out

    def save(self, path: str) -> None:
        """Write the Chrome trace JSON (open in Perfetto / chrome://tracing)."""
        with open(path, "w") as f:
            json.dump(self.to_chrome_trace(), f)

    # --------------------------------------------------------- OTLP export
    def to_otlp(self, service_name: str = "ddp-serve") -> dict:
        """Render the per-request records as an OTLP-JSON
        ``ExportTraceServiceRequest`` (the shape an OTLP/HTTP collector
        accepts at /v1/traces), alongside the Chrome export.

        Mapping: every record carrying a trace_id becomes one span;
        traceId (16 bytes) / spanId (8 bytes) are derived by stable hash
        from the request's trace_id and the record identity, the
        "request" async span is the trace root and every other record
        parents onto it (lane spans and instants are children — instants
        become zero-duration spans). Records WITHOUT a trace_id (shared
        decode-burst lanes, clock_offset instants) are infrastructure,
        not request traces, and stay in the Chrome export only.
        Timestamps are the recorder's clock domain as unix-nanos strings
        (proto3 JSON int64); the original trace_id and lane rides along
        as ``ddp.*`` attributes, so tools/check_otlp.py can round-trip
        against the Chrome export. Resource attributes carry the
        sampling header."""
        with self._lock:
            records = [r for r in self._records if r.trace_id is not None]
        by_trace: Dict[str, list] = defaultdict(list)
        for r in records:
            by_trace[str(r.trace_id)].append(r)
        spans = []
        for tid_, recs in sorted(by_trace.items()):
            recs.sort(key=lambda r: (r.t0, r.seq))
            root_sid = None
            for r in recs:
                if r.kind == _ASYNC and r.name == "request":
                    root_sid = _otlp_span_id(tid_, r.seq)
                    break
            for r in recs:
                spans.append(_otlp_record_span(r, tid_, root_sid))
        return self._otlp_request(service_name, spans)

    def drain_otlp(self, service_name: str = "ddp-serve"
                   ) -> Optional[dict]:
        """Incremental OTLP export: the per-request records that entered
        the ring since the previous drain, as one
        ``ExportTraceServiceRequest`` (None when nothing is new). This is
        the push-plane producer (utils/telemetry.py OtlpPusher): each
        record is emitted in EXACTLY one batch — a seq high-water mark —
        so a collector that dedups whole batches by batch id never sees
        a duplicate spanId across the merged capture. The first
        "request" async span seen for a trace becomes (and stays) its
        root: spans in later batches parent onto it even though it
        shipped batches ago, and spans drained BEFORE their root exists
        go parentless — legal OTLP roots until the real root arrives."""
        with self._lock:
            records = [r for r in self._records
                       if r.trace_id is not None
                       and r.seq > self._otlp_drained]
            if not records:
                return None
            self._otlp_drained = max(r.seq for r in records)
        records.sort(key=lambda r: (str(r.trace_id), r.t0, r.seq))
        spans = []
        for r in records:
            tid_ = str(r.trace_id)
            root_sid = self._otlp_roots.get(tid_)
            if (root_sid is None and r.kind == _ASYNC
                    and r.name == "request"):
                root_sid = _otlp_span_id(tid_, r.seq)
                if len(self._otlp_roots) >= 16384:
                    self._otlp_roots.pop(next(iter(self._otlp_roots)))
                self._otlp_roots[tid_] = root_sid
            spans.append(_otlp_record_span(r, tid_, root_sid))
        return self._otlp_request(service_name, spans)

    def _otlp_request(self, service_name: str, spans: list) -> dict:
        """Wrap built spans in the export envelope (resource header =
        service name + sampling accounting + drop count)."""
        resource_attrs = {"service.name": service_name}
        sm = self.sampling_meta()
        if sm is not None:
            resource_attrs["ddp.sampling.head_rate"] = sm["head_rate"]
            resource_attrs["ddp.sampling.traces_kept"] = sm["traces_kept"]
            resource_attrs["ddp.sampling.traces_suppressed"] = (
                sm["traces_suppressed"])
            resource_attrs["ddp.sampling.spans_suppressed"] = (
                sm["spans_suppressed"])
        if self.dropped:
            resource_attrs["ddp.trace.dropped_events"] = self.dropped
        return {"resourceSpans": [{
            "resource": {"attributes": _otlp_attrs(resource_attrs)},
            "scopeSpans": [{
                "scope": {"name": "ddp_practice_tpu.trace"},
                "spans": spans,
            }],
        }]}

    def save_otlp(self, path: str,
                  service_name: str = "ddp-serve") -> None:
        """Write the OTLP-JSON export (tools/check_otlp.py validates)."""
        with open(path, "w") as f:
            json.dump(self.to_otlp(service_name=service_name), f)


def _otlp_trace_id(trace_id: str) -> str:
    """16-byte OTLP traceId as 32 hex chars, stable-hashed from the
    request trace_id (md5 as a hash, not a credential)."""
    return hashlib.md5(("ddp:" + trace_id).encode("utf-8")).hexdigest()


def _otlp_span_id(trace_id: str, seq: int) -> str:
    """8-byte OTLP spanId as 16 hex chars, unique per record."""
    return hashlib.md5(
        f"{trace_id}#{seq}".encode("utf-8")).hexdigest()[:16]


def _otlp_record_span(r: "_Rec", tid_: str,
                      root_sid: Optional[str]) -> dict:
    """One record -> one OTLP span (shared by the exit-time to_otlp and
    the incremental drain_otlp, so both exports speak the same shape).
    `root_sid` is the trace root's spanId or None; the root itself
    (sid == root_sid) carries the status instead of a parent link."""
    sid = _otlp_span_id(tid_, r.seq)
    attrs = {"ddp.trace_id": tid_, "ddp.pid": r.pid,
             "ddp.kind": ("span", "async", "instant")[r.kind]}
    if r.kind == _DUR:
        attrs["ddp.tid"] = r.tid
    if r.attrs:
        attrs.update(r.attrs)
    span = {
        "traceId": _otlp_trace_id(tid_),
        "spanId": sid,
        "name": str(r.name),
        "kind": 1,  # SPAN_KIND_INTERNAL
        "startTimeUnixNano": str(int(round(r.t0 * 1e9))),
        "endTimeUnixNano": str(int(round(r.t1 * 1e9))),
        "attributes": _otlp_attrs(attrs),
    }
    if root_sid is not None and sid != root_sid:
        span["parentSpanId"] = root_sid
    elif sid == root_sid:
        status = (r.attrs or {}).get("status")
        if status is not None:
            span["status"] = (
                {"code": 1} if status in _CLEAN_STATUSES
                else {"code": 2, "message": str(status)})
    return span


def _otlp_attrs(attrs: dict) -> list:
    """dict -> OTLP KeyValue list (string/bool/int/double values)."""
    out = []
    for k, v in attrs.items():
        if isinstance(v, bool):
            val = {"boolValue": v}
        elif isinstance(v, int):
            val = {"intValue": str(v)}
        elif isinstance(v, float):
            val = {"doubleValue": v}
        else:
            val = {"stringValue": str(v)}
        out.append({"key": str(k), "value": val})
    return out


# ------------------------------------------------------- lane label helpers
def label_replica(recorder: TraceRecorder, replica: int,
                  max_slots: int) -> None:
    """Stamp the serving lane names for one replica: pid=replica,
    tid 0 = engine (decode dispatches), tid 1+slot = prefill lanes."""
    recorder.set_process_name(replica, f"replica{replica}")
    recorder.set_thread_name(replica, ENGINE_LANE, "engine")
    for s in range(max_slots):
        recorder.set_thread_name(replica, SLOT_LANE_BASE + s, f"slot{s}")


def label_router(recorder: TraceRecorder) -> None:
    recorder.set_process_name(ROUTER_PID, "router")
    recorder.set_thread_name(ROUTER_PID, 0, "dispatch")


# -------------------------------------------------- adaptive head rate
class AdaptiveHeadRateController:
    """Feedback loop steering the head sample rate toward a kept-spans/
    sec budget — Dapper's production lesson, applied: the right rate is
    a function of observed traffic, not a hand-tuned constant baked
    into the fleet spec.

    Each `step(now)` past `interval_s` measures the kept-span flow from
    the recorder's own accounting counters (spans_sampled + spans_kept,
    the same totals trace_spans_*_total export) and applies one
    multiplicative correction `rate *= budget / observed`, clamped to
    [min_rate, max_rate] — kept flow is ~linear in the head rate, so a
    single step lands near the budget and the loop converges without a
    gain schedule. Two guards keep it from thrashing:

    - **deadband**: observed flow within ±`deadband` (fraction) of the
      budget is "on budget" — no correction, no churn.
    - **hold window**: after a change the rate holds for `hold_s`
      regardless of the error signal, so a correction's effect is
      actually OBSERVED before the next one (and, trivially, the rate
      never reverses inside its own hold window — the no-oscillation
      contract the tests pin).

    Every change is applied to the local sampler, pushed to the fleet
    via `apply_fn(new_rate)` (each worker handle's live rpc ``trace``
    op), and stamped into the timeline as a ``trace_rate`` instant —
    a span captured at 2% says so, right in the trace. Per-tenant
    overrides are left alone: the controller steers the fleet DEFAULT
    rate only.
    """

    def __init__(self, recorder: TraceRecorder, budget_sps: float, *,
                 clock=None, interval_s: float = 1.0,
                 min_rate: float = 0.001, max_rate: float = 1.0,
                 deadband: float = 0.1, hold_s: float = 5.0,
                 apply_fn=None) -> None:
        if budget_sps <= 0:
            raise ValueError("budget_sps must be positive")
        self.recorder = recorder
        self.budget_sps = float(budget_sps)
        self.interval_s = float(interval_s)
        self.min_rate = float(min_rate)
        self.max_rate = float(max_rate)
        self.deadband = float(deadband)
        self.hold_s = float(hold_s)
        self.apply_fn = apply_fn
        self._now = _resolve_clock(clock)
        sampler = recorder.sampler
        self.rate = sampler.rate if sampler is not None else 1.0
        self.changes = 0
        self.rate_log: list = []
        self._last_eval: Optional[float] = None
        self._last_count: Optional[int] = None
        self._last_change_t: Optional[float] = None
        self.last_observed_sps: Optional[float] = None

    def _kept_count(self) -> int:
        r = self.recorder
        return r.spans_sampled + r.spans_kept

    def step(self, now: Optional[float] = None) -> Optional[float]:
        """Evaluate once; returns the new rate when a change was applied,
        None otherwise. Call from the serve loop — cheap when the
        interval has not elapsed."""
        if now is None:
            now = self._now()
        if self._last_eval is None:
            # first call establishes the measurement baseline
            self._last_eval = now
            self._last_count = self._kept_count()
            return None
        dt = now - self._last_eval
        if dt < self.interval_s:
            return None
        count = self._kept_count()
        observed = (count - self._last_count) / dt
        self._last_eval = now
        self._last_count = count
        self.last_observed_sps = observed
        if abs(observed - self.budget_sps) <= (
                self.deadband * self.budget_sps):
            return None
        if (self._last_change_t is not None
                and now - self._last_change_t < self.hold_s):
            return None
        cur = self.rate
        if observed <= 0.0:
            # nothing kept at all: probe upward instead of dividing by 0
            new = cur * 2.0
        else:
            new = cur * (self.budget_sps / observed)
        new = min(self.max_rate, max(self.min_rate, new))
        if new == cur:
            return None
        self.rate = new
        self.changes += 1
        self._last_change_t = now
        self.rate_log.append({"t": now, "prev": cur, "rate": new,
                              "observed_sps": observed})
        if self.recorder.sampler is not None:
            self.recorder.sampler.rate = new
        self.recorder.record_instant(
            "trace_rate", now, pid=ROUTER_PID,
            attrs={"rate": new, "prev": cur, "observed_sps": observed,
                   "budget_sps": self.budget_sps})
        if self.apply_fn is not None:
            # fleet push (worker handles' live trace op) must never take
            # the control loop down with it
            try:
                self.apply_fn(new)
            except Exception:
                pass
        return new


# ------------------------------------------------------- fleet trace plane
class ClockOffsetEstimator:
    """NTP-style clock-offset estimate from RPC round trips.

    Worker processes stamp trace events with their OWN clocks; merging
    them onto the router's timeline needs the per-worker offset. Each
    ping/poll round trip yields one sample: the client reads its clock
    before (t0) and after (t3) the call, the worker stamps its clock
    (tw) while handling it; then

        offset = tw - (t0 + t3) / 2        (remote minus local)

    with worst-case error rtt/2 — the classic symmetric-delay bound
    (the true receive instant lies somewhere inside [t0, t3]; assuming
    the midpoint is wrong by at most half the round trip, however
    asymmetric the two legs actually were). So the BEST sample is the
    minimum-RTT one: we keep the lowest-RTT samples seen and answer
    with the lowest's offset, `bound` = its rtt/2. `reset()` on
    reconnect/restart — a new worker incarnation is a new clock domain.
    """

    def __init__(self, max_samples: int = 32) -> None:
        self.max_samples = max_samples
        self._samples: list = []   # (rtt, offset), sorted ascending rtt
        self.total_samples = 0

    def add(self, t0: float, t_remote: float, t3: float) -> bool:
        """Fold one round trip in; True when the best (min-RTT) sample
        — and therefore the answer — changed."""
        if t3 < t0:
            return False  # a torn reading is not a sample
        rtt = t3 - t0
        offset = t_remote - 0.5 * (t0 + t3)
        self.total_samples += 1
        best_before = self._samples[0] if self._samples else None
        self._samples.append((rtt, offset))
        self._samples.sort(key=lambda s: s[0])
        del self._samples[self.max_samples:]
        return self._samples[0] != best_before

    @property
    def n_samples(self) -> int:
        return len(self._samples)

    @property
    def offset(self) -> float:
        """Best current estimate of (remote clock - local clock); 0.0
        until a sample exists (merge unshifted rather than invent)."""
        return self._samples[0][1] if self._samples else 0.0

    @property
    def min_rtt(self) -> Optional[float]:
        return self._samples[0][0] if self._samples else None

    @property
    def bound(self) -> Optional[float]:
        """Worst-case error of `offset` (min observed rtt / 2)."""
        return self._samples[0][0] / 2.0 if self._samples else None

    def reset(self) -> None:
        self._samples.clear()


class TraceCollector:
    """Router-side merge of worker-streamed trace events into ONE fleet
    recorder.

    Workers record their own prefill/decode_burst/queued/request spans
    locally (serve/worker.py) and push them back over the RPC push
    stream as batched ``trace`` frames; this collector folds each frame
    into the fleet TraceRecorder so `--trace-out` exports one merged
    timeline — the Dapper collection step. Contracts:

    - **pid = worker lane.** Events arrive already stamped with the
      worker's replica pid (the PR-4 lane convention); `label_worker`
      names that lane ``worker-N`` so the merged trace reads as a fleet,
      and the worker's own ``replicaN`` process_name meta is dropped in
      favour of it. Cross-process trace_id propagation is untouched —
      a SIGKILL-failover request's pre-crash spans (streamed before the
      kill) and its survivor spans share the original trace_id, so it
      renders as ONE timeline.
    - **Clock alignment.** Every event timestamp is shifted by the
      worker's measured offset (ClockOffsetEstimator, fed by the
      handle's ping/poll round trips) at merge time; the current
      offset/bound is recorded as a ``clock_offset`` instant on the
      worker's lane whenever the estimate improves, so the exported
      trace carries its own skew model (tools/check_traces.py --fleet
      reads it back as the causality tolerance).
    - **At-most-once, any order.** Frames carry a per-incarnation
      sequence number; duplicates (transport retry / stream+poll
      overlap) are skipped, out-of-order frames merge fine because
      every record carries absolute timestamps (the exporter sorts).
      `on_worker_restart` resets seq dedup and the offset — a new
      process is a new stream and a new clock.
    - **Loss is counted, never silent.** Frames carry the worker's
      cumulative dropped count (bounded buffer + full push queues);
      the delta folds into the fleet recorder's `dropped` (and the
      optional ``trace_events_dropped_total`` counter), which the
      export stamps into its metadata.
    """

    def __init__(self, recorder: TraceRecorder, *,
                 registry=None) -> None:
        self.recorder = recorder
        if registry is not None and recorder._drop_counter is None:
            recorder._drop_counter = registry.counter(
                "trace_events_dropped_total"
            )
        self._estimators: Dict[int, ClockOffsetEstimator] = {}
        self._seen: Dict[int, set] = {}        # applied frame seqs
        self._last_dropped: Dict[int, int] = {}  # worker cumulative
        self._labelled: set = set()
        self.frames = 0
        self.events = 0
        self.duplicates = 0
        # merged span/async/instant events per worker — observable
        # progress of each worker's stream (tests gate chaos on it: a
        # kill is only meaningful once the victim's spans ARRIVED)
        self.events_by_worker: Dict[int, int] = {}

    # --------------------------------------------------- clock alignment
    def estimator(self, worker: int) -> ClockOffsetEstimator:
        est = self._estimators.get(worker)
        if est is None:
            est = self._estimators[worker] = ClockOffsetEstimator()
        return est

    def add_clock_sample(self, worker: int, t0: float, t_remote: float,
                         t3: float) -> None:
        est = self.estimator(worker)
        if est.add(t0, t_remote, t3):
            # the estimate improved: stamp the skew model into the
            # timeline itself (local clock domain — t3 just happened)
            self.recorder.record_instant(
                "clock_offset", t3, pid=worker,
                attrs={"offset_s": est.offset, "bound_s": est.bound,
                       "rtt_s": est.min_rtt, "samples": est.total_samples},
            )

    def offset(self, worker: int) -> float:
        est = self._estimators.get(worker)
        return est.offset if est is not None else 0.0

    def skew_bound(self, worker: Optional[int] = None) -> Optional[float]:
        """The measured worst-case skew — one worker's, or the fleet
        max (the causality tolerance check_traces --fleet applies)."""
        if worker is not None:
            est = self._estimators.get(worker)
            return est.bound if est is not None else None
        bounds = [e.bound for e in self._estimators.values()
                  if e.bound is not None]
        return max(bounds) if bounds else None

    # ----------------------------------------------------------- labels
    def label_worker(self, worker: int, max_slots: int) -> None:
        """Name the worker's merged lanes (pid=worker, the same
        engine/slot tid layout label_replica stamps in-process)."""
        self._labelled.add(worker)
        self.recorder.set_process_name(worker, f"worker-{worker}")
        self.recorder.set_thread_name(worker, ENGINE_LANE, "engine")
        for s in range(max_slots):
            self.recorder.set_thread_name(
                worker, SLOT_LANE_BASE + s, f"slot{s}")

    # ------------------------------------------------------ the ingest
    def on_worker_restart(self, worker: int) -> None:
        """A new incarnation numbers its own frames and runs its own
        clock: forget the old stream's dedup set, offset, and drop
        baseline (cumulative counts restart at 0)."""
        self._seen.pop(worker, None)
        self._last_dropped.pop(worker, None)
        est = self._estimators.get(worker)
        if est is not None:
            est.reset()

    def ingest(self, worker: int, frame: dict) -> int:
        """Merge one ``trace`` push frame; returns events applied
        (0 for a duplicate)."""
        seq = frame.get("seq")
        if seq is not None:
            seen = self._seen.setdefault(worker, set())
            if seq in seen:
                self.duplicates += 1
                return 0
            seen.add(seq)
            if len(seen) > 8192:   # bounded dedup window, newest kept
                cut = max(seen) - 8192
                self._seen[worker] = {s for s in seen if s > cut}
        dropped = frame.get("dropped")
        if dropped is not None:
            delta = dropped - self._last_dropped.get(worker, 0)
            if delta > 0:
                self.recorder.count_external_drops(delta)
            self._last_dropped[worker] = dropped
        if not self.recorder.enabled:
            # plane toggled off: the frame is consumed (seq marked,
            # drops booked) but nothing merges — record_* would no-op
            # silently, and counting phantom events would make
            # `events_by_worker` overstate what the timeline holds
            return 0
        off = self.offset(worker)
        rec = self.recorder
        # sampling coherence: a worker only streams spans for requests
        # it decided belong in the timeline (head-sampled or tail-kept).
        # If the router staged its own records for such a trace (its
        # dispatch/failover instants), honor the worker's keep decision
        # — one request, one verdict, fleet-wide.
        if rec.sampler is not None:
            for t in {ev.get("trace_id") for ev in frame.get("events", ())
                      if ev.get("trace_id") is not None}:
                rec.note_keep(t, "remote")
        n = 0
        for ev in frame.get("events", ()):
            kind = ev.get("kind")
            if kind == "span":
                rec.record_span(
                    ev["name"], ev["t0"] - off, ev["t1"] - off,
                    trace_id=ev.get("trace_id"), pid=ev.get("pid", worker),
                    tid=ev.get("tid", 0), attrs=ev.get("attrs"),
                )
            elif kind == "async":
                rec.record_async(
                    ev["name"], ev["t0"] - off, ev["t1"] - off,
                    trace_id=ev.get("trace_id"),
                    pid=ev.get("pid", worker), attrs=ev.get("attrs"),
                )
            elif kind == "instant":
                rec.record_instant(
                    ev["name"], ev["t"] - off,
                    trace_id=ev.get("trace_id"),
                    pid=ev.get("pid", worker), tid=ev.get("tid", 0),
                    attrs=ev.get("attrs"),
                )
            elif kind == "meta":
                # the collector's worker-N lane names win over the
                # worker's own replicaN process label; thread names
                # (engine/slotK) pass through for lanes not yet named
                if ev.get("meta") == "process_name":
                    if ev.get("pid") not in self._labelled:
                        rec.set_process_name(ev["pid"], ev["name"])
                elif ev.get("meta") == "thread_name":
                    key = (ev.get("pid"), ev.get("tid"))
                    if key not in rec._thread_names:
                        rec.set_thread_name(ev["pid"], ev["tid"],
                                            ev["name"])
                n -= 1  # meta is bookkeeping, not a merged event
            else:
                n -= 1
            n += 1
        self.frames += 1
        self.events += max(0, n)
        self.events_by_worker[worker] = (
            self.events_by_worker.get(worker, 0) + max(0, n)
        )
        return max(0, n)
