"""Request-lifecycle tracing: Dapper-style spans over an injectable clock.

The serving stack (Scheduler -> SlotEngine/PagedEngine -> Router) and the
training loop both answer "where did the time go?" with aggregate gauges
only (utils/metrics.py) — a bad TTFT or a failover hop leaves no record
of queue wait vs bucketed prefill vs decode-burst stalls vs retry hops.
This module is the missing recorder:

- **Host-pure and thread-safe.** Nothing here touches jax; appends are
  deque ops under the GIL, snapshots take the lock. A serve loop is
  single-threaded, but submission may come from another thread.
- **Injectable clock.** The recorder reads time through the same clock
  the schedulers use (`MonotonicClock` in production, `FakeClock` in
  tests), so a chaos replay's trace is bit-for-bit deterministic.
- **Bounded.** Records live in a ring buffer (`max_events`); a
  long-lived server's tracing memory is O(1), and the exported timeline
  is the most recent window — a flight recorder, not an archive.
- **Zero-overhead when off.** A disabled recorder's `span()` returns a
  shared no-op context manager and `instant()` returns immediately; the
  instrumented hot paths additionally gate on `tracer is not None`, so
  the production default (no tracer) pays a single attribute test.
- **Streamable.** An optional `sink` (utils/telemetry.py
  TelemetryExporter) receives every record as a plain dict THE MOMENT it
  is recorded — line-delimited JSONL export that survives a SIGKILL,
  where `save()` (the exit-time Chrome dump) would leave nothing.
  tools/check_traces.py validates both forms.

Three record kinds, three Chrome trace-event encodings
(`to_chrome_trace()` emits the JSON Perfetto / chrome://tracing /
vLLM's tooling consume):

- **Lane spans** (`span()` / `record_span()`): synchronous work on one
  (pid, tid) lane — a prefill dispatch on a slot lane, a decode burst
  on the engine lane, a train step phase. Exported as matched B/E
  pairs, properly nested per lane (tools/check_traces.py validates).
- **Request spans** (`record_async()`): per-request lifecycle intervals
  ("request", "queued") that overlap freely across requests. Exported
  as Chrome ASYNC events (ph "b"/"e") keyed by `id=trace_id`, so one
  request renders as one timeline row however many replicas it crossed.
- **Instants** (`instant()`): point events (shed, retry, failover,
  brownout flip) — ph "i".

Lane conventions for serving (shared by both engines and the router):
pid = replica id (`ROUTER_PID` for the router's own lane), tid 0 =
`ENGINE_LANE` (decode dispatches + scheduler instants), tid 1+slot =
the slot's prefill lane. `label_replica()` / `label_router()` stamp the
matching process/thread-name metadata so traces open pre-labelled.

Trace-id propagation is the router's failover contract: a re-admitted
request's sub-Request carries the ORIGINAL trace_id, so a crash-migrated
request's spans on the survivor join the same async track as its spans
on the dead replica — one request, one timeline (pinned in
tests/test_trace.py). The engines also name their
`jax.profiler.TraceAnnotation` regions with the dispatch's trace-ids, so
a device timeline captured by utils/profiling.py lines up with the host
spans by name (utils/xprof.py reads the device side back).
"""

from __future__ import annotations

import contextlib
import itertools
import json
import threading
import time
from collections import defaultdict, deque
from typing import Dict, Optional

# record kinds (internal)
_DUR, _ASYNC, _INSTANT = 0, 1, 2

# serving lane conventions (see module doc)
ENGINE_LANE = 0          # tid for decode dispatches + scheduler instants
SLOT_LANE_BASE = 1       # tid = SLOT_LANE_BASE + slot for prefill spans
ROUTER_PID = -1          # the router's own pid (replicas are 0..N-1)

# the shared no-op span: what a disabled recorder hands out, and what
# instrumented hot paths use when no tracer is attached at all
NULL_SPAN = contextlib.nullcontext()
_NULL_SPAN = NULL_SPAN


def _resolve_clock(clock):
    """Accept a scheduler-style clock object (has .now()), a plain
    callable, or None (wall monotonic)."""
    if clock is None:
        return time.monotonic
    now = getattr(clock, "now", None)
    if callable(now):
        return now
    if callable(clock):
        return clock
    raise TypeError(f"clock must have .now() or be callable: {clock!r}")


class _Rec:
    __slots__ = ("kind", "name", "t0", "t1", "pid", "tid", "trace_id",
                 "attrs", "seq")

    def __init__(self, kind, name, t0, t1, pid, tid, trace_id, attrs, seq):
        self.kind = kind
        self.name = name
        self.t0 = t0
        self.t1 = t1
        self.pid = pid
        self.tid = tid
        self.trace_id = trace_id
        self.attrs = attrs
        self.seq = seq


class _Span:
    """Context manager for one lane span; created only when enabled."""

    __slots__ = ("rec", "name", "trace_id", "pid", "tid", "attrs", "t0")

    def __init__(self, rec, name, trace_id, pid, tid, attrs):
        self.rec = rec
        self.name = name
        self.trace_id = trace_id
        self.pid = pid
        self.tid = tid
        self.attrs = attrs

    def __enter__(self):
        self.t0 = self.rec._now()
        return self

    def __exit__(self, exc_type, exc, tb):
        self.rec.record_span(
            self.name, self.t0, self.rec._now(), trace_id=self.trace_id,
            pid=self.pid, tid=self.tid, attrs=self.attrs,
        )
        return False


class TraceRecorder:
    """Bounded, clock-injected span/event recorder (see module doc)."""

    def __init__(self, *, clock=None, max_events: int = 65536,
                 enabled: bool = True, sink=None,
                 drop_counter=None) -> None:
        if max_events < 1:
            raise ValueError("max_events must be positive")
        self._now = _resolve_clock(clock)
        self.enabled = enabled
        self._records: deque = deque(maxlen=max_events)
        self._seq = itertools.count()
        self._lock = threading.Lock()
        # span-loss accounting: the ring buffer SILENTLY evicts the
        # oldest record when full — count every eviction (plus drops
        # reported by external producers, e.g. a worker's bounded trace
        # buffer via TraceCollector) so a truncated timeline is
        # observable instead of quietly validating. `drop_counter` is an
        # optional utils/metrics.py Counter (trace_events_dropped_total);
        # the count is also stamped into the export metadata so
        # tools/check_traces.py can warn.
        self.dropped = 0
        self._drop_counter = drop_counter
        self._process_names: Dict[int, str] = {}
        self._thread_names: Dict[tuple, str] = {}
        # streaming sink (utils/telemetry.py TelemetryExporter): called
        # with one plain dict per record AS IT IS RECORDED, so a killed
        # run's events survive outside this ring buffer. None = the
        # exit-time export (save()) is the only output.
        self._sink = None
        if sink is not None:
            self.set_sink(sink)

    def set_sink(self, sink) -> None:
        """Attach a streaming consumer: `sink(record_dict)` per span/
        async/instant record (kind-tagged; see _stream) plus one "meta"
        record per lane label. Already-recorded lane labels are replayed
        into the sink at attach time, so a sink attached after
        label_replica() still knows every pid."""
        self._sink = sink
        for pid, name in self._process_names.items():
            sink({"kind": "meta", "meta": "process_name",
                  "pid": pid, "name": name})
        for (pid, tid), name in self._thread_names.items():
            sink({"kind": "meta", "meta": "thread_name",
                  "pid": pid, "tid": tid, "name": name})

    def _stream(self, rec: dict) -> None:
        if self._sink is not None:
            self._sink(rec)

    def _stream_record(self, kind: str, name, pid, tid, trace_id,
                       attrs, **times) -> None:
        """Build + emit one sink record (callers gate on `_sink is not
        None` first, so the no-sink hot path never builds the dict).
        The stream schema has ONE producer: change it here, and every
        record kind follows."""
        rec = {"kind": kind, "name": name, **times, "pid": pid}
        if tid is not None:
            rec["tid"] = tid
        if trace_id is not None:
            rec["trace_id"] = trace_id
        if attrs:
            rec["attrs"] = attrs
        self._sink(rec)

    # ------------------------------------------------------------ recording
    def now(self) -> float:
        return self._now()

    def _append(self, rec: "_Rec") -> None:
        if len(self._records) == self._records.maxlen:
            self._note_drops(1)
        self._records.append(rec)

    def _note_drops(self, n: int) -> None:
        if n <= 0:
            return
        self.dropped += n
        if self._drop_counter is not None:
            self._drop_counter.inc(n)

    def count_external_drops(self, n: int) -> None:
        """Fold drops that happened OUTSIDE this ring buffer (a worker's
        bounded trace buffer, a full push queue) into this recorder's
        loss accounting — one number answers "is this timeline whole"."""
        self._note_drops(n)

    def span(self, name: str, *, trace_id: Optional[str] = None,
             pid: int = 0, tid: int = 0, **attrs):
        """Lane span context manager; a shared no-op when disabled."""
        if not self.enabled:
            return _NULL_SPAN
        return _Span(self, name, trace_id, pid, tid, attrs)

    def record_span(self, name: str, t0: float, t1: float, *,
                    trace_id: Optional[str] = None, pid: int = 0,
                    tid: int = 0, attrs: Optional[dict] = None) -> None:
        """Explicit-timestamp lane span (for intervals the caller timed)."""
        if not self.enabled:
            return
        self._append(_Rec(
            _DUR, name, t0, t1, pid, tid, trace_id, attrs, next(self._seq)
        ))
        if self._sink is not None:
            self._stream_record("span", name, pid, tid, trace_id, attrs,
                                t0=t0, t1=t1)

    def record_async(self, name: str, t0: float, t1: float, *,
                     trace_id: str, pid: int = 0,
                     attrs: Optional[dict] = None) -> None:
        """Per-request interval: exported as async b/e keyed by trace_id,
        so overlapping requests never fight over one lane's B/E stack."""
        if not self.enabled:
            return
        self._append(_Rec(
            _ASYNC, name, t0, t1, pid, 0, trace_id, attrs, next(self._seq)
        ))
        if self._sink is not None:
            self._stream_record("async", name, pid, None, trace_id,
                                attrs, t0=t0, t1=t1)

    def instant(self, name: str, *, trace_id: Optional[str] = None,
                pid: int = 0, tid: int = 0, **attrs) -> None:
        if not self.enabled:
            return
        self.record_instant(name, self._now(), trace_id=trace_id,
                            pid=pid, tid=tid, attrs=attrs or None)

    def record_instant(self, name: str, t: float, *,
                       trace_id: Optional[str] = None, pid: int = 0,
                       tid: int = 0, attrs: Optional[dict] = None) -> None:
        """Explicit-timestamp instant — for events timed in another
        process's clock domain (TraceCollector merges worker instants
        with the measured offset already applied)."""
        if not self.enabled:
            return
        self._append(_Rec(
            _INSTANT, name, t, t, pid, tid, trace_id, attrs or None,
            next(self._seq)
        ))
        if self._sink is not None:
            self._stream_record("instant", name, pid, tid, trace_id,
                                attrs or None, t=t)

    # ------------------------------------------------------------- metadata
    def set_process_name(self, pid: int, name: str) -> None:
        self._process_names[pid] = name
        self._stream({"kind": "meta", "meta": "process_name",
                      "pid": pid, "name": name})

    def set_thread_name(self, pid: int, tid: int, name: str) -> None:
        self._thread_names[(pid, tid)] = name
        self._stream({"kind": "meta", "meta": "thread_name",
                      "pid": pid, "tid": tid, "name": name})

    # ------------------------------------------------------------- plumbing
    def __len__(self) -> int:
        return len(self._records)

    def clear(self) -> None:
        """Drop recorded events (lane labels survive) — e.g. after a
        warmup phase whose compile-time spans would dwarf the workload."""
        with self._lock:
            self._records.clear()

    def disable(self) -> None:
        self.enabled = False

    def enable(self) -> None:
        self.enabled = True

    # --------------------------------------------------------------- export
    def to_chrome_trace(self) -> dict:
        """Render the ring buffer as Chrome trace-event JSON.

        Lane spans become matched B/E pairs, emitted per (pid, tid) in
        stack order (outer-first at shared starts), so zero-duration
        spans on a FakeClock still nest cleanly; request spans become
        async b/e pairs keyed by id=trace_id; instants become ph "i".
        ts is microseconds of the recorder's clock domain.
        """
        with self._lock:
            records = list(self._records)
        events = []
        pids = ({r.pid for r in records} | set(self._process_names))
        for pid in sorted(pids):
            events.append({
                "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
                "args": {"name": self._process_names.get(pid, f"pid{pid}")},
            })
        lane_tids = {(r.pid, r.tid) for r in records if r.kind == _DUR}
        for (pid, tid) in sorted(set(self._thread_names) | lane_tids):
            events.append({
                "name": "thread_name", "ph": "M", "pid": pid, "tid": tid,
                "args": {"name": self._thread_names.get(
                    (pid, tid), f"tid{tid}")},
            })

        def us(t: float) -> float:
            return round(t * 1e6, 3)

        def begin(r: _Rec, ph: str) -> dict:
            ev = {"name": r.name, "ph": ph, "ts": us(r.t0),
                  "pid": r.pid, "tid": r.tid}
            args = dict(r.attrs) if r.attrs else {}
            if r.trace_id is not None:
                args["trace_id"] = r.trace_id
            if args:
                ev["args"] = args
            if ph == "b":
                ev["cat"] = "request"
                ev["id"] = r.trace_id
            return ev

        def end(r: _Rec, ph: str) -> dict:
            ev = {"name": r.name, "ph": ph, "ts": us(r.t1),
                  "pid": r.pid, "tid": r.tid}
            if ph == "e":
                ev["cat"] = "request"
                ev["id"] = r.trace_id
            return ev

        def sweep(recs, b_ph, e_ph):
            """Emit properly nested begin/end pairs for one lane: sort by
            (start, -end, seq), close every span that ends at-or-before
            the next span's start, drain at the end. Genuinely crossing
            intervals come out ts-disordered — the validator flags them
            rather than this export papering over them."""
            recs.sort(key=lambda r: (r.t0, -r.t1, r.seq))
            stack = []
            for r in recs:
                while stack and stack[-1].t1 <= r.t0:
                    events.append(end(stack.pop(), e_ph))
                events.append(begin(r, b_ph))
                stack.append(r)
            while stack:
                events.append(end(stack.pop(), e_ph))

        lanes = defaultdict(list)
        asyncs = defaultdict(list)
        instants = []
        for r in records:
            if r.kind == _DUR:
                lanes[(r.pid, r.tid)].append(r)
            elif r.kind == _ASYNC:
                asyncs[(r.pid, r.trace_id)].append(r)
            else:
                instants.append(r)
        for key in sorted(lanes):
            sweep(lanes[key], "B", "E")
        for key in sorted(asyncs, key=lambda k: (k[0], str(k[1]))):
            sweep(asyncs[key], "b", "e")
        for r in instants:
            ev = begin(r, "i")
            ev["s"] = "t"  # thread-scoped instant
            events.append(ev)
        out = {"traceEvents": events, "displayTimeUnit": "ms"}
        if self.dropped:
            # a flight recorder that lost events must SAY so: the
            # validator (tools/check_traces.py) warns on this instead of
            # blessing a quietly truncated timeline
            out["metadata"] = {"trace_events_dropped": self.dropped}
        return out

    def save(self, path: str) -> None:
        """Write the Chrome trace JSON (open in Perfetto / chrome://tracing)."""
        with open(path, "w") as f:
            json.dump(self.to_chrome_trace(), f)


# ------------------------------------------------------- lane label helpers
def label_replica(recorder: TraceRecorder, replica: int,
                  max_slots: int) -> None:
    """Stamp the serving lane names for one replica: pid=replica,
    tid 0 = engine (decode dispatches), tid 1+slot = prefill lanes."""
    recorder.set_process_name(replica, f"replica{replica}")
    recorder.set_thread_name(replica, ENGINE_LANE, "engine")
    for s in range(max_slots):
        recorder.set_thread_name(replica, SLOT_LANE_BASE + s, f"slot{s}")


def label_router(recorder: TraceRecorder) -> None:
    recorder.set_process_name(ROUTER_PID, "router")
    recorder.set_thread_name(ROUTER_PID, 0, "dispatch")


# ------------------------------------------------------- fleet trace plane
class ClockOffsetEstimator:
    """NTP-style clock-offset estimate from RPC round trips.

    Worker processes stamp trace events with their OWN clocks; merging
    them onto the router's timeline needs the per-worker offset. Each
    ping/poll round trip yields one sample: the client reads its clock
    before (t0) and after (t3) the call, the worker stamps its clock
    (tw) while handling it; then

        offset = tw - (t0 + t3) / 2        (remote minus local)

    with worst-case error rtt/2 — the classic symmetric-delay bound
    (the true receive instant lies somewhere inside [t0, t3]; assuming
    the midpoint is wrong by at most half the round trip, however
    asymmetric the two legs actually were). So the BEST sample is the
    minimum-RTT one: we keep the lowest-RTT samples seen and answer
    with the lowest's offset, `bound` = its rtt/2. `reset()` on
    reconnect/restart — a new worker incarnation is a new clock domain.
    """

    def __init__(self, max_samples: int = 32) -> None:
        self.max_samples = max_samples
        self._samples: list = []   # (rtt, offset), sorted ascending rtt
        self.total_samples = 0

    def add(self, t0: float, t_remote: float, t3: float) -> bool:
        """Fold one round trip in; True when the best (min-RTT) sample
        — and therefore the answer — changed."""
        if t3 < t0:
            return False  # a torn reading is not a sample
        rtt = t3 - t0
        offset = t_remote - 0.5 * (t0 + t3)
        self.total_samples += 1
        best_before = self._samples[0] if self._samples else None
        self._samples.append((rtt, offset))
        self._samples.sort(key=lambda s: s[0])
        del self._samples[self.max_samples:]
        return self._samples[0] != best_before

    @property
    def n_samples(self) -> int:
        return len(self._samples)

    @property
    def offset(self) -> float:
        """Best current estimate of (remote clock - local clock); 0.0
        until a sample exists (merge unshifted rather than invent)."""
        return self._samples[0][1] if self._samples else 0.0

    @property
    def min_rtt(self) -> Optional[float]:
        return self._samples[0][0] if self._samples else None

    @property
    def bound(self) -> Optional[float]:
        """Worst-case error of `offset` (min observed rtt / 2)."""
        return self._samples[0][0] / 2.0 if self._samples else None

    def reset(self) -> None:
        self._samples.clear()


class TraceCollector:
    """Router-side merge of worker-streamed trace events into ONE fleet
    recorder.

    Workers record their own prefill/decode_burst/queued/request spans
    locally (serve/worker.py) and push them back over the RPC push
    stream as batched ``trace`` frames; this collector folds each frame
    into the fleet TraceRecorder so `--trace-out` exports one merged
    timeline — the Dapper collection step. Contracts:

    - **pid = worker lane.** Events arrive already stamped with the
      worker's replica pid (the PR-4 lane convention); `label_worker`
      names that lane ``worker-N`` so the merged trace reads as a fleet,
      and the worker's own ``replicaN`` process_name meta is dropped in
      favour of it. Cross-process trace_id propagation is untouched —
      a SIGKILL-failover request's pre-crash spans (streamed before the
      kill) and its survivor spans share the original trace_id, so it
      renders as ONE timeline.
    - **Clock alignment.** Every event timestamp is shifted by the
      worker's measured offset (ClockOffsetEstimator, fed by the
      handle's ping/poll round trips) at merge time; the current
      offset/bound is recorded as a ``clock_offset`` instant on the
      worker's lane whenever the estimate improves, so the exported
      trace carries its own skew model (tools/check_traces.py --fleet
      reads it back as the causality tolerance).
    - **At-most-once, any order.** Frames carry a per-incarnation
      sequence number; duplicates (transport retry / stream+poll
      overlap) are skipped, out-of-order frames merge fine because
      every record carries absolute timestamps (the exporter sorts).
      `on_worker_restart` resets seq dedup and the offset — a new
      process is a new stream and a new clock.
    - **Loss is counted, never silent.** Frames carry the worker's
      cumulative dropped count (bounded buffer + full push queues);
      the delta folds into the fleet recorder's `dropped` (and the
      optional ``trace_events_dropped_total`` counter), which the
      export stamps into its metadata.
    """

    def __init__(self, recorder: TraceRecorder, *,
                 registry=None) -> None:
        self.recorder = recorder
        if registry is not None and recorder._drop_counter is None:
            recorder._drop_counter = registry.counter(
                "trace_events_dropped_total"
            )
        self._estimators: Dict[int, ClockOffsetEstimator] = {}
        self._seen: Dict[int, set] = {}        # applied frame seqs
        self._last_dropped: Dict[int, int] = {}  # worker cumulative
        self._labelled: set = set()
        self.frames = 0
        self.events = 0
        self.duplicates = 0
        # merged span/async/instant events per worker — observable
        # progress of each worker's stream (tests gate chaos on it: a
        # kill is only meaningful once the victim's spans ARRIVED)
        self.events_by_worker: Dict[int, int] = {}

    # --------------------------------------------------- clock alignment
    def estimator(self, worker: int) -> ClockOffsetEstimator:
        est = self._estimators.get(worker)
        if est is None:
            est = self._estimators[worker] = ClockOffsetEstimator()
        return est

    def add_clock_sample(self, worker: int, t0: float, t_remote: float,
                         t3: float) -> None:
        est = self.estimator(worker)
        if est.add(t0, t_remote, t3):
            # the estimate improved: stamp the skew model into the
            # timeline itself (local clock domain — t3 just happened)
            self.recorder.record_instant(
                "clock_offset", t3, pid=worker,
                attrs={"offset_s": est.offset, "bound_s": est.bound,
                       "rtt_s": est.min_rtt, "samples": est.total_samples},
            )

    def offset(self, worker: int) -> float:
        est = self._estimators.get(worker)
        return est.offset if est is not None else 0.0

    def skew_bound(self, worker: Optional[int] = None) -> Optional[float]:
        """The measured worst-case skew — one worker's, or the fleet
        max (the causality tolerance check_traces --fleet applies)."""
        if worker is not None:
            est = self._estimators.get(worker)
            return est.bound if est is not None else None
        bounds = [e.bound for e in self._estimators.values()
                  if e.bound is not None]
        return max(bounds) if bounds else None

    # ----------------------------------------------------------- labels
    def label_worker(self, worker: int, max_slots: int) -> None:
        """Name the worker's merged lanes (pid=worker, the same
        engine/slot tid layout label_replica stamps in-process)."""
        self._labelled.add(worker)
        self.recorder.set_process_name(worker, f"worker-{worker}")
        self.recorder.set_thread_name(worker, ENGINE_LANE, "engine")
        for s in range(max_slots):
            self.recorder.set_thread_name(
                worker, SLOT_LANE_BASE + s, f"slot{s}")

    # ------------------------------------------------------ the ingest
    def on_worker_restart(self, worker: int) -> None:
        """A new incarnation numbers its own frames and runs its own
        clock: forget the old stream's dedup set, offset, and drop
        baseline (cumulative counts restart at 0)."""
        self._seen.pop(worker, None)
        self._last_dropped.pop(worker, None)
        est = self._estimators.get(worker)
        if est is not None:
            est.reset()

    def ingest(self, worker: int, frame: dict) -> int:
        """Merge one ``trace`` push frame; returns events applied
        (0 for a duplicate)."""
        seq = frame.get("seq")
        if seq is not None:
            seen = self._seen.setdefault(worker, set())
            if seq in seen:
                self.duplicates += 1
                return 0
            seen.add(seq)
            if len(seen) > 8192:   # bounded dedup window, newest kept
                cut = max(seen) - 8192
                self._seen[worker] = {s for s in seen if s > cut}
        dropped = frame.get("dropped")
        if dropped is not None:
            delta = dropped - self._last_dropped.get(worker, 0)
            if delta > 0:
                self.recorder.count_external_drops(delta)
            self._last_dropped[worker] = dropped
        if not self.recorder.enabled:
            # plane toggled off: the frame is consumed (seq marked,
            # drops booked) but nothing merges — record_* would no-op
            # silently, and counting phantom events would make
            # `events_by_worker` overstate what the timeline holds
            return 0
        off = self.offset(worker)
        rec = self.recorder
        n = 0
        for ev in frame.get("events", ()):
            kind = ev.get("kind")
            if kind == "span":
                rec.record_span(
                    ev["name"], ev["t0"] - off, ev["t1"] - off,
                    trace_id=ev.get("trace_id"), pid=ev.get("pid", worker),
                    tid=ev.get("tid", 0), attrs=ev.get("attrs"),
                )
            elif kind == "async":
                rec.record_async(
                    ev["name"], ev["t0"] - off, ev["t1"] - off,
                    trace_id=ev.get("trace_id"),
                    pid=ev.get("pid", worker), attrs=ev.get("attrs"),
                )
            elif kind == "instant":
                rec.record_instant(
                    ev["name"], ev["t"] - off,
                    trace_id=ev.get("trace_id"),
                    pid=ev.get("pid", worker), tid=ev.get("tid", 0),
                    attrs=ev.get("attrs"),
                )
            elif kind == "meta":
                # the collector's worker-N lane names win over the
                # worker's own replicaN process label; thread names
                # (engine/slotK) pass through for lanes not yet named
                if ev.get("meta") == "process_name":
                    if ev.get("pid") not in self._labelled:
                        rec.set_process_name(ev["pid"], ev["name"])
                elif ev.get("meta") == "thread_name":
                    key = (ev.get("pid"), ev.get("tid"))
                    if key not in rec._thread_names:
                        rec.set_thread_name(ev["pid"], ev["tid"],
                                            ev["name"])
                n -= 1  # meta is bookkeeping, not a merged event
            else:
                n -= 1
            n += 1
        self.frames += 1
        self.events += max(0, n)
        self.events_by_worker[worker] = (
            self.events_by_worker.get(worker, 0) + max(0, n)
        )
        return max(0, n)
