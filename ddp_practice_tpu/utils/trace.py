"""Request-lifecycle tracing: Dapper-style spans over an injectable clock.

The serving stack (Scheduler -> SlotEngine/PagedEngine -> Router) and the
training loop both answer "where did the time go?" with aggregate gauges
only (utils/metrics.py) — a bad TTFT or a failover hop leaves no record
of queue wait vs bucketed prefill vs decode-burst stalls vs retry hops.
This module is the missing recorder:

- **Host-pure and thread-safe.** Nothing here touches jax; appends are
  deque ops under the GIL, snapshots take the lock. A serve loop is
  single-threaded, but submission may come from another thread.
- **Injectable clock.** The recorder reads time through the same clock
  the schedulers use (`MonotonicClock` in production, `FakeClock` in
  tests), so a chaos replay's trace is bit-for-bit deterministic.
- **Bounded.** Records live in a ring buffer (`max_events`); a
  long-lived server's tracing memory is O(1), and the exported timeline
  is the most recent window — a flight recorder, not an archive.
- **Zero-overhead when off.** A disabled recorder's `span()` returns a
  shared no-op context manager and `instant()` returns immediately; the
  instrumented hot paths additionally gate on `tracer is not None`, so
  the production default (no tracer) pays a single attribute test.
- **Streamable.** An optional `sink` (utils/telemetry.py
  TelemetryExporter) receives every record as a plain dict THE MOMENT it
  is recorded — line-delimited JSONL export that survives a SIGKILL,
  where `save()` (the exit-time Chrome dump) would leave nothing.
  tools/check_traces.py validates both forms.

Three record kinds, three Chrome trace-event encodings
(`to_chrome_trace()` emits the JSON Perfetto / chrome://tracing /
vLLM's tooling consume):

- **Lane spans** (`span()` / `record_span()`): synchronous work on one
  (pid, tid) lane — a prefill dispatch on a slot lane, a decode burst
  on the engine lane, a train step phase. Exported as matched B/E
  pairs, properly nested per lane (tools/check_traces.py validates).
- **Request spans** (`record_async()`): per-request lifecycle intervals
  ("request", "queued") that overlap freely across requests. Exported
  as Chrome ASYNC events (ph "b"/"e") keyed by `id=trace_id`, so one
  request renders as one timeline row however many replicas it crossed.
- **Instants** (`instant()`): point events (shed, retry, failover,
  brownout flip) — ph "i".

Lane conventions for serving (shared by both engines and the router):
pid = replica id (`ROUTER_PID` for the router's own lane), tid 0 =
`ENGINE_LANE` (decode dispatches + scheduler instants), tid 1+slot =
the slot's prefill lane. `label_replica()` / `label_router()` stamp the
matching process/thread-name metadata so traces open pre-labelled.

Trace-id propagation is the router's failover contract: a re-admitted
request's sub-Request carries the ORIGINAL trace_id, so a crash-migrated
request's spans on the survivor join the same async track as its spans
on the dead replica — one request, one timeline (pinned in
tests/test_trace.py). The engines also name their
`jax.profiler.TraceAnnotation` regions with the dispatch's trace-ids, so
a device timeline captured by utils/profiling.py lines up with the host
spans by name (utils/xprof.py reads the device side back).
"""

from __future__ import annotations

import contextlib
import itertools
import json
import threading
import time
from collections import defaultdict, deque
from typing import Dict, Optional

# record kinds (internal)
_DUR, _ASYNC, _INSTANT = 0, 1, 2

# serving lane conventions (see module doc)
ENGINE_LANE = 0          # tid for decode dispatches + scheduler instants
SLOT_LANE_BASE = 1       # tid = SLOT_LANE_BASE + slot for prefill spans
ROUTER_PID = -1          # the router's own pid (replicas are 0..N-1)

# the shared no-op span: what a disabled recorder hands out, and what
# instrumented hot paths use when no tracer is attached at all
NULL_SPAN = contextlib.nullcontext()
_NULL_SPAN = NULL_SPAN


def _resolve_clock(clock):
    """Accept a scheduler-style clock object (has .now()), a plain
    callable, or None (wall monotonic)."""
    if clock is None:
        return time.monotonic
    now = getattr(clock, "now", None)
    if callable(now):
        return now
    if callable(clock):
        return clock
    raise TypeError(f"clock must have .now() or be callable: {clock!r}")


class _Rec:
    __slots__ = ("kind", "name", "t0", "t1", "pid", "tid", "trace_id",
                 "attrs", "seq")

    def __init__(self, kind, name, t0, t1, pid, tid, trace_id, attrs, seq):
        self.kind = kind
        self.name = name
        self.t0 = t0
        self.t1 = t1
        self.pid = pid
        self.tid = tid
        self.trace_id = trace_id
        self.attrs = attrs
        self.seq = seq


class _Span:
    """Context manager for one lane span; created only when enabled."""

    __slots__ = ("rec", "name", "trace_id", "pid", "tid", "attrs", "t0")

    def __init__(self, rec, name, trace_id, pid, tid, attrs):
        self.rec = rec
        self.name = name
        self.trace_id = trace_id
        self.pid = pid
        self.tid = tid
        self.attrs = attrs

    def __enter__(self):
        self.t0 = self.rec._now()
        return self

    def __exit__(self, exc_type, exc, tb):
        self.rec.record_span(
            self.name, self.t0, self.rec._now(), trace_id=self.trace_id,
            pid=self.pid, tid=self.tid, attrs=self.attrs,
        )
        return False


class TraceRecorder:
    """Bounded, clock-injected span/event recorder (see module doc)."""

    def __init__(self, *, clock=None, max_events: int = 65536,
                 enabled: bool = True, sink=None) -> None:
        if max_events < 1:
            raise ValueError("max_events must be positive")
        self._now = _resolve_clock(clock)
        self.enabled = enabled
        self._records: deque = deque(maxlen=max_events)
        self._seq = itertools.count()
        self._lock = threading.Lock()
        self._process_names: Dict[int, str] = {}
        self._thread_names: Dict[tuple, str] = {}
        # streaming sink (utils/telemetry.py TelemetryExporter): called
        # with one plain dict per record AS IT IS RECORDED, so a killed
        # run's events survive outside this ring buffer. None = the
        # exit-time export (save()) is the only output.
        self._sink = None
        if sink is not None:
            self.set_sink(sink)

    def set_sink(self, sink) -> None:
        """Attach a streaming consumer: `sink(record_dict)` per span/
        async/instant record (kind-tagged; see _stream) plus one "meta"
        record per lane label. Already-recorded lane labels are replayed
        into the sink at attach time, so a sink attached after
        label_replica() still knows every pid."""
        self._sink = sink
        for pid, name in self._process_names.items():
            sink({"kind": "meta", "meta": "process_name",
                  "pid": pid, "name": name})
        for (pid, tid), name in self._thread_names.items():
            sink({"kind": "meta", "meta": "thread_name",
                  "pid": pid, "tid": tid, "name": name})

    def _stream(self, rec: dict) -> None:
        if self._sink is not None:
            self._sink(rec)

    def _stream_record(self, kind: str, name, pid, tid, trace_id,
                       attrs, **times) -> None:
        """Build + emit one sink record (callers gate on `_sink is not
        None` first, so the no-sink hot path never builds the dict).
        The stream schema has ONE producer: change it here, and every
        record kind follows."""
        rec = {"kind": kind, "name": name, **times, "pid": pid}
        if tid is not None:
            rec["tid"] = tid
        if trace_id is not None:
            rec["trace_id"] = trace_id
        if attrs:
            rec["attrs"] = attrs
        self._sink(rec)

    # ------------------------------------------------------------ recording
    def now(self) -> float:
        return self._now()

    def span(self, name: str, *, trace_id: Optional[str] = None,
             pid: int = 0, tid: int = 0, **attrs):
        """Lane span context manager; a shared no-op when disabled."""
        if not self.enabled:
            return _NULL_SPAN
        return _Span(self, name, trace_id, pid, tid, attrs)

    def record_span(self, name: str, t0: float, t1: float, *,
                    trace_id: Optional[str] = None, pid: int = 0,
                    tid: int = 0, attrs: Optional[dict] = None) -> None:
        """Explicit-timestamp lane span (for intervals the caller timed)."""
        if not self.enabled:
            return
        self._records.append(_Rec(
            _DUR, name, t0, t1, pid, tid, trace_id, attrs, next(self._seq)
        ))
        if self._sink is not None:
            self._stream_record("span", name, pid, tid, trace_id, attrs,
                                t0=t0, t1=t1)

    def record_async(self, name: str, t0: float, t1: float, *,
                     trace_id: str, pid: int = 0,
                     attrs: Optional[dict] = None) -> None:
        """Per-request interval: exported as async b/e keyed by trace_id,
        so overlapping requests never fight over one lane's B/E stack."""
        if not self.enabled:
            return
        self._records.append(_Rec(
            _ASYNC, name, t0, t1, pid, 0, trace_id, attrs, next(self._seq)
        ))
        if self._sink is not None:
            self._stream_record("async", name, pid, None, trace_id,
                                attrs, t0=t0, t1=t1)

    def instant(self, name: str, *, trace_id: Optional[str] = None,
                pid: int = 0, tid: int = 0, **attrs) -> None:
        if not self.enabled:
            return
        t = self._now()
        self._records.append(_Rec(
            _INSTANT, name, t, t, pid, tid, trace_id, attrs or None,
            next(self._seq)
        ))
        if self._sink is not None:
            self._stream_record("instant", name, pid, tid, trace_id,
                                attrs or None, t=t)

    # ------------------------------------------------------------- metadata
    def set_process_name(self, pid: int, name: str) -> None:
        self._process_names[pid] = name
        self._stream({"kind": "meta", "meta": "process_name",
                      "pid": pid, "name": name})

    def set_thread_name(self, pid: int, tid: int, name: str) -> None:
        self._thread_names[(pid, tid)] = name
        self._stream({"kind": "meta", "meta": "thread_name",
                      "pid": pid, "tid": tid, "name": name})

    # ------------------------------------------------------------- plumbing
    def __len__(self) -> int:
        return len(self._records)

    def clear(self) -> None:
        """Drop recorded events (lane labels survive) — e.g. after a
        warmup phase whose compile-time spans would dwarf the workload."""
        with self._lock:
            self._records.clear()

    def disable(self) -> None:
        self.enabled = False

    def enable(self) -> None:
        self.enabled = True

    # --------------------------------------------------------------- export
    def to_chrome_trace(self) -> dict:
        """Render the ring buffer as Chrome trace-event JSON.

        Lane spans become matched B/E pairs, emitted per (pid, tid) in
        stack order (outer-first at shared starts), so zero-duration
        spans on a FakeClock still nest cleanly; request spans become
        async b/e pairs keyed by id=trace_id; instants become ph "i".
        ts is microseconds of the recorder's clock domain.
        """
        with self._lock:
            records = list(self._records)
        events = []
        pids = ({r.pid for r in records} | set(self._process_names))
        for pid in sorted(pids):
            events.append({
                "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
                "args": {"name": self._process_names.get(pid, f"pid{pid}")},
            })
        lane_tids = {(r.pid, r.tid) for r in records if r.kind == _DUR}
        for (pid, tid) in sorted(set(self._thread_names) | lane_tids):
            events.append({
                "name": "thread_name", "ph": "M", "pid": pid, "tid": tid,
                "args": {"name": self._thread_names.get(
                    (pid, tid), f"tid{tid}")},
            })

        def us(t: float) -> float:
            return round(t * 1e6, 3)

        def begin(r: _Rec, ph: str) -> dict:
            ev = {"name": r.name, "ph": ph, "ts": us(r.t0),
                  "pid": r.pid, "tid": r.tid}
            args = dict(r.attrs) if r.attrs else {}
            if r.trace_id is not None:
                args["trace_id"] = r.trace_id
            if args:
                ev["args"] = args
            if ph == "b":
                ev["cat"] = "request"
                ev["id"] = r.trace_id
            return ev

        def end(r: _Rec, ph: str) -> dict:
            ev = {"name": r.name, "ph": ph, "ts": us(r.t1),
                  "pid": r.pid, "tid": r.tid}
            if ph == "e":
                ev["cat"] = "request"
                ev["id"] = r.trace_id
            return ev

        def sweep(recs, b_ph, e_ph):
            """Emit properly nested begin/end pairs for one lane: sort by
            (start, -end, seq), close every span that ends at-or-before
            the next span's start, drain at the end. Genuinely crossing
            intervals come out ts-disordered — the validator flags them
            rather than this export papering over them."""
            recs.sort(key=lambda r: (r.t0, -r.t1, r.seq))
            stack = []
            for r in recs:
                while stack and stack[-1].t1 <= r.t0:
                    events.append(end(stack.pop(), e_ph))
                events.append(begin(r, b_ph))
                stack.append(r)
            while stack:
                events.append(end(stack.pop(), e_ph))

        lanes = defaultdict(list)
        asyncs = defaultdict(list)
        instants = []
        for r in records:
            if r.kind == _DUR:
                lanes[(r.pid, r.tid)].append(r)
            elif r.kind == _ASYNC:
                asyncs[(r.pid, r.trace_id)].append(r)
            else:
                instants.append(r)
        for key in sorted(lanes):
            sweep(lanes[key], "B", "E")
        for key in sorted(asyncs, key=lambda k: (k[0], str(k[1]))):
            sweep(asyncs[key], "b", "e")
        for r in instants:
            ev = begin(r, "i")
            ev["s"] = "t"  # thread-scoped instant
            events.append(ev)
        return {"traceEvents": events, "displayTimeUnit": "ms"}

    def save(self, path: str) -> None:
        """Write the Chrome trace JSON (open in Perfetto / chrome://tracing)."""
        with open(path, "w") as f:
            json.dump(self.to_chrome_trace(), f)


# ------------------------------------------------------- lane label helpers
def label_replica(recorder: TraceRecorder, replica: int,
                  max_slots: int) -> None:
    """Stamp the serving lane names for one replica: pid=replica,
    tid 0 = engine (decode dispatches), tid 1+slot = prefill lanes."""
    recorder.set_process_name(replica, f"replica{replica}")
    recorder.set_thread_name(replica, ENGINE_LANE, "engine")
    for s in range(max_slots):
        recorder.set_thread_name(replica, SLOT_LANE_BASE + s, f"slot{s}")


def label_router(recorder: TraceRecorder) -> None:
    recorder.set_process_name(ROUTER_PID, "router")
    recorder.set_thread_name(ROUTER_PID, 0, "dispatch")
