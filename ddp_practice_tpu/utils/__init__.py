"""Utilities: process-0 logging, metrics registry, timing, profiling."""

from ddp_practice_tpu.utils.logging import (
    emit_metrics,
    get_logger,
    main_process_only,
)
from ddp_practice_tpu.utils.metrics import (
    MetricsRegistry,
    default_registry,
)
from ddp_practice_tpu.utils.timing import Timer
from ddp_practice_tpu.utils.profiling import profile_region

__all__ = [
    "emit_metrics",
    "get_logger",
    "main_process_only",
    "MetricsRegistry",
    "default_registry",
    "Timer",
    "profile_region",
]
