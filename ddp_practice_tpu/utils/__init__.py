"""Utilities: process-0 logging, metrics registry, backoff, timing,
profiling."""

from ddp_practice_tpu.utils.logging import (
    emit_metrics,
    get_logger,
    main_process_only,
)
from ddp_practice_tpu.utils.backoff import backoff_delay
from ddp_practice_tpu.utils.metrics import (
    MetricsRegistry,
    default_registry,
    labelled,
)
from ddp_practice_tpu.utils.timing import Timer
from ddp_practice_tpu.utils.profiling import profile_region

__all__ = [
    "backoff_delay",
    "emit_metrics",
    "get_logger",
    "labelled",
    "main_process_only",
    "MetricsRegistry",
    "default_registry",
    "Timer",
    "profile_region",
]
