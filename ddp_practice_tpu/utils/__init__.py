"""Utilities: process-0 logging, timing, profiling hooks."""

from ddp_practice_tpu.utils.logging import get_logger, main_process_only
from ddp_practice_tpu.utils.timing import Timer
from ddp_practice_tpu.utils.profiling import profile_region

__all__ = ["get_logger", "main_process_only", "Timer", "profile_region"]
