"""Profiling hooks (SURVEY §5.1 — absent from the reference; built here).

Wraps `jax.profiler`: traces dump to a directory viewable in
TensorBoard/Perfetto/XProf; step/epoch regions are annotated with
`TraceAnnotation` so device timelines line up with the training loop.
"""

from __future__ import annotations

import contextlib
from typing import Optional

import jax


@contextlib.contextmanager
def profile_region(name: str, profile_dir: Optional[str] = None):
    """Annotate a region; if profile_dir is set, capture a full trace."""
    if profile_dir:
        jax.profiler.start_trace(profile_dir)
    try:
        with jax.profiler.TraceAnnotation(name):
            yield
    finally:
        if profile_dir:
            jax.profiler.stop_trace()


@contextlib.contextmanager
def step_annotation(step: int):
    with jax.profiler.StepTraceAnnotation("train", step_num=step):
        yield
