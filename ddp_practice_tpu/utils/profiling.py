"""Profiling hooks (SURVEY §5.1 — absent from the reference; built here).

Wraps `jax.profiler`: traces dump to a directory viewable in
TensorBoard/Perfetto/XProf; step/epoch regions are annotated with
`TraceAnnotation` so device timelines line up with the training loop
(and, since the serving engines name their annotations with request
trace-ids, with utils/trace.py host spans too).

`profile_region` is re-entrancy-safe and exception-transparent:

- the jax profiler is a process-global singleton, so only the OUTERMOST
  region holding a `profile_dir` starts/stops a capture — nested regions
  (or a region inside a loop-managed `start_trace`) annotate only,
  instead of crashing with "profiler already started";
- a `stop_trace()` failure on the way out of a body that already raised
  is logged and swallowed — the body's real exception propagates, not
  the secondary teardown error. When the body succeeded, a stop failure
  is real signal and raises normally.
"""

from __future__ import annotations

import contextlib
import logging
import threading
from typing import Optional

import jax

log = logging.getLogger(__name__)

# process-global: is a trace WE started currently active? (the jax
# profiler itself is a singleton; this mirrors just enough of its state
# to make nesting a no-op instead of a crash)
_lock = threading.Lock()
_trace_active = False


def _try_start(profile_dir: str) -> bool:
    """Start a capture if no profile_region capture is active; True if
    THIS call now owns the stop. An externally-started profiler (e.g.
    train/loop.py's epoch-window start_trace) surfaces as RuntimeError —
    treated the same as nesting: annotate only."""
    global _trace_active
    with _lock:
        if _trace_active:
            return False
        try:
            jax.profiler.start_trace(profile_dir)
        except RuntimeError as e:  # profiler already started elsewhere
            log.warning("profile_region: not starting a trace (%s)", e)
            return False
        _trace_active = True
        return True


def _stop(swallow: bool) -> None:
    """Stop the capture this module started. The active flag drops
    FIRST, so a failing stop cannot wedge every later region into
    annotate-only mode against a profiler that is actually stopped."""
    global _trace_active
    with _lock:
        _trace_active = False
        try:
            jax.profiler.stop_trace()
        except Exception:
            if not swallow:
                raise
            log.exception(
                "profile_region: stop_trace failed (suppressed — the "
                "body's exception is the one that matters)"
            )


@contextlib.contextmanager
def profile_region(name: str, profile_dir: Optional[str] = None):
    """Annotate a region; if profile_dir is set, capture a full trace.
    Nested capture requests annotate only (see module doc)."""
    owns = bool(profile_dir) and _try_start(profile_dir)
    try:
        with jax.profiler.TraceAnnotation(name):
            yield
    except BaseException:
        if owns:
            _stop(swallow=True)
        raise
    else:
        if owns:
            _stop(swallow=False)


@contextlib.contextmanager
def step_annotation(step: int):
    with jax.profiler.StepTraceAnnotation("train", step_num=step):
        yield
