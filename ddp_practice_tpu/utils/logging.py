"""Process-0-gated logging.

The reference gates prints and saves on rank 0 by hand at each site
(ddp_main.py:158-169); here the gate is one decorator / logger filter.
"""

from __future__ import annotations

import functools
import logging
import sys


def get_logger(name: str = "ddp_practice_tpu") -> logging.Logger:
    logger = logging.getLogger(name)
    if not logger.handlers:
        handler = logging.StreamHandler(sys.stdout)
        handler.setFormatter(
            logging.Formatter("%(asctime)s %(name)s %(levelname)s %(message)s")
        )
        logger.addHandler(handler)
        logger.setLevel(logging.INFO)
        logger.propagate = False
    return logger


def main_process_only(fn):
    """Run fn only on process 0 — the rank-0 side-effect gate."""

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        import jax

        if jax.process_index() == 0:
            return fn(*args, **kwargs)
        return None

    return wrapper


@main_process_only
def emit_metrics(snapshot: dict, logger: logging.Logger = None) -> str:
    """Log one `metrics {...}` JSON line — process 0 only.

    The serving observability sink (serve/metrics.py): every replica of a
    multi-host server runs the same scheduler loop and accumulates the
    same registry, so an ungated emit would print one duplicate line per
    host. Routed through `main_process_only`, consistent with every other
    rank-0 side effect in the framework (train/loop.py info0/warn0).
    Returns the rendered line (None on non-0 processes — the decorator's
    contract), which is what the unit test pins.
    """
    import json

    line = "metrics " + json.dumps(snapshot, sort_keys=True, default=float)
    (logger or get_logger("ddp_practice_tpu.serve")).info(line)
    return line
