"""Process-0-gated logging.

The reference gates prints and saves on rank 0 by hand at each site
(ddp_main.py:158-169); here the gate is one decorator / logger filter.
"""

from __future__ import annotations

import functools
import logging
import sys


def get_logger(name: str = "ddp_practice_tpu") -> logging.Logger:
    logger = logging.getLogger(name)
    if not logger.handlers:
        handler = logging.StreamHandler(sys.stdout)
        handler.setFormatter(
            logging.Formatter("%(asctime)s %(name)s %(levelname)s %(message)s")
        )
        logger.addHandler(handler)
        logger.setLevel(logging.INFO)
        logger.propagate = False
    return logger


def main_process_only(fn):
    """Run fn only on process 0 — the rank-0 side-effect gate."""

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        import jax

        if jax.process_index() == 0:
            return fn(*args, **kwargs)
        return None

    return wrapper
