"""CLI launcher.

One command replaces both reference launch styles (SURVEY §2.4): the
mp.spawn parent (ddp_main.py:173-178) and torchrun (README launch cmd) —
on TPU there is one process per host, so "launching" is just running this
module; multi-host runs add --coordinator (no hardcoded port — the
reference pins 19198, ddp_main.py:62).

Parity flags kept: -e/--epochs (default 3), -b/--batch_size (default 32,
per data-parallel replica) — origin_main.py:34-54. `--gpu` has no TPU
meaning; `--devices N` limits visible local devices instead.

Examples:
  python -m ddp_practice_tpu.cli                      # ConvNet/MNIST parity run
  python -m ddp_practice_tpu.cli --precision bf16     # the "AMP" variant
  python -m ddp_practice_tpu.cli --model vit_tiny --dataset cifar10 \\
      --tensor 2 --optimizer adamw --lr 1e-3
  python -m ddp_practice_tpu.cli serve                # continuous-batching
                                                      # serve bench (serve/)
  python -m ddp_practice_tpu.cli serve --replicas 2 \\
      --fault-plan '{"faults": [{"kind": "crash", "tick": 40}]}'
                                       # fault-tolerant router fleet:
                                       # goodput under injected faults
  python -m ddp_practice_tpu.cli serve --procs 2  # CROSS-PROCESS fleet:
                                       # real worker OS processes behind
                                       # the RPC seam (serve/worker.py,
                                       # supervised + federated telemetry)
  python -m ddp_practice_tpu.cli serve --procs 2 --rate 100 \\
      --fault-plan '{"faults": [{"kind": "kill", "at_s": 1.0}]}'
                                       # chaos with teeth: SIGKILL a live
                                       # worker mid-decode, goodput +
                                       # zero-lost measured for real
  python -m ddp_practice_tpu.cli serve --procs 2 --trace-out fleet.json
                                       # FLEET tracing: worker spans
                                       # stream back + merge into ONE
                                       # clock-aligned timeline; validate
                                       # with check_traces.py --fleet
  python -m ddp_practice_tpu.cli serve --procs 2 \\
      --otlp-endpoint http://collector:4318/v1/traces
                                       # LIVE egress: kept spans batch-
                                       # POST to an OTLP/HTTP collector
                                       # as they land (bounded queue,
                                       # retry backoff, dead-endpoint
                                       # breaker; at-least-once with
                                       # batch-id dedup)
  python -m ddp_practice_tpu.cli serve --procs 2 --rate 100 \\
      --adaptive-sampling --trace-budget-sps 150
                                       # adaptive head rate: a feedback
                                       # loop steers kept-spans/s to the
                                       # budget through a 4x load step,
                                       # pushing rate changes live over
                                       # the rpc trace op
  python -m ddp_practice_tpu.cli serve --procs 2 \\
      --trace-tenant-rates '{"acme": 1.0, "free-tier": 0.01}'
                                       # per-tenant head rates: tenants
                                       # keep their own sampling floor;
                                       # tail keeps (faults, failovers)
                                       # stay tenant-blind
  python -m ddp_practice_tpu.cli serve --procs 3 --autoscale --rate 25
                                       # ELASTIC fleet vs the peak-
                                       # provisioned fixed arm through a
                                       # 4x arrival step: trip-fast scale
                                       # up from a pre-warmed standby
                                       # (ms, not ~15 s), resolve-slow
                                       # drain back down; gates goodput/
                                       # worker-second, reaction time,
                                       # zero lost, oscillation bound
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import sys
import time

from ddp_practice_tpu.config import MeshConfig, TrainConfig


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser("ddp_practice_tpu")
    p.add_argument("-e", "--epochs", type=int, default=3)
    p.add_argument("-b", "--batch_size", type=int, default=32,
                   help="per data-parallel replica, like the reference")
    p.add_argument("--model", default="convnet",
                   choices=["convnet", "resnet18", "resnet50", "vit_tiny",
                            "vit_base", "vit_tiny_moe", "vit_tiny_pipe",
                            "lm_tiny", "lm_base", "lm_moe", "lm_pipe"])
    p.add_argument("--num_heads", type=int, default=0,
                   help="override attention head count (transformer models; "
                        "0 = model default — note tensor parallelism needs "
                        "heads divisible by the tensor degree)")
    p.add_argument("--dataset", default="mnist",
                   help="image models: mnist|cifar10|imagenet|synthetic; "
                        "lm models: text (bytes from --data_dir) or "
                        "anything else for the synthetic Markov corpus")
    p.add_argument("--seq_len", type=int, default=256,
                   help="LM sequence length (lm_* models)")
    p.add_argument("--remat", action="store_true",
                   help="rematerialize LM block activations in backward "
                        "(longer sequences for ~1/3 more FLOPs)")
    p.add_argument("--pos_emb", default="learned", choices=["learned", "rope"],
                   help="LM position encoding: learned absolute table or "
                        "rotary Q/K (relative; long-context default)")
    p.add_argument("--tied", action="store_true",
                   help="tie the LM output projection to the token "
                        "embedding (GPT-2 weight tying)")
    p.add_argument("--data_dir", default="./data")
    p.add_argument("--synthetic_size", type=int, default=0,
                   help="synthetic-fallback corpus size (train split; "
                        "0 = per-dataset default)")
    p.add_argument("--lr", type=float, default=1e-4)
    p.add_argument("--optimizer", default="sgd", choices=["sgd", "adam", "adamw"])
    p.add_argument("--momentum", type=float, default=0.0)
    p.add_argument("--clip_norm", type=float, default=0.0,
                   help="clip gradients to this global L2 norm (0 = off)")
    p.add_argument("--dropout", type=float, default=0.0,
                   help="dropout rate for the ViT/LM transformer blocks "
                        "(residual branches + LM embedding; 0 = off)")
    p.add_argument("--weight_decay", type=float, default=0.0)
    p.add_argument("--lr_schedule", default="constant",
                   choices=["constant", "cosine", "warmup_cosine"])
    p.add_argument("--accum_steps", type=int, default=1,
                   help="gradient accumulation: average grads over k "
                        "micro-steps before each optimizer apply")
    p.add_argument("--scale_lr", action="store_true",
                   help="scale lr by replica count (the reference deliberately "
                        "does not; README.md:506)")
    p.add_argument("--seed", type=int, default=3407)
    p.add_argument("--precision", default="fp32", choices=["fp32", "bf16"])
    p.add_argument("--data_axis", type=int, default=-1)
    p.add_argument("--seq", type=int, default=1, help="sequence-parallel degree")
    p.add_argument("--tensor", type=int, default=1, help="tensor-parallel degree")
    p.add_argument("--pipe", type=int, default=1, help="pipeline-parallel stages")
    p.add_argument("--expert", type=int, default=1, help="expert-parallel degree")
    p.add_argument("--sp_impl", default="ring", choices=["ring", "ulysses"],
                   help="sequence-parallel attention scheme")
    p.add_argument("--attn_impl", default="xla", choices=["xla", "flash"],
                   help="local attention kernel (flash = Pallas tiled)")
    p.add_argument("--microbatches", type=int, default=4,
                   help="GPipe microbatches per step (pipe > 1)")
    p.add_argument("--pipe_schedule", default="gpipe",
                   choices=["gpipe", "1f1b", "interleaved"],
                   help="pipeline schedule (pipe > 1): gpipe = autodiff "
                        "scan, activation memory O(M+P); 1f1b = one-F-one-B "
                        "backward, O(P) memory; interleaved = virtual "
                        "pipeline chunks (Megatron), ~V-fold smaller "
                        "bubble (LM models)")
    p.add_argument("--num_virtual", type=int, default=2,
                   help="virtual pipeline chunks per device (interleaved "
                        "schedule only; depth must divide pipe*V)")
    p.add_argument("--num_experts", type=int, default=0,
                   help="MoE expert count (0 = auto from --expert axis)")
    p.add_argument("--moe_router", default="topk",
                   choices=["topk", "expert_choice"],
                   help="MoE routing scheme: topk = tokens choose experts "
                        "(GShard/Switch; aux loss + balance bias + capacity "
                        "drops); expert_choice = experts choose tokens "
                        "(perfect balance, zero drops/padding — ops/moe.py)")
    p.add_argument("--fsdp", action="store_true",
                   help="ZeRO-3: shard params + optimizer state over 'data'")
    p.add_argument("--devices", type=int, default=0,
                   help="use only the first N local devices (0 = all)")
    p.add_argument("--cpu", type=int, default=0, metavar="N",
                   help="force the CPU platform with N virtual devices "
                        "(sharding dev-runs without TPU hardware; set via "
                        "jax.config because TPU plugins override env vars)")
    p.add_argument("--coordinator", default=None,
                   help="host:port for multi-host rendezvous")
    p.add_argument("--num_processes", type=int, default=None)
    p.add_argument("--process_id", type=int, default=None)
    p.add_argument("--ckpt_dir", default=None)
    p.add_argument("--ckpt_every", type=int, default=0, metavar="STEPS",
                   help="also checkpoint every N optimizer steps "
                        "(async write; 0 = only per-epoch/end)")
    p.add_argument("--ckpt_sync", action="store_true",
                   help="force synchronous periodic checkpoint writes")
    p.add_argument("--resume", action="store_true")
    p.add_argument("--max_restarts", type=int, default=0,
                   help="checkpoint-based restarts on training failure")
    p.add_argument("--watchdog", type=float, default=0.0, metavar="SECS",
                   help="fail-fast if no step completes within SECS")
    p.add_argument("--sync_check", type=int, default=0, metavar="STEPS",
                   help="assert cross-host driver sync every STEPS steps")
    p.add_argument("--eval_every", type=int, default=0)
    p.add_argument("--max_steps", type=int, default=0,
                   help="cap steps per epoch (smoke runs; 0 = full epoch)")
    p.add_argument("--log_every", type=int, default=100)
    p.add_argument("--profile_dir", default=None)
    p.add_argument("--trace-out", "--trace_out", dest="trace_out",
                   default=None, metavar="PATH",
                   help="write a Chrome trace-event JSON of host-side "
                        "step phases (data/dispatch/block/checkpoint "
                        "spans) at fit end — open in Perfetto; "
                        "validate with tools/check_traces.py")
    p.add_argument("--metrics_file", default=None, metavar="PATH",
                   help="append one JSON record per logged step / eval / "
                        "summary (training curves; process 0 only)")
    p.add_argument("--metrics-port", "--metrics_port", dest="metrics_port",
                   type=int, default=None, metavar="PORT",
                   help="serve /metrics (Prometheus), /healthz and "
                        "/flight (rolling step-time percentiles) over "
                        "HTTP during the fit (0 = ephemeral port, "
                        "logged at startup; process 0 only)")
    p.add_argument("--telemetry-out", "--telemetry_out",
                   dest="telemetry_out", default=None, metavar="PATH",
                   help="stream step spans + step records + metrics "
                        "snapshots as line-delimited JSONL while "
                        "training (survives a killed run; validate "
                        "with tools/check_traces.py)")
    p.add_argument("--slo", default=None, metavar="JSON|PATH",
                   help="SLO config (serve/slo.py) — arms a burn-rate "
                        "watchdog over the step-time straggler "
                        "detector; alerts land in the telemetry "
                        "stream and the metrics registry")
    p.add_argument("--alert-sink", "--alert_sink", dest="alert_sink",
                   action="append", default=None, metavar="KIND:TARGET",
                   help="repeatable; PUSH SLO alert edges to an "
                        "operator sink (command:..., webhook:http://..., "
                        "jsonl:path) with retry backoff and a dead-sink "
                        "breaker (serve/slo.py AlertSinks); needs --slo")
    p.add_argument("--loader", default="auto", choices=["auto", "native", "python"])
    p.add_argument("--steps_per_call", type=int, default=1,
                   help="K optimizer steps per jitted call (amortizes host "
                        "dispatch + H2D for small models); -1 = the whole "
                        "epoch per call (device-resident data only)")
    p.add_argument("--data_placement", default="auto",
                   choices=["auto", "host", "device"],
                   help="corpus home: device = upload once to HBM, epochs "
                        "driven by index grids alone; host = stream batches; "
                        "auto = device when single-process and it fits")
    p.add_argument("--compile_cache", default="auto",
                   help="persistent XLA compilation cache dir (repeat runs "
                        "skip compile); auto = ~/.cache/ddp_practice_tpu/xla, "
                        "off = disable")
    p.add_argument("--fused", nargs="?", const="on", default="auto",
                   choices=["auto", "on", "off"],
                   help="fused Pallas encoder-layer kernels "
                        "(ops/fused_encoder.py — the small-d HBM-bound "
                        "fix). auto (default): selected whenever the "
                        "model/shape supports them (vit_tiny, dense LMs "
                        "with head_dim a multiple of 64 via --num_heads), "
                        "silent per-op fallback otherwise; on (or bare "
                        "--fused): force, raising on unsupported configs "
                        "(exception: an MoE-interleaved LM fuses its DENSE "
                        "blocks only — routed blocks have no fused kernel); "
                        "off: always per-op")
    p.add_argument("--augment", action="store_true",
                   help="on-device augmentation inside the jitted train "
                        "step (image models; deterministic per seed/step — "
                        "ops/augment.py)")
    p.add_argument("--augment_kind", default="crop_flip",
                   choices=["crop_flip", "rrc"],
                   help="crop_flip: pad-crop + flip (CIFAR/MNIST rung); "
                        "rrc: random resized crop (the ImageNet rung)")
    p.add_argument("--json", action="store_true", help="print summary as JSON")
    return p


def _alert_sinks_from(args):
    if not args.alert_sink:
        return None
    if not args.slo:
        # the sinks only ever carry the watchdog's edges — accepting
        # them without --slo would arm a pager that can never fire
        raise SystemExit("--alert-sink needs --slo (the sinks carry "
                         "the watchdog's trip/resolve edges)")
    return tuple(args.alert_sink)


def config_from_args(args) -> TrainConfig:
    if args.augment_kind != "crop_flip" and not args.augment:
        raise SystemExit(
            "--augment_kind has no effect without --augment — pass both "
            "(the run would otherwise train UNAUGMENTED while its flags "
            "suggest otherwise)"
        )
    return TrainConfig(
        model=args.model,
        dataset=args.dataset,
        data_dir=args.data_dir,
        synthetic_size=args.synthetic_size,
        seq_len=args.seq_len,
        remat=args.remat,
        pos_emb=args.pos_emb,
        tied_embeddings=args.tied,
        epochs=args.epochs,
        batch_size=args.batch_size,
        learning_rate=args.lr,
        optimizer=args.optimizer,
        momentum=args.momentum,
        clip_norm=args.clip_norm,
        dropout=args.dropout,
        weight_decay=args.weight_decay,
        lr_schedule=args.lr_schedule,
        scale_lr_by_replicas=args.scale_lr,
        accum_steps=args.accum_steps,
        seed=args.seed,
        precision=args.precision,
        mesh=MeshConfig(
            data=args.data_axis, seq=args.seq, tensor=args.tensor,
            pipe=args.pipe, expert=args.expert,
        ),
        fsdp=args.fsdp,
        sp_impl=args.sp_impl,
        attn_impl=args.attn_impl,
        num_microbatches=args.microbatches,
        pipe_schedule=args.pipe_schedule,
        num_virtual=args.num_virtual,
        augment=args.augment,
        augment_kind=args.augment_kind,
        fused_encoder=args.fused,
        num_experts=args.num_experts,
        moe_router=args.moe_router,
        num_heads=args.num_heads,
        coordinator_address=args.coordinator,
        num_processes=args.num_processes,
        process_id=args.process_id,
        checkpoint_dir=args.ckpt_dir,
        checkpoint_every_steps=args.ckpt_every,
        checkpoint_async=not args.ckpt_sync,
        resume=args.resume,
        max_restarts=args.max_restarts,
        watchdog_timeout_s=args.watchdog,
        sync_check_every_steps=args.sync_check,
        eval_every_epochs=args.eval_every,
        max_steps_per_epoch=args.max_steps,
        log_every_steps=args.log_every,
        profile_dir=args.profile_dir,
        trace_out=args.trace_out,
        metrics_file=args.metrics_file,
        metrics_port=args.metrics_port,
        telemetry_out=args.telemetry_out,
        slo=args.slo,
        alert_sinks=_alert_sinks_from(args),
        loader_backend=args.loader,
        steps_per_call=args.steps_per_call,
        data_placement=args.data_placement,
        compilation_cache=args.compile_cache,
    )


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if argv and argv[0] == "serve":
        # inference subcommand: the training flags below don't apply, so
        # dispatch before the trainer parser sees the argv (serve/bench.py
        # owns the serve flag surface)
        from ddp_practice_tpu.serve.bench import main as serve_main

        return serve_main(argv[1:])
    args = build_parser().parse_args(argv)
    if args.devices:
        import os

        os.environ.setdefault("JAX_NUM_CPU_DEVICES", str(args.devices))
    if args.cpu:
        import os

        import jax

        jax.config.update("jax_platforms", "cpu")
        try:
            jax.config.update("jax_num_cpu_devices", args.cpu)
        except AttributeError:
            # older jax: the option doesn't exist; the XLA flag works as
            # long as jax hasn't initialized its backends yet (it hasn't —
            # the train loop import below is the first device touch)
            flags = os.environ.get("XLA_FLAGS", "")
            if "xla_force_host_platform_device_count" not in flags:
                os.environ["XLA_FLAGS"] = (
                    flags
                    + f" --xla_force_host_platform_device_count={args.cpu}"
                ).strip()
    from ddp_practice_tpu.train.loop import fit  # deferred: jax import cost

    t0 = time.time()
    summary = fit(config_from_args(args))
    summary["wall_seconds"] = time.time() - t0
    if args.json:
        print(json.dumps(summary))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
