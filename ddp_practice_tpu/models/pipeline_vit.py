"""Pipeline-parallel ViT: stage-sharded encoder stack under GPipe schedule.

No pipeline parallelism exists in the reference (SURVEY §2.3). This model
partitions the ViT encoder depth across the 'pipe' mesh axis: parameters of
all blocks are stacked on a leading depth dimension (initialized with a
vmap over per-block PRNG keys), sharded stage-wise, and applied through
`parallel.pipeline.pipeline_apply` — one compiled SPMD program, activations
hopping stages via ppermute (see that module for the schedule).

Embed (patch + position) and head (LN + pool + classifier) run outside the
pipeline under plain GSPMD, replicated over 'pipe'. Composes with the
'data' axis (microbatches split the per-shard batch), with 'tensor'
(Megatron specs on the stacked block leaves ride GSPMD inside each stage
— the pipeline shard_map is manual over 'pipe'/'data' only), and with
'seq' (ring/Ulysses open a nested island over the still-automatic seq
axis inside each stage). `init`/`apply` duck-type the flax module
interface the train steps consume, so the same `make_train_step` drives
pipelined and sequential models identically.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from ddp_practice_tpu.config import MeshConfig
from ddp_practice_tpu.models.vit import EncoderBlock, ViTEmbed, ViTHead
from ddp_practice_tpu.parallel.pipeline import pipeline_apply, stack_stages


class PipelinedViT:
    """Duck-typed model: init(rng, x) -> variables; apply(variables, x)."""

    def __init__(
        self,
        *,
        num_classes: int = 10,
        patch_size: int = 4,
        hidden_dim: int = 192,
        depth: int = 12,
        num_heads: int = 3,
        mlp_dim: int = 768,
        dtype: jnp.dtype = jnp.float32,
        param_dtype: jnp.dtype = jnp.float32,
        num_stages: int = 1,
        num_microbatches: int = 4,
        pipe_axis: str = MeshConfig.AXIS_PIPE,
        remat: bool = True,
        seq_axis: Optional[str] = None,
        sp_impl: str = "ring",
        attn_impl: str = "xla",
        axis_name: Optional[str] = None,
    ):
        if depth % max(num_stages, 1) != 0:
            raise ValueError(f"depth {depth} % stages {num_stages} != 0")
        self.depth = depth
        self.num_stages = num_stages
        self.num_microbatches = num_microbatches
        self.pipe_axis = pipe_axis
        self.remat = remat
        self.embed = ViTEmbed(
            patch_size=patch_size,
            hidden_dim=hidden_dim,
            dtype=dtype,
            param_dtype=param_dtype,
        )
        # seq_axis rides into each stage's attention: the pipeline
        # shard_map is manual over 'pipe'/'data' only, so ring/Ulysses
        # open their own nested island over the still-automatic 'seq'
        # axis (parallel/ring.py _island_mesh_and_spec) — sp x pp composes
        self.block = EncoderBlock(
            num_heads, mlp_dim, dtype=dtype, param_dtype=param_dtype,
            attn_impl=attn_impl, seq_axis=seq_axis, sp_impl=sp_impl,
        )
        self.head = ViTHead(
            num_classes=num_classes, dtype=dtype, param_dtype=param_dtype
        )

    def init(self, rng, x, *, train: bool = False):
        r_embed, r_blocks, r_head = jax.random.split(rng, 3)
        embed_vars = self.embed.init(r_embed, x)
        tokens = self.embed.apply(embed_vars, x)
        keys = jax.random.split(r_blocks, self.depth)
        block_params = jax.vmap(
            lambda k: self.block.init(k, tokens)["params"]
        )(keys)
        head_vars = self.head.init(r_head, tokens)
        return {
            "params": {
                "embed": embed_vars["params"],
                "blocks": block_params,
                "head": head_vars["params"],
            }
        }

    def apply(self, variables, x, *, train: bool = False, mutable=None,
              rngs=None):
        # rngs accepted for step-interface uniformity; unused (the
        # pipelined blocks have no stochastic layers — dropout_rate is not
        # a PipelinedViT knob, and the Trainer refuses --dropout for it)
        p = variables["params"]
        tokens = self.embed.apply({"params": p["embed"]}, x)
        tokens = self.run_blocks(p["blocks"], tokens)
        out = self.head.apply({"params": p["head"]}, tokens)
        if mutable is not None:
            return out, {}  # flax mutable-apply contract; nothing sown here
        return out

    def run_blocks(self, block_params, tokens):
        if self.num_stages <= 1:
            return self._sequential(block_params, tokens)
        stages = stack_stages(block_params, self.num_stages)

        def block_fn(stage_params, xb):
            def body(h, bp):
                return self.block.apply({"params": bp}, h), None

            h, _ = lax.scan(body, xb, stage_params)
            return h

        return pipeline_apply(
            block_fn,
            stages,
            tokens,
            num_microbatches=self.num_microbatches,
            axis_name=self.pipe_axis,
            remat=self.remat,
        )

    def _sequential(self, block_params, tokens):
        """Reference path (also used for numerics tests): same stacked
        params applied depth-sequentially without the pipeline."""

        def body(h, bp):
            return self.block.apply({"params": bp}, h), None

        h, _ = lax.scan(body, tokens, block_params)
        return h
