"""Decoder-only transformer language model — the long-context flagship.

Nothing like this exists in the reference (a 2-conv MNIST CNN,
origin_main.py:9-31); this is the model family that exercises the
framework's long-context machinery at the scale it was built for:
causal attention through `ops.attention.dot_product_attention`, so one
flag each selects the Pallas flash kernel (`attn_impl="flash"`, O(seq)
training memory) and sequence parallelism over the 'seq' mesh axis
(`seq_axis=...`, ring K/V rotation or Ulysses head scatter) — the same
composition matrix as the ViTs, now with the future masked.

TPU notes: the block stack reuses `models.vit.EncoderBlock` (pre-LN,
causal=True), so the tensor-parallel PartitionSpec rules that match the
ViT param names (`parallel/sharding_rules.py`) apply unchanged. The
embedding table and the (untied) output projection both shard over
'tensor' by name. Logits are fp32 (softmax stability under bf16 compute).

Wired surfaces: `bench.py --models lm_long` (tokens/sec + MFU at long
sequence on the real chip), `__graft_entry__.dryrun_multichip` (dp x sp
causal ring + flash case), `train/steps.py make_lm_train_step` (next-token
loss), `tests/test_lm.py`.
"""

from __future__ import annotations

from typing import Optional

import flax.linen as nn
import jax.numpy as jnp

from ddp_practice_tpu.models.vit import EncoderBlock


class TransformerLM(nn.Module):
    vocab_size: int = 256           # byte-level by default
    max_len: int = 2048
    hidden_dim: int = 256
    depth: int = 4
    num_heads: int = 8
    mlp_dim: int = 1024
    dtype: jnp.dtype = jnp.float32
    param_dtype: jnp.dtype = jnp.float32
    seq_axis: Optional[str] = None  # mesh axis for sequence parallelism
    sp_impl: str = "ring"
    attn_impl: str = "xla"
    # KV-cache storage dtype for decode: None (= compute dtype), a
    # jnp.dtype, or the string "int8" (quantized cache + scales); see
    # models/vit.py SelfAttention.kv_cache_dtype
    kv_cache_dtype: object = None
    # rematerialize each block's activations in the backward pass
    # (jax.checkpoint): trades ~1/3 more FLOPs for O(depth) less
    # activation memory — the standard long-context lever (with the
    # streaming flash kernels it makes training memory per block O(seq·d)
    # instead of O(seq·d·n_intermediates))
    remat: bool = False
    # "learned": absolute position table added to the embedding (GPT-2
    # style, tied to max_len). "rope": rotary Q/K inside every attention —
    # relative positions, the long-context default (ops/rope.py).
    pos_emb: str = "learned"
    # share the token-embedding table with the output projection (GPT-2
    # weight tying): logits = x @ tok_embed.T — removes the (d, vocab)
    # lm_head parameter. TP-consistent: tok_embed shards its vocab rows
    # over 'tensor' (sharding_rules._lm_rule), so the tied logits come out
    # vocab-sharded exactly like the untied column-parallel head.
    tied_embeddings: bool = False
    # embedding + residual-branch dropout (GPT-2 placement); never active
    # in decode mode (generation always runs deterministic)
    dropout_rate: float = 0.0
    # MoE composition: every `moe_every`-th block (GShard layout) swaps
    # its dense MLP for a routed expert MLP (ops/moe.py). 0 = dense.
    # Router health flows out as moe_* metrics (train/steps.py).
    moe_every: int = 0
    num_experts: int = 8
    moe_top_k: int = 2
    capacity_factor: float = 1.25
    # load-balance aux-loss weight: 0.01 (Switch/GShard convention) keeps
    # the warm router's drop rate ~10% on unstructured data; the bench
    # and balance test use the same knob (ops/moe.py top_k_gating)
    moe_aux_weight: float = 0.01
    # online selection-bias update rate (ops/moe.py MoEMlp
    # bias_update_rate); 0 disables the aux-free balancer
    moe_bias_rate: float = 0.02
    # tokens per routing group (0 = whole sequence); smaller groups cut
    # the dispatch einsum cost ~linearly at a measured capacity tradeoff
    # (ops/moe.py group_size)
    moe_group_size: int = 0
    moe_group_stride: bool = True
    # routing scheme: "topk" | "expert_choice" (ops/moe.py MoEMlp.router)
    moe_router: str = "topk"
    # run each block as ONE Pallas kernel per direction with causal
    # masking (ops/fused_encoder.py, round 4) — the small-d short-seq
    # HBM-bound fix, now available to decoder LMs. Training-only
    # execution strategy: params are identical to the unfused model, so
    # checkpoints generate through the normal (unfused) decode path.
    # Composes with pos_emb="learned" only (the kernel refuses rope).
    # "auto" (default, round 5) fuses when the EncoderBlock's
    # constraints hold — e.g. lm_tiny needs num_heads=4 for the 64-
    # aligned head_dim; the default heads=8 silently keeps per-op.
    fused: object = "auto"  # bool | "auto"
    axis_name: Optional[str] = None  # registry uniformity (no BN anywhere)

    @nn.compact
    def __call__(self, tokens, *, train: bool = False, decode: bool = False,
                 attn_start=None, page_table=None, kv_lengths=None):
        """tokens (batch, seq) int32 -> logits (batch, seq, vocab) in the
        policy compute dtype (consumers upcast — see the return comment).

        `decode=True` is KV-cache inference mode (inference.py): the call
        appends `s` tokens at the cache cursor instead of reading positions
        from zero, so the same instance serves training, prompt prefill
        (s = prompt length) and single-token generation steps (s = 1).
        Initialize the cache collection by calling `init`/`eval_shape` with
        a max-generation-length input and `decode=True`.

        `attn_start` (b,) int32, decode-only: first real (non-pad) key
        position per sequence — the variable-length-prompt mask for
        LEFT-padded batches (inference.py). Requires pos_emb="rope":
        rotary scores depend only on relative offsets, so a uniform left
        shift is invisible; a learned absolute table would silently
        misplace every real token, so that combination raises.

        `page_table` (b, max_blocks_per_slot) + `kv_lengths` (b,) int32,
        decode-only: paged KV-cache mode (serve/kv_pages.py) — the cache
        collection holds a pool of fixed-size blocks, each sequence
        writes/attends at its OWN slot-local position through its page
        table row, and attn_start/positions are slot-local. Requires
        pos_emb="rope" (per-slot offsets). s == 1 is the decode step;
        s > 1 is the paged PREFILL (prefix-cache admissions append a
        prompt suffix at kv_lengths, attending the shared prefix blocks
        through the table — models/vit.py `_paged_decode`).
        """
        if page_table is not None and self.pos_emb != "rope":
            raise ValueError(
                "paged decode needs pos_emb='rope' — per-slot positions "
                "require relative position encoding"
            )
        if page_table is not None and not decode:
            raise ValueError("page_table is a KV-cache decode feature")
        if attn_start is not None and self.pos_emb != "rope":
            raise ValueError(
                "variable-length (left-padded) prompts need pos_emb='rope' "
                "— learned absolute positions would shift with the padding"
            )
        if attn_start is not None and not decode:
            raise ValueError(
                "attn_start is a KV-cache decode feature (inference.py); "
                "the training forward has no left-padding mask"
            )
        b, s = tokens.shape
        if s > self.max_len:
            raise ValueError(f"sequence {s} exceeds max_len {self.max_len}")
        embed = nn.Embed(
            self.vocab_size,
            self.hidden_dim,
            dtype=self.dtype,
            param_dtype=self.param_dtype,
            name="tok_embed",
        )
        x = embed(tokens)
        if self.pos_emb not in ("learned", "rope"):
            raise ValueError(
                f"unknown pos_emb {self.pos_emb!r} (want 'learned'|'rope')"
            )
        if self.pos_emb == "learned":
            pos = self.param(
                "pos_embed",
                nn.initializers.normal(stddev=0.02),
                (1, self.max_len, self.hidden_dim),
                self.param_dtype,
            )
            if decode:
                # the position cursor mirrors the attention caches' write
                # index (they advance in lockstep; this one lives at the top
                # level so the embedding lookup doesn't reach into a block's
                # variables)
                pos_index = self.variable(
                    "cache", "pos_index", lambda: jnp.zeros((), jnp.int32)
                )
                if self.is_initializing():
                    x = x + pos[:, :s].astype(self.dtype)
                else:
                    from jax import lax

                    p = lax.dynamic_slice(
                        pos, (0, pos_index.value, 0), (1, s, self.hidden_dim)
                    )
                    x = x + p.astype(self.dtype)
                    pos_index.value = pos_index.value + s
            else:
                x = x + pos[:, :s].astype(self.dtype)
        # rope: positions enter inside each attention (the blocks' caches
        # already track the decode cursor; nothing to add at the embedding)
        x = nn.Dropout(
            self.dropout_rate, deterministic=not (train and not decode)
        )(x)
        # remat only matters for the training backward pass; the decode path
        # mutates cache variables, which jax.checkpoint must not wrap. The
        # (decode, train) call args are static under remat (argnums 2, 3 —
        # self is 0), so dropout composes with rematerialization.
        block_cls = (
            nn.remat(EncoderBlock, static_argnums=(2, 3))
            if (self.remat and not decode)
            else EncoderBlock
        )
        for i in range(self.depth):
            block_moe = (
                self.moe_every > 0
                and i % self.moe_every == self.moe_every - 1
            )
            block = block_cls(
                self.num_heads,
                self.mlp_dim,
                dtype=self.dtype,
                param_dtype=self.param_dtype,
                seq_axis=self.seq_axis,
                sp_impl=self.sp_impl,
                attn_impl=self.attn_impl,
                causal=True,
                rope=self.pos_emb == "rope",
                kv_cache_dtype=self.kv_cache_dtype,
                dropout_rate=self.dropout_rate,
                use_moe=block_moe,
                num_experts=self.num_experts,
                moe_top_k=self.moe_top_k,
                capacity_factor=self.capacity_factor,
                moe_aux_weight=self.moe_aux_weight,
                moe_bias_rate=self.moe_bias_rate,
                moe_group_size=self.moe_group_size,
                moe_group_stride=self.moe_group_stride,
                moe_router=self.moe_router,
                # tri-state pass-through ("auto" must survive; `and` would
                # collapse it to a bool). decode always takes the per-op
                # KV-cache path; routed blocks can never fuse (the kernel
                # has no expert dispatch), so a forced fused=True means
                # "fuse every DENSE block" rather than raising on the
                # MoE-interleaved layout
                fused=False if (decode or block_moe) else self.fused,
                name=f"block{i}",
            )
            # positional (decode, train): nn.remat's static_argnums are
            # positional indices. Dropout never fires in decode mode —
            # generation is deterministic whatever the caller passes.
            # attn_start only rides the decode path (remat never applies
            # there, so the array kwarg never meets jax.checkpoint).
            if decode and (attn_start is not None or page_table is not None):
                x = block(x, True, False, attn_start=attn_start,
                          page_table=page_table, kv_lengths=kv_lengths)
            else:
                x = block(x, decode, train and not decode)
        x = nn.LayerNorm(
            dtype=self.dtype, param_dtype=self.param_dtype, name="ln_f"
        )(x)
        if self.tied_embeddings:
            logits = embed.attend(x)  # x @ tok_embed.T, no lm_head param
        else:
            # bias-free, the GPT-2 convention — and not only cosmetics:
            # the bias GRADIENT is a full rowsum pass over the
            # (tokens, V) dlogits tensor, 1.4 ms/step of pure HBM reads
            # at lm_base/32k-vocab (round-4 profile), for a learned
            # per-class log-prior offset that GPT-family models train
            # fine without
            logits = nn.Dense(
                self.vocab_size,
                dtype=self.dtype,
                param_dtype=self.param_dtype,
                use_bias=False,
                name="lm_head",
            )(x)
        # logits stay in the policy compute dtype: at LM vocab sizes an
        # fp32 logit tensor is gigabytes of HBM traffic per step (~5% of
        # the lm_base step, round-4 profile), and every consumer
        # (ops.losses cross-entropy, inference.sample_logits) upcasts
        # per-element inside its own fused reductions. This mirrors the
        # reference's autocast semantics exactly: its model emits
        # half-precision logits and nn.CrossEntropyLoss upcasts
        # (origin_main.py autocast block).
        return logits


def LMTiny(**kw):
    """Test-sized decoder (d=256, L=4): the LM numerics/composition pin."""
    kw.setdefault("hidden_dim", 256)
    kw.setdefault("depth", 4)
    kw.setdefault("num_heads", 8)
    kw.setdefault("mlp_dim", 1024)
    return TransformerLM(**kw)


def LMBase(**kw):
    """Bench-sized decoder (d=768, L=12, GPT-2-small shape) for the
    long-context throughput/MFU measurements (bench.py lm_long)."""
    kw.setdefault("hidden_dim", 768)
    kw.setdefault("depth", 12)
    kw.setdefault("num_heads", 12)
    kw.setdefault("mlp_dim", 3072)
    kw.setdefault("max_len", 8192)
    return TransformerLM(**kw)
