"""The reference ConvNet, as a single flax.linen module.

Architecture (reference origin_main.py:12-24, identical in ddp_main.py:16-28):
two blocks of [Conv 5x5 pad 2 -> BatchNorm -> ReLU -> MaxPool 2x2]
(channels 1 -> 16 -> 32), flatten, Linear(7*7*32 -> 10).

Differences by design (TPU-first, not a port):
- NHWC layout (XLA:TPU-preferred) instead of torch NCHW.
- Mixed precision is a dtype policy on the module (compute in `dtype`,
  params in `param_dtype`) instead of an autocast context manager
  (ddp_main.py:31-36); logits are returned in fp32 for a stable loss.
- `axis_name` turns BatchNorm statistics into cross-replica statistics via
  `lax.pmean` over the data axis — the SyncBatchNorm equivalent
  (ddp_main.py:120) — with zero code change at the call site.
- BatchNorm momentum 0.9 matches torch's default momentum=0.1 under
  linen's opposite convention; epsilon 1e-5 matches torch's default.
"""

from __future__ import annotations

from typing import Optional, Sequence

import flax.linen as nn
import jax.numpy as jnp


class ConvNet(nn.Module):
    num_classes: int = 10
    features: Sequence[int] = (16, 32)
    dtype: jnp.dtype = jnp.float32
    param_dtype: jnp.dtype = jnp.float32
    axis_name: Optional[str] = None

    @nn.compact
    def __call__(self, x: jnp.ndarray, *, train: bool = False) -> jnp.ndarray:
        x = x.astype(self.dtype)
        for feat in self.features:
            x = nn.Conv(
                feat,
                kernel_size=(5, 5),
                padding=2,
                dtype=self.dtype,
                param_dtype=self.param_dtype,
            )(x)
            x = nn.BatchNorm(
                use_running_average=not train,
                momentum=0.9,
                epsilon=1e-5,
                dtype=self.dtype,
                param_dtype=self.param_dtype,
                axis_name=self.axis_name,
            )(x)
            x = nn.relu(x)
            x = nn.max_pool(x, window_shape=(2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(
            self.num_classes, dtype=self.dtype, param_dtype=self.param_dtype
        )(x)
        return x.astype(jnp.float32)
