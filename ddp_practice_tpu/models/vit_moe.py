"""ViT-MoE: Vision Transformer with mixture-of-experts MLP blocks.

The expert-parallel rung of the model ladder (no MoE anywhere in the
reference — SURVEY §2.3). Every `moe_every`-th encoder block swaps its
dense MLP for `ops.moe.MoEMlp`: top-k routed experts stacked on a leading
dim sharded over the 'expert' mesh axis, dispatch/combine lowered to
all-to-alls by GSPMD. Attention blocks are the standard ones (TP/SP
compose as in plain ViT).
"""

from __future__ import annotations

from typing import Optional

import flax.linen as nn
import jax.numpy as jnp

from ddp_practice_tpu.models.vit import MlpBlock, SelfAttention, ViTEmbed, ViTHead
from ddp_practice_tpu.ops.moe import MoEMlp


class MoEEncoderBlock(nn.Module):
    num_heads: int
    mlp_dim: int
    num_experts: int = 8
    top_k: int = 2
    capacity_factor: float = 1.25
    dtype: jnp.dtype = jnp.float32
    param_dtype: jnp.dtype = jnp.float32
    seq_axis: Optional[str] = None
    sp_impl: str = "ring"
    attn_impl: str = "xla"
    use_moe: bool = True

    @nn.compact
    def __call__(self, x):
        y = nn.LayerNorm(dtype=self.dtype, param_dtype=self.param_dtype, name="ln1")(x)
        y = SelfAttention(
            self.num_heads,
            dtype=self.dtype,
            param_dtype=self.param_dtype,
            seq_axis=self.seq_axis,
            sp_impl=self.sp_impl,
            attn_impl=self.attn_impl,
            name="attn",
        )(y)
        x = x + y
        y = nn.LayerNorm(dtype=self.dtype, param_dtype=self.param_dtype, name="ln2")(x)
        if self.use_moe:
            y = MoEMlp(
                num_experts=self.num_experts,
                top_k=self.top_k,
                capacity_factor=self.capacity_factor,
                mlp_dim=self.mlp_dim,
                dtype=self.dtype,
                param_dtype=self.param_dtype,
                name="moe",
            )(y)
        else:
            y = MlpBlock(
                self.mlp_dim, dtype=self.dtype, param_dtype=self.param_dtype,
                name="mlp",
            )(y)
        return x + y


class ViTMoE(nn.Module):
    num_classes: int = 10
    patch_size: int = 4
    hidden_dim: int = 192
    depth: int = 12
    num_heads: int = 3
    mlp_dim: int = 768
    num_experts: int = 8
    top_k: int = 2
    capacity_factor: float = 1.25
    moe_every: int = 2               # every 2nd block is MoE (GShard layout)
    dtype: jnp.dtype = jnp.float32
    param_dtype: jnp.dtype = jnp.float32
    seq_axis: Optional[str] = None
    sp_impl: str = "ring"
    attn_impl: str = "xla"
    axis_name: Optional[str] = None  # registry uniformity (no BN)

    @nn.compact
    def __call__(self, x, *, train: bool = False):
        x = ViTEmbed(
            patch_size=self.patch_size,
            hidden_dim=self.hidden_dim,
            dtype=self.dtype,
            param_dtype=self.param_dtype,
            name="embed",
        )(x)
        for i in range(self.depth):
            x = MoEEncoderBlock(
                self.num_heads,
                self.mlp_dim,
                num_experts=self.num_experts,
                top_k=self.top_k,
                capacity_factor=self.capacity_factor,
                dtype=self.dtype,
                param_dtype=self.param_dtype,
                seq_axis=self.seq_axis,
                sp_impl=self.sp_impl,
                attn_impl=self.attn_impl,
                use_moe=(i % self.moe_every == self.moe_every - 1),
                name=f"block{i}",
            )(x)
        return ViTHead(
            num_classes=self.num_classes,
            dtype=self.dtype,
            param_dtype=self.param_dtype,
            name="classifier",
        )(x)
