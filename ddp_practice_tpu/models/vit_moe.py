"""ViT-MoE: Vision Transformer with mixture-of-experts MLP blocks.

The expert-parallel rung of the model ladder (no MoE anywhere in the
reference — SURVEY §2.3). Every `moe_every`-th encoder block swaps its
dense MLP for `ops.moe.MoEMlp`: top-k routed experts stacked on a leading
dim sharded over the 'expert' mesh axis, dispatch/combine lowered to
all-to-alls by GSPMD. Attention blocks are the standard ones (TP/SP
compose as in plain ViT).
"""

from __future__ import annotations

from typing import Optional

import flax.linen as nn
import jax.numpy as jnp

from ddp_practice_tpu.models.vit import EncoderBlock, ViTEmbed, ViTHead


class ViTMoE(nn.Module):
    num_classes: int = 10
    patch_size: int = 4
    hidden_dim: int = 192
    depth: int = 12
    num_heads: int = 3
    mlp_dim: int = 768
    num_experts: int = 8
    top_k: int = 2
    capacity_factor: float = 1.25
    moe_every: int = 2               # every 2nd block is MoE (GShard layout)
    # routing scheme: "topk" | "expert_choice" (ops/moe.py MoEMlp.router)
    moe_router: str = "topk"
    dtype: jnp.dtype = jnp.float32
    param_dtype: jnp.dtype = jnp.float32
    seq_axis: Optional[str] = None
    sp_impl: str = "ring"
    attn_impl: str = "xla"
    axis_name: Optional[str] = None  # registry uniformity (no BN)

    @nn.compact
    def __call__(self, x, *, train: bool = False):
        x = ViTEmbed(
            patch_size=self.patch_size,
            hidden_dim=self.hidden_dim,
            dtype=self.dtype,
            param_dtype=self.param_dtype,
            name="embed",
        )(x)
        for i in range(self.depth):
            # the one shared dense/MoE block swap (models/vit.py
            # EncoderBlock use_moe) — identical submodule names keep
            # existing vit_tiny_moe param trees valid
            x = EncoderBlock(
                self.num_heads,
                self.mlp_dim,
                num_experts=self.num_experts,
                moe_top_k=self.top_k,
                capacity_factor=self.capacity_factor,
                moe_router=self.moe_router,
                dtype=self.dtype,
                param_dtype=self.param_dtype,
                seq_axis=self.seq_axis,
                sp_impl=self.sp_impl,
                attn_impl=self.attn_impl,
                use_moe=(i % self.moe_every == self.moe_every - 1),
                name=f"block{i}",
            )(x, False, train)
        return ViTHead(
            num_classes=self.num_classes,
            dtype=self.dtype,
            param_dtype=self.param_dtype,
            name="classifier",
        )(x)
