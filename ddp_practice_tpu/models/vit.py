"""Vision Transformer, TPU-first flax.linen implementation.

Not in the reference (no attention anywhere, origin_main.py:9-31); this is
the BASELINE.json transformer rung ("ViT-Tiny on CIFAR-10, pjit DP") and the
flagship model for sharded training: its parameter names line up with the
tensor-parallel sharding rules in `ddp_practice_tpu/parallel/sharding_rules.py`
(attention QKV/out projections and MLP in/out projections shard over the
'tensor' mesh axis), and its attention can run under sequence parallelism via
`ddp_practice_tpu.parallel.ring.ring_attention`.

TPU notes: everything is batched matmul (MXU-friendly); attention uses the
framework's own `ops.attention` (switchable between a fused jnp path and the
ring path); compute dtype policy-driven (bf16), logits fp32.
"""

from __future__ import annotations

from typing import Optional

import flax.linen as nn
import jax.numpy as jnp

from ddp_practice_tpu.ops.attention import dot_product_attention
from ddp_practice_tpu.ops.rope import apply_rope


class ViTEmbed(nn.Module):
    """Patch + position embedding stem (shared by ViT/ViT-MoE/PipelinedViT)."""

    patch_size: int = 4
    hidden_dim: int = 192
    dtype: jnp.dtype = jnp.float32
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        x = x.astype(self.dtype)
        p = self.patch_size
        x = nn.Conv(
            self.hidden_dim,
            kernel_size=(p, p),
            strides=(p, p),
            padding="VALID",
            dtype=self.dtype,
            param_dtype=self.param_dtype,
            name="patch_embed",
        )(x)
        b, h, w, d = x.shape
        x = x.reshape((b, h * w, d))
        pos = self.param(
            "pos_embed",
            nn.initializers.normal(stddev=0.02),
            (1, h * w, d),
            self.param_dtype,
        )
        return x + pos.astype(self.dtype)


class ViTHead(nn.Module):
    """Final LN + global average pool + classifier (shared across ViTs)."""

    num_classes: int = 10
    dtype: jnp.dtype = jnp.float32
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        x = nn.LayerNorm(dtype=self.dtype, param_dtype=self.param_dtype, name="ln_f")(x)
        x = jnp.mean(x, axis=1)  # global average pool (no class token; MXU-friendlier)
        x = nn.Dense(
            self.num_classes, dtype=self.dtype, param_dtype=self.param_dtype, name="head"
        )(x)
        return x.astype(jnp.float32)


class MlpBlock(nn.Module):
    mlp_dim: int
    dtype: jnp.dtype = jnp.float32
    param_dtype: jnp.dtype = jnp.float32
    dropout_rate: float = 0.0

    @nn.compact
    def __call__(self, x, *, train: bool = False):
        d = x.shape[-1]
        x = nn.Dense(
            self.mlp_dim, dtype=self.dtype, param_dtype=self.param_dtype, name="fc_in"
        )(x)
        x = nn.gelu(x)
        x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        x = nn.Dense(d, dtype=self.dtype, param_dtype=self.param_dtype, name="fc_out")(x)
        return x


class SelfAttention(nn.Module):
    num_heads: int
    dtype: jnp.dtype = jnp.float32
    param_dtype: jnp.dtype = jnp.float32
    seq_axis: Optional[str] = None  # mesh axis for sequence parallelism
    sp_impl: str = "ring"           # "ring" | "ulysses"
    attn_impl: str = "xla"          # "xla" | "flash" (Pallas kernel)
    causal: bool = False            # decoder (LM) blocks mask the future
    rope: bool = False              # rotary Q/K (ops/rope.py) vs none here
    # decode-mode KV-cache storage dtype. None = the compute dtype (bf16
    # under the bf16 policy — already the small option there); set
    # jnp.bfloat16 to halve cache traffic under an fp32 policy. The
    # string "int8" stores a QUANTIZED cache (1 byte/element + per-
    # (batch, head, position) fp32 scales; ~1% relative logit error,
    # pinned in tests/test_decode_attention.py) — measured +17.5%
    # decode tokens/s at bs=8/L=1024 where the cache read dominates;
    # below L~768 the scale traffic eats the saving (BENCHMARKS.md).
    # Writes round to this dtype; attention math runs at the q/k
    # promotion (int8 dequantizes inside the packed kernel).
    kv_cache_dtype: object = None  # None | jnp.dtype | "int8"

    @nn.compact
    def __call__(self, x, *, decode: bool = False, attn_start=None,
                 page_table=None, kv_lengths=None):
        b, s, d = x.shape
        assert d % self.num_heads == 0, (d, self.num_heads)
        head_dim = d // self.num_heads
        qkv = nn.DenseGeneral(
            (3, self.num_heads, head_dim),
            dtype=self.dtype,
            param_dtype=self.param_dtype,
            name="qkv",
        )(x)
        if (
            self.attn_impl == "flash"
            and not decode
            and not self.rope
            and self.seq_axis is None
        ):
            # hand the raw projection output to the packed kernels: the
            # (3, h, hd) feature flatten IS the [q|k|v] column layout they
            # window at offsets, so q/k/v never materialize as slices
            # (~4 ms/step of layout traffic at lm_base — round-4 profile).
            # rope rotates q/k in 4D before the kernel and keeps the
            # sliced path; flash_attention_qkv itself falls back for
            # unpackable head shapes. Falls through to the shared output
            # projection below.
            from ddp_practice_tpu.ops.flash_attention import (
                flash_attention_qkv,
            )

            out = flash_attention_qkv(
                qkv.reshape(b, s, 3 * d), self.num_heads, causal=self.causal
            )
            return self._out_proj(out)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        if self.rope and not decode:
            # global positions: under GSPMD jit the sequence dim is sharded
            # by annotation, not split — s IS the global length (the SP
            # shard_map island opens inside ring/ulysses, after this).
            # Rotations bake absolute position into Q/K, so attention
            # scores depend only on relative offsets downstream.
            positions = jnp.arange(s)
            q = apply_rope(q, positions)
            k = apply_rope(k, positions)
        if decode:
            # KV-cache incremental decoding: the cache collection holds
            # pre-allocated FLAT (b, max_len, h*hd) key/value buffers
            # (shaped by a full-length init call) plus the write cursor.
            # The flat layout is load-bearing, not cosmetic: minor dims
            # (h, hd) tile-pad on TPU and a padded buffer defeats
            # in-place dynamic_update_slice — every per-token write
            # became a full cache relayout copy, 53.6% of the bs=8
            # decode step (round-4 profile; probes in
            # experiments/decode_layouts.py). Flat updates run in-place
            # (~0.2 us). One code path serves prefill (s = prompt length
            # at cursor 0) and single-token steps (s = 1): the step
            # attention is a packed Pallas kernel reading the flat cache
            # per head (ops/decode_attention.py), prefill reshapes once
            # and takes the masked XLA path.
            if not self.causal:
                raise ValueError("decode=True requires causal attention")
            if self.seq_axis is not None:
                raise ValueError(
                    "decode (KV-cache) mode does not compose with sequence "
                    "parallelism — generate on a data/tensor-sharded mesh"
                )
            if page_table is not None:
                # paged KV cache (serve/kv_pages.py): block-pool leaves,
                # per-slot page tables and write positions — no shared
                # cursor. Declares its own cache variables, so it must
                # branch before the flat-cache declarations below.
                return self._out_proj(self._paged_decode(
                    q, k, v, page_table, kv_lengths, attn_start
                ))
            # "int8": quantized cache — 1 byte/element plus per-(batch,
            # head, position) fp32 scales. Decode is HBM-bound and the
            # cache is ~40% of its traffic at batched sizes, so this is
            # the decode-MBU lever (round 5; ops/decode_attention.py
            # folds the scales into the kernel's score/probability
            # rows). The scale buffers are small ((b, h, L) f32); their
            # minor-dim dynamic updates may copy, which at ~KB scale is
            # noise next to the MB-scale cache stream they halve.
            quant = self.kv_cache_dtype == "int8"
            cache_dtype = (
                jnp.int8 if quant else (self.kv_cache_dtype or k.dtype)
            )
            b_, s_, h_, hd_ = k.shape
            flat_kv = (b_, s_, h_ * hd_)
            cached_key = self.variable(
                "cache", "cached_key", jnp.zeros, flat_kv, cache_dtype
            )
            cached_value = self.variable(
                "cache", "cached_value", jnp.zeros, flat_kv, cache_dtype
            )
            cache_index = self.variable(
                "cache", "cache_index", lambda: jnp.zeros((), jnp.int32)
            )
            key_scale = value_scale = None
            if quant:
                key_scale = self.variable(
                    "cache", "cached_key_scale", jnp.zeros,
                    (b_, h_, s_), jnp.float32,
                )
                value_scale = self.variable(
                    "cache", "cached_value_scale", jnp.zeros,
                    (b_, h_, s_), jnp.float32,
                )
            if self.is_initializing():
                out = dot_product_attention(q, k, v, causal=True, impl="xla")
            else:
                from jax import lax

                from ddp_practice_tpu.ops.attention import attention_with_mask
                from ddp_practice_tpu.ops.decode_attention import (
                    decode_attention_packed,
                )
                from ddp_practice_tpu.ops.flash_attention import (
                    _heads_per_pack,
                )

                max_len = cached_key.value.shape[1]
                cur = cache_index.value
                if self.rope:
                    # cached keys are stored rotated, so only the incoming
                    # block needs rotation — at its absolute positions
                    positions = cur + jnp.arange(s)
                    q = apply_rope(q, positions)
                    k = apply_rope(k, positions)
                if quant:
                    def _quantize(x4):
                        # per-(batch, token, head) symmetric int8: the
                        # scale is that row's max |.| mapped to 127
                        amax = jnp.max(
                            jnp.abs(x4.astype(jnp.float32)), axis=-1
                        )                                # (b, s, h)
                        scale = jnp.maximum(amax, 1e-8) / 127.0
                        xq = jnp.round(
                            x4.astype(jnp.float32) / scale[..., None]
                        ).astype(jnp.int8)
                        return xq, jnp.swapaxes(scale, 1, 2)  # (b, h, s)

                    k_store, ks_new = _quantize(k)
                    v_store, vs_new = _quantize(v)
                    key_scale.value = lax.dynamic_update_slice(
                        key_scale.value, ks_new, (0, 0, cur)
                    )
                    value_scale.value = lax.dynamic_update_slice(
                        value_scale.value, vs_new, (0, 0, cur)
                    )
                else:
                    k_store, v_store = k, v
                kc = lax.dynamic_update_slice(
                    cached_key.value,
                    k_store.reshape(flat_kv[0], s, -1).astype(cache_dtype),
                    (0, cur, 0),
                )
                vc = lax.dynamic_update_slice(
                    cached_value.value,
                    v_store.reshape(flat_kv[0], s, -1).astype(cache_dtype),
                    (0, cur, 0),
                )
                cached_key.value = kc
                cached_value.value = vc
                cache_index.value = cur + s
                if s == 1 and _heads_per_pack(h_, hd_) is not None:
                    # token step: packed kernel on the flat cache —
                    # no reshape, O(cur) cache reads (int8: scales ride
                    # as separate small operands)
                    out = decode_attention_packed(
                        q.reshape(flat_kv[0], 1, -1), kc, vc, cur,
                        attn_start, n_heads=h_,
                        k_scale=key_scale.value if quant else None,
                        v_scale=value_scale.value if quant else None,
                    ).reshape(flat_kv[0], 1, h_, hd_)
                else:
                    # prefill (s = prompt length) or unpackable head
                    # shapes: reshape the cache once and take the masked
                    # XLA path (amortized over the whole generation)
                    k4 = kc.reshape(flat_kv[0], max_len, h_, hd_)
                    v4 = vc.reshape(flat_kv[0], max_len, h_, hd_)
                    if quant:
                        # dequantize for the XLA path (one prefill pass
                        # per generation — amortized)
                        ks_t = jnp.swapaxes(key_scale.value, 1, 2)
                        vs_t = jnp.swapaxes(value_scale.value, 1, 2)
                        k4 = (k4.astype(jnp.float32)
                              * ks_t[..., None]).astype(q.dtype)
                        v4 = (v4.astype(jnp.float32)
                              * vs_t[..., None]).astype(q.dtype)
                    pos_q = cur + jnp.arange(s)
                    mask = jnp.arange(max_len)[None, :] <= pos_q[:, None]
                    if attn_start is not None:
                        # left-padded prompts (inference.py variable-
                        # length batching): key positions before each
                        # sequence's first real token never get attention
                        mask = mask[None] & (
                            jnp.arange(max_len)[None, None, :]
                            >= attn_start[:, None, None]
                        )
                        mask = mask[:, None]  # (b, 1, sq, sk)
                    out = attention_with_mask(q, k4, v4, mask)
        else:
            out = dot_product_attention(
                q, k, v, causal=self.causal, seq_axis=self.seq_axis,
                sp_impl=self.sp_impl, impl=self.attn_impl,
            )
        return self._out_proj(out)

    def _paged_decode(self, q, k, v, page_table, kv_lengths, attn_start):
        """Paged KV-cache decode step / prefill (serve/kv_pages.py).

        The "cache" collection leaves are a POOL of fixed-size blocks
        (num_blocks, block_size, h*hd) shared by every slot; `page_table`
        (b, max_blocks_per_slot) int32 maps each slot's block list and
        `kv_lengths` (b,) int32 is each slot's write position — slot-LOCAL
        coordinates starting at 0, so RoPE rotates each slot at its own
        offset and there is no shared cursor to run out.

        s == 1 (decode step): the incoming token's K/V scatters into pool
        block `page_table[b, pos // block_size]` row `pos % block_size`;
        attention gathers through the same table
        (ops/decode_attention.paged_decode_attention) and masks
        [attn_start[b], pos[b]] in slot-local positions.

        s > 1 (paged PREFILL, PR 6): the s tokens occupy positions
        `kv_lengths[b] + [0, s)` — the prefix-cache admission path, where
        a prompt whose first `kv_lengths` positions are already resident
        (shared radix-cache blocks) prefills only its SUFFIX, attending
        the cached prefix through the page table. Writes scatter per
        position; attention gathers the slot's span once and masks
        causally per query row (amortized over the whole admission, the
        same trade the flat prefill makes). The SAME s > 1 path serves
        speculative-decoding verify windows (serve/spec.py): k drafted
        tokens scored in one forward at positions kv_lengths + [0, k),
        each attending the committed context plus the drafts before it —
        no extra model surface, the verify window IS a short paged
        prefill.

        kv_cache_dtype="int8" composes (PR 6): the pool carries
        per-block (num_blocks, h, block_size) fp32 scale pages
        (`cached_key_scale`/`cached_value_scale`, make_paged_cache) and
        the quantized kernel walks them through the same page table.
        """
        from ddp_practice_tpu.ops.attention import attention_with_mask
        from ddp_practice_tpu.ops.decode_attention import (
            gather_pages,
            paged_decode_attention,
        )

        if kv_lengths is None:
            raise ValueError(
                "paged decode needs kv_lengths (per-slot write positions)"
            )
        if not self.rope:
            raise ValueError(
                "paged decode needs rope=True — slot-local positions "
                "require relative position encoding"
            )
        b_, s_, h_, hd_ = k.shape
        if self.is_initializing():
            raise ValueError(
                "paged cache pools are allocated by serve/kv_pages.py "
                "make_paged_cache, not by model.init"
            )
        quant = self.kv_cache_dtype == "int8"
        cache_dtype = (
            jnp.int8 if quant
            else (self.kv_cache_dtype if self.kv_cache_dtype is not None
                  else k.dtype)
        )
        cached_key = self.variable(
            "cache", "cached_key", jnp.zeros, (b_, s_, h_ * hd_), cache_dtype
        )
        cached_value = self.variable(
            "cache", "cached_value", jnp.zeros, (b_, s_, h_ * hd_),
            cache_dtype,
        )
        key_scale = value_scale = None
        if quant:
            key_scale = self.variable(
                "cache", "cached_key_scale", jnp.zeros,
                (b_, h_, s_), jnp.float32,
            )
            value_scale = self.variable(
                "cache", "cached_value_scale", jnp.zeros,
                (b_, h_, s_), jnp.float32,
            )
        # declared for tree parity with the flat cache (make_paged_cache
        # mirrors make_cache's structure); a block pool has no global
        # clock, so the scalar stays untouched
        self.variable(
            "cache", "cache_index", lambda: jnp.zeros((), jnp.int32)
        )
        block_size = cached_key.value.shape[1]
        pool_dtype = cached_key.value.dtype
        pos0 = jnp.asarray(kv_lengths, jnp.int32)
        # (b, s) slot-local positions of the incoming tokens
        positions = pos0[:, None] + jnp.arange(s_, dtype=jnp.int32)[None, :]
        q = apply_rope(q, positions)
        k = apply_rope(k, positions)
        if quant:
            def _quantize(x4):
                # per-(batch, token, head) symmetric int8, same recipe
                # as the flat int8 cache above
                amax = jnp.max(jnp.abs(x4.astype(jnp.float32)), axis=-1)
                scale = jnp.maximum(amax, 1e-8) / 127.0    # (b, s, h)
                xq = jnp.round(
                    x4.astype(jnp.float32) / scale[..., None]
                ).astype(jnp.int8)
                return xq, scale

            k_store, ks_new = _quantize(k)
            v_store, vs_new = _quantize(v)
        else:
            k_store, v_store = k, v
        # clamp keeps a retired slot (page row 0, length pinned) writing
        # inside the table; active slots never reach the clamp — the
        # engine pre-allocates blocks for every position it dispatches
        blk_col = jnp.minimum(positions // block_size,
                              page_table.shape[1] - 1)
        blk = jnp.take_along_axis(page_table, blk_col, axis=1)  # (b, s)
        off = positions % block_size
        kc = cached_key.value.at[blk, off].set(
            k_store.reshape(b_, s_, -1).astype(pool_dtype)
        )
        vc = cached_value.value.at[blk, off].set(
            v_store.reshape(b_, s_, -1).astype(pool_dtype)
        )
        cached_key.value = kc
        cached_value.value = vc
        ks_pool = vs_pool = None
        if quant:
            # scale pages: advanced indices (b, s) on axes 0/2 straddle
            # the head slice, so the indexed result is (b, s, h) — set
            # with the per-(batch, token, head) scales directly
            ks_pool = key_scale.value.at[blk, :, off].set(ks_new)
            vs_pool = value_scale.value.at[blk, :, off].set(vs_new)
            key_scale.value = ks_pool
            value_scale.value = vs_pool
        if s_ == 1:
            out = paged_decode_attention(
                q.reshape(b_, 1, -1), kc, vc, page_table, pos0, attn_start,
                n_heads=h_, k_scale=ks_pool, v_scale=vs_pool,
            )
            return out.reshape(b_, 1, h_, hd_)
        # paged prefill: gather the slot's span once (dequantizing int8
        # pools through their scale pages) and mask causally per query
        # row in slot-local coordinates
        k4 = gather_pages(kc, page_table, h_, ks_pool)
        v4 = gather_pages(vc, page_table, h_, vs_pool)
        span = k4.shape[1]
        kpos = jnp.arange(span, dtype=jnp.int32)
        valid = kpos[None, None, :] <= positions[:, :, None]  # (b, s, span)
        if attn_start is not None:
            valid &= kpos[None, None, :] >= attn_start[:, None, None]
        cd = pool_dtype if not quant else q.dtype
        out = attention_with_mask(
            q.astype(cd), k4.astype(cd), v4.astype(cd), valid[:, None]
        )
        return out.reshape(b_, s_, h_, hd_).astype(q.dtype)

    def _out_proj(self, out):
        """Shared output projection over (b, s, h, hd) attention output —
        one definition for the fused-QKV and sliced/decode paths (they
        share the 'out' parameters)."""
        d = out.shape[-2] * out.shape[-1]
        return nn.DenseGeneral(
            d,
            axis=(-2, -1),
            dtype=self.dtype,
            param_dtype=self.param_dtype,
            name="out",
        )(out)


class EncoderBlock(nn.Module):
    num_heads: int
    mlp_dim: int
    dtype: jnp.dtype = jnp.float32
    param_dtype: jnp.dtype = jnp.float32
    seq_axis: Optional[str] = None
    sp_impl: str = "ring"
    attn_impl: str = "xla"
    causal: bool = False
    rope: bool = False
    # pass-through to SelfAttention: None | jnp.dtype | "int8"
    kv_cache_dtype: object = None
    # residual-branch dropout (after the attention projection and inside
    # the MLP). Deliberately NOT on the attention probabilities: that
    # variant cannot compose with the flash/ring kernels, which never
    # materialize the probability matrix.
    dropout_rate: float = 0.0
    # swap the dense MLP for a routed expert MLP (ops/moe.py) — the LM
    # MoE composition (models/lm.py moe_every); ViT's dedicated MoE
    # blocks live in models/vit_moe.py
    use_moe: bool = False
    num_experts: int = 8
    moe_top_k: int = 2
    capacity_factor: float = 1.25
    moe_aux_weight: float = 0.01
    moe_bias_rate: float = 0.02
    # tokens per routing group (0 = whole sequence); see ops/moe.py
    moe_group_size: int = 0
    moe_group_stride: bool = True
    # routing scheme: "topk" (tokens choose) | "expert_choice" (experts
    # choose — zero padding/drops; ops/moe.py MoEMlp.router)
    moe_router: str = "topk"
    # run the whole layer as ONE Pallas kernel per direction
    # (ops/fused_encoder.py): the HBM-bound small-d regime's fix
    # (BENCHMARKS.md ViT-Tiny analysis). Short-sequence blocks whose
    # weights fit VMEM only; the default backward is the hand-derived
    # Pallas kernel, pinned against unfused autodiff at 2e-4 tolerance
    # in tests/test_fused_encoder.py (bwd_impl="reference" gives the
    # bit-exact unfused gradients instead). Tri-state:
    #   "auto" (the model default) — fuse when the block is plain
    #     (no decode/rope/SP/MoE/dropout/attn override), the shape is
    #     kernel-feasible (fused_shape_supported), and the program runs
    #     compiled on a single TPU chip. Silent per-op fallback
    #     otherwise — users get the fast path without flags (round-4
    #     verdict: the documented vit_tiny command trained at 16.9% MFU
    #     while the fused kernel sat opt-in at 38.4%).
    #   True — force; unsupported configs raise (the pre-round-5
    #     behavior, what the numerics tests pin).
    #   False — always the per-op pipeline.
    fused: object = False  # bool | "auto"

    @nn.compact
    def __call__(self, x, decode: bool = False, train: bool = False, *,
                 attn_start=None, page_table=None, kv_lengths=None):
        # decode/train are positional-friendly: the LM's remat path wraps
        # this module in nn.remat(static_argnums=(2, 3)), and jax.checkpoint
        # only accepts non-array arguments at static positions. attn_start
        # / page_table / kv_lengths (arrays) are decode-only, where remat
        # never applies.
        fused = self.fused
        if fused == "auto":
            fused = not self.is_initializing() and self._auto_fuse(
                x, decode
            )
        if fused and not self.is_initializing():
            if not self._plain_block(decode):
                raise ValueError(
                    "fused encoder layer supports plain blocks only — "
                    "bidirectional or causal (round 4) — with no decode/"
                    "rope/seq-parallel/MoE/dropout/attn_impl override; "
                    "those paths keep the per-op pipeline"
                )
            from ddp_practice_tpu.ops.fused_encoder import (
                fused_encoder_layer,
            )

            return fused_encoder_layer(
                x, self.variables["params"],
                num_heads=self.num_heads,
                compute_dtype=self.dtype,
                causal=self.causal,
            )
        return self._unfused(x, decode=decode, train=train,
                             attn_start=attn_start, page_table=page_table,
                             kv_lengths=kv_lengths)

    def _plain_block(self, decode) -> bool:
        """The ONE definition of 'plain block' — what the fused kernels
        can express. Shared by the fused=True loud gate and the "auto"
        fallback so they cannot drift apart."""
        return not (
            decode or self.rope or self.seq_axis is not None
            or self.use_moe or self.dropout_rate > 0.0
            or self.attn_impl != "xla"
        )

    def _auto_fuse(self, x, decode) -> bool:
        """Resolve fused="auto" at trace time: plain block + feasible
        shape + compiled single-chip TPU execution.

        The device gate is deliberate: CPU runs the kernel in interpret
        mode (orders of magnitude slower than per-op XLA — auto must
        never pick it), and compiled Pallas under a multi-chip GSPMD
        partition is not validated on hardware here, so implicit
        selection stays out of that regime; multi-chip users who have
        verified it force fused=True / --fused on."""
        if not self._plain_block(decode):
            return False
        from ddp_practice_tpu.parallel.ring import single_chip_tpu

        if not single_chip_tpu():
            return False
        from ddp_practice_tpu.ops.fused_encoder import fused_shape_supported

        return fused_shape_supported(
            seq_len=x.shape[1], d=x.shape[2], mlp_dim=self.mlp_dim,
            num_heads=self.num_heads, compute_dtype=self.dtype,
        )

    def _unfused(self, x, *, decode, train, attn_start,
                 page_table=None, kv_lengths=None):
        y = nn.LayerNorm(dtype=self.dtype, param_dtype=self.param_dtype, name="ln1")(x)
        y = SelfAttention(
            self.num_heads,
            dtype=self.dtype,
            param_dtype=self.param_dtype,
            seq_axis=self.seq_axis,
            sp_impl=self.sp_impl,
            attn_impl=self.attn_impl,
            causal=self.causal,
            rope=self.rope,
            kv_cache_dtype=self.kv_cache_dtype,
            name="attn",
        )(y, decode=decode, attn_start=attn_start, page_table=page_table,
          kv_lengths=kv_lengths)
        y = nn.Dropout(self.dropout_rate, deterministic=not train)(y)
        x = x + y
        y = nn.LayerNorm(dtype=self.dtype, param_dtype=self.param_dtype, name="ln2")(x)
        if self.use_moe:
            from ddp_practice_tpu.ops.moe import MoEMlp

            y = MoEMlp(
                num_experts=self.num_experts,
                top_k=self.moe_top_k,
                capacity_factor=self.capacity_factor,
                aux_loss_weight=self.moe_aux_weight,
                bias_update_rate=self.moe_bias_rate,
                group_size=self.moe_group_size,
                group_stride=self.moe_group_stride,
                router=self.moe_router,
                mlp_dim=self.mlp_dim,
                dtype=self.dtype,
                param_dtype=self.param_dtype,
                name="moe",
            )(y, decode=decode)
            # residual-branch dropout for the routed MLP — the dense
            # MlpBlock applies its own internally; without this the MoE
            # blocks would silently train unregularized under --dropout
            y = nn.Dropout(self.dropout_rate, deterministic=not train)(y)
        else:
            y = MlpBlock(
                self.mlp_dim, dtype=self.dtype, param_dtype=self.param_dtype,
                dropout_rate=self.dropout_rate, name="mlp",
            )(y, train=train)
        return x + y


class ViT(nn.Module):
    num_classes: int = 10
    patch_size: int = 4
    hidden_dim: int = 192
    depth: int = 12
    num_heads: int = 3
    mlp_dim: int = 768
    dtype: jnp.dtype = jnp.float32
    param_dtype: jnp.dtype = jnp.float32
    seq_axis: Optional[str] = None
    sp_impl: str = "ring"
    attn_impl: str = "xla"
    dropout_rate: float = 0.0       # residual-branch dropout in every block
    # one-Pallas-kernel layers (small-d fix); "auto" picks them whenever
    # the EncoderBlock's constraints hold (see EncoderBlock.fused)
    fused: object = "auto"          # bool | "auto"
    axis_name: Optional[str] = None  # accepted for registry uniformity (no BN)

    @nn.compact
    def __call__(self, x, *, train: bool = False):
        x = ViTEmbed(
            patch_size=self.patch_size,
            hidden_dim=self.hidden_dim,
            dtype=self.dtype,
            param_dtype=self.param_dtype,
            name="embed",
        )(x)
        for i in range(self.depth):
            x = EncoderBlock(
                self.num_heads,
                self.mlp_dim,
                dtype=self.dtype,
                param_dtype=self.param_dtype,
                seq_axis=self.seq_axis,
                sp_impl=self.sp_impl,
                attn_impl=self.attn_impl,
                dropout_rate=self.dropout_rate,
                fused=self.fused,
                name=f"block{i}",
            )(x, train=train)
        return ViTHead(
            num_classes=self.num_classes,
            dtype=self.dtype,
            param_dtype=self.param_dtype,
            name="classifier",
        )(x)


def ViTTiny(**kw):
    kw.setdefault("hidden_dim", 192)
    kw.setdefault("depth", 12)
    kw.setdefault("num_heads", 3)
    kw.setdefault("mlp_dim", 768)
    return ViT(**kw)


def ViTBase(**kw):
    kw.setdefault("hidden_dim", 768)
    kw.setdefault("depth", 12)
    kw.setdefault("num_heads", 12)
    kw.setdefault("mlp_dim", 3072)
    return ViT(**kw)
