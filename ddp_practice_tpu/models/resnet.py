"""ResNet family (v1.5), TPU-first flax.linen implementation.

Not present in the reference (its only model is the 2-conv MNIST net,
origin_main.py:9-31); this is the BASELINE.json model ladder — ResNet-18 for
CIFAR-10 and ResNet-50 for ImageNet — exercising the same conv/BN/pool path
at scale. All BatchNorms take `axis_name` so data-parallel training gets
cross-replica statistics (the SyncBatchNorm equivalent, ddp_main.py:120).

TPU notes: NHWC layout; 3x3 stride-2 downsampling in the 'deep' stem variant
avoids the 7x7 stride-2 conv's poor MXU utilization on small images; compute
dtype is policy-driven (bf16 on TPU), final logits fp32.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp

ModuleDef = Any


class BasicBlock(nn.Module):
    filters: int
    strides: Tuple[int, int]
    conv: ModuleDef
    norm: ModuleDef

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (3, 3), self.strides)(x)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters, (3, 3))(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters, (1, 1), self.strides, name="proj")(
                residual
            )
            residual = self.norm(name="proj_bn")(residual)
        return nn.relu(residual + y)


class BottleneckBlock(nn.Module):
    filters: int
    strides: Tuple[int, int]
    conv: ModuleDef
    norm: ModuleDef

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1))(x)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters, (3, 3), self.strides)(y)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters * 4, (1, 1))(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(
                self.filters * 4, (1, 1), self.strides, name="proj"
            )(residual)
            residual = self.norm(name="proj_bn")(residual)
        return nn.relu(residual + y)


class ResNet(nn.Module):
    stage_sizes: Sequence[int]
    block_cls: Callable
    num_classes: int = 10
    num_filters: int = 64
    small_images: bool = True  # CIFAR-style 3x3 stem; False = ImageNet 7x7 stem
    dtype: jnp.dtype = jnp.float32
    param_dtype: jnp.dtype = jnp.float32
    axis_name: Optional[str] = None

    @nn.compact
    def __call__(self, x, *, train: bool = False):
        conv = functools.partial(
            nn.Conv,
            use_bias=False,
            padding="SAME",
            dtype=self.dtype,
            param_dtype=self.param_dtype,
        )
        norm = functools.partial(
            nn.BatchNorm,
            use_running_average=not train,
            momentum=0.9,
            epsilon=1e-5,
            dtype=self.dtype,
            param_dtype=self.param_dtype,
            axis_name=self.axis_name,
        )
        x = x.astype(self.dtype)
        if self.small_images:
            x = conv(self.num_filters, (3, 3), name="stem_conv")(x)
        else:
            x = conv(self.num_filters, (7, 7), (2, 2), name="stem_conv")(x)
        x = norm(name="stem_bn")(x)
        x = nn.relu(x)
        if not self.small_images:
            x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for i, n_blocks in enumerate(self.stage_sizes):
            for j in range(n_blocks):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = self.block_cls(
                    filters=self.num_filters * 2**i,
                    strides=strides,
                    conv=conv,
                    norm=norm,
                )(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(
            self.num_classes, dtype=self.dtype, param_dtype=self.param_dtype
        )(x)
        return x.astype(jnp.float32)


ResNet18 = functools.partial(ResNet, stage_sizes=(2, 2, 2, 2), block_cls=BasicBlock)
ResNet50 = functools.partial(
    ResNet, stage_sizes=(3, 4, 6, 3), block_cls=BottleneckBlock, small_images=False
)
