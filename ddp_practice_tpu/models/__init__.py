"""Model zoo.

The reference defines a single ConvNet three times over (origin_main.py:9-31,
ddp_main.py:13-36, ddp_main_torchrun.py:12-35). Here models are flax.linen
modules defined once, parameterized by a precision policy and an optional
data-parallel axis name (which turns every BatchNorm into a SyncBatchNorm,
replacing ddp_main.py:120).

Ladder beyond parity (BASELINE.json configs): ResNet-18/50, ViT-Tiny.
"""

from typing import Optional

from ddp_practice_tpu.config import PrecisionPolicy
from ddp_practice_tpu.models.convnet import ConvNet
from ddp_practice_tpu.models.resnet import ResNet, ResNet18, ResNet50
from ddp_practice_tpu.models.vit import ViT, ViTBase, ViTTiny
from ddp_practice_tpu.models.pipeline_lm import PipelinedLM
from ddp_practice_tpu.models.pipeline_vit import PipelinedViT
from ddp_practice_tpu.models.vit_moe import ViTMoE
from ddp_practice_tpu.models.lm import LMBase, LMTiny, TransformerLM

_REGISTRY = {}
# registry names whose module exposes the tri-state `fused` field
# (bool | "auto" — models/vit.py EncoderBlock); declared at registration
# so callers (train/loop.py --fused off) never maintain a parallel list
_FUSED_CAPABLE = set()


def register(name, *, fused_capable: bool = False):
    def deco(fn):
        _REGISTRY[name] = fn
        if fused_capable:
            _FUSED_CAPABLE.add(name)
        return fn
    return deco


def accepts_fused(name: str) -> bool:
    """True when `create_model(name, fused=...)` is a valid call."""
    return name.lower() in _FUSED_CAPABLE


def create_model(
    name: str,
    *,
    num_classes: int = 10,
    policy: Optional[PrecisionPolicy] = None,
    axis_name: Optional[str] = None,
    **kwargs,
):
    """Instantiate a model by name.

    axis_name: data-parallel mesh axis for cross-replica batch statistics
    (the SyncBatchNorm equivalent); None for single-device training.
    """
    policy = policy or PrecisionPolicy.fp32()
    name = name.lower()
    if name not in _REGISTRY:
        raise ValueError(f"unknown model {name!r}; have {sorted(_REGISTRY)}")
    return _REGISTRY[name](
        num_classes=num_classes, policy=policy, axis_name=axis_name, **kwargs
    )


@register("convnet")
def _convnet(*, num_classes, policy, axis_name, **kw):
    return ConvNet(
        num_classes=num_classes,
        dtype=policy.compute_dtype,
        param_dtype=policy.param_dtype,
        axis_name=axis_name,
        **kw,
    )


@register("resnet18")
def _resnet18(*, num_classes, policy, axis_name, **kw):
    return ResNet18(
        num_classes=num_classes,
        dtype=policy.compute_dtype,
        param_dtype=policy.param_dtype,
        axis_name=axis_name,
        **kw,
    )


@register("resnet50")
def _resnet50(*, num_classes, policy, axis_name, **kw):
    return ResNet50(
        num_classes=num_classes,
        dtype=policy.compute_dtype,
        param_dtype=policy.param_dtype,
        axis_name=axis_name,
        **kw,
    )


@register("vit_tiny", fused_capable=True)
def _vit_tiny(*, num_classes, policy, axis_name, **kw):
    return ViTTiny(
        num_classes=num_classes,
        dtype=policy.compute_dtype,
        param_dtype=policy.param_dtype,
        **kw,
    )


@register("vit_base", fused_capable=True)
def _vit_base(*, num_classes, policy, axis_name, **kw):
    return ViTBase(
        num_classes=num_classes,
        dtype=policy.compute_dtype,
        param_dtype=policy.param_dtype,
        **kw,
    )


@register("vit_tiny_moe")
def _vit_tiny_moe(*, num_classes, policy, axis_name, **kw):
    kw.setdefault("hidden_dim", 192)
    kw.setdefault("depth", 12)
    kw.setdefault("num_heads", 3)
    kw.setdefault("mlp_dim", 768)
    return ViTMoE(
        num_classes=num_classes,
        dtype=policy.compute_dtype,
        param_dtype=policy.param_dtype,
        **kw,
    )


@register("lm_tiny", fused_capable=True)
def _lm_tiny(*, num_classes, policy, axis_name, **kw):
    # LMs have a vocab, not classes: num_classes/axis_name are accepted for
    # registry uniformity and ignored (vocab_size is an explicit kwarg)
    return LMTiny(
        dtype=policy.compute_dtype,
        param_dtype=policy.param_dtype,
        **kw,
    )


@register("lm_base", fused_capable=True)
def _lm_base(*, num_classes, policy, axis_name, **kw):
    return LMBase(
        dtype=policy.compute_dtype,
        param_dtype=policy.param_dtype,
        **kw,
    )


@register("vit_tiny_pipe")
def _vit_tiny_pipe(*, num_classes, policy, axis_name, **kw):
    kw.setdefault("hidden_dim", 192)
    kw.setdefault("depth", 12)
    kw.setdefault("num_heads", 3)
    kw.setdefault("mlp_dim", 768)
    return PipelinedViT(
        num_classes=num_classes,
        dtype=policy.compute_dtype,
        param_dtype=policy.param_dtype,
        axis_name=axis_name,
        **kw,
    )


@register("lm_moe", fused_capable=True)
def _lm_moe(*, num_classes, policy, axis_name, **kw):
    # decoder LM with routed expert MLPs every other block (GShard
    # layout); dims default to lm_tiny's — the bench sizes it up via
    # model_kwargs
    kw.setdefault("moe_every", 2)
    # top-2 capacity headroom 2.0 (the GShard convention): per-GROUP
    # routing correlation (tokens of one sequence share context, so they
    # crowd the same experts) sets a drop floor that no global balancing
    # signal can remove — measured ~10% at cf 1.25 vs <2% at 2.0 with a
    # warm router (BENCHMARKS.md round-4 MoE section)
    kw.setdefault("capacity_factor", 2.0)
    return LMTiny(
        dtype=policy.compute_dtype,
        param_dtype=policy.param_dtype,
        **kw,
    )


@register("lm_pipe")
def _lm_pipe(*, num_classes, policy, axis_name, **kw):
    # LM registry convention: num_classes/axis_name accepted and ignored
    # (vocab_size is the explicit kwarg); defaults mirror lm_tiny
    kw.setdefault("hidden_dim", 256)
    kw.setdefault("depth", 4)
    kw.setdefault("num_heads", 8)
    kw.setdefault("mlp_dim", 1024)
    return PipelinedLM(
        dtype=policy.compute_dtype,
        param_dtype=policy.param_dtype,
        **kw,
    )


__all__ = [
    "create_model",
    "ConvNet",
    "ResNet",
    "ResNet18",
    "ResNet50",
    "ViT",
    "ViTTiny",
    "ViTBase",
    "PipelinedLM",
    "PipelinedViT",
    "ViTMoE",
    "TransformerLM",
    "LMTiny",
    "LMBase",
]
