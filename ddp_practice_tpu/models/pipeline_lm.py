"""Pipeline-parallel decoder LM: stage-sharded causal block stack.

The LM counterpart of PipelinedViT (models/pipeline_vit.py) — nothing
like either exists in the reference (SURVEY §2.3, "Pipeline parallel —
No"). Embedding (token table + learned positions, or RoPE inside the
blocks) and the final LN + vocab projection run outside the pipeline
under plain GSPMD; the causal EncoderBlock stack is depth-stacked,
stage-sharded over 'pipe', and scheduled by `pipeline_apply` (GPipe
microbatches over the BATCH dim — the sequence stays whole per
microbatch, so causal masking is untouched by the schedule).

Composes like the ViT pipeline: 'data' (microbatch split), 'tensor'
(Megatron specs on the stacked leaves ride GSPMD inside each stage),
'seq' (ring/Ulysses nested island inside each stage — causal ring).
Decode/KV-cache generation is NOT wired for the pipelined variant
(generate from the equivalent lm_tiny/lm_base checkpoint instead);
tied embeddings and dropout are likewise the dense family's features.
"""

from __future__ import annotations

from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax

from ddp_practice_tpu.config import MeshConfig
from ddp_practice_tpu.models.vit import EncoderBlock
from ddp_practice_tpu.parallel.pipeline import pipeline_apply, stack_stages


class _LMEmbed(nn.Module):
    """Token embedding + (optionally) learned positions.

    Mirrors TransformerLM's inline embed (models/lm.py) — the layouts are
    hand-synchronized, and tests/test_pipeline_lm.py pins the numeric
    equivalence by mapping a dense param tree into this layout."""

    vocab_size: int
    max_len: int
    hidden_dim: int
    pos_emb: str = "learned"
    dtype: jnp.dtype = jnp.float32
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, tokens):
        b, s = tokens.shape
        x = nn.Embed(
            self.vocab_size,
            self.hidden_dim,
            dtype=self.dtype,
            param_dtype=self.param_dtype,
            name="tok_embed",
        )(tokens)
        if self.pos_emb == "learned":
            pos = self.param(
                "pos_embed",
                nn.initializers.normal(stddev=0.02),
                (1, self.max_len, self.hidden_dim),
                self.param_dtype,
            )
            x = x + pos[:, :s].astype(self.dtype)
        return x


class _LMHead(nn.Module):
    """Final LN + vocab projection; logits fp32.

    DELIBERATELY fp32 (unlike TransformerLM's policy-dtype logits): these
    logits cross the pipeline shard_map's masked-psum boundary
    (parallel/pipeline_1f1b.py:79), and sub-fp32 psums over manual axes
    CHECK-fail in JAX 0.9 (the workaround documented at
    pipeline_1f1b.py:36). The bf16-logit HBM saving applies only to the
    dense LM."""

    vocab_size: int
    dtype: jnp.dtype = jnp.float32
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        x = nn.LayerNorm(
            dtype=self.dtype, param_dtype=self.param_dtype, name="ln_f"
        )(x)
        logits = nn.Dense(
            self.vocab_size,
            dtype=self.dtype,
            param_dtype=self.param_dtype,
            use_bias=False,  # GPT-2 convention, matching TransformerLM
            name="lm_head",
        )(x)
        return logits.astype(jnp.float32)


class PipelinedLM:
    """Duck-typed model: init(rng, tokens) -> variables; apply(...)."""

    def __init__(
        self,
        *,
        vocab_size: int = 256,
        max_len: int = 2048,
        hidden_dim: int = 256,
        depth: int = 4,
        num_heads: int = 8,
        mlp_dim: int = 1024,
        dtype: jnp.dtype = jnp.float32,
        param_dtype: jnp.dtype = jnp.float32,
        num_stages: int = 1,
        num_microbatches: int = 4,
        pipe_axis: str = MeshConfig.AXIS_PIPE,
        remat: bool = True,
        pos_emb: str = "learned",
        seq_axis: Optional[str] = None,
        sp_impl: str = "ring",
        attn_impl: str = "xla",
        schedule: str = "gpipe",
        # interleaved schedule only: layer chunks per device (virtual
        # pipeline stages, Megatron-style — parallel/interleave.py)
        num_virtual: int = 2,
        axis_name: Optional[str] = None,
    ):
        if pos_emb not in ("learned", "rope"):
            raise ValueError(f"unknown pos_emb {pos_emb!r}")
        if schedule not in ("gpipe", "1f1b", "interleaved"):
            raise ValueError(
                f"unknown schedule {schedule!r} (gpipe|1f1b|interleaved)"
            )
        n_logical = (
            num_stages * num_virtual if schedule == "interleaved"
            else num_stages
        )
        if depth % max(n_logical, 1) != 0:
            raise ValueError(
                f"depth {depth} % logical stages {n_logical} != 0"
            )
        self.vocab_size = vocab_size
        self.max_len = max_len
        self.hidden_dim = hidden_dim
        self.depth = depth
        self.num_heads = num_heads
        self.mlp_dim = mlp_dim
        self.num_stages = num_stages
        self.num_virtual = num_virtual
        self.num_microbatches = num_microbatches
        self.pipe_axis = pipe_axis
        self.remat = remat
        self.schedule = schedule
        self.dtype = dtype
        self.embed = _LMEmbed(
            vocab_size=vocab_size,
            max_len=max_len,
            hidden_dim=hidden_dim,
            pos_emb=pos_emb,
            dtype=dtype,
            param_dtype=param_dtype,
        )
        self.block = EncoderBlock(
            num_heads, mlp_dim, dtype=dtype, param_dtype=param_dtype,
            attn_impl=attn_impl, seq_axis=seq_axis, sp_impl=sp_impl,
            causal=True, rope=pos_emb == "rope",
        )
        self.head = _LMHead(
            vocab_size=vocab_size, dtype=dtype, param_dtype=param_dtype
        )

    def init(self, rng, tokens, *, train: bool = False):
        if tokens.shape[1] > self.max_len:
            raise ValueError(
                f"sequence {tokens.shape[1]} exceeds max_len {self.max_len}"
            )
        r_embed, r_blocks, r_head = jax.random.split(rng, 3)
        embed_vars = self.embed.init(r_embed, tokens)
        x = self.embed.apply(embed_vars, tokens)
        keys = jax.random.split(r_blocks, self.depth)
        block_params = jax.vmap(
            lambda k: self.block.init(k, x)["params"]
        )(keys)
        head_vars = self.head.init(r_head, x)
        return {
            "params": {
                "embed": embed_vars["params"],
                "blocks": block_params,
                "head": head_vars["params"],
            }
        }

    def apply(self, variables, tokens, *, train: bool = False, mutable=None,
              rngs=None):
        # train/rngs accepted for step-interface uniformity; the pipelined
        # blocks have no stochastic layers (dropout is a dense-LM feature)
        if tokens.shape[1] > self.max_len:
            raise ValueError(
                f"sequence {tokens.shape[1]} exceeds max_len {self.max_len}"
            )
        p = variables["params"]
        x = self.embed.apply({"params": p["embed"]}, tokens)
        x = self.run_blocks(p["blocks"], x)
        out = self.head.apply({"params": p["head"]}, x)
        if mutable is not None:
            return out, {}
        return out

    def loss_and_grad(self, params, inputs, targets, *, weight=None,
                      label_smoothing: float = 0.0,
                      with_accuracy: bool = True):
        """((loss, counts), grads) via the 1F1B schedule — the train-step
        entry point when schedule='1f1b' (train/steps.py dispatches here
        instead of jax.value_and_grad; apply() stays on the GPipe forward
        for eval, where there is no backward to schedule). `counts` is
        {"correct", "total"} — accuracy pieces accumulated as SCALARS in
        the last stage's ticks; full logits are deliberately never
        materialized (an (M, mb, s, V) metrics buffer would dwarf the
        schedule's O(P) activation stash at real vocab sizes).

        Embedding runs OUTSIDE the pipeline region under plain GSPMD (its
        vjp closes the loop with the dx cotangents the schedule emits at
        stage 0); head + loss fold into the LAST stage's backward ticks
        inside parallel/pipeline_1f1b.py.
        """
        from ddp_practice_tpu.ops.losses import (
            accuracy_counts,
            cross_entropy_sum,
        )
        from ddp_practice_tpu.parallel.pipeline_1f1b import (
            pipeline_1f1b_loss_and_grad,
            pipeline_interleaved_loss_and_grad,
        )

        M = self.num_microbatches
        b, s = inputs.shape
        if b % M != 0:
            raise ValueError(f"batch {b} not divisible by microbatches {M}")
        if weight is None:
            weight = jnp.ones((b, s), jnp.float32)

        def embed_fn(ep):
            return self.embed.apply(
                {"params": ep}, inputs
            ).astype(jnp.float32)

        x, embed_vjp = jax.vjp(embed_fn, params["embed"])
        xs = x.reshape((M, b // M) + x.shape[1:])

        # honor remat here exactly like run_blocks/_sequential do: the
        # backward tick's vjp otherwise stashes every block's internals
        # (attention matrices, 4x MLP hiddens) — in the schedule whose
        # whole point is bounded activation memory
        apply_block = (
            jax.checkpoint(self.block.apply) if self.remat
            else self.block.apply
        )

        def block_fn(stage_params, xb):
            def body(h, bp):
                return apply_block({"params": bp}, h), None

            h, _ = lax.scan(body, xb, stage_params)
            return h

        def head_loss_fn(hp, y, tgt, wgt):
            logits = self.head.apply({"params": hp}, y)
            loss_sum, wsum = cross_entropy_sum(
                logits, tgt, weight=wgt, label_smoothing=label_smoothing
            )
            aux = {"weight": wsum}
            if with_accuracy:
                # the argmax is a full extra pass over the microbatch
                # logits; with_accuracy=False (the bench) drops it, same
                # contract as _lm_train_step_fn
                correct, total = accuracy_counts(logits, tgt, weight=wgt)
                aux.update(correct=correct, total=total)
            return loss_sum, aux

        if self.schedule == "interleaved":
            stages = stack_stages(
                params["blocks"], self.num_stages * self.num_virtual
            )
            loss_sum, aux, stage_grads, head_grads, dxs = (
                pipeline_interleaved_loss_and_grad(
                    block_fn,
                    head_loss_fn,
                    stages,
                    params["head"],
                    xs,
                    targets.reshape((M, b // M, s)),
                    weight.reshape((M, b // M, s)),
                    num_microbatches=M,
                    num_virtual=self.num_virtual,
                    compute_dtype=self.dtype,
                    axis_name=self.pipe_axis,
                )
            )
        else:
            stages = stack_stages(params["blocks"], self.num_stages)
            loss_sum, aux, stage_grads, head_grads, dxs = (
                pipeline_1f1b_loss_and_grad(
                    block_fn,
                    head_loss_fn,
                    stages,
                    params["head"],
                    xs,
                    targets.reshape((M, b // M, s)),
                    weight.reshape((M, b // M, s)),
                    num_microbatches=M,
                    compute_dtype=self.dtype,
                    axis_name=self.pipe_axis,
                )
            )
        denom = jnp.maximum(aux["weight"], 1.0)
        loss = loss_sum / denom
        # the schedule differentiates the loss SUM; rescale to mean-loss
        # gradients and close the embedding's own vjp with the rescaled dx
        scale = 1.0 / denom
        (embed_grads,) = embed_vjp(
            (dxs * scale).reshape(x.shape).astype(x.dtype)
        )
        unstack = jax.tree.map(
            lambda g: g.reshape((self.depth,) + g.shape[2:]), stage_grads
        )
        grads = {
            "embed": embed_grads,
            "blocks": jax.tree.map(
                lambda g, p: (g * scale).astype(p.dtype),
                unstack, params["blocks"],
            ),
            "head": jax.tree.map(
                lambda g, p: (g * scale).astype(p.dtype),
                head_grads, params["head"],
            ),
        }
        counts = (
            {"correct": aux["correct"], "total": aux["total"]}
            if with_accuracy else None
        )
        return (loss, counts), grads

    def run_blocks(self, block_params, x):
        if self.num_stages <= 1:
            return self._sequential(block_params, x)
        stages = stack_stages(block_params, self.num_stages)

        def block_fn(stage_params, xb):
            def body(h, bp):
                return self.block.apply({"params": bp}, h), None

            h, _ = lax.scan(body, xb, stage_params)
            return h

        return pipeline_apply(
            block_fn,
            stages,
            x,
            num_microbatches=self.num_microbatches,
            axis_name=self.pipe_axis,
            remat=self.remat,
        )

    def _sequential(self, block_params, x):
        # honor remat on the unpipelined path too (num_stages == 1): the
        # trainer forwards --remat here, and silently training with full
        # O(depth) activation memory would contradict the flag
        apply_block = (
            jax.checkpoint(self.block.apply) if self.remat
            else self.block.apply
        )

        def body(h, bp):
            return apply_block({"params": bp}, h), None

        h, _ = lax.scan(body, x, block_params)
        return h
