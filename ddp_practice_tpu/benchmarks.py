"""Benchmark harness: honest steady-state training throughput + MFU.

Methodology (see BENCHMARKS.md at the repo root for the full story):

- **Device-resident data.** A pool of uint8 images lives in HBM; every
  step gathers a batch by on-device PRNG index and normalizes uint8 ->
  float on device. This measures the accelerator's training rate — the
  quantity MFU is defined over — rather than the host link. (On the
  tunneled dev TPU used for CI the host<->device link runs ~30 MB/s,
  1000x below a real deployment's DMA; streaming real batches would
  benchmark the tunnel. End-to-end numbers with the real input pipeline
  are recorded separately in PARITY.md.)
- **Fenced timing.** Some PJRT transports return from
  `jax.block_until_ready` before device execution completes, so every
  timing window is closed by a host readback of a scalar metric
  (`float(loss)`), which cannot resolve until the whole dependency chain
  has executed. Round-1 numbers lacked this fence and were invalid.
- **K steps per dispatch.** `lax.scan` over K optimizer steps per call
  amortizes dispatch latency; per-call overhead is <2% of the window.
- **Analytic FLOPs.** utils/flops.py; fwd+bwd = 3x forward. XLA's
  cost_analysis undercounts on this backend (~8x vs hand counts).
"""

from __future__ import annotations

from typing import Optional

import numpy as np


def bench_train(
    model_name: str,
    *,
    image_shape=(32, 32, 3),
    num_classes: int = 10,
    batch_size: int = 1024,
    steps_per_call: int = 32,
    calls: int = 8,
    warmup_calls: int = 2,
    precision: str = "bf16",
    pool_size: int = 8192,
    optimizer: str = "sgd",
    learning_rate: float = 1e-4,
    model_kwargs: Optional[dict] = None,
    seed: int = 0,
) -> dict:
    """Measure steady-state training throughput of one model, single host.

    Returns a dict with images/sec/chip, ms/step, and (on known TPU chips)
    achieved TFLOP/s and MFU against the bf16 peak.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ddp_practice_tpu.config import MeshConfig, PrecisionPolicy, TrainConfig
    from ddp_practice_tpu.models import create_model
    from ddp_practice_tpu.parallel.mesh import (
        batch_sharding,
        build_mesh,
        replicated,
        shard_state,
    )
    from ddp_practice_tpu.parallel.ring import set_current_mesh
    from ddp_practice_tpu.parallel.sharding_rules import param_sharding_rules
    from ddp_practice_tpu.train.state import create_state, make_optimizer
    from ddp_practice_tpu.train.steps import _train_step_fn
    from ddp_practice_tpu.utils.flops import chip_peak_flops, train_flops_per_image

    mesh = build_mesh(MeshConfig(data=-1))
    set_current_mesh(mesh)
    try:
        policy = PrecisionPolicy.from_name(precision)
        model = create_model(
            model_name, num_classes=num_classes, policy=policy, axis_name=None,
            **(model_kwargs or {}),
        )
        tcfg = TrainConfig(
            model=model_name, optimizer=optimizer, learning_rate=learning_rate
        )
        tx = make_optimizer(tcfg)

        sample = jnp.zeros((batch_size,) + tuple(image_shape), jnp.float32)

        def init_fn(r):
            return create_state(model, tx, rng=r, sample_input=sample)

        abstract = jax.eval_shape(init_fn, jax.random.PRNGKey(seed))
        rules = param_sharding_rules(model_name)
        state_shardings = shard_state(abstract, mesh, rules)
        state = jax.jit(init_fn, out_shardings=state_shardings)(
            jax.random.PRNGKey(seed)
        )

        # uint8 pool in HBM; labels alongside (synthetic — the benchmark measures
        # compute rate, not convergence; convergence parity lives in tests/PARITY)
        host_rng = np.random.default_rng(seed)
        pool_img_np = host_rng.integers(
            0, 256, size=(pool_size,) + tuple(image_shape), dtype=np.uint8
        )
        pool_lbl_np = host_rng.integers(
            0, num_classes, size=(pool_size,), dtype=np.int32
        )
        rep = replicated(mesh)
        pool_img = jax.device_put(pool_img_np, rep)
        pool_lbl = jax.device_put(pool_lbl_np, rep)

        bsh = batch_sharding(mesh)
        step_fn = _train_step_fn(model, tx, 0.0)
        base_key = jax.random.PRNGKey(seed + 1)
        k_steps = steps_per_call

        def chunk(state, pimg, plbl):
            def body(st, key):
                idx = jax.random.randint(key, (batch_size,), 0, pool_size)
                img = jnp.take(pimg, idx, axis=0).astype(jnp.float32) / 255.0
                batch = {
                    "image": lax.with_sharding_constraint(img, bsh),
                    "label": lax.with_sharding_constraint(
                        jnp.take(plbl, idx, axis=0), bsh
                    ),
                    "weight": jnp.ones((batch_size,), jnp.float32),
                }
                return step_fn(st, batch)

            keys = jax.random.split(
                jax.random.fold_in(base_key, state.step), k_steps
            )
            state, ms = lax.scan(body, state, keys)
            return state, jax.tree.map(lambda v: v[-1], ms)

        jchunk = jax.jit(
            chunk,
            donate_argnums=0,
            in_shardings=(state_shardings, rep, rep),
            out_shardings=(state_shardings, rep),
        )

        import time

        for _ in range(max(warmup_calls, 1)):  # >=1: the timed loop must not compile
            state, metrics = jchunk(state, pool_img, pool_lbl)
        _fence = float(metrics["loss"])  # forces completion (see module docstring)

        # two independently fenced windows covering exactly `calls`
        # calls: their agreement is the run-to-run stability evidence
        # (the round-3 ConvNet entry swung 62-91k img/s on single short
        # windows — round-4 verdict item 7). calls=1 runs one window and
        # reports no spread.
        w_calls = [calls - calls // 2, calls // 2]
        window_rates = []
        t0 = time.perf_counter()
        for wc in w_calls:
            if wc == 0:
                continue
            tw = time.perf_counter()
            for _ in range(wc):
                state, metrics = jchunk(state, pool_img, pool_lbl)
            final_loss = float(metrics["loss"])  # fence closes the window
            window_rates.append(
                wc * k_steps * batch_size / (time.perf_counter() - tw)
            )
        dt = time.perf_counter() - t0

        n_chips = jax.device_count()
        images = calls * k_steps * batch_size
        ips = images / dt
        ips_chip = ips / n_chips
        ms_per_step = dt / (calls * k_steps) * 1e3
        spread_pct = (
            100.0 * abs(window_rates[0] - window_rates[-1])
            / max(ips, 1e-9)
            if len(window_rates) > 1 else None
        )
        device_kind = jax.devices()[0].device_kind

        vit_kw = {}
        if model_name.startswith("vit"):
            # read the instantiated module's own config (registry defaults +
            # model_kwargs overrides) so the FLOP count matches what actually ran
            vit_kw = dict(
                patch_size=model.patch_size,
                hidden_dim=model.hidden_dim,
                depth=model.depth,
                mlp_dim=model.mlp_dim,
            )
        flops_img = train_flops_per_image(
            model_name, tuple(image_shape), num_classes, **vit_kw
        )
        out = {
            "model": model_name,
            "image_shape": list(image_shape),
            "batch_size": batch_size,
            "steps_per_call": k_steps,
            "precision": precision,
            "device_kind": device_kind,
            "n_chips": n_chips,
            "images_per_sec": round(ips, 1),
            "images_per_sec_per_chip": round(ips_chip, 1),
            "ms_per_step": round(ms_per_step, 3),
            "final_loss": round(final_loss, 4),
        }
        if spread_pct is not None:
            # agreement of the two fenced half-windows, % of the mean rate
            out["window_spread_pct"] = round(spread_pct, 2)
        if flops_img:
            tflops_chip = ips_chip * flops_img / 1e12
            out["train_flops_per_image"] = flops_img
            out["tflops_per_chip"] = round(tflops_chip, 2)
            peak = chip_peak_flops(device_kind)
            if peak:
                out["mfu_pct"] = round(100.0 * tflops_chip * 1e12 / peak, 2)
                out["peak_bf16_tflops"] = peak / 1e12
        return out
    finally:
        set_current_mesh(None)


def bench_lm_train(
    model_name: str = "lm_base",
    *,
    seq_len: int = 2048,
    vocab_size: int = 32768,
    batch_size: int = 8,
    steps_per_call: int = 4,
    calls: int = 4,
    warmup_calls: int = 1,
    precision: str = "bf16",
    attn_impl: str = "flash",
    optimizer: str = "adamw",
    learning_rate: float = 3e-4,
    model_kwargs: Optional[dict] = None,
    seed: int = 0,
    # "random": uniform randint tokens drawn on device (pure compute-rate
    # measurement). "corpus": device-resident windows of the synthetic
    # Markov byte corpus (data/lm_corpus.py) — vocab_size follows the
    # corpus. The MoE entry benches on the corpus: router balance is a
    # property of TRAINED routing, and uniform-random tokens leave
    # embeddings untrained (each of 32k ids seen ~0.5x per batch), so the
    # router chases drifting inputs and the recorded health is
    # meaningless (measured: drop oscillates 0.10-0.45 on random tokens
    # vs <2% warm on the corpus at identical model dims).
    data: str = "random",
) -> dict:
    """Steady-state LM training throughput at long sequence length:
    tokens/sec/chip + MFU. Same fenced-timing methodology as bench_train;
    token batches are drawn on device (randint — measuring compute rate,
    not convergence). Default kernel is the Pallas flash path: at seq 2k+
    the O(seq^2) dense score materialization is exactly what the tiled
    kernel exists to avoid."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    from ddp_practice_tpu.config import MeshConfig, PrecisionPolicy, TrainConfig
    from ddp_practice_tpu.models import create_model
    from ddp_practice_tpu.parallel.mesh import (
        batch_sharding,
        build_mesh,
        replicated,
        shard_state,
    )
    from ddp_practice_tpu.parallel.ring import set_current_mesh
    from ddp_practice_tpu.parallel.sharding_rules import param_sharding_rules
    from ddp_practice_tpu.train.state import create_state, make_optimizer
    from ddp_practice_tpu.train.steps import _lm_train_step_fn
    from ddp_practice_tpu.utils.flops import chip_peak_flops, lm_train_flops_per_token

    mesh = build_mesh(MeshConfig(data=-1))
    set_current_mesh(mesh)
    try:
        corpus_windows = None
        if data == "corpus":
            from ddp_practice_tpu.data.lm_corpus import synthetic_token_corpus

            c = synthetic_token_corpus(n_tokens=1 << 20, seed=seed + 7)
            vocab_size = c.vocab_size
            corpus_windows = jnp.asarray(c.windows(seq_len))
        elif data != "random":
            raise ValueError(f"unknown data source {data!r}")
        policy = PrecisionPolicy.from_name(precision)
        kwargs = dict(
            vocab_size=vocab_size, max_len=seq_len, attn_impl=attn_impl
        )
        kwargs.update(model_kwargs or {})
        model = create_model(model_name, policy=policy, **kwargs)
        tcfg = TrainConfig(
            model=model_name, optimizer=optimizer, learning_rate=learning_rate
        )
        tx = make_optimizer(tcfg)

        sample = jnp.zeros((batch_size, seq_len), jnp.int32)

        def init_fn(r):
            return create_state(model, tx, rng=r, sample_input=sample)

        abstract = jax.eval_shape(init_fn, jax.random.PRNGKey(seed))
        rules = param_sharding_rules(model_name)
        state_shardings = shard_state(abstract, mesh, rules)
        state = jax.jit(init_fn, out_shardings=state_shardings)(
            jax.random.PRNGKey(seed)
        )

        rep = replicated(mesh)
        bsh = batch_sharding(mesh)
        # loss-only metrics: the per-step accuracy argmax is a full
        # extra logits pass the reference's train loop never does
        step_fn = _lm_train_step_fn(model, tx, with_accuracy=False)
        base_key = jax.random.PRNGKey(seed + 1)
        k_steps = steps_per_call

        def chunk(state):
            def body(st, key):
                if corpus_windows is not None:
                    idx = jax.random.randint(
                        key, (batch_size,), 0, corpus_windows.shape[0],
                        dtype=jnp.int32,
                    )
                    tokens = corpus_windows[idx]
                else:
                    tokens = jax.random.randint(
                        key, (batch_size, seq_len + 1), 0, vocab_size,
                        dtype=jnp.int32,
                    )
                batch = {"tokens": lax.with_sharding_constraint(tokens, bsh)}
                return step_fn(st, batch)

            keys = jax.random.split(
                jax.random.fold_in(base_key, state.step), k_steps
            )
            state, ms = lax.scan(body, state, keys)
            return state, jax.tree.map(lambda v: v[-1], ms)

        jchunk = jax.jit(
            chunk,
            donate_argnums=0,
            in_shardings=(state_shardings,),
            out_shardings=(state_shardings, rep),
        )

        import time

        for _ in range(max(warmup_calls, 1)):
            state, metrics = jchunk(state)
        _fence = float(metrics["loss"])

        t0 = time.perf_counter()
        for _ in range(calls):
            state, metrics = jchunk(state)
        final_loss = float(metrics["loss"])
        dt = time.perf_counter() - t0

        n_chips = jax.device_count()
        tokens = calls * k_steps * batch_size * seq_len
        tps = tokens / dt
        tps_chip = tps / n_chips
        device_kind = jax.devices()[0].device_kind
        flops_tok = lm_train_flops_per_token(
            hidden_dim=model.hidden_dim, depth=model.depth,
            mlp_dim=model.mlp_dim, vocab_size=vocab_size, seq_len=seq_len,
            causal=True,
            moe_every=getattr(model, "moe_every", 0),
            moe_top_k=getattr(model, "moe_top_k", 2),
        )
        out = {
            "model": model_name,
            "seq_len": seq_len,
            "vocab_size": vocab_size,
            "batch_size": batch_size,
            "steps_per_call": k_steps,
            "precision": precision,
            "attn_impl": attn_impl,
            "device_kind": device_kind,
            "n_chips": n_chips,
            "tokens_per_sec": round(tps, 1),
            "tokens_per_sec_per_chip": round(tps_chip, 1),
            "ms_per_step": round(dt / (calls * k_steps) * 1e3, 3),
            "final_loss": round(final_loss, 4),
            "train_flops_per_token": flops_tok,
        }
        tflops_chip = tps_chip * flops_tok / 1e12
        out["tflops_per_chip"] = round(tflops_chip, 2)
        peak = chip_peak_flops(device_kind)
        if peak:
            out["mfu_pct"] = round(100.0 * tflops_chip * 1e12 / peak, 2)
            out["peak_bf16_tflops"] = peak / 1e12
        # router health from the final step's metrics (lm_moe)
        for k in ("moe_drop_rate", "moe_load_max", "moe_load_min"):
            if k in metrics:
                out[k] = round(float(metrics[k]), 4)
        return out
    finally:
        set_current_mesh(None)


def bench_lm_decode(
    model_name: str = "lm_base",
    *,
    prompt_len: int = 128,
    max_new_tokens: int = 512,
    batch_size: int = 8,
    vocab_size: int = 256,
    precision: str = "bf16",
    calls: int = 3,
    warmup_calls: int = 1,
    temperature: float = 1.0,
    top_k: int = 0,
    model_kwargs: Optional[dict] = None,
    seed: int = 0,
    # dtype the params are STREAMED in during decode. None follows the
    # precision policy: bf16 compute -> bf16 streaming (inference needs no
    # fp32 masters, and the cast is bit-identical to what every matmul
    # already does per-step — inference.cast_params_for_streaming), fp32
    # policy -> fp32 streaming. Pass explicitly to measure the other path.
    stream_dtype: Optional[str] = None,
    # KV-cache storage: "policy" (the compute dtype — bf16 here) or
    # "int8" (quantized cache + per-(head, position) scales,
    # models/vit.py / ops/decode_attention.py — halves the cache's
    # share of the bandwidth-bound step)
    kv_cache: str = "policy",
    # accepted for bench.py CLI-override uniformity; decode has no chunking
    steps_per_call: int = 0,
) -> dict:
    """Autoregressive generation throughput: KV-cache decode tokens/sec.

    Decode is HBM-bandwidth-bound, not MXU-bound: every generated token
    re-reads the full parameter set (plus the growing KV cache), so the
    roofline metric is model-bandwidth utilization (MBU) = bytes actually
    streamed per second / chip HBM bandwidth — reported alongside
    tokens/sec. Training keeps fp32 master params, but inference does not
    need them: under the bf16 policy the resident params are cast once,
    so the per-step traffic floor is 2 bytes/param + the bf16 KV cache
    read (`--precision fp32` / `stream_dtype="fp32"` measures the
    master-param path at 4 bytes/param — the two knobs move together
    unless stream_dtype is passed explicitly, so the reported precision
    always matches what streams). The whole generation (prefill + lax.scan of
    single-token steps, inference.py) is ONE jitted call; timing fences
    on a host readback of the final tokens.

    tokens_per_sec is the end-to-end generation rate (prefill included —
    that is what a caller of gen() experiences). The per-decode-step
    metrics (ms_per_token_step, mbu_pct) subtract a separately timed
    prefill-only call from the window, so they measure the decode loop
    itself rather than understating MBU by the prefill share. Configs
    where prefill would dominate are rejected rather than silently
    reported as decode rates.
    """
    if prompt_len > max_new_tokens:
        raise ValueError(
            f"prompt_len {prompt_len} > max_new_tokens {max_new_tokens}: "
            "end-to-end tokens_per_sec would be prefill-dominated — "
            "generate more tokens"
        )
    import time

    import jax
    import jax.numpy as jnp
    import numpy as np

    from ddp_practice_tpu.config import PrecisionPolicy
    from ddp_practice_tpu.inference import make_cache, make_generate_fn
    from ddp_practice_tpu.models import create_model
    from ddp_practice_tpu.utils.flops import chip_hbm_bandwidth

    policy = PrecisionPolicy.from_name(precision)
    kwargs = dict(
        vocab_size=vocab_size, max_len=prompt_len + max_new_tokens
    )
    if kv_cache == "int8":
        kwargs["kv_cache_dtype"] = "int8"
    elif kv_cache != "policy":
        raise ValueError(f"kv_cache {kv_cache!r} (want 'policy'|'int8')")
    kwargs.update(model_kwargs or {})
    model = create_model(model_name, policy=policy, **kwargs)
    rng = np.random.default_rng(seed)
    prompt = jnp.asarray(
        rng.integers(0, vocab_size, (batch_size, prompt_len)), jnp.int32
    )
    params = model.init(
        jax.random.PRNGKey(seed), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    if stream_dtype is None:
        stream_dtype = "bf16" if precision == "bf16" else "fp32"
    if stream_dtype not in ("bf16", "fp32"):
        raise ValueError(f"stream_dtype {stream_dtype!r} (want bf16|fp32)")
    param_bytes = 2 if stream_dtype == "bf16" else 4
    if stream_dtype == "bf16":
        from ddp_practice_tpu.inference import cast_params_for_streaming

        params = cast_params_for_streaming(params)
    n_params = sum(int(np.prod(l.shape)) for l in jax.tree.leaves(params))
    gen = jax.jit(
        make_generate_fn(
            model,
            max_new_tokens=max_new_tokens,
            temperature=temperature,
            top_k=top_k,
        )
    )
    key = jax.random.PRNGKey(seed + 1)
    for i in range(max(warmup_calls, 1)):
        tokens = gen(params, prompt, jax.random.fold_in(key, i))
    _fence = int(jax.device_get(tokens[0, -1]))

    # prefill-only program, timed separately so the decode-step metrics can
    # exclude it (same cache allocation + prompt pass as gen()'s first leg).
    # Both windows are fenced with one dispatch + one host readback per
    # call, so the per-call transport overhead (large on this tunnel —
    # ~100 ms/readback) appears identically in dt and prefill_dt and
    # cancels in the subtraction, leaving pure decode-scan time.
    @jax.jit
    def prefill_only(params, prompt):
        cache = make_cache(model, batch_size, prompt_len + max_new_tokens)
        logits, _ = model.apply(
            {"params": params, "cache": cache},
            prompt, decode=True, mutable=["cache"],
        )
        return logits[:, -1, 0]

    for _ in range(2):  # compile + one warm rep
        _fence = float(jax.device_get(prefill_only(params, prompt)[0]))
    t0 = time.perf_counter()
    for _ in range(calls):
        _fence = float(jax.device_get(prefill_only(params, prompt)[0]))
    prefill_dt = time.perf_counter() - t0

    t0 = time.perf_counter()
    for i in range(calls):
        tokens = gen(params, prompt, jax.random.fold_in(key, 100 + i))
        _fence = int(jax.device_get(tokens[0, -1]))  # fence every call
    dt = time.perf_counter() - t0
    # decode-only window; prefill can't exceed the whole, but guard the
    # subtraction against timer noise on tiny configs. When the floor
    # engages, the record says so (decode_window_clamped) — the advisor
    # flagged that ms_per_token_step/mbu would otherwise quietly come
    # from the fallback instead of the measurement
    decode_window_clamped = dt - prefill_dt < 0.2 * dt
    decode_dt = max(dt - prefill_dt, 0.2 * dt)
    if decode_window_clamped:
        import sys as _sys

        print(
            "[bench] decode window clamped to 20% of the call: prefill "
            f"timing ({prefill_dt:.3f}s) ate >80% of {dt:.3f}s — "
            "ms_per_token_step/mbu come from the floor, not the "
            "measurement",
            file=_sys.stderr,
        )

    # generation here is an UNSHARDED jit: it runs on one device no matter
    # how many are visible (unlike bench_lm_train's data-parallel mesh),
    # so per-chip rates divide by 1, not jax.device_count()
    n_chips = 1
    new_tokens = calls * batch_size * max_new_tokens
    tps = new_tokens / dt
    # param reads/sec (batched), decode loop only — prefill subtracted
    steps_per_sec = calls * max_new_tokens / decode_dt
    device_kind = jax.devices()[0].device_kind
    out = {
        "model": model_name,
        "mode": "decode",
        "prompt_len": prompt_len,
        "max_new_tokens": max_new_tokens,
        "batch_size": batch_size,
        "vocab_size": vocab_size,
        "precision": precision,
        "stream_dtype": stream_dtype,
        "device_kind": device_kind,
        "n_chips": n_chips,
        "n_params": n_params,
        "tokens_per_sec": round(tps, 1),
        "tokens_per_sec_per_chip": round(tps / n_chips, 1),
        "ms_per_token_step": round(1e3 / steps_per_sec, 3),
        "seconds_per_call": round(dt / calls, 3),
        "prefill_ms_per_call": round(prefill_dt / calls * 1e3, 1),
        "kv_cache": "int8" if kv_cache == "int8" else policy.name,
    }
    if decode_window_clamped:
        out["decode_window_clamped"] = True
    bw = chip_hbm_bandwidth(device_kind)
    if bw:
        # mbu_pct: the PARAMS-ONLY floor at the streamed dtype — kept
        # for cross-round comparability, but note it mathematically
        # CAPS below 100% whenever the cache read is a real fraction of
        # traffic (at bs=8/L=640/bf16 the cap is params/(params+cache)
        # ~= 60% — BENCHMARKS.md round-5 decode section).
        bytes_per_sec = n_params * param_bytes * steps_per_sec
        out["mbu_pct"] = round(100.0 * bytes_per_sec / (bw * n_chips), 2)
        # mbu_total_pct: params + the KV bytes the step ACTUALLY reads
        # (the single-block kernel reads the full allocated L each step;
        # int8 adds its fp32 scale rows) — the honest utilization of
        # the memory system.
        depth = getattr(model, "depth", 0)
        dm = getattr(model, "hidden_dim", 0)
        heads = getattr(model, "num_heads", 0)
        L = prompt_len + max_new_tokens
        # cache bytes follow the CACHE dtype — the policy compute dtype
        # (or int8), NOT stream_dtype, which only governs the params
        # (the stream_dtype="fp32" override keeps a bf16-policy cache)
        if kv_cache == "int8":
            kv_elem_bytes = 1
        else:
            kv_elem_bytes = jnp.dtype(policy.compute_dtype).itemsize
        kv_step = 2 * depth * L * dm * batch_size * kv_elem_bytes
        if kv_cache == "int8":
            kv_step += 2 * depth * heads * L * 4 * batch_size
        out["kv_bytes_per_step_mb"] = round(kv_step / 2**20, 1)
        out["mbu_total_pct"] = round(
            100.0 * (n_params * param_bytes + kv_step) * steps_per_sec
            / (bw * n_chips), 2,
        )
        out["hbm_gbps"] = bw / 1e9
    return out


def bench_pipeline(
    *,
    num_stages: int = 4,
    microbatch_counts=(2, 4, 8),
    hidden_dim: int = 256,
    depth: int = 4,
    num_heads: int = 8,
    mlp_dim: int = 1024,
    vocab_size: int = 256,
    seq_len: int = 256,
    mb_rows: int = 4,
    fixed_global_batch: int = 0,
    steps: int = 5,
    warmup: int = 2,
    precision: str = "bf16",
) -> list:
    """Pipeline schedule comparison: GPipe vs 1F1B over the microbatch
    count M, on whatever mesh the current devices allow (pipe=num_stages,
    data=rest).

    Two quantities per (schedule, M):

    - ms/step. Default mode holds the per-microbatch size FIXED (global
      batch grows with M), so pipeline efficiency = ideal/actual falls
      out of the schedule-length model t(M) ~ slope * (M + overhead):
      efficiency = slope * M / t(M), slope estimated from the two largest
      M. With `fixed_global_batch` set, the global batch stays constant
      (microbatches shrink as M grows) — the memory-story mode;
    - compiled temp memory (XLA memory_analysis) — at fixed global batch
      every input/output buffer is M-independent, so this isolates the
      schedules' activation state: GPipe's scan-transpose stash grows
      with M, 1F1B's ring stash must not.

    Run on the 8-virtual-device CPU mesh for the schedule comparison
    (pipe > 1 needs multiple devices; the CI TPU is a single chip) — the
    RELATIVE schedule behavior is device-independent; absolute ms/step on
    CPU is not a TPU number and BENCHMARKS.md never quotes it as one.
    """
    import time

    import jax
    import jax.numpy as jnp

    from ddp_practice_tpu.config import MeshConfig, PrecisionPolicy, TrainConfig
    from ddp_practice_tpu.models import create_model
    from ddp_practice_tpu.parallel.mesh import (
        batch_sharding,
        build_mesh,
        shard_state,
    )
    from ddp_practice_tpu.parallel.ring import set_current_mesh
    from ddp_practice_tpu.parallel.sharding_rules import param_sharding_rules
    from ddp_practice_tpu.train.state import create_state, make_optimizer
    from ddp_practice_tpu.train.steps import make_lm_train_step

    n_dev = jax.device_count()
    if n_dev % num_stages != 0:
        raise ValueError(f"{n_dev} devices not divisible by pipe={num_stages}")
    dp = n_dev // num_stages
    policy = PrecisionPolicy.from_name(precision)
    results = []
    for schedule in ("gpipe", "1f1b"):
        for mb_count in microbatch_counts:
            mesh = build_mesh(MeshConfig(data=dp, pipe=num_stages))
            set_current_mesh(mesh)
            try:
                model = create_model(
                    "lm_pipe", policy=policy, vocab_size=vocab_size,
                    max_len=seq_len, hidden_dim=hidden_dim, depth=depth,
                    num_heads=num_heads, mlp_dim=mlp_dim,
                    num_stages=num_stages, num_microbatches=mb_count,
                    schedule=schedule,
                )
                tx = make_optimizer(
                    TrainConfig(optimizer="adamw", learning_rate=1e-3)
                )
                if fixed_global_batch:
                    if fixed_global_batch % (mb_count * dp):
                        raise ValueError(
                            f"fixed_global_batch {fixed_global_batch} not "
                            f"divisible by M*dp = {mb_count * dp}"
                        )
                    b = fixed_global_batch
                else:
                    b = mb_count * mb_rows * dp
                sample = jnp.zeros((b, seq_len), jnp.int32)

                def init_fn(r):
                    return create_state(
                        model, tx, rng=r, sample_input=sample
                    )

                abstract = jax.eval_shape(init_fn, jax.random.PRNGKey(0))
                shardings = shard_state(
                    abstract, mesh, param_sharding_rules("lm_pipe")
                )
                state = jax.jit(init_fn, out_shardings=shardings)(
                    jax.random.PRNGKey(0)
                )
                step = make_lm_train_step(
                    model, tx, mesh=mesh, state_shardings=shardings,
                    batch_shardings=batch_sharding(mesh),
                )
                rng = np.random.default_rng(0)
                batch = {
                    "tokens": jnp.asarray(
                        rng.integers(0, vocab_size, (b, seq_len + 1)),
                        jnp.int32,
                    )
                }
                temp_bytes = None
                try:
                    compiled = step.lower(state, batch).compile()
                    mem = compiled.memory_analysis()
                    if mem is not None:
                        temp_bytes = int(mem.temp_size_in_bytes)
                except Exception:  # noqa: BLE001 — backend-dependent API
                    pass
                for _ in range(max(warmup, 1)):  # >=1: compile + metrics
                    state, metrics = step(state, batch)
                _ = float(metrics["loss"])
                steps = max(steps, 1)
                t0 = time.perf_counter()
                for _ in range(steps):
                    state, metrics = step(state, batch)
                    _ = float(metrics["loss"])  # fence (serializes on CPU)
                dt = time.perf_counter() - t0
                results.append({
                    "schedule": schedule,
                    "num_stages": num_stages,
                    "microbatches": mb_count,
                    "global_batch": b,
                    "seq_len": seq_len,
                    "ms_per_step": round(dt / steps * 1e3, 1),
                    "temp_bytes": temp_bytes,
                    "loss": round(float(metrics["loss"]), 4),
                })
            finally:
                set_current_mesh(None)
    if fixed_global_batch:
        return results  # constant work per step: the slope model is moot
    # schedule-length model: slope from the two largest M of each schedule
    for schedule in ("gpipe", "1f1b"):
        rs = [r for r in results if r["schedule"] == schedule]
        rs.sort(key=lambda r: r["microbatches"])
        if len(rs) >= 2:
            a, bb = rs[-2], rs[-1]
            slope = (bb["ms_per_step"] - a["ms_per_step"]) / (
                bb["microbatches"] - a["microbatches"]
            )
            for r in rs:
                if slope > 0:
                    r["efficiency_pct"] = round(
                        100.0 * slope * r["microbatches"] / r["ms_per_step"],
                        1,
                    )
    return results


def bench_serve(**kwargs) -> dict:
    """Continuous-batching vs static-batch serving on one Poisson trace.

    Delegates to serve/bench.py serve_bench (the serving subsystem owns
    its methodology — see that module's docstring); registered here so
    the benchmark surface stays one import. Returns the report dict with
    per-mode tokens/sec and TTFT/latency percentiles plus the
    continuous/static throughput ratio (BENCHMARKS.md serving section).
    """
    from ddp_practice_tpu.serve.bench import serve_bench

    return serve_bench(**kwargs)
