"""ddp_practice_tpu — a TPU-native (JAX/XLA/shard_map/pallas) training framework.

Brand-new implementation of the capabilities of the reference `gbbin/DDP-practice`
(single/multi-device data-parallel training with mixed precision), re-designed
TPU-first:

- NCCL process groups        -> `jax.distributed` + `jax.sharding.Mesh`
  (reference: ddp_main.py:69-73)
- DistributedDataParallel    -> `lax.pmean` gradient sync inside a jitted,
  shard_mapped train step (reference: ddp_main.py:121-123)
- SyncBatchNorm              -> cross-replica `pmean` of batch statistics via
  BatchNorm(axis_name=...) (reference: ddp_main.py:120)
- autocast + GradScaler      -> native bf16 precision policy, fp32 params
  (reference: ddp_main.py:31,126,91-93)
- DistributedSampler         -> per-host sharded input with (seed, epoch)-keyed
  shuffling (reference: ddp_main.py:130-142,160)
"""

__version__ = "0.1.0"

from ddp_practice_tpu.config import TrainConfig, MeshConfig, PrecisionPolicy

__all__ = ["TrainConfig", "MeshConfig", "PrecisionPolicy", "__version__"]
