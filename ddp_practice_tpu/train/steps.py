"""Jitted train/eval step factories.

The whole per-batch sequence of the reference — H2D copy, autocast forward,
loss, zero_grad, scaled backward with overlapped gradient all-reduce, scaler
step/update (ddp_main.py:85-93, SURVEY §3.4) — compiles here into ONE XLA
program per step. Distribution is by sharding, not wrappers: with the batch
sharded over the 'data' mesh axis and params replicated (or TP-sharded),
XLA inserts and overlaps the gradient all-reduce that DDP's bucketing reducer
performs in C++ (ddp_main.py:121-123), and BatchNorm's batch-axis mean IS the
global-batch mean (the SyncBatchNorm contract, ddp_main.py:120) because the
mean of a 'data'-sharded axis lowers to a cross-replica reduction.

Eval returns weighted (correct, total) sums — the dist.reduce(SUM) pair of
ddp_main.py:108-109, but exact under padding (SURVEY §2.5).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import optax

from ddp_practice_tpu.ops.losses import accuracy_counts, cross_entropy
from ddp_practice_tpu.train.state import TrainState


def prepare_image(img):
    """On-device ToTensor: uint8 batches ride H2D at 1/4 the bandwidth and
    become [0,1] float here, where XLA fuses the scale into the first conv
    (the reference's `ToTensor()` runs on host CPU per sample,
    origin_main.py:89). float32 batches pass through untouched. True
    division, not *(1/255): x/255.0 and x*(1/255.0) differ by 1 ulp for
    168 of the 256 uint8 values, and bit-identity with a host-side
    .astype(float32)/255.0 corpus is part of the storage contract
    (data/datasets.py)."""
    if img.dtype == jnp.uint8:
        return img.astype(jnp.float32) / 255.0
    return img


def _sown_aux_loss(intermediates):
    """Sum every sown leaf whose name carries the "aux_loss" suffix (MoE
    load-balance); diagnostic sows (router health, activations) never
    leak into the objective."""
    return sum(
        jnp.sum(leaf)
        for path, leaf in jax.tree_util.tree_flatten_with_path(
            intermediates
        )[0]
        if "aux_loss" in jax.tree_util.keystr(path)
    )


def _moe_metrics(intermediates):
    """Router-health scalars from the MoE diagnostic sows (ops/moe.py):
    worst/best per-expert share of routed tokens (ideal = 1/E each) and
    the mean assignment-slot drop rate, aggregated over MoE layers."""
    fracs, drops = [], []
    for path, leaf in jax.tree_util.tree_flatten_with_path(
        intermediates
    )[0]:
        name = jax.tree_util.keystr(path)
        if "moe_load_frac" in name:
            fracs.append(jnp.ravel(leaf))
        elif "moe_drop_rate" in name:
            drops.append(jnp.ravel(leaf))
    out = {}
    if fracs:
        stacked = jnp.concatenate(fracs)
        out["moe_load_max"] = jnp.max(stacked)
        out["moe_load_min"] = jnp.min(stacked)
    if drops:
        out["moe_drop_rate"] = jnp.mean(jnp.concatenate(drops))
    return out


def _step_rngs(step, seed: int = 0):
    """Per-step RNGs for stochastic layers (dropout).

    Keyed on (run seed, global step): reproducible for a given --seed,
    decorrelated across seeds, deterministic across checkpoint resume
    (state.step restores), and identical under the per-step, chunked-scan,
    and device-resident drivers at the same step. Under GSPMD the key is
    replicated and the dropout mask is a global array — each device
    materializes only its shard."""
    return {"dropout": jax.random.fold_in(jax.random.PRNGKey(seed), step)}


def _train_step_fn(model, tx, label_smoothing: float, seed: int = 0,
                   augment: bool = False):
    """The pure (state, batch) -> (state, metrics) function both the
    per-step and the scan-chunked factories jit."""

    def train_step(state: TrainState, batch):
        has_bn = state.batch_stats is not None
        images = prepare_image(batch["image"])
        if augment:
            # inside the jitted step, after the (resident) gather +
            # normalize; keyed on the global step so every driver variant
            # sees the same crops at the same step. `augment` is a kind:
            # True/"crop_flip" = pad-crop+flip, "rrc" = random resized
            # crop, the ImageNet rung (ops/augment.py)
            from ddp_practice_tpu.ops.augment import apply_augment, augment_rng

            images = apply_augment(
                images, augment_rng(seed, state.step), augment
            )

        def loss_fn(params):
            variables = {"params": params}
            mutable = ["intermediates"]  # routed layers sow aux losses here
            if has_bn:
                variables["batch_stats"] = state.batch_stats
                mutable.append("batch_stats")
            logits, updated = model.apply(
                variables, images, train=True,
                mutable=mutable, rngs=_step_rngs(state.step, seed),
            )
            new_stats = updated["batch_stats"] if has_bn else None
            loss = cross_entropy(
                logits, batch["label"], label_smoothing=label_smoothing
            )
            inter = updated.get("intermediates", {})
            loss = loss + _sown_aux_loss(inter)
            return loss, (logits, new_stats, inter)

        (loss, (logits, new_stats, inter)), grads = jax.value_and_grad(
            loss_fn, has_aux=True
        )(state.params)
        updates, new_opt_state = tx.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        correct, total = accuracy_counts(logits, batch["label"])
        metrics = {
            "loss": loss,
            "accuracy": correct / total,
            "grad_norm": optax.global_norm(grads),
            **_moe_metrics(inter),
        }
        new_state = TrainState(
            step=state.step + 1,
            params=new_params,
            batch_stats=new_stats,
            opt_state=new_opt_state,
        )
        return new_state, metrics

    return train_step


def make_train_step(
    model,
    tx,
    *,
    label_smoothing: float = 0.0,
    seed: int = 0,
    augment: bool = False,
    mesh=None,
    state_shardings=None,
    batch_shardings=None,
):
    """Build the jitted train step.

    When mesh/shardings are given, they pin input/output layouts (GSPMD);
    the state buffer is donated so parameters update in place in HBM.
    """
    train_step = _train_step_fn(model, tx, label_smoothing, seed, augment)
    if mesh is not None and state_shardings is not None:
        from ddp_practice_tpu.parallel.mesh import replicated

        rep = replicated(mesh)
        return jax.jit(
            train_step,
            in_shardings=(state_shardings, batch_shardings),
            out_shardings=(state_shardings, rep),
            donate_argnums=0,
        )
    return jax.jit(train_step, donate_argnums=0)


def stack_shardings(batch_shardings):
    """Sharding for (num_steps, batch, ...) stacked batches: leading scan
    dim replicated, inner dims as the per-batch shardings. Single source of
    truth for make_chunked_train_step, the Trainer, and prefetch_chunked
    callers — the jit in_shardings and the device_put layout must agree or
    every chunk pays a reshard."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    def one(sh):
        return NamedSharding(sh.mesh, P(None, *sh.spec))

    return jax.tree.map(
        one, batch_shardings, is_leaf=lambda x: isinstance(x, NamedSharding)
    )


def make_chunked_train_step(
    model,
    tx,
    *,
    num_steps: int,
    label_smoothing: float = 0.0,
    seed: int = 0,
    augment: bool = False,
    mesh=None,
    state_shardings=None,
    batch_shardings=None,
):
    """Build a jitted K-steps-per-call train step: `lax.scan` over batches
    stacked on a leading (num_steps, ...) dim.

    For small models the per-step cost is host dispatch + H2D latency, not
    device compute (the reference pays the same per-step H2D, pinned-memory
    copies at origin_main.py:60-61); scanning K optimizer steps inside one
    XLA program amortizes both by K. Identical math to K calls of
    make_train_step. Returned metrics are the final step's.
    """
    step_fn = _train_step_fn(model, tx, label_smoothing, seed, augment)

    def chunk_step(state, batches):
        state, ms = jax.lax.scan(step_fn, state, batches)
        return state, jax.tree.map(lambda v: v[-1], ms)

    if mesh is not None and state_shardings is not None:
        from ddp_practice_tpu.parallel.mesh import replicated

        rep = replicated(mesh)
        stacked = stack_shardings(batch_shardings)
        return jax.jit(
            chunk_step,
            in_shardings=(state_shardings, stacked),
            out_shardings=(state_shardings, rep),
            donate_argnums=0,
        )
    return jax.jit(chunk_step, donate_argnums=0)


def _lm_train_step_fn(model, tx, label_smoothing: float = 0.0, seed: int = 0,
                      with_accuracy: bool = True):
    """(state, batch) -> (state, metrics) for next-token language modeling.

    batch["tokens"] is (batch, seq+1) int32; position t predicts t+1 (the
    standard shifted objective). Optional batch["weight"] (batch, seq)
    masks padded positions out of the mean loss. Metrics report loss,
    perplexity (exp loss), next-token accuracy, and grad_norm — the LM
    equivalents of the image metrics in _train_step_fn.

    with_accuracy=False drops the per-step next-token accuracy from the
    metrics: its argmax is a full extra pass over the (tokens, vocab)
    logits (~1.7 ms/step at lm_base/32k vocab — round-4 profile), and the
    reference's own train loop computes loss only (train() at
    ddp_main.py:83-93; accuracy is the EVAL contract, ddp_main.py:96-112,
    which eval_step keeps exact). The bench uses the loss-only form."""

    def train_step(state: TrainState, batch):
        tokens = batch["tokens"]
        inputs, targets = tokens[:, :-1], tokens[:, 1:]
        weight = batch.get("weight")
        # lm_moe routers keep their aux-free balancing bias in
        # batch_stats (ops/moe.py MoEMlp) — threaded through the step
        # exactly like BatchNorm stats in the image step above
        has_stats = state.batch_stats is not None

        def loss_fn(params):
            variables = {"params": params}
            mutable = ["intermediates"]
            if has_stats:
                variables["batch_stats"] = state.batch_stats
                mutable.append("batch_stats")
            logits, updated = model.apply(
                variables, inputs, train=True,
                mutable=mutable,
                rngs=_step_rngs(state.step, seed),
            )
            new_stats = updated["batch_stats"] if has_stats else None
            loss = cross_entropy(
                logits, targets, weight=weight,
                label_smoothing=label_smoothing,
            )
            inter = updated.get("intermediates", {})
            # MoE blocks (lm_moe) sow their load-balance loss + router
            # health here, exactly like the image step
            loss = loss + _sown_aux_loss(inter)
            return loss, (logits, new_stats, inter)

        if getattr(model, "schedule", None) in ("1f1b", "interleaved"):
            # memory-bounded pipeline: the model runs its own fwd+bwd
            # interleaving (parallel/pipeline_1f1b.py) — autodiff of the
            # forward would force the GPipe all-F-then-all-B order. The
            # accuracy counts come back as scalars (full logits would be
            # an O(batch*seq*vocab) metrics buffer inside the schedule)
            (loss, counts), grads = model.loss_and_grad(
                state.params, inputs, targets, weight=weight,
                label_smoothing=label_smoothing,
                with_accuracy=with_accuracy,
            )
            if counts is not None:
                correct, total = counts["correct"], counts["total"]
            else:
                correct, total = None, None
            inter = {}
            # pipelined LMs carry no non-param state; a future pipelined
            # MoE would need its router bias threaded through the
            # schedule, not silently dropped here
            assert state.batch_stats is None, (
                "1F1B schedule does not thread batch_stats"
            )
            new_stats = None
        else:
            (loss, (logits, new_stats, inter)), grads = jax.value_and_grad(
                loss_fn, has_aux=True
            )(state.params)
            if with_accuracy:
                correct, total = accuracy_counts(
                    logits, targets, weight=weight
                )
            else:
                correct, total = None, None
        updates, new_opt_state = tx.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        metrics = {
            "loss": loss,
            "perplexity": jnp.exp(loss),
            "grad_norm": optax.global_norm(grads),
            **_moe_metrics(inter),
        }
        if correct is not None:
            metrics["accuracy"] = correct / jnp.maximum(total, 1.0)
        new_state = TrainState(
            step=state.step + 1,
            params=new_params,
            batch_stats=new_stats,
            opt_state=new_opt_state,
        )
        return new_state, metrics

    return train_step


def make_lm_train_step(
    model,
    tx,
    *,
    label_smoothing: float = 0.0,
    seed: int = 0,
    mesh=None,
    state_shardings=None,
    batch_shardings=None,
):
    """Jitted next-token LM train step; sharding contract identical to
    make_train_step (batch leaves sharded over 'data' and — for sequence
    parallelism — the token dim over 'seq')."""
    train_step = _lm_train_step_fn(model, tx, label_smoothing, seed)
    if mesh is not None and state_shardings is not None:
        from ddp_practice_tpu.parallel.mesh import replicated

        rep = replicated(mesh)
        return jax.jit(
            train_step,
            in_shardings=(state_shardings, batch_shardings),
            out_shardings=(state_shardings, rep),
            donate_argnums=0,
        )
    return jax.jit(train_step, donate_argnums=0)


def make_chunked_lm_train_step(
    model,
    tx,
    *,
    num_steps: int,
    label_smoothing: float = 0.0,
    seed: int = 0,
    mesh=None,
    state_shardings=None,
    batch_shardings=None,
):
    """K LM steps per dispatch (`lax.scan` over stacked token batches) —
    the dispatch-amortization scheme of make_chunked_train_step for the
    LM objective."""
    step_fn = _lm_train_step_fn(model, tx, label_smoothing, seed)

    def chunk_step(state, batches):
        state, ms = jax.lax.scan(step_fn, state, batches)
        return state, jax.tree.map(lambda v: v[-1], ms)

    if mesh is not None and state_shardings is not None:
        from ddp_practice_tpu.parallel.mesh import replicated

        rep = replicated(mesh)
        stacked = stack_shardings(batch_shardings)
        return jax.jit(
            chunk_step,
            in_shardings=(state_shardings, stacked),
            out_shardings=(state_shardings, rep),
            donate_argnums=0,
        )
    return jax.jit(chunk_step, donate_argnums=0)


def make_lm_eval_step(model, *, mesh=None, state_shardings=None,
                      batch_shardings=None):
    """Jitted LM eval: weighted (correct, total) next-token counts plus
    summed token NLL — the LM analogues of the image eval contract
    (accuracy for the parity-visible print, NLL/total = perplexity)."""

    def eval_step(state: TrainState, batch):
        tokens = batch["tokens"]
        inputs, targets = tokens[:, :-1], tokens[:, 1:]
        variables = {"params": state.params}
        if state.batch_stats is not None:
            # lm_moe router balancing bias (read-only at eval)
            variables["batch_stats"] = state.batch_stats
        logits = model.apply(variables, inputs, train=False)
        correct, total = accuracy_counts(logits, targets)
        nll = cross_entropy(logits, targets) * total
        return correct, total, nll

    if mesh is not None and state_shardings is not None:
        from ddp_practice_tpu.parallel.mesh import replicated

        rep = replicated(mesh)
        return jax.jit(
            eval_step,
            in_shardings=(state_shardings, batch_shardings),
            out_shardings=(rep, rep, rep),
        )
    return jax.jit(eval_step)


def _resident_gather(data, idx, batch_sharding=None):
    """Materialize one batch from the device-resident corpus: a gather of
    rows `idx` (B,) from each (N, ...) leaf. With the corpus replicated and
    `idx` sharded over 'data', GSPMD slices the index vector per device —
    each replica gathers only its rows, no collective.

    The sharding constraint + optimization_barrier pin the gathered batch
    to exactly the layout a host-fed batch has at the jit boundary
    (batch-dim sharded over 'data', materialized). Without them GSPMD may
    leave the batch replicated and fuse the gather into the first conv —
    BatchNorm's batch mean and the gradient reductions then partition
    differently and the resident path drifts bitwise from the host path it
    must mirror. Cost: one batch-sized buffer per step, negligible."""
    batch = {k: jnp.take(v, idx, axis=0) for k, v in data.items()}
    if batch_sharding is not None:
        batch = jax.lax.with_sharding_constraint(batch, batch_sharding)
    return jax.lax.optimization_barrier(batch)


def make_resident_train_step(
    model,
    tx,
    *,
    label_smoothing: float = 0.0,
    seed: int = 0,
    augment: bool = False,
    mesh=None,
    state_shardings=None,
):
    """Train G steps per jitted call against a device-RESIDENT dataset.

    `(state, data, idx)` where `data = {"image": (N,H,W,C) uint8, "label":
    (N,)}` lives in HBM (uploaded once per run) and `idx` is a (G, B) int32
    grid — one row per optimizer step. The scan body gathers its batch
    on device, so the only per-epoch host↔device traffic is the index grid
    (4·G·B bytes, ~240 KB for an MNIST epoch vs ~47 MB of pixels).

    This is the TPU-idiomatic endpoint of the reference's pinned-memory H2D
    pipeline (origin_main.py:96,60-61): for corpora that fit in HBM there is
    nothing left to transfer. Same math as G calls of make_train_step on
    the host-gathered batches (agreement to float noise — different XLA
    programs associate reductions differently; tests/test_resident.py).
    G is read from idx's shape — one factory serves any group size; each
    distinct G compiles once. Returned metrics are the final step's.
    """
    step_fn = _train_step_fn(model, tx, label_smoothing, seed, augment)
    bsh = None
    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec as P

        bsh = NamedSharding(mesh, P("data"))

    def resident_chunk(state, data, idx):
        def body(st, row):
            return step_fn(st, _resident_gather(data, row, bsh))

        state, ms = jax.lax.scan(body, state, idx)
        return state, jax.tree.map(lambda v: v[-1], ms)

    if mesh is not None and state_shardings is not None:
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ddp_practice_tpu.parallel.mesh import replicated

        rep = replicated(mesh)
        idx_sh = NamedSharding(mesh, P(None, "data"))
        return jax.jit(
            resident_chunk,
            in_shardings=(state_shardings, rep, idx_sh),
            out_shardings=(state_shardings, rep),
            donate_argnums=0,
        )
    return jax.jit(resident_chunk, donate_argnums=0)


def make_resident_eval_step(model, *, mesh=None, state_shardings=None):
    """Eval G batches per jitted call against the device-resident corpus:
    scan over (idx, weight) (G, B) grids, summing weighted (correct, total)
    in-graph — same exact-under-padding contract as the host eval steps."""
    step_fn = _eval_step_fn(model)
    bsh = None
    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec as P

        bsh = NamedSharding(mesh, P("data"))

    def resident_eval(state, data, idx, weight):
        def body(carry, row):
            i, w = row
            batch = _resident_gather(data, i, bsh)
            batch["weight"] = w
            c, t = step_fn(state, batch)
            return (carry[0] + c, carry[1] + t), None

        zero = jnp.zeros((), jnp.float32)
        (correct, total), _ = jax.lax.scan(body, (zero, zero), (idx, weight))
        return correct, total

    if mesh is not None and state_shardings is not None:
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ddp_practice_tpu.parallel.mesh import replicated

        rep = replicated(mesh)
        grid_sh = NamedSharding(mesh, P(None, "data"))
        return jax.jit(
            resident_eval,
            in_shardings=(state_shardings, rep, grid_sh, grid_sh),
            out_shardings=(rep, rep),
        )
    return jax.jit(resident_eval)


def _eval_step_fn(model):
    def eval_step(state: TrainState, batch):
        variables = {"params": state.params}
        if state.batch_stats is not None:
            variables["batch_stats"] = state.batch_stats
        logits = model.apply(variables, prepare_image(batch["image"]), train=False)
        return accuracy_counts(logits, batch["label"], weight=batch["weight"])

    return eval_step


def make_eval_step(model, *, mesh=None, state_shardings=None, batch_shardings=None):
    """Build the jitted eval step: weighted (correct, total) counts."""
    eval_step = _eval_step_fn(model)
    if mesh is not None and state_shardings is not None:
        from ddp_practice_tpu.parallel.mesh import replicated

        rep = replicated(mesh)
        return jax.jit(
            eval_step,
            in_shardings=(state_shardings, batch_shardings),
            out_shardings=(rep, rep),
        )
    return jax.jit(eval_step)


def make_chunked_eval_step(
    model,
    *,
    num_steps: int,
    mesh=None,
    state_shardings=None,
    batch_shardings=None,
):
    """K eval batches per jitted call: `lax.scan` over a stacked
    (num_steps, batch, ...) input, summing (correct, total) in-graph.

    Same dispatch-amortization rationale as make_chunked_train_step — the
    reference's eval loop pays one launch + H2D per batch
    (ddp_main.py:101-107); here one call covers K batches. The weight
    field keeps padded-tail exactness identical to the per-batch step.
    """
    step_fn = _eval_step_fn(model)

    def chunk_eval(state, batches):
        def body(carry, batch):
            c, t = step_fn(state, batch)
            return (carry[0] + c, carry[1] + t), None

        zero = jnp.zeros((), jnp.float32)
        (correct, total), _ = jax.lax.scan(body, (zero, zero), batches)
        return correct, total

    if mesh is not None and state_shardings is not None:
        from ddp_practice_tpu.parallel.mesh import replicated

        rep = replicated(mesh)
        stacked = stack_shardings(batch_shardings)
        return jax.jit(
            chunk_eval,
            in_shardings=(state_shardings, stacked),
            out_shardings=(rep, rep),
        )
    return jax.jit(chunk_eval)


def _lm_window_gather(tokens, starts, window: int, batch_sharding=None):
    """Materialize one (B, window) token batch from the HBM-resident
    stream: a strided gather at `starts` (B,) offsets. Same layout-pinning
    rationale as _resident_gather (sharding constraint + barrier keep the
    resident path bitwise on the host path's program shape)."""
    batch = tokens[starts[:, None] + jnp.arange(window)[None, :]]
    if batch_sharding is not None:
        batch = jax.lax.with_sharding_constraint(batch, batch_sharding)
    return jax.lax.optimization_barrier(batch)


def make_resident_lm_train_step(
    model,
    tx,
    *,
    window: int,
    label_smoothing: float = 0.0,
    seed: int = 0,
    mesh=None,
    state_shardings=None,
):
    """LM counterpart of make_resident_train_step: the token STREAM (a 1D
    int32 array — megabytes where the image corpora are tens of MB) lives
    in HBM, and each scanned step gathers its (B, seq_len + 1) windows
    on device from a (G, B) grid of start offsets (LMDataLoader
    .epoch_plan). Per-epoch host→device traffic: the grid alone."""
    step_fn = _lm_train_step_fn(model, tx, label_smoothing, seed)
    bsh = None
    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec as P

        bsh = NamedSharding(mesh, P("data"))

    def resident_chunk(state, data, starts):
        def body(st, row):
            batch = {
                "tokens": _lm_window_gather(data["tokens"], row, window, bsh)
            }
            return step_fn(st, batch)

        state, ms = jax.lax.scan(body, state, starts)
        return state, jax.tree.map(lambda v: v[-1], ms)

    if mesh is not None and state_shardings is not None:
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ddp_practice_tpu.parallel.mesh import replicated

        rep = replicated(mesh)
        grid_sh = NamedSharding(mesh, P(None, "data"))
        return jax.jit(
            resident_chunk,
            in_shardings=(state_shardings, rep, grid_sh),
            out_shardings=(state_shardings, rep),
            donate_argnums=0,
        )
    return jax.jit(resident_chunk, donate_argnums=0)


def make_resident_lm_eval_step(
    model, *, window: int, mesh=None, state_shardings=None
):
    """Eval G batches per call against the resident token stream: summed
    (correct, total, nll) over the scanned grid — the resident analogue of
    make_lm_eval_step's per-batch triple."""
    bsh = None
    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec as P

        bsh = NamedSharding(mesh, P("data"))

    def resident_eval(state, data, starts):
        def body(carry, row):
            tokens = _lm_window_gather(data["tokens"], row, window, bsh)
            inputs, targets = tokens[:, :-1], tokens[:, 1:]
            logits = model.apply(
                {"params": state.params}, inputs, train=False
            )
            c, t = accuracy_counts(logits, targets)
            s = cross_entropy(logits, targets) * t
            return (carry[0] + c, carry[1] + t, carry[2] + s), None

        zero = jnp.zeros((), jnp.float32)
        (correct, total, nll), _ = jax.lax.scan(
            body, (zero, zero, zero), starts
        )
        return correct, total, nll

    if mesh is not None and state_shardings is not None:
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ddp_practice_tpu.parallel.mesh import replicated

        rep = replicated(mesh)
        grid_sh = NamedSharding(mesh, P(None, "data"))
        return jax.jit(
            resident_eval,
            in_shardings=(state_shardings, rep, grid_sh),
            out_shardings=(rep, rep, rep),
        )
    return jax.jit(resident_eval)
