"""Training: state pytree, jitted step functions, epoch driver.

The reference's train()/test() loops (origin_main.py:57-81, ddp_main.py:83-112)
collapse here into two jitted functions over sharded arrays (SURVEY §3.4):
the host loop only feeds batches and logs.
"""

from ddp_practice_tpu.train.state import TrainState, create_state, make_optimizer
from ddp_practice_tpu.train.steps import make_train_step, make_eval_step
from ddp_practice_tpu.train.loop import Trainer, fit

__all__ = [
    "TrainState",
    "create_state",
    "make_optimizer",
    "make_train_step",
    "make_eval_step",
    "Trainer",
    "fit",
]
