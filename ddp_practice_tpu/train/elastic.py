"""Failure detection and checkpoint-based elastic restart.

The reference has neither: mp.spawn just waits on children and torchrun is
used --standalone with no restart policy exercised (ddp_main.py:176,
SURVEY §5.3 — "no retry, no health checks"). TPU-native stance:

- **fail fast**: jax.distributed.initialize carries its own rendezvous
  timeout; inside a run, a step watchdog detects a hung step (a stuck
  collective, a dead host) and terminates the process so the fleet
  scheduler / supervisor can reschedule — on TPU pods the supervisor owns
  process lifecycles, so in-process thread respawning (the GPU elastic-agent
  idiom) is the wrong layer.
- **recover by checkpoint**: `run_with_restarts` re-enters training from
  the last checkpoint (the resume path the reference lacks), bounding lost
  work to one checkpoint interval.
- **debug sync check** (SURVEY §5.2): JAX's SPMD model makes divergent
  collective sequences impossible *inside* one compiled program, but hosts
  can still drift in the Python driver loop (different step counts, skewed
  data exhaustion). `assert_in_sync` all-gathers a fingerprint across
  processes and raises on mismatch.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional

import jax

from ddp_practice_tpu.utils.logging import get_logger

log = get_logger()


class StepWatchdog:
    """Detects a hung training step (stuck collective / dead peer).

    `beat()` after every completed step; if no beat arrives within
    `timeout_s`, `on_timeout` fires from the watchdog thread (default:
    log CRITICAL and hard-exit so the supervisor restarts the process —
    fail-fast, matching how TPU pod schedulers manage lifecycles).
    """

    def __init__(
        self,
        timeout_s: float,
        on_timeout: Optional[Callable[[float], None]] = None,
        first_beat_grace: float = 10.0,
    ):
        self.timeout_s = timeout_s
        # until the first beat, the run is (re)compiling — XLA compile of a
        # large sharded program routinely dwarfs a step, so the first
        # window gets `first_beat_grace` x the step timeout
        self.first_beat_grace = first_beat_grace
        self._on_timeout = on_timeout or self._default_timeout
        self._last = time.monotonic()
        self._beaten = False
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    @staticmethod
    def _default_timeout(stalled_s: float) -> None:
        import os

        log.critical(
            "watchdog: no step completed in %.0fs — assuming hung "
            "collective or dead peer; exiting for supervisor restart",
            stalled_s,
        )
        os._exit(42)

    def start(self) -> "StepWatchdog":
        self._last = time.monotonic()
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()
        return self

    def beat(self) -> None:
        self._last = time.monotonic()
        self._beaten = True

    def seconds_since_beat(self) -> float:
        return time.monotonic() - self._last

    def probe_due(self) -> bool:
        """True when the next progress confirmation should not wait any
        longer: past half the timeout without a beat. Callers use this to
        couple probe cadence to the timeout, so a step-count probe
        interval can never starve the watchdog into a spurious firing on
        a healthy-but-slow run."""
        return self.seconds_since_beat() > self.timeout_s / 2

    def probe(self, value, fetch=None) -> None:
        """Record progress only after `value` resolves on the host.

        Under async dispatch a jit call returns before the device runs it
        (and on some PJRT transports even `block_until_ready` does not
        fence — BENCHMARKS.md), so beating after dispatch would let a hung
        collective go undetected while the host keeps enqueueing. Fetching
        a scalar from a step's metrics cannot complete until that step —
        and, by data dependence, every step before it — actually executed;
        if the device is hung, this call blocks, beats stop, and the
        watchdog thread fires on schedule.
        """
        if fetch is None:
            import jax

            fetch = jax.device_get
        fetch(value)
        self.beat()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)

    def __enter__(self) -> "StepWatchdog":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    def _run(self) -> None:
        poll = min(1.0, self.timeout_s / 4)
        while not self._stop.wait(poll):
            stalled = time.monotonic() - self._last
            limit = self.timeout_s if self._beaten else (
                self.timeout_s * self.first_beat_grace
            )
            if stalled > limit:
                self._on_timeout(stalled)
                return


def assert_in_sync(fingerprint: int, *, what: str = "step") -> None:
    """Raise if `fingerprint` differs across processes (driver-loop drift).

    All processes must call this at the same point — it is itself a
    collective (process_allgather). No-op with a single process.
    """
    if jax.process_count() == 1:
        return
    from jax.experimental import multihost_utils
    import numpy as np

    all_vals = np.asarray(
        multihost_utils.process_allgather(np.int64(fingerprint))
    ).reshape(-1)
    if not (all_vals == all_vals[0]).all():
        raise RuntimeError(
            f"hosts out of sync on {what}: process {jax.process_index()} "
            f"sees {fingerprint}, fleet sees {all_vals.tolist()}"
        )


def run_with_restarts(
    make_trainer: Callable[[bool], "object"],
    *,
    max_restarts: int = 0,
    restart_delay_s: float = 0.0,
    backoff_factor: float = 2.0,
    max_delay_s: float = 300.0,
    jitter: float = 0.5,
    seed: int = 0,
    metrics=None,
    sleep: Callable[[float], None] = time.sleep,
):
    """Run `trainer.fit()` with checkpoint-based recovery.

    make_trainer(resume) builds a fresh trainer; on a failed attempt the
    next one is built with resume=True so it restores the last checkpoint
    (requires a checkpoint_dir for recovery to actually shorten rework).
    Returns fit()'s summary. Re-raises after max_restarts failures.

    The wait before attempt k is exponential with deterministic jitter —
    `backoff_delay(k-1, base_s=restart_delay_s, ...)`, the same helper
    the serving router's retry budget and the replica circuit breaker
    use (utils/backoff.py) — so a fleet-wide failure does not restart
    every host in lockstep against the same struggling storage or
    rendezvous endpoint, yet a seeded test replays the exact schedule.
    restart_delay_s=0 keeps the legacy immediate-restart behavior.
    Restarts are counted in the metrics registry
    (``train_restarts_total``), so a supervisor can tell one bad step
    from a crash loop.
    """
    from ddp_practice_tpu.utils.backoff import backoff_delay
    from ddp_practice_tpu.utils.metrics import default_registry

    restarts = (metrics or default_registry()).counter(
        "train_restarts_total"
    )
    attempt = 0
    while True:
        try:
            trainer = make_trainer(attempt > 0)
            return trainer.fit()
        except KeyboardInterrupt:
            raise
        except Exception as e:  # noqa: BLE001 — any failure is restartable
            attempt += 1
            if attempt > max_restarts:
                raise
            restarts.inc()
            delay = backoff_delay(
                attempt - 1, base_s=restart_delay_s,
                factor=backoff_factor, max_s=max_delay_s,
                jitter=jitter, seed=seed,
            ) if restart_delay_s else 0.0
            log.error(
                "training attempt %d failed (%s: %s); restarting from last "
                "checkpoint in %.2fs (%d/%d)",
                attempt, type(e).__name__, e, delay, attempt, max_restarts,
            )
            if delay:
                sleep(delay)
