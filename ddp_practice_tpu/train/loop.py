"""Training driver: the main() of the framework.

Mirrors the reference's driver contract (ddp_main.py:115-170): epoch loop
with per-epoch reshuffle (set_epoch, ddp_main.py:160), eval participated in
by every process with globally reduced counts (ddp_main.py:108-109), side
effects (prints, checkpoint) on process 0 only (ddp_main.py:158-169), and
the three parity-visible outputs: epoch banners, "Accuracy is XX.XX%", and
final elapsed seconds (origin_main.py:109,81,121).

TPU-first differences: one process per host; a Mesh instead of ranks; the
step is one compiled XLA program; throughput is reported as images/sec/chip
(the BASELINE.json north-star metric).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ddp_practice_tpu import checkpoint as ckpt
from ddp_practice_tpu.config import MeshConfig, TrainConfig
from ddp_practice_tpu.data import DataLoader, ShardSpec, load_dataset
from ddp_practice_tpu.data.loader import prefetch_to_device
from ddp_practice_tpu.models import create_model
from ddp_practice_tpu.parallel import dist
from ddp_practice_tpu.parallel.mesh import batch_sharding, build_mesh, shard_state
from ddp_practice_tpu.parallel.ring import set_current_mesh
from ddp_practice_tpu.parallel.sharding_rules import param_sharding_rules
from ddp_practice_tpu.train.state import create_state, make_optimizer
from ddp_practice_tpu.utils.logging import get_logger, main_process_only
from ddp_practice_tpu.utils.profiling import profile_region, step_annotation
from ddp_practice_tpu.utils.timing import Timer
from ddp_practice_tpu.utils.trace import NULL_SPAN as _NULL_SPAN

log = get_logger()


def _future_ready(x) -> bool:
    """Best-effort completion check for a device scalar (False when the
    runtime can't say — the probe then just confirms this older rung)."""
    try:
        return bool(x.is_ready())
    except (AttributeError, RuntimeError):
        return False

# side effects on process 0 only (ddp_main.py:158-169); collectives and
# device work above these gates still run on every process
info0 = main_process_only(log.info)
warn0 = main_process_only(log.warning)


def _enable_compilation_cache(setting: str) -> None:
    """Point XLA's persistent compilation cache somewhere durable so repeat
    runs skip compile (the dominant cost of short runs: the parity
    experiment drops 28.5 s -> 10.0 s warm, PARITY.md). The reference has
    no equivalent — CUDA kernels arrive precompiled; XLA programs are
    compiled per (program, shapes) and this cache is the TPU-native answer.
    Idempotent; respects an explicit $JAX_COMPILATION_CACHE_DIR."""
    if setting == "off":
        return
    import os

    path = setting
    if setting == "auto":
        path = os.environ.get("JAX_COMPILATION_CACHE_DIR") or os.path.join(
            os.path.expanduser("~"), ".cache", "ddp_practice_tpu", "xla"
        )
    try:
        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
    except (OSError, AttributeError) as e:  # unwritable dir: run uncached
        log.warning("compilation cache disabled: %s", e)


class Trainer:
    def __init__(self, config: TrainConfig):
        self.config = config
        _enable_compilation_cache(config.compilation_cache)
        dist.initialize(
            config.coordinator_address, config.num_processes, config.process_id
        )
        policy = config.precision_policy()
        self.mesh = build_mesh(config.mesh)
        set_current_mesh(self.mesh)
        mesh_shape = dict(zip(self.mesh.axis_names, self.mesh.devices.shape))
        self.dp = mesh_shape.get(MeshConfig.AXIS_DATA, 1)
        self.sp = mesh_shape.get(MeshConfig.AXIS_SEQ, 1)
        self.pp = mesh_shape.get(MeshConfig.AXIS_PIPE, 1)

        # data — per-replica batch size x data-parallel degree = global batch
        # (the reference's "batch 32 per process" contract, README.md:506)
        self.global_batch = config.batch_size * self.dp
        shard = ShardSpec(dist.process_index(), dist.process_count())
        # lm_* models train on token streams (data/lm_corpus.py), the image
        # families on the dataset registry; both honor the same sampler
        # contract (seed/epoch permutation, per-process shards)
        self.task = "lm" if config.model.lower().startswith("lm") else "image"
        if self.task == "lm":
            self.train_ds = self.eval_ds = None
            (self.train_loader, self.eval_loader,
             self._vocab_size) = self._build_lm_data(shard)
        else:
            self.train_ds = load_dataset(
                config.dataset, config.data_dir, "train", seed=config.seed,
                synthetic_size=config.synthetic_size or None,
            )
            self.eval_ds = load_dataset(
                config.dataset, config.data_dir, "test", seed=config.seed,
                synthetic_size=(max(config.synthetic_size // 6, 1)
                                if config.synthetic_size else None),
            )
            self.train_loader = DataLoader(
                self.train_ds,
                global_batch_size=self.global_batch,
                shard=shard,
                seed=config.seed,
                shuffle=True,
                backend=config.loader_backend,
            )
            self.eval_loader = DataLoader(
                self.eval_ds,
                global_batch_size=self.global_batch,
                shard=shard,
                seed=config.seed,
                shuffle=config.shuffle_eval,
                backend=config.loader_backend,
            )

        # model
        model_kwargs = {}
        if config.num_heads:
            if not config.model.startswith(("vit", "lm")):
                raise ValueError(
                    f"--num_heads applies to transformer models, not "
                    f"{config.model!r}"
                )
            model_kwargs["num_heads"] = config.num_heads
        if config.dropout:
            if not 0.0 < config.dropout < 1.0:
                # rate >= 1 would silently zero every residual branch;
                # negative rates silently rescale activations
                raise ValueError(
                    f"--dropout must be in [0, 1), got {config.dropout}"
                )
            if config.model not in ("vit_tiny", "vit_base") and not (
                config.model.startswith("lm") and config.model != "lm_pipe"
            ):
                raise ValueError(
                    "--dropout is wired for the dense transformer families "
                    f"(vit_tiny, vit_base, lm_tiny/lm_base), not "
                    f"{config.model!r}"
                )
            model_kwargs["dropout_rate"] = config.dropout
        if self.sp > 1:
            model_kwargs["seq_axis"] = MeshConfig.AXIS_SEQ
            model_kwargs["sp_impl"] = config.sp_impl
        if config.attn_impl != "xla":
            # only attention models accept this; a conv model raises loudly
            # rather than silently ignoring the requested kernel
            model_kwargs["attn_impl"] = config.attn_impl
        fused_req = config.fused_encoder
        from ddp_practice_tpu.models import accepts_fused

        if fused_req in (True, "on"):
            if not accepts_fused(config.model):
                raise ValueError(
                    "--fused on is the small-d fused encoder-layer kernel "
                    "(ops/fused_encoder.py) for the dense transformer "
                    f"families, not {config.model!r} (conv/pipelined/"
                    "ViT-MoE keep their paths). Note wide models "
                    "(vit_base, lm_base) will then fail the kernel's VMEM "
                    "weight-residency check loudly, and lm_tiny needs "
                    "--num_heads 4 (head_dim must be a multiple of 64)"
                )
            model_kwargs["fused"] = True
        elif fused_req in (False, "off"):
            # the dense transformer families default to fused="auto";
            # an explicit off must override that, but only models that
            # take the kwarg can receive it (declared at registration —
            # models/__init__.py accepts_fused)
            if accepts_fused(config.model):
                model_kwargs["fused"] = False
        elif fused_req != "auto":
            raise ValueError(
                f"fused_encoder={fused_req!r} (want 'auto'|'on'|'off')"
            )
        # "auto": nothing to pass — the models default to fused="auto"
        # and resolve per block (models/vit.py EncoderBlock._auto_fuse)
        if config.pipe_schedule != "gpipe":
            # same fail-loudly convention as the other pipeline flags: a
            # schedule request on a pipe-less mesh, or for a model family
            # that only implements GPipe, must not train something else
            if self.pp <= 1:
                raise ValueError(
                    f"--pipe_schedule {config.pipe_schedule} needs a "
                    "pipeline mesh axis (--pipe > 1)"
                )
            if not config.model.startswith("lm_"):
                raise ValueError(
                    f"--pipe_schedule {config.pipe_schedule} is an LM "
                    "pipeline feature (models/pipeline_lm.py); "
                    f"{config.model} schedules with GPipe only"
                )
        if self.pp > 1:
            # pipeline-capable models take the stage count from the mesh; a
            # non-pipeline model with mesh.pipe > 1 fails loudly here rather
            # than silently training unpipelined
            model_kwargs["num_stages"] = self.pp
            model_kwargs["num_microbatches"] = config.num_microbatches
            if config.pipe_schedule != "gpipe":
                model_kwargs["schedule"] = config.pipe_schedule
            if config.pipe_schedule == "interleaved":
                model_kwargs["num_virtual"] = config.num_virtual
            # tensor parallelism composes: the pipeline shard_map is manual
            # over 'pipe'/'data' only, so the _vit_pipe_rule tensor specs
            # ride GSPMD inside each stage (parallel/pipeline.py)
        self.ep = mesh_shape.get(MeshConfig.AXIS_EXPERT, 1)
        if self.ep > 1 or config.num_experts:
            # expert count must divide evenly over the 'expert' axis; default
            # rounds the model's 8 up to the nearest multiple of the axis
            n_exp = config.num_experts or ((8 + self.ep - 1) // self.ep) * self.ep
            if n_exp % self.ep != 0:
                raise ValueError(
                    f"num_experts={n_exp} not divisible by expert axis {self.ep}"
                )
            model_kwargs["num_experts"] = n_exp
        if config.moe_router != "topk":
            if config.model not in ("vit_tiny_moe", "lm_moe"):
                raise ValueError(
                    "--moe_router applies to the MoE model families "
                    f"(vit_tiny_moe, lm_moe), not {config.model!r}"
                )
            model_kwargs["moe_router"] = config.moe_router
            # expert choice fills buffers by construction: cf 1.0 IS
            # "executed == active FLOPs". The registries' token-choice
            # headroom defaults (lm_moe 2.0) would silently double the
            # expert compute here.
            model_kwargs.setdefault("capacity_factor", 1.0)
        if self.task == "lm":
            model_kwargs["vocab_size"] = self._vocab_size
            model_kwargs["max_len"] = config.seq_len
            if config.remat:
                model_kwargs["remat"] = True
            if config.pos_emb != "learned":
                model_kwargs["pos_emb"] = config.pos_emb
            if config.tied_embeddings:
                if config.model == "lm_pipe":
                    raise ValueError(
                        "--tied is not wired for the pipelined LM — use "
                        "lm_tiny/lm_base for weight tying"
                    )
                model_kwargs["tied_embeddings"] = True
            if self.pp > 1 and config.model != "lm_pipe":
                raise ValueError(
                    "pipeline parallelism for language models uses the "
                    "stage-sharded variant: --model lm_pipe"
                )
            self.model = create_model(
                config.model, policy=policy, **model_kwargs
            )
        elif config.pos_emb != "learned":
            raise ValueError(
                "--pos_emb applies to the LM family (lm_*); "
                f"{config.model!r} keeps its own position scheme"
            )
        elif config.tied_embeddings:
            raise ValueError(
                "--tied (embedding/output weight tying) applies to the LM "
                f"family (lm_*), not {config.model!r}"
            )
        elif config.remat:
            raise ValueError(
                "remat is only wired for the LM family (lm_*) — the image "
                "models at these sizes gain nothing from rematerialization"
            )
        else:
            self.model = create_model(
                config.model,
                num_classes=self.train_ds.num_classes,
                policy=policy,
                axis_name=None,  # GSPMD: batch-axis stats are global by sharding
                **model_kwargs,
            )
        tp = mesh_shape.get(MeshConfig.AXIS_TENSOR, 1)
        if tp > 1:
            # fail with the fix named, not a pjit divisibility traceback:
            # the Megatron rules shard the head dim of qkv/out kernels
            heads = getattr(self.model, "num_heads", None) or getattr(
                getattr(self.model, "block", None), "num_heads", None
            )
            if heads is not None and heads % tp:
                raise ValueError(
                    f"tensor parallelism shards attention heads: "
                    f"{config.model} has {heads} heads, not divisible by "
                    f"--tensor {tp} — pass --num_heads (e.g. "
                    f"{((heads // tp) + 1) * tp}) or a different degree"
                )
        self.tx = make_optimizer(config, self.train_loader.steps_per_epoch)

        # state, sharded at init (params materialize directly on the mesh)
        rng = jax.random.PRNGKey(config.seed)
        # init with the global batch shape: sequence-parallel models open a
        # shard_map island whose dims must divide the mesh even during init
        if self.task == "lm":
            sample = jnp.zeros((self.global_batch, config.seq_len), jnp.int32)
        else:
            sample = jnp.zeros(
                (self.global_batch,) + self.train_ds.image_shape, jnp.float32
            )

        def init_fn(r):
            return create_state(self.model, self.tx, rng=r, sample_input=sample)

        abstract = jax.eval_shape(init_fn, rng)
        rules = param_sharding_rules(config.model)
        if config.fsdp:
            from ddp_practice_tpu.parallel.fsdp import fsdp_rules

            rules = fsdp_rules(self.dp, rules)
        self.state_shardings = shard_state(abstract, self.mesh, rules)
        self.state = jax.jit(init_fn, out_shardings=self.state_shardings)(rng)

        self.batch_shardings = batch_sharding(self.mesh)
        # one construction block for both tasks: only the factories differ
        # (the step signatures are deliberately uniform, train/steps.py)
        if self.task == "lm":
            from ddp_practice_tpu.train.steps import (
                make_chunked_lm_train_step as chunk_factory,
                make_lm_eval_step as eval_factory,
                make_lm_train_step as train_factory,
            )
        else:
            from ddp_practice_tpu.train.steps import (
                make_chunked_train_step as chunk_factory,
                make_eval_step as eval_factory,
                make_train_step as train_factory,
            )
        common = dict(
            mesh=self.mesh,
            state_shardings=self.state_shardings,
            batch_shardings=self.batch_shardings,
        )
        step_kwargs = dict(
            label_smoothing=config.label_smoothing, seed=config.seed,
        )
        if config.augment:
            if self.task == "lm":
                raise ValueError(
                    "--augment is image-input augmentation (random crop + "
                    "flip, ops/augment.py); it does not apply to token "
                    "streams"
                )
            step_kwargs["augment"] = config.augment_kind
        self.train_step = train_factory(
            self.model, self.tx, **step_kwargs, **common,
        )
        self.chunk_step = None
        if config.steps_per_call > 1:
            from ddp_practice_tpu.train.steps import stack_shardings

            self.stacked_shardings = stack_shardings(self.batch_shardings)
            self.chunk_step = chunk_factory(
                self.model, self.tx,
                num_steps=config.steps_per_call,
                **step_kwargs, **common,
            )
        self.eval_step = eval_factory(self.model, **common)
        # device-resident data: corpus uploaded to HBM once, epochs driven
        # by index grids alone (no per-batch H2D) — see _train_epoch_resident
        self.resident_train_step = None
        self.resident_eval_step = None
        if self._use_resident_data():
            from jax.sharding import NamedSharding, PartitionSpec as P

            from ddp_practice_tpu.parallel.mesh import replicated
            from ddp_practice_tpu.train.steps import (
                make_resident_eval_step,
                make_resident_train_step,
            )

            rep = replicated(self.mesh)
            self._grid_sharding = NamedSharding(
                self.mesh, P(None, MeshConfig.AXIS_DATA)
            )
            if self.task == "lm":
                from ddp_practice_tpu.train.steps import (
                    make_resident_lm_eval_step,
                    make_resident_lm_train_step,
                )

                self._train_data = {
                    "tokens": jax.device_put(
                        np.asarray(
                            self.train_loader.corpus.tokens, np.int32
                        ),
                        rep,
                    ),
                }
                self._eval_data = {
                    "tokens": jax.device_put(
                        np.asarray(self.eval_loader.corpus.tokens, np.int32),
                        rep,
                    ),
                }
                window = config.seq_len + 1
                self.resident_train_step = make_resident_lm_train_step(
                    self.model,
                    self.tx,
                    window=window,
                    label_smoothing=config.label_smoothing,
                    seed=config.seed,
                    mesh=self.mesh,
                    state_shardings=self.state_shardings,
                )
                self.resident_eval_step = make_resident_lm_eval_step(
                    self.model,
                    window=window,
                    mesh=self.mesh,
                    state_shardings=self.state_shardings,
                )
            else:
                self._train_data = {
                    "image": jax.device_put(
                        np.asarray(self.train_ds.images), rep
                    ),
                    "label": jax.device_put(
                        np.asarray(self.train_ds.labels), rep
                    ),
                }
                self._eval_data = {
                    "image": jax.device_put(
                        np.asarray(self.eval_ds.images), rep
                    ),
                    "label": jax.device_put(
                        np.asarray(self.eval_ds.labels), rep
                    ),
                }
                self.resident_train_step = make_resident_train_step(
                    self.model,
                    self.tx,
                    label_smoothing=config.label_smoothing,
                    seed=config.seed,
                    augment=(config.augment_kind if config.augment
                             else False),
                    mesh=self.mesh,
                    state_shardings=self.state_shardings,
                )
                self.resident_eval_step = make_resident_eval_step(
                    self.model,
                    mesh=self.mesh,
                    state_shardings=self.state_shardings,
                )
        elif config.steps_per_call == -1:
            raise ValueError(
                "steps_per_call=-1 (whole epoch per dispatch) needs "
                "device-resident data; got data_placement="
                f"{config.data_placement!r}"
                + (" in a multi-process run" if dist.process_count() > 1 else "")
                + " — use data_placement='device' (single process) or a "
                "positive steps_per_call"
            )
        self.chunk_eval_step = None
        if config.steps_per_call > 1 and self.task == "image":
            from ddp_practice_tpu.train.steps import make_chunked_eval_step

            self.chunk_eval_step = make_chunked_eval_step(
                self.model,
                num_steps=config.steps_per_call,
                mesh=self.mesh,
                state_shardings=self.state_shardings,
                batch_shardings=self.batch_shardings,
            )

        if config.resume and config.checkpoint_dir and ckpt.exists(config.checkpoint_dir):
            self.state = ckpt.restore(
                config.checkpoint_dir, self.state, shardings=self.state_shardings
            )
            info0("resumed from %s at step %d",
                  config.checkpoint_dir, int(self.state.step))

        self._train_images = 0
        self._train_seconds = 0.0
        self.eval_perplexity = None  # set by _evaluate_lm
        # host-side step-phase tracing (utils/trace.py): data / dispatch /
        # block / checkpoint spans into the same recorder family the
        # serving stack uses, written as Chrome trace JSON at fit end.
        # Device-side profiles (profile_dir) line up with these by wall
        # clock; process 0 only, None = zero overhead.
        self._tracer = None
        # a recorder exists for EITHER consumer: --trace_out wants the
        # exit-time dump, --telemetry_out wants the live stream (the
        # exporter attaches as sink below)
        if (config.trace_out or config.telemetry_out) \
                and dist.process_index() == 0:
            from ddp_practice_tpu.utils.trace import TraceRecorder

            self._tracer = TraceRecorder()
            self._tracer.set_process_name(0, "train")
            self._tracer.set_thread_name(0, 0, "steps")
        # XLA:CPU's in-process collective rendezvous can deadlock when more
        # than one execution of a collective-bearing program is in flight
        # (device threads join different run_ids). On the CPU dev platform,
        # serialize step dispatch; on TPU, keep async dispatch (collectives
        # ride ICI and overlap is the point).
        self._serialize_steps = jax.default_backend() == "cpu"
        self._watchdog = None
        self._pending_save = None  # in-flight async checkpoint write
        self._metrics_fh = None
        if config.metrics_file and dist.process_index() == 0:
            import os

            d = os.path.dirname(config.metrics_file)
            if d:
                os.makedirs(d, exist_ok=True)
            # append: records carry the global step, so a resumed run's
            # curve continues the same file
            self._metrics_fh = open(config.metrics_file, "a")
        # ladder of per-step scalar futures (see _probe_if_due)
        from collections import deque

        self._pending = deque()

        # ---- live telemetry plane (utils/telemetry.py; process 0 only):
        # step-time histogram, per-step MFU gauge (utils/flops.py
        # analytic count / measured step time / chip peak), rolling-MAD
        # straggler detector, optionally exported as streaming JSONL
        # (--telemetry_out) and scraped over HTTP (--metrics_port), with
        # an SLO burn-rate watchdog (--slo) over the detector's verdicts
        # — the same plane the serving stack exposes (serve/slo.py).
        self._telemetry = None
        self._tele_server = None
        self._train_registry = None
        self._anomaly = None
        self._slo = None
        self._last_group_t = None
        plane_on = (config.metrics_port is not None
                    or config.telemetry_out or config.slo)
        if plane_on and dist.process_index() == 0:
            from ddp_practice_tpu.utils.flops import chip_peak_flops
            from ddp_practice_tpu.utils.metrics import MetricsRegistry
            from ddp_practice_tpu.utils.telemetry import (
                StepAnomalyDetector,
                TelemetryExporter,
                TelemetryServer,
            )

            reg = MetricsRegistry()
            self._train_registry = reg
            self._step_time = reg.histogram("train_step_time_s")
            self._mfu_gauge = reg.gauge("train_mfu")
            self._anomaly_ctr = reg.counter("train_step_anomalies_total")
            self._anomaly = StepAnomalyDetector()
            self._flops_per_step = self._estimate_flops_per_step()
            self._peak_flops = chip_peak_flops(
                jax.devices()[0].device_kind
            )
            if config.telemetry_out:
                self._telemetry = TelemetryExporter(
                    config.telemetry_out, registry=reg
                )
                if self._tracer is not None:
                    self._telemetry.attach(self._tracer)
            if config.metrics_port is not None:
                self._tele_server = TelemetryServer(
                    registry=reg,
                    # one lane; DEGRADED while the step-time SLO burns
                    health_fn=lambda: {0: (
                        "degraded"
                        if self._slo is not None and self._slo.active
                        else "healthy"
                    )},
                    flight_fn=lambda: {
                        "step_time_s": self._step_time.summary(),
                    },
                    port=config.metrics_port,
                )
                info0("telemetry: /metrics /healthz /flight on port %d",
                      self._tele_server.port)
            if config.slo:
                from ddp_practice_tpu.serve.slo import (
                    AlertSinks,
                    SLOConfig,
                    SLOWatchdog,
                )

                sinks = (AlertSinks(config.alert_sinks, registry=reg)
                         if config.alert_sinks else None)
                self._slo = SLOWatchdog(
                    SLOConfig.from_json(config.slo), registry=reg,
                    tracer=self._tracer, telemetry=self._telemetry,
                    sinks=sinks, pid=0,
                )

    def _estimate_flops_per_step(self) -> Optional[float]:
        """Analytic train FLOPs per optimizer step (utils/flops.py) for
        the MFU gauge — best-effort: None (gauge stays 0) when the
        architecture has no analytic model here."""
        cfg = self.config
        try:
            if self.task == "lm":
                from ddp_practice_tpu.utils.flops import (
                    lm_train_flops_per_token,
                )

                m = self.model
                per_tok = lm_train_flops_per_token(
                    hidden_dim=m.hidden_dim, depth=m.depth,
                    mlp_dim=m.mlp_dim, vocab_size=m.vocab_size,
                    seq_len=cfg.seq_len,
                )
                return per_tok * cfg.seq_len * self.global_batch
            from ddp_practice_tpu.utils.flops import train_flops_per_image

            kw = {}
            if cfg.model.startswith("vit"):
                m = self.model
                kw = dict(patch_size=m.patch_size, hidden_dim=m.hidden_dim,
                          depth=m.depth, mlp_dim=m.mlp_dim)
            f = train_flops_per_image(
                cfg.model, tuple(self.train_ds.image_shape),
                self.train_ds.num_classes, **kw,
            )
            return f * self.global_batch if f else None
        except (AttributeError, TypeError, ValueError):
            return None

    def _observe_group(self, k: int) -> None:
        """Telemetry per dispatch group: step-time histogram, rolling-
        MAD straggler verdict (counted, traced, streamed), per-step MFU
        gauge, SLO feed. Host wall time between group boundaries — a
        straggler is a straggler whether the time went to the device,
        the data pipeline, or dispatch."""
        import time as _time

        now = _time.monotonic()
        last, self._last_group_t = self._last_group_t, now
        if last is None or k <= 0:
            return
        step_s = (now - last) / k
        self._step_time.observe(step_s)
        anomalous = self._anomaly.observe(step_s)
        if anomalous:
            self._anomaly_ctr.inc()
            warn0("step-time anomaly: %.3fs/step vs rolling median "
                  "(straggler?)", step_s)
            if self._tracer is not None and self._tracer.enabled:
                self._tracer.instant("step_anomaly", pid=0, tid=0,
                                     step_s=round(step_s, 6))
            if self._telemetry is not None:
                self._telemetry.emit("anomaly", step_s=step_s)
        if self._flops_per_step and self._peak_flops:
            self._mfu_gauge.set(
                self._flops_per_step / step_s
                / (self._peak_flops * jax.device_count())
            )
        if self._slo is not None:
            # the straggler SLO: an anomalous step is the bad event
            self._slo.observe_event(
                t=now, status="error" if anomalous else "eos"
            )
            self._slo.evaluate(now)

    def _tspan(self, name: str, **attrs):
        """A step-phase span on the train lane, or a no-op without
        --trace-out (one attribute test on the hot path)."""
        if self._tracer is None:
            return _NULL_SPAN
        return self._tracer.span(name, pid=0, tid=0, **attrs)

    def _traced_batches(self, items):
        """Wrap the prefetch stream so the time spent WAITING for the
        next batch (host data stall) shows up as "data" spans."""
        it = iter(items)
        while True:
            with self._tspan("data"):
                try:
                    item = next(it)
                except StopIteration:
                    return
            yield item

    def _save_trace(self) -> None:
        if self._tracer is None or not self.config.trace_out:
            return  # stream-only runs (--telemetry_out) have no dump
        try:
            self._tracer.save(self.config.trace_out)
            info0("wrote host trace to %s (%d events)",
                  self.config.trace_out, len(self._tracer))
        except OSError:
            log.exception("could not write --trace_out")

    def _track(self, scalar) -> None:
        """Record one step's scalar metric future on the progress ladder."""
        if self._watchdog is not None:
            self._pending.append(scalar)

    def _probe_if_due(self, prev: int, cur: int) -> None:
        """Watchdog probe on CONFIRMED device progress, when due: either the
        starvation rule (half the timeout without a beat) or a step-count
        boundary of watchdog_probe_every_steps crossed between prev and cur
        (boundary crossing, not modulo: chunked steps advance by K).

        The probe fetches the OLDEST unconfirmed step's scalar, never the
        newest: under async dispatch the host runs arbitrarily far ahead of
        the device, and fetching the newest step's metrics would block on
        the entire in-flight backlog — a healthy-but-behind device would
        then look hung and be killed. Fetching one rung past the last
        confirmed point blocks for at most one step of device time, so the
        watchdog fires exactly when NO step completes within the timeout.
        Already-completed rungs are skipped via is_ready() (if is_ready
        under-reports, probes just re-confirm older rungs — detection
        stays monotone, only delayed)."""
        n = self.config.watchdog_probe_every_steps
        if self._watchdog is None or not self._pending:
            return
        if self._watchdog.probe_due() or (n and prev // n != cur // n):
            while len(self._pending) > 1 and _future_ready(self._pending[0]):
                self._pending.popleft()
            self._watchdog.probe(self._pending.popleft())

    def _drain_pending(self) -> None:
        """Confirm every remaining ladder rung (beating on each) before an
        end-of-phase fence: the monolithic block_until_ready/device_get at
        epoch or eval end waits on the whole in-flight backlog, and without
        intermediate beats a healthy-but-behind device would look hung."""
        if self._watchdog is None:
            self._pending.clear()
            return
        while self._pending:
            self._watchdog.probe(self._pending.popleft())

    # ------------------------------------------------------------------ #

    def _build_lm_data(self, shard):
        """Token loaders for the LM task: dataset='text' reads bytes from
        data_dir (file or directory), anything else (or missing files)
        falls back to the deterministic synthetic Markov corpus. The last
        10% of the token stream is the held-out eval split."""
        from ddp_practice_tpu.data.lm_corpus import (
            LMDataLoader,
            TokenCorpus,
            load_text_corpus,
            synthetic_token_corpus,
        )

        cfg = self.config
        window = cfg.seq_len + 1
        batch_tokens = self.global_batch * window
        corpus = None
        if cfg.dataset == "text":
            try:
                corpus = load_text_corpus(cfg.data_dir)
            except FileNotFoundError:
                warn0(
                    "no readable files under %s — using the synthetic "
                    "Markov corpus", cfg.data_dir,
                )
        if corpus is None:
            # the synthetic default scales with the global batch so both
            # splits always hold >= one batch of windows on any mesh size
            corpus = synthetic_token_corpus(
                cfg.synthetic_size or max(262144, 16 * batch_tokens),
                seed=cfg.seed,
            )
        # eval = 10% of the stream, but never less than one global batch
        n_eval = max(len(corpus) - int(len(corpus) * 0.9), batch_tokens)
        n_train = len(corpus) - n_eval
        if n_train < batch_tokens:
            raise ValueError(
                f"corpus {corpus.name} has {len(corpus)} tokens — too few "
                f"for one train + one eval batch of {batch_tokens} tokens "
                f"each (global_batch {self.global_batch} x window {window}); "
                "grow the corpus or shrink batch_size/seq_len"
            )
        train_c = TokenCorpus(
            corpus.tokens[:n_train], corpus.vocab_size, f"{corpus.name}-train"
        )
        eval_c = TokenCorpus(
            corpus.tokens[n_train:], corpus.vocab_size, f"{corpus.name}-eval"
        )

        def make(c, shuffle):
            return LMDataLoader(
                c, seq_len=cfg.seq_len, global_batch_size=self.global_batch,
                shard=shard, seed=cfg.seed, shuffle=shuffle,
            )

        return make(train_c, True), make(eval_c, cfg.shuffle_eval), corpus.vocab_size

    def _use_resident_data(self) -> bool:
        """Decide the corpus's home. 'device' demands it (and single-process
        addressability); 'auto' takes it when it fits; 'host' never."""
        cfg = self.config
        if cfg.data_placement == "host":
            return False
        multi = dist.process_count() > 1
        if self.task == "lm":
            if cfg.data_placement == "device":
                if multi:
                    raise ValueError(
                        "data_placement='device' requires a single process"
                    )
                return True
            # auto: token streams are tiny (bytes per token; uploaded as
            # int32) — resident whenever they fit the same budget
            nbytes = 4 * (
                len(self.train_loader.corpus) + len(self.eval_loader.corpus)
            )
            return not multi and nbytes <= cfg.resident_max_bytes
        if cfg.data_placement == "device":
            if multi:
                raise ValueError(
                    "data_placement='device' requires a single process: the "
                    "whole corpus must be addressable to upload it; "
                    "multi-host runs stream with data_placement='host'"
                )
            return True
        if cfg.data_placement != "auto":
            raise ValueError(
                f"unknown data_placement {cfg.data_placement!r} "
                "(auto | host | device)"
            )
        nbytes = sum(
            ds.images.nbytes + ds.labels.nbytes
            for ds in (self.train_ds, self.eval_ds)
        )
        return not multi and nbytes <= cfg.resident_max_bytes

    def _resident_group(self, total_steps: int) -> int:
        """Steps per dispatch in resident mode: the whole epoch at
        steps_per_call=-1, else the configured chunk (min 1).

        With a watchdog enabled, the group is capped at
        watchdog_probe_every_steps: the watchdog's contract is that a
        probe blocks for at most ~one dispatch group of device time
        (_probe_if_due), so a whole-epoch group would turn every probe
        into an epoch-long blocking wait with no beats — a timeout
        shorter than compile+epoch would then kill a healthy run.
        Bounded groups keep hang detection and dispatch amortization
        both honest."""
        k = self.config.steps_per_call
        g = max(total_steps, 1) if k == -1 else max(k, 1)
        if self.config.watchdog_timeout_s:
            g = min(g, max(self.config.watchdog_probe_every_steps, 1))
        return g

    def _after_train_group(self, epoch: int, prev: int, steps_done: int,
                           metrics) -> None:
        """Post-dispatch bookkeeping shared by the host and resident train
        loops: progress ladder + watchdog probe, cross-host driver sync
        check, and the log-every readback (which doubles as a confirmed-
        progress beat). Boundary-crossing tests, not modulo: groups
        advance by K."""
        cfg = self.config
        if self._train_registry is not None:
            self._observe_group(steps_done - prev)
        self._track(metrics["loss"])
        self._probe_if_due(prev, steps_done)
        if cfg.sync_check_every_steps and (
            prev // cfg.sync_check_every_steps
            != steps_done // cfg.sync_check_every_steps
        ):
            from ddp_practice_tpu.train.elastic import assert_in_sync

            # host-side counter, NOT device state: detects driver-loop
            # drift (skewed data exhaustion, missed batches) — SURVEY §5.2
            assert_in_sync(
                epoch * self.train_loader.steps_per_epoch + steps_done,
                what="driver step",
            )
        bookkeeping = False  # log readback / checkpoint this boundary?
        if cfg.log_every_steps and (
            prev // cfg.log_every_steps != steps_done // cfg.log_every_steps
        ):
            bookkeeping = True
            with self._tspan("block", step=steps_done):
                m = jax.device_get(metrics)
            if self._watchdog is not None:
                self._watchdog.beat()  # the device_get confirmed progress
            info0(
                "epoch %d step %d loss %.4f acc %.3f",
                epoch, steps_done, float(m["loss"]), float(m["accuracy"]),
            )
            self._write_metrics({
                "kind": "train",
                "epoch": epoch,
                "step": int(self.state.step),
                **{k: float(v) for k, v in m.items()},
            })
        if (
            cfg.checkpoint_dir
            and cfg.checkpoint_every_steps
            and prev // cfg.checkpoint_every_steps
            != steps_done // cfg.checkpoint_every_steps
        ):
            bookkeeping = True
            self.save(periodic=True)
        if bookkeeping and self._train_registry is not None:
            # the readback/checkpoint above is boundary bookkeeping, not
            # a step: restart the step-time window AFTER it, or the next
            # group's sample absorbs it and the straggler detector / SLO
            # flags a healthy run (same reason _close_train_epoch resets)
            import time as _time

            self._last_group_t = _time.monotonic()

    def _write_metrics(self, record: dict) -> None:
        """Append one JSON line to the metrics file (process 0; no-op
        otherwise). Flushed per record so a crashed run's curve survives."""
        if self._metrics_fh is None:
            return
        import json
        import time as _time

        record.setdefault("time", _time.time())
        self._metrics_fh.write(json.dumps(record) + "\n")
        self._metrics_fh.flush()

    def _write_eval_record(self, epoch: int, accuracy: float) -> None:
        """One {kind: "eval"} metrics record — shared by the in-loop
        (--eval_every) and end-of-run eval sites."""
        self._write_metrics({
            "kind": "eval", "epoch": epoch,
            "step": int(self.state.step), "accuracy": accuracy,
            **({"perplexity": self.eval_perplexity}
               if self.eval_perplexity is not None else {}),
        })

    def _close_train_epoch(self, final_metrics) -> None:
        """End-of-epoch fence shared by both train loops: drain the probe
        ladder rung by rung (beats during the wait), then close timing on
        a scalar readback — the only progress signal that fences on every
        transport (block_until_ready may not — BENCHMARKS.md)."""
        with self._tspan("block", at="epoch_end"):
            self._drain_pending()
            jax.block_until_ready(self.state.params)
            if final_metrics is not None:
                jax.device_get(final_metrics["loss"])
                if self._watchdog is not None:
                    self._watchdog.beat()
        # an epoch boundary's eval/checkpoint gap is not a step — don't
        # let the straggler detector judge it as one
        self._last_group_t = None

    def _train_epoch_resident(self, epoch: int) -> dict:
        """One epoch against the HBM-resident corpus: the only H2D traffic
        is the (steps, batch) int32 index grid (~4·S·B bytes — for MNIST at
        bs 32, ~240 KB/epoch vs ~47 MB of pixels), sliced into groups of
        `_resident_group` rows per dispatch. With steps_per_call=-1 the
        epoch is ONE XLA call. Numerically equivalent to the host path:
        same (seed, epoch) plan (DataLoader.epoch_plan), same batches, same
        math — agreement is to float noise (the two compile as different
        XLA programs, so reductions associate differently; <= 2 ulps
        measured, tests/test_resident.py).

        With profile_dir, the trace covers the whole first epoch (the first
        group includes compile; use bench.py for steady-state traces)."""
        cfg = self.config
        self.train_loader.set_epoch(epoch)
        idx, _ = self.train_loader.epoch_plan()
        if cfg.max_steps_per_epoch:
            idx = idx[: cfg.max_steps_per_epoch]
        total = len(idx)
        g = self._resident_group(total)
        final_metrics = None
        self._pending.clear()
        timer = Timer()
        # host-side global step base for trace labels (resume-aware); the
        # state is quiescent at epoch start so this readback is free
        step_base = int(self.state.step)
        steps_done = 0
        profiling = False
        if cfg.profile_dir and epoch == 0:
            jax.profiler.start_trace(cfg.profile_dir)
            profiling = True
        try:
            for g0 in range(0, total, g):
                with self._tspan("data", step=step_base + steps_done):
                    rows = jax.device_put(
                        idx[g0 : g0 + g], self._grid_sharding
                    )
                with step_annotation(step_base + steps_done), \
                        self._tspan("dispatch", step=step_base + steps_done):
                    self.state, metrics = self.resident_train_step(
                        self.state, self._train_data, rows
                    )
                if self._serialize_steps:
                    jax.block_until_ready(metrics)
                inc = min(g, total - g0)
                prev = steps_done
                steps_done += inc
                final_metrics = metrics
                self._after_train_group(epoch, prev, steps_done, metrics)
            self._close_train_epoch(final_metrics)
        finally:
            if profiling:
                jax.profiler.stop_trace()
        dt = timer.elapsed()
        images = self.global_batch * steps_done
        self._train_images += images
        self._train_seconds += dt
        return {"epoch_seconds": dt, "images": images}

    def _evaluate_resident(self) -> float:
        """Exact global accuracy from the HBM-resident eval corpus; the
        padded tail carries zero weights in the plan grid, so the weighted
        counts match the host path bit for bit."""
        idx, w = self.eval_loader.epoch_plan()
        total_rows = len(idx)
        g = self._resident_group(total_rows)
        correct = jnp.zeros((), jnp.float32)
        total = jnp.zeros((), jnp.float32)
        self._pending.clear()
        with profile_region("eval"):
            n_eval = 0
            for g0 in range(0, total_rows, g):
                di = jax.device_put(idx[g0 : g0 + g], self._grid_sharding)
                dw = jax.device_put(w[g0 : g0 + g], self._grid_sharding)
                c, t = self.resident_eval_step(
                    self.state, self._eval_data, di, dw
                )
                if self._serialize_steps:
                    jax.block_until_ready(c)
                correct = correct + c
                total = total + t
                prev = n_eval
                n_eval += min(g, total_rows - g0)
                self._track(c)
                self._probe_if_due(prev, n_eval)
        self._drain_pending()
        acc = float(correct) / max(float(total), 1.0)
        if self._watchdog is not None:
            self._watchdog.beat()
        return acc

    def _tagged_batches(self, loader, k: int):
        """Prefetched ("chunk"|"single", device_batch) stream: K-stacked
        chunks when k > 1, per-batch otherwise — one selection point for
        both the train and eval loops."""
        if k > 1:
            from ddp_practice_tpu.data.loader import prefetch_chunked

            return prefetch_chunked(
                iter(loader), k,
                self.batch_shardings, self.stacked_shardings,
                size=self.config.prefetch,
            )
        return (
            ("single", b) for b in prefetch_to_device(
                iter(loader), self.batch_shardings,
                size=self.config.prefetch,
            )
        )

    def train_epoch(self, epoch: int) -> dict:
        if self.resident_train_step is not None:
            return self._train_epoch_resident(epoch)
        cfg = self.config
        self.train_loader.set_epoch(epoch)  # ≡ sampler.set_epoch (ddp_main.py:160)
        k = max(1, cfg.steps_per_call if self.chunk_step is not None else 1)
        items = self._tagged_batches(self.train_loader, k)
        batches = self._traced_batches(items)
        final_metrics = None
        self._pending.clear()
        timer = Timer()
        images_this_epoch = 0
        # host-side global step base for trace labels (resume-aware); the
        # state is quiescent at epoch start, and a host counter — unlike
        # int(self.state.step) per group — never blocks on in-flight steps
        step_base = int(self.state.step)
        # profile a steady-state window (post-compile) of the first epoch,
        # shrunk to fit short (smoke) epochs
        profile_window = None
        if cfg.profile_dir and epoch == 0:
            n = self.train_loader.steps_per_epoch
            if cfg.max_steps_per_epoch:
                n = min(n, cfg.max_steps_per_epoch)
            start = min(10, max(0, n - 10))
            stop = min(start + 10, n)
            if stop > start:
                profile_window = (start, stop)
            else:
                warn0("profile_dir set but epoch has %d steps — skipping trace", n)
        profiling = False
        steps_done = 0
        try:
            for tag, batch in batches:
                if cfg.max_steps_per_epoch and steps_done >= cfg.max_steps_per_epoch:
                    break
                if profiling and steps_done >= profile_window[1]:
                    jax.block_until_ready(self.state.params)
                    jax.profiler.stop_trace()
                    profiling = False
                    profile_window = None
                # start once anywhere past the window start (chunked runs
                # only visit multiples of k, which may skip the window)
                if profile_window and not profiling and (
                    steps_done >= profile_window[0]
                ):
                    jax.profiler.start_trace(cfg.profile_dir)
                    profiling = True
                with step_annotation(step_base + steps_done), \
                        self._tspan("dispatch", step=step_base + steps_done):
                    remaining = (
                        cfg.max_steps_per_epoch - steps_done
                        if cfg.max_steps_per_epoch else None
                    )
                    if tag == "chunk" and (remaining is None or remaining >= k):
                        self.state, metrics = self.chunk_step(self.state, batch)
                        inc = k
                    elif tag == "chunk":
                        # step cap mid-chunk: run the tail as single steps so
                        # the cap (and the resume-epoch math) stays exact
                        for j in range(remaining):
                            sub = jax.tree.map(lambda v: v[j], batch)
                            self.state, metrics = self.train_step(self.state, sub)
                        inc = remaining
                    else:
                        self.state, metrics = self.train_step(self.state, batch)
                        inc = 1
                if self._serialize_steps:
                    jax.block_until_ready(metrics)
                prev = steps_done
                steps_done += inc
                images_this_epoch += self.global_batch * inc
                final_metrics = metrics
                self._after_train_group(epoch, prev, steps_done, metrics)
            self._close_train_epoch(final_metrics)
        finally:
            items.close()  # stop the prefetch producer thread promptly
            if profiling:  # short epoch or mid-window failure: close trace
                jax.profiler.stop_trace()
        dt = timer.elapsed()
        self._train_images += images_this_epoch
        self._train_seconds += dt
        return {"epoch_seconds": dt, "images": images_this_epoch}

    def evaluate(self) -> float:
        """Global exact accuracy; all processes participate in the reduction
        (the all-ranks-call-the-collective contract, ddp_main.py:164,108-109).

        With steps_per_call > 1, K eval batches run per dispatch (scan),
        mirroring the chunked train path; the padded-tail weights keep the
        result exact either way."""
        if self.task == "lm":
            return self._evaluate_lm()
        if self.resident_eval_step is not None:
            return self._evaluate_resident()
        k = max(1, self.config.steps_per_call if self.chunk_eval_step else 1)
        it = self._tagged_batches(self.eval_loader, k)
        correct = jnp.zeros((), jnp.float32)
        total = jnp.zeros((), jnp.float32)
        self._pending.clear()
        try:
            # trace annotation: eval separates from train on device timelines
            with profile_region("eval"):
                n_eval = 0
                for tag, batch in it:
                    if tag == "chunk":
                        c, t = self.chunk_eval_step(self.state, batch)
                        inc = k
                    else:
                        c, t = self.eval_step(self.state, batch)
                        inc = 1
                    if self._serialize_steps:
                        jax.block_until_ready(c)
                    correct = correct + c
                    total = total + t
                    prev = n_eval
                    n_eval += inc
                    self._track(c)
                    self._probe_if_due(prev, n_eval)
        finally:
            it.close()  # stop the prefetch producer thread promptly
        self._drain_pending()  # rung-by-rung: beats during the wait
        acc = float(correct) / max(float(total), 1.0)  # readback = confirmed
        if self._watchdog is not None:
            self._watchdog.beat()
        return acc

    def _evaluate_lm(self) -> float:
        """Held-out next-token accuracy (the parity-visible number) plus
        perplexity (exp of mean token NLL, stored on self.eval_perplexity
        and in the fit summary) — all processes participate, like the
        image eval."""
        if self.resident_eval_step is not None:
            return self._evaluate_lm_resident()
        it = prefetch_to_device(
            iter(self.eval_loader), self.batch_shardings,
            size=self.config.prefetch,
        )
        correct = jnp.zeros((), jnp.float32)
        total = jnp.zeros((), jnp.float32)
        nll = jnp.zeros((), jnp.float32)
        self._pending.clear()
        try:
            with profile_region("eval"):
                n_eval = 0
                for batch in it:
                    c, t, s = self.eval_step(self.state, batch)
                    if self._serialize_steps:
                        jax.block_until_ready(c)
                    correct = correct + c
                    total = total + t
                    nll = nll + s
                    prev = n_eval
                    n_eval += 1
                    self._track(c)
                    self._probe_if_due(prev, n_eval)
        finally:
            it.close()
        return self._finish_lm_eval(correct, total, nll)

    def _evaluate_lm_resident(self) -> float:
        """LM eval against the HBM-resident token stream: grouped grids of
        window starts, (correct, total, nll) summed in-graph."""
        starts, _ = self.eval_loader.epoch_plan()
        total_rows = len(starts)
        g = self._resident_group(total_rows)
        correct = jnp.zeros((), jnp.float32)
        total = jnp.zeros((), jnp.float32)
        nll = jnp.zeros((), jnp.float32)
        self._pending.clear()
        with profile_region("eval"):
            n_eval = 0
            for g0 in range(0, total_rows, g):
                rows = jax.device_put(
                    starts[g0 : g0 + g], self._grid_sharding
                )
                c, t, s = self.resident_eval_step(
                    self.state, self._eval_data, rows
                )
                if self._serialize_steps:
                    jax.block_until_ready(c)
                correct = correct + c
                total = total + t
                nll = nll + s
                prev = n_eval
                n_eval += min(g, total_rows - g0)  # steps, not dispatches
                self._track(c)
                self._probe_if_due(prev, n_eval)
        return self._finish_lm_eval(correct, total, nll)

    def _finish_lm_eval(self, correct, total, nll) -> float:
        """Shared LM-eval epilogue (host + resident paths): drain the probe
        ladder, derive accuracy/perplexity, confirm progress."""
        import math

        self._drain_pending()
        t_f = max(float(total), 1.0)
        acc = float(correct) / t_f
        self.eval_perplexity = math.exp(min(float(nll) / t_f, 30.0))
        if self._watchdog is not None:
            self._watchdog.beat()
        return acc

    def save(self, *, periodic: bool = False) -> None:
        """Checkpoint the current state.

        periodic=True (the every-N-steps saves) uses the async writer in
        single-process runs: the leaf gather fences the device, the
        serialization + rename overlap the next steps. The previous write
        is always waited on first (overlapping saves to one directory are
        forbidden — checkpoint.save_async). End-of-fit and multi-host
        saves are synchronous."""
        if self._watchdog is not None:
            self._watchdog.beat()  # checkpoint IO is progress, not a hang
        with self._tspan("checkpoint", periodic=periodic):
            self._save_impl(periodic=periodic)

    def _save_impl(self, *, periodic: bool) -> None:
        if self._pending_save is not None:
            self._pending_save.wait()  # surfaces write errors too
            self._pending_save = None
        if self.config.checkpoint_dir:
            cfg = self.config
            # everything needed to rebuild the state TREE (not just values)
            # offline: generate.py restores a checkpoint with no knowledge
            # of the training invocation, so the knobs that change the
            # optimizer-state structure ride along in the manifest
            extra = {
                "step": int(self.state.step),
                "precision_policy": cfg.precision_policy().name,
                "model": cfg.model,
                "optimizer": cfg.optimizer,
                "momentum": cfg.momentum,
                "clip_norm": cfg.clip_norm,
                "weight_decay": cfg.weight_decay,
                "accum_steps": cfg.accum_steps,
            }
            if self.task == "lm":
                extra["seq_len"] = cfg.seq_len
                extra["vocab_size"] = self._vocab_size
                extra["remat"] = bool(cfg.remat)
                extra["pos_emb"] = cfg.pos_emb
                extra["tied_embeddings"] = bool(cfg.tied_embeddings)
            if periodic and cfg.checkpoint_async and dist.process_count() == 1:
                self._pending_save = ckpt.save_async(
                    cfg.checkpoint_dir, self.state, extra=extra
                )
            else:
                ckpt.save(cfg.checkpoint_dir, self.state, extra=extra)

    def fit(self) -> dict:
        cfg = self.config
        if cfg.watchdog_timeout_s:
            from ddp_practice_tpu.train.elastic import StepWatchdog

            self._watchdog = StepWatchdog(cfg.watchdog_timeout_s).start()
        try:
            return self._fit_inner()
        finally:
            if self._watchdog is not None:
                self._watchdog.stop()
                self._watchdog = None
            if self._pending_save is not None:
                # an exception mid-epoch must not leave an orphan writer
                # racing a restarted Trainer's restore/save in the same
                # directory (run_with_restarts reconstructs immediately);
                # swallow the write error — the original exception wins
                try:
                    self._pending_save.wait()
                except Exception:
                    log.exception("async checkpoint write failed")
                self._pending_save = None
            if self._metrics_fh is not None:
                # crash path: a restarted Trainer reopens the same file in
                # append mode; don't leak this fd until GC
                self._metrics_fh.close()
                self._metrics_fh = None
            # written in the finally so a crashed run still leaves its
            # partial timeline — a flight recorder's whole point
            self._save_trace()
            if self._tele_server is not None:
                self._tele_server.close()
                self._tele_server = None
            if self._telemetry is not None:
                # drain + final snapshot; the streamed lines were
                # flushed as they happened, so even skipping this
                # (SIGKILL) leaves a valid line-by-line file
                self._telemetry.close()
                self._telemetry = None

    def _fit_inner(self) -> dict:
        cfg = self.config
        timer = Timer()
        accuracy: Optional[float] = None
        # after a checkpoint restore, continue from the epoch the restored
        # step count falls in — lost work is bounded by one checkpoint
        # interval, not replayed from epoch 0
        steps_per_epoch = self.train_loader.steps_per_epoch
        if cfg.max_steps_per_epoch:
            steps_per_epoch = min(steps_per_epoch, cfg.max_steps_per_epoch)
        start_epoch = min(int(self.state.step) // max(steps_per_epoch, 1),
                          cfg.epochs)
        if start_epoch:
            info0("resuming at epoch %d (step %d)",
                  start_epoch, int(self.state.step))
        for epoch in range(start_epoch, cfg.epochs):
            info0("=== epoch %d / %d ===", epoch + 1, cfg.epochs)
            self.train_epoch(epoch)
            if cfg.eval_every_epochs and (epoch + 1) % cfg.eval_every_epochs == 0:
                accuracy = self.evaluate()
                info0("Accuracy is %.2f%%", accuracy * 100.0)
                self._write_eval_record(epoch, accuracy)
            if cfg.checkpoint_every_epochs and (epoch + 1) % cfg.checkpoint_every_epochs == 0:
                self.save()
        if accuracy is None or not cfg.eval_every_epochs:
            accuracy = self.evaluate()
            self._write_eval_record(cfg.epochs - 1, accuracy)
        self.save()
        elapsed = timer.elapsed()
        ips = self._train_images / max(self._train_seconds, 1e-9)
        summary = {
            "accuracy": accuracy,
            "elapsed_seconds": elapsed,
            "train_seconds": self._train_seconds,
            "images_per_sec": ips,
            "images_per_sec_per_chip": ips / jax.device_count(),
            "steps": int(self.state.step),
            "global_batch": self.global_batch,
            "devices": jax.device_count(),
        }
        if self.task == "lm" and self.eval_perplexity is not None:
            summary["perplexity"] = self.eval_perplexity
            summary["tokens_per_sec_per_chip"] = (
                ips * cfg.seq_len / jax.device_count()
            )
            info0("perplexity: %.3f", self.eval_perplexity)
        # the reference's three parity-visible lines (SURVEY §5.5)
        info0("Accuracy is %.2f%%", accuracy * 100.0)
        info0("time elapsed: %.2fs", elapsed)
        info0("throughput: %.1f images/sec (%.1f /chip)",
              ips, ips / jax.device_count())
        self._write_metrics({"kind": "summary", **summary})
        return summary


def fit(config: TrainConfig) -> dict:
    """Train once, or with checkpoint-based elastic restarts when
    max_restarts > 0 (recovery is effective with a checkpoint_dir set)."""
    if config.max_restarts > 0:
        from ddp_practice_tpu.train.elastic import run_with_restarts

        return run_with_restarts(
            lambda resume: Trainer(
                config.replace(resume=config.resume or resume)
            ),
            max_restarts=config.max_restarts,
        )
    return Trainer(config).fit()
