"""Training driver: the main() of the framework.

Mirrors the reference's driver contract (ddp_main.py:115-170): epoch loop
with per-epoch reshuffle (set_epoch, ddp_main.py:160), eval participated in
by every process with globally reduced counts (ddp_main.py:108-109), side
effects (prints, checkpoint) on process 0 only (ddp_main.py:158-169), and
the three parity-visible outputs: epoch banners, "Accuracy is XX.XX%", and
final elapsed seconds (origin_main.py:109,81,121).

TPU-first differences: one process per host; a Mesh instead of ranks; the
step is one compiled XLA program; throughput is reported as images/sec/chip
(the BASELINE.json north-star metric).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ddp_practice_tpu import checkpoint as ckpt
from ddp_practice_tpu.config import MeshConfig, TrainConfig
from ddp_practice_tpu.data import DataLoader, ShardSpec, load_dataset
from ddp_practice_tpu.data.loader import prefetch_to_device
from ddp_practice_tpu.models import create_model
from ddp_practice_tpu.parallel import dist
from ddp_practice_tpu.parallel.mesh import batch_sharding, build_mesh, shard_state
from ddp_practice_tpu.parallel.ring import set_current_mesh
from ddp_practice_tpu.parallel.sharding_rules import param_sharding_rules
from ddp_practice_tpu.train.state import create_state, make_optimizer
from ddp_practice_tpu.train.steps import make_eval_step, make_train_step
from ddp_practice_tpu.utils.logging import get_logger, main_process_only
from ddp_practice_tpu.utils.profiling import profile_region, step_annotation
from ddp_practice_tpu.utils.timing import Timer

log = get_logger()


def _future_ready(x) -> bool:
    """Best-effort completion check for a device scalar (False when the
    runtime can't say — the probe then just confirms this older rung)."""
    try:
        return bool(x.is_ready())
    except (AttributeError, RuntimeError):
        return False

# side effects on process 0 only (ddp_main.py:158-169); collectives and
# device work above these gates still run on every process
info0 = main_process_only(log.info)
warn0 = main_process_only(log.warning)


class Trainer:
    def __init__(self, config: TrainConfig):
        self.config = config
        dist.initialize(
            config.coordinator_address, config.num_processes, config.process_id
        )
        policy = config.precision_policy()
        self.mesh = build_mesh(config.mesh)
        set_current_mesh(self.mesh)
        mesh_shape = dict(zip(self.mesh.axis_names, self.mesh.devices.shape))
        self.dp = mesh_shape.get(MeshConfig.AXIS_DATA, 1)
        self.sp = mesh_shape.get(MeshConfig.AXIS_SEQ, 1)
        self.pp = mesh_shape.get(MeshConfig.AXIS_PIPE, 1)

        # data — per-replica batch size x data-parallel degree = global batch
        # (the reference's "batch 32 per process" contract, README.md:506)
        self.global_batch = config.batch_size * self.dp
        shard = ShardSpec(dist.process_index(), dist.process_count())
        self.train_ds = load_dataset(
            config.dataset, config.data_dir, "train", seed=config.seed,
            synthetic_size=config.synthetic_size or None,
        )
        self.eval_ds = load_dataset(
            config.dataset, config.data_dir, "test", seed=config.seed,
            synthetic_size=(max(config.synthetic_size // 6, 1)
                            if config.synthetic_size else None),
        )
        self.train_loader = DataLoader(
            self.train_ds,
            global_batch_size=self.global_batch,
            shard=shard,
            seed=config.seed,
            shuffle=True,
            backend=config.loader_backend,
        )
        self.eval_loader = DataLoader(
            self.eval_ds,
            global_batch_size=self.global_batch,
            shard=shard,
            seed=config.seed,
            shuffle=config.shuffle_eval,
            backend=config.loader_backend,
        )

        # model
        model_kwargs = {}
        if self.sp > 1:
            model_kwargs["seq_axis"] = MeshConfig.AXIS_SEQ
            model_kwargs["sp_impl"] = config.sp_impl
        if config.attn_impl != "xla":
            # only attention models accept this; a conv model raises loudly
            # rather than silently ignoring the requested kernel
            model_kwargs["attn_impl"] = config.attn_impl
        if self.pp > 1:
            # pipeline-capable models take the stage count from the mesh; a
            # non-pipeline model with mesh.pipe > 1 fails loudly here rather
            # than silently training unpipelined
            model_kwargs["num_stages"] = self.pp
            model_kwargs["num_microbatches"] = config.num_microbatches
            if mesh_shape.get(MeshConfig.AXIS_TENSOR, 1) > 1:
                # TP rules deliberately leave pipeline block params' inner
                # dims replicated (sharding_rules._vit_pipe_rule); training
                # with --tensor>1 --pipe>1 would silently not be
                # tensor-parallel, so refuse instead
                raise ValueError(
                    "tensor parallelism is not composed into the pipeline "
                    "shard_map yet: use tensor>1 with pipe=1, or pipe>1 "
                    "with tensor=1 (supported combinations: README "
                    "'Parallelism composition')"
                )
        self.ep = mesh_shape.get(MeshConfig.AXIS_EXPERT, 1)
        if self.ep > 1 or config.num_experts:
            # expert count must divide evenly over the 'expert' axis; default
            # rounds the model's 8 up to the nearest multiple of the axis
            n_exp = config.num_experts or ((8 + self.ep - 1) // self.ep) * self.ep
            if n_exp % self.ep != 0:
                raise ValueError(
                    f"num_experts={n_exp} not divisible by expert axis {self.ep}"
                )
            model_kwargs["num_experts"] = n_exp
        self.model = create_model(
            config.model,
            num_classes=self.train_ds.num_classes,
            policy=policy,
            axis_name=None,  # GSPMD: batch-axis stats are global by sharding
            **model_kwargs,
        )
        self.tx = make_optimizer(config, self.train_loader.steps_per_epoch)

        # state, sharded at init (params materialize directly on the mesh)
        rng = jax.random.PRNGKey(config.seed)
        # init with the global batch shape: sequence-parallel models open a
        # shard_map island whose dims must divide the mesh even during init
        sample = jnp.zeros(
            (self.global_batch,) + self.train_ds.image_shape, jnp.float32
        )

        def init_fn(r):
            return create_state(self.model, self.tx, rng=r, sample_input=sample)

        abstract = jax.eval_shape(init_fn, rng)
        rules = param_sharding_rules(config.model)
        if config.fsdp:
            from ddp_practice_tpu.parallel.fsdp import fsdp_rules

            rules = fsdp_rules(self.dp, rules)
        self.state_shardings = shard_state(abstract, self.mesh, rules)
        self.state = jax.jit(init_fn, out_shardings=self.state_shardings)(rng)

        self.batch_shardings = batch_sharding(self.mesh)
        self.train_step = make_train_step(
            self.model,
            self.tx,
            label_smoothing=config.label_smoothing,
            mesh=self.mesh,
            state_shardings=self.state_shardings,
            batch_shardings=self.batch_shardings,
        )
        self.chunk_step = None
        if config.steps_per_call > 1:
            from ddp_practice_tpu.train.steps import (
                make_chunked_train_step,
                stack_shardings,
            )

            self.stacked_shardings = stack_shardings(self.batch_shardings)
            self.chunk_step = make_chunked_train_step(
                self.model,
                self.tx,
                num_steps=config.steps_per_call,
                label_smoothing=config.label_smoothing,
                mesh=self.mesh,
                state_shardings=self.state_shardings,
                batch_shardings=self.batch_shardings,
            )
        self.eval_step = make_eval_step(
            self.model,
            mesh=self.mesh,
            state_shardings=self.state_shardings,
            batch_shardings=self.batch_shardings,
        )

        if config.resume and config.checkpoint_dir and ckpt.exists(config.checkpoint_dir):
            self.state = ckpt.restore(
                config.checkpoint_dir, self.state, shardings=self.state_shardings
            )
            info0("resumed from %s at step %d",
                  config.checkpoint_dir, int(self.state.step))

        self._train_images = 0
        self._train_seconds = 0.0
        # XLA:CPU's in-process collective rendezvous can deadlock when more
        # than one execution of a collective-bearing program is in flight
        # (device threads join different run_ids). On the CPU dev platform,
        # serialize step dispatch; on TPU, keep async dispatch (collectives
        # ride ICI and overlap is the point).
        self._serialize_steps = jax.default_backend() == "cpu"
        self._watchdog = None
        # ladder of per-step scalar futures (see _probe_if_due)
        from collections import deque

        self._pending = deque()

    def _track(self, scalar) -> None:
        """Record one step's scalar metric future on the progress ladder."""
        if self._watchdog is not None:
            self._pending.append(scalar)

    def _probe_if_due(self, prev: int, cur: int) -> None:
        """Watchdog probe on CONFIRMED device progress, when due: either the
        starvation rule (half the timeout without a beat) or a step-count
        boundary of watchdog_probe_every_steps crossed between prev and cur
        (boundary crossing, not modulo: chunked steps advance by K).

        The probe fetches the OLDEST unconfirmed step's scalar, never the
        newest: under async dispatch the host runs arbitrarily far ahead of
        the device, and fetching the newest step's metrics would block on
        the entire in-flight backlog — a healthy-but-behind device would
        then look hung and be killed. Fetching one rung past the last
        confirmed point blocks for at most one step of device time, so the
        watchdog fires exactly when NO step completes within the timeout.
        Already-completed rungs are skipped via is_ready() (if is_ready
        under-reports, probes just re-confirm older rungs — detection
        stays monotone, only delayed)."""
        n = self.config.watchdog_probe_every_steps
        if self._watchdog is None or not self._pending:
            return
        if self._watchdog.probe_due() or (n and prev // n != cur // n):
            while len(self._pending) > 1 and _future_ready(self._pending[0]):
                self._pending.popleft()
            self._watchdog.probe(self._pending.popleft())

    def _drain_pending(self) -> None:
        """Confirm every remaining ladder rung (beating on each) before an
        end-of-phase fence: the monolithic block_until_ready/device_get at
        epoch or eval end waits on the whole in-flight backlog, and without
        intermediate beats a healthy-but-behind device would look hung."""
        if self._watchdog is None:
            self._pending.clear()
            return
        while self._pending:
            self._watchdog.probe(self._pending.popleft())

    # ------------------------------------------------------------------ #

    def train_epoch(self, epoch: int) -> dict:
        cfg = self.config
        self.train_loader.set_epoch(epoch)  # ≡ sampler.set_epoch (ddp_main.py:160)
        k = max(1, cfg.steps_per_call if self.chunk_step is not None else 1)
        if k > 1:
            from ddp_practice_tpu.data.loader import prefetch_chunked

            items = prefetch_chunked(
                iter(self.train_loader), k,
                self.batch_shardings, self.stacked_shardings,
                size=cfg.prefetch,
            )
        else:
            items = (
                ("single", b) for b in prefetch_to_device(
                    iter(self.train_loader), self.batch_shardings,
                    size=cfg.prefetch,
                )
            )
        last_metrics = {}
        final_metrics = None
        self._pending.clear()
        timer = Timer()
        images_this_epoch = 0
        # profile a steady-state window (post-compile) of the first epoch,
        # shrunk to fit short (smoke) epochs
        profile_window = None
        if cfg.profile_dir and epoch == 0:
            n = self.train_loader.steps_per_epoch
            if cfg.max_steps_per_epoch:
                n = min(n, cfg.max_steps_per_epoch)
            start = min(10, max(0, n - 10))
            stop = min(start + 10, n)
            if stop > start:
                profile_window = (start, stop)
            else:
                warn0("profile_dir set but epoch has %d steps — skipping trace", n)
        profiling = False
        steps_done = 0
        try:
            for tag, batch in items:
                if cfg.max_steps_per_epoch and steps_done >= cfg.max_steps_per_epoch:
                    break
                if profiling and steps_done >= profile_window[1]:
                    jax.block_until_ready(self.state.params)
                    jax.profiler.stop_trace()
                    profiling = False
                    profile_window = None
                # start once anywhere past the window start (chunked runs
                # only visit multiples of k, which may skip the window)
                if profile_window and not profiling and (
                    steps_done >= profile_window[0]
                ):
                    jax.profiler.start_trace(cfg.profile_dir)
                    profiling = True
                with step_annotation(int(self.state.step)):
                    remaining = (
                        cfg.max_steps_per_epoch - steps_done
                        if cfg.max_steps_per_epoch else None
                    )
                    if tag == "chunk" and (remaining is None or remaining >= k):
                        self.state, metrics = self.chunk_step(self.state, batch)
                        inc = k
                    elif tag == "chunk":
                        # step cap mid-chunk: run the tail as single steps so
                        # the cap (and the resume-epoch math) stays exact
                        for j in range(remaining):
                            sub = jax.tree.map(lambda v: v[j], batch)
                            self.state, metrics = self.train_step(self.state, sub)
                        inc = remaining
                    else:
                        self.state, metrics = self.train_step(self.state, batch)
                        inc = 1
                if self._serialize_steps:
                    jax.block_until_ready(metrics)
                prev = steps_done
                steps_done += inc
                self._track(metrics["loss"])
                self._probe_if_due(prev, steps_done)
                if cfg.sync_check_every_steps and (
                    prev // cfg.sync_check_every_steps
                    != steps_done // cfg.sync_check_every_steps
                ):
                    from ddp_practice_tpu.train.elastic import assert_in_sync

                    # host-side counter, NOT device state: detects driver-loop
                    # drift (skewed data exhaustion, missed batches) — SURVEY §5.2
                    assert_in_sync(
                        epoch * self.train_loader.steps_per_epoch + steps_done,
                        what="driver step",
                    )
                images_this_epoch += self.global_batch * inc
                final_metrics = metrics
                if cfg.log_every_steps and (
                    prev // cfg.log_every_steps != steps_done // cfg.log_every_steps
                ):
                    last_metrics = jax.device_get(metrics)
                    if self._watchdog is not None:
                        self._watchdog.beat()  # the device_get confirmed progress
                    info0(
                        "epoch %d step %d loss %.4f acc %.3f",
                        epoch, steps_done,
                        float(last_metrics["loss"]),
                        float(last_metrics["accuracy"]),
                    )
            self._drain_pending()  # rung-by-rung: beats during the wait
            jax.block_until_ready(self.state.params)
            if final_metrics is not None:
                # a scalar readback is the only progress signal that fences
                # on every transport (block_until_ready may not —
                # BENCHMARKS.md), so epoch timing closes on it
                jax.device_get(final_metrics["loss"])
                if self._watchdog is not None:
                    self._watchdog.beat()
        finally:
            items.close()  # stop the prefetch producer thread promptly
            if profiling:  # short epoch or mid-window failure: close trace
                jax.profiler.stop_trace()
        dt = timer.elapsed()
        self._train_images += images_this_epoch
        self._train_seconds += dt
        return {"epoch_seconds": dt, "images": images_this_epoch}

    def evaluate(self) -> float:
        """Global exact accuracy; all processes participate in the reduction
        (the all-ranks-call-the-collective contract, ddp_main.py:164,108-109)."""
        it = prefetch_to_device(
            iter(self.eval_loader), self.batch_shardings, size=self.config.prefetch
        )
        correct = jnp.zeros((), jnp.float32)
        total = jnp.zeros((), jnp.float32)
        self._pending.clear()
        try:
            # trace annotation: eval separates from train on device timelines
            with profile_region("eval"):
                n_eval = 0
                for batch in it:
                    c, t = self.eval_step(self.state, batch)
                    if self._serialize_steps:
                        jax.block_until_ready(c)
                    correct = correct + c
                    total = total + t
                    n_eval += 1
                    self._track(c)
                    self._probe_if_due(n_eval - 1, n_eval)
        finally:
            it.close()  # stop the prefetch producer thread promptly
        self._drain_pending()  # rung-by-rung: beats during the wait
        acc = float(correct) / max(float(total), 1.0)  # readback = confirmed
        if self._watchdog is not None:
            self._watchdog.beat()
        return acc

    def save(self) -> None:
        if self._watchdog is not None:
            self._watchdog.beat()  # checkpoint IO is progress, not a hang
        if self.config.checkpoint_dir:
            ckpt.save(
                self.config.checkpoint_dir,
                self.state,
                extra={
                    "step": int(self.state.step),
                    "precision_policy": self.config.precision_policy().name,
                    "model": self.config.model,
                },
            )

    def fit(self) -> dict:
        cfg = self.config
        if cfg.watchdog_timeout_s:
            from ddp_practice_tpu.train.elastic import StepWatchdog

            self._watchdog = StepWatchdog(cfg.watchdog_timeout_s).start()
        try:
            return self._fit_inner()
        finally:
            if self._watchdog is not None:
                self._watchdog.stop()
                self._watchdog = None

    def _fit_inner(self) -> dict:
        cfg = self.config
        timer = Timer()
        accuracy: Optional[float] = None
        # after a checkpoint restore, continue from the epoch the restored
        # step count falls in — lost work is bounded by one checkpoint
        # interval, not replayed from epoch 0
        steps_per_epoch = self.train_loader.steps_per_epoch
        if cfg.max_steps_per_epoch:
            steps_per_epoch = min(steps_per_epoch, cfg.max_steps_per_epoch)
        start_epoch = min(int(self.state.step) // max(steps_per_epoch, 1),
                          cfg.epochs)
        if start_epoch:
            info0("resuming at epoch %d (step %d)",
                  start_epoch, int(self.state.step))
        for epoch in range(start_epoch, cfg.epochs):
            info0("=== epoch %d / %d ===", epoch + 1, cfg.epochs)
            self.train_epoch(epoch)
            if cfg.eval_every_epochs and (epoch + 1) % cfg.eval_every_epochs == 0:
                accuracy = self.evaluate()
                info0("Accuracy is %.2f%%", accuracy * 100.0)
            if cfg.checkpoint_every_epochs and (epoch + 1) % cfg.checkpoint_every_epochs == 0:
                self.save()
        if accuracy is None or not cfg.eval_every_epochs:
            accuracy = self.evaluate()
        self.save()
        elapsed = timer.elapsed()
        ips = self._train_images / max(self._train_seconds, 1e-9)
        summary = {
            "accuracy": accuracy,
            "elapsed_seconds": elapsed,
            "train_seconds": self._train_seconds,
            "images_per_sec": ips,
            "images_per_sec_per_chip": ips / jax.device_count(),
            "steps": int(self.state.step),
            "global_batch": self.global_batch,
            "devices": jax.device_count(),
        }
        # the reference's three parity-visible lines (SURVEY §5.5)
        info0("Accuracy is %.2f%%", accuracy * 100.0)
        info0("time elapsed: %.2fs", elapsed)
        info0("throughput: %.1f images/sec (%.1f /chip)",
              ips, ips / jax.device_count())
        return summary


def fit(config: TrainConfig) -> dict:
    """Train once, or with checkpoint-based elastic restarts when
    max_restarts > 0 (recovery is effective with a checkpoint_dir set)."""
    if config.max_restarts > 0:
        from ddp_practice_tpu.train.elastic import run_with_restarts

        return run_with_restarts(
            lambda resume: Trainer(
                config.replace(resume=config.resume or resume)
            ),
            max_restarts=config.max_restarts,
        )
    return Trainer(config).fit()
