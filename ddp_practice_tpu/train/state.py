"""Train state pytree and optimizer construction.

Replaces the reference's mutable module + torch.optim.SGD pair
(origin_main.py:85-87) with an immutable pytree threaded through jitted
steps. Parameter init is explicitly seeded with `jax.random.PRNGKey` —
the reference leaves init unseeded and relies on DDP's implicit rank-0
broadcast (SURVEY §2.5); JAX has no implicit broadcast, so determinism is
by construction: every process computes identical init from the same key.
"""

from __future__ import annotations

from typing import Any, Optional

import flax.struct
import jax
import jax.numpy as jnp
import optax

from ddp_practice_tpu.config import TrainConfig


@flax.struct.dataclass
class TrainState:
    step: jnp.ndarray
    params: Any
    batch_stats: Any          # None for models without BatchNorm
    opt_state: Any


def make_optimizer(config: TrainConfig, steps_per_epoch: int = 0) -> optax.GradientTransformation:
    """SGD lr 1e-4 by default — parity with ddp_main.py:125, including the
    deliberate choice NOT to scale lr with replica count (README.md:506)
    unless `scale_lr_by_replicas` is set."""
    lr = config.learning_rate
    if config.scale_lr_by_replicas:
        lr = lr * jax.device_count()
    # under gradient accumulation the schedule count advances once per
    # optimizer APPLY (every accum_steps micro-steps, optax.MultiSteps),
    # so decay/warmup horizons are in applies, not micro-steps — without
    # this division a cosine schedule would finish only 1/k of its decay
    accum = max(config.accum_steps, 1)
    total_steps = max(steps_per_epoch * config.epochs // accum, 1)
    warmup = config.warmup_steps // accum
    if config.lr_schedule == "constant":
        schedule = optax.constant_schedule(lr)
    elif config.lr_schedule == "cosine":
        schedule = optax.cosine_decay_schedule(lr, total_steps)
    elif config.lr_schedule == "warmup_cosine":
        schedule = optax.warmup_cosine_decay_schedule(
            0.0, lr, warmup, total_steps
        )
    else:
        raise ValueError(f"unknown lr_schedule {config.lr_schedule!r}")

    if config.optimizer == "sgd":
        tx = optax.sgd(schedule, momentum=config.momentum or None)
    elif config.optimizer == "adamw":
        tx = optax.adamw(schedule, weight_decay=config.weight_decay)
    elif config.optimizer == "adam":
        tx = optax.adam(schedule)
    else:
        raise ValueError(f"unknown optimizer {config.optimizer!r}")
    if config.weight_decay and config.optimizer == "sgd":
        tx = optax.chain(optax.add_decayed_weights(config.weight_decay), tx)
    if config.clip_norm:
        # clip FIRST (on the raw global grad norm), then the optimizer —
        # the standard transformer-training order
        tx = optax.chain(optax.clip_by_global_norm(config.clip_norm), tx)
    if config.accum_steps > 1:
        # gradient accumulation: average grads over k micro-steps, apply
        # the inner optimizer on the k-th (optax.MultiSteps). Because it
        # wraps the GradientTransformation, every driver path — per-step,
        # chunked scan, device-resident — gets it for free; state.step
        # counts micro-steps. For losses that are per-batch means (ours),
        # k micro-batches of size b equal one batch of size k*b exactly
        # for SGD (tests/test_train.py pins this).
        tx = optax.MultiSteps(tx, every_k_schedule=config.accum_steps)
    return tx


def create_state(
    model,
    tx,
    *,
    rng: jax.Array,
    sample_input: jnp.ndarray,
) -> TrainState:
    """Initialize params (explicit PRNG key) and optimizer state."""
    variables = model.init(rng, sample_input, train=False)
    params = variables["params"]
    batch_stats = variables.get("batch_stats", None)
    return TrainState(
        step=jnp.zeros((), jnp.int32),
        params=params,
        batch_stats=batch_stats,
        opt_state=tx.init(params),
    )
