"""Dataset provisioning: fetch-or-verify real MNIST/CIFAR into --data_dir.

The reference gets data through torchvision's `download=True`
(/root/reference/origin_main.py:88-90). This is that contract's
counterpart: one command that (optionally) downloads the canonical
archives, VERIFIES them against the published MD5 checksums, and lays
them out exactly where `data/datasets.py`'s loaders look — after which
the documented parity run (`python -m ddp_practice_tpu.cli -e 3 -b 32
--dataset mnist --data_dir DATA`) trains on real pixels and reproduces
the reference's 91.55%-in-3-epochs contract (PARITY.md "with real
files").

    python -m ddp_practice_tpu.data.ingest --dataset mnist --out ./data
    python -m ddp_practice_tpu.data.ingest --dataset mnist \
        --src ~/torch_data --out ./data          # ingest existing files
    python -m ddp_practice_tpu.data.ingest --dataset cifar10 --out ./data

--src accepts every common layout: the four IDX files flat or under
MNIST/raw/ (the torchvision tree), raw or .gz; CIFAR as the
cifar-10-batches-py directory or the cifar-10-python.tar.gz archive.
Nothing lands in --out before passing verification (downloads go to a
.part file; a bad mirror or truncated archive is removed and the next
mirror tried — a corrupt file must never be discoverable by the
loaders); pass --no-verify only for self-made fixtures like
tests/data/mini_mnist.
"""

from __future__ import annotations

import argparse
import hashlib
import os
import pickle
import shutil
import sys
import tarfile
from typing import Optional

from ddp_practice_tpu.data.datasets import idx_dims

# canonical archives: (filename -> md5, n_items) — the MD5s published
# with the original distributions (yann.lecun.com/exdb/mnist mirrors;
# cs.toronto.edu/~kriz/cifar.html)
_MNIST_GZ = {
    "train-images-idx3-ubyte.gz": ("f68b3c2dcbeaaa9fbdd348bbdeb94873", 60000),
    "train-labels-idx1-ubyte.gz": ("d53e105ee54ea40749a09fcbcd1e9432", 60000),
    "t10k-images-idx3-ubyte.gz": ("9fb629c4189551a2d022fa330f9573f3", 10000),
    "t10k-labels-idx1-ubyte.gz": ("ec29112dd5afa0611ce80d1b7f02629c", 10000),
}
_MNIST_URLS = [
    "https://ossci-datasets.s3.amazonaws.com/mnist/",  # torchvision mirror
    "http://yann.lecun.com/exdb/mnist/",
]
_CIFAR_TGZ = ("cifar-10-python.tar.gz", "c58f30108f718f92721af3b95e74349a")
_CIFAR_URL = "https://www.cs.toronto.edu/~kriz/cifar-10-python.tar.gz"


def _md5(path: str) -> str:
    h = hashlib.md5()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def _find_source(src: str, name: str) -> Optional[str]:
    """Locate `name` under every layout the torchvision ecosystem
    produces. IDX files may also exist as their uncompressed twin
    (torchvision extracts them); archives like the CIFAR tar.gz are
    matched by their exact name only."""
    stems = [name]
    if name.endswith(".gz") and "ubyte" in name:
        stems.append(name[:-3])
    for stem in stems:
        for sub in ("", "MNIST/raw", "raw"):
            p = os.path.join(src, sub, stem)
            if os.path.exists(p):
                return p
    return None


def _fetch_verified(urls, dest: str, md5: Optional[str]) -> bool:
    """Download to dest via a .part file, verifying BEFORE the move —
    a corrupt mirror response (or an HTML error served as 200) is
    deleted and the next mirror tried, and nothing unverified ever
    sits at a loader-discoverable path."""
    import urllib.request

    part = dest + ".part"
    for url in urls:
        try:
            print(f"[ingest] fetching {url}")
            with urllib.request.urlopen(url, timeout=60) as r, open(
                part, "wb"
            ) as f:
                shutil.copyfileobj(r, f)
        except Exception as e:  # noqa: BLE001 — any failure: next mirror
            print(f"[ingest] fetch failed ({e})")
            if os.path.exists(part):
                os.remove(part)
            continue
        if md5 is not None:
            got = _md5(part)
            if got != md5:
                print(f"[ingest] {url}: checksum mismatch ({got}), "
                      "discarding and trying the next mirror")
                os.remove(part)
                continue
        os.replace(part, dest)
        return True
    return False


def ingest_mnist(src: Optional[str], out: str, *, verify: bool = True,
                 fetch: bool = False) -> int:
    os.makedirs(out, exist_ok=True)
    placed = 0
    for name, (md5, count) in _MNIST_GZ.items():
        dest = os.path.join(out, name)
        # an already-ingested verified copy short-circuits the fetch
        if os.path.exists(dest) and (not verify or _md5(dest) == md5):
            print(f"[ingest] {dest} already present"
                  + (" (verified)" if verify else ""))
            placed += 1
            continue
        found = _find_source(src, name) if src else None
        if found is None and fetch:
            if _fetch_verified(
                [base + name for base in _MNIST_URLS], dest,
                md5 if verify else None,
            ):
                found = dest
        if found is None:
            print(f"[ingest] MISSING {name} (searched "
                  f"{src or '(no --src)'}; fetch={'on' if fetch else 'off'})")
            continue
        if verify:
            if found != dest and found.endswith(".gz"):
                got = _md5(found)
                if got != md5:
                    raise SystemExit(
                        f"[ingest] checksum mismatch for {found}: got {got}, "
                        f"want {md5} — refusing to place a corrupt/unknown "
                        "file (use --no-verify only for self-made fixtures)"
                    )
            n = idx_dims(found)[0]
            if n != count:
                raise SystemExit(
                    f"[ingest] {found}: {n} items, expected {count}"
                )
        final = os.path.join(out, os.path.basename(found))
        if os.path.abspath(found) != os.path.abspath(final):
            shutil.copyfile(found, final)
        print(f"[ingest] placed {final}"
              + (" (verified)" if verify else " (UNVERIFIED)"))
        placed += 1
    if placed == 4:
        print(f"[ingest] MNIST ready in {out} — run: "
              f"python -m ddp_practice_tpu.cli -e 3 -b 32 "
              f"--dataset mnist --data_dir {out}  (expect >= 91%)")
        return 0
    return 1


def _check_cifar_tree(base: str) -> None:
    """Structural verification of an extracted cifar-10-batches-py tree:
    every batch unpickles to (N, 3072) rows with N matching labels. (The
    per-file MD5s aren't published for the extracted form; structure is
    what we can honestly check — and what keeps miniature fixtures
    ingestable.)"""
    import numpy as np

    names = [f"data_batch_{i}" for i in range(1, 6)] + ["test_batch"]
    for fn in names:
        p = os.path.join(base, fn)
        if not os.path.exists(p):
            raise SystemExit(f"[ingest] {base}: missing {fn}")
        with open(p, "rb") as f:
            d = pickle.load(f, encoding="bytes")
        data = np.asarray(d[b"data"])
        if data.ndim != 2 or data.shape[1] != 3072 or len(d[b"labels"]) != (
            data.shape[0]
        ):
            raise SystemExit(
                f"[ingest] {p}: not a CIFAR batch "
                f"(shape {data.shape}, {len(d[b'labels'])} labels)"
            )


def ingest_cifar10(src: Optional[str], out: str, *, verify: bool = True,
                   fetch: bool = False) -> int:
    os.makedirs(out, exist_ok=True)
    batches = os.path.join(out, "cifar-10-batches-py")
    # already-extracted tree offered directly
    if src:
        tree = (
            src if os.path.basename(src) == "cifar-10-batches-py"
            else os.path.join(src, "cifar-10-batches-py")
        )
        if os.path.isdir(tree):
            if verify:
                _check_cifar_tree(tree)
            if os.path.abspath(tree) != os.path.abspath(batches):
                shutil.copytree(tree, batches, dirs_exist_ok=True)
            print(f"[ingest] placed {batches}"
                  + (" (structurally verified)" if verify
                     else " (UNVERIFIED)"))
            return 0
    name, md5 = _CIFAR_TGZ
    archive = _find_source(src, name) if src else None
    if archive is None and fetch:
        dest = os.path.join(out, name)
        if _fetch_verified([_CIFAR_URL], dest, md5 if verify else None):
            archive = dest
    if archive is None:
        print(f"[ingest] MISSING {name} (searched {src or '(no --src)'}; "
              f"fetch={'on' if fetch else 'off'})")
        return 1
    if verify and archive != os.path.join(out, name):
        got = _md5(archive)
        if got != md5:
            raise SystemExit(
                f"[ingest] checksum mismatch for {archive}: got {got}, "
                f"want {md5}"
            )
    with tarfile.open(archive, "r:gz") as t:
        t.extractall(out, filter="data")
    print(f"[ingest] extracted {batches}"
          + (" (verified)" if verify else " (UNVERIFIED)"))
    print(f"[ingest] CIFAR-10 ready — run: python -m ddp_practice_tpu.cli "
          f"--model vit_tiny --dataset cifar10 --data_dir {out} "
          f"--optimizer adamw --lr 1e-3 --precision bf16")
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser("ddp_practice_tpu.data.ingest")
    p.add_argument("--dataset", required=True, choices=["mnist", "cifar10"])
    p.add_argument("--out", default="./data",
                   help="target --data_dir for training runs")
    p.add_argument("--src", default=None,
                   help="directory holding already-downloaded files "
                        "(torchvision MNIST/raw trees, IDX files, CIFAR "
                        "tar.gz or batches directory)")
    p.add_argument("--fetch", action="store_true",
                   help="attempt to download the canonical archives first "
                        "(the reference's download=True; degrades to "
                        "--src ingestion without network egress)")
    p.add_argument("--no-verify", dest="verify", action="store_false",
                   help="skip checksum/count verification (self-made "
                        "fixtures only)")
    a = p.parse_args(argv)
    fn = ingest_mnist if a.dataset == "mnist" else ingest_cifar10
    return fn(a.src, a.out, verify=a.verify, fetch=a.fetch)


if __name__ == "__main__":
    sys.exit(main())
