"""Array-backed datasets.

The reference consumes `torchvision.datasets.MNIST` with a bare `ToTensor()`
transform — pixel values scaled to [0, 1], NO mean/std normalization
(origin_main.py:88-90, SURVEY §1 L2). We reproduce that contract from raw IDX
files when present, and fall back to a deterministic procedurally generated
dataset of the same shape when the real files are unavailable (this build
environment has no network egress; `download=True` is not an option).
"""

from __future__ import annotations

import dataclasses
import gzip
import os
import struct
from typing import Optional, Tuple

import numpy as np

# Canonical MNIST IDX file names (either raw or .gz).
_MNIST_FILES = {
    "train": ("train-images-idx3-ubyte", "train-labels-idx1-ubyte"),
    "test": ("t10k-images-idx3-ubyte", "t10k-labels-idx1-ubyte"),
}


@dataclasses.dataclass
class Dataset:
    """An in-memory dataset: images in [0,1] float32 NHWC, integer labels."""

    images: np.ndarray  # (N, H, W, C) float32 in [0, 1]
    labels: np.ndarray  # (N,) int32
    num_classes: int
    name: str = "dataset"

    def __post_init__(self):
        assert self.images.ndim == 4, self.images.shape
        assert len(self.images) == len(self.labels)

    def __len__(self) -> int:
        return len(self.images)

    @property
    def image_shape(self) -> Tuple[int, int, int]:
        return tuple(self.images.shape[1:])


def _read_idx(path: str) -> np.ndarray:
    """Parse an IDX-format file (the MNIST on-disk format)."""
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        magic = struct.unpack(">I", f.read(4))[0]
        dtype_code = (magic >> 8) & 0xFF
        ndim = magic & 0xFF
        if dtype_code != 0x08:  # unsigned byte — the only type MNIST uses
            raise ValueError(f"unsupported IDX dtype 0x{dtype_code:02x} in {path}")
        dims = struct.unpack(f">{ndim}I", f.read(4 * ndim))
        data = np.frombuffer(f.read(), dtype=np.uint8)
        return data.reshape(dims)


def _find_idx(data_dir: str, base: str) -> Optional[str]:
    for cand in (base, base + ".gz", base.replace("-idx", ".idx"),
                 base.replace("-idx", ".idx") + ".gz"):
        p = os.path.join(data_dir, cand)
        if os.path.exists(p):
            return p
        # torchvision layout: data/MNIST/raw/<file>
        p = os.path.join(data_dir, "MNIST", "raw", cand)
        if os.path.exists(p):
            return p
    return None


def load_mnist(data_dir: str, split: str) -> Optional[Dataset]:
    """Load real MNIST from IDX files if present, else None."""
    img_base, lbl_base = _MNIST_FILES[split]
    img_path = _find_idx(data_dir, img_base)
    lbl_path = _find_idx(data_dir, lbl_base)
    if img_path is None or lbl_path is None:
        return None
    images = _read_idx(img_path).astype(np.float32) / 255.0
    labels = _read_idx(lbl_path).astype(np.int32)
    images = images[..., None]  # NHWC, C=1
    return Dataset(images=images, labels=labels, num_classes=10, name=f"mnist-{split}")


def synthetic_image_classification(
    *,
    n: int,
    image_shape: Tuple[int, int, int],
    num_classes: int,
    seed: int,
    split_seed: int = 0,
    noise: float = 0.35,
    name: str = "synthetic",
) -> Dataset:
    """Deterministic, learnable synthetic classification dataset.

    Each class c has a fixed random template T_c; a sample is
    clip(T_c + noise * N(0,1), 0, 1). The templates depend only on `seed`
    (shared across train/test so the task is learnable); `split_seed`
    decorrelates the samples between splits. Linearly separable enough that
    the parity models reach high accuracy in a few epochs, so the
    reference's behavioral contract ("accuracy rises past 91% in 3 epochs",
    origin_main.py / README) remains testable without network access.
    """
    h, w, c = image_shape
    template_rng = np.random.default_rng(np.random.SeedSequence([seed, 0xDA7A]))
    templates = template_rng.uniform(0.0, 1.0, size=(num_classes, h, w, c)).astype(
        np.float32
    )
    rng = np.random.default_rng(np.random.SeedSequence([seed, split_seed]))
    labels = rng.integers(0, num_classes, size=n).astype(np.int32)
    images = templates[labels] + noise * rng.standard_normal(
        (n, h, w, c), dtype=np.float32
    )
    images = np.clip(images, 0.0, 1.0)
    return Dataset(images=images, labels=labels, num_classes=num_classes, name=name)


def load_dataset(
    name: str,
    data_dir: str,
    split: str,
    *,
    seed: int = 0,
    synthetic_size: Optional[int] = None,
) -> Dataset:
    """Dataset registry.

    ``mnist`` / ``cifar10`` load real files when available and otherwise fall
    back to a shape-compatible synthetic dataset (and say so via the name).
    ``synthetic*`` is always procedural.
    """
    name = name.lower()
    if name == "mnist":
        ds = load_mnist(data_dir, split)
        if ds is not None:
            return ds
        n = synthetic_size or (60000 if split == "train" else 10000)
        return synthetic_image_classification(
            n=n, image_shape=(28, 28, 1), num_classes=10,
            seed=seed, split_seed=(0 if split == "train" else 1),
            name=f"mnist-synthetic-{split}",
        )
    if name == "cifar10":
        ds = _load_cifar10(data_dir, split)
        if ds is not None:
            return ds
        n = synthetic_size or (50000 if split == "train" else 10000)
        return synthetic_image_classification(
            n=n, image_shape=(32, 32, 3), num_classes=10,
            seed=seed, split_seed=(0 if split == "train" else 1),
            name=f"cifar10-synthetic-{split}",
        )
    if name.startswith("synthetic"):
        n = synthetic_size or (4096 if split == "train" else 1024)
        return synthetic_image_classification(
            n=n, image_shape=(28, 28, 1), num_classes=10,
            seed=seed, split_seed=(0 if split == "train" else 1),
            name=f"{name}-{split}",
        )
    raise ValueError(f"unknown dataset {name!r}")


def _load_cifar10(data_dir: str, split: str) -> Optional[Dataset]:
    """Load CIFAR-10 from the standard python-pickle batches if present."""
    import pickle

    base = os.path.join(data_dir, "cifar-10-batches-py")
    if not os.path.isdir(base):
        return None
    files = (
        [f"data_batch_{i}" for i in range(1, 6)] if split == "train" else ["test_batch"]
    )
    imgs, lbls = [], []
    for fn in files:
        p = os.path.join(base, fn)
        if not os.path.exists(p):
            return None
        with open(p, "rb") as f:
            d = pickle.load(f, encoding="bytes")
        imgs.append(d[b"data"])
        lbls.extend(d[b"labels"])
    images = (
        np.concatenate(imgs).reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
        .astype(np.float32) / 255.0
    )
    labels = np.asarray(lbls, dtype=np.int32)
    return Dataset(images=images, labels=labels, num_classes=10, name=f"cifar10-{split}")
