"""Array-backed datasets.

The reference consumes `torchvision.datasets.MNIST` with a bare `ToTensor()`
transform — pixel values scaled to [0, 1], NO mean/std normalization
(origin_main.py:88-90, SURVEY §1 L2). We reproduce that contract from raw IDX
files when present, and fall back to a deterministic procedurally generated
dataset of the same shape when the real files are unavailable (this build
environment has no network egress; `download=True` is not an option).
"""

from __future__ import annotations

import dataclasses
import gzip
import os
import struct
from typing import Optional, Tuple

import numpy as np

# Canonical MNIST IDX file names (either raw or .gz).
_MNIST_FILES = {
    "train": ("train-images-idx3-ubyte", "train-labels-idx1-ubyte"),
    "test": ("t10k-images-idx3-ubyte", "t10k-labels-idx1-ubyte"),
}


@dataclasses.dataclass
class Dataset:
    """An array-backed dataset: NHWC images, integer labels.

    Two storage contracts, distinguished by dtype:

    - ``float32`` in [0, 1] — the reference's post-`ToTensor()` layout
      (origin_main.py:89); fine for MNIST/CIFAR-sized data held in RAM.
    - ``uint8`` in [0, 255] — raw pixels, 4x smaller in RAM *and* over
      H2D; normalization to [0,1] happens on device inside the jitted
      step (train/steps.py), where XLA fuses it into the first conv.
      ``images`` may be an ``np.memmap`` so ImageNet-scale corpora
      stream from disk through the OS page cache instead of
      materializing in host memory.
    """

    images: np.ndarray  # (N, H, W, C) float32 in [0,1] or uint8 in [0,255]
    labels: np.ndarray  # (N,) int32
    num_classes: int
    name: str = "dataset"

    def __post_init__(self):
        assert self.images.ndim == 4, self.images.shape
        assert self.images.dtype in (np.float32, np.uint8), self.images.dtype
        assert len(self.images) == len(self.labels)

    def __len__(self) -> int:
        return len(self.images)

    @property
    def image_shape(self) -> Tuple[int, int, int]:
        return tuple(self.images.shape[1:])


def read_idx_header(f, path: str = "<stream>"):
    """Parse an IDX header from an open binary stream -> dims tuple.

    The ONE definition of the header format, shared by the loader below
    and the ingest tool's structural verification (data/ingest.py)."""
    magic = struct.unpack(">I", f.read(4))[0]
    dtype_code = (magic >> 8) & 0xFF
    ndim = magic & 0xFF
    if dtype_code != 0x08:  # unsigned byte — the only type MNIST uses
        raise ValueError(f"unsupported IDX dtype 0x{dtype_code:02x} in {path}")
    return struct.unpack(f">{ndim}I", f.read(4 * ndim))


def idx_dims(path: str):
    """Dims tuple of an IDX file (raw or .gz) without reading the data."""
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        return read_idx_header(f, path)


def _read_idx(path: str) -> np.ndarray:
    """Parse an IDX-format file (the MNIST on-disk format)."""
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        dims = read_idx_header(f, path)
        data = np.frombuffer(f.read(), dtype=np.uint8)
        return data.reshape(dims)


def _find_idx(data_dir: str, base: str) -> Optional[str]:
    for cand in (base, base + ".gz", base.replace("-idx", ".idx"),
                 base.replace("-idx", ".idx") + ".gz"):
        p = os.path.join(data_dir, cand)
        if os.path.exists(p):
            return p
        # torchvision layout: data/MNIST/raw/<file>
        p = os.path.join(data_dir, "MNIST", "raw", cand)
        if os.path.exists(p):
            return p
    return None


def load_mnist(data_dir: str, split: str) -> Optional[Dataset]:
    """Load real MNIST from IDX files if present, else None."""
    img_base, lbl_base = _MNIST_FILES[split]
    img_path = _find_idx(data_dir, img_base)
    lbl_path = _find_idx(data_dir, lbl_base)
    if img_path is None or lbl_path is None:
        return None
    # keep raw uint8: 4x less RAM and H2D traffic; the /255 happens on
    # device (train/steps.py prepare_image) — bit-identical to host ToTensor
    images = _read_idx(img_path)[..., None]  # NHWC, C=1
    labels = _read_idx(lbl_path).astype(np.int32)
    return Dataset(images=images, labels=labels, num_classes=10, name=f"mnist-{split}")


def synthetic_image_classification(
    *,
    n: int,
    image_shape: Tuple[int, int, int],
    num_classes: int,
    seed: int,
    split_seed: int = 0,
    noise: float = 0.35,
    name: str = "synthetic",
) -> Dataset:
    """Deterministic, learnable synthetic classification dataset.

    Each class c has a fixed random template T_c; a sample is
    clip(T_c + noise * N(0,1), 0, 1). The templates depend only on `seed`
    (shared across train/test so the task is learnable); `split_seed`
    decorrelates the samples between splits. Linearly separable enough that
    the parity models reach high accuracy in a few epochs, so the
    reference's behavioral contract ("accuracy rises past 91% in 3 epochs",
    origin_main.py / README) remains testable without network access.

    Stored as uint8 (like the real datasets it stands in for): 4x less RAM
    and H2D traffic; [0,1] scaling happens on device (prepare_image).
    """
    h, w, c = image_shape
    template_rng = np.random.default_rng(np.random.SeedSequence([seed, 0xDA7A]))
    templates = template_rng.uniform(0.0, 1.0, size=(num_classes, h, w, c)).astype(
        np.float32
    )
    rng = np.random.default_rng(np.random.SeedSequence([seed, split_seed]))
    labels = rng.integers(0, num_classes, size=n).astype(np.int32)
    images = templates[labels] + noise * rng.standard_normal(
        (n, h, w, c), dtype=np.float32
    )
    images = np.clip(images * 255.0, 0.0, 255.0).astype(np.uint8)
    return Dataset(images=images, labels=labels, num_classes=num_classes, name=name)


def load_dataset(
    name: str,
    data_dir: str,
    split: str,
    *,
    seed: int = 0,
    synthetic_size: Optional[int] = None,
) -> Dataset:
    """Dataset registry.

    ``mnist`` / ``cifar10`` load real files when available and otherwise fall
    back to a shape-compatible synthetic dataset (and say so via the name).
    ``synthetic*`` is always procedural.
    """
    name = name.lower()
    if name == "mnist":
        ds = load_mnist(data_dir, split)
        if ds is not None:
            return ds
        n = synthetic_size or (60000 if split == "train" else 10000)
        return synthetic_image_classification(
            n=n, image_shape=(28, 28, 1), num_classes=10,
            seed=seed, split_seed=(0 if split == "train" else 1),
            name=f"mnist-synthetic-{split}",
        )
    if name == "cifar10":
        ds = _load_cifar10(data_dir, split)
        if ds is not None:
            return ds
        n = synthetic_size or (50000 if split == "train" else 10000)
        return synthetic_image_classification(
            n=n, image_shape=(32, 32, 3), num_classes=10,
            seed=seed, split_seed=(0 if split == "train" else 1),
            name=f"cifar10-synthetic-{split}",
        )
    if name == "imagenet":
        real = os.path.join(data_dir, "imagenet-arrays")
        # accept the conventional 'val' name for the held-out split
        candidates = (split, "val") if split == "test" else (split,)
        for cand in candidates:
            if _array_dataset_exists(real, cand):
                return load_array_dataset(real, cand)
        if os.path.isdir(real):
            # a real corpus exists but not this split: refuse rather than
            # silently mixing real training with synthetic-noise eval
            raise FileNotFoundError(
                f"{real} exists but has no complete "
                f"{' or '.join(candidates)!s} split; expected "
                f"<split>-images.npy + <split>-labels.npy + meta.json"
            )
        n = synthetic_size or (16384 if split == "train" else 2048)
        root = os.path.join(data_dir, f"imagenet-synthetic-{n}-s{seed}")
        return synthetic_imagenet_corpus(root, split, n=n, seed=seed)
    if name.startswith("synthetic"):
        n = synthetic_size or (4096 if split == "train" else 1024)
        return synthetic_image_classification(
            n=n, image_shape=(28, 28, 1), num_classes=10,
            seed=seed, split_seed=(0 if split == "train" else 1),
            name=f"{name}-{split}",
        )
    raise ValueError(f"unknown dataset {name!r}")


# --------------------------------------------------------------------- #
# Array-record corpus: the ImageNet-scale storage format.
#
# A corpus directory holds `{split}-images.npy` (uint8, N x H x W x C) and
# `{split}-labels.npy` (int32, N) plus `meta.json`. `.npy` because
# `np.load(mmap_mode="r")` memory-maps it directly: batch gather touches
# only the pages it indexes, so a ~150 GB ImageNet-sized corpus streams
# through the OS page cache — nothing is ever materialized as fp32 in RAM
# (the reference leans on torchvision + DataLoader workers for this role,
# origin_main.py:88-107). Writes are chunked through a writer memmap and
# finished with os.replace, so a crashed writer never leaves a readable
# but torn corpus behind.
# --------------------------------------------------------------------- #


_STALE_TMP_AGE_S = 3600.0


def _sweep_stale_tmps(root: str) -> None:
    """Remove tmp files abandoned by crashed writers (a killed worker's
    finally never runs, and its full-size memmap would otherwise sit on
    the data disk forever). Age-gated so live concurrent writers — which
    use pid-unique names and touch their files continuously — are never
    swept."""
    import time

    now = time.time()
    for name in os.listdir(root):
        if ".tmp." not in name:
            continue
        p = os.path.join(root, name)
        try:
            if now - os.path.getmtime(p) > _STALE_TMP_AGE_S:
                os.remove(p)
        except OSError:
            pass


def _array_dataset_exists(root: str, split: str) -> bool:
    """A split is complete only when its files exist AND meta.json lists
    it: the writer drops the split's meta entry before rewriting the data
    files and restores it after both are in place, so a crash between the
    two file replaces leaves an incomplete-marked corpus, never a readable
    images/labels pair from different generations."""
    import json

    if not all(
        os.path.exists(os.path.join(root, f))
        for f in (f"{split}-images.npy", f"{split}-labels.npy", "meta.json")
    ):
        return False
    try:
        with open(os.path.join(root, "meta.json")) as f:
            meta = json.load(f)
    except (OSError, ValueError):
        return False
    return split in meta.get("splits", {})


class _MetaLock:
    """Best-effort advisory lock serializing meta.json read-modify-write
    (concurrent writers of *different* splits would otherwise drop each
    other's entry). flock is per-host-reliable and works on NFSv4; where
    it is a no-op the split-completeness protocol still bounds the damage
    to a spurious regeneration, never corruption."""

    def __init__(self, root: str):
        self._path = os.path.join(root, ".meta.lock")
        self._f = None

    def __enter__(self):
        self._f = open(self._path, "a+")
        try:
            import fcntl

            fcntl.flock(self._f, fcntl.LOCK_EX)
        except (ImportError, OSError):
            pass
        return self

    def __exit__(self, *exc):
        try:
            self._f.close()  # releases the flock
        except OSError:
            pass
        return False


def write_array_dataset(
    root: str,
    split: str,
    chunks,
    *,
    n: int,
    image_shape: Tuple[int, int, int],
    num_classes: int,
    name: str = "array",
    extra_meta: Optional[dict] = None,
) -> None:
    """Stream `chunks` of (uint8 images, labels) into an array-record corpus.

    Peak host memory is one chunk regardless of `n`: chunks are copied
    straight into a writer memmap. Files appear under their final names
    only when complete (tmp + os.replace), `meta.json` last. Tmp names are
    pid-unique, so concurrent writers (e.g. the per-host processes of a
    multi-host run racing to generate the same synthetic corpus) never
    truncate each other's mapping; deterministic generators make the
    last-rename-wins outcome byte-identical.
    """
    import json

    import uuid

    os.makedirs(root, exist_ok=True)
    _sweep_stale_tmps(root)
    # host-unique suffix: PIDs collide across hosts on a shared filesystem
    tag = f"{os.getpid()}.{uuid.uuid4().hex[:8]}"
    img_tmp = os.path.join(root, f".{split}-images.npy.tmp.{tag}")
    lbl_tmp = os.path.join(root, f".{split}-labels.npy.tmp.{tag}")
    done = False
    try:
        images = np.lib.format.open_memmap(
            img_tmp, mode="w+", dtype=np.uint8, shape=(n,) + tuple(image_shape)
        )
        labels = np.lib.format.open_memmap(
            lbl_tmp, mode="w+", dtype=np.int32, shape=(n,)
        )
        written = 0
        for img_chunk, lbl_chunk in chunks:
            img_chunk = np.asarray(img_chunk)
            lbl_chunk = np.asarray(lbl_chunk, dtype=np.int32)
            if img_chunk.dtype != np.uint8:
                raise ValueError(f"chunk dtype {img_chunk.dtype}, expected uint8")
            k = len(img_chunk)
            # exact-shape checks: numpy assignment would happily broadcast a
            # mis-shaped chunk into a silently corrupted corpus
            if img_chunk.shape[1:] != tuple(image_shape):
                raise ValueError(
                    f"chunk image shape {img_chunk.shape[1:]}, "
                    f"expected {tuple(image_shape)}"
                )
            if lbl_chunk.shape != (k,):
                raise ValueError(
                    f"chunk labels shape {lbl_chunk.shape}, expected ({k},)"
                )
            if written + k > n:
                raise ValueError(f"chunks exceed declared n={n}")
            images[written : written + k] = img_chunk
            labels[written : written + k] = lbl_chunk
            written += k
            # mmap writes do not update mtime; touch so a concurrent
            # writer's stale-tmp sweep never reaps a live slow writer
            os.utime(img_tmp)
            os.utime(lbl_tmp)
        if written != n:
            raise ValueError(f"chunks provided {written} samples, declared n={n}")
        images.flush()
        labels.flush()
        del images, labels  # close the writer maps before rename
        # mark the split incomplete across the two-file swap: a crash
        # between the replaces must not leave new images readable against
        # old labels (see _array_dataset_exists)
        _update_meta(root, tag, num_classes, name, split, None)
        os.replace(img_tmp, os.path.join(root, f"{split}-images.npy"))
        os.replace(lbl_tmp, os.path.join(root, f"{split}-labels.npy"))
        _update_meta(root, tag, num_classes, name, split, {
            "n": n, "image_shape": list(image_shape),
            **({"gen": extra_meta} if extra_meta else {}),
        })
        done = True
    finally:
        if not done:  # a failed writer must not strand a full-size tmp
            for p in (img_tmp, lbl_tmp):
                try:
                    os.remove(p)
                except OSError:
                    pass


def _update_meta(root, tag, num_classes, name, split, entry) -> None:
    """Atomically merge one split entry into meta.json under the advisory
    lock (entry=None removes the split, marking it incomplete)."""
    import json

    meta_path = os.path.join(root, "meta.json")
    with _MetaLock(root):
        meta = {"num_classes": num_classes, "name": name, "splits": {}}
        if os.path.exists(meta_path):
            try:
                with open(meta_path) as f:
                    meta = json.load(f)
            except (OSError, ValueError):
                pass  # rebuild a fresh meta over the corrupt one
        meta["num_classes"] = num_classes
        meta["name"] = name
        splits = meta.setdefault("splits", {})
        if entry is None:
            splits.pop(split, None)
        else:
            splits[split] = entry
        tmp = f"{meta_path}.tmp.{tag}"
        with open(tmp, "w") as f:
            json.dump(meta, f, indent=1)
        os.replace(tmp, meta_path)


def load_array_dataset(root: str, split: str, *, mmap: bool = True) -> Dataset:
    """Open an array-record corpus split; `mmap=True` (default) streams
    pixels from disk on access instead of loading them into RAM."""
    import json

    with open(os.path.join(root, "meta.json")) as f:
        meta = json.load(f)
    mode = "r" if mmap else None
    images = np.load(os.path.join(root, f"{split}-images.npy"), mmap_mode=mode)
    labels = np.asarray(
        np.load(os.path.join(root, f"{split}-labels.npy")), dtype=np.int32
    )
    return Dataset(
        images=images,
        labels=labels,
        num_classes=int(meta["num_classes"]),
        name=f"{meta.get('name', 'array')}-{split}",
    )


def synthetic_imagenet_corpus(
    root: str,
    split: str,
    *,
    n: int,
    image_shape: Tuple[int, int, int] = (224, 224, 3),
    num_classes: int = 1000,
    seed: int = 3407,
    noise: float = 0.35,
    chunk_size: int = 256,
) -> Dataset:
    """ImageNet-shaped synthetic corpus, generated to disk once and
    memory-mapped thereafter.

    Same template+noise construction as `synthetic_image_classification`
    (learnable, deterministic in (seed, split)) but streamed: class
    templates live at 1/16 resolution and are upsampled per chunk, so
    generation and loading both run in O(chunk) host memory — the property
    the fp32 in-RAM path fundamentally lacks at this scale.
    """
    gen_params = {
        "seed": seed, "noise": noise, "num_classes": num_classes,
        "split": split,
    }
    if _array_dataset_exists(root, split):
        import json

        with open(os.path.join(root, "meta.json")) as f:
            meta = json.load(f)
        cached = meta.get("splits", {}).get(split, {})
        # cache hit only when every generation parameter matches — a corpus
        # from a different seed/noise/class-count must not be silently reused
        if (
            cached.get("n") == n
            and tuple(cached.get("image_shape", ())) == tuple(image_shape)
            and cached.get("gen") == gen_params
        ):
            return load_array_dataset(root, split)
    h, w, c = image_shape
    th, tw = max(1, h // 16), max(1, w // 16)
    template_rng = np.random.default_rng(np.random.SeedSequence([seed, 0xDA7A]))
    templates = template_rng.uniform(
        0.0, 1.0, size=(num_classes, th, tw, c)
    ).astype(np.float32)
    split_seed = 0 if split == "train" else 1
    rng = np.random.default_rng(np.random.SeedSequence([seed, split_seed, 0x1A6E]))
    labels = rng.integers(0, num_classes, size=n).astype(np.int32)
    ry, rx = -(-h // th), -(-w // tw)  # repeat factors, then crop

    def chunks():
        for start in range(0, n, chunk_size):
            lbl = labels[start : start + chunk_size]
            t = templates[lbl]
            t = np.repeat(np.repeat(t, ry, axis=1), rx, axis=2)[:, :h, :w, :]
            img = t + noise * rng.standard_normal(t.shape, dtype=np.float32)
            yield (
                np.clip(img * 255.0, 0.0, 255.0).astype(np.uint8),
                lbl,
            )

    write_array_dataset(
        root, split, chunks(), n=n, image_shape=image_shape,
        num_classes=num_classes, name="imagenet-synthetic",
        extra_meta=gen_params,
    )
    return load_array_dataset(root, split)


def _load_cifar10(data_dir: str, split: str) -> Optional[Dataset]:
    """Load CIFAR-10 from the standard python-pickle batches if present."""
    import pickle

    base = os.path.join(data_dir, "cifar-10-batches-py")
    if not os.path.isdir(base):
        return None
    files = (
        [f"data_batch_{i}" for i in range(1, 6)] if split == "train" else ["test_batch"]
    )
    imgs, lbls = [], []
    for fn in files:
        p = os.path.join(base, fn)
        if not os.path.exists(p):
            return None
        with open(p, "rb") as f:
            d = pickle.load(f, encoding="bytes")
        imgs.append(d[b"data"])
        lbls.extend(d[b"labels"])
    # raw uint8, normalized on device (prepare_image) — see load_mnist
    images = np.ascontiguousarray(
        np.concatenate(imgs).reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
    )
    labels = np.asarray(lbls, dtype=np.int32)
    return Dataset(images=images, labels=labels, num_classes=10, name=f"cifar10-{split}")
