"""Token corpora and the LM data loader.

The reference has no text path at all (MNIST CNN, origin_main.py:9-31);
this module gives the decoder LM family (models/lm.py) the same data
contract the image loaders give the CNNs: deterministic (seed, epoch)
epoch plans, per-process shards, and dict batches for the jitted steps.

Two corpus sources:
- **bytes files** (`load_text_corpus`): any file(s) become a byte-level
  corpus (vocab 256) — no tokenizer, no network, works on whatever text
  the machine has.
- **synthetic Markov** (`synthetic_token_corpus`): a seeded order-1
  Markov chain over a small vocab with sparse transitions — structured
  enough that next-token loss collapses well below the uniform entropy
  within an epoch, so e2e training has a testable contract.

Batches are non-overlapping (seq_len + 1) windows (position t predicts
t + 1, train/steps.py make_lm_train_step); the epoch permutation shuffles
window order, keyed on (seed, epoch) like the image sampler
(data/sharding.py, ≡ sampler.set_epoch ddp_main.py:160).
"""

from __future__ import annotations

import os
from typing import Iterator, Optional

import numpy as np

from ddp_practice_tpu.data.sharding import ShardSpec, epoch_indices


class TokenCorpus:
    """A flat token stream (1D integer array) plus its vocab size."""

    def __init__(self, tokens: np.ndarray, vocab_size: int, name: str = "tokens"):
        tokens = np.asarray(tokens)
        assert tokens.ndim == 1, tokens.shape
        assert tokens.dtype in (np.uint8, np.uint16, np.int32), tokens.dtype
        self.tokens = tokens
        self.vocab_size = int(vocab_size)
        self.name = name

    def __len__(self) -> int:
        return len(self.tokens)

    def windows(self, seq_len: int) -> np.ndarray:
        """Non-overlapping (n_windows, seq_len + 1) int32 training windows
        (inputs + shifted targets). The device-resident random-draw
        convention shared by the MoE bench, the balance test, and the
        experiments (one source so the windowing can never drift)."""
        window = seq_len + 1
        n_win = len(self.tokens) // window
        return np.asarray(
            self.tokens[: n_win * window], np.int32
        ).reshape(n_win, window)


def load_text_corpus(path: str, name: Optional[str] = None) -> TokenCorpus:
    """Byte-level corpus from one file or every regular file in a
    directory (sorted for determinism)."""
    paths = []
    if os.path.isdir(path):
        for root, _, files in sorted(os.walk(path)):
            paths.extend(os.path.join(root, f) for f in sorted(files))
    else:
        paths = [path]
    chunks = []
    for p in paths:
        try:
            with open(p, "rb") as f:
                chunks.append(np.frombuffer(f.read(), dtype=np.uint8))
        except OSError:
            continue
    if not chunks:
        raise FileNotFoundError(f"no readable files under {path!r}")
    return TokenCorpus(
        np.concatenate(chunks), 256, name=name or f"bytes:{os.path.basename(path)}"
    )


def synthetic_token_corpus(
    n_tokens: int = 262144, *, vocab_size: int = 64, seed: int = 3407,
    branching: int = 4,
) -> TokenCorpus:
    """Order-1 Markov chain: each token has `branching` permitted
    successors with a shared skewed distribution — entropy well below
    log(vocab), so a trained LM's perplexity must drop far under uniform."""
    rng = np.random.default_rng(np.random.SeedSequence([seed, 0x7E47]))
    successors = np.stack([
        rng.choice(vocab_size, size=branching, replace=False)
        for _ in range(vocab_size)
    ])
    probs = rng.dirichlet(np.full(branching, 0.4))
    walk = np.empty(n_tokens, dtype=np.uint16 if vocab_size > 256 else np.uint8)
    state = int(rng.integers(vocab_size))
    choices = rng.choice(branching, size=n_tokens, p=probs)
    for i in range(n_tokens):
        walk[i] = state
        state = int(successors[state, choices[i]])
    return TokenCorpus(walk, vocab_size, name=f"markov{vocab_size}")


class LMDataLoader:
    """Yields {"tokens": (local_batch, seq_len + 1) int32} batches.

    Non-overlapping windows; window order is a (seed, epoch)-keyed global
    permutation; each process takes a contiguous slice of every global
    batch (the image DataLoader's sharding contract, data/loader.py).
    Trailing windows that don't fill a global batch are dropped (standard
    LM practice — the stream has no sample boundary to pad against).
    """

    def __init__(
        self,
        corpus: TokenCorpus,
        *,
        seq_len: int,
        global_batch_size: int,
        shard: Optional[ShardSpec] = None,
        seed: int = 3407,
        shuffle: bool = True,
    ):
        self.corpus = corpus
        self.seq_len = int(seq_len)
        self.global_batch_size = int(global_batch_size)
        self.shard = shard or ShardSpec()
        self.seed = seed
        self.shuffle = shuffle
        self._epoch = 0
        self.window = self.seq_len + 1
        self.num_windows = len(corpus) // self.window
        if self.num_windows < self.global_batch_size:
            raise ValueError(
                f"corpus has {self.num_windows} windows of {self.window} "
                f"tokens — fewer than one global batch "
                f"({self.global_batch_size}); shrink seq_len/batch or grow "
                "the corpus"
            )

    def set_epoch(self, epoch: int) -> None:
        self._epoch = int(epoch)

    @property
    def steps_per_epoch(self) -> int:
        return self.num_windows // self.global_batch_size

    def __len__(self) -> int:
        return self.steps_per_epoch

    def epoch_plan(self):
        """(starts, None): a (steps, global_batch) int32 grid of token
        START offsets for the device-resident driver — the same
        (seed, epoch)-keyed window order __iter__ streams, as offsets the
        on-device gather consumes (tokens[start : start + seq_len + 1]).
        The second element keeps the image DataLoader.epoch_plan interface
        (its eval weights); LM drops trailing windows instead of padding,
        so there is nothing to weight."""
        order = epoch_indices(
            self.num_windows, seed=self.seed, epoch=self._epoch,
            shuffle=self.shuffle,
        )
        usable = self.steps_per_epoch * self.global_batch_size
        starts = order[:usable].reshape(
            self.steps_per_epoch, self.global_batch_size
        ) * self.window
        return starts.astype(np.int32), None

    def __iter__(self) -> Iterator[dict]:
        order = epoch_indices(
            self.num_windows, seed=self.seed, epoch=self._epoch,
            shuffle=self.shuffle,
        )
        usable = self.steps_per_epoch * self.global_batch_size
        order = order[:usable]
        sl = self.shard.local_slice(self.global_batch_size)
        w = self.window
        toks = self.corpus.tokens
        for start in range(0, usable, self.global_batch_size):
            widx = order[start : start + self.global_batch_size][sl]
            batch = np.stack([toks[i * w : (i + 1) * w] for i in widx])
            yield {"tokens": batch.astype(np.int32)}
