"""Batched, sharded, deterministic data loader.

Replaces the reference's `DataLoader(num_workers=4, pin_memory=True)` +
`DistributedSampler` stack (origin_main.py:91-107, ddp_main.py:127-156).
On TPU the analogue of the pinned-memory H2D pipeline is forming globally
sharded `jax.Array`s from process-local numpy data and letting the runtime
overlap the transfer; `prefetch_to_device` below keeps a small queue of
batches in flight.

Batch assembly (index gather) can run through the optional native C++
backend (ddp_practice_tpu/data/native_loader.py) when built; the numpy
path is the always-available fallback.
"""

from __future__ import annotations

import collections
from typing import Iterator, Optional

import numpy as np

from ddp_practice_tpu.data.datasets import Dataset
from ddp_practice_tpu.data.sharding import ShardSpec, epoch_indices, pad_to_multiple


class DataLoader:
    """Iterates dicts of numpy arrays: image, label, weight.

    One instance per process; each process sees only its slice of every
    global batch. `set_epoch` mirrors the reference's reshuffle contract
    (ddp_main.py:160).
    """

    def __init__(
        self,
        dataset: Dataset,
        *,
        global_batch_size: int,
        shard: Optional[ShardSpec] = None,
        seed: int = 3407,
        shuffle: bool = True,
        drop_last: bool = False,
        backend: str = "auto",
    ):
        self.dataset = dataset
        self.global_batch_size = int(global_batch_size)
        self.shard = shard or ShardSpec()
        self.seed = seed
        self.shuffle = shuffle
        self.drop_last = drop_last
        self._epoch = 0
        self._gather = _make_gather(backend, dataset)

    def set_epoch(self, epoch: int) -> None:
        self._epoch = int(epoch)

    @property
    def steps_per_epoch(self) -> int:
        n = len(self.dataset)
        if self.drop_last:
            return n // self.global_batch_size
        return -(-n // self.global_batch_size)

    def __len__(self) -> int:
        return self.steps_per_epoch

    def __iter__(self) -> Iterator[dict]:
        n = len(self.dataset)
        order = epoch_indices(n, seed=self.seed, epoch=self._epoch, shuffle=self.shuffle)
        if self.drop_last:
            usable = (n // self.global_batch_size) * self.global_batch_size
            order, weights = order[:usable], np.ones(usable, dtype=np.float32)
        else:
            order, weights = pad_to_multiple(order, self.global_batch_size)
        sl = self.shard.local_slice(self.global_batch_size)
        for start in range(0, len(order), self.global_batch_size):
            gidx = order[start : start + self.global_batch_size]
            gw = weights[start : start + self.global_batch_size]
            lidx, lw = gidx[sl], gw[sl]
            images, labels = self._gather(lidx)
            yield {"image": images, "label": labels, "weight": lw}


def _make_gather(backend: str, dataset: Dataset):
    """Return fn(indices) -> (images, labels), optionally native-accelerated."""
    if backend in ("auto", "native"):
        try:
            from ddp_practice_tpu.data import native_loader

            gather = native_loader.make_gather(dataset)
            if gather is not None:
                return gather
            if backend == "native":
                raise RuntimeError("native loader requested but not built")
        except ImportError:
            if backend == "native":
                raise
    return lambda idx: (dataset.images[idx], dataset.labels[idx])


def prefetch_to_device(iterator, sharding, *, size: int = 2):
    """Form globally sharded jax.Arrays from local batches, keeping `size`
    batches in flight — the TPU analogue of pin_memory+async H2D
    (origin_main.py:96,60-61).

    `sharding` maps batch keys to `jax.sharding.NamedSharding`s (a single
    sharding is broadcast to all keys).
    """
    import jax

    def to_global(batch):
        out = {}
        for k, v in batch.items():
            sh = sharding[k] if isinstance(sharding, dict) else sharding
            out[k] = jax.make_array_from_process_local_data(sh, np.asarray(v))
        return out

    queue = collections.deque()
    for batch in iterator:
        queue.append(to_global(batch))
        if len(queue) > size:
            yield queue.popleft()
    while queue:
        yield queue.popleft()
