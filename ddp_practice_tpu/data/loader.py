"""Batched, sharded, deterministic data loader.

Replaces the reference's `DataLoader(num_workers=4, pin_memory=True)` +
`DistributedSampler` stack (origin_main.py:91-107, ddp_main.py:127-156).
On TPU the analogue of the pinned-memory H2D pipeline is forming globally
sharded `jax.Array`s from process-local numpy data and letting the runtime
overlap the transfer; `prefetch_to_device` below keeps a small queue of
batches in flight.

Batch assembly (index gather) can run through the optional native C++
backend (ddp_practice_tpu/data/native_loader.py) when built; the numpy
path is the always-available fallback.
"""

from __future__ import annotations

from typing import Iterator, Optional

import numpy as np

from ddp_practice_tpu.data.datasets import Dataset
from ddp_practice_tpu.data.sharding import ShardSpec, epoch_indices, pad_to_multiple


class DataLoader:
    """Iterates dicts of numpy arrays: image, label, weight.

    One instance per process; each process sees only its slice of every
    global batch. `set_epoch` mirrors the reference's reshuffle contract
    (ddp_main.py:160).
    """

    def __init__(
        self,
        dataset: Dataset,
        *,
        global_batch_size: int,
        shard: Optional[ShardSpec] = None,
        seed: int = 3407,
        shuffle: bool = True,
        drop_last: bool = False,
        backend: str = "auto",
    ):
        self.dataset = dataset
        self.global_batch_size = int(global_batch_size)
        self.shard = shard or ShardSpec()
        self.seed = seed
        self.shuffle = shuffle
        self.drop_last = drop_last
        self._epoch = 0
        self._gather = _make_gather(backend, dataset)

    def set_epoch(self, epoch: int) -> None:
        self._epoch = int(epoch)

    @property
    def steps_per_epoch(self) -> int:
        n = len(self.dataset)
        if self.drop_last:
            return n // self.global_batch_size
        return -(-n // self.global_batch_size)

    def __len__(self) -> int:
        return self.steps_per_epoch

    def epoch_plan(self) -> tuple:
        """The epoch's GLOBAL batch plan as (indices, weights) grids of
        shape (steps, global_batch) — the exact order __iter__ walks, before
        per-process slicing. Device-resident training feeds these grids
        straight to make_resident_train_step/make_resident_eval_step: the
        sampler semantics (seed/epoch permutation, wrap-padding with zero
        eval weights) stay in this one place."""
        order, weights = self._epoch_order()
        b = self.global_batch_size
        return (
            order.reshape(-1, b).astype(np.int32),
            weights.reshape(-1, b),
        )

    def _epoch_order(self) -> tuple:
        n = len(self.dataset)
        order = epoch_indices(n, seed=self.seed, epoch=self._epoch, shuffle=self.shuffle)
        if self.drop_last:
            usable = (n // self.global_batch_size) * self.global_batch_size
            return order[:usable], np.ones(usable, dtype=np.float32)
        return pad_to_multiple(order, self.global_batch_size)

    def __iter__(self) -> Iterator[dict]:
        order, weights = self._epoch_order()
        sl = self.shard.local_slice(self.global_batch_size)
        for start in range(0, len(order), self.global_batch_size):
            gidx = order[start : start + self.global_batch_size]
            gw = weights[start : start + self.global_batch_size]
            lidx, lw = gidx[sl], gw[sl]
            images, labels = self._gather(lidx)
            yield {"image": images, "label": labels, "weight": lw}


def _make_gather(backend: str, dataset: Dataset):
    """Return fn(indices) -> (images, labels), optionally native-accelerated."""
    if backend in ("auto", "native"):
        try:
            from ddp_practice_tpu.data import native_loader

            gather = native_loader.make_gather(dataset)
            if gather is not None:
                return gather
            if backend == "native":
                raise RuntimeError("native loader requested but not built")
        except ImportError:
            if backend == "native":
                raise
    return lambda idx: (dataset.images[idx], dataset.labels[idx])


def _to_global(batch, sharding):
    """Host batch dict -> globally sharded jax.Arrays.

    Single-process: `jax.device_put` with the NamedSharding (measured ~3.6x
    cheaper than make_array_from_process_local_data for small batches).
    Multi-process: each host contributes its local shard via
    make_array_from_process_local_data.
    """
    import jax

    single = jax.process_count() == 1
    out = {}
    for k, v in batch.items():
        sh = sharding[k] if isinstance(sharding, dict) else sharding
        arr = np.asarray(v)
        if single:
            out[k] = jax.device_put(arr, sh)
        else:
            out[k] = jax.make_array_from_process_local_data(sh, arr)
    return out


def _threaded_prefetch(host_iterator, to_device, *, size: int):
    """Overlap host-side batch assembly with device execution: a producer
    thread fills a bounded queue with HOST batches; the consumer (main)
    thread issues the device transfer — JAX's async dispatch then overlaps
    the H2D with in-flight steps (the pin_memory role of the reference,
    origin_main.py:96,60-61). Device APIs are only touched from the main
    thread: backend clients are not guaranteed thread-safe against
    concurrent execution dispatch.
    """
    import queue as queue_mod
    import threading

    q: "queue_mod.Queue" = queue_mod.Queue(maxsize=max(size, 1))
    stop = threading.Event()
    errors = []
    _DONE = object()

    def producer():
        try:
            for item in host_iterator:
                while not stop.is_set():
                    try:
                        q.put(item, timeout=0.1)
                        break
                    except queue_mod.Full:
                        continue
                if stop.is_set():
                    return
        except BaseException as e:  # surfaced on the consumer side
            errors.append(e)
        finally:
            while not stop.is_set():
                try:
                    q.put(_DONE, timeout=0.1)
                    break
                except queue_mod.Full:
                    continue

    thread = threading.Thread(target=producer, daemon=True, name="prefetch")
    thread.start()
    try:
        while True:
            item = q.get()
            if item is _DONE:
                if errors:
                    raise errors[0]
                return
            yield to_device(item)
    finally:
        stop.set()


def prefetch_to_device(iterator, sharding, *, size: int = 2):
    """Form globally sharded jax.Arrays from local batches, keeping `size`
    batches in flight on a background thread.

    `sharding` maps batch keys to `jax.sharding.NamedSharding`s (a single
    sharding is broadcast to all keys).
    """
    yield from _threaded_prefetch(
        iterator, lambda b: _to_global(b, sharding), size=size
    )


def prefetch_chunked(iterator, num_steps, batch_sharding, stacked_sharding,
                     *, size: int = 2):
    """Prefetch for K-steps-per-call training (`make_chunked_train_step`):
    groups of `num_steps` host batches are np.stack-ed and transferred as
    ONE (K, batch, ...) array — one H2D per K steps. The epoch tail that
    doesn't fill a group is yielded as single batches.

    Yields ("chunk", stacked_device_batch) and ("single", device_batch).
    """

    def host_iter():
        buf = []
        for b in iterator:
            buf.append(b)
            if len(buf) == num_steps:
                yield ("chunk", {
                    k: np.stack([x[k] for x in buf]) for k in buf[0]
                })
                buf = []
        for b in buf:
            yield ("single", b)

    def to_device(item):
        tag, batch = item
        sh = stacked_sharding if tag == "chunk" else batch_sharding
        return (tag, _to_global(batch, sh))

    yield from _threaded_prefetch(host_iter(), to_device, size=size)
