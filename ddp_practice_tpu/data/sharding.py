"""Deterministic epoch shuffling and per-process sharding.

Reproduces the reference's `DistributedSampler` + `set_epoch` semantics
(ddp_main.py:130-142,160) the JAX way: a single global permutation keyed on
(seed, epoch) — so every process agrees on the epoch's order without
communication — then a strided per-process shard. Where the reference's
sampler silently pads eval shards with duplicates (double-counted in its
reduced accuracy, SURVEY §2.5), we carry an explicit per-sample weight so
padded entries contribute zero to eval counts: eval is exact here.
"""

from __future__ import annotations

import dataclasses

import numpy as np


def epoch_indices(n: int, *, seed: int, epoch: int, shuffle: bool = True) -> np.ndarray:
    """Global sample order for one epoch, identical on every process.

    Keyed on (seed, epoch) like the reference's `sampler.set_epoch(epoch)`
    reshuffle (ddp_main.py:160).
    """
    if not shuffle:
        return np.arange(n, dtype=np.int64)
    rng = np.random.default_rng(np.random.SeedSequence([seed, epoch]))
    return rng.permutation(n).astype(np.int64)


@dataclasses.dataclass(frozen=True)
class ShardSpec:
    """Which contiguous slice of each global batch this process owns.

    The reference shards with rank-strided indices (DistributedSampler);
    here each process owns a *contiguous* slice of every global batch so the
    local slice maps directly onto the process's devices in a
    `jax.make_array_from_process_local_data` call. Sample→process assignment
    differs from the reference, but the distributional contract (disjoint
    shards, union = dataset, reshuffled per epoch) is identical.
    """

    process_index: int = 0
    num_processes: int = 1

    def __post_init__(self):
        assert 0 <= self.process_index < self.num_processes

    def local_slice(self, global_batch: int) -> slice:
        if global_batch % self.num_processes != 0:
            raise ValueError(
                f"global batch {global_batch} not divisible by "
                f"{self.num_processes} processes"
            )
        per = global_batch // self.num_processes
        return slice(self.process_index * per, (self.process_index + 1) * per)


def pad_to_multiple(indices: np.ndarray, multiple: int) -> tuple:
    """Pad index array (wrapping, like DistributedSampler) to a multiple.

    Returns (padded_indices, weights) where weights are 1.0 for real samples
    and 0.0 for padding — used by eval to stay exact where the reference
    double-counts (SURVEY §2.5).
    """
    n = len(indices)
    remainder = n % multiple
    if remainder == 0:
        return indices, np.ones(n, dtype=np.float32)
    pad = multiple - remainder
    reps = int(np.ceil(pad / max(n, 1)))
    padded = np.concatenate([indices, np.tile(indices, reps)[:pad]])
    weights = np.concatenate(
        [np.ones(n, dtype=np.float32), np.zeros(pad, dtype=np.float32)]
    )
    return padded, weights
