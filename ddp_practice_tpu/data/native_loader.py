"""ctypes binding for the native (C++) batch-assembly backend.

Builds `native/libddp_loader.so` on first use if a compiler is available
(no pybind11 in this environment; the C ABI + ctypes keeps the binding
dependency-free). Falls back silently — callers treat None from
`make_gather` as "use the numpy path", which is bit-identical.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Callable, Optional

import numpy as np

from ddp_practice_tpu.data.datasets import Dataset

_NATIVE_DIR = os.path.join(os.path.dirname(__file__), "..", "..", "native")
# ABI-versioned filename (matches native/Makefile TARGET): a stale build
# from an older ABI simply has a different name and is never picked up —
# dlopen's per-pathname handle caching makes same-name reloads impossible.
_SO_NAME = "libddp_loader.v3.so"

_lib = None
_lib_lock = threading.Lock()
_build_attempted = False


_ABI_VERSION = 3  # keep in sync with dl_version() in native/dataloader.cpp


def _load_library() -> Optional[ctypes.CDLL]:
    global _lib, _build_attempted
    with _lib_lock:
        if _lib is not None:
            return _lib if _lib is not _UNAVAILABLE else None
        so_path = os.path.abspath(os.path.join(_NATIVE_DIR, _SO_NAME))
        if not os.path.exists(so_path) and not _build_attempted:
            _build_attempted = True
            _try_build()
        if not os.path.exists(so_path):
            _lib = _UNAVAILABLE  # cache the negative result
            return None
        lib = ctypes.CDLL(so_path)
        lib.dl_create.restype = ctypes.c_void_p
        lib.dl_create.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_int32,
        ]
        lib.dl_destroy.argtypes = [ctypes.c_void_p]
        lib.dl_gather.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int32,
        ]
        lib.dl_gather.restype = ctypes.c_int32
        lib.dl_version.restype = ctypes.c_int32
        if lib.dl_version() != _ABI_VERSION:  # filename/ABI drift guard
            _lib = _UNAVAILABLE
            return None
        _lib = lib
        return _lib


_UNAVAILABLE = object()  # sentinel: library looked for and not usable


def _try_build() -> None:
    makefile = os.path.join(_NATIVE_DIR, "Makefile")
    if not os.path.exists(makefile):
        return
    try:
        subprocess.run(
            ["make", "-C", os.path.abspath(_NATIVE_DIR)],
            check=True,
            capture_output=True,
            timeout=120,
        )
    except (subprocess.SubprocessError, OSError):
        pass


class _NativeGather:
    """Callable gather backed by the C++ library.

    Wraps the dataset's own storage zero-copy in its own dtype: fp32
    arrays stay fp32, uint8 stays uint8 (4x less memory traffic), and a
    memmapped corpus is wrapped at its mapped address — the C++ memcpy
    then streams pages from disk through the OS page cache. References
    are held for the handle's lifetime.
    """

    def __init__(self, lib: ctypes.CDLL, dataset: Dataset):
        self._lib = lib
        # already-contiguous arrays (incl. .npy memmaps) pass through as
        # views — no copy, no fp32 materialization
        self._images = np.ascontiguousarray(dataset.images)
        self._labels = np.ascontiguousarray(dataset.labels, dtype=np.int32)
        self._dtype = self._images.dtype
        self._sample_shape = self._images.shape[1:]
        self._sample_elems = int(np.prod(self._sample_shape))
        self._handle = lib.dl_create(
            self._images.ctypes.data_as(ctypes.c_void_p),
            self._labels.ctypes.data_as(ctypes.c_void_p),
            len(self._images),
            self._sample_elems,
            self._dtype.itemsize,
        )

    def __call__(self, indices: np.ndarray):
        idx = np.ascontiguousarray(indices, dtype=np.int64)
        n = len(idx)
        out_images = np.empty((n,) + self._sample_shape, self._dtype)
        out_labels = np.empty((n,), np.int32)
        status = self._lib.dl_gather(
            self._handle,
            idx.ctypes.data_as(ctypes.c_void_p),
            n,
            out_images.ctypes.data_as(ctypes.c_void_p),
            out_labels.ctypes.data_as(ctypes.c_void_p),
            0,
        )
        if status != 0:  # same error class as the numpy fancy-index path
            raise IndexError(
                f"native gather: index out of range for dataset of "
                f"{len(self._images)} samples"
            )
        return out_images, out_labels

    def __del__(self):
        try:
            if self._handle:
                self._lib.dl_destroy(self._handle)
        except Exception:
            pass


def make_gather(dataset: Dataset) -> Optional[Callable]:
    """Return a native gather callable, or None if the backend is
    unavailable (caller falls back to numpy)."""
    lib = _load_library()
    if lib is None:
        return None
    return _NativeGather(lib, dataset)


def available() -> bool:
    return _load_library() is not None
