"""Input pipeline: datasets, per-host sharding, batching.

Replaces the reference's torchvision MNIST + DataLoader + DistributedSampler
stack (origin_main.py:88-107, ddp_main.py:127-156) with NumPy-array datasets,
a deterministic (seed, epoch)-keyed global shuffle, per-host strided shards,
and device placement through `jax.make_array_from_process_local_data`.
"""

from ddp_practice_tpu.data.datasets import (
    Dataset,
    load_array_dataset,
    load_dataset,
    synthetic_imagenet_corpus,
    write_array_dataset,
)
from ddp_practice_tpu.data.sharding import ShardSpec, epoch_indices
from ddp_practice_tpu.data.loader import DataLoader

__all__ = [
    "Dataset",
    "load_dataset",
    "load_array_dataset",
    "write_array_dataset",
    "synthetic_imagenet_corpus",
    "ShardSpec",
    "epoch_indices",
    "DataLoader",
]
