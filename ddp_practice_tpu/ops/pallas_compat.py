"""Pallas TPU API compatibility: CompilerParams naming across jax versions.

Current jax spells it `pltpu.CompilerParams`; the older jax this image
may ship only has `pltpu.TPUCompilerParams` (same fields — the kernels
here use `dimension_semantics` and `vmem_limit_bytes`, both present in
either). Kernel modules call this factory instead of naming the class,
so the version split lives in one place (mirrors parallel/compat.py for
shard_map).
"""

from __future__ import annotations


def tpu_compiler_params(**kwargs):
    from jax.experimental.pallas import tpu as pltpu

    cls = getattr(pltpu, "CompilerParams", None)
    if cls is None:
        cls = pltpu.TPUCompilerParams
    return cls(**kwargs)
