"""Mixture-of-Experts: top-k routed MLP with expert parallelism.

Absent from the reference ("Expert parallel (EP/MoE) — No", SURVEY §2.3).
TPU-first construction (the GShard/Switch recipe, which was designed FOR
TPUs): routing is dense one-hot linear algebra — no gather/scatter, no
dynamic shapes, everything lands on the MXU as batched einsums —

    logits  (G,T,E) -> top-k assignment + position-in-expert via cumsum
    dispatch (G,T,E,C) one-hot   combine (G,T,E,C) gate-weighted
    expert_in  = einsum(dispatch, x)      -> (E, G, C, D)
    expert_out = batched expert MLP       -> (E, G, C, D)
    y          = einsum(combine, expert_out) -> (G, T, D)

Expert weights are stacked on a leading E dim sharded over the 'expert'
mesh axis, and the (E, ...) activation tensors carry a
`with_sharding_constraint` to the same axis — XLA lowers the layout switch
(tokens grouped-by-expert <-> experts-by-token) into all-to-alls over ICI,
which is exactly the manual NCCL a2a pattern of GPU MoE frameworks, here
derived from shardings. Capacity overflow drops tokens (residual passes
them through untouched); a Switch-style load-balance auxiliary loss keeps
routing uniform.
"""

from __future__ import annotations

from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from ddp_practice_tpu.config import MeshConfig


def _constrain(x, spec):
    """Pin a layout on the current framework mesh (no-op without a mesh —
    e.g. plain single-device unit tests). Uses NamedSharding, which binds
    under jit without a jax context mesh."""
    from ddp_practice_tpu.parallel.ring import get_current_mesh

    mesh = get_current_mesh()
    if mesh is None:
        return x
    return jax.lax.with_sharding_constraint(
        x, jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec(*spec))
    )


def top_k_gating(
    router_logits: jnp.ndarray,  # (G, T, E) fp32
    *,
    k: int,
    capacity: int,
):
    """Return (dispatch (G,T,E,C) bool-ish, combine (G,T,E,C), aux_loss).

    Iterative top-k: pick the best expert per token, compute each token's
    position within that expert's buffer by a cumsum over the token dim,
    drop tokens past `capacity`, mask the chosen expert out, repeat. All
    dense ops — compiles to static-shape TPU code.
    """
    g, t, e = router_logits.shape
    gates = jax.nn.softmax(router_logits.astype(jnp.float32), axis=-1)

    remaining = gates
    fill = jnp.zeros((g, e), jnp.float32)  # tokens already claimed per expert
    dispatch = jnp.zeros((g, t, e, capacity), jnp.float32)
    for _ in range(k):
        choice = jnp.argmax(remaining, axis=-1)              # (G, T)
        onehot = jax.nn.one_hot(choice, e, dtype=jnp.float32)  # (G, T, E)
        pos = (
            jnp.cumsum(onehot, axis=1) - onehot + fill[:, None, :]
        )  # (G, T, E): position within expert buffer
        pos_tok = jnp.sum(pos * onehot, axis=-1)             # (G, T)
        keep = (pos_tok < capacity).astype(jnp.float32)      # (G, T)
        pos_oh = jax.nn.one_hot(
            pos_tok.astype(jnp.int32), capacity, dtype=jnp.float32
        )
        dispatch = dispatch + jnp.einsum(
            "gte,gtc->gtec", onehot * keep[..., None], pos_oh
        )
        fill = fill + jnp.sum(onehot * keep[..., None], axis=1)
        remaining = remaining * (1.0 - onehot)

    # per-slot combine weight: router gates renormalized over each token's
    # kept experts (tokens dropped everywhere get an all-zero combine row —
    # the residual connection carries them through unchanged)
    dispatched_expert = jnp.sum(dispatch, axis=-1)           # (G, T, E)
    gsel = gates * dispatched_expert
    gsel = gsel / jnp.maximum(jnp.sum(gsel, axis=-1, keepdims=True), 1e-9)
    combine = dispatch * gsel[..., None]

    # Switch-style load-balance loss: E * sum_e fraction_e * prob_e
    frac = jnp.mean(dispatched_expert, axis=(0, 1))          # (E,) usage
    prob = jnp.mean(gates, axis=(0, 1))                      # (E,) router mass
    aux = e * jnp.sum(frac * prob)
    return dispatch, combine, aux


class MoEMlp(nn.Module):
    """Expert-parallel MLP: drop-in for a dense transformer MLP block."""

    num_experts: int = 8
    top_k: int = 2
    capacity_factor: float = 1.25
    mlp_dim: int = 768
    aux_loss_weight: float = 0.01
    dtype: jnp.dtype = jnp.float32
    param_dtype: jnp.dtype = jnp.float32
    expert_axis: Optional[str] = MeshConfig.AXIS_EXPERT

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:  # (G, T, D)
        g, t, d = x.shape
        e, f = self.num_experts, self.mlp_dim
        capacity = max(
            1, int(self.capacity_factor * self.top_k * t / e)
        )

        router = nn.Dense(
            e,
            dtype=jnp.float32,
            param_dtype=self.param_dtype,
            use_bias=False,
            name="router",
        )
        logits = router(x.astype(jnp.float32))               # (G, T, E)
        dispatch, combine, aux = top_k_gating(
            logits, k=self.top_k, capacity=capacity
        )
        self.sow("intermediates", "moe_aux_loss", self.aux_loss_weight * aux)
        # router health (diagnostic sows — no "aux_loss" in the name, so
        # they never join the objective; train/steps.py surfaces them as
        # moe_* metrics): per-expert share of ROUTED tokens, and the
        # fraction of the k*T assignment slots lost to capacity drops
        routed = jnp.sum(dispatch)
        self.sow(
            "intermediates", "moe_load_frac",
            jnp.sum(dispatch, axis=(0, 1, 3)) / jnp.maximum(routed, 1.0),
        )
        self.sow(
            "intermediates", "moe_drop_rate",
            1.0 - routed / (self.top_k * g * t),
        )

        w_in = self.param(
            "expert_w_in",
            nn.initializers.lecun_normal(batch_axis=(0,)),
            (e, d, f),
            self.param_dtype,
        )
        b_in = self.param(
            "expert_b_in", nn.initializers.zeros, (e, f), self.param_dtype
        )
        w_out = self.param(
            "expert_w_out",
            nn.initializers.lecun_normal(batch_axis=(0,)),
            (e, f, d),
            self.param_dtype,
        )
        b_out = self.param(
            "expert_b_out", nn.initializers.zeros, (e, d), self.param_dtype
        )

        ax = self.expert_axis
        cdtype = self.dtype
        xin = jnp.einsum(
            "gtec,gtd->egcd", dispatch.astype(cdtype), x.astype(cdtype)
        )
        xin = _constrain(xin, (ax, MeshConfig.AXIS_DATA, None, None))
        h = jnp.einsum("egcd,edf->egcf", xin, w_in.astype(cdtype))
        h = nn.gelu(h + b_in.astype(cdtype)[:, None, None, :])
        out = jnp.einsum("egcf,efd->egcd", h, w_out.astype(cdtype))
        out = out + b_out.astype(cdtype)[:, None, None, :]
        out = _constrain(out, (ax, MeshConfig.AXIS_DATA, None, None))
        y = jnp.einsum("gtec,egcd->gtd", combine.astype(cdtype), out)
        return y.astype(x.dtype)
