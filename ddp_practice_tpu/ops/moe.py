"""Mixture-of-Experts: top-k routed MLP with expert parallelism.

Absent from the reference ("Expert parallel (EP/MoE) — No", SURVEY §2.3).
TPU-first construction (the GShard/Switch recipe, which was designed FOR
TPUs): routing is dense one-hot linear algebra — no gather/scatter, no
dynamic shapes, everything lands on the MXU as batched einsums —

    logits  (G,T,E) -> top-k assignment + position-in-expert via cumsum
    dispatch (G,T,E,C) one-hot   combine (G,T,E,C) gate-weighted
    expert_in  = einsum(dispatch, x)      -> (E, G, C, D)
    expert_out = batched expert MLP       -> (E, G, C, D)
    y          = einsum(combine, expert_out) -> (G, T, D)

Expert weights are stacked on a leading E dim sharded over the 'expert'
mesh axis, and the (E, ...) activation tensors carry a
`with_sharding_constraint` to the same axis — XLA lowers the layout switch
(tokens grouped-by-expert <-> experts-by-token) into all-to-alls over ICI,
which is exactly the manual NCCL a2a pattern of GPU MoE frameworks, here
derived from shardings. Capacity overflow drops tokens (residual passes
them through untouched); a Switch-style load-balance auxiliary loss keeps
routing uniform.
"""

from __future__ import annotations

from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from ddp_practice_tpu.config import MeshConfig


def _constrain(x, spec):
    """Pin a layout on the current framework mesh (no-op without a mesh —
    e.g. plain single-device unit tests). Uses NamedSharding, which binds
    under jit without a jax context mesh."""
    from ddp_practice_tpu.parallel.ring import get_current_mesh

    mesh = get_current_mesh()
    if mesh is None:
        return x
    return jax.lax.with_sharding_constraint(
        x, jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec(*spec))
    )


def top_k_gating(
    router_logits: jnp.ndarray,  # (G, T, E) fp32
    *,
    k: int,
    capacity: int,
    routing_bias: Optional[jnp.ndarray] = None,  # (E,) selection-only
):
    """Return (dispatch (G,T,E,C), combine (G,T,E,C), aux_loss, demand).

    Iterative top-k: pick the best expert per token, compute each token's
    position within that expert's buffer by a cumsum over the token dim,
    drop tokens past `capacity`, mask the chosen expert out, repeat. All
    dense ops — compiles to static-shape TPU code.

    `routing_bias` biases SELECTION only (which experts a token goes to),
    never the combine weights — the aux-free online balancing signal
    (MoEMlp maintains it; the DeepSeek-V3 scheme). `demand` is the (E,)
    pre-drop share of the k*T assignment slots each expert attracted —
    the overload signal the bias update consumes.
    """
    g, t, e = router_logits.shape
    gates = jax.nn.softmax(router_logits.astype(jnp.float32), axis=-1)
    if routing_bias is not None:
        sel = jax.nn.softmax(
            router_logits.astype(jnp.float32)
            + routing_bias.astype(jnp.float32), axis=-1
        )
    else:
        sel = gates

    remaining = sel
    fill = jnp.zeros((g, e), jnp.float32)  # tokens already claimed per expert
    dispatch = jnp.zeros((g, t, e, capacity), jnp.float32)
    first_choice = None
    demand = jnp.zeros((e,), jnp.float32)
    for _ in range(k):
        choice = jnp.argmax(remaining, axis=-1)              # (G, T)
        onehot = jax.nn.one_hot(choice, e, dtype=jnp.float32)  # (G, T, E)
        demand = demand + jnp.mean(onehot, axis=(0, 1)) / k
        if first_choice is None:
            first_choice = onehot
        pos = (
            jnp.cumsum(onehot, axis=1) - onehot + fill[:, None, :]
        )  # (G, T, E): position within expert buffer
        pos_tok = jnp.sum(pos * onehot, axis=-1)             # (G, T)
        keep = (pos_tok < capacity).astype(jnp.float32)      # (G, T)
        pos_oh = jax.nn.one_hot(
            pos_tok.astype(jnp.int32), capacity, dtype=jnp.float32
        )
        dispatch = dispatch + jnp.einsum(
            "gte,gtc->gtec", onehot * keep[..., None], pos_oh
        )
        fill = fill + jnp.sum(onehot * keep[..., None], axis=1)
        remaining = remaining * (1.0 - onehot)

    # per-slot combine weight: router gates renormalized over each token's
    # kept experts (tokens dropped everywhere get an all-zero combine row —
    # the residual connection carries them through unchanged)
    dispatched_expert = jnp.sum(dispatch, axis=-1)           # (G, T, E)
    gsel = gates * dispatched_expert
    gsel = gsel / jnp.maximum(jnp.sum(gsel, axis=-1, keepdims=True), 1e-9)
    combine = dispatch * gsel[..., None]

    # Switch-style load-balance loss: E * sum_e fraction_e * prob_e, with
    # frac from the PRE-DROP first-choice assignments (Switch eq. 4). An
    # earlier version used the post-drop dispatched counts — self-
    # defeating: an over-capacity expert's fraction saturates at
    # capacity, so the loss could not see (or penalize) overload beyond
    # it, and raising the aux weight made balance WORSE (measured,
    # BENCHMARKS.md round-4 MoE section).
    frac = jnp.mean(first_choice, axis=(0, 1))               # (E,) demand
    prob = jnp.mean(gates, axis=(0, 1))                      # (E,) router mass
    aux = e * jnp.sum(frac * prob)
    return dispatch, combine, aux, demand


class MoEMlp(nn.Module):
    """Expert-parallel MLP: drop-in for a dense transformer MLP block."""

    num_experts: int = 8
    top_k: int = 2
    capacity_factor: float = 1.25
    mlp_dim: int = 768
    aux_loss_weight: float = 0.01
    # aux-free online balancing (the DeepSeek-V3 scheme): a NON-LEARNED
    # per-expert bias nudges SELECTION (never combine weights) against
    # measured overload each training step: b -= rate * sign(demand -
    # 1/E). Unlike the gradient aux loss, it acts on the argmax directly,
    # so it balances even when hidden states share a dominant common-mode
    # direction (measured: the aux loss alone plateaued at ~10% drops and
    # OSCILLATED when strengthened — BENCHMARKS.md round-4 MoE section).
    # Lives in "batch_stats" so it rides the existing non-param state
    # plumbing (train/steps.py, checkpointing). 0 disables.
    bias_update_rate: float = 0.02
    # tokens per routing group. 0 = one group per leading-dim row (the
    # whole sequence — the GShard default). Smaller groups cut the
    # dispatch/combine einsum cost, which is O(group_size) PER TOKEN
    # (the one-hot contracts t x (E*C) with C ∝ group_size): at lm_moe
    # shape, group 2048 -> 256 is ~8x less dispatch matmul. The price is
    # capacity granularity: per-group demand varies more, so pair small
    # groups with the strided interleave below and a measured capacity
    # factor (BENCHMARKS.md round-4 MoE section).
    group_size: int = 0
    # interleave-stride the sequence into groups (with n_sub = seq /
    # group_size groups per sequence, group j takes tokens {j, j+n_sub,
    # j+2*n_sub, ...}): adjacent tokens — which share local context
    # and crowd the same experts — land in DIFFERENT groups, so
    # per-group demand concentrates less than contiguous chunks at the
    # same size. Shard-safe: the transpose is within one sequence
    # (leading dim untouched), so dp sharding never moves.
    group_stride: bool = True
    dtype: jnp.dtype = jnp.float32
    param_dtype: jnp.dtype = jnp.float32
    expert_axis: Optional[str] = MeshConfig.AXIS_EXPERT

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:  # (G, T, D)
        g0, t0, d = x.shape
        n_sub = 1
        if (self.group_size > t0 and not self.is_initializing()
                and self.is_mutable_collection("batch_stats")):
            # a group larger than the sequence cannot exist; routing falls
            # back to whole-sequence, whose capacity behavior differs from
            # what the group-tuned capacity factor was calibrated for
            # (advisor round 4). Warn, don't raise — and only on the
            # TRAINING path (mutable batch_stats, like the router-bias
            # update): short inputs are NORMAL in decode/prefill (t0 =
            # prompt length or 1 — inference.py drives this module with
            # the training group_size) and must stay silent.
            import warnings

            warnings.warn(
                f"moe group_size {self.group_size} exceeds the sequence "
                f"length {t0}: routing whole-sequence — pass 0 or a "
                "divisor of the sequence length",
                stacklevel=2,
            )
        if 0 < self.group_size < t0:
            if t0 % self.group_size:
                raise ValueError(
                    f"moe group_size {self.group_size} must divide the "
                    f"sequence length {t0}"
                )
            n_sub = t0 // self.group_size
            if self.group_stride:
                # (g0, t0, d) -> (g0 * n_sub, group_size, d), group j of
                # a sequence = tokens {j, j + n_sub, ...}
                x = x.reshape(g0, self.group_size, n_sub, d)
                x = jnp.swapaxes(x, 1, 2)
                x = x.reshape(g0 * n_sub, self.group_size, d)
            else:
                x = x.reshape(g0 * n_sub, self.group_size, d)
        g, t, d = x.shape
        e, f = self.num_experts, self.mlp_dim
        capacity = max(
            1, int(self.capacity_factor * self.top_k * t / e)
        )

        router = nn.Dense(
            e,
            dtype=jnp.float32,
            param_dtype=self.param_dtype,
            use_bias=False,
            name="router",
        )
        logits = router(x.astype(jnp.float32))               # (G, T, E)
        # decode/eval paths may apply without the batch_stats collection
        # (generate.py builds variables from params + cache only): route
        # with no bias there — selection then follows the raw gates,
        # which the aux loss keeps roughly balanced
        bias = None
        if self.is_initializing() or self.has_variable(
            "batch_stats", "router_bias"
        ):
            bias = self.variable(
                "batch_stats", "router_bias",
                lambda: jnp.zeros((e,), jnp.float32),
            )
        dispatch, combine, aux, demand = top_k_gating(
            logits, k=self.top_k, capacity=capacity,
            routing_bias=None if bias is None else bias.value,
        )
        if bias is not None and self.is_mutable_collection(
            "batch_stats"
        ) and self.bias_update_rate > 0.0:
            bias.value = jax.lax.stop_gradient(
                bias.value - self.bias_update_rate
                * jnp.sign(demand - 1.0 / e)
            )
        self.sow("intermediates", "moe_aux_loss", self.aux_loss_weight * aux)
        # router health (diagnostic sows — no "aux_loss" in the name, so
        # they never join the objective; train/steps.py surfaces them as
        # moe_* metrics): per-expert share of ROUTED tokens, and the
        # fraction of the k*T assignment slots lost to capacity drops
        routed = jnp.sum(dispatch)
        self.sow(
            "intermediates", "moe_load_frac",
            jnp.sum(dispatch, axis=(0, 1, 3)) / jnp.maximum(routed, 1.0),
        )
        self.sow(
            "intermediates", "moe_drop_rate",
            1.0 - routed / (self.top_k * g * t),
        )

        w_in = self.param(
            "expert_w_in",
            nn.initializers.lecun_normal(batch_axis=(0,)),
            (e, d, f),
            self.param_dtype,
        )
        b_in = self.param(
            "expert_b_in", nn.initializers.zeros, (e, f), self.param_dtype
        )
        w_out = self.param(
            "expert_w_out",
            nn.initializers.lecun_normal(batch_axis=(0,)),
            (e, f, d),
            self.param_dtype,
        )
        b_out = self.param(
            "expert_b_out", nn.initializers.zeros, (e, d), self.param_dtype
        )

        ax = self.expert_axis
        cdtype = self.dtype
        xin = jnp.einsum(
            "gtec,gtd->egcd", dispatch.astype(cdtype), x.astype(cdtype)
        )
        xin = _constrain(xin, (ax, MeshConfig.AXIS_DATA, None, None))
        h = jnp.einsum("egcd,edf->egcf", xin, w_in.astype(cdtype))
        h = nn.gelu(h + b_in.astype(cdtype)[:, None, None, :])
        out = jnp.einsum("egcf,efd->egcd", h, w_out.astype(cdtype))
        out = out + b_out.astype(cdtype)[:, None, None, :]
        out = _constrain(out, (ax, MeshConfig.AXIS_DATA, None, None))
        y = jnp.einsum("gtec,egcd->gtd", combine.astype(cdtype), out)
        if n_sub > 1:
            if self.group_stride:
                y = y.reshape(g0, n_sub, self.group_size, d)
                y = jnp.swapaxes(y, 1, 2)
            y = y.reshape(g0, t0, d)
        return y.astype(x.dtype)
