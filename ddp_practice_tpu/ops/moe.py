"""Mixture-of-Experts: top-k routed MLP with expert parallelism.

Absent from the reference ("Expert parallel (EP/MoE) — No", SURVEY §2.3).
TPU-first construction (the GShard/Switch recipe, which was designed FOR
TPUs): routing is dense one-hot linear algebra — no gather/scatter, no
dynamic shapes, everything lands on the MXU as batched einsums —

    logits  (G,T,E) -> top-k assignment + position-in-expert via cumsum
    dispatch (G,T,E,C) one-hot   combine (G,T,E,C) gate-weighted
    expert_in  = einsum(dispatch, x)      -> (E, G, C, D)
    expert_out = batched expert MLP       -> (E, G, C, D)
    y          = einsum(combine, expert_out) -> (G, T, D)

Expert weights are stacked on a leading E dim sharded over the 'expert'
mesh axis, and the (E, ...) activation tensors carry a
`with_sharding_constraint` to the same axis — XLA lowers the layout switch
(tokens grouped-by-expert <-> experts-by-token) into all-to-alls over ICI,
which is exactly the manual NCCL a2a pattern of GPU MoE frameworks, here
derived from shardings. Capacity overflow drops tokens (residual passes
them through untouched); a Switch-style load-balance auxiliary loss keeps
routing uniform.
"""

from __future__ import annotations

import functools
from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from ddp_practice_tpu.config import MeshConfig


def _constrain(x, spec):
    """Pin a layout on the current framework mesh (no-op without a mesh —
    e.g. plain single-device unit tests). Uses NamedSharding, which binds
    under jit without a jax context mesh."""
    from ddp_practice_tpu.parallel.ring import get_current_mesh

    mesh = get_current_mesh()
    if mesh is None:
        return x
    return jax.lax.with_sharding_constraint(
        x, jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec(*spec))
    )


def topk_choices(
    router_logits: jnp.ndarray,  # (..., E) fp32
    *,
    k: int,
    routing_bias: Optional[jnp.ndarray] = None,  # (E,) selection-only
):
    """Top-k expert selection without the capacity machinery.

    Returns (choices (..., k) int32, combine gates (..., k) fp32
    renormalized over each token's k picks, aux_loss, demand (E,)).
    The dropless sorted path (below) consumes this directly; the
    capacity-dropping einsum path keeps `top_k_gating`, whose combine
    weights renormalize over the KEPT experts instead. `routing_bias`
    biases selection only, never the combine weights (the DeepSeek-V3
    aux-free balancing scheme, same contract as top_k_gating)."""
    e = router_logits.shape[-1]
    logits = router_logits.astype(jnp.float32)
    gates = jax.nn.softmax(logits, axis=-1)
    sel = logits if routing_bias is None else (
        logits + routing_bias.astype(jnp.float32)
    )
    _, choices = jax.lax.top_k(sel, k)                    # (..., k)
    cgates = jnp.take_along_axis(gates, choices, axis=-1)  # (..., k)
    cgates = cgates / jnp.maximum(
        jnp.sum(cgates, axis=-1, keepdims=True), 1e-9
    )
    lead = tuple(range(router_logits.ndim - 1))
    onehot = jax.nn.one_hot(choices, e, dtype=jnp.float32)  # (..., k, E)
    demand = jnp.mean(jnp.sum(onehot, axis=-2), axis=lead) / k
    # Switch-style load-balance loss (eq. 4): pre-drop first-choice
    # fractions x mean router mass — identical to top_k_gating's
    frac = jnp.mean(onehot[..., 0, :], axis=lead)
    prob = jnp.mean(gates, axis=lead)
    aux = e * jnp.sum(frac * prob)
    return choices, cgates, aux, demand


def top_k_routing(
    router_logits: jnp.ndarray,  # (G, T, E) fp32
    *,
    k: int,
    capacity: int,
    routing_bias: Optional[jnp.ndarray] = None,  # (E,) selection-only
):
    """Capacity-constrained top-k routing as INDEX tensors.

    Returns (choices (G,T,k) int32, positions (G,T,k) int32 — each
    token's buffer position within its chosen expert, keeps (G,T,k)
    fp32 — 0 where the token overflowed capacity, gsel (G,T,E) fp32 —
    router gates renormalized over each token's kept experts, aux_loss,
    demand (E,)).

    Iterative top-k: pick the best expert per token, compute each
    token's position within that expert's buffer by a cumsum over the
    token dim, drop tokens past `capacity`, mask the chosen expert out,
    repeat. All dense ops — compiles to static-shape TPU code. Both
    expert-compute layouts derive from these indices: the einsum path
    expands them to one-hot dispatch/combine tensors (top_k_gating),
    the gather path consumes them directly.

    `routing_bias` biases SELECTION only (which experts a token goes
    to), never the combine weights — the aux-free online balancing
    signal (MoEMlp maintains it; the DeepSeek-V3 scheme). `demand` is
    the (E,) pre-drop share of the k*T assignment slots each expert
    attracted — the overload signal the bias update consumes.
    """
    g, t, e = router_logits.shape
    gates = jax.nn.softmax(router_logits.astype(jnp.float32), axis=-1)
    if routing_bias is not None:
        sel = jax.nn.softmax(
            router_logits.astype(jnp.float32)
            + routing_bias.astype(jnp.float32), axis=-1
        )
    else:
        sel = gates

    remaining = sel
    fill = jnp.zeros((g, e), jnp.float32)  # tokens already claimed per expert
    first_choice = None
    demand = jnp.zeros((e,), jnp.float32)
    kept_expert = jnp.zeros((g, t, e), jnp.float32)
    choices, positions, keeps = [], [], []
    for _ in range(k):
        choice = jnp.argmax(remaining, axis=-1)              # (G, T)
        onehot = jax.nn.one_hot(choice, e, dtype=jnp.float32)  # (G, T, E)
        demand = demand + jnp.mean(onehot, axis=(0, 1)) / k
        if first_choice is None:
            first_choice = onehot
        pos = (
            jnp.cumsum(onehot, axis=1) - onehot + fill[:, None, :]
        )  # (G, T, E): position within expert buffer
        pos_tok = jnp.sum(pos * onehot, axis=-1)             # (G, T)
        keep = (pos_tok < capacity).astype(jnp.float32)      # (G, T)
        choices.append(choice.astype(jnp.int32))
        positions.append(jnp.minimum(pos_tok, capacity - 1).astype(jnp.int32))
        keeps.append(keep)
        kept_expert = kept_expert + onehot * keep[..., None]
        fill = fill + jnp.sum(onehot * keep[..., None], axis=1)
        remaining = remaining * (1.0 - onehot)

    # per-slot combine weight: router gates renormalized over each token's
    # kept experts (tokens dropped everywhere get an all-zero combine row —
    # the residual connection carries them through unchanged)
    gsel = gates * kept_expert
    gsel = gsel / jnp.maximum(jnp.sum(gsel, axis=-1, keepdims=True), 1e-9)

    # Switch-style load-balance loss: E * sum_e fraction_e * prob_e, with
    # frac from the PRE-DROP first-choice assignments (Switch eq. 4). An
    # earlier version used the post-drop dispatched counts — self-
    # defeating: an over-capacity expert's fraction saturates at
    # capacity, so the loss could not see (or penalize) overload beyond
    # it, and raising the aux weight made balance WORSE (measured,
    # BENCHMARKS.md round-4 MoE section).
    frac = jnp.mean(first_choice, axis=(0, 1))               # (E,) demand
    prob = jnp.mean(gates, axis=(0, 1))                      # (E,) router mass
    aux = e * jnp.sum(frac * prob)
    return (
        jnp.stack(choices, axis=-1), jnp.stack(positions, axis=-1),
        jnp.stack(keeps, axis=-1), gsel, aux, demand,
    )


def expert_choice_gating(
    router_logits: jnp.ndarray,  # (G, T, E) fp32
    *,
    capacity: int,
):
    """Expert-choice routing (Zhou et al. 2022): experts pick tokens.

    Each expert takes the top-`capacity` tokens of its softmax column,
    so every buffer slot is filled — perfect load balance, zero drops,
    and zero capacity padding BY CONSTRUCTION (executed expert FLOPs ==
    active FLOPs; with capacity k*T/E the compute matches top-k routing
    exactly). No auxiliary loss and no balancing bias are needed; the
    machinery that token-choice requires to fight imbalance simply has
    nothing to do. Combine weights are the raw router gates at the
    picked (token, expert) pairs (the paper's formulation — tokens
    chosen by several experts sum their contributions; tokens chosen by
    none ride the residual).

    Returns (dispatch (G,T,E,C), combine (G,T,E,C), uncovered — the
    fraction of tokens no expert picked, the quality-relevant analogue
    of token-choice's drop rate).

    Caveat (documented, inherent to EC): a token's routing depends on
    which OTHER tokens in its routing group compete for the same
    experts — for causal LMs that lets training-time routing (only
    routing, never attention) see the future. Mixture-of-Depths
    (Raposo et al. 2024) discusses the same property and its inference
    predictors; scope the competition with routing groups and prefer
    token-choice when strict train-time causality matters.
    """
    g, t, e = router_logits.shape
    gates = jax.nn.softmax(router_logits.astype(jnp.float32), axis=-1)
    scores = jnp.swapaxes(gates, 1, 2)                    # (G, E, T)
    _, idx = jax.lax.top_k(scores, capacity)              # (G, E, C)
    onehot = jax.nn.one_hot(idx, t, dtype=jnp.float32)    # (G, E, C, T)
    dispatch = jnp.transpose(onehot, (0, 3, 1, 2))        # (G, T, E, C)
    combine = dispatch * gates[..., None]
    covered = jnp.clip(jnp.sum(dispatch, axis=(2, 3)), 0.0, 1.0)
    uncovered = 1.0 - jnp.mean(covered)
    return dispatch, combine, uncovered


def top_k_gating(
    router_logits: jnp.ndarray,  # (G, T, E) fp32
    *,
    k: int,
    capacity: int,
    routing_bias: Optional[jnp.ndarray] = None,  # (E,) selection-only
):
    """Return (dispatch (G,T,E,C), combine (G,T,E,C), aux_loss, demand).

    The one-hot expansion of top_k_routing — the GShard layout the
    einsum path and its expert-sharded all-to-alls contract over."""
    choices, positions, keeps, gsel, aux, demand = top_k_routing(
        router_logits, k=k, capacity=capacity, routing_bias=routing_bias,
    )
    e = router_logits.shape[-1]
    dispatch = jnp.zeros(
        router_logits.shape[:2] + (e, capacity), jnp.float32
    )
    for j in range(choices.shape[-1]):
        onehot = jax.nn.one_hot(choices[..., j], e, dtype=jnp.float32)
        pos_oh = jax.nn.one_hot(
            positions[..., j], capacity, dtype=jnp.float32
        )
        dispatch = dispatch + jnp.einsum(
            "gte,gtc->gtec", onehot * keeps[..., j, None], pos_oh
        )
    combine = dispatch * gsel[..., None]
    return dispatch, combine, aux, demand


def _assignment_permutation(choices_flat: jnp.ndarray, e: int):
    """Static-shape counting sort of the (N*k,) expert assignments.

    Returns (counts (E,) int32, dest (N*k,) int32, inv (N*k,) int32):
    assignment a lands at row dest[a] of the expert-sorted buffer, and
    sorted row r holds assignment inv[r]. Pure cumsum arithmetic — no
    lax.sort, no scatter with duplicate indices (inv's scatter writes a
    permutation, which XLA lowers as a gather of the inverse)."""
    nk = choices_flat.shape[0]
    onehot = jax.nn.one_hot(choices_flat, e, dtype=jnp.int32)   # (Nk, E)
    counts = jnp.sum(onehot, axis=0)                            # (E,)
    offsets = jnp.cumsum(counts) - counts                       # exclusive
    pos_in_expert = jnp.cumsum(onehot, axis=0) - onehot         # (Nk, E)
    dest = (
        jnp.sum(pos_in_expert * onehot, axis=-1) + offsets[choices_flat]
    ).astype(jnp.int32)
    # inv from ONE stable sort: counting-sort order IS (expert, arrival)
    # order, which a stable sort by expert id reproduces exactly
    _, inv = jax.lax.sort_key_val(
        choices_flat, jnp.arange(nk, dtype=jnp.int32)
    )
    return counts, dest, inv


def _slot_tables(choices, positions, keeps, e: int, capacity: int):
    """Invert the (token -> slot) routing into per-slot lookup tables.

    Returns (slot_token (G, E*C) int32, slot_round (G, E*C) int32,
    slot_mask (G, E*C) fp32, dest (G, T, k) int32 — each assignment's
    flat slot, E*C for dropped). Dropped assignments scatter into a
    spare trailing column so they can never collide with a live slot.
    The scatters move 3*k*T int32-sized elements per group — index
    metadata, not rows; the row traffic all rides gathers (the point
    of this path)."""
    g, t, k = choices.shape
    ec = e * capacity
    dest = choices * capacity + positions                  # (G, T, k)
    dest = jnp.where(keeps > 0, dest, ec).astype(jnp.int32)
    gi = jnp.arange(g, dtype=jnp.int32)[:, None, None]
    ti = jnp.broadcast_to(
        jnp.arange(t, dtype=jnp.int32)[None, :, None], (g, t, k)
    )
    ri = jnp.broadcast_to(
        jnp.arange(k, dtype=jnp.int32)[None, None, :], (g, t, k)
    )
    gi = jnp.broadcast_to(gi, (g, t, k))
    slot_token = jnp.zeros((g, ec + 1), jnp.int32).at[gi, dest].set(
        ti, mode="drop"
    )[:, :ec]
    slot_round = jnp.zeros((g, ec + 1), jnp.int32).at[gi, dest].set(
        ri, mode="drop"
    )[:, :ec]
    slot_mask = jnp.zeros((g, ec + 1), jnp.float32).at[gi, dest].set(
        1.0, mode="drop"
    )[:, :ec]
    return slot_token, slot_round, slot_mask, dest


@jax.custom_vjp
def _dispatch_gather(x, slot_token, slot_mask, dest):
    """xin[g, s] = x[g, slot_token[g, s]] * slot_mask[g, s].

    Forward is one batched row gather over the token dim; the custom
    backward is k row gathers (dx[g, t] = sum_j dxin[g, dest[g, t, j]],
    with dropped assignments pointing at the masked spare slot) instead
    of the scatter-add autodiff would emit."""
    del dest
    xin = jnp.take_along_axis(x, slot_token[..., None], axis=1)
    return xin * slot_mask[..., None].astype(xin.dtype)


def _dispatch_gather_fwd(x, slot_token, slot_mask, dest):
    return _dispatch_gather(x, slot_token, slot_mask, dest), dest


def _dispatch_gather_bwd(dest, g_out):
    # pad a zero spare slot so dropped assignments (dest == E*C) read 0
    gz = jnp.pad(g_out, ((0, 0), (0, 1), (0, 0)))
    k = dest.shape[-1]
    dx = jnp.take_along_axis(gz, dest[..., 0, None], axis=1)
    for j in range(1, k):
        dx = dx + jnp.take_along_axis(gz, dest[..., j, None], axis=1)
    return dx, None, None, None


_dispatch_gather.defvjp(_dispatch_gather_fwd, _dispatch_gather_bwd)


@jax.custom_vjp
def _combine_gather(out, w, dest, slot_token, slot_round, slot_mask):
    """y[g, t] = sum_j w[g, t, j] * out[g, dest[g, t, j]].

    Gather-only in both directions: the backward for `out` reads
    gy rows back through the slot tables (d out[g, s] =
    gy[g, slot_token[g, s]] * w[g, slot_token, slot_round] * mask) and
    the backward for `w` is k gathers + row dots."""
    del slot_token, slot_round, slot_mask
    k = dest.shape[-1]
    oz = jnp.pad(out, ((0, 0), (0, 1), (0, 0)))
    y = jnp.take_along_axis(oz, dest[..., 0, None], axis=1) * (
        w[..., 0, None].astype(out.dtype)
    )
    for j in range(1, k):
        y = y + jnp.take_along_axis(oz, dest[..., j, None], axis=1) * (
            w[..., j, None].astype(out.dtype)
        )
    return y


def _combine_gather_fwd(out, w, dest, slot_token, slot_round, slot_mask):
    y = _combine_gather(out, w, dest, slot_token, slot_round, slot_mask)
    return y, (out, w, dest, slot_token, slot_round, slot_mask)


def _combine_gather_bwd(res, gy):
    out, w, dest, slot_token, slot_round, slot_mask = res
    k = dest.shape[-1]
    # d out: route each slot back to its token's cotangent row, scaled
    # by that slot's combine weight (pure indexing of residuals)
    w_slot = jnp.take_along_axis(
        w.reshape(w.shape[0], -1),
        (slot_token * k + slot_round), axis=1,
    ) * slot_mask                                           # (G, E*C)
    dout = jnp.take_along_axis(gy, slot_token[..., None], axis=1) * (
        w_slot[..., None].astype(gy.dtype)
    )
    oz = jnp.pad(out, ((0, 0), (0, 1), (0, 0)))
    dw = jnp.stack(
        [
            jnp.sum(
                gy * jnp.take_along_axis(oz, dest[..., j, None], axis=1),
                axis=-1,
            )
            for j in range(k)
        ],
        axis=-1,
    ).astype(w.dtype)
    return dout, dw, None, None, None, None


_combine_gather.defvjp(_combine_gather_fwd, _combine_gather_bwd)


# largest tile <= target that divides dim exactly — megablox rejects
# non-dividing m tiles, and small test shapes would otherwise reject the
# tuned production tiles (one definition, shared with the fused encoder)
from ddp_practice_tpu.ops.fused_encoder import _fit_tile  # noqa: E402


def _gmm_tiling(m: int, k: int, n: int):
    """v5e-tuned megablox tiling for the sorted path's grouped matmuls.

    The megablox default (128, 128, 128) ran the lm_moe shapes at ~11
    TFLOP/s — each tiny k-tile re-streams operands. Full-contraction k
    tiles with 512-wide m/n tiles measured 4-6x faster
    (experiments/gmm_tune.py: (m=32k, k=768, n=3072) 70 TF/s at
    (512, 768, 512); (m=32k, k=3072, n=768) 42 TF/s at
    (512, 3072, 768) — both within ~2% of the dense-matmul rate of the
    same FLOPs). Keyed by each CALL's effective dims, so forward and
    the two backward directions each get their own shape's optimum."""
    return (
        _fit_tile(m, 512), min(k, 3072),
        _fit_tile(n, n if n <= 768 else 512),
    )


def _mb_gmm(lhs, rhs, gs, *, transpose_rhs: bool, interpret: bool):
    # from-import of the SUBMODULE path: the package __init__ exports a
    # custom_vjp FUNCTION named gmm that shadows the gmm submodule, so
    # `megablox.gmm` attribute access raises — and tgmm is not
    # re-exported at all
    from jax.experimental.pallas.ops.tpu.megablox.gmm import gmm as raw_gmm

    k_dim = rhs.shape[2] if transpose_rhs else rhs.shape[1]
    n_dim = rhs.shape[1] if transpose_rhs else rhs.shape[2]
    tiling = _gmm_tiling(lhs.shape[0], k_dim, n_dim)
    return raw_gmm(
        lhs, rhs, gs, lhs.dtype, tiling, None, None, transpose_rhs,
        interpret,
    )


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _grouped_matmul(lhs, rhs, group_sizes, interpret):
    """Differentiable grouped matmul over expert-sorted rows.

    A thin re-wrap of megablox gmm/tgmm (jax.experimental.pallas)
    ONLY so each autodiff direction picks its own tuned tiling — the
    stock jax wrapper threads one tiling through forward, grad-lhs,
    and tgmm, and no single tuple is good for all three shapes (the
    measured spread is 4x; _gmm_tiling)."""
    return _mb_gmm(lhs, rhs, group_sizes, transpose_rhs=False,
                   interpret=interpret)


def _grouped_matmul_fwd(lhs, rhs, group_sizes, interpret):
    out = _mb_gmm(lhs, rhs, group_sizes, transpose_rhs=False,
                  interpret=interpret)
    return out, (lhs, rhs, group_sizes)


def _grouped_matmul_bwd(interpret, res, g):
    from jax.experimental.pallas.ops.tpu.megablox.gmm import tgmm

    lhs, rhs, gs = res
    dlhs = _mb_gmm(g, rhs, gs, transpose_rhs=True, interpret=interpret)
    # dW: tgmm((k, m), (m, n)) -> (e, k, n). tgmm's tiling is
    # (contraction m, k, n). Measured (experiments/gmm_tune.py): small-k
    # dW (w_in-like) peaks at (512, k, 512) = 51 TF/s and larger
    # contraction tiles fail to compile there; wide-k dW (w_out-like)
    # peaks at (2048, 1024, n) = 39 TF/s
    m_dim = lhs.shape[0]
    if lhs.shape[1] <= 1024:
        tiling = (
            _fit_tile(m_dim, 512), lhs.shape[1],
            _fit_tile(g.shape[1], 512),
        )
    else:
        tiling = (
            _fit_tile(m_dim, 2048), 1024, _fit_tile(g.shape[1], 768),
        )
    drhs = tgmm(
        lhs.swapaxes(0, 1), g, gs, rhs.dtype, tiling, None,
        rhs.shape[0], interpret=interpret,
    )
    return dlhs, drhs, None


_grouped_matmul.defvjp(_grouped_matmul_fwd, _grouped_matmul_bwd)


@jax.custom_vjp
def _dispatch_rows(xf, tok, dest_nk):
    """Expert-sort gather: row r of the output is token tok[r]'s vector.

    Custom VJP so NEITHER direction is a TPU scatter: the forward is a
    row gather, and the cotangent of token n is the sum of its k sorted
    rows — dest_nk (N, k) holds exactly those row ids, so the backward
    is k gathers + adds instead of a 2N-way scatter-add."""
    del dest_nk
    return xf[tok]


def _dispatch_rows_fwd(xf, tok, dest_nk):
    return xf[tok], (tok, dest_nk)


def _dispatch_rows_bwd(res, g):
    tok, dest_nk = res
    k = dest_nk.shape[1]
    dxf = g[dest_nk[:, 0]]
    for j in range(1, k):
        dxf = dxf + g[dest_nk[:, j]]
    return dxf, None, None


_dispatch_rows.defvjp(_dispatch_rows_fwd, _dispatch_rows_bwd)


@jax.custom_vjp
def _combine_rows(out, cgates, tok, dest_nk, inv):
    """Weighted un-sort: y[n] = sum_j cgates[n, j] * out[dest_nk[n, j]].

    Forward is k row gathers + fma. Backward stays gather-only too:
    d out[r] = gy[tok[r]] * cgates.flat[inv[r]] (row gather x scalar),
    d cgates[n, j] = <gy[n], out[dest_nk[n, j]]> (gather + rowwise dot).
    """
    del tok, inv
    k = dest_nk.shape[1]
    y = out[dest_nk[:, 0]] * cgates[:, 0, None]
    for j in range(1, k):
        y = y + out[dest_nk[:, j]] * cgates[:, j, None]
    return y


def _combine_rows_fwd(out, cgates, tok, dest_nk, inv):
    return _combine_rows(out, cgates, tok, dest_nk, inv), (
        out, cgates, tok, dest_nk, inv,
    )


def _combine_rows_bwd(res, gy):
    out, cgates, tok, dest_nk, inv = res
    gate_sorted = cgates.reshape(-1)[inv]                       # (Nk,)
    dout = gy[tok] * gate_sorted[:, None].astype(gy.dtype)
    dc = [
        jnp.sum(gy * out[dest_nk[:, j]], axis=-1)
        for j in range(dest_nk.shape[1])
    ]
    dcgates = jnp.stack(dc, axis=-1).astype(cgates.dtype)
    return dout, dcgates, None, None, None


_combine_rows.defvjp(_combine_rows_fwd, _combine_rows_bwd)


@jax.custom_vjp
def _bias_rows(b, sorted_expert, onehot_sorted):
    """Per-row expert bias gather b[sorted_expert] with a dense-matmul
    backward: db = onehot_sorted^T @ g — an (E, rows) x (rows, F) dot on
    the MXU instead of a rows->E scatter-add."""
    del onehot_sorted
    return b[sorted_expert]


def _bias_rows_fwd(b, sorted_expert, onehot_sorted):
    # zero-size dtype token: custom_vjp residuals must be JAX types
    return b[sorted_expert], (onehot_sorted, jnp.zeros((0,), b.dtype))


def _bias_rows_bwd(res, g):
    onehot_sorted, dtype_token = res
    db = jax.lax.dot_general(
        onehot_sorted.astype(jnp.float32), g.astype(jnp.float32),
        (((0,), (0,)), ((), ())),
    )
    return db.astype(dtype_token.dtype), None, None


_bias_rows.defvjp(_bias_rows_fwd, _bias_rows_bwd)


class MoEMlp(nn.Module):
    """Expert-parallel MLP: drop-in for a dense transformer MLP block."""

    num_experts: int = 8
    top_k: int = 2
    capacity_factor: float = 1.25
    mlp_dim: int = 768
    aux_loss_weight: float = 0.01
    # aux-free online balancing (the DeepSeek-V3 scheme): a NON-LEARNED
    # per-expert bias nudges SELECTION (never combine weights) against
    # measured overload each training step: b -= rate * sign(demand -
    # 1/E). Unlike the gradient aux loss, it acts on the argmax directly,
    # so it balances even when hidden states share a dominant common-mode
    # direction (measured: the aux loss alone plateaued at ~10% drops and
    # OSCILLATED when strengthened — BENCHMARKS.md round-4 MoE section).
    # Lives in "batch_stats" so it rides the existing non-param state
    # plumbing (train/steps.py, checkpointing). 0 disables.
    bias_update_rate: float = 0.02
    # tokens per routing group. 0 = one group per leading-dim row (the
    # whole sequence — the GShard default). Smaller groups cut the
    # dispatch/combine einsum cost, which is O(group_size) PER TOKEN
    # (the one-hot contracts t x (E*C) with C ∝ group_size): at lm_moe
    # shape, group 2048 -> 256 is ~8x less dispatch matmul. The price is
    # capacity granularity: per-group demand varies more, so pair small
    # groups with the strided interleave below and a measured capacity
    # factor (BENCHMARKS.md round-4 MoE section).
    group_size: int = 0
    # interleave-stride the sequence into groups (with n_sub = seq /
    # group_size groups per sequence, group j takes tokens {j, j+n_sub,
    # j+2*n_sub, ...}): adjacent tokens — which share local context
    # and crowd the same experts — land in DIFFERENT groups, so
    # per-group demand concentrates less than contiguous chunks at the
    # same size. Shard-safe: the transpose is within one sequence
    # (leading dim untouched), so dp sharding never moves.
    group_stride: bool = True
    dtype: jnp.dtype = jnp.float32
    param_dtype: jnp.dtype = jnp.float32
    expert_axis: Optional[str] = MeshConfig.AXIS_EXPERT
    # expert-compute implementation:
    #   "einsum" — the GShard dense one-hot dispatch/combine einsums with
    #     capacity dropping: shardable over the 'expert' mesh axis (the
    #     sharding constraints lower to all-to-alls), the multichip path.
    #   "gather" — same capacity/grouping semantics, but dispatch and
    #     combine are index GATHERS through per-slot lookup tables
    #     (custom VJPs keep the backward gather-only too) while the
    #     expert MLP stays the dense batched einsum. Measured SLOWER
    #     than einsum at the lm_moe bench shape (31.9% vs 37.7% MFU):
    #     XLA lowers a TPU row gather at ~0.25-0.5 ms per (32k, 768)
    #     pass and this path needs ~8 per layer, while the one-hot
    #     dispatch matmuls it replaces cost ~1 ms/layer once routing
    #     groups shrink them. Kept for the regime that inverts the
    #     tradeoff (capacity >> group_size, where one-hot tensors
    #     explode quadratically but gathers stay linear).
    #   "sorted" — dropless counting-sort + grouped matmul (megablox gmm
    #     Pallas kernels, v5e-tuned tilings): no capacity padding at
    #     all (exactly k*N expert rows). Also measured BELOW einsum —
    #     XLA's dense batched expert einsum reaches ~103-139 TF/s where
    #     gmm peaks at ~70/42 (experiments/gmm_tune.py) — but it is the
    #     only drop-free top-k path, and wins when capacity waste
    #     dominates (high cf or skewed loads).
    #   "auto" (default) — einsum everywhere, by measurement: the
    #     GShard dense-linear-algebra design IS the TPU-native answer
    #     at production shapes (BENCHMARKS.md round-5 MoE section
    #     records the full gather/sorted shootout).
    impl: str = "auto"
    # routing scheme:
    #   "topk" — tokens choose experts (GShard/Switch): the default;
    #     needs the aux loss + balancing bias, pays capacity padding
    #     (cf x active FLOPs executed) and drops overflow tokens.
    #   "expert_choice" — experts choose tokens (expert_choice_gating):
    #     perfect balance, zero drops, zero padding by construction —
    #     executed == active FLOPs at cf 1.0, the TPU-efficiency
    #     choice. Training-time routing sees the whole routing group
    #     (causality caveat in the gating docstring).
    router: str = "topk"

    @nn.compact
    def __call__(self, x: jnp.ndarray, *, decode: bool = False
                 ) -> jnp.ndarray:  # (G, T, D)
        impl = self.impl
        if impl == "auto":
            impl = "einsum"
        elif impl not in ("einsum", "gather", "sorted"):
            raise ValueError(
                f"moe impl {impl!r} (want 'auto'|'einsum'|'gather'|"
                "'sorted')"
            )
        if self.router not in ("topk", "expert_choice"):
            raise ValueError(
                f"moe router {self.router!r} (want 'topk'|'expert_choice')"
            )
        if self.router == "expert_choice" and impl != "einsum":
            raise ValueError(
                "expert_choice routing runs on the einsum path (its "
                "dispatch is already dense and padding-free); pass "
                "impl='auto'/'einsum'"
            )
        if impl == "sorted" and not self.is_initializing():
            return self._sorted(x)
        if impl == "gather" and not self.is_initializing():
            return self._gather(x)
        return self._einsum(x, decode=decode)

    def _group(self, x):
        """Apply the routing-group reshape (see group_size/group_stride);
        returns (grouped x, n_sub)."""
        g0, t0, d = x.shape
        if not 0 < self.group_size < t0:
            return x, 1
        if t0 % self.group_size:
            raise ValueError(
                f"moe group_size {self.group_size} must divide the "
                f"sequence length {t0}"
            )
        n_sub = t0 // self.group_size
        if self.group_stride:
            # (g0, t0, d) -> (g0 * n_sub, group_size, d), group j of
            # a sequence = tokens {j, j + n_sub, ...}
            x = x.reshape(g0, self.group_size, n_sub, d)
            x = jnp.swapaxes(x, 1, 2)
        return x.reshape(g0 * n_sub, self.group_size, d), n_sub

    def _ungroup(self, y, g0, t0, n_sub):
        if n_sub <= 1:
            return y
        d = y.shape[-1]
        if self.group_stride:
            y = y.reshape(g0, n_sub, self.group_size, d)
            y = jnp.swapaxes(y, 1, 2)
        return y.reshape(g0, t0, d)

    def _gather(self, x: jnp.ndarray) -> jnp.ndarray:
        """Capacity-layout expert compute with index-gather glue.

        Identical routing semantics to the einsum path (same groups,
        same capacity drops, same combine weights — pinned by
        tests/test_moe.py equality tests) but the (G,T,E,C) one-hot
        dispatch/combine tensors never exist: per-slot lookup tables
        (_slot_tables) drive row gathers into the (G,E,C,D) buffer and
        back, with custom VJPs that stay gather-only. The expert MLP
        keeps the dense batched einsum — measured ~139 TF/s on v5e,
        2-3x any grouped-matmul kernel at this shape."""
        g0, t0, d = x.shape
        self._warn_oversized_group(t0)
        x, n_sub = self._group(x)
        g, t, _ = x.shape
        e, f, k = self.num_experts, self.mlp_dim, self.top_k
        capacity = max(1, int(self.capacity_factor * k * t / e))
        router = nn.Dense(
            e,
            dtype=jnp.float32,
            param_dtype=self.param_dtype,
            use_bias=False,
            name="router",
        )
        logits = router(x.astype(jnp.float32))               # (G, T, E)
        bias = self._router_bias(e)
        choices, positions, keeps, gsel, aux, demand = top_k_routing(
            logits, k=k, capacity=capacity,
            routing_bias=None if bias is None else bias.value,
        )
        self._update_bias(bias, demand, e)
        self.sow("intermediates", "moe_aux_loss", self.aux_loss_weight * aux)
        routed = jnp.sum(keeps)
        load = jnp.sum(
            jax.nn.one_hot(choices, e, dtype=jnp.float32)
            * keeps[..., None],
            axis=(0, 1, 2),
        )
        self.sow(
            "intermediates", "moe_load_frac",
            load / jnp.maximum(routed, 1.0),
        )
        self.sow(
            "intermediates", "moe_drop_rate",
            1.0 - routed / (k * g * t),
        )

        slot_token, slot_round, slot_mask, dest = _slot_tables(
            choices, positions, keeps, e, capacity
        )
        w_in, b_in, w_out, b_out = self._expert_params(d, e, f)
        cd = self.dtype
        xin = _dispatch_gather(
            x.astype(cd), slot_token, slot_mask.astype(cd), dest
        )                                                    # (G, E*C, D)
        xin = xin.reshape(g, e, capacity, d)
        h = jnp.einsum("gecd,edf->gecf", xin, w_in.astype(cd))
        h = nn.gelu(h + b_in.astype(cd)[None, :, None, :])
        out = jnp.einsum("gecf,efd->gecd", h, w_out.astype(cd))
        out = out + b_out.astype(cd)[None, :, None, :]
        w = jnp.take_along_axis(gsel, choices, axis=-1) * keeps  # (G,T,k)
        y = _combine_gather(
            out.reshape(g, e * capacity, d), w.astype(cd), dest,
            slot_token, slot_round, slot_mask,
        )
        return self._ungroup(y, g0, t0, n_sub).astype(x.dtype)

    def _router_bias(self, e: int):
        """The aux-free balancing bias variable, shared by both paths.

        decode/eval paths may apply without the batch_stats collection
        (generate.py builds variables from params + cache only): route
        with no bias there — selection then follows the raw gates,
        which the aux loss keeps roughly balanced."""
        if self.is_initializing() or self.has_variable(
            "batch_stats", "router_bias"
        ):
            return self.variable(
                "batch_stats", "router_bias",
                lambda: jnp.zeros((e,), jnp.float32),
            )
        return None

    def _update_bias(self, bias, demand, e: int):
        if bias is not None and self.is_mutable_collection(
            "batch_stats"
        ) and self.bias_update_rate > 0.0:
            bias.value = jax.lax.stop_gradient(
                bias.value - self.bias_update_rate
                * jnp.sign(demand - 1.0 / e)
            )

    def _expert_params(self, d: int, e: int, f: int):
        w_in = self.param(
            "expert_w_in",
            nn.initializers.lecun_normal(batch_axis=(0,)),
            (e, d, f),
            self.param_dtype,
        )
        b_in = self.param(
            "expert_b_in", nn.initializers.zeros, (e, f), self.param_dtype
        )
        w_out = self.param(
            "expert_w_out",
            nn.initializers.lecun_normal(batch_axis=(0,)),
            (e, f, d),
            self.param_dtype,
        )
        b_out = self.param(
            "expert_b_out", nn.initializers.zeros, (e, d), self.param_dtype
        )
        return w_in, b_in, w_out, b_out

    def _sorted(self, x: jnp.ndarray) -> jnp.ndarray:
        """Dropless sorted expert compute (single device).

        Tokens flatten to (N, D); the k assignments counting-sort by
        expert (dest by cumsum arithmetic, inv by one stable
        lax.sort_key_val — no scatters); the expert MLP runs as TWO
        grouped matmuls over the ragged (N*k, ·) buffer (megablox gmm —
        jax.experimental.pallas.ops.tpu.megablox, fp32 accumulation);
        combine gathers each token's k rows back with renormalized
        gates. Router health/aux/bias machinery is shared with the
        einsum path; drop rate is exactly 0 by construction.

        group_size/group_stride are deliberately NOT applied here:
        routing groups exist to scope CAPACITY competition (which
        tokens crowd each other out of an expert's buffer), and the
        dropless path has no capacity — per-token top-k choices, and
        therefore the output, demand statistics, and balance-bias
        updates, are identical with or without the group reshape, so
        applying it would only pay the strided transpose's HBM
        traffic for nothing."""
        g0, t0, d = x.shape
        e, f, k = self.num_experts, self.mlp_dim, self.top_k
        n = g0 * t0
        xf = x.reshape(n, d)
        router = nn.Dense(
            e,
            dtype=jnp.float32,
            param_dtype=self.param_dtype,
            use_bias=False,
            name="router",
        )
        logits = router(xf.astype(jnp.float32))              # (N, E)
        bias = self._router_bias(e)
        choices, cgates, aux, demand = topk_choices(
            logits, k=k, routing_bias=None if bias is None else bias.value,
        )
        self._update_bias(bias, demand, e)
        self.sow("intermediates", "moe_aux_loss", self.aux_loss_weight * aux)

        cf = choices.reshape(n * k)
        counts, dest, inv = _assignment_permutation(cf, e)
        dest_nk = dest.reshape(n, k)
        tok = inv // k
        self.sow(
            "intermediates", "moe_load_frac",
            counts.astype(jnp.float32) / (k * n),
        )
        self.sow(
            "intermediates", "moe_drop_rate", jnp.zeros((), jnp.float32)
        )

        w_in, b_in, w_out, b_out = self._expert_params(d, e, f)
        cd = self.dtype
        interpret = jax.default_backend() == "cpu"
        sorted_expert = cf[inv]
        onehot_sorted = jax.nn.one_hot(sorted_expert, e, dtype=cd)
        x_sorted = _dispatch_rows(xf.astype(cd), tok, dest_nk)
        h = _grouped_matmul(x_sorted, w_in.astype(cd), counts, interpret)
        h = nn.gelu(h + _bias_rows(b_in.astype(cd), sorted_expert,
                                   onehot_sorted))
        out = _grouped_matmul(h, w_out.astype(cd), counts, interpret)
        out = out + _bias_rows(b_out.astype(cd), sorted_expert,
                               onehot_sorted)
        y = _combine_rows(out, cgates.astype(cd), tok, dest_nk, inv)
        return y.reshape(g0, t0, d).astype(x.dtype)

    def _warn_oversized_group(self, t0: int) -> None:
        """A group larger than the sequence cannot exist; routing falls
        back to whole-sequence, whose capacity behavior differs from
        what the group-tuned capacity factor was calibrated for
        (advisor round 4). Warn, don't raise — and only on the
        TRAINING path (mutable batch_stats, like the router-bias
        update): short inputs are NORMAL in decode/prefill (t0 =
        prompt length or 1 — inference.py drives this module with
        the training group_size) and must stay silent. The training
        signal is a mutable "intermediates" collection (the metric
        sows) — NOT batch_stats, which expert-choice models don't
        create at all."""
        if (self.group_size > t0 and not self.is_initializing()
                and self.is_mutable_collection("intermediates")):
            import warnings

            warnings.warn(
                f"moe group_size {self.group_size} exceeds the sequence "
                f"length {t0}: routing whole-sequence — pass 0 or a "
                "divisor of the sequence length",
                stacklevel=2,
            )

    def _einsum(self, x: jnp.ndarray, *, decode: bool = False
                ) -> jnp.ndarray:
        g0, t0, d = x.shape
        self._warn_oversized_group(t0)
        x, n_sub = self._group(x)
        g, t, d = x.shape
        e, f = self.num_experts, self.mlp_dim
        capacity = max(
            1, int(self.capacity_factor * self.top_k * t / e)
        )

        router = nn.Dense(
            e,
            dtype=jnp.float32,
            param_dtype=self.param_dtype,
            use_bias=False,
            name="router",
        )
        logits = router(x.astype(jnp.float32))               # (G, T, E)
        if self.router == "expert_choice" and not decode:
            # experts pick tokens: full buffers, no aux loss, no
            # balancing bias — the imbalance-fighting machinery has
            # nothing to do (expert_choice_gating docstring). Capacity
            # clamps to the group token count: small groups / few
            # experts make cf*k*T/E exceed T, and an expert cannot
            # pick more tokens than exist.
            dispatch, combine, uncovered = expert_choice_gating(
                logits, capacity=min(capacity, t)
            )
            self.sow(
                "intermediates", "moe_aux_loss", jnp.zeros((), jnp.float32)
            )
            # the quality-relevant analogue of the drop rate: tokens no
            # expert picked (they ride the residual unchanged). Capacity
            # drops are zero by construction; this reports coverage.
            self.sow("intermediates", "moe_drop_rate", uncovered)
        else:
            if self.router == "expert_choice":
                # KV-cache decode: expert choice has no serving story of
                # its own (with T=1 every expert would pick the lone
                # token — E/k the trained compute, different function).
                # Use the standard EC serving approximation: per-token
                # top-k over the gates, capacity = t so nothing drops.
                # Combine with the RAW gates at the picked experts —
                # EC training combines with raw gates, so reusing
                # top_k_gating's renormalized weights would rescale
                # every MoE branch by ~1/(sum of picked gates) at
                # serve time. A train/infer expert-selection mismatch
                # is inherent to EC (Zhou et al. 2022 §3.2 /
                # Mixture-of-Depths §inference discuss predictors);
                # token-choice routing is the option without it.
                dispatch, _combine, _aux, _demand = top_k_gating(
                    logits, k=self.top_k, capacity=t, routing_bias=None,
                )
                gates = jax.nn.softmax(logits.astype(jnp.float32), -1)
                combine = dispatch * gates[..., None]
            else:
                bias = self._router_bias(e)
                dispatch, combine, aux, demand = top_k_gating(
                    logits, k=self.top_k, capacity=capacity,
                    routing_bias=None if bias is None else bias.value,
                )
                self._update_bias(bias, demand, e)
                self.sow(
                    "intermediates", "moe_aux_loss",
                    self.aux_loss_weight * aux,
                )
                # the fraction of the k*T slots lost to capacity drops
                # (diagnostic sows — no "aux_loss" in the name, so they
                # never join the objective; train/steps.py surfaces
                # them as moe_* metrics)
                self.sow(
                    "intermediates", "moe_drop_rate",
                    1.0 - jnp.sum(dispatch) / (self.top_k * g * t),
                )
        # per-expert share of ROUTED tokens — shared router-health sow
        routed = jnp.sum(dispatch)
        self.sow(
            "intermediates", "moe_load_frac",
            jnp.sum(dispatch, axis=(0, 1, 3)) / jnp.maximum(routed, 1.0),
        )

        w_in, b_in, w_out, b_out = self._expert_params(d, e, f)

        ax = self.expert_axis
        cdtype = self.dtype
        xin = jnp.einsum(
            "gtec,gtd->egcd", dispatch.astype(cdtype), x.astype(cdtype)
        )
        xin = _constrain(xin, (ax, MeshConfig.AXIS_DATA, None, None))
        h = jnp.einsum("egcd,edf->egcf", xin, w_in.astype(cdtype))
        h = nn.gelu(h + b_in.astype(cdtype)[:, None, None, :])
        out = jnp.einsum("egcf,efd->egcd", h, w_out.astype(cdtype))
        out = out + b_out.astype(cdtype)[:, None, None, :]
        out = _constrain(out, (ax, MeshConfig.AXIS_DATA, None, None))
        y = jnp.einsum("gtec,egcd->gtd", combine.astype(cdtype), out)
        return self._ungroup(y, g0, t0, n_sub).astype(x.dtype)
