"""Attention ops.

No attention exists in the reference (its model is a 2-conv CNN,
origin_main.py:9-31); this implements the transformer path of the model
ladder. Two execution paths:

- fused single-device/GSPMD path: plain jnp softmax attention, fp32
  accumulation, fused by XLA onto the MXU.
- sequence-parallel path: `parallel.ring.ring_attention` — blockwise
  attention with online softmax, K/V blocks rotated around the 'seq' mesh
  axis with `lax.ppermute` (ring attention; long-context first-class).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

# Decode query-broadcast tuning, measured on TPU v5e (2026-07-30 profile,
# BENCHMARKS.md decode section): 8 = the sublane width (smallest MXU row
# tile); b <= 16 because at larger batches the batch dim already feeds
# the vector units and the 8x score/prob tensors cost more than the
# matvec saves (measured 2x SLOWER at bs 64). Other chips may warrant
# different values — they are constants, not hardware-derived.
_Q8_ROWS = 8
_Q8_MAX_BATCH = 16


def dot_product_attention(
    q: jnp.ndarray,  # (batch, seq, heads, head_dim)
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    causal: bool = False,
    seq_axis: Optional[str] = None,
    sp_impl: str = "ring",
    impl: str = "xla",
) -> jnp.ndarray:
    """Multi-head attention; dispatches to a sequence-parallel scheme when
    `seq_axis` names a mesh axis the sequence dimension is sharded over:
    "ring" (K/V rotation, extreme lengths) or "ulysses" (all-to-all head
    scatter, maximally fused local attention). `impl` picks the local
    kernel: "xla" (fused by the XLA compiler) or "flash" (the Pallas
    tiled online-softmax kernel, ops.flash_attention) — and composes with
    both sequence-parallel schemes (flash runs as the per-block local
    attention inside ring, and as the full-sequence attention after
    Ulysses' head scatter)."""
    if impl not in ("xla", "flash"):
        raise ValueError(f"unknown attention impl {impl!r} (want 'xla'|'flash')")
    if seq_axis is not None:
        if sp_impl == "ring":
            from ddp_practice_tpu.parallel.ring import ring_attention

            return ring_attention(
                q, k, v, axis_name=seq_axis, causal=causal, impl=impl
            )
        if sp_impl == "ulysses":
            from ddp_practice_tpu.parallel.ulysses import ulysses_attention

            return ulysses_attention(
                q, k, v, axis_name=seq_axis, causal=causal, impl=impl
            )
        raise ValueError(f"unknown sp_impl {sp_impl!r} (want 'ring'|'ulysses')")
    if impl == "flash":
        from ddp_practice_tpu.ops.flash_attention import flash_attention

        return flash_attention(q, k, v, causal=causal)
    return _attention(q, k, v, causal=causal)


def attention_with_mask(q, k, v, mask) -> jnp.ndarray:
    """Attention under an explicit boolean mask (True = attend).

    `mask` broadcasts against scores (b, h, sq, sk); a 2D (sq, sk) mask is
    promoted. This is the KV-cache decode path (models/vit.py SelfAttention
    `decode=True`): the query block sits at a dynamic offset inside a
    pre-allocated key/value buffer, so validity is position arithmetic, not
    a static triangle.
    """
    if mask.ndim == 2:
        mask = mask[None, None]
    if (
        q.shape[1] == 1
        and q.shape[0] <= _Q8_MAX_BATCH
        and jax.default_backend() != "cpu"
    ):
        # small-batch single-token decode steps: a 1-row query makes both
        # attention contractions matvecs, which XLA lowers to VPU
        # multiply-reduce loop fusions at ~1/5 of HBM bandwidth — 81% of
        # the decode step in the bs=8 profile (BENCHMARKS.md).
        # Since round 4 the hot single-token path uses the packed Pallas
        # decode kernel (ops/decode_attention.py) instead; this broadcast
        # remains for unpackable head shapes. Skipped on the CPU backend,
        # where there is no MXU and the 8x score/prob inflation was never
        # measured to pay for itself (tests still pin the branch's
        # numerics by calling _q8_attention directly).
        return _q8_attention(q, k, v, mask)
    return _attention(q, k, v, causal=False, mask=mask)


def _q8_attention(q, k, v, mask) -> jnp.ndarray:
    """Single-token attention with the query broadcast to _Q8_ROWS
    sublane rows so both contractions are real MXU matmuls; rows 1..n
    compute the identical result and are discarded — FLOPs are free in a
    bandwidth-bound decode step."""
    q8 = jnp.broadcast_to(q, (q.shape[0], _Q8_ROWS) + q.shape[2:])
    return _attention(q8, k, v, causal=False, mask=mask)[:, :1]


def _attention(q, k, v, *, causal: bool, mask=None) -> jnp.ndarray:
    in_dtype = q.dtype
    head_dim = q.shape[-1]
    scale = 1.0 / jnp.sqrt(jnp.asarray(head_dim, jnp.float32))
    # (b, s, h, d) -> scores (b, h, sq, sk), accumulate in fp32
    scores = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * scale
    if causal:
        sq, sk = scores.shape[-2], scores.shape[-1]
        tri = jnp.tril(jnp.ones((sq, sk), dtype=bool), k=sk - sq)
        scores = jnp.where(tri, scores, jnp.asarray(-1e30, scores.dtype))
    if mask is not None:
        scores = jnp.where(mask, scores, jnp.asarray(-1e30, scores.dtype))
    probs = jnp.exp(
        scores - jnp.max(scores, axis=-1, keepdims=True)
    )
    probs = probs / jnp.sum(probs, axis=-1, keepdims=True)
    out = jnp.einsum(
        "bhqk,bkhd->bqhd", probs.astype(in_dtype), v,
        preferred_element_type=jnp.float32,
    )
    return out.astype(in_dtype)
