"""Single-token KV-cache decode attention as a packed Pallas TPU kernel.

Why this exists (round 4, measured): the decode KV cache used to be stored
as (b, L, h, head_dim). On TPU that shape's minor dims (h=12, hd=64) are
tile-padded, and XLA cannot update such a buffer in place — every
per-token `dynamic_update_slice` lowered to a full cache relayout copy,
53.6% of the bs=8 decode step (experiments/decode_profile.py). Probing
update patterns (experiments/decode_layouts.py) showed in-place DUS DOES
engage when the dynamic index is on a major dim and the minor dims are
unpadded: a FLAT (b, L, h*hd) cache updates in 0.2 us instead of 24 us.

XLA attention cannot consume the flat cache per head without a reshape
(which re-introduces the relayout), but a Pallas kernel can — the same
trick as ops/flash_attention.py's packed family: the kv tile is a
(block_l, h*hd) slice of the UNTRANSPOSED cache and the kernel walks
heads via 64-aligned column slices. So decode runs:

    cache: flat (b, L, h*hd), written in place by dynamic_update_slice
    step attention: this kernel, directly on the flat cache

Kernel structure — grid (batch, L-blocks), one cell covers ALL heads (a
head-split grid dim would multiply DMA cell count; the head walk is a
python-unrolled loop over column slices):

    q (1, h*hd) -> per head: broadcast to 8 sublane rows (1-row matvecs
      cannot use the MXU; rows 1-7 compute identical results and are
      discarded — the round-3 q8 trick, now inside the kernel for every
      batch size)
    s = q8 @ k_block^T  per head                     # MXU
    mask: k_pos <= cur  (and k_pos >= attn_start[b] for left-padded
      prompts) — cur/attn_start arrive via scalar prefetch
    online softmax accumulate across L-blocks (lane-replicated state,
      normalized acc — same scheme as the flash kernels)

L-blocks past `cur` are skipped: `@pl.when` gates the compute and the
index map pins their DMA to block 0 (Pallas elides DMAs whose block
index is unchanged), so a step at position p reads O(p) cache bytes, not
O(L) — the einsum path always paid O(L).

The reference has no decode path at all (its model is a CNN classifier);
this backs the generation stack (inference.py), whose API the LM family
needs for parity with torch generation loops.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from ddp_practice_tpu.ops.pallas_compat import tpu_compiler_params

from ddp_practice_tpu.ops.flash_attention import (
    _LANES,
    _NEG_INF,
    _dot_tb,
    _heads_per_pack,
    _softmax_accumulate,
)


def _online_softmax_cell(
    cur, start, j, n_j,
    q_ref, k_ref, v_ref, o_ref,
    m_scr, l_scr, acc_scr,
    *, sm_scale, block, n_heads, d,
):
    """One grid cell of the multi-block online-softmax decode walk,
    shared by the flat (`_kernel`) and paged (`_paged_kernel`) kernels —
    the only thing that differs between them is where `cur` comes from
    (pool-global scalar vs per-slot length) and how the kv tile was
    addressed (contiguous vs page table), both settled by the caller.
    `cur`/`start` are this cell's cursor scalars (start None = no
    left-padding mask); key positions are `j * block + offset`."""

    @pl.when(j == 0)
    def _init():
        m_scr[:] = jnp.full(m_scr.shape, -jnp.inf, jnp.float32)
        l_scr[:] = jnp.zeros(l_scr.shape, jnp.float32)
        acc_scr[:] = jnp.zeros(acc_scr.shape, jnp.float32)

    @pl.when(j * block <= cur)
    def _compute():
        k_pos = j * block + jax.lax.broadcasted_iota(
            jnp.int32, (8, block), 1
        )
        valid = k_pos <= cur
        if start is not None:
            valid &= k_pos >= start
        penalty = jnp.where(valid, 0.0, _NEG_INF)
        for hh in range(n_heads):
            lo, hi = hh * d, (hh + 1) * d
            qs = (q_ref[:, lo:hi] * sm_scale).astype(q_ref.dtype)  # (1, d)
            q8 = jnp.broadcast_to(qs, (8, d))
            s = _dot_tb(q8, k_ref[:, lo:hi]) + penalty    # (8, block) f32
            m_scr[hh], l_scr[hh], acc_scr[:, lo:hi] = _softmax_accumulate(
                s, v_ref[:, lo:hi], m_scr[hh], l_scr[hh], acc_scr[:, lo:hi]
            )

    @pl.when(j == n_j - 1)
    def _finalize():
        o_ref[:] = acc_scr[:1].astype(o_ref.dtype)


def _kernel(
    cur_ref, start_ref,              # scalar prefetch (SMEM)
    q_ref, k_ref, v_ref, o_ref,      # blocks
    m_scr, l_scr, acc_scr,
    *, sm_scale, block_l, n_heads, d, has_start,
):
    b_idx = pl.program_id(0)
    j = pl.program_id(1)
    _online_softmax_cell(
        cur_ref[0], start_ref[b_idx] if has_start else None,
        j, pl.num_programs(1),
        q_ref, k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr,
        sm_scale=sm_scale, block=block_l, n_heads=n_heads, d=d,
    )


def _kernel_single(
    cur_ref, start_ref,
    q_ref, k_ref, v_ref, o_ref,
    *, sm_scale, L, n_heads, d, has_start, compute_dtype=None,
):
    """Single-block fast path (whole cache in one tile): plain softmax,
    no online state, no scratch carry — at large batch the multi-block
    kernel's per-cell state machinery dominates the step (bs=64 profile,
    round 4), and a cache that fits one tile needs none of it.

    compute_dtype: dtype the K/V tiles are cast to before the dots —
    needed when the cache is stored quantized (int8), where the MXU
    can't consume the raw tile."""
    b_idx = pl.program_id(0)
    cur = cur_ref[0]
    k_pos = jax.lax.broadcasted_iota(jnp.int32, (8, L), 1)
    valid = k_pos <= cur
    if has_start:
        valid &= k_pos >= start_ref[b_idx]
    penalty = jnp.where(valid, 0.0, _NEG_INF)
    cd = compute_dtype or q_ref.dtype
    for hh in range(n_heads):
        lo, hi = hh * d, (hh + 1) * d
        qs = (q_ref[:, lo:hi] * sm_scale).astype(cd)
        q8 = jnp.broadcast_to(qs, (8, d))
        s = _dot_tb(q8, k_ref[:, lo:hi].astype(cd)) + penalty  # (8, L) f32
        m = jnp.max(s, axis=1, keepdims=True)
        p = jnp.exp(s - m)
        l = jnp.sum(p, axis=1, keepdims=True)
        pv = lax.dot_general(
            p.astype(cd), v_ref[:, lo:hi].astype(cd),
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        o_ref[:, lo:hi] = (pv[:1] / l[:1]).astype(o_ref.dtype)


def _kernel_single_quant(
    cur_ref, start_ref,
    q_ref, k_ref, v_ref, ks_ref, vs_ref, o_ref,
    *, sm_scale, L, n_heads, d, has_start, compute_dtype,
):
    """Single-tile kernel over an INT8 cache with per-(head, position)
    scales, shapes (h, L). The scales never touch the int8 tiles
    directly: the K scale multiplies the score row AFTER the q.k dot
    (s_h(l) = ks(h,l) * <q_h, k_int8(l)>), and the V scale folds into
    the probability vector BEFORE the p.v dot — two (8, L) VPU
    multiplies replace any dequantized (L, d) materialization, so the
    MXU still consumes plain tiles and HBM still streams 1 byte/elem."""
    b_idx = pl.program_id(0)
    cur = cur_ref[0]
    k_pos = jax.lax.broadcasted_iota(jnp.int32, (8, L), 1)
    valid = k_pos <= cur
    if has_start:
        valid &= k_pos >= start_ref[b_idx]
    penalty = jnp.where(valid, 0.0, _NEG_INF)
    cd = compute_dtype
    for hh in range(n_heads):
        lo, hi = hh * d, (hh + 1) * d
        qs = (q_ref[:, lo:hi] * sm_scale).astype(cd)
        q8 = jnp.broadcast_to(qs, (8, d))
        s = _dot_tb(q8, k_ref[:, lo:hi].astype(cd))      # (8, L) f32
        ks = ks_ref[hh, :].reshape(1, L)                 # (1, L) f32
        s = s * ks + penalty
        m = jnp.max(s, axis=1, keepdims=True)
        p = jnp.exp(s - m)
        l = jnp.sum(p, axis=1, keepdims=True)
        vs = vs_ref[hh, :].reshape(1, L)
        pv = lax.dot_general(
            (p * vs).astype(cd), v_ref[:, lo:hi].astype(cd),
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        o_ref[:, lo:hi] = (pv[:1] / l[:1]).astype(o_ref.dtype)


def decode_attention_packed(
    q: jnp.ndarray,        # (b, 1, h*hd) — the current token's queries
    k_cache: jnp.ndarray,  # (b, L, h*hd) flat cache
    v_cache: jnp.ndarray,
    cur: jnp.ndarray,      # int32 scalar: position of the current token
    attn_start=None,       # optional (b,) int32: first valid key position
    *,
    n_heads: int,
    k_scale=None,          # (b, h, L) f32 — int8-cache dequant scales
    v_scale=None,
    block_l: int = 256,
    single_block_max: int = 1024,
) -> jnp.ndarray:
    """One decode step of masked attention over the flat KV cache.

    Valid keys for every query are positions [attn_start[b], cur] (cur
    INCLUSIVE — the current token attends to itself; the caller writes
    its K/V at `cur` before calling). Returns (b, 1, h*hd).

    Caches up to `single_block_max` positions run the one-tile plain-
    softmax kernel; longer caches run the multi-block online-softmax
    kernel, where `block_l` trades DMA granularity against grid
    overhead: reads round up to whole blocks past `cur` and skipped
    blocks cost ~nothing.

    k_scale/v_scale mark an INT8 cache (models/vit.py
    kv_cache_dtype="int8"): tiles stream at 1 byte/element and the
    per-(head, position) scales fold into the score row / probability
    vector inside the kernel (_kernel_single_quant) — decode traffic
    is the bandwidth roofline, so halving cache bytes is the lever the
    round-5 MBU work turned (BENCHMARKS.md decode section).
    """
    from jax.experimental.pallas import tpu as pltpu

    b, sq, hd_total = q.shape
    if sq != 1:
        raise ValueError(
            f"decode_attention_packed is the single-token step kernel "
            f"(got {sq} query rows); prefill takes the masked XLA path"
        )
    L = k_cache.shape[1]
    d = hd_total // n_heads
    if _heads_per_pack(n_heads, d) is None:
        raise ValueError(
            f"heads={n_heads}, head_dim={d} don't pack into 128-lane tiles"
        )
    sm_scale = 1.0 / (d ** 0.5)
    has_start = attn_start is not None
    quant = k_scale is not None
    if quant and v_scale is None:
        raise ValueError("int8 cache needs BOTH k_scale and v_scale")

    cur1 = jnp.asarray(cur, jnp.int32).reshape(1)
    start = (
        jnp.asarray(attn_start, jnp.int32)
        if has_start else jnp.zeros((b,), jnp.int32)
    )
    interpret = jax.default_backend() == "cpu"
    sem = tpu_compiler_params

    if quant and L > single_block_max:
        # long-cache int8 falls back to a dequantized pass through the
        # multi-block kernel below: correct, but it materializes a bf16
        # cache copy — the quantized multi-block kernel is future work
        # (the bench regime L<=1024 never takes this branch)
        scale_k = jnp.swapaxes(k_scale, 1, 2).repeat(d, axis=-1)
        scale_v = jnp.swapaxes(v_scale, 1, 2).repeat(d, axis=-1)
        k_cache = (k_cache.astype(jnp.float32) * scale_k).astype(q.dtype)
        v_cache = (v_cache.astype(jnp.float32) * scale_v).astype(q.dtype)
        quant = False

    if L <= single_block_max:
        if quant:
            kernel = functools.partial(
                _kernel_single_quant, sm_scale=sm_scale, L=L,
                n_heads=n_heads, d=d, has_start=has_start,
                compute_dtype=q.dtype,
            )
            scale_spec = pl.BlockSpec((None, n_heads, L),
                                      lambda b_, *_: (b_, 0, 0))
            return pl.pallas_call(
                kernel,
                grid_spec=pltpu.PrefetchScalarGridSpec(
                    num_scalar_prefetch=2,
                    grid=(b,),
                    in_specs=[
                        pl.BlockSpec((None, 1, hd_total),
                                     lambda b_, *_: (b_, 0, 0)),
                        pl.BlockSpec((None, L, hd_total),
                                     lambda b_, *_: (b_, 0, 0)),
                        pl.BlockSpec((None, L, hd_total),
                                     lambda b_, *_: (b_, 0, 0)),
                        scale_spec,
                        scale_spec,
                    ],
                    out_specs=pl.BlockSpec((None, 1, hd_total),
                                           lambda b_, *_: (b_, 0, 0)),
                ),
                out_shape=jax.ShapeDtypeStruct((b, 1, hd_total), q.dtype),
                compiler_params=sem(dimension_semantics=("parallel",)),
                interpret=interpret,
            )(cur1, start, q, k_cache, v_cache, k_scale, v_scale)
        kernel = functools.partial(
            _kernel_single, sm_scale=sm_scale, L=L, n_heads=n_heads, d=d,
            has_start=has_start,
        )
        return pl.pallas_call(
            kernel,
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=2,
                grid=(b,),
                in_specs=[
                    pl.BlockSpec((None, 1, hd_total),
                                 lambda b_, *_: (b_, 0, 0)),
                    pl.BlockSpec((None, L, hd_total),
                                 lambda b_, *_: (b_, 0, 0)),
                    pl.BlockSpec((None, L, hd_total),
                                 lambda b_, *_: (b_, 0, 0)),
                ],
                out_specs=pl.BlockSpec((None, 1, hd_total),
                                       lambda b_, *_: (b_, 0, 0)),
            ),
            out_shape=jax.ShapeDtypeStruct((b, 1, hd_total), q.dtype),
            compiler_params=sem(dimension_semantics=("parallel",)),
            interpret=interpret,
        )(cur1, start, q, k_cache, v_cache)

    block_l = min(block_l, L)
    while L % block_l:
        block_l //= 2

    def kv_map(b_, j, cur_ref, start_ref):
        return (b_, lax.select(j * block_l <= cur_ref[0], j, 0), 0)

    kernel = functools.partial(
        _kernel, sm_scale=sm_scale, block_l=block_l, n_heads=n_heads, d=d,
        has_start=has_start,
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(b, L // block_l),
            in_specs=[
                pl.BlockSpec((None, 1, hd_total),
                             lambda b_, j, *_: (b_, 0, 0)),
                pl.BlockSpec((None, block_l, hd_total), kv_map),
                pl.BlockSpec((None, block_l, hd_total), kv_map),
            ],
            out_specs=pl.BlockSpec((None, 1, hd_total),
                                   lambda b_, j, *_: (b_, 0, 0)),
            scratch_shapes=[
                pltpu.VMEM((n_heads, 8, _LANES), jnp.float32),
                pltpu.VMEM((n_heads, 8, _LANES), jnp.float32),
                pltpu.VMEM((8, hd_total), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((b, 1, hd_total), q.dtype),
        compiler_params=sem(dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(cur1, start, q, k_cache, v_cache)
    return out


# --------------------------------------------------------------------- paged
# PagedAttention-style decode (serve/kv_pages.py): K/V live in a pool of
# fixed-size blocks shared by all slots, and each slot reaches its own
# history through a per-slot PAGE TABLE of block indices. Positions are
# slot-local — position p of slot b lives in pool block
# `page_table[b, p // block_size]` at row `p % block_size` — so there is
# no shared cursor and a step's attention span is the slot's own
# occupied pages, not a pool-global [0, max_len).


def _paged_kernel(
    len_ref, start_ref, pt_ref,          # scalar prefetch (SMEM)
    q_ref, k_ref, v_ref, o_ref,          # blocks
    m_scr, l_scr, acc_scr,
    *, sm_scale, block_size, n_heads, d, has_start,
):
    """Grid (batch, blocks-per-slot); the kv tile of cell (b, j) is pool
    block `pt_ref[b, j]` — the page-table indirection happens in the
    BlockSpec index map, so the body is `_online_softmax_cell` with a
    per-SLOT cursor (`len_ref[b]`) instead of the pool-global scalar.
    Blocks past the slot's length are skipped: `@pl.when` gates the
    compute and the index map pins their DMA to the slot's block 0
    (unchanged index -> Pallas elides the copy), so a slot with `p`
    occupied positions pays O(p) cache reads however large the pool or
    the per-slot capacity."""
    b_idx = pl.program_id(0)
    j = pl.program_id(1)
    _online_softmax_cell(
        len_ref[b_idx], start_ref[b_idx] if has_start else None,
        j, pl.num_programs(1),
        q_ref, k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr,
        sm_scale=sm_scale, block=block_size, n_heads=n_heads, d=d,
    )


def gather_pages(pages: jnp.ndarray, page_table: jnp.ndarray,
                 n_heads: int, k_scale=None):
    """Materialize each slot's pages as one contiguous span:
    (num_blocks, block_size, h*hd) pool + (b, mb) table ->
    (b, mb*block_size, h, d). With `k_scale` ((num_blocks, h,
    block_size) fp32 — the int8 pool's per-block scale pages) the span
    is dequantized per (position, head) on the way out. Shared by the
    reference attention below and the model's paged PREFILL path
    (models/vit.py `_paged_decode` s > 1)."""
    b = page_table.shape[0]
    bs, hh = pages.shape[1], pages.shape[2]
    d = hh // n_heads
    mb = page_table.shape[1]
    span = mb * bs
    k = jnp.take(pages, page_table, axis=0).reshape(b, span, n_heads, d)
    if k_scale is not None:
        # (b, mb, h, bs) -> per-position (b, span, h)
        sc = jnp.take(k_scale, page_table, axis=0)
        sc = jnp.swapaxes(sc, 2, 3).reshape(b, span, n_heads)
        k = k.astype(jnp.float32) * sc[..., None]
    return k


def paged_attention_reference(
    q: jnp.ndarray,           # (b, 1, h*hd)
    k_pages: jnp.ndarray,     # (num_blocks, block_size, h*hd) pool
    v_pages: jnp.ndarray,
    page_table: jnp.ndarray,  # (b, max_blocks_per_slot) int32
    lengths: jnp.ndarray,     # (b,) int32: slot-local position of the
                              # current token (attends itself — inclusive)
    attn_start=None,          # optional (b,) int32 slot-local first key
    *,
    n_heads: int,
    k_scale=None,             # (num_blocks, h, block_size) f32 — int8
    v_scale=None,             # pool per-block dequant scale pages
) -> jnp.ndarray:
    """XLA gather path: materialize each slot's pages as a contiguous
    (b, max_blocks_per_slot * block_size) span and run masked attention.

    The span is the PER-SLOT capacity (sized to the request's own
    context budget), not the pool — the slot engine's cost driver was
    the pool-global [0, max_len) scan, which this path already removes.
    It is also the correctness oracle for `_paged_kernel` (and its int8
    variant) and the serving path on backends without the kernel (CPU
    tests; unpackable head shapes). An int8 pool dequantizes through
    its scale pages during the gather."""
    from ddp_practice_tpu.ops.attention import attention_with_mask

    b = q.shape[0]
    hh = k_pages.shape[2]
    d = hh // n_heads
    span = page_table.shape[1] * k_pages.shape[1]
    k = gather_pages(k_pages, page_table, n_heads, k_scale)
    v = gather_pages(v_pages, page_table, n_heads, v_scale)
    pos = jnp.arange(span, dtype=jnp.int32)[None, :]
    valid = pos <= lengths[:, None]
    if attn_start is not None:
        valid &= pos >= attn_start[:, None]
    cd = k_pages.dtype if k_scale is None else jnp.float32
    out = attention_with_mask(
        q.reshape(b, 1, n_heads, d).astype(cd),
        k.astype(cd), v.astype(cd), valid[:, None, None, :],
    )
    return out.reshape(b, 1, hh).astype(q.dtype)


def _paged_kernel_quant(
    len_ref, start_ref, pt_ref,              # scalar prefetch (SMEM)
    q_ref, k_ref, v_ref, ks_ref, vs_ref, o_ref,
    m_scr, l_scr, acc_scr,
    *, sm_scale, block_size, n_heads, d, has_start, compute_dtype,
):
    """`_paged_kernel` over an INT8 block pool with per-block scale
    pages: the (h, block_size) scale tiles ride the SAME page-table
    index map as the K/V tiles they dequantize, the K scale multiplies
    the score row after the q.k dot and the V scale folds into the
    probability row before the p.v dot (`_softmax_accumulate(vs_row=)`) —
    no dequantized tile ever materializes, so HBM still streams
    1 byte/element for the cache walk."""
    b_idx = pl.program_id(0)
    j = pl.program_id(1)
    cur = len_ref[b_idx]
    n_j = pl.num_programs(1)
    cd = compute_dtype

    @pl.when(j == 0)
    def _init():
        m_scr[:] = jnp.full(m_scr.shape, -jnp.inf, jnp.float32)
        l_scr[:] = jnp.zeros(l_scr.shape, jnp.float32)
        acc_scr[:] = jnp.zeros(acc_scr.shape, jnp.float32)

    @pl.when(j * block_size <= cur)
    def _compute():
        k_pos = j * block_size + jax.lax.broadcasted_iota(
            jnp.int32, (8, block_size), 1
        )
        valid = k_pos <= cur
        if has_start:
            valid &= k_pos >= start_ref[b_idx]
        penalty = jnp.where(valid, 0.0, _NEG_INF)
        for hh in range(n_heads):
            lo, hi = hh * d, (hh + 1) * d
            qs = (q_ref[:, lo:hi] * sm_scale).astype(cd)
            q8 = jnp.broadcast_to(qs, (8, d))
            s = _dot_tb(q8, k_ref[:, lo:hi].astype(cd))   # (8, bs) f32
            ks = ks_ref[hh, :].reshape(1, block_size)
            s = s * ks + penalty
            vs = vs_ref[hh, :].reshape(1, block_size)
            (m_scr[hh], l_scr[hh],
             acc_scr[:, lo:hi]) = _softmax_accumulate(
                s, v_ref[:, lo:hi].astype(cd),
                m_scr[hh], l_scr[hh], acc_scr[:, lo:hi], vs_row=vs,
            )

    @pl.when(j == n_j - 1)
    def _finalize():
        o_ref[:] = acc_scr[:1].astype(o_ref.dtype)


def paged_decode_attention(
    q: jnp.ndarray,
    k_pages: jnp.ndarray,
    v_pages: jnp.ndarray,
    page_table: jnp.ndarray,
    lengths: jnp.ndarray,
    attn_start=None,
    *,
    n_heads: int,
    k_scale=None,
    v_scale=None,
    impl: str = "auto",
) -> jnp.ndarray:
    """One paged decode step; returns (b, 1, h*hd). See the module-level
    paged section for the layout.

    impl: "auto" runs the Pallas kernel on TPU when the heads pack into
    128-lane tiles and the gather reference otherwise (on CPU the
    reference IS the fast path — interpret-mode pays python emulation
    per grid cell, and the reference's gather is one fused XLA op);
    "kernel" forces the kernel (interpret-mode on CPU — the numerics-
    test hook); "reference" forces the gather path.

    k_scale/v_scale mark an INT8 block pool (serve/kv_pages.py
    make_paged_cache over a kv_cache_dtype="int8" model): per-block
    (num_blocks, h, block_size) fp32 scale pages, walked through the
    same page table and folded into the score/probability rows inside
    `_paged_kernel_quant` — cache bytes/token halve while the numerics
    stay pinned to the dequantizing gather reference.
    """
    from jax.experimental.pallas import tpu as pltpu

    b, sq, hd_total = q.shape
    if sq != 1:
        raise ValueError(
            f"paged_decode_attention is the single-token step (got {sq} "
            f"query rows); prefill runs through a contiguous scratch "
            f"cache and scatters whole blocks (serve/kv_pages.py)"
        )
    quant = k_scale is not None
    if quant != (v_scale is not None):
        raise ValueError("int8 page pool needs BOTH k_scale and v_scale")
    bs = k_pages.shape[1]
    d = hd_total // n_heads
    packable = _heads_per_pack(n_heads, d) is not None and bs % 8 == 0
    if impl == "reference" or (impl == "auto" and (
            not packable or jax.default_backend() == "cpu")):
        return paged_attention_reference(
            q, k_pages, v_pages, page_table, lengths, attn_start,
            n_heads=n_heads, k_scale=k_scale, v_scale=v_scale,
        )
    if not packable:
        raise ValueError(
            f"impl='kernel' needs packable heads (h={n_heads}, d={d}) "
            f"and a block_size multiple of 8 (got {bs})"
        )
    sm_scale = 1.0 / (d ** 0.5)
    has_start = attn_start is not None
    mb = page_table.shape[1]
    lens = jnp.asarray(lengths, jnp.int32)
    start = (
        jnp.asarray(attn_start, jnp.int32)
        if has_start else jnp.zeros((b,), jnp.int32)
    )
    pt = jnp.asarray(page_table, jnp.int32)

    def kv_map(b_, j, len_ref, start_ref, pt_ref):
        j_sel = lax.select(j * bs <= len_ref[b_], j, 0)
        return (pt_ref[b_, j_sel], 0, 0)

    common = dict(
        grid=(b, mb),
        out_specs=pl.BlockSpec((None, 1, hd_total),
                               lambda b_, j, *_: (b_, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((n_heads, 8, _LANES), jnp.float32),
            pltpu.VMEM((n_heads, 8, _LANES), jnp.float32),
            pltpu.VMEM((8, hd_total), jnp.float32),
        ],
    )
    q_spec = pl.BlockSpec((None, 1, hd_total), lambda b_, j, *_: (b_, 0, 0))
    kv_spec = pl.BlockSpec((None, bs, hd_total), kv_map)
    if quant:
        scale_spec = pl.BlockSpec((None, n_heads, bs), kv_map)
        kernel = functools.partial(
            _paged_kernel_quant, sm_scale=sm_scale, block_size=bs,
            n_heads=n_heads, d=d, has_start=has_start,
            compute_dtype=q.dtype,
        )
        return pl.pallas_call(
            kernel,
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=3,
                in_specs=[q_spec, kv_spec, kv_spec,
                          scale_spec, scale_spec],
                **common,
            ),
            out_shape=jax.ShapeDtypeStruct((b, 1, hd_total), q.dtype),
            compiler_params=tpu_compiler_params(
                dimension_semantics=("parallel", "arbitrary")
            ),
            interpret=jax.default_backend() == "cpu",
        )(lens, start, pt, q, k_pages, v_pages, k_scale, v_scale)
    kernel = functools.partial(
        _paged_kernel, sm_scale=sm_scale, block_size=bs,
        n_heads=n_heads, d=d, has_start=has_start,
    )
    return pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=3,
            in_specs=[q_spec, kv_spec, kv_spec],
            **common,
        ),
        out_shape=jax.ShapeDtypeStruct((b, 1, hd_total), q.dtype),
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "arbitrary")
        ),
        interpret=jax.default_backend() == "cpu",
    )(lens, start, pt, q, k_pages, v_pages)
