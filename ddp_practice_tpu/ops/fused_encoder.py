"""Fused transformer encoder layer as one Pallas TPU kernel (forward).

Why this exists (BENCHMARKS.md "Why ViT-Tiny sits at ~17%"): at d=192 the
per-op XLA pipeline is HBM-bound — every matmul in the layer reads and
writes (tokens, d)-shaped tensors to HBM at intensity ~77 FLOP/byte, well
under the v5e ridge (~240). Fusing the WHOLE layer — LN1 → QKV →
attention → proj + residual → LN2 → MLP + residual — into one kernel
reads the token tensor from HBM once and writes it once; every
intermediate lives in VMEM, lifting intrinsic intensity to ~600 FLOP/byte
(compute-bound). The reference consumes the CUDA analogue of this idea
through cuDNN's fused blocks (SURVEY §2.2); on TPU it has to be a Pallas
kernel because XLA will not fuse across matmuls.

Shape contract: short fixed sequences that fit VMEM whole (the ViT
regime: S = 64 tokens at 32²/patch 4). The grid tiles the BATCH — each
cell processes `img_tile` images; weights (~0.7 MB at d=192) are
broadcast to every cell and stay VMEM-resident. Long-sequence models keep
the streaming flash-attention kernels (ops/flash_attention.py) instead —
different regime, different kernel.

Backward: also one Pallas kernel (`jax.custom_vjp`; residuals are just
(x, params) — remat semantics, O(x) training memory). Each backward grid
cell RECOMPUTES its tile's forward intermediates in VMEM (LN stats,
attention probabilities, gelu pre-activations — one extra forward's
FLOPs at fused-kernel efficiency, far cheaper than reading them from
HBM at d=192 intensity) and then runs the hand-derived transposes in
VMEM too. Weight gradients accumulate across grid cells directly in the
revisited output blocks (every cell maps its dW block to (0, 0); the
TPU grid is sequential, so the block lives in VMEM for the whole sweep
and flushes once). A `reference_apply` unfused backward is kept as an
option (`bwd_impl="reference"`) and is what the numerics tests compare
against.

Runs compiled on TPU; `interpret=True` under the CPU backend so the same
tests cover it everywhere (the flash-attention pattern).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from ddp_practice_tpu.ops.pallas_compat import tpu_compiler_params

_LN_EPS = 1e-6  # flax.linen.LayerNorm default


def _interpret() -> bool:
    return jax.default_backend() == "cpu"


def _layer_norm(xt, scale, bias):
    """fp32 LayerNorm over the last dim -> (affine out, normalized, rstd)."""
    mu = jnp.mean(xt, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(xt - mu), axis=-1, keepdims=True)
    r = jax.lax.rsqrt(var + _LN_EPS)
    yhat = (xt - mu) * r
    return yhat * scale + bias, yhat, r


def _layer_norm_bwd(dya, yhat, r, scale):
    """Cotangent of the LN input given the affine output's; plus the
    scale/bias grads. dya/yhat: (t, d); r: (t, 1)."""
    dscale = jnp.sum(dya * yhat, axis=0, keepdims=True)
    dbias = jnp.sum(dya, axis=0, keepdims=True)
    dxhat = dya * scale
    m1 = jnp.mean(dxhat, axis=-1, keepdims=True)
    m2 = jnp.mean(dxhat * yhat, axis=-1, keepdims=True)
    dx = r * (dxhat - m1 - yhat * m2)
    return dx, dscale, dbias


_GELU_C = 0.7978845608028654  # sqrt(2/pi)
_GELU_A = 0.044715


def _gelu_grad(x, t):
    """d gelu(x)/dx given t = tanh(c(x + a x^3)) (tanh approximation —
    what flax nn.gelu computes)."""
    return 0.5 * (1.0 + t) + 0.5 * x * (1.0 - t * t) * _GELU_C * (
        1.0 + 3.0 * _GELU_A * x * x
    )


def _mm(a, w, cd):
    return jax.lax.dot(a.astype(cd), w.astype(cd),
                       preferred_element_type=jnp.float32)


def _bdot(a, b, contract_a, contract_b, cd):
    """Batched (leading-dim) dot in the compute dtype, fp32 accumulate."""
    return jax.lax.dot_general(
        a.astype(cd), b.astype(cd),
        (((contract_a,), (contract_b,)), ((0,), (0,))),
        preferred_element_type=jnp.float32,
    )


def _fwd_core(xt, imgs, s, ln1_s, ln1_b, wqkv, bqkv, wproj, bproj,
              ln2_s, ln2_b, w_in, b_in, w_out, b_out,
              *, num_heads, head_dim, compute_dtype, causal=False,
              seq_merge=1):
    """The whole layer on a (t, d) fp32 token tile; returns every
    intermediate the backward needs (the fwd kernel uses `out` only and
    the compiler drops the rest).

    Attention runs per head in a Python loop (heads are few at small d)
    with images as the dot_general batch dim: Mosaic has no 4D head
    transpose, but 64-aligned column slices + major-dim reshapes lower
    cleanly. Head outputs lane-concat into o_all for a single K=d
    projection dot (three K=64 dots measured ~21% MXU efficiency).
    Matmuls take compute-dtype (bf16) operands with fp32 accumulation —
    the MXU contract, matching the unfused policy; LN/softmax/residual
    math runs in fp32, while bulky intermediates whose only consumers
    are cd-casting dots (qkv, hg) are stored in the compute dtype
    (bit-identical results, half the backward tile's VMEM).
    """
    cd = compute_dtype
    f32 = jnp.float32
    t, d = xt.shape
    h, hd = num_heads, head_dim
    y1a, y1hat, r1 = _layer_norm(xt, ln1_s, ln1_b)
    # qkv is stored in the compute dtype: its only consumers are the
    # per-head slices, whose dots cast to cd anyway (bit-identical), and
    # an f32 (t, 3d) buffer was ~1.2 MB of the backward tile's VMEM
    qkv = (_mm(y1a, wqkv, cd) + bqkv).astype(cd)      # (t, 3*h*hd)
    scale = 1.0 / (hd ** 0.5)
    # seq_merge m > 1 folds m images into ONE attention sequence of m*s
    # positions under a static block-diagonal additive mask: exp(-1e30)
    # zeroes every cross-image probability, so softmax rows, o, and all
    # five backward dots are EXACT per image while the MXU sees (m*s)-
    # sized operands instead of latency-dominated (s, hd) tiles (at
    # s=64/hd=64 each dot is ~16 cycles of useful work against ~10x that
    # in pipeline latency — the round-4 ablation measured the per-head
    # dots at 12% efficiency, 17% of the forward kernel). The executed
    # attention FLOPs grow m-fold; the measured win at the ViT shape
    # (m=2..4) is what picks the default in _pick_seq_merge.
    m = seq_merge
    im, sm = imgs // m, s * m
    # one (sm, sm) additive penalty shared by every merged row and head:
    # same-image blocks pass (with the causal triangle inside each block
    # when asked — within a diagonal block qpos >= kpos IS intra-image
    # causality), everything else is -1e30
    penalty = None
    if causal or m > 1:
        qpos = jax.lax.broadcasted_iota(jnp.int32, (sm, sm), 0)
        kpos = jax.lax.broadcasted_iota(jnp.int32, (sm, sm), 1)
        ok = (qpos // s) == (kpos // s)
        if causal:
            ok = ok & (qpos >= kpos)
        penalty = jnp.where(ok, 0.0, -1e30)[None]
    heads = []
    outs = []
    for hi in range(h):
        def head_slice(base):
            col = base + hi * hd
            return qkv[:, col: col + hd].reshape(im, sm, hd)

        q = head_slice(0)
        k = head_slice(h * hd)
        v = head_slice(2 * h * hd)
        scores = _bdot(q, k, 2, 2, cd) * scale        # (im, sm, sm)
        if penalty is not None:
            scores = scores + penalty
        scores = scores - jnp.max(scores, axis=-1, keepdims=True)
        p = jnp.exp(scores)
        p = p / jnp.sum(p, axis=-1, keepdims=True)
        o = _bdot(p, v, 2, 1, cd)                     # (im, sm, hd)
        outs.append(o.reshape(t, hd))
        heads.append((q, k, v, p))
    # concatenated head outputs -> ONE (t, d) @ (d, d) projection: three
    # K=64 per-head dots ran at ~21% MXU efficiency (round-4 standalone
    # shape probe); the lane-concat is a VPU copy, the K=192 dot ~3x
    # denser
    o_all = jnp.concatenate(outs, axis=1)             # (t, h*hd)
    x2 = xt + _mm(o_all, wproj, cd) + bproj
    y2a, y2hat, r2 = _layer_norm(x2, ln2_s, ln2_b)
    hpre = _mm(y2a, w_in, cd) + b_in                  # (t, mlp)
    tanh = jnp.tanh(_GELU_C * (hpre + _GELU_A * hpre * hpre * hpre))
    # hg in compute dtype: both consumers (the fc_out matmul here and
    # dw_out in the backward) cast to cd — identical results, half the
    # (t, mlp) buffer
    hg = (0.5 * hpre * (1.0 + tanh)).astype(cd)
    out = x2 + _mm(hg, w_out, cd) + b_out
    return dict(
        y1a=y1a, y1hat=y1hat, r1=r1, qkv=qkv, heads=heads, o_all=o_all,
        x2=x2, y2a=y2a, y2hat=y2hat, r2=r2, hpre=hpre, tanh=tanh, hg=hg,
        out=out,
    )


def _weights_f32(ln1_s, ln1_b, wqkv, bqkv, wproj, bproj, ln2_s, ln2_b,
                 w_in, b_in, w_out, b_out):
    f32 = jnp.float32
    return (
        ln1_s[0].astype(f32), ln1_b[0].astype(f32), wqkv[:], bqkv[0]
        .astype(f32), wproj[:], bproj[0].astype(f32), ln2_s[0].astype(f32),
        ln2_b[0].astype(f32), w_in[:], b_in[0].astype(f32), w_out[:],
        b_out[0].astype(f32),
    )


def _fused_kernel(
    x_ref, ln1_s, ln1_b, wqkv, bqkv, wproj, bproj, ln2_s, ln2_b,
    w_in, b_in, w_out, b_out, o_ref,
    *, num_heads, head_dim, compute_dtype, causal, seq_merge,
):
    """Forward grid cell: the full encoder layer for `img_tile` images."""
    imgs, s, d = x_ref.shape
    xt = x_ref[:].astype(jnp.float32).reshape(imgs * s, d)
    core = _fwd_core(
        xt, imgs, s,
        *_weights_f32(ln1_s, ln1_b, wqkv, bqkv, wproj, bproj, ln2_s,
                      ln2_b, w_in, b_in, w_out, b_out),
        num_heads=num_heads, head_dim=head_dim, compute_dtype=compute_dtype,
        causal=causal, seq_merge=seq_merge,
    )
    o_ref[:] = core["out"].reshape(imgs, s, d).astype(o_ref.dtype)


def _fused_bwd_kernel(
    x_ref, g_ref, ln1_s, ln1_b, wqkv, bqkv, wproj, bproj, ln2_s, ln2_b,
    w_in, b_in, w_out, b_out,
    dx_ref, dln1_s, dln1_b, dwqkv, dbqkv, dwproj, dbproj, dln2_s, dln2_b,
    dw_in, db_in, dw_out, db_out,
    *, num_heads, head_dim, compute_dtype, causal, seq_merge,
):
    """Backward grid cell: recompute the tile's forward in VMEM, then the
    hand-derived transposes. Weight-gradient outputs map every cell to
    block (0, 0): the TPU grid is sequential and Pallas keeps revisited
    output blocks in VMEM, so `ref[:] += ...` accumulates across the
    whole sweep and flushes once at the end (`@pl.when(cell 0)` zeroes)."""
    cd = compute_dtype
    f32 = jnp.float32
    imgs, s, d = x_ref.shape
    h, hd = num_heads, head_dim
    t = imgs * s
    xt = x_ref[:].astype(f32).reshape(t, d)
    g = g_ref[:].astype(f32).reshape(t, d)
    ws = _weights_f32(ln1_s, ln1_b, wqkv, bqkv, wproj, bproj, ln2_s,
                      ln2_b, w_in, b_in, w_out, b_out)
    (l1s, l1b, Wqkv, Bqkv, Wproj, Bproj, l2s, l2b,
     Win, Bin, Wout, Bout) = ws
    core = _fwd_core(
        xt, imgs, s, *ws,
        num_heads=num_heads, head_dim=head_dim, compute_dtype=cd,
        causal=causal, seq_merge=seq_merge,
    )

    @pl.when(pl.program_id(0) == 0)
    def _init():
        for ref in (dln1_s, dln1_b, dwqkv, dbqkv, dwproj, dbproj, dln2_s,
                    dln2_b, dw_in, db_in, dw_out, db_out):
            ref[:] = jnp.zeros(ref.shape, ref.dtype)

    def mmT_left(a, b):
        # a^T @ b without materializing the transpose: contract dim 0
        return jax.lax.dot_general(
            a.astype(cd), b.astype(cd), (((0,), (0,)), ((), ())),
            preferred_element_type=f32,
        )

    def mmT_right(a, w):
        # a @ w^T: contract both dim 1
        return jax.lax.dot_general(
            a.astype(cd), w.astype(cd), (((1,), (1,)), ((), ())),
            preferred_element_type=f32,
        )

    # ---- MLP branch (out = x2 + hg @ Wout + Bout)
    dw_out[:] += mmT_left(core["hg"], g)
    db_out[:] += jnp.sum(g, axis=0, keepdims=True)
    dhg = mmT_right(g, Wout)                          # (t, mlp)
    dhpre = dhg * _gelu_grad(core["hpre"], core["tanh"])
    dw_in[:] += mmT_left(core["y2a"], dhpre)
    db_in[:] += jnp.sum(dhpre, axis=0, keepdims=True)
    dy2a = mmT_right(dhpre, Win)                      # (t, d)
    dx2_ln, ds2, db2 = _layer_norm_bwd(dy2a, core["y2hat"], core["r2"], l2s)
    dln2_s[:] += ds2
    dln2_b[:] += db2
    dx2 = g + dx2_ln

    # ---- attention branch (x2 = xt + o_all @ Wproj + Bproj)
    dbproj[:] += jnp.sum(dx2, axis=0, keepdims=True)
    dwproj[:] += mmT_left(core["o_all"], dx2)
    do_all = mmT_right(dx2, Wproj)                    # (t, h*hd)
    scale = 1.0 / (hd ** 0.5)
    dqkv_cols = []
    for hi, (q, k, v, p) in enumerate(core["heads"]):
        # heads live in the seq_merge layout (imgs/m, m*s, hd); the five
        # grad dots below are exact there — every cross-image term rides
        # a zero of p (see _fwd_core)
        im, sm = q.shape[0], q.shape[1]
        do = do_all[:, hi * hd: (hi + 1) * hd].reshape(im, sm, hd)
        dp = _bdot(do, v, 2, 2, cd)                   # (im, sm, sm)
        dv = _bdot(p, do, 1, 1, cd)                   # (im, sm, hd)
        dsc = p * (dp - jnp.sum(dp * p, axis=-1, keepdims=True))
        dsc = dsc * scale
        dq = _bdot(dsc, k, 2, 1, cd)                  # (im, sm, hd)
        dk = _bdot(dsc, q, 1, 1, cd)                  # (im, sm, hd)
        dqkv_cols.append((dq.reshape(t, hd), dk.reshape(t, hd),
                          dv.reshape(t, hd)))
    # columns in qkv order: all q heads, all k heads, all v heads. The
    # bias grad sums the f32 pieces FIRST; the concatenated dqkv is then
    # stored in the compute dtype — both its consumers are dots that cast
    # to cd anyway (bit-identical grads), and an f32 (t, 3*h*hd) buffer
    # was ~1.7 MB of the tile's VMEM stack
    cols = (
        [c[0] for c in dqkv_cols] + [c[1] for c in dqkv_cols]
        + [c[2] for c in dqkv_cols]
    )
    dbqkv[:] += jnp.concatenate(
        [jnp.sum(c, axis=0, keepdims=True) for c in cols], axis=1
    )
    dqkv = jnp.concatenate(
        [c.astype(cd) for c in cols], axis=1,
    )                                                  # (t, 3*h*hd)
    dwqkv[:] += mmT_left(core["y1a"], dqkv)
    dy1a = mmT_right(dqkv, Wqkv)
    dx1_ln, ds1, db1 = _layer_norm_bwd(dy1a, core["y1hat"], core["r1"], l1s)
    dln1_s[:] += ds1
    dln1_b[:] += db1
    dx = dx2 + dx1_ln
    dx_ref[:] = dx.reshape(imgs, s, d).astype(dx_ref.dtype)


# merged attention positions ceiling shared by _pick_seq_merge and
# _auto_tile's budget estimate — retune in ONE place
_MERGE_TARGET = 128


def _pick_seq_merge(s, tile, target: int = _MERGE_TARGET):
    """Images per merged attention sequence: the largest power of two m
    dividing the tile with m*s <= target. 128 merged positions is the
    measured sweet spot at the ViT shape (s=64: m=2, -2% fwd / -1.5% bwd
    vs unmerged) — bigger merges pay more masked-out FLOPs than they
    save; sequences already >= target (the causal LM shapes) keep m=1."""
    m = 1
    while (
        m * 2 * s <= target and tile % (m * 2) == 0
    ):
        m *= 2
    return m


def _vmem_params(interpret):
    """Explicit 17 MB scoped-VMEM declaration for the fused kernels.

    Under the DEFAULT declaration XLA checks each kernel against a flat
    16 MB scoped budget, and inside a real train step the backward cell
    at its measured-best tile (8 images — 12% faster than 4) plus XLA's
    own S(1) buffers around the call (next-layer weight prefetches, the
    dW result tuple) lands at 16.06 MB — a 66 KB overflow that fails the
    e2e compile even though the standalone kernel fits. An explicit
    vmem_limit_bytes switches XLA to its program-wide scoped-vmem
    accounting against the physical budget (~128 MB on v5e), where the
    whole step needs ~127.9 MB and passes with the declaration at 17 MB
    (measured: 15/14 MB declarations FAIL that program-wide check —
    the limit scales with the declaration — and the default fails the
    flat check; 17 MB is the empirical window on v5e).

    The window is v5e-calibrated; other shapes/TPU generations can
    retune without editing the kernel via DDP_TPU_FUSED_VMEM_MB
    (advisor round 4)."""
    if interpret:
        return None
    import os

    from jax.experimental.pallas import tpu as pltpu

    raw = os.environ.get("DDP_TPU_FUSED_VMEM_MB", "17")
    try:
        mb = int(raw)
        if mb <= 0:
            raise ValueError(raw)
    except ValueError:
        raise ValueError(
            f"DDP_TPU_FUSED_VMEM_MB={raw!r}: want a positive integer "
            "(MB of scoped VMEM to declare for the fused encoder kernels)"
        ) from None
    return tpu_compiler_params(vmem_limit_bytes=mb * 1024 * 1024)


def _fit_tile(n, tile):
    tile = min(tile, n)
    while n % tile:
        tile -= 1
    return max(tile, 1)


def _auto_tile(imgs, s, compute_dtype, *, fwd: bool, d: int = 192,
               mlp_dim: int = 768, num_heads: int = 3,
               strict: bool = False):
    """Default images-per-cell honoring the 16 MB scoped-VMEM budget.

    Calibrated on v5e at the ViT-Tiny shape (d=192, mlp 768, h=3, s=64):
    the forward fits 2048 bf16-compute tokens per cell (tile 32 at s=64 —
    the bench shape), the backward 512 (more live intermediates; tile 8
    measured 12% faster than 4 at the bench shape, 16 OOMs — paid for
    by compute-dtype stores of qkv/hg/dqkv, which is also why fp32
    compute keeps its original smaller calibrated budget rather than a
    halved one). Other shapes scale the budget by relative live bytes
    per token: ~11d (residual/LN/qkv/head streams) + 3*mlp
    (hpre/tanh/hg) + h*s*seq_merge (the per-head probability tiles,
    (m*s, m*s) under merging — the term that blows up at LM sequence
    lengths; round-4 lm_tiny s=256 OOM'd the fixed budget by 3%)."""
    bytes_ = jnp.dtype(compute_dtype).itemsize
    # prospective seq_merge at this s (like _pick_seq_merge before the
    # tile-divisibility cut): merged per-head probability tiles are
    # (m*s, m*s) — m x the per-token bytes
    def m_est(seq):
        m = 1
        while m * 2 * seq <= _MERGE_TARGET:
            m *= 2
        return m

    ref_cost = 11 * 192 + 3 * 768 + 3 * 64 * m_est(64)
    cost = 11 * d + 3 * mlp_dim + num_heads * s * m_est(s)
    if bytes_ <= 2:
        base = 2048 if fwd else 512
    else:
        # fp32 compute: the compute-dtype stores (qkv/hg/dqkv) that pay
        # for the doubled bf16 backward tile free nothing here, so keep
        # the original calibrated fp32 budget
        base = 1024 if fwd else 128
    tokens = base * ref_cost // cost
    if strict:
        # feasibility probe (fused_shape_supported): 0 = the budget does
        # not admit even one full sequence per cell
        return tokens // s
    return max(1, tokens // s)


def fused_shape_supported(*, seq_len: int, d: int, mlp_dim: int,
                          num_heads: int, compute_dtype) -> bool:
    """True when the fused kernels can run this encoder shape at all.

    The auto-selection predicate (EncoderBlock fused="auto"): mirrors the
    kernel's hard constraints without raising — head_dim 64-aligned
    column slices (_prep), whole-weight VMEM residency
    (_check_vmem_residency), and a backward VMEM budget that admits at
    least one full sequence per grid cell (_auto_tile's token budget;
    long-sequence models fail here and keep the streaming flash kernels
    instead). Callers that want loud failures pass fused=True and get
    the original ValueErrors."""
    if not _head_dim_ok(d, num_heads):
        return False
    try:
        _check_vmem_residency(d, mlp_dim, compute_dtype)
    except ValueError:
        return False
    # backward (the tighter budget) must fit >= 1 sequence per cell
    return _auto_tile(
        seq_len, seq_len, compute_dtype, fwd=False, d=d, mlp_dim=mlp_dim,
        num_heads=num_heads, strict=True,
    ) >= 1


def _check_vmem_residency(d, mlp_dim, compute_dtype):
    """The kernel keeps ALL weights VMEM-resident; past ~8 MB of weights
    there is no room left for a useful tile. Fail loudly — this is the
    small-d kernel (d=192-class); wide models are compute-bound under
    per-op XLA anyway (BENCHMARKS.md: ViT-Base trains at ~55% unfused)."""
    w_bytes = (d * 3 * d + d * d + 2 * d * mlp_dim) * jnp.dtype(
        compute_dtype
    ).itemsize
    if w_bytes > 8 * 1024 * 1024:
        raise ValueError(
            f"fused encoder layer: weights at d={d}, mlp={mlp_dim} need "
            f"{w_bytes / 2**20:.1f} MB of VMEM residency — over the "
            "budget. This kernel targets the small-d HBM-bound regime; "
            "use the per-op path for wide models"
        )


def _head_dim_ok(d: int, num_heads: int) -> bool:
    """The in-kernel head walk's alignment contract — ONE definition
    shared by _prep's loud gate and fused_shape_supported's silent
    auto-selection predicate."""
    return d % num_heads == 0 and (d // num_heads) % 64 == 0


def _prep(x, params, num_heads, img_tile, compute_dtype):
    """(dims, weight mats, weight specs) shared by the fwd/bwd wrappers."""
    imgs, s, d = x.shape
    if d % num_heads:
        raise ValueError(f"d={d} % heads={num_heads}")
    if not _head_dim_ok(d, num_heads):
        raise ValueError(
            f"fused encoder layer needs head_dim a multiple of 64 (got "
            f"{d // num_heads}): the in-kernel head walk slices qkv "
            "columns at head_dim offsets and Mosaic only lowers "
            "64-aligned column slices — pick a head count with "
            "head_dim >= 64 (e.g. --num_heads 4 for d=256)"
        )
    tile = _fit_tile(imgs, img_tile)
    cd = compute_dtype

    def w2(a, shape):
        return jnp.asarray(a).reshape(shape).astype(cd)

    attn, mlp = params["attn"], params["mlp"]
    mats = [
        w2(params["ln1"]["scale"], (1, d)), w2(params["ln1"]["bias"], (1, d)),
        w2(attn["qkv"]["kernel"], (d, 3 * d)),
        w2(attn["qkv"]["bias"], (1, 3 * d)),
        w2(attn["out"]["kernel"], (d, d)), w2(attn["out"]["bias"], (1, d)),
        w2(params["ln2"]["scale"], (1, d)), w2(params["ln2"]["bias"], (1, d)),
        w2(mlp["fc_in"]["kernel"], (d, -1)), w2(mlp["fc_in"]["bias"], (1, -1)),
        w2(mlp["fc_out"]["kernel"], (-1, d)), w2(mlp["fc_out"]["bias"], (1, d)),
    ]
    _check_vmem_residency(d, mats[8].shape[1], compute_dtype)
    full = lambda shape: pl.BlockSpec(shape, lambda i: (0,) * len(shape))
    w_specs = [full(tuple(m.shape)) for m in mats]
    return imgs, s, d, tile, mats, w_specs


def fused_encoder_forward(
    x, params, *, num_heads: int, compute_dtype=jnp.bfloat16,
    img_tile: int = 0, interpret=None, causal: bool = False,
):
    """Pallas forward of one encoder layer. x: (imgs, s, d); params: the
    flax EncoderBlock param subtree (ln1/attn/ln2/mlp). img_tile 0 =
    auto (VMEM-budget-aware, _auto_tile)."""
    if interpret is None:
        interpret = _interpret()
    img_tile = img_tile or _auto_tile(
        x.shape[0], x.shape[1], compute_dtype, fwd=True, d=x.shape[2],
        mlp_dim=jnp.asarray(params["mlp"]["fc_in"]["kernel"]).shape[-1],
        num_heads=num_heads,
    )
    imgs, s, d, tile, mats, w_specs = _prep(
        x, params, num_heads, img_tile, compute_dtype
    )
    kernel = functools.partial(
        _fused_kernel, num_heads=num_heads, head_dim=d // num_heads,
        compute_dtype=compute_dtype, causal=causal,
        seq_merge=_pick_seq_merge(s, tile),
    )
    return pl.pallas_call(
        kernel,
        grid=(imgs // tile,),
        in_specs=[pl.BlockSpec((tile, s, d), lambda i: (i, 0, 0))] + w_specs,
        out_specs=pl.BlockSpec((tile, s, d), lambda i: (i, 0, 0)),
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        compiler_params=_vmem_params(interpret),
        interpret=interpret,
    )(x, *mats)


def fused_encoder_backward(
    x, g, params, *, num_heads: int, compute_dtype=jnp.bfloat16,
    img_tile: int = 0, interpret=None, causal: bool = False,
):
    """Pallas backward: (dx, dparams-tree). Recompute + transpose per grid
    cell; weight grads accumulate across cells in revisited fp32 blocks.
    img_tile 0 = auto — a much tighter budget than the forward's (the
    backward holds ~3x the live intermediates; see _auto_tile)."""
    if interpret is None:
        interpret = _interpret()
    img_tile = img_tile or _auto_tile(
        x.shape[0], x.shape[1], compute_dtype, fwd=False, d=x.shape[2],
        mlp_dim=jnp.asarray(params["mlp"]["fc_in"]["kernel"]).shape[-1],
        num_heads=num_heads,
    )
    imgs, s, d, tile, mats, w_specs = _prep(
        x, params, num_heads, img_tile, compute_dtype
    )
    mlp_dim = mats[8].shape[1]
    f32 = jnp.float32
    kernel = functools.partial(
        _fused_bwd_kernel, num_heads=num_heads, head_dim=d // num_heads,
        compute_dtype=compute_dtype, causal=causal,
        seq_merge=_pick_seq_merge(s, tile),
    )
    full = lambda shape: pl.BlockSpec(shape, lambda i: (0,) * len(shape))
    dw_shapes = [
        (1, d), (1, d), (d, 3 * d), (1, 3 * d), (d, d), (1, d),
        (1, d), (1, d), (d, mlp_dim), (1, mlp_dim), (mlp_dim, d), (1, d),
    ]
    x_spec = pl.BlockSpec((tile, s, d), lambda i: (i, 0, 0))
    outs = pl.pallas_call(
        kernel,
        grid=(imgs // tile,),
        in_specs=[x_spec, x_spec] + w_specs,
        out_specs=[x_spec] + [full(sh) for sh in dw_shapes],
        out_shape=[jax.ShapeDtypeStruct(x.shape, x.dtype)]
        + [jax.ShapeDtypeStruct(sh, f32) for sh in dw_shapes],
        compiler_params=_vmem_params(interpret),
        interpret=interpret,
    )(x, g.astype(x.dtype), *mats)
    dx = outs[0]
    (dl1s, dl1b, dwqkv, dbqkv, dwproj, dbproj, dl2s, dl2b,
     dwin, dbin, dwout, dbout) = outs[1:]

    def like(mat, leaf):
        return mat.reshape(jnp.shape(leaf)).astype(jnp.asarray(leaf).dtype)

    attn, mlp = params["attn"], params["mlp"]
    dparams: dict = {
        "ln1": {"scale": like(dl1s, params["ln1"]["scale"]),
                "bias": like(dl1b, params["ln1"]["bias"])},
        "attn": {
            "qkv": {"kernel": like(dwqkv, attn["qkv"]["kernel"]),
                    "bias": like(dbqkv, attn["qkv"]["bias"])},
            "out": {"kernel": like(dwproj, attn["out"]["kernel"]),
                    "bias": like(dbproj, attn["out"]["bias"])},
        },
        "ln2": {"scale": like(dl2s, params["ln2"]["scale"]),
                "bias": like(dl2b, params["ln2"]["bias"])},
        "mlp": {
            "fc_in": {"kernel": like(dwin, mlp["fc_in"]["kernel"]),
                      "bias": like(dbin, mlp["fc_in"]["bias"])},
            "fc_out": {"kernel": like(dwout, mlp["fc_out"]["kernel"]),
                       "bias": like(dbout, mlp["fc_out"]["bias"])},
        },
    }
    if hasattr(params, "unfreeze"):  # match a FrozenDict input's structure
        from flax.core import freeze

        dparams = freeze(dparams)
    return dx, dparams


def fused_encoder_layer(x, params, *, num_heads: int, reference_apply=None,
                        compute_dtype=jnp.bfloat16, img_tile: int = 0,
                        bwd_impl: str = "kernel", causal: bool = False):
    """Differentiable fused layer: Pallas forward AND backward.

    Residuals are just (x, params) — remat semantics. bwd_impl="kernel"
    (default) runs the fused Pallas backward; "reference" recomputes
    `reference_apply(params, x)` under jax.vjp instead — the unfused flax
    block, bit-exact unfused gradients, used by the numerics tests as the
    ground truth the kernel is pinned against. `img_tile` tunes the
    FORWARD only; the backward always auto-sizes (its VMEM budget is ~3x
    tighter — _auto_tile).
    """
    if bwd_impl not in ("kernel", "reference"):
        raise ValueError(f"bwd_impl {bwd_impl!r} (kernel|reference)")
    if bwd_impl == "reference" and reference_apply is None:
        raise ValueError("bwd_impl='reference' needs reference_apply")

    @jax.custom_vjp
    def layer(x, p):
        return fused_encoder_forward(
            x, p, num_heads=num_heads, compute_dtype=compute_dtype,
            img_tile=img_tile, causal=causal,
        )

    def fwd(x, p):
        return layer(x, p), (x, p)

    def bwd(res, g):
        x, p = res
        if bwd_impl == "kernel":
            return fused_encoder_backward(
                x, g, p, num_heads=num_heads, compute_dtype=compute_dtype,
                causal=causal,
            )
        _, vjp = jax.vjp(lambda xx, pp: reference_apply(pp, xx), x, p)
        return vjp(g)

    layer.defvjp(fwd, bwd)
    return layer(x, params)
