"""Rotary position embeddings (RoPE, Su et al.) — relative positions for
the LM family's long-context work.

No counterpart in the reference (a CNN; no sequence axis anywhere,
origin_main.py:9-31). Learned absolute positions (models/lm.py pos_embed)
tie the model to max_len at train time; RoPE encodes position as a
rotation of each query/key pair so attention scores depend only on
relative offsets — the standard choice for long-context decoders and the
variant that composes with the framework's sequence-parallel schemes for
free: applied to Q/K *before* attention, the rotation is baked into the
tensors, so ring K/V blocks travel with their positions and Ulysses'
head scatter never sees positions at all.

TPU notes: angles are computed in fp32 (bf16 loses position resolution
past a few thousand tokens) and cast back; the rotate-half layout keeps
everything as two contiguous (…, d/2) slabs — no interleaved gathers, so
XLA fuses the whole thing into the surrounding matmul's prologue.
"""

from __future__ import annotations

import jax.numpy as jnp


def apply_rope(
    x: jnp.ndarray,
    positions: jnp.ndarray,
    *,
    theta: float = 10000.0,
) -> jnp.ndarray:
    """Rotate (b, s, h, d) by per-position angles; positions is (s,) int,
    or (b, s) int when sequences sit at different absolute offsets (the
    paged KV cache decodes every slot at its OWN write position —
    serve/kv_pages.py — so the batch no longer shares one cursor).

    GPT-NeoX rotate-half convention: channel pairs are (i, i + d/2).
    Under GSPMD jit the model sees the GLOBAL sequence, so callers pass
    `arange(s)` (+ the KV-cache cursor when decoding); inside a hand-built
    shard_map over the sequence the caller must add its shard offset.
    """
    d = x.shape[-1]
    if d % 2:
        raise ValueError(f"RoPE needs an even head_dim, got {d}")
    half = d // 2
    freqs = theta ** (-jnp.arange(0, half, dtype=jnp.float32) / half)
    angles = positions.astype(jnp.float32)[..., None] * freqs  # (..., half)
    if angles.ndim == 2:        # (s, half): shared across the batch
        cos = jnp.cos(angles)[None, :, None, :]
        sin = jnp.sin(angles)[None, :, None, :]
    elif angles.ndim == 3:      # (b, s, half): per-sequence offsets
        cos = jnp.cos(angles)[:, :, None, :]
        sin = jnp.sin(angles)[:, :, None, :]
    else:
        raise ValueError(
            f"positions must be (s,) or (b, s), got ndim {positions.ndim}"
        )
    x1 = x[..., :half].astype(jnp.float32)
    x2 = x[..., half:].astype(jnp.float32)
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return out.astype(x.dtype)
