"""On-device input augmentation: PRNG-keyed random crop + horizontal flip.

The reference trains with bare `ToTensor()` (origin_main.py:89) — no
augmentation exists to port, but the ImageNet rung (ResNet-50, BASELINE
config 5) cannot train to real accuracy without crop/flip, so the data
layer needs the hook. TPU-first placement: augmentation runs INSIDE the
jitted train step, after the (device-resident) batch gather and the
uint8 -> float normalize — the host never touches pixels, the whole
epoch stays one dispatch under the resident driver (train/steps.py), and
XLA fuses the flip/crop gathers into the first conv's input read.

Determinism contract: the caller keys each step as
fold_in(fold_in(PRNGKey(seed), AUGMENT_TAG), global_step) — reproducible
for a given --seed, decorrelated from the dropout stream (different
fold-in tag), identical under the per-step, chunked-scan and resident
drivers at the same global step (which encodes epoch), and stable across
checkpoint resume (state.step restores).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

# fold_in tag separating the augmentation stream from dropout (tag-free)
AUGMENT_TAG = 0x415547  # "AUG"


def random_crop_flip(
    images: jnp.ndarray,
    key: jax.Array,
    *,
    pad: int = 4,
    flip: bool = True,
) -> jnp.ndarray:
    """Pad-and-crop plus horizontal flip, per image, one fused program.

    images: (B, H, W, C) float (post-normalize). Zero-pads H/W by `pad`,
    takes a per-image random (H, W) window (offsets uniform in
    [0, 2*pad]), then mirrors each image left-right with probability 1/2.
    Static shapes throughout: the crop is a vmapped dynamic_slice, the
    flip a mask-select — no data-dependent shapes, scan/jit-safe.
    """
    b, h, w, c = images.shape
    kc, kf = jax.random.split(key)
    if pad > 0:
        padded = jnp.pad(
            images, ((0, 0), (pad, pad), (pad, pad), (0, 0))
        )
        off = jax.random.randint(kc, (b, 2), 0, 2 * pad + 1)

        def crop(img, o):
            return lax.dynamic_slice(img, (o[0], o[1], 0), (h, w, c))

        images = jax.vmap(crop)(padded, off)
    if flip:
        mirror = jax.random.bernoulli(kf, 0.5, (b,))
        images = jnp.where(
            mirror[:, None, None, None], images[:, :, ::-1, :], images
        )
    return images


def random_resized_crop(
    images: jnp.ndarray,
    key: jax.Array,
    *,
    scale=(0.08, 1.0),
    ratio=(3.0 / 4.0, 4.0 / 3.0),
    flip: bool = True,
) -> jnp.ndarray:
    """Inception-style random resized crop + horizontal flip — the
    ImageNet-rung augmentation (ResNet-50/224, BASELINE config 5 trains
    to real accuracy with this, not pad-crop).

    Per image: sample a target area fraction in `scale` and an aspect
    ratio log-uniform in `ratio`, place the crop window uniformly, then
    resample the window back to (H, W). TPU-first: shapes stay STATIC —
    the variable-size window never materializes; the resize is
    `jax.image.scale_and_translate` with per-image (traced) scale and
    translation, vmapped over the batch, which XLA lowers to two 1D
    interpolation contractions on the MXU. Where torchvision rejection-
    samples until the window fits and falls back to a center crop, this
    CLIPS the sampled window to the image bounds — same family of crops,
    jit-compatible control flow (the distribution differs slightly at
    extreme aspect ratios; documented, deterministic).

    Same determinism contract as random_crop_flip (augment_rng keying).
    """
    b, h, w, c = images.shape
    k_area, k_ratio, k_pos, k_flip = jax.random.split(key, 4)
    area = jax.random.uniform(
        k_area, (b,), minval=scale[0], maxval=scale[1]
    ) * (h * w)
    log_r = jax.random.uniform(
        k_ratio, (b,),
        minval=jnp.log(ratio[0]), maxval=jnp.log(ratio[1]),
    )
    r = jnp.exp(log_r)
    crop_h = jnp.clip(jnp.sqrt(area / r), 1.0, h)
    crop_w = jnp.clip(jnp.sqrt(area * r), 1.0, w)
    u = jax.random.uniform(k_pos, (b, 2))
    off_y = u[:, 0] * (h - crop_h)
    off_x = u[:, 1] * (w - crop_w)
    # map the window [off, off+crop) onto the full output grid:
    # out_coord = in_coord * s + t  =>  s = H/crop_h, t = -off_y * s
    s_y = h / crop_h
    s_x = w / crop_w
    t_y = -off_y * s_y
    t_x = -off_x * s_x

    def resample(img, sy, sx, ty, tx):
        return jax.image.scale_and_translate(
            img, (h, w, c), (0, 1),
            jnp.stack([sy, sx]), jnp.stack([ty, tx]),
            method="linear", antialias=False,
        )

    images = jax.vmap(resample)(images, s_y, s_x, t_y, t_x)
    if flip:
        mirror = jax.random.bernoulli(k_flip, 0.5, (b,))
        images = jnp.where(
            mirror[:, None, None, None], images[:, :, ::-1, :], images
        )
    return images


def apply_augment(images: jnp.ndarray, key: jax.Array, kind) -> jnp.ndarray:
    """Dispatch an augmentation `kind`: False/"" -> identity,
    True/"crop_flip" -> pad-crop+flip (the CIFAR/MNIST rung),
    "rrc" -> random resized crop (the ImageNet rung)."""
    if not kind:
        return images
    if kind is True or kind == "crop_flip":
        return random_crop_flip(images, key)
    if kind == "rrc":
        return random_resized_crop(images, key)
    raise ValueError(
        f"unknown augment kind {kind!r} (want 'crop_flip'|'rrc')"
    )


def augment_rng(seed: int, step) -> jax.Array:
    """The per-step augmentation key (see module docstring contract)."""
    return jax.random.fold_in(
        jax.random.fold_in(jax.random.PRNGKey(seed), AUGMENT_TAG), step
    )
